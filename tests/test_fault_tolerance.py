"""Fault-tolerance suite (SURVEY §5.3): async atomic checkpoints, verified
manifest fallback, exact full-state resume, and the deterministic fault-
injection harness (common/faultinject) driving every recovery path in-process
— the subprocess hard-kill variant lives in test_kill_resume.py."""

import json
import logging
import os
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.common import faultinject
from deeplearning4j_tpu.common.profiler import OpProfiler
from deeplearning4j_tpu.data import DataSet, NDArrayDataSetIterator
from deeplearning4j_tpu.learning import Adam, Sgd
from deeplearning4j_tpu.ndarray.ndarray import NDArray
from deeplearning4j_tpu.ndarray.rng import set_default_seed
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.optimize import NanSentinelListener
from deeplearning4j_tpu.optimize.listeners import (
    CheckpointListener, CollectScoresIterationListener)
from deeplearning4j_tpu.util import checkpoint as ckpt_util


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.clear_plan()
    yield
    faultinject.clear_plan()


def small_model(seed: int = 5) -> MultiLayerNetwork:
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(learning_rate=0.05)).activation("tanh").list()
            .layer(L.DenseLayer(n_out=8))
            .layer(L.OutputLayer(n_out=2, loss="mcxent",
                                 activation="softmax"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def make_data():
    rng = np.random.RandomState(7)
    x = rng.randn(64, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    return x, y


def make_iter():
    x, y = make_data()
    # shuffle=True on purpose: resume must replay the per-epoch shuffle
    # RNG exactly (the cursor fast-forward consumes skipped epochs/batches)
    return NDArrayDataSetIterator(x, y, batch_size=16, shuffle=True, seed=3)


def plan(*specs):
    faultinject.set_plan(faultinject.FaultPlan(list(specs)))


# ---------------------------------------------------------------------------
# atomic commit + manifest + fallback
# ---------------------------------------------------------------------------

class TestAtomicCheckpoints:
    def test_midwrite_kill_falls_back_to_previous_intact(self, tmp_path):
        """A crash between tmp-write and rename must leave last_checkpoint
        on the PREVIOUS committed checkpoint, and resume must work."""
        set_default_seed(1)
        model = small_model()
        cl = CheckpointListener(str(tmp_path), save_every_n_iterations=2,
                                keep_last=5)
        model.set_listeners(cl)
        # the 4th zip write (commit seq 3 == iter_8) dies pre-rename
        plan({"site": "checkpoint/pre_rename", "index": 3, "kind": "crash"})
        model.fit(make_iter(), epochs=2, batch_size=16)
        cl.close()
        assert len(cl.errors()) == 1          # failure observable, not silent
        files = sorted(os.listdir(tmp_path))
        assert "checkpoint_iter_8.zip.tmp" in files     # the torn write
        assert "checkpoint_iter_8.zip" not in files     # never committed
        last = CheckpointListener.last_checkpoint(str(tmp_path))
        assert last is not None and "iter_6" in last
        # resume succeeds from the fallback
        fresh = small_model()
        fresh.fit(make_iter(), epochs=2, batch_size=16, resume_from=last)
        assert fresh._iteration == 8

    def test_corrupted_checkpoint_skipped_with_warning(self, tmp_path,
                                                       caplog):
        set_default_seed(1)
        model = small_model()
        cl = CheckpointListener(str(tmp_path), save_every_n_iterations=2,
                                keep_last=5)
        model.set_listeners(cl)
        model.fit(make_iter(), epochs=2, batch_size=16)
        cl.close()
        assert len(cl.saved) >= 2
        newest, previous = cl.saved[-1], cl.saved[-2]
        # bit-flip the newest ...
        blob = bytearray(open(newest, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        open(newest, "wb").write(bytes(blob))
        with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
            last = CheckpointListener.last_checkpoint(str(tmp_path))
        assert last == previous
        assert any("checksum" in r.message for r in caplog.records)
        # ... and truncate the fallback too: next-previous (or None) wins
        open(previous, "wb").write(open(previous, "rb").read()[:100])
        last2 = CheckpointListener.last_checkpoint(str(tmp_path))
        assert last2 not in (newest, previous)

    def test_retention_and_index_survive_restart(self, tmp_path):
        """Relaunched listener rebuilds its saved list from the directory
        (today's bug: it forgot prior checkpoints), keeps rotating the
        same set, and clears stale tmp wreckage."""
        set_default_seed(1)
        model = small_model()
        cl = CheckpointListener(str(tmp_path), save_every_n_iterations=2,
                                keep_last=3)
        model.set_listeners(cl)
        model.fit(make_iter(), epochs=2, batch_size=16)
        cl.close()
        (tmp_path / "checkpoint_dead.zip.tmp").write_bytes(b"torn")
        cl2 = CheckpointListener(str(tmp_path), save_every_n_iterations=2,
                                 keep_last=3)
        assert [os.path.basename(p) for p in cl2.saved] == \
            [os.path.basename(p) for p in cl.saved]
        assert not (tmp_path / "checkpoint_dead.zip.tmp").exists()
        # continue training through the SAME retention window
        model2 = small_model()
        model2.set_listeners(cl2)
        last = CheckpointListener.last_checkpoint(str(tmp_path))
        model2.fit(make_iter(), epochs=3, batch_size=16, resume_from=last)
        cl2.close()
        names = [f for f in os.listdir(tmp_path)
                 if f.startswith("checkpoint_") and f.endswith(".zip")]
        assert len(names) == 3     # retention never exceeded keep_last
        manifest = json.loads((tmp_path / "checkpoint.json").read_text())
        listed = {e["file"] for e in manifest["checkpoints"]}
        assert listed == set(names)    # index only references live files

    def test_manifest_checksums_and_verified_reads(self, tmp_path):
        set_default_seed(1)
        model = small_model()
        cl = CheckpointListener(str(tmp_path), save_every_n_iterations=3,
                                keep_last=2)
        model.set_listeners(cl)
        model.fit(make_iter(), epochs=2, batch_size=16)
        cl.close()
        manifest = json.loads((tmp_path / "checkpoint.json").read_text())
        assert manifest["format"] == 2
        for entry in manifest["checkpoints"]:
            path = tmp_path / entry["file"]
            assert path.exists()
            assert ckpt_util.verify_checkpoint(str(tmp_path), entry) == \
                str(path)
        # a v2 checkpoint stays loadable by the plain v1 reader
        restored = MultiLayerNetwork.load(
            CheckpointListener.last_checkpoint(str(tmp_path)),
            load_updater=True)
        assert restored.num_params() == model.num_params()

    def test_scan_fallback_survives_torn_manifest(self, tmp_path):
        set_default_seed(1)
        model = small_model()
        cl = CheckpointListener(str(tmp_path), save_every_n_iterations=2,
                                keep_last=3)
        model.set_listeners(cl)
        model.fit(make_iter(), epochs=2, batch_size=16)
        cl.close()
        expect = cl.saved[-1]
        (tmp_path / "checkpoint.json").write_text('{"form')   # torn write
        assert CheckpointListener.last_checkpoint(str(tmp_path)) == expect


# ---------------------------------------------------------------------------
# pipeline fault injection + retry
# ---------------------------------------------------------------------------

class TestPipelineFaults:
    def test_transient_fault_retried_then_recovered(self):
        prof = OpProfiler.get()
        prof.reset()
        set_default_seed(1)
        model = small_model()
        scores = CollectScoresIterationListener()
        model.set_listeners(scores)
        plan({"site": "pipeline/bind", "index": 2, "kind": "transient",
              "times": 2})
        model.fit(make_iter(), epochs=1, batch_size=16)
        assert prof.counter_value("pipeline/retries") == 2
        assert model._iteration == 4          # all steps trained
        assert len(scores.scores) == 4
        stats = prof.fault_stats()
        assert stats["faults/pipeline/bind/transient"] == 2
        assert stats["retry_backoff_s"] > 0

    def test_transient_fault_exhausts_retries_and_raises(self):
        set_default_seed(1)
        model = small_model()
        plan({"site": "pipeline/bind", "index": 1, "kind": "transient",
              "times": 10})
        with pytest.raises(faultinject.TransientFault):
            model.fit(make_iter(), epochs=1, batch_size=16)

    def test_transient_place_fault_retried(self):
        prof = OpProfiler.get()
        prof.reset()
        set_default_seed(1)
        model = small_model()
        plan({"site": "pipeline/place", "index": 1, "kind": "transient"})
        model.fit(make_iter(), epochs=1, batch_size=16)
        assert prof.counter_value("pipeline/retries") == 1
        assert model._iteration == 4

    def test_slow_batch_injection(self):
        set_default_seed(1)
        model = small_model()
        plan({"site": "pipeline/bind", "index": 0, "kind": "slow",
              "seconds": 0.05})
        t0 = time.perf_counter()
        model.fit(make_iter(), epochs=1, batch_size=16)
        assert time.perf_counter() - t0 >= 0.05
        assert model._iteration == 4

    def test_nan_injection_composes_with_nan_sentinel_skip(self):
        """An injected NaN batch drives the step's grads non-finite; the
        PR-2 NanSentinelListener skip policy drops the poisoned update
        in-graph and training continues with finite params."""
        import jax

        set_default_seed(1)
        model = small_model()
        sentinel = NanSentinelListener("skip", check_every_n=2)
        scores = CollectScoresIterationListener()
        model.set_listeners(sentinel, scores)
        plan({"site": "pipeline/bind", "index": 1, "kind": "nan"})
        model.fit(make_iter(), epochs=1, batch_size=16)
        assert len(sentinel.events) == 1
        assert sentinel.events[0]["iteration"] == 2
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree.leaves(model._params))
        # the non-poisoned steps' losses stayed finite
        finite = [s for i, s in scores.scores if i != 2]
        assert np.isfinite(finite).all()

    def test_env_driven_plan(self, monkeypatch):
        """The env route a relaunched subprocess uses."""
        monkeypatch.setenv(faultinject.ENV_PLAN, json.dumps(
            [{"site": "pipeline/bind", "index": 0, "kind": "transient"}]))
        faultinject.clear_plan()     # force env re-read
        prof = OpProfiler.get()
        prof.reset()
        set_default_seed(1)
        model = small_model()
        model.fit(make_iter(), epochs=1, batch_size=16)
        assert prof.counter_value("pipeline/retries") == 1


# ---------------------------------------------------------------------------
# exact resume parity
# ---------------------------------------------------------------------------

def _baseline(fit_kwargs, epochs=3):
    set_default_seed(99)
    model = small_model()
    scores = CollectScoresIterationListener()
    model.set_listeners(scores)
    model.fit(make_iter(), epochs=epochs, **fit_kwargs)
    return [s for _, s in scores.scores]


def _killed_and_resumed(tmp_path, fit_kwargs, crash_at, every=3, epochs=3):
    set_default_seed(99)
    model = small_model()
    scores = CollectScoresIterationListener()
    cl = CheckpointListener(str(tmp_path), save_every_n_iterations=every,
                            keep_last=2)
    model.set_listeners(scores, cl)
    plan({"site": "train/step", "index": crash_at, "kind": "crash"})
    with pytest.raises(faultinject.SimulatedCrash):
        model.fit(make_iter(), epochs=epochs, **fit_kwargs)
    faultinject.clear_plan()
    cl.close()
    last = CheckpointListener.last_checkpoint(str(tmp_path))
    assert last is not None
    # "fresh process": new model object, new listeners, same fit call
    resumed = small_model(seed=17)      # different init — must be overwritten
    scores2 = CollectScoresIterationListener()
    cl2 = CheckpointListener(str(tmp_path), save_every_n_iterations=every,
                             keep_last=2)
    resumed.set_listeners(scores2, cl2)
    resumed.fit(make_iter(), epochs=epochs, resume_from=last, **fit_kwargs)
    cl2.close()
    return [s for _, s in scores2.scores]


class TestExactResumeParity:
    """The acceptance bar: a run hard-killed at step k and resumed yields
    the SAME loss sequence as the uninterrupted run — bit-identical on
    CPU. Listener state rides the checkpoint, so the resumed
    CollectScores listener holds the full history."""

    def test_plain_fit_parity(self, tmp_path):
        base = _baseline({})
        got = _killed_and_resumed(tmp_path, {}, crash_at=7)
        assert got == base

    def test_steps_per_dispatch_parity(self, tmp_path):
        base = _baseline({"steps_per_dispatch": 4})
        got = _killed_and_resumed(tmp_path, {"steps_per_dispatch": 4},
                                  crash_at=7)
        assert got == base

    def test_parallel_wrapper_parity(self, tmp_path):
        from deeplearning4j_tpu.parallel import ParallelWrapper

        def run(resume_dir=None, crash_at=None):
            set_default_seed(99)
            model = small_model()
            pw = ParallelWrapper.Builder(model).workers(2).build()
            scores = CollectScoresIterationListener()
            listeners = [scores]
            cl = None
            if resume_dir is not None:
                cl = CheckpointListener(resume_dir,
                                        save_every_n_iterations=2,
                                        keep_last=2)
                listeners.append(cl)
            pw.set_listeners(*listeners)
            if crash_at is not None:
                plan({"site": "train/step", "index": crash_at,
                      "kind": "crash"})
                with pytest.raises(faultinject.SimulatedCrash):
                    pw.fit(make_iter(), epochs=2, batch_size=16)
                faultinject.clear_plan()
                cl.close()
                return None
            if resume_dir is not None:
                last = CheckpointListener.last_checkpoint(resume_dir)
                assert last is not None
                # fresh wrapper + model, exact continuation
                model2 = small_model(seed=17)
                pw2 = ParallelWrapper.Builder(model2).workers(2).build()
                scores2 = CollectScoresIterationListener()
                cl2 = CheckpointListener(resume_dir,
                                         save_every_n_iterations=2,
                                         keep_last=2)
                pw2.set_listeners(scores2, cl2)
                pw2.fit(make_iter(), epochs=2, batch_size=16,
                        resume_from=last)
                cl2.close()
                return [s for _, s in scores2.scores]
            pw.fit(make_iter(), epochs=2, batch_size=16)
            return [s for _, s in scores.scores]

        base = run()
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            run(resume_dir=d, crash_at=5)
            got = run(resume_dir=d)
        assert got == base

    def test_computation_graph_parity(self, tmp_path):
        from deeplearning4j_tpu.nn import (ComputationGraph,
                                           ComputationGraphConfiguration,
                                           NeuralNetConfiguration)

        def build():
            conf = (ComputationGraphConfiguration
                    .graph_builder(NeuralNetConfiguration.builder()
                                   .seed(7).updater(Adam(0.05))
                                   .activation("tanh"))
                    .add_inputs("in")
                    .add_layer("dense", L.DenseLayer(n_out=8), "in")
                    .add_layer("out", L.OutputLayer(n_out=2), "dense")
                    .set_outputs("out")
                    .set_input_types(InputType.feed_forward(4))
                    .build())
            return ComputationGraph(conf).init()

        set_default_seed(42)
        g1 = build()
        c1 = CollectScoresIterationListener()
        g1.set_listeners(c1)
        g1.fit(make_iter(), epochs=2, batch_size=16)
        base = [s for _, s in c1.scores]

        set_default_seed(42)
        g2 = build()
        cl = CheckpointListener(str(tmp_path), save_every_n_iterations=2,
                                keep_last=2)
        g2.set_listeners(CollectScoresIterationListener(), cl)
        plan({"site": "train/step", "index": 5, "kind": "crash"})
        with pytest.raises(faultinject.SimulatedCrash):
            g2.fit(make_iter(), epochs=2, batch_size=16)
        faultinject.clear_plan()
        cl.close()
        g3 = build()
        c3 = CollectScoresIterationListener()
        g3.set_listeners(c3)
        g3.fit(make_iter(), epochs=2, batch_size=16,
               resume_from=CheckpointListener.last_checkpoint(str(tmp_path)))
        assert [s for _, s in c3.scores] == base

    def test_mid_epoch_cursor_round_trip(self, tmp_path):
        """The cursor must place the resumed run mid-epoch: kill inside
        epoch 2, checkpoint mid-epoch, and the epoch counter + per-epoch
        shuffle land exactly where the uninterrupted run's did."""
        base = _baseline({}, epochs=4)
        got = _killed_and_resumed(tmp_path, {}, crash_at=9, every=5,
                                  epochs=4)
        assert got == base

    def test_resume_restores_rng_stream(self, tmp_path):
        """Dropout draws per-step keys from the stateful stream — a
        seed-only restore would desync it. The model here has dropout, so
        parity proves the KEY (not just the seed) was restored."""
        def dropout_model(seed=5):
            conf = (NeuralNetConfiguration.builder().seed(seed)
                    .updater(Sgd(learning_rate=0.1)).activation("tanh")
                    .list()
                    .layer(L.DenseLayer(n_out=16, dropout=0.5))
                    .layer(L.OutputLayer(n_out=2, loss="mcxent",
                                         activation="softmax"))
                    .set_input_type(InputType.feed_forward(4))
                    .build())
            return MultiLayerNetwork(conf).init()

        set_default_seed(7)
        m1 = dropout_model()
        s1 = CollectScoresIterationListener()
        m1.set_listeners(s1)
        m1.fit(make_iter(), epochs=2, batch_size=16)
        base = [s for _, s in s1.scores]

        set_default_seed(7)
        m2 = dropout_model()
        s2 = CollectScoresIterationListener()
        cl = CheckpointListener(str(tmp_path), save_every_n_iterations=3,
                                keep_last=2)
        m2.set_listeners(s2, cl)
        plan({"site": "train/step", "index": 5, "kind": "crash"})
        with pytest.raises(faultinject.SimulatedCrash):
            m2.fit(make_iter(), epochs=2, batch_size=16)
        faultinject.clear_plan()
        cl.close()
        m3 = dropout_model(seed=11)
        s3 = CollectScoresIterationListener()
        m3.set_listeners(s3)
        m3.fit(make_iter(), epochs=2, batch_size=16,
               resume_from=CheckpointListener.last_checkpoint(str(tmp_path)))
        assert [s for _, s in s3.scores] == base


# ---------------------------------------------------------------------------
# serving-side fault tolerance
# ---------------------------------------------------------------------------

class _SlowModel:
    """Stand-in for a wedged replica: output() blocks far past any
    reasonable deadline."""

    def __init__(self, delay_s: float):
        self.delay_s = delay_s

    def output(self, batch):
        time.sleep(self.delay_s)
        b = np.asarray(batch)
        return NDArray(np.zeros((b.shape[0], 2), np.float32))


class TestParallelInferenceFaults:
    def test_output_times_out_with_descriptive_error(self):
        from deeplearning4j_tpu.parallel import ParallelInference

        pi = (ParallelInference.Builder(_SlowModel(5.0))
              .inference_mode("batched").max_wait_ms(5)
              .request_timeout_ms(200).build())
        try:
            with pytest.raises(TimeoutError) as ei:
                pi.output(np.zeros((1, 4), np.float32))
            msg = str(ei.value)
            assert "queue depth" in msg and "replicas alive" in msg
        finally:
            pi.shutdown()

    def test_dead_replica_retired_and_pool_survives(self):
        from deeplearning4j_tpu.parallel import ParallelInference

        prof = OpProfiler.get()
        prof.reset()
        model = small_model()
        pi = (ParallelInference.Builder(model).inference_mode("batched")
              .workers(2).max_wait_ms(5).request_timeout_ms(5000).build())
        try:
            assert pi.output(np.zeros((2, 4), np.float32)).shape == (2, 2)
            plan({"site": "inference/worker", "kind": "dead_replica"})
            with pytest.raises(faultinject.DeadReplicaFault):
                pi.output(np.zeros((2, 4), np.float32))
            faultinject.clear_plan()
            assert pi.alive_replicas() == 1
            assert prof.counter_value("inference/replica_retired") == 1
            # the surviving replica keeps serving
            assert pi.output(np.zeros((3, 4), np.float32)).shape == (3, 2)
        finally:
            pi.shutdown()

    def test_shutdown_fails_queued_futures(self):
        from deeplearning4j_tpu.parallel import ParallelInference

        pi = (ParallelInference.Builder(_SlowModel(0.5))
              .inference_mode("batched").batch_limit(1).max_wait_ms(1)
              .build())
        # first request occupies the single worker; the rest stay queued
        futs = [pi.output_async(np.zeros((1, 4), np.float32))
                for _ in range(4)]
        pi.shutdown()
        resolved = [f for f in futs if f.done()]
        # every future resolves (result or error) — nobody hangs
        for f in futs:
            assert f.done()
        errs = [f for f in futs if f.exception(timeout=0) is not None]
        assert errs, resolved
        # post-shutdown submissions fail fast
        fut = pi.output_async(np.zeros((1, 4), np.float32))
        assert isinstance(fut.exception(timeout=0), RuntimeError)


# ---------------------------------------------------------------------------
# background helpers (satellite)
# ---------------------------------------------------------------------------

class TestBackgroundHygiene:
    def test_prefetch_worker_thread_named_and_joined(self):
        from deeplearning4j_tpu.common.background import staged_iter

        def slow_source():
            for i in range(100):
                yield i

        it = staged_iter(slow_source(), depth=1, host_prefetch=4)
        assert next(it) == 0
        names = {t.name for t in threading.enumerate()}
        assert "dl4j-prefetch-worker" in names
        it.close()    # abandoning the iterator must join the worker
        deadline = time.time() + 5
        while time.time() < deadline:
            if not any(t.name == "dl4j-prefetch-worker"
                       for t in threading.enumerate()):
                break
            time.sleep(0.01)
        assert not any(t.name == "dl4j-prefetch-worker"
                       for t in threading.enumerate())

    def test_worker_exception_carries_producer_traceback(self):
        from deeplearning4j_tpu.common.background import prefetch_iter

        def bad_source():
            yield 1
            raise ValueError("producer exploded")

        it = prefetch_iter(bad_source(), maxsize=2)
        assert next(it) == 1
        with pytest.raises(ValueError, match="producer exploded") as ei:
            list(it)
        # the producer's own frame must be visible in the chained traceback
        import traceback

        frames = "".join(traceback.format_exception(
            type(ei.value), ei.value, ei.value.__traceback__))
        assert "bad_source" in frames
