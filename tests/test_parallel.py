"""Parallel/distributed tests on the 8-device virtual CPU mesh — the
reference's ParallelWrapperTest/ParallelInferenceTest concerns (SURVEY.md
§4.5) plus tensor-parallel sharding (absent in the reference; TPU-native
addition)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.data import DataSet, IrisDataSetIterator
from deeplearning4j_tpu.learning import Adam, Sgd
from deeplearning4j_tpu.nn import InputType, MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.parallel import (DenseAllReduceAccumulator,
                                         EncodedGradientsAccumulator,
                                         ParallelInference, ParallelWrapper,
                                         apply_tp, make_mesh, shard_batch,
                                         tp_param_specs)


def small_model(updater=None, seed=1):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(updater or Adam(0.05)).activation("tanh")
            .list()
            .layer(L.DenseLayer(n_out=16))
            .layer(L.OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


class TestMesh:
    def test_make_mesh_shapes(self):
        m = make_mesh(data=8)
        assert m.shape == {"data": 8, "model": 1}
        m2 = make_mesh(data=4, model=2)
        assert m2.shape == {"data": 4, "model": 2}
        m3 = make_mesh(model=2)  # data inferred = 4
        assert m3.shape == {"data": 4, "model": 2}

    def test_too_many_devices_raises(self):
        with pytest.raises(ValueError, match="need"):
            make_mesh(data=99)

    def test_shard_batch_places_on_mesh(self):
        m = make_mesh(data=8)
        x = np.zeros((16, 4), np.float32)
        xs = shard_batch(m, x)
        assert len(xs.sharding.device_set) == 8


class TestParallelWrapper:
    def test_dp_training_converges(self):
        model = small_model()
        pw = (ParallelWrapper.Builder(model)
              .workers(8)
              .training_mode("shared_gradients")
              .build())
        it = IrisDataSetIterator(batch_size=144)  # 144 = 8*18 per shard
        pw.fit(it, epochs=40)
        ev = model.evaluate(IrisDataSetIterator(batch_size=150))
        assert ev.accuracy() > 0.9, ev.stats()

    def test_dp_matches_single_device_math(self):
        """Sync psum of shard gradients == single-device full-batch gradient:
        one step on 8 shards must equal one step on 1 device (Sgd, no rng)."""
        rng = np.random.RandomState(0)
        x = rng.randn(32, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)]

        m1 = small_model(updater=Sgd(0.1), seed=7)
        m2 = small_model(updater=Sgd(0.1), seed=7)
        np.testing.assert_allclose(np.asarray(m1._params[0]["W"]),
                                   np.asarray(m2._params[0]["W"]))
        m1.fit(DataSet(x, y))  # single device, full batch

        pw = ParallelWrapper.Builder(m2).workers(8).build()
        pw.fit(DataSet(x, y))  # 8-way sharded same batch
        np.testing.assert_allclose(np.asarray(m1._params[0]["W"]),
                                   np.asarray(m2._params[0]["W"]), atol=1e-5)

    def test_uneven_batch_padded(self):
        model = small_model()
        pw = ParallelWrapper.Builder(model).workers(8).build()
        x = np.random.randn(10, 4).astype(np.float32)  # not divisible by 8
        y = np.eye(3, dtype=np.float32)[np.random.randint(0, 3, 10)]
        pw.fit(DataSet(x, y))
        assert np.isfinite(model.score_value)

    def test_uneven_batch_matches_single_device_math(self):
        """Remainder batches must not rescale the gradient: wrap-padded rows
        are masked out and the loss renormalizes to mean-over-real-examples,
        so one 8-way step on 10 examples == one single-device step on 10."""
        rng = np.random.RandomState(3)
        x = rng.randn(10, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 10)]

        m1 = small_model(updater=Sgd(0.1), seed=7)
        m2 = small_model(updater=Sgd(0.1), seed=7)
        m1.fit(DataSet(x, y))  # single device, real rows only

        pw = ParallelWrapper.Builder(m2).workers(8).build()
        pw.fit(DataSet(x, y))  # sharded: 10 real + 6 wrap-padded masked rows
        np.testing.assert_allclose(np.asarray(m1._params[0]["W"]),
                                   np.asarray(m2._params[0]["W"]), atol=1e-5)
        np.testing.assert_allclose(float(m1._score_dev), float(m2._score_dev),
                                   atol=1e-5)

    def test_averaging_mode_accepted(self):
        model = small_model()
        pw = (ParallelWrapper.Builder(model).workers(4)
              .training_mode("averaging").averaging_frequency(5).build())
        pw.fit(IrisDataSetIterator(batch_size=148), epochs=1)
        assert np.isfinite(model.score_value)

    def test_encoded_accumulator_api_compat(self):
        model = small_model()
        acc = EncodedGradientsAccumulator(parties=8)
        pw = (ParallelWrapper.Builder(model).workers(8)
              .gradients_accumulator(acc).build())
        pw.fit(IrisDataSetIterator(batch_size=144), epochs=2)
        assert np.isfinite(model.score_value)
        assert acc.threshold_algorithm is not None  # config carried

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError, match="unknown training mode"):
            ParallelWrapper.Builder(small_model()).training_mode("async_chaos")


class TestTensorParallel:
    def test_tp_specs_shard_big_weights(self):
        from jax.sharding import PartitionSpec as P

        model = small_model()
        mesh = make_mesh(data=4, model=2)
        specs = jax.tree.leaves(
            tp_param_specs(model._params, mesh),
            is_leaf=lambda s: isinstance(s, P))
        specs = [s for s in specs if isinstance(s, P)]
        assert any(s == P(None, "model") for s in specs)  # dense W sharded

    def test_tp_forward_matches_replicated(self):
        model = small_model()
        mesh = make_mesh(data=1, model=2, devices=jax.devices()[:2])
        x = np.random.RandomState(0).randn(4, 4).astype(np.float32)
        expected = model.output(x).to_numpy()
        model._params = apply_tp(model._params, mesh)
        model._infer_fn = None  # retrace with sharded params
        got = model.output(x).to_numpy()
        np.testing.assert_allclose(got, expected, atol=1e-5)


class TestParallelInference:
    def test_sequential_mode(self):
        model = small_model()
        pi = (ParallelInference.Builder(model)
              .inference_mode("sequential").build())
        out = pi.output(np.zeros((2, 4), np.float32))
        assert out.shape == (2, 3)
        pi.shutdown()

    def test_batched_mode_coalesces_and_scatters(self):
        model = small_model()
        pi = (ParallelInference.Builder(model)
              .inference_mode("batched").batch_limit(8).max_wait_ms(50).build())
        futs = [pi.output_async(np.full((1, 4), float(i), np.float32))
                for i in range(6)]
        outs = [f.result(timeout=10) for f in futs]
        assert all(o.shape == (1, 3) for o in outs)
        # results must match per-request sequential execution (scatter order)
        for i, o in enumerate(outs):
            direct = model.output(np.full((1, 4), float(i), np.float32)).to_numpy()
            np.testing.assert_allclose(o.to_numpy(), direct, atol=1e-6)
        pi.shutdown()


class TestSharedTrainingMaster:
    def test_fit_and_kill_resume(self, tmp_path):
        """The §5.3 story: checkpoint, 'kill', resume from latest."""
        from deeplearning4j_tpu.parallel import SharedTrainingMaster

        model = small_model()
        master = (SharedTrainingMaster.Builder(batch_size_per_worker=18)
                  .checkpoint(str(tmp_path), every_n_iterations=1)
                  .build())
        master.fit(model, IrisDataSetIterator(batch_size=144), epochs=2)
        from deeplearning4j_tpu.optimize import CheckpointListener

        last = CheckpointListener.last_checkpoint(str(tmp_path))
        assert last is not None
        # simulate a fresh process resuming: master.fit loads the checkpoint
        fresh = small_model(seed=99)  # different init — must be overwritten
        resumed = master.fit(fresh, IrisDataSetIterator(batch_size=144), epochs=1)
        assert resumed._iteration > 2  # continued counting from the checkpoint
