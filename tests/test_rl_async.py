"""Async RL family + dueling DQN + HistoryProcessor tests (round-3 verdict
item 9: the rl4j async half). Reference: rl4j ``async`` package,
``HistoryProcessor`` (SURVEY §2.3)."""

from __future__ import annotations

import numpy as np
import pytest

from deeplearning4j_tpu.rl import (A3CConfiguration, A3CDiscreteDense,
                                   ACPolicy, ActorCriticNetwork,
                                   AsyncNStepQLearningDiscreteDense,
                                   AsyncQLConfiguration, DuelingQNetwork,
                                   GridWorld, HistoryProcessor,
                                   HistoryProcessorConfiguration,
                                   QLConfiguration, QLearningDiscreteDense,
                                   SameDiffQNetwork)


def _gridworld_factory(seed=0):
    return lambda: GridWorld(size=6)


class TestDuelingDQN:
    def test_dueling_head_structure(self):
        net = DuelingQNetwork(4, 3, hidden=(16,), seed=0)
        q = net.output(np.zeros((2, 4), np.float32)).to_numpy()
        assert q.shape == (2, 3)
        # dueling decomposition: mean-centered advantages mean the Q spread
        # comes from the A head; V shifts all actions equally. Check the
        # graph has both heads.
        names = set(net.sd._vars)
        assert "value_w" in names and "adv_w" in names

    @pytest.mark.slow
    def test_dueling_converges_on_gridworld(self):
        mdp = GridWorld(size=6)
        obs_dim = int(np.prod(mdp.observation_space.shape))
        net = DuelingQNetwork(obs_dim, mdp.action_space.n, hidden=(32,),
                              lr=5e-3, seed=1)
        conf = QLConfiguration(seed=1, max_step=2500, max_epoch_step=40,
                               batch_size=32, target_dqn_update_freq=100,
                               update_start=50, epsilon_nb_step=1200,
                               min_epsilon=0.05, double_dqn=True)
        learner = QLearningDiscreteDense(mdp, net, conf)
        learner.train()
        reward = learner.get_policy().play(GridWorld(size=6), max_steps=40)
        assert reward > 0.5, reward


class TestA3C:
    @pytest.mark.slow
    def test_converges_on_gridworld(self):
        # single worker for the convergence ASSERTION (deterministic);
        # the 2-worker path is smoke-tested below
        mdp0 = GridWorld(size=6)
        obs_dim = int(np.prod(mdp0.observation_space.shape))
        net = ActorCriticNetwork(obs_dim, mdp0.action_space.n,
                                 hidden=(32,), lr=6e-3, seed=2)
        conf = A3CConfiguration(seed=2, max_step=6000, max_epoch_step=40,
                                num_threads=1, nstep=8)
        a3c = A3CDiscreteDense(_gridworld_factory(), net, conf)
        rewards = a3c.train()
        assert len(rewards) > 5
        policy = a3c.get_policy()
        plays = [policy.play(GridWorld(size=6), max_steps=40)
                 for _ in range(5)]
        assert np.mean(plays) > 0.5, plays

    @pytest.mark.slow
    def test_two_workers_train_concurrently(self):
        mdp0 = GridWorld(size=6)
        obs_dim = int(np.prod(mdp0.observation_space.shape))
        net = ActorCriticNetwork(obs_dim, mdp0.action_space.n,
                                 hidden=(16,), lr=5e-3, seed=5)
        conf = A3CConfiguration(seed=5, max_step=800, max_epoch_step=40,
                                num_threads=2, nstep=8)
        a3c = A3CDiscreteDense(_gridworld_factory(), net, conf)
        rewards = a3c.train()
        assert a3c.step_count >= 800
        assert len(rewards) >= 2
        logits, value = net.policy_and_value(
            np.zeros((1, obs_dim), np.float32))
        assert np.isfinite(logits).all() and np.isfinite(value).all()

    def test_ac_policy_samples_and_greedy(self):
        net = ActorCriticNetwork(4, 3, hidden=(8,), seed=0)
        stochastic = ACPolicy(net, np.random.default_rng(0))
        greedy = ACPolicy(net, greedy=True)
        obs = np.zeros(4, np.float32)
        acts = {stochastic.next_action(obs) for _ in range(30)}
        assert len(acts) >= 2, "stochastic policy never explored"
        g = {greedy.next_action(obs) for _ in range(5)}
        assert len(g) == 1, "greedy policy must be deterministic"


class TestAsyncNStepQ:
    @pytest.mark.slow
    def test_converges_on_gridworld(self):
        # single worker for the convergence ASSERTION: thread scheduling
        # makes multi-worker runs nondeterministic despite fixed seeds
        mdp0 = GridWorld(size=6)
        obs_dim = int(np.prod(mdp0.observation_space.shape))
        net = SameDiffQNetwork(obs_dim, mdp0.action_space.n, hidden=(32,),
                               lr=8e-3, seed=3)
        conf = AsyncQLConfiguration(seed=3, max_step=8000,
                                    max_epoch_step=40, num_threads=1,
                                    nstep=5, target_dqn_update_freq=50,
                                    epsilon_nb_step=3000, min_epsilon=0.05)
        learner = AsyncNStepQLearningDiscreteDense(_gridworld_factory(),
                                                   net, conf)
        learner.train()
        reward = learner.get_policy().play(GridWorld(size=6), max_steps=40)
        assert reward > 0.5, reward

    @pytest.mark.slow
    def test_two_workers_train_concurrently(self):
        # multi-worker smoke: both threads contribute steps/episodes and
        # the shared net stays finite (no convergence assertion — async
        # interleaving is nondeterministic by design)
        mdp0 = GridWorld(size=6)
        obs_dim = int(np.prod(mdp0.observation_space.shape))
        net = SameDiffQNetwork(obs_dim, mdp0.action_space.n, hidden=(16,),
                               lr=5e-3, seed=4)
        conf = AsyncQLConfiguration(seed=4, max_step=800,
                                    max_epoch_step=40, num_threads=2,
                                    nstep=5, target_dqn_update_freq=20)
        learner = AsyncNStepQLearningDiscreteDense(_gridworld_factory(),
                                                   net, conf)
        rewards = learner.train()
        assert learner.step_count >= 800
        assert len(rewards) >= 2
        q = net.output(np.zeros((1, obs_dim), np.float32)).to_numpy()
        assert np.isfinite(q).all()


class TestHistoryProcessor:
    def test_stacking_and_initial_fill(self):
        hp = HistoryProcessor(HistoryProcessorConfiguration(
            history_length=3))
        hp.start_episode(np.asarray([1.0, 2.0]))
        assert hp.is_ready()
        h = hp.get_history()
        np.testing.assert_array_equal(h, np.tile([1.0, 2.0], (3, 1)))
        hp.add(np.asarray([3.0, 4.0]))
        h = hp.get_history()
        np.testing.assert_array_equal(h[-1], [3.0, 4.0])
        np.testing.assert_array_equal(h[0], [1.0, 2.0])
        assert hp.flat_history().shape == (6,)

    def test_skip_frame(self):
        hp = HistoryProcessor(HistoryProcessorConfiguration(
            history_length=2, skip_frame=3))
        taken = [hp.record(np.asarray([float(i)])) for i in range(7)]
        assert taken == [True, False, False, True, False, False, True]
        np.testing.assert_array_equal(hp.get_history(),
                                      [[3.0], [6.0]])

    def test_crop_and_rescale(self):
        conf = HistoryProcessorConfiguration(
            history_length=1, crop_top=2, crop_bottom=2, crop_left=4,
            crop_right=4, rescaled_width=4, rescaled_height=4)
        hp = HistoryProcessor(conf)
        frame = np.arange(20 * 16, dtype=np.float32).reshape(20, 16)
        out = hp.preprocess(frame)
        assert out.shape == (4, 4)
        # cropped region is rows 2:18, cols 4:12; corners map to its corners
        assert out[0, 0] == frame[2, 4]
