"""Online elastic data-parallel training (ISSUE 6; ROADMAP item 4(b);
arXiv:2004.13336): shrink/grow the worker set at a dispatch boundary with no
process restart — ``ParallelWrapper.resize`` bitwise parity against a fresh
run from the same state, encoded-residual carry through the permutation
layout, the ``device/loss`` fault kind, and the supervisor's
``shrink_and_continue`` policy with grow-back probes."""

import json
import zipfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common import faultinject, flightrec
from deeplearning4j_tpu.common.profiler import OpProfiler
from deeplearning4j_tpu.data import NDArrayDataSetIterator
from deeplearning4j_tpu.learning import Adam, Sgd
from deeplearning4j_tpu.ndarray.rng import get_random, set_default_seed
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.optimize.listeners import (
    CheckpointListener, CollectScoresIterationListener)
from deeplearning4j_tpu.parallel import (EncodedGradientsAccumulator,
                                         ParallelWrapper,
                                         ReduceScatterAccumulator,
                                         TrainingSupervisor, elastic_pool,
                                         make_mesh)
from deeplearning4j_tpu.parallel.distributed import (CLASS_DEVICE,
                                                     DEFAULT_POLICIES,
                                                     classify_failure)


@pytest.fixture(autouse=True)
def _clean():
    faultinject.clear_plan()
    OpProfiler.get().reset()
    yield
    faultinject.clear_plan()


def small_model(updater=None, seed=5):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(updater or Adam(learning_rate=0.05))
            .activation("tanh").list()
            .layer(L.DenseLayer(n_out=9))      # odd widths: uneven leaves
            .layer(L.OutputLayer(n_out=3, loss="mcxent",
                                 activation="softmax"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def make_iter(n=96, batch=24):
    rng = np.random.RandomState(7)
    x = rng.randn(n, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return NDArrayDataSetIterator(x, y, batch_size=batch, shuffle=True,
                                  seed=3)


def build_wrapper(model, workers=4, acc="zero1"):
    b = ParallelWrapper.Builder(model).workers(workers)
    if acc == "zero1":
        b.gradients_accumulator(ReduceScatterAccumulator())
    elif acc is not None:
        b.gradients_accumulator(acc)
    return b.build()


def host_state(model):
    """Owning host snapshot of the full training state (the same moves
    resize() makes before re-placing)."""
    return jax.tree.map(np.array, jax.device_get(
        (model._params, model._states, model._updater_state,
         getattr(model, "_acc_state", None) or None)))


def install_state(model, state):
    """Fresh-run-from-state: hand a host snapshot to a model a NEW wrapper
    will own (params/states re-materialized; updater/accumulator state
    left host-side so `_ensure_parallel_state` does its own resharding)."""
    params, states, upd, acc = state
    model._params = jax.tree.map(jnp.array, params)
    model._states = jax.tree.map(jnp.array, states)
    model._updater_state = upd
    model._acc_state = acc


def leaves_equal(a, b):
    la = jax.tree.leaves(jax.device_get(a))
    lb = jax.tree.leaves(jax.device_get(b))
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


def run_to_device_loss(pw, step, replica, epochs=3, **fit_kwargs):
    """Fit until the injected device loss fires; return the live cursor
    and the rng state at the boundary the fit unwound at."""
    faultinject.set_plan(faultinject.FaultPlan(
        [{"site": "device/loss", "index": step, "kind": "device_loss",
          "replica": replica}]))
    with pytest.raises(faultinject.DeviceLostError) as ei:
        pw.fit(make_iter(), epochs=epochs, **fit_kwargs)
    faultinject.clear_plan()
    m = pw.model
    assert ei.value.replica == replica
    return ((m._epoch - m._fit_epoch0, m._steps_in_epoch),
            get_random().get_state())


# ---------------------------------------------------------------------------
# device pool + fault kind + classification plumbing
# ---------------------------------------------------------------------------

class TestElasticPlumbing:
    def test_elastic_pool_orders_survivors_first(self):
        devs = jax.devices()
        mesh = make_mesh(data=3, model=1, devices=devs[:3])
        pool = elastic_pool(mesh)
        assert pool[:3] == list(mesh.devices.flat)
        assert set(pool) == set(devs)

    def test_elastic_pool_excludes_lost(self):
        devs = jax.devices()
        mesh = make_mesh(data=4, model=1, devices=devs[:4])
        pool = elastic_pool(mesh, exclude=[devs[1]])
        assert devs[1] not in pool
        assert pool[:3] == [devs[0], devs[2], devs[3]]

    def test_device_loss_fault_raises_and_counts(self):
        faultinject.set_plan(faultinject.FaultPlan(
            [{"site": "device/loss", "index": 2, "kind": "device_loss",
              "replica": 3}]))
        assert faultinject.fault_point("device/loss", 0) == []
        with pytest.raises(faultinject.DeviceLostError) as ei:
            faultinject.fault_point("device/loss", 2)
        assert ei.value.replica == 3
        assert OpProfiler.get().fault_stats()[
            "faults/device/loss/device_loss"] == 1

    def test_device_loss_classifies_as_device_failure(self):
        exc = faultinject.DeviceLostError("gone", replica=1)
        assert classify_failure(exc) == CLASS_DEVICE
        assert DEFAULT_POLICIES[CLASS_DEVICE] == "shrink_and_continue"


# ---------------------------------------------------------------------------
# encoded-accumulator residual carry (pure numpy; satellite 1)
# ---------------------------------------------------------------------------

class TestResidualResize:
    def _state(self, n, shapes=((5,), (3, 2))):
        rng = np.random.RandomState(0)
        return {
            "residual": [rng.randn(n, *s).astype(np.float32)
                         for s in shapes],
            "threshold": np.float32(1e-3),
            "steps": np.int32(7),
        }

    def test_shrink_folds_lost_residual_mass(self):
        acc = EncodedGradientsAccumulator()
        st = self._state(4)
        out = acc.resize_state(st, 4, 3, lost_replicas=[1])
        for old, new in zip(st["residual"], out["residual"]):
            assert new.shape == (3,) + old.shape[1:]
            # survivors 0/2/3 compact to rows 0/1/2; row 1's mass folds
            # into survivor 0 — total pending mass is preserved exactly
            np.testing.assert_array_equal(new[0], old[0] + old[1])
            np.testing.assert_array_equal(new[1], old[2])
            np.testing.assert_array_equal(new[2], old[3])
            np.testing.assert_allclose(new.sum(axis=0), old.sum(axis=0),
                                       rtol=1e-6)
        assert out["threshold"] == st["threshold"]
        assert out["steps"] == st["steps"]

    def test_grow_adds_zero_rows(self):
        acc = EncodedGradientsAccumulator()
        st = self._state(3)
        out = acc.resize_state(st, 3, 4)
        for old, new in zip(st["residual"], out["residual"]):
            np.testing.assert_array_equal(new[:3], old)
            assert not new[3].any()

    def test_shrink_without_loss_list_folds_tail(self):
        acc = EncodedGradientsAccumulator()
        st = self._state(4)
        out = acc.resize_state(st, 4, 3)
        for old, new in zip(st["residual"], out["residual"]):
            np.testing.assert_array_equal(new[0], old[0] + old[3])
            np.testing.assert_allclose(new.sum(axis=0), old.sum(axis=0),
                                       rtol=1e-6)

    def test_non_residual_state_passthrough(self):
        acc = EncodedGradientsAccumulator()
        assert acc.resize_state({"foo": 1}, 4, 3) == {"foo": 1}
        assert acc.resize_state(None, 4, 3) is None

    def test_stateless_accumulator_passthrough(self):
        acc = ReduceScatterAccumulator()
        st = {"anything": np.zeros(3)}
        assert acc.resize_state(st, 4, 2) is st


# ---------------------------------------------------------------------------
# resize mechanics + bitwise parity (the tentpole contract)
# ---------------------------------------------------------------------------

class TestResizeParity:
    def test_resize_same_count_is_noop(self):
        set_default_seed(99)
        pw = build_wrapper(small_model(), workers=3)
        pw.fit(make_iter(), epochs=1)
        assert pw.resize(3) == []
        assert OpProfiler.get().counter_value("elastic/resizes") == 0

    def test_resize_validations(self):
        set_default_seed(99)
        pw = build_wrapper(small_model(), workers=2)
        with pytest.raises(ValueError):
            pw.resize(0)
        with pytest.raises(ValueError):
            pw.resize(1, lost_replicas=[5])
        with pytest.raises(ValueError):
            pw.resize(len(jax.devices()) + 1)

    def test_shrink_midepoch_bitwise_parity_zero1(self):
        # elastic: 4 workers, device loss mid epoch 2, resize to 3,
        # continue — must equal a FRESH 3-worker run from the same state
        set_default_seed(99)
        m1 = small_model()
        pw = build_wrapper(m1, workers=4)
        cursor, rng = run_to_device_loss(pw, step=5, replica=1)
        assert cursor == (1, 1)          # mid-epoch: 4 steps/epoch
        snap = host_state(m1)
        it, ep = m1._iteration, m1._epoch
        removed = pw.resize(3, lost_replicas=[1])
        assert len(removed) == 1
        # the resize is a span on the flight-recorder timeline, with the
        # from/to counts a postmortem needs
        ev = [e for e in flightrec.events("elastic/resize")
              if e["ph"] == "B"][-1]
        assert ev["attrs"]["workers_from"] == 4
        assert ev["attrs"]["workers_to"] == 3
        assert ev["attrs"]["lost"] == [1]
        pw.fit(make_iter(), epochs=3, resume_cursor=cursor)

        set_default_seed(99)
        m2 = small_model()
        install_state(m2, snap)
        m2._iteration, m2._epoch = it, ep
        get_random().set_state(rng)
        pw2 = build_wrapper(m2, workers=3)
        pw2.fit(make_iter(), epochs=3, resume_cursor=cursor)
        assert leaves_equal(m1._params, m2._params)
        assert leaves_equal(m1._updater_state, m2._updater_state)

    def test_shrink_parity_dense_accumulator(self):
        set_default_seed(99)
        m1 = small_model(updater=Sgd(learning_rate=0.1))
        pw = build_wrapper(m1, workers=4, acc=None)
        cursor, rng = run_to_device_loss(pw, step=6, replica=0)
        snap = host_state(m1)
        it, ep = m1._iteration, m1._epoch
        pw.resize(3, lost_replicas=[0])
        pw.fit(make_iter(), epochs=3, resume_cursor=cursor)

        set_default_seed(99)
        m2 = small_model(updater=Sgd(learning_rate=0.1))
        install_state(m2, snap)
        m2._iteration, m2._epoch = it, ep
        get_random().set_state(rng)
        pw2 = build_wrapper(m2, workers=3, acc=None)
        pw2.fit(make_iter(), epochs=3, resume_cursor=cursor)
        assert leaves_equal(m1._params, m2._params)

    def test_growback_parity(self):
        # 3 -> 4 at an epoch boundary must equal a fresh 4-worker run
        # from the same state
        set_default_seed(99)
        m1 = small_model()
        pw = build_wrapper(m1, workers=3)
        pw.fit(make_iter(), epochs=1)
        snap = host_state(m1)
        it, ep = m1._iteration, m1._epoch
        rng = get_random().get_state()
        pw.resize(4)
        assert pw.workers_count == 4
        pw.fit(make_iter(), epochs=2, resume_cursor=(1, 0))

        set_default_seed(99)
        m2 = small_model()
        install_state(m2, snap)
        m2._iteration, m2._epoch = it, ep
        get_random().set_state(rng)
        pw2 = build_wrapper(m2, workers=4)
        pw2.fit(make_iter(), epochs=2, resume_cursor=(1, 0))
        assert leaves_equal(m1._params, m2._params)

    def test_one_compile_per_worker_count(self):
        # shrink then grow back: the per-worker-count executable cache
        # must hold the elastic contract at exactly one compile per count
        set_default_seed(99)
        prof = OpProfiler.get()
        m = small_model()
        pw = build_wrapper(m, workers=4)
        pw.fit(make_iter(), epochs=1)
        pw.resize(3)
        pw.fit(make_iter(), epochs=2, resume_cursor=(1, 0))
        pw.resize(4)
        pw.fit(make_iter(), epochs=3, resume_cursor=(2, 0))
        assert prof.trace_counts().get("trace/pw_fit_step") == 2
        stats = prof.elastic_stats()
        assert stats["resizes"] == 2
        assert stats["shrinks"] == 1 and stats["grows"] == 1
        assert stats["workers"] == 4

    def test_shrink_encoded_chunks_parity(self):
        # encoded accumulator + steps_per_dispatch chunks: the residual
        # carry rides the resize (no reset warning) and the continuation
        # equals a fresh 3-worker run handed the SAME folded residuals
        set_default_seed(99)
        m1 = small_model()
        acc1 = EncodedGradientsAccumulator()
        pw = build_wrapper(m1, workers=4, acc=acc1)
        cursor, rng = run_to_device_loss(pw, step=4, replica=2,
                                         steps_per_dispatch=2)
        snap = host_state(m1)
        it, ep = m1._iteration, m1._epoch
        pw.resize(3, lost_replicas=[2])
        res = jax.device_get(m1._acc_state["residual"])
        assert all(l.shape[0] == 3 for l in jax.tree.leaves(res))
        pw.fit(make_iter(), epochs=3, resume_cursor=cursor,
               steps_per_dispatch=2)

        set_default_seed(99)
        m2 = small_model()
        acc2 = EncodedGradientsAccumulator()
        params, states, upd, acc_st = snap
        acc_st = acc2.resize_state(acc_st, 4, 3, lost_replicas=[2])
        install_state(m2, (params, states, upd, acc_st))
        m2._iteration, m2._epoch = it, ep
        get_random().set_state(rng)
        pw2 = build_wrapper(m2, workers=3, acc=acc2)
        pw2.fit(make_iter(), epochs=3, resume_cursor=cursor,
                steps_per_dispatch=2)
        assert leaves_equal(m1._params, m2._params)
        assert leaves_equal(m1._acc_state["residual"],
                            m2._acc_state["residual"])

    def test_checkpoint_records_live_workers_and_resumes(self, tmp_path):
        # shrink composed with checkpoint resume: a snapshot taken AFTER
        # the shrink records workers=3 in resume.json and restores into
        # a fresh 3-worker wrapper bit-exactly
        set_default_seed(99)
        m1 = small_model()
        pw = build_wrapper(m1, workers=4)
        cursor, rng = run_to_device_loss(pw, step=5, replica=1)
        pw.resize(3, lost_replicas=[1])
        cl = CheckpointListener(str(tmp_path))
        path = cl.save_now(m1, "post_shrink", rng_state=rng)
        cl.close()
        with zipfile.ZipFile(path) as zf:
            resume = json.loads(zf.read("resume.json"))
        assert resume["cursor"]["workers"] == 3
        assert resume["cursor"] == {"epochs_done": cursor[0],
                                    "steps_in_epoch": cursor[1],
                                    "workers": 3}
        pw.fit(make_iter(), epochs=3, resume_cursor=cursor)

        set_default_seed(99)
        m2 = small_model()
        pw2 = build_wrapper(m2, workers=3)
        pw2.fit(make_iter(), epochs=3, resume_from=path)
        assert m2._ckpt_workers == 3
        assert leaves_equal(m1._params, m2._params)


# ---------------------------------------------------------------------------
# supervisor-driven elastic drills (satellites 3 + the end-to-end criterion)
# ---------------------------------------------------------------------------

class TestSupervisorElastic:
    def test_supervised_shrink_drill_bitwise_parity(self, tmp_path):
        # THE acceptance drill: device/loss kills 1 of 4 workers
        # mid-epoch; the supervised run completes without a restart and
        # its final params equal a manually-resized reference
        set_default_seed(99)
        m1 = small_model()
        pw = build_wrapper(m1, workers=4)
        faultinject.set_plan(faultinject.FaultPlan(
            [{"site": "device/loss", "index": 5, "kind": "device_loss",
              "replica": 1}]))
        sup = TrainingSupervisor(pw, checkpoint_dir=str(tmp_path),
                                 elastic_grow=False)
        res = sup.fit(make_iter, epochs=3)
        faultinject.clear_plan()
        assert res.status == "completed"
        assert res.restarts == 0          # progress accounting: no budget
        assert [h["policy"] for h in res.history] == ["shrink_and_continue"]
        assert pw.workers_count == 3
        stats = OpProfiler.get().elastic_stats()
        assert stats["shrinks"] == 1 and stats["workers"] == 3
        assert OpProfiler.get().counter_value("supervisor/shrinks") == 1

        # manual reference: same fault, caught by hand, manual resize
        set_default_seed(99)
        m2 = small_model()
        pw2 = build_wrapper(m2, workers=4)
        cursor, _rng = run_to_device_loss(pw2, step=5, replica=1)
        pw2.resize(3, lost_replicas=[1])
        pw2.fit(make_iter(), epochs=3, resume_cursor=cursor)
        assert leaves_equal(m1._params, m2._params)

    def test_shrink_counts_as_progress_never_storms(self, tmp_path):
        # a device loss must complete with max_restarts=0 and
        # storm_threshold=1: shrink-and-continue consumes neither
        set_default_seed(99)
        pw = build_wrapper(small_model(), workers=4)
        faultinject.set_plan(faultinject.FaultPlan(
            [{"site": "device/loss", "index": 3, "kind": "device_loss",
              "replica": 3}]))
        sup = TrainingSupervisor(pw, checkpoint_dir=str(tmp_path),
                                 max_restarts=0, storm_threshold=1,
                                 elastic_grow=False)
        res = sup.fit(make_iter, epochs=2)
        faultinject.clear_plan()
        assert res.status == "completed"
        assert res.restarts == 0
        assert pw.workers_count == 3

    def test_fallback_to_restart_without_resize_target(self, tmp_path):
        # a target with no resize() (plain MLN) must take the documented
        # checkpoint-restart fallback and still heal
        set_default_seed(99)
        model = small_model()
        faultinject.set_plan(faultinject.FaultPlan(
            [{"site": "device/loss", "index": 3, "kind": "device_loss"}]))
        sup = TrainingSupervisor(model, checkpoint_dir=str(tmp_path),
                                 backoff_base_s=0.01)
        res = sup.fit(make_iter, epochs=2)
        faultinject.clear_plan()
        assert res.status == "completed"
        assert res.restarts == 1
        assert [h["policy"] for h in res.history] == ["restart"]

    def test_resize_never_reinstates_dead_device_from_cache(
            self, monkeypatch):
        # a later resize to a cached worker count must re-probe once-lost
        # devices: a still-dead one is excluded (cache rejected, mesh
        # rebuilt), never silently reinstated from the stashed mesh
        set_default_seed(99)
        pw = build_wrapper(small_model(), workers=4)
        pw.fit(make_iter(), epochs=1)
        dead = list(pw.mesh.devices.flat)[1]
        pw.resize(3, lost_replicas=[1])
        from deeplearning4j_tpu.parallel import wrapper as wmod
        monkeypatch.setattr(wmod, "probe_device", lambda d: d is not dead)
        pw.resize(4)
        assert pw.workers_count == 4
        assert dead not in set(pw.mesh.devices.flat)   # a spare took over

    def test_grow_failure_limit_gives_up_and_stays_shrunk(
            self, tmp_path, monkeypatch):
        # the lost device answers probes but the grow RESIZE keeps
        # failing: after grow_failure_limit consecutive failures the
        # supervisor abandons grow-back instead of unwinding training
        # every backoff period forever
        set_default_seed(99)
        pw = build_wrapper(small_model(), workers=4)
        orig = pw.resize

        def flaky(n, **kw):
            if n > pw.workers_count and not kw.get("lost_replicas"):
                raise RuntimeError("placement OOM on returning device")
            return orig(n, **kw)

        monkeypatch.setattr(pw, "resize", flaky)
        faultinject.set_plan(faultinject.FaultPlan(
            [{"site": "device/loss", "index": 2, "kind": "device_loss",
              "replica": 1}]))
        sup = TrainingSupervisor(pw, checkpoint_dir=str(tmp_path),
                                 grow_probe_base_s=0.0,
                                 grow_probe_max_s=0.01,
                                 grow_failure_limit=2)
        res = sup.fit(make_iter, epochs=6)
        faultinject.clear_plan()
        assert res.status == "completed"
        assert res.restarts == 0
        assert pw.workers_count == 3                   # stayed shrunk
        policies = [h["policy"] for h in res.history]
        assert policies.count("grow_failed") == 2
        assert "grow_and_continue" not in policies
        assert OpProfiler.get().counter_value("elastic/grow_abandoned") == 1

    def test_second_loss_disarms_pending_grow_and_merges(self, tmp_path):
        # a grow-back armed before a SECOND device loss must not fire
        # (it would reinstate a cached mesh containing the new dead
        # device): the shrink disarms it and the probe list merges both
        # losses, with the ORIGINAL full count kept as the grow target
        set_default_seed(99)
        pw = build_wrapper(small_model(), workers=4)
        pw.fit(make_iter(), epochs=1)
        sup = TrainingSupervisor(pw, checkpoint_dir=str(tmp_path))
        removed_a = sup._apply_shrink([2])
        assert sup._grow["target"] == 4
        sup._resize_request = 4            # probe found device A healthy
        removed_b = sup._apply_shrink([0])
        assert sup._resize_request is None
        assert sup._grow["target"] == 4
        assert set(sup._grow["devices"]) == set(removed_a + removed_b)
        assert pw.workers_count == 2

    @pytest.mark.slow
    def test_supervised_growback_drill(self, tmp_path):
        # shrink on device loss, then the grow-back probe returns the
        # device at the next dispatch boundary; every step still lands
        set_default_seed(99)
        m = small_model()
        scores = CollectScoresIterationListener()
        pw = build_wrapper(m, workers=4)
        pw.set_listeners(scores)
        faultinject.set_plan(faultinject.FaultPlan(
            [{"site": "device/loss", "index": 3, "kind": "device_loss",
              "replica": 2}]))
        sup = TrainingSupervisor(pw, checkpoint_dir=str(tmp_path),
                                 elastic_grow=True, grow_probe_base_s=0.0)
        res = sup.fit(make_iter, epochs=6)
        faultinject.clear_plan()
        assert res.status == "completed"
        assert res.restarts == 0
        assert pw.workers_count == 4
        classes = [h["class"] for h in res.history]
        assert classes[0] == "device_failure"
        assert "elastic_grow" in classes
        assert len(scores.scores) == 6 * 4      # no step lost or doubled
        stats = OpProfiler.get().elastic_stats()
        assert stats["shrinks"] == 1 and stats["grows"] >= 1
        assert stats["workers"] == 4

    @pytest.mark.slow
    def test_grow_probe_failure_backoff(self, tmp_path):
        # a still-dead device (elastic/probe fault) keeps the axis shrunk
        # through the failed probes, then grows back when probes succeed
        set_default_seed(99)
        pw = build_wrapper(small_model(), workers=4)
        faultinject.set_plan(faultinject.FaultPlan([
            {"site": "device/loss", "index": 2, "kind": "device_loss",
             "replica": 0},
            {"site": "elastic/probe", "kind": "dead_replica", "times": 2},
        ]))
        sup = TrainingSupervisor(pw, checkpoint_dir=str(tmp_path),
                                 elastic_grow=True,
                                 grow_probe_base_s=0.05,
                                 grow_probe_max_s=0.1)
        res = sup.fit(make_iter, epochs=8)
        faultinject.clear_plan()
        assert res.status == "completed"
        assert pw.workers_count == 4
        prof = OpProfiler.get()
        assert prof.counter_value("elastic/probe_failures") == 2
        assert prof.counter_value("elastic/probes") >= 3
