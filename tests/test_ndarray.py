"""NDArray semantics tests — ports the reference's NDArrayTest* concerns
(views/strides/cast/in-place ops) to the TPU build (SURVEY.md §4.1/§7.3.2)."""

import numpy as np
import pytest

import deeplearning4j_tpu as d4t
from deeplearning4j_tpu import factory as nd


class TestBasics:
    def test_create_shape_dtype(self):
        a = nd.create([[1.0, 2.0], [3.0, 4.0]])
        assert a.shape == (2, 2)
        assert a.data_type() == d4t.DataType.FLOAT

    def test_zeros_ones(self):
        assert nd.zeros(3, 4).to_numpy().sum() == 0
        assert nd.ones(3, 4).to_numpy().sum() == 12

    def test_dtype_zoo(self):
        for dt in (d4t.DataType.FLOAT, d4t.DataType.DOUBLE, d4t.DataType.BFLOAT16,
                   d4t.DataType.INT32, d4t.DataType.INT64, d4t.DataType.UINT8,
                   d4t.DataType.BOOL):
            a = nd.zeros(2, 2, dtype=dt)
            assert a.data_type() == dt, dt

    def test_cast(self):
        a = nd.create([1.5, 2.5])
        b = a.cast(d4t.DataType.INT32)
        assert b.data_type() == d4t.DataType.INT32
        assert b.to_numpy().tolist() == [1, 2]

    def test_arange_linspace_eye(self):
        assert nd.arange(5).to_numpy().tolist() == [0, 1, 2, 3, 4]
        assert np.allclose(nd.linspace(0, 1, 5).to_numpy(), [0, 0.25, 0.5, 0.75, 1])
        assert np.allclose(nd.eye(3).to_numpy(), np.eye(3))


class TestInPlace:
    def test_addi_muli(self):
        a = nd.create([1.0, 2.0, 3.0])
        a.addi(1.0).muli(2.0)
        assert a.to_numpy().tolist() == [4.0, 6.0, 8.0]

    def test_assign(self):
        a = nd.zeros(2, 3)
        a.assign(7.0)
        assert (a.to_numpy() == 7).all()

    def test_put_scalar(self):
        a = nd.zeros(2, 2)
        a.put_scalar((0, 1), 5.0)
        assert a.get_double(0, 1) == 5.0
        assert a.to_numpy().sum() == 5.0


class TestViews:
    def test_view_read(self):
        a = nd.create(np.arange(12).reshape(3, 4), dtype=d4t.DataType.FLOAT)
        row = a[1]
        assert row.to_numpy().tolist() == [4, 5, 6, 7]

    def test_view_write_aliases_base(self):
        """The SURVEY §7.3.2 hard case: addi on a slice must update the base."""
        a = nd.create(np.zeros((3, 4)), dtype=d4t.DataType.FLOAT)
        row = a[1]
        row.addi(5.0)
        expected = np.zeros((3, 4))
        expected[1] = 5.0
        assert np.allclose(a.to_numpy(), expected)

    def test_view_of_view_write(self):
        a = nd.create(np.zeros((3, 4)), dtype=d4t.DataType.FLOAT)
        row = a[2]
        elem = row[1:3]
        elem.assign(9.0)
        assert a.to_numpy()[2, 1] == 9.0 and a.to_numpy()[2, 2] == 9.0
        assert a.to_numpy().sum() == 18.0

    def test_setitem(self):
        a = nd.zeros(3, 3)
        a[0, :] = nd.ones(3)
        assert a.to_numpy()[0].sum() == 3

    def test_tensor_along_dimension(self):
        a = nd.create(np.arange(24).reshape(2, 3, 4), dtype=d4t.DataType.FLOAT)
        tad = a.tensor_along_dimension(1, 2)  # spans dim 2; index 1 of (2,3) flattened
        assert tad.to_numpy().tolist() == [4, 5, 6, 7]

    def test_dup_detaches(self):
        a = nd.create([1.0, 2.0])
        b = a.dup()
        b.addi(10)
        assert a.to_numpy().tolist() == [1.0, 2.0]


class TestShapeOps:
    def test_reshape_permute(self):
        a = nd.arange(6, dtype=d4t.DataType.FLOAT).reshape(2, 3)
        assert a.shape == (2, 3)
        assert a.permute(1, 0).shape == (3, 2)
        assert a.T.shape == (3, 2)

    def test_broadcast(self):
        a = nd.ones(1, 3).broadcast(4, 3)
        assert a.shape == (4, 3)

    def test_concat_stack(self):
        a, b = nd.ones(2, 3), nd.zeros(2, 3)
        assert nd.concat(0, a, b).shape == (4, 3)
        assert nd.concat(1, a, b).shape == (2, 6)
        assert nd.stack(0, a, b).shape == (2, 2, 3)


class TestArithmetic:
    def test_ops(self):
        a, b = nd.create([1.0, 2.0]), nd.create([3.0, 4.0])
        assert (a + b).to_numpy().tolist() == [4.0, 6.0]
        assert (a - b).to_numpy().tolist() == [-2.0, -2.0]
        assert (a * b).to_numpy().tolist() == [3.0, 8.0]
        assert (b / a).to_numpy().tolist() == [3.0, 2.0]
        assert a.rsub(1.0).to_numpy().tolist() == [0.0, -1.0]
        assert a.rdiv(2.0).to_numpy().tolist() == [2.0, 1.0]

    def test_mmul_rides_dot(self):
        a = nd.create([[1.0, 2.0], [3.0, 4.0]])
        b = nd.eye(2)
        assert np.allclose(a.mmul(b).to_numpy(), a.to_numpy())

    def test_gemm(self):
        a = nd.create([[1.0, 2.0], [3.0, 4.0]])
        out = nd.gemm(a, a, transpose_b=True, alpha=2.0)
        assert np.allclose(out.to_numpy(), 2.0 * (a.to_numpy() @ a.to_numpy().T))


class TestReductions:
    def test_reductions(self):
        a = nd.create([[1.0, 2.0], [3.0, 4.0]])
        assert a.sum().get_double() == 10.0
        assert a.mean().get_double() == 2.5
        assert a.max().get_double() == 4.0
        assert a.min().get_double() == 1.0
        assert a.sum(0).to_numpy().tolist() == [4.0, 6.0]
        assert a.sum(1).to_numpy().tolist() == [3.0, 7.0]
        assert a.argmax(1).to_numpy().tolist() == [1, 1]
        assert abs(a.norm2().get_double() - np.sqrt(30.0)) < 1e-5

    def test_std_bias_correction(self):
        a = nd.create([1.0, 2.0, 3.0, 4.0])
        assert abs(a.std().get_double() - np.std(a.to_numpy(), ddof=1)) < 1e-6
        assert abs(a.std(bias_corrected=False).get_double() - np.std(a.to_numpy())) < 1e-6


class TestRng:
    def test_reproducible(self):
        r = d4t.get_random()
        r.set_seed(42)
        a = r.uniform((100,))
        r.set_seed(42)
        b = r.uniform((100,))
        assert np.allclose(a.to_numpy(), b.to_numpy())

    def test_streams_differ(self):
        r = d4t.get_random()
        a = r.uniform((100,))
        b = r.uniform((100,))
        assert not np.allclose(a.to_numpy(), b.to_numpy())

    def test_gaussian_moments(self):
        r = d4t.get_random()
        g = r.gaussian((20000,), mean=1.0, std=2.0).to_numpy()
        assert abs(g.mean() - 1.0) < 0.1
        assert abs(g.std() - 2.0) < 0.1

    def test_bernoulli(self):
        r = d4t.get_random()
        b = r.bernoulli((10000,), p=0.3).to_numpy()
        assert abs(b.mean() - 0.3) < 0.05


class TestEnvironment:
    def test_singleton_flags(self):
        env = d4t.Environment.get()
        assert env is d4t.Environment.get()
        env.set_verbose(True)
        assert env.is_verbose()
        env.set_verbose(False)
        assert env.num_devices() >= 8  # virtual CPU mesh from conftest


class TestReviewRegressions:
    """Cases from the round-1 code review findings."""

    def test_wide_dtypes_without_conftest_help(self):
        # x64 is enabled by the package itself, not just the test harness
        a = nd.create([2**40], dtype=d4t.DataType.INT64)
        assert a.to_numpy()[0] == 2**40
        assert nd.zeros(2, dtype=d4t.DataType.DOUBLE).data_type() == d4t.DataType.DOUBLE

    def test_fancy_index_view(self):
        base = nd.create([10.0, 20.0, 30.0])
        sel = base[nd.create([0, 2], dtype=d4t.DataType.INT32)]
        assert sel.to_numpy().tolist() == [10.0, 30.0]
        sel.addi(1.0)
        assert base.to_numpy().tolist() == [11.0, 20.0, 31.0]

    def test_tad_negative_dim(self):
        t = nd.create(np.arange(6).reshape(2, 3), dtype=d4t.DataType.FLOAT)
        assert t.tensor_along_dimension(0, -1).to_numpy().tolist() == [0, 1, 2]

    def test_elementwise_eq(self):
        a, b = nd.create([1.0, 2.0]), nd.create([1.0, 5.0])
        assert (a == b).to_numpy().tolist() == [True, False]
        assert (a != b).to_numpy().tolist() == [False, True]

    def test_equals_to_f64_precision(self):
        a = nd.create([16777216.0], dtype=d4t.DataType.DOUBLE)
        b = nd.create([16777217.0], dtype=d4t.DataType.DOUBLE)
        assert not a.equals_to(b)
