"""XLA performance observatory (ISSUE 15): executable census, roofline
attribution, HBM watermarks, and the benchtrack regression gates.

Doubles as the DRILL CORPUS for graftlint's executable-census rule and
the xprof/exec + xprof/hbm flight-recorder events: the EXPECTED_SITES
table below carries every registered census name literally, and the
live tests exercise the core trainer families (mln fit/infer, fleet,
serving AOT, fused-Pallas counted sub-executable)."""

import gc
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.common import flightrec, xprof
from deeplearning4j_tpu.common.profiler import OpProfiler
from deeplearning4j_tpu.data import NDArrayDataSetIterator
from deeplearning4j_tpu.learning import Adam, Nesterovs
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf import layers as L

# the census registry, literally — the executable-census lint rule
# requires every registered name referenced from the test corpus, and
# this table IS that reference (asserted complete below)
EXPECTED_SITES = [
    "data/feature_transform",
    "embeddings/lookup", "embeddings/update",
    "fleet/infer", "fleet/step",
    "graph/fit_chunk", "graph/fit_step", "graph/infer",
    "mln/fit_chunk", "mln/fit_step", "mln/infer", "mln/pretrain_step",
    "mln/tbptt_step",
    "nlp/fasttext_block", "nlp/glove_block",
    "nlp/pv_dbow_block", "nlp/pv_dm_block",
    "nlp/pv_pos_map", "nlp/pv_subsample",
    "nlp/w2v_cbow_block", "nlp/w2v_sg_block", "nlp/w2v_subsample",
    "nlp/w2v_table_block",
    "pallas/update_bucket",
    "pipeline/fit_step", "pipeline/hetero_fwd", "pipeline/hetero_step",
    "pipeline/legacy_fwd", "pipeline/legacy_step",
    "pw/fit_chunk", "pw/fit_step",
    "samediff/exec", "samediff/fit_step", "samediff/grad",
    "serving/bucket",
    "transfer/featurize",
]


def _mlp(n_in=16, hidden=24, n_out=4, updater=None, seed=3):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(updater or Nesterovs(learning_rate=0.01,
                                          momentum=0.9))
            .activation("relu").weight_init("xavier").list()
            .layer(L.DenseLayer(n_out=hidden))
            .layer(L.OutputLayer(n_out=n_out, loss="mcxent",
                                 activation="softmax"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _batches(n=96, n_in=16, n_out=4, batch=32, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, n_in).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rng.randint(0, n_out, n)]
    return x, y, NDArrayDataSetIterator(x, y, batch_size=batch)


@pytest.fixture
def fresh_census():
    xprof.reset()
    xprof.configure(enabled=True)
    yield
    xprof.reset()
    xprof.configure(enabled=True)


class TestCensusCore:
    def test_unknown_site_refused(self):
        with pytest.raises(ValueError, match="unknown executable-census"):
            xprof.register_jit("bogus/site", jax.jit(lambda x: x))

    def test_wrapper_counts_calls_and_generations(self, fresh_census):
        f = xprof.register_jit("mln/infer", jax.jit(lambda x: x * 2))
        f(jnp.ones((4,)))
        f(jnp.ones((4,)))
        e = xprof.census()["mln/infer"]
        assert e["calls"] == 2 and e["generations"] == 1
        f(jnp.ones((8,)))           # new signature = new executable
        e = xprof.census()["mln/infer"]
        assert e["calls"] == 3 and e["generations"] == 2
        assert e["compile_s"] > 0

    def test_wrapper_is_call_transparent(self, fresh_census):
        jitted = jax.jit(lambda x: x + 1)
        f = xprof.register_jit("mln/infer", jitted)
        # attribute fall-through: AOT introspection sees the jit
        lowered = f.lower(jnp.ones((3,)))
        assert lowered.cost_analysis() is not None
        assert f.wrapped is jitted

    def test_disabled_census_counts_nothing(self, fresh_census):
        f = xprof.register_jit("mln/infer", jax.jit(lambda x: x))
        xprof.configure(enabled=False)
        try:
            assert float(f(jnp.ones((2,)))[0]) == 1.0
            assert xprof.census()["mln/infer"]["calls"] == 0
        finally:
            xprof.configure(enabled=True)

    def test_reregistration_accumulates(self, fresh_census):
        # a rebuilt step (set_params, telemetry flip) re-registers the
        # same name — that IS the retrace-generation ledger
        f1 = xprof.register_jit("mln/fit_step", jax.jit(lambda x: x))
        f1(jnp.ones((2,)))
        f2 = xprof.register_jit("mln/fit_step", jax.jit(lambda x: -x))
        f2(jnp.ones((2,)))
        e = xprof.census()["mln/fit_step"]
        assert e["calls"] == 2 and e["generations"] == 2

    def test_register_aot_extracts_immediately(self, fresh_census):
        jitted = jax.jit(lambda a, b: a @ b)
        aval = jax.ShapeDtypeStruct((8, 16), jnp.float32)
        bval = jax.ShapeDtypeStruct((16, 4), jnp.float32)
        exe = jitted.lower(aval, bval).compile()
        xprof.register_aot("serving/bucket", exe, variant="(8, 16)",
                           compile_s=0.25)
        e = xprof.census()["serving/bucket"]
        assert e["variants"] == 1 and e["compile_s"] == 0.25
        assert e["cost"]["flops"] == pytest.approx(2 * 8 * 16 * 4)
        assert e["memory"]["argument_bytes"] > 0
        # a second bucket accumulates onto the same entry
        xprof.register_aot("serving/bucket", exe, variant="again")
        e = xprof.census()["serving/bucket"]
        assert e["variants"] == 2
        assert e["cost"]["flops"] == pytest.approx(2 * 2 * 8 * 16 * 4)

    def test_register_aot_none_is_noop(self, fresh_census):
        xprof.register_aot("serving/bucket", None)
        assert "serving/bucket" not in xprof.census()

    def test_reset_opens_a_clean_window_without_orphaning(
            self, fresh_census):
        # a live wrapper must re-enter the census after reset() — the
        # entry is resolved by name per dispatch, never captured
        f = xprof.register_jit("mln/fit_step", jax.jit(lambda x: x * 2),
                               donate=(0,))
        f(jnp.ones((4,)))
        xprof.reset()
        assert xprof.census() == {}
        f(jnp.ones((4,)))            # warm cache, fresh window
        e = xprof.census()["mln/fit_step"]
        assert e["calls"] == 1
        # the warm executable counts as this window's first generation
        # and its avals are re-captured so analyze() still works
        assert e["generations"] == 1
        assert e["fingerprint"]["donate_argnums"] == (0,)
        assert list(xprof.analyze()) == ["mln/fit_step"]

    def test_note_subexec_counted_last_trace_wins(self, fresh_census):
        xprof.note_subexec("pallas/update_bucket", flops=100.0,
                           bytes_accessed=400.0, kind="adam")
        # a re-trace (rebuild / analysis lowering) must not inflate the
        # row — the cost always describes ONE parent execution
        xprof.note_subexec("pallas/update_bucket", flops=100.0,
                           bytes_accessed=400.0, kind="adam")
        e = xprof.census()["pallas/update_bucket"]
        assert e["subexec"] is True and e["cost_source"] == "counted"
        assert e["generations"] == 2
        assert e["cost"]["flops"] == 100.0
        assert e["cost"]["bytes_accessed"] == 400.0


class TestAnalysis:
    def test_xla_cost_matches_hand_computed_flops(self, fresh_census):
        # roofline join against hand-computed matmul flops: XLA counts
        # x@w on (B,K)x(K,N) as 2*B*K*N
        B, K, N = 8, 32, 6
        f = xprof.register_jit("mln/infer",
                               jax.jit(lambda x, w: x @ w))
        f(jnp.ones((B, K), jnp.float32), jnp.ones((K, N), jnp.float32))
        res = xprof.analyze()
        assert "mln/infer" in res
        e = xprof.census()["mln/infer"]
        assert e["cost_source"] == "xla"
        assert e["cost"]["flops"] == pytest.approx(2 * B * K * N)
        # bytes accessed: inputs + output, f32
        assert e["cost"]["bytes_accessed"] == pytest.approx(
            4 * (B * K + K * N + B * N))
        assert e["memory"]["argument_bytes"] == 4 * (B * K + K * N)
        assert e["memory"]["output_bytes"] == 4 * B * N

    def test_analyze_is_idempotent_per_generation(self, fresh_census):
        f = xprof.register_jit("mln/infer", jax.jit(lambda x: x * 3))
        f(jnp.ones((4,)))
        assert list(xprof.analyze()) == ["mln/infer"]
        assert xprof.analyze() == {}      # nothing new to analyze
        f(jnp.ones((6,)))                 # new generation -> re-analyzed
        assert list(xprof.analyze()) == ["mln/infer"]

    def test_counted_fallback_when_backend_analysis_fails(
            self, fresh_census, monkeypatch):
        f = xprof.register_jit("mln/infer", jax.jit(lambda x: x + 1))
        f(jnp.ones((10,), jnp.float32))
        # backend returns nothing: both analysis surfaces unavailable
        monkeypatch.setattr(xprof, "_cost_dict", lambda obj: None)
        monkeypatch.setattr(xprof, "_memory_dict", lambda obj: None)
        res = xprof.analyze()
        e = res["mln/infer"]
        assert e["cost_source"] == "counted"
        # counted bytes = input avals (+ output when the lowering's
        # out_info is available)
        assert e["cost"]["bytes_accessed"] >= 40
        ledger = xprof.ledger()
        assert ledger["mln/infer/counted"] == 1.0

    def test_collected_executable_degrades_gracefully(self, fresh_census):
        f = xprof.register_jit("mln/infer", jax.jit(lambda x: x + 2))
        f(jnp.ones((4,)))
        del f
        gc.collect()
        res = xprof.analyze()
        e = res["mln/infer"]
        assert e["cost_source"] == "counted"
        assert "collected" in e["error"]


class TestRoofline:
    def test_join_math_and_bound_verdict(self, fresh_census):
        # hand-checkable join: roof 1 TFLOP/s + 100 GB/s -> ridge 10
        # flops/byte. 5e8 flops / 1e9 bytes -> AI 0.5 -> HBM-bound;
        # measured 1 ms -> 5e11 flops/s -> MFU 0.5.
        xprof.set_roof(1e12, 1e11)
        xprof.note_subexec("pallas/update_bucket", flops=5e8,
                           bytes_accessed=1e9)
        xprof.note_measured("pallas/update_bucket", 1e-3)
        row = xprof.roofline()["pallas/update_bucket"]
        assert row["arithmetic_intensity"] == pytest.approx(0.5)
        assert row["bound"] == "hbm"
        assert row["mfu"] == pytest.approx(0.5)
        assert row["effective_flops_per_s"] == pytest.approx(5e11)
        # flip to compute-bound (last trace wins): AI 20 >= ridge 10
        xprof.note_subexec("pallas/update_bucket", flops=2e10,
                           bytes_accessed=1e9)
        row = xprof.roofline()["pallas/update_bucket"]
        assert row["arithmetic_intensity"] == pytest.approx(20.0)
        assert row["bound"] == "compute"

    def test_ledger_is_flat_and_on_the_profiler(self, fresh_census):
        xprof.set_roof(1e12, 1e11)
        xprof.note_subexec("pallas/update_bucket", flops=1e6,
                           bytes_accessed=1e7)
        led = OpProfiler.get().xla_stats()
        assert led["executables"] == 1
        assert led["pallas/update_bucket/flops"] == 1e6
        assert led["pallas/update_bucket/compute_bound"] == 0.0
        assert all(isinstance(v, (int, float)) for v in led.values())
        assert ("xla", "xla_stats") in OpProfiler.LEDGERS

    def test_measured_step_beats_dispatch_mean(self, fresh_census):
        f = xprof.register_jit("mln/infer", jax.jit(lambda x: x))
        f(jnp.ones((4,)))
        xprof.note_measured("mln/infer", 42.0)
        assert xprof.roofline()["mln/infer"]["step_s"] == 42.0


class TestTrainerFamilies:
    def test_mln_fit_and_infer_register(self, fresh_census):
        model = _mlp()
        x, y, it = _batches()
        model.fit(it, epochs=1)
        model.output(x[:8])
        census = xprof.census()
        assert census["mln/fit_step"]["calls"] >= 3
        assert census["mln/fit_step"]["generations"] >= 1
        assert census["mln/infer"]["calls"] == 1
        # fingerprint records the donation signature
        assert census["mln/fit_step"]["fingerprint"][
            "donate_argnums"] == (0, 1, 2)

    def test_mln_chunk_step_registers(self, fresh_census):
        model = _mlp()
        _, _, it = _batches(n=128)
        model.fit(it, epochs=1, steps_per_dispatch=2)
        assert xprof.census()["mln/fit_chunk"]["calls"] >= 1

    def test_fleet_step_registers(self, fresh_census):
        from deeplearning4j_tpu.parallel.fleet import FleetTrainer

        fleet = FleetTrainer(_mlp(n_in=8, hidden=8, n_out=2,
                                  updater=Adam(1e-3)), 3, seed=7)
        rng = np.random.RandomState(0)
        x = rng.randn(16, 8).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]
        fleet.step(x, y)
        assert xprof.census()["fleet/step"]["calls"] == 1

    def test_serving_bucket_aot_registers(self, fresh_census):
        from deeplearning4j_tpu.parallel import ServingEngine

        model = _mlp(n_in=12, hidden=8, n_out=3, updater=Adam(1e-3))
        eng = (ServingEngine.Builder(model)
               .buckets([1, 4]).input_shape((12,))
               .workers(1).max_wait_ms(1.0).build())
        try:
            e = xprof.census()["serving/bucket"]
            assert e["variants"] == 2
            assert e["cost_source"] == "xla"
            assert e["cost"]["flops"] > 0
            assert e["compile_s"] > 0
            # serving warmup took an HBM watermark sample
            assert xprof.watermarks()["serving_warmup"]["samples"] >= 1
        finally:
            eng.shutdown()

    def test_fused_pallas_counted_subexec(self, fresh_census):
        model = _mlp(updater=Adam(1e-3))
        model.conf.global_conf.fused_update = True
        _, _, it = _batches()
        model.fit(it, epochs=1)
        e = xprof.census()["pallas/update_bucket"]
        assert e["subexec"] is True and e["cost_source"] == "counted"
        n_params = model.num_params()
        # adam: 12 flops/elem analytic; one trace -> one bump
        assert e["cost"]["flops"] == pytest.approx(12 * n_params)
        assert e["cost"]["bytes_accessed"] > 0

    def test_exec_events_emitted(self, fresh_census):
        rec = flightrec.get()
        rec.configure(enabled=True)
        before = len(rec.events(prefix="xprof/exec"))
        model = _mlp()
        _, _, it = _batches()
        model.fit(it, epochs=1)
        evs = rec.events(prefix="xprof/exec")[before:]
        assert any(e["attrs"].get("executable") == "mln/fit_step"
                   for e in evs)


class TestWatermarks:
    def test_rise_and_fall_across_fit(self, fresh_census):
        model = _mlp()
        _, _, it = _batches()
        model.fit(it, epochs=3)
        wm = xprof.watermarks()["fit"]
        assert wm["samples"] == 3
        assert wm["peak_live_bytes"] >= wm["last_live_bytes"] > 0
        counters = OpProfiler.get().get_counters()
        assert counters.get("xprof/live_buffer_bytes", 0) > 0
        assert "xprof/peak_live_bytes/fit" in counters
        # a big allocation raises the peak; releasing it lowers LAST but
        # never the peak (rise-and-fall)
        ballast = jnp.ones((256, 1024), jnp.float32) + 0
        xprof.memory_watermark("fit")
        peak_with_ballast = xprof.watermarks()["fit"]["peak_live_bytes"]
        assert peak_with_ballast >= 2**20    # the 1 MiB ballast is live
        del ballast
        gc.collect()
        xprof.memory_watermark("fit")
        wm2 = xprof.watermarks()["fit"]
        assert wm2["peak_live_bytes"] == peak_with_ballast
        assert wm2["last_live_bytes"] < peak_with_ballast

    def test_watermark_shares_the_health_census(self, fresh_census):
        # one census function: the watermark returns exactly the
        # memory_summary() shape /api/health serves
        census = xprof.memory_watermark("global")
        assert "host" in census and "devices" in census
        assert "live_buffers" in census
        evs = flightrec.events(prefix="xprof/hbm")
        assert any(e["attrs"].get("phase") == "global" for e in evs)

    def test_dump_memory_census(self, fresh_census, tmp_path):
        xprof.memory_watermark("fit")
        path = str(tmp_path / "memcensus.json")
        assert xprof.dump_memory_census(path) == path
        blob = json.load(open(path))
        assert blob["watermarks"]["fit"]["samples"] == 1
        assert "census" in blob and "ledger" in blob

    def test_blackbox_dumps_memcensus_alongside(self, fresh_census,
                                                tmp_path):
        from deeplearning4j_tpu.parallel import TrainingSupervisor

        model = _mlp()
        sup = TrainingSupervisor(model, str(tmp_path))
        xprof.memory_watermark("fit")
        assert sup._dump_blackbox() is not None
        assert os.path.exists(sup.blackbox_path())
        assert os.path.exists(sup.memcensus_path())
        blob = json.load(open(sup.memcensus_path()))
        assert "watermarks" in blob and "census" in blob

    def test_health_and_metrics_carry_the_xla_ledger(self, fresh_census):
        from deeplearning4j_tpu.ui.server import UIServer, prometheus_text

        xprof.set_roof(1e12, 1e11)
        xprof.note_subexec("pallas/update_bucket", flops=1e6,
                          bytes_accessed=1e7)
        health = UIServer().health()
        assert health["xla"]["pallas/update_bucket/flops"] == 1e6
        text = prometheus_text()
        assert 'ledger="xla"' in text


class TestBenchtrack:
    def _round_file(self, tmp_path, n, records):
        tail = "\n".join(json.dumps(r) for r in records)
        path = tmp_path / f"BENCH_r{n:02d}.json"
        path.write_text(json.dumps(
            {"n": n, "cmd": "python bench.py", "rc": 0, "tail": tail,
             "parsed": records[-1]}))
        return str(path)

    def _rec(self, **over):
        rec = {"metric": "resnet50_imagenet_train", "value": 2500.0,
               "unit": "images/sec", "batch": 128, "platform": "tpu",
               "step_ms_median": 50.0, "step_ms_p10": 49.5,
               "mfu_vs_bf16_peak": 0.29,
               "traces": {"trace/graph_fit_step": 1},
               "updater_state_bytes": {"total": 1000}}
        rec.update(over)
        return rec

    def test_parse_driver_round_shape(self, tmp_path):
        from tools import benchtrack

        path = self._round_file(tmp_path, 6, [self._rec()])
        rnd = benchtrack.parse_round(path)
        assert rnd["round"] == 6 and rnd["rc"] == 0
        assert "resnet50_imagenet_train" in rnd["records"]

    def test_trajectory_and_markdown(self, tmp_path):
        from tools import benchtrack

        self._round_file(tmp_path, 1, [self._rec(value=2000.0)])
        self._round_file(tmp_path, 2, [self._rec(value=2500.0)])
        rounds = benchtrack.load_rounds(str(tmp_path))
        traj = benchtrack.trajectory(rounds)
        assert [n for n, _ in traj["resnet50_imagenet_train"]] == [1, 2]
        md = benchtrack.render_markdown(rounds)
        assert "resnet50_imagenet_train" in md and "| r01 |" in md

    def test_regressed_record_fails(self):
        from tools import benchtrack

        base = {"m": self._rec()}
        cur = {"m": self._rec(step_ms_median=60.0, step_ms_p10=59.5,
                              value=2083.0)}
        res = benchtrack.compare_records(base, cur)
        assert any("step time regressed" in v for v in res["violations"])
        assert any("throughput regressed" in v
                   for v in res["violations"])

    def test_noisy_but_flat_passes(self):
        from tools import benchtrack

        # median 8% up (host noise) but p10 at baseline: the min-over-
        # rounds bound says the hardware still hits the old time
        base = {"m": self._rec()}
        cur = {"m": self._rec(step_ms_median=54.0, step_ms_p10=49.8,
                              value=2320.0)}
        res = benchtrack.compare_records(base, cur)
        assert res["violations"] == []
        assert res["compared"] == ["m"]

    def test_platform_change_skips_never_fails(self):
        from tools import benchtrack

        base = {"m": self._rec()}
        cur = {"m": self._rec(platform="cpu", step_ms_median=5000.0,
                              step_ms_p10=4900.0, value=25.0)}
        res = benchtrack.compare_records(base, cur)
        assert res["violations"] == [] and res["compared"] == []
        assert any("platform changed" in s for s in res["skipped"])

    def test_compile_count_and_state_bytes_gates(self):
        from tools import benchtrack

        base = {"m": self._rec()}
        cur = {"m": self._rec(
            traces={"trace/graph_fit_step": 3},
            updater_state_bytes={"total": 2000})}
        res = benchtrack.compare_records(base, cur)
        assert any("compile count grew" in v for v in res["violations"])
        assert any("state bytes grew" in v for v in res["violations"])

    def test_mfu_gate(self):
        from tools import benchtrack

        base = {"m": self._rec()}
        res = benchtrack.compare_records(
            base, {"m": self._rec(mfu_vs_bf16_peak=0.20)})
        assert any("MFU regressed" in v for v in res["violations"])

    def test_missing_fields_skip_gates(self):
        from tools import benchtrack

        base = {"m": {"metric": "m", "value": 1.0, "unit": "x",
                      "platform": "cpu"}}
        cur = {"m": {"metric": "m", "value": 1.0, "unit": "x",
                     "platform": "cpu"}}
        assert benchtrack.compare_records(base, cur)["violations"] == []

    def test_empty_baseline_skips_with_message(self, tmp_path):
        """An empty baseline round (smoke config that emitted nothing,
        truncated file) gates nothing, says so, and exits 0 — never a
        crash, never a silent vacuous pass."""
        import json as _json

        from tools import benchtrack

        res = benchtrack.compare_records({}, {"m": self._rec()})
        assert res["violations"] == [] and res["compared"] == []
        assert any("no records" in s for s in res["skipped"])
        # end-to-end through the CLI: exit 0 on the empty baseline
        empty = tmp_path / "BENCH_r00.json"
        empty.write_text(_json.dumps({"n": 0, "rc": 0, "tail": "",
                                      "parsed": []}))
        cur = tmp_path / "BENCH_r01.json"
        cur.write_text(_json.dumps(self._rec() | {"metric": "m"}))
        assert benchtrack.main(["--compare", str(empty), str(cur)]) == 0


class TestRegistryTable:
    """The 4-way agreement's test-corpus leg (mirrors the fault-site
    and event-name registries)."""

    def test_expected_sites_match_registry(self):
        assert EXPECTED_SITES == sorted(xprof.EXEC_SITES)

    def test_registry_covers_every_docstring_site(self):
        for site in xprof.EXEC_SITES:
            assert site in (xprof.__doc__ or ""), site

    def test_registry_entries_carry_desc_and_drill(self):
        assert len(xprof.EXEC_SITES) >= 30
        for site, meta in xprof.EXEC_SITES.items():
            assert meta["desc"], site
            assert meta["drill"], site

    def test_xprof_events_registered(self):
        assert "xprof/exec" in flightrec.EVENT_SITES
        assert "xprof/hbm" in flightrec.EVENT_SITES
