#!/usr/bin/env python
"""Benchmark entry point (driver contract): prints ONE JSON line whose first
keys are {"metric", "value", "unit", "vs_baseline"}; extra keys carry the
self-validation evidence.

Self-validating methodology (round-2 contract):
- every timed step is synced (``jax.block_until_ready``) so per-step times are
  real device times, reported as median/p10/p90 over >= 30 steps;
- FLOPs per step come from XLA's own cost analysis of the compiled train-step
  module (fallback: none, fields omitted);
- effective TFLOP/s and MFU vs the chip's published peak are printed, and the
  run HARD-FAILS if MFU > 100% (physically impossible => timing bug);
- batch/image size/steps/data provenance are pinned in the JSON line.

The throughput value is batch / median_step_time: robust to warmup bleed and
host-side hiccups, and reproducible run-to-run within a few percent.

Usage: python bench.py [--config lenet|resnet50] [--steps N] [--with-listener]
"""

import argparse
import json
import os
import statistics
import sys
import time

import numpy as np

# Baseline ledger (see BASELINE.md "Measured" table). The LeNet row is this
# same config measured with the jax CPU backend on the build machine.
BASELINES = {
    "lenet_mnist_train": {"value": 1470.0, "unit": "images/sec"},
    # North star: "match nd4j-cuda on V100"; the reference publishes no numbers
    # (SURVEY.md §6), so the planning anchor is V100 fp32 ResNet-50 ~390 img/s.
    "resnet50_imagenet_train": {"value": 390.0, "unit": "images/sec"},
    # Planning anchor (not reference-derived): V100 BERT-base fine-tune at
    # seq 128 ~ 100 samples/sec in contemporary frameworks.
    "bert_base_finetune": {"value": 100.0, "unit": "samples/sec"},
    # Planning anchor: the chaos soak heals its 8-fault catalog in under
    # ~4 min of wall clock (faults healed per soak minute; see
    # bench_soak_smoke gates — the value is throughput of PROVEN recovery,
    # every fault must close a complete-chain incident to count at all).
    "soak_smoke": {"value": 2.0, "unit": "faults/min"},
}

# Published bf16 peak per chip, TFLOP/s. v5e: 197 (v5p: 459; v4: 275). The
# axon platform reports "TPU v5 lite" = v5e. CPU runs skip the MFU check.
TPU_BF16_PEAK_TFLOPS = 197.0


# Steps per timed chunk. The relay's value-readback fence costs ~76 ms
# (measured: float() of a tiny op); it amortizes to fence/CHUNK per step, so
# 40 keeps the distortion under ~2 ms/step on all TPU configs while the chunk
# still finishes in a few seconds.
CHUNK = 40


def _timed_steps(run_step, fence_value, warmup: int, steps: int):
    """Chunked per-step wall times with a VALUE-readback fence per chunk.

    Why not ``jax.block_until_ready``: through the axon TPU relay it returns
    before device work completes (measured 3.4 ms/step for a ResNet-50 step
    whose true cost is ~32 ms — the source of round 1's physically impossible
    28,170/13,401 img/s readings). A fence that reads back the loss VALUE
    cannot be faked: train step n consumes step n-1's params, so the chunk's
    final loss existing implies every step in the chunk executed. Steps are
    timed in chunks of CHUNK so the fence round-trip amortizes and dispatch
    still pipelines inside a chunk (the steady-state regime); the per-step
    figure is chunk_time / CHUNK.
    """
    for _ in range(warmup):
        run_step()
    fence_value()
    times = []
    n_chunks = max(6, (steps + CHUNK - 1) // CHUNK)
    for _ in range(n_chunks):
        t0 = time.perf_counter()
        for _ in range(CHUNK):
            run_step()
        fence_value()
        times.append((time.perf_counter() - t0) / CHUNK)
    return times


def _flops_of(step_fn, args) -> float | None:
    """XLA's FLOP count for a jitted step. The lowered (pre-compile) module's
    cost analysis is tried first — it avoids paying a second AOT compile of a
    step the jit cache already holds; the optimized-executable count is the
    fallback. Call BEFORE the timed loop if the step donates its arguments."""
    try:
        lowered = step_fn.lower(*args)
    except Exception:
        return None
    for get in (lambda: lowered.cost_analysis(),
                lambda: lowered.compile().cost_analysis()):
        try:
            cost = get()
            if isinstance(cost, list):  # per-device list on some backends
                cost = cost[0]
            f = cost.get("flops") if cost else None
            if f and f > 0:
                return float(f)
        except Exception:
            continue
    return None


def _flops_per_step(model, args) -> float | None:
    return _flops_of(model._fit_step, args)


def _summarize(metric: str, times, batch: int, flops_per_step, platform: str,
               extra: dict) -> dict:
    med = statistics.median(times)
    p10 = np.percentile(times, 10)
    p90 = np.percentile(times, 90)
    result = {
        "metric": metric,
        "value": batch / med,
        "unit": "images/sec",
        "steps_timed": len(times) * CHUNK,
        "chunk": CHUNK,
        "batch": batch,
        "step_ms_median": round(med * 1e3, 3),
        "step_ms_p10": round(float(p10) * 1e3, 3),
        "step_ms_p90": round(float(p90) * 1e3, 3),
        "platform": platform,
        **extra,
    }
    if flops_per_step:
        eff_tflops = flops_per_step / med / 1e12
        result["flops_per_step"] = flops_per_step
        result["effective_tflops"] = round(eff_tflops, 2)
        if platform.startswith("tpu") or platform == "axon":
            mfu = eff_tflops / TPU_BF16_PEAK_TFLOPS
            result["mfu_vs_bf16_peak"] = round(mfu, 4)
            if mfu > 1.0:
                print(json.dumps({"error": "MFU > 100% of chip peak — timing "
                                  "or FLOP accounting is broken", **result}))
                sys.exit(1)
    return result


def _ab_rounds(timed_epoch, rounds: int = 6):
    """Interleaved A/B rounds with alternating order (time-correlated
    host-load drift hits both halves of each pair equally); returns
    per-config times and per-round on/off ratios."""
    times = {"off": [], "on": []}
    ratios = []
    for r in range(rounds):
        order = ("on", "off") if r % 2 == 0 else ("off", "on")
        round_t = {name: timed_epoch(name) for name in order}
        times["on"].append(round_t["on"])
        times["off"].append(round_t["off"])
        ratios.append(round_t["on"] / round_t["off"])
    return times, ratios


def _ab_overhead_gate(what: str, budget: float, run_rounds, fail):
    """De-noised A/B overhead gate, shared by every overhead smoke
    (telemetry/fault/supervisor/obs — ISSUE 11 satellite). The estimator
    is the MIN over per-round on/off ratios: host-load noise on this box
    can only INFLATE a ratio (the measured effects are small and
    additive), so the min is the tightest honest bound — the same
    estimator mfu-smoke already uses. A gate breach automatically
    re-runs the WHOLE A/B pair once before hard-failing, and both
    measurements are logged either way (in the emitted JSON on pass, in
    the failure payload on fail). ``run_rounds() -> (times, ratios)``;
    returns ``(overhead, times, runs)`` of the passing (or last) run."""
    runs = []
    for attempt in (1, 2):
        times, ratios = run_rounds()
        overhead = min(ratios) - 1.0
        runs.append({"attempt": attempt,
                     "overhead_frac": round(overhead, 4),
                     "ratios": [round(r, 4) for r in ratios],
                     "off_s": [round(t, 4) for t in times["off"]],
                     "on_s": [round(t, 4) for t in times["on"]]})
        if overhead <= budget:
            return overhead, times, runs
        if attempt == 1:
            print(json.dumps({"warning": f"{what} overhead "
                              f"{overhead:.1%} over the {budget:.0%} "
                              f"budget — re-running the A/B pair once "
                              f"before failing", "measurement": runs[-1]}),
                  file=sys.stderr, flush=True)
    fail(f"{what} overhead {overhead:.1%} exceeds the {budget:.0%} "
         f"budget in both A/B runs", measurements=runs)


def _resnet50_model(image_size: int = 224):
    """The flagship ResNet-50 exactly as benched (bf16 compute / fp32
    params) — shared by the throughput bench and the cold-start audit so
    the two can never drift apart silently."""
    from deeplearning4j_tpu.models import ResNet50

    model = ResNet50(num_classes=1000, image_size=image_size).init()
    model.conf.global_conf.compute_dtype = "bfloat16"
    return model


def _bert_training(batch: int = 32, seq: int = 128):
    """BERT-base import + fine-tune training step setup (shared by
    bench_bert and the cold-start audit). Returns
    (step, params, upd, ph, n_params)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.autodiff.samediff import TrainingConfig
    from deeplearning4j_tpu.imports import import_frozen_tf
    from deeplearning4j_tpu.imports.tf_fixtures import (
        build_bert_frozen_graph, make_bert_batch)
    from deeplearning4j_tpu.learning import Adam

    hidden, vocab, n_classes = 768, 30522, 3
    gd, in_names, n_params = build_bert_frozen_graph(
        batch=batch, seq=seq, hidden=hidden, vocab=vocab)
    sd = import_frozen_tf(gd)
    sd.convert_to_variables()
    pooled = sd.get_variable(sd.tf_outputs[0])
    w = sd.var("cls_w", shape=(hidden, n_classes), init="xavier")
    b = sd.var("cls_b", shape=(n_classes,), init="zeros")
    pooled.mmul(w).add(b).rename("logits")
    sd.placeholder("labels", shape=(batch, n_classes))
    sd.ops.softmax_cross_entropy(sd.get_variable("logits"),
                                 sd.get_variable("labels"), name="loss")
    sd.set_loss_variables("loss")
    tc = TrainingConfig(updater=Adam(2e-5), loss_name="loss")
    sd.set_training_config(tc)
    ids, types, mask, y = make_bert_batch(batch, seq, vocab, n_classes)
    ph = {k: jnp.asarray(v) for k, v in
          {**dict(zip(in_names, (ids, types, mask))), "labels": y}.items()}
    params = sd._params()
    upd = tc.updater.init(params)
    step = sd._train_step_fn("loss", tuple(sd.placeholders()))
    return step, params, upd, ph, n_params


def _lenet_model():
    """The flagship LeNet config (shared bench / cold-audit)."""
    from deeplearning4j_tpu.learning import Nesterovs
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import layers as L

    conf = (NeuralNetConfiguration.builder()
            .seed(123)
            .updater(Nesterovs(learning_rate=0.01, momentum=0.9))
            .activation("relu")
            .weight_init("xavier")
            .list()
            .layer(L.ConvolutionLayer(n_out=20, kernel_size=(5, 5)))
            .layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(L.ConvolutionLayer(n_out=50, kernel_size=(5, 5)))
            .layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(L.DenseLayer(n_out=500))
            .layer(L.OutputLayer(n_out=10, loss="mcxent",
                                 activation="softmax"))
            .set_input_type(InputType.convolutional(28, 28, 1))
            .build())
    return MultiLayerNetwork(conf).init()


def _w2v_model():
    """The flagship Word2Vec hyperparameters (shared bench / cold-audit)."""
    from deeplearning4j_tpu.nlp import Word2Vec

    return Word2Vec(min_word_frequency=5, layer_size=100, window=5,
                    negative=5, sampling=1e-3, epochs=1, batch_size=8192,
                    seed=42)


def bench_resnet50(steps: int, batch: int = 64, image_size: int = 224,
                   with_listener: bool = False) -> dict:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.data import DataSet

    model = _resnet50_model(image_size)
    # the in-graph MFU tier (ISSUE 8): flat-bucket fused weight update +
    # bf16 updater state w/ stochastic rounding — the flagship trains
    # with the full hot-path stack on (mfu-smoke A/B-gates the tier;
    # here it reports the footprint win alongside throughput)
    model.conf.global_conf.fused_update = True
    model.conf.global_conf.updater.state_dtype = "bfloat16"
    if with_listener:
        from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener

        model.set_listeners(ScoreIterationListener(print_iterations=10))

    rng = np.random.RandomState(0)
    x = rng.randn(batch, 3, image_size, image_size).astype(np.float32)
    y = np.eye(1000, dtype=np.float32)[rng.randint(0, 1000, batch)]
    # DataSet/NDArray hold device arrays, so the synthetic batch uploads once
    # regardless; passing jnp arrays just skips the host-side staging copy.
    # (The disk-fed input pipeline is the resnet50-disk config.)
    ds = DataSet(jnp.asarray(x), jnp.asarray(y))

    times = _timed_steps(lambda: model.fit(ds, epochs=1),
                         lambda: float(model._score_dev),
                         warmup=3, steps=steps)
    assert np.isfinite(float(model._score_dev)), "non-finite training loss"

    inputs = {model.conf.network_inputs[0]: jnp.asarray(x)}
    labels = {model.conf.network_outputs[0]: jnp.asarray(y)}
    flops = _flops_per_step(
        model, (model._params, model._states, model._updater_state, inputs,
                labels, {}, jax.random.PRNGKey(0), jnp.asarray(0)))
    from deeplearning4j_tpu.common import xprof
    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.learning.precision import updater_state_bytes

    state_bytes = updater_state_bytes(jax.device_get(model._updater_state))
    pstats = OpProfiler.get().precision_stats()
    # the performance observatory (ISSUE 15): join the value-fenced step
    # median onto the census and attach the per-executable roofline —
    # the cost/MFU/bound fields the BENCH_r06+ trajectory carries.
    # analyze(compile=False): cost analysis from the lowering only — an
    # AOT re-compile here would double the bench's compile bill.
    xprof.note_measured("graph/fit_step", statistics.median(times))
    xprof.analyze(compile=False)
    # single-DataSet fits ride the serial path (no run_epochs epoch
    # boundary), so sample the steady-state HBM watermark explicitly —
    # one live-buffer census at the end of the timed loop
    xprof.memory_watermark("fit")
    roofline = {}
    for name, row in xprof.roofline().items():
        if not (row.get("calls") or row.get("generations")):
            continue
        out_row = {k: (round(v, 6) if isinstance(v, float) else v)
                   for k, v in row.items()
                   if k in ("calls", "generations", "step_s", "mfu",
                            "arithmetic_intensity", "bound",
                            "cost_source")}
        cost = row.get("cost", {})
        if cost:
            out_row["flops"] = cost.get("flops")
            out_row["bytes"] = cost.get("bytes_accessed")
        roofline[name] = out_row
    return _summarize(
        "resnet50_imagenet_train", times, batch, flops,
        jax.devices()[0].platform,
        {"image_size": image_size,
         "dtype": "bf16 compute / fp32 params / bf16 updater state "
                  "(fused flat-bucket update)",
         # the BENCH_r* trajectory captures the footprint win, not just
         # img/s: state bytes by dtype + the fused-kernel hit ledger
         "updater_state_bytes": state_bytes,
         "fused_kernel": {k: int(v) for k, v in pstats.items()
                          if k.startswith("fused_") or k == "sr_draws"},
         "xla_roofline": roofline,
         "hbm_watermarks": xprof.watermarks(),
         "data": "synthetic batch, device-resident (train-step config; the "
                 "disk-fed input pipeline is the resnet50-disk config)",
         "listener": with_listener})


def bench_bert(steps: int, batch: int = 32, seq: int = 128) -> dict:
    """North-star config 3: BERT-base imported from a frozen TF GraphDef,
    fine-tune step (forward+backward+Adam over all 110M params) timed."""
    import jax
    import jax.numpy as jnp

    step, params, upd, ph, n_params = _bert_training(batch, seq)
    state = {"params": params, "upd": upd, "loss": None}

    # FLOP count must be taken BEFORE the timed loop: the jitted step donates
    # its params/state, so lowering against them afterwards hits deleted arrays
    flops = _flops_of(step, (params, upd, ph, jax.random.PRNGKey(0),
                             jnp.asarray(0)))

    def run_step():
        state["params"], state["upd"], state["loss"] = step(
            state["params"], state["upd"], ph, jax.random.PRNGKey(0),
            jnp.asarray(0))

    times = _timed_steps(run_step, lambda: float(state["loss"]),
                         warmup=2, steps=steps)
    assert np.isfinite(float(state["loss"])), "non-finite BERT loss"
    res = _summarize("bert_base_finetune", times, batch, flops,
                     jax.devices()[0].platform,
                     {"seq_len": seq, "dtype": "fp32",
                      "model_params": n_params,
                      "data": "synthetic ids/mask (frozen graph built with "
                              "local TF at random init; no egress)"})
    res["unit"] = "samples/sec"
    return res


def bench_lenet(steps: int, with_listener: bool = False) -> dict:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.data import MnistDataSetIterator

    batch = 128
    model = _lenet_model()
    if with_listener:
        from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener

        model.set_listeners(ScoreIterationListener(print_iterations=10))

    it = MnistDataSetIterator(batch_size=batch, train=True,
                              num_examples=batch, flatten=False)
    ds = next(iter(it))
    mnist_real = not it.synthetic

    times = _timed_steps(lambda: model.fit(ds, epochs=1),
                         lambda: float(model._score_dev),
                         warmup=3, steps=steps)

    x = jnp.asarray(ds.features.value)
    y = jnp.asarray(ds.labels.value)
    flops = _flops_per_step(
        model, (model._params, model._states, model._updater_state, x, y,
                None, jax.random.PRNGKey(0), jnp.asarray(0)))
    return _summarize(
        "lenet_mnist_train", times, batch, flops, jax.devices()[0].platform,
        {"image_size": 28, "dtype": "fp32",
         "data": ("MNIST IDX files" if mnist_real
                  else "deterministic synthetic MNIST fallback (no IDX files "
                       "on disk)"),
         "listener": with_listener})


def bench_resnet50_disk(steps: int, batch: int = 64,
                        image_size: int = 224) -> dict:
    """ResNet-50 training fed from JPEG FILES ON DISK through the full ETL
    path — ImageRecordReader (parallel decode) → RecordReaderDataSetIterator
    → AsyncDataSetIterator (device prefetch) → fit. The number the VERDICT
    asked for: sustained throughput facing a real input pipeline, not
    device-resident arrays. Dataset: synthetic JPEGs generated once into a
    cache dir (no egress; decode cost is what matters, not content)."""
    import tempfile
    from pathlib import Path

    import jax

    from deeplearning4j_tpu.data import (AsyncDataSetIterator, FileSplit,
                                         ImageRecordReader,
                                         RecordReaderDataSetIterator)
    from deeplearning4j_tpu.models import ResNet50

    n_images = (max(steps, 10) + 2) * batch   # +warmup batch headroom
    cache = Path(tempfile.gettempdir()) / \
        f"d4t_bench_jpegs_{image_size}_{n_images}"
    if not cache.exists() or len(list(cache.rglob("*.jpg"))) < n_images:
        from PIL import Image

        rng = np.random.default_rng(0)
        for cls in range(10):
            (cache / f"class_{cls:02d}").mkdir(parents=True, exist_ok=True)
        for i in range(n_images):
            d = cache / f"class_{i % 10:02d}"
            p = d / f"{i:06d}.jpg"
            if not p.exists():
                arr = rng.integers(0, 255, (image_size, image_size, 3),
                                   dtype=np.uint8)
                Image.fromarray(arr).save(p, quality=85)

    model = ResNet50(num_classes=1000, image_size=image_size).init()
    model.conf.global_conf.compute_dtype = "bfloat16"

    rr = ImageRecordReader(height=image_size, width=image_size, channels=3,
                           workers=os.cpu_count() or 8)
    rr.initialize(FileSplit(cache, allowed_extensions=[".jpg"]))
    base = RecordReaderDataSetIterator(rr, batch_size=batch, label_index=1,
                                       num_classes=1000)
    it = AsyncDataSetIterator(base, queue_size=8, device_prefetch=True)

    # ONE generator for warmup + timing: a second iter(it) would spawn a
    # second worker thread racing the first over the shared reader state
    gen = iter(it)
    first = next(gen)
    model.fit(first, epochs=1)     # warmup: compile the step
    float(model._score_dev)

    t0 = time.perf_counter()
    n = 0
    for ds in gen:
        if n >= steps:
            break
        model.fit(ds, epochs=1)
        n += 1
    float(model._score_dev)        # value fence: consume the chained loss
    dt = time.perf_counter() - t0
    gen.close()                    # shut the prefetch worker down
    return {
        "metric": "resnet50_imagenet_train_diskpipe",
        "value": n * batch / dt,
        "unit": "images/sec",
        "steps_timed": n, "batch": batch,
        "platform": jax.devices()[0].platform,
        "image_size": image_size,
        "dtype": "bf16 compute / fp32 params",
        "decode_workers": rr.workers,
        "data": f"{n_images} synthetic JPEGs on disk -> ImageRecordReader -> "
                "async device prefetch",
    }


def bench_resnet50_predecoded(steps: int, batch: int = 64,
                              image_size: int = 224) -> dict:
    """ResNet-50 fed from the PRE-DECODED binary record container
    (data/binary_records.py; VERDICT r3 item 4) — the same disk pipeline
    as resnet50-disk but with JPEG decode paid ONCE at conversion: training
    reads are memmap slices at page-cache speed. On this 1-core host the
    decode-bound path does ~34 img/s; this shows what the container buys."""
    import tempfile
    from pathlib import Path

    import jax

    from deeplearning4j_tpu.data import (AsyncDataSetIterator,
                                         BinaryRecordDataSetIterator)
    from deeplearning4j_tpu.models import ResNet50

    n_images = (max(steps, 10) + 2) * batch
    container = Path(tempfile.gettempdir()) / \
        f"d4t_bench_predec_{image_size}_{n_images}.d4tbin"
    if not container.exists():
        # decode-once conversion: synthesize pixels straight into the
        # container (decoding n JPEGs first would take n/34 s on this
        # 1-core host and measure nothing new — the round-trip fidelity of
        # ImageRecordReader→write_records is covered in tests). Write to a
        # temp name + rename so an interrupted conversion never leaves a
        # truncated container that later runs would trust.
        from deeplearning4j_tpu.data.binary_records import BinaryRecordWriter

        rng = np.random.default_rng(0)
        tmp = container.with_suffix(".tmp")
        with BinaryRecordWriter(
                str(tmp),
                [("features", (3, image_size, image_size), "uint8"),
                 ("label", (), "int32")], chunk_records=batch) as w:
            for i in range(n_images):
                w.append(rng.integers(0, 255,
                                      (3, image_size, image_size),
                                      dtype=np.uint8), i % 10)
        os.replace(tmp, container)

    model = ResNet50(num_classes=1000, image_size=image_size).init()
    model.conf.global_conf.compute_dtype = "bfloat16"

    import jax.numpy as jnp

    # ship raw uint8 (4× less H2D traffic than f32), scale ON DEVICE, and
    # keep the worker thread jax-free (raw_numpy): both the host f32 cast
    # (~830 img/s on this 1-core host) and worker-thread device_put
    # (catastrophic through the axon relay) are measured cliffs —
    # BASELINE.md round-4 input-pipeline audit
    base = BinaryRecordDataSetIterator(str(container), batch_size=batch,
                                       num_classes=1000, raw_numpy=True)
    it = AsyncDataSetIterator(
        base, queue_size=8, device_prefetch=True,
        feature_transform=lambda x: x.astype(jnp.float32) / 255.0)
    gen = iter(it)
    first = next(gen)
    model.fit(first, epochs=1)     # warmup: compile the step
    float(model._score_dev)

    t0 = time.perf_counter()
    n = 0
    for ds in gen:
        if n >= steps:
            break
        model.fit(ds, epochs=1)
        n += 1
    float(model._score_dev)
    dt = time.perf_counter() - t0
    gen.close()
    return {
        "metric": "resnet50_imagenet_train_predecoded",
        "value": n * batch / dt,
        "unit": "images/sec",
        "steps_timed": n, "batch": batch,
        "platform": jax.devices()[0].platform,
        "image_size": image_size,
        "dtype": "bf16 compute / fp32 params",
        "container_bytes": container.stat().st_size,
        "data": f"{n_images} pre-decoded uint8 records in a .d4tbin "
                "container on disk -> memmap chunk reads -> async device "
                "prefetch",
    }


def bench_pipeline_smoke(steps: int, batch: int = 64,
                         steps_per_dispatch: int = 4) -> dict:
    """Fast CPU-friendly smoke of the shared input/dispatch pipeline
    (data/pipeline.py): a small MLP trained from an iterator whose final
    batch is PARTIAL, with padding + async device feed + multi-step
    dispatch all on. Self-validating: hard-fails unless the retrace
    counters prove the per-step jit traced at most once and the scan chunk
    exactly once. The emitted metrics (padded batches, host-wait vs
    dispatch overlap) are the input-pipeline ledger for BENCH_*.json
    rounds."""
    import jax

    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.data import NDArrayDataSetIterator
    from deeplearning4j_tpu.learning import Nesterovs
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import layers as L
    from deeplearning4j_tpu.optimize.listeners import PipelineMetricsListener

    conf = (NeuralNetConfiguration.builder().seed(123)
            .updater(Nesterovs(learning_rate=0.01, momentum=0.9))
            .activation("relu").weight_init("xavier").list()
            .layer(L.DenseLayer(n_out=256))
            .layer(L.DenseLayer(n_out=128))
            .layer(L.OutputLayer(n_out=10, loss="mcxent",
                                 activation="softmax"))
            .set_input_type(InputType.feed_forward(784)).build())
    model = MultiLayerNetwork(conf).init()
    listener = PipelineMetricsListener()
    model.set_listeners(listener)

    rng = np.random.RandomState(0)
    n = steps * batch + batch // 2      # the half batch forces a partial tail
    x = rng.randn(n, 784).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]
    it = NDArrayDataSetIterator(x, y, batch_size=batch)

    from deeplearning4j_tpu.common import tracecheck

    prof = OpProfiler.get()
    prof.reset()
    model.fit(it, epochs=1, steps_per_dispatch=steps_per_dispatch)  # warmup
    float(model._score_dev)
    prof.reset()
    t0 = time.perf_counter()
    try:
        # the timed epoch is a DECLARED steady-state region: counters
        # were reset after the warmup fit, so any trace/compile/device_get
        # in here is a hot-loop regression and the sanitizer raises
        with tracecheck.steady_state("pipeline-smoke timed epoch"):
            model.fit(it, epochs=1, steps_per_dispatch=steps_per_dispatch)
            float(model._score_dev)     # value fence
    except tracecheck.SteadyStateViolation as e:
        print(json.dumps({"error": "input pipeline violated steady state "
                          "— shape-stable batching is broken",
                          "violation": str(e).splitlines()[0],
                          "report": {k: v for k, v in e.report.items()
                                     if k != "first_stack"}}))
        sys.exit(1)
    dt = time.perf_counter() - t0
    traces = prof.trace_counts()

    # the sanitizer itself must be ARMED, not just quiet: inject a real
    # retrace (a fit at a different batch size re-traces the step) inside
    # a declared region and require the hard failure
    xs = rng.randn(batch, 784).astype(np.float32)
    ys = np.eye(10, dtype=np.float32)[rng.randint(0, 10, batch)]
    try:
        with tracecheck.steady_state("injected-retrace drill",
                                     max_host_syncs=None):
            model.fit(NDArrayDataSetIterator(xs, ys,
                                             batch_size=batch // 2),
                      epochs=1)
        print(json.dumps({"error": "trace sanitizer FAILED to detect an "
                          "injected steady-state retrace"}))
        sys.exit(1)
    except tracecheck.SteadyStateViolation:
        pass                            # armed and firing
    images = n + (batch - n % batch) % batch    # padded count actually run
    return {
        "metric": "input_pipeline_smoke",
        "value": images / dt,
        "unit": "images/sec",
        "steps_timed": -(-images // batch),
        "batch": batch,
        "steps_per_dispatch": steps_per_dispatch,
        "platform": jax.devices()[0].platform,
        "traces": traces,
        "tracecheck": prof.tracecheck_stats(),   # 2 regions, 1 violation
        "padded_batches": prof.counter_value("pipeline/padded_batches"),
        "overlap": {k: (round(v, 4) if isinstance(v, float) else v)
                    for k, v in prof.overlap_stats().items()},
        "data": "synthetic MLP batches with a partial final batch "
                "(pipeline padding + async feed + multi-step dispatch)",
    }


def bench_telemetry_smoke(steps: int, batch: int = 64,
                          steps_per_dispatch: int = 4) -> dict:
    """CPU-friendly smoke of the in-graph telemetry layer: a LeNet-class
    conv model (realistic FLOP:param ratio — telemetry cost is O(params)
    while the step is O(params x batch)) trained from an iterator with a
    partial final batch, once with telemetry off and once with a
    TelemetrySink + NanSentinelListener attached. Self-validating
    hard-fails:

    - any retrace in either timed window (telemetry must not destabilize
      shapes), checked on BOTH the per-step jit and the
      ``steps_per_dispatch`` scan chunk;
    - any delta between the two configs' compile footprints (each must
      trace each step kind exactly once);
    - telemetry step-time overhead > 10%.

    Timing methodology (shared by every overhead smoke via
    ``_ab_overhead_gate``): the off/on epochs are INTERLEAVED with
    alternating order and the overhead estimator is the MIN over
    per-round ratios, so host-load drift (this box swings >20%
    run-to-run, and noise can only inflate a ratio) hits both configs
    equally instead of masquerading as telemetry overhead; a gate breach
    re-runs the whole A/B pair once, logging both measurements. The
    emitted JSON carries the overlap ledger and the telemetry drain
    ledger (batched-readback time — the only host sync telemetry
    pays)."""
    import statistics as _stats

    import jax

    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.data import NDArrayDataSetIterator
    from deeplearning4j_tpu.optimize import (NanSentinelListener,
                                             TelemetrySink)
    from deeplearning4j_tpu.ui import InMemoryStatsStorage

    rng = np.random.RandomState(0)
    n = steps * batch + batch // 2      # the half batch forces a partial tail
    x = rng.randn(n, 1, 28, 28).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]
    it = NDArrayDataSetIterator(x, y, batch_size=batch)
    prof = OpProfiler.get()

    storage = InMemoryStatsStorage()
    models = {"off": _lenet_model(), "on": _lenet_model()}
    models["on"].set_listeners(TelemetrySink(storage, drain_every_n=25),
                               NanSentinelListener("warn", check_every_n=25))

    def fail(msg, **extra):
        print(json.dumps({"error": msg, **extra}))
        sys.exit(1)

    # compile footprint: one warmup fit per config on the CHUNKED path
    # (traces both the per-step jit and the scan chunk); the footprints
    # must be identical — telemetry rides the same single trace per kind
    warm = {}
    for name, model in models.items():
        prof.reset()
        model.fit(it, epochs=1, steps_per_dispatch=steps_per_dispatch)
        float(model._score_dev)
        warm[name] = prof.trace_counts()
    if warm["on"] != warm["off"]:
        fail("telemetry changed the compile footprint (retrace delta)",
             off_traces=warm["off"], on_traces=warm["on"])

    from deeplearning4j_tpu.common import tracecheck

    prof.reset()

    def timed_epoch(name):
        model = models[name]
        t0 = time.perf_counter()
        model.fit(it, epochs=1, steps_per_dispatch=steps_per_dispatch)
        float(model._score_dev)         # value fence
        return time.perf_counter() - t0

    try:
        # the interleaved timed rounds are one steady-state region; the
        # telemetry drain's batched device_get cadence is data-dependent
        # by design, so host syncs are counted but not policed here
        with tracecheck.steady_state("telemetry-smoke timed rounds",
                                     max_host_syncs=None):
            overhead, times, overhead_runs = _ab_overhead_gate(
                "telemetry step-time", 0.10,
                lambda: _ab_rounds(timed_epoch, rounds=5), fail)
    except tracecheck.SteadyStateViolation as e:
        fail("train step retraced inside a timed window — telemetry or "
             "pipeline shape stability is broken",
             violation=str(e).splitlines()[0])
    t_off = _stats.median(times["off"])
    t_on = _stats.median(times["on"])
    if not storage.series("loss") \
            or not any(t.startswith("grad_norm/") for t in storage.tags()):
        fail("telemetry enabled but no grad-norm series reached the "
             "storage", tags=storage.tags())

    images = n + (batch - n % batch) % batch    # padded count actually run
    return {
        "metric": "telemetry_smoke",
        "value": images / t_on,
        "unit": "images/sec",
        "batch": batch,
        "steps_per_dispatch": steps_per_dispatch,
        "platform": jax.devices()[0].platform,
        "traces": warm["on"],
        "telemetry_overhead_frac": round(overhead, 4),
        "overhead_runs": overhead_runs,
        "epoch_s_off_median": round(t_off, 4),
        "epoch_s_on_median": round(t_on, 4),
        "overlap": {k: (round(v, 4) if isinstance(v, float) else v)
                    for k, v in prof.overlap_stats().items()},
        "telemetry_drain": {k: (round(v, 5) if isinstance(v, float) else v)
                            for k, v in prof.telemetry_stats().items()},
        "series_collected": len(storage.tags()),
        "data": "synthetic LeNet batches with a partial final batch; "
                "telemetry on vs off interleaved, identical pipeline knobs",
    }


def bench_fault_smoke(steps: int, batch: int = 64,
                      checkpoint_every: int | None = None) -> dict:
    """CPU-friendly smoke of the fault-tolerance layer: a LeNet-class
    conv model (realistic step-compute : checkpoint-bytes ratio — the
    checkpoint payload is O(params) while the step is O(params x batch))
    trained from an iterator with a partial final batch, once with
    checkpointing off and once with an async-atomic CheckpointListener
    attached, then one injected transient input fault, then a simulated
    kill + exact resume. ``checkpoint_every`` defaults to 2 checkpoints
    per epoch — a cadence the background writer sustains without
    backpressure (submissions spaced further apart than one
    serialize+commit), which is the regime async checkpointing is
    designed for. Self-validating hard-fails:

    - resume-parity mismatch: a run crashed mid-fit (injected
      ``SimulatedCrash``) and resumed from its last intact checkpoint
      must reproduce the uninterrupted run's loss sequence EXACTLY
      (bit-identical float equality, CPU);
    - any retrace in a timed window, or any compile-footprint delta
      between the checkpoint-on and checkpoint-off configs;
    - injected transient fault not retried/recovered (retry counter must
      read exactly the injected count and training must complete);
    - async checkpointing step-time overhead > 10% vs checkpoint-off
      (interleaved A/B min-over-ratios with one automatic re-run, the
      shared ``_ab_overhead_gate`` methodology).

    Emits the checkpoint ledger (snapshot readback time — the only
    hot-loop cost — plus background write time and bytes) and the fault
    ledger."""
    import shutil
    import statistics as _stats
    import tempfile

    import jax

    from deeplearning4j_tpu.common import faultinject
    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.data import NDArrayDataSetIterator
    from deeplearning4j_tpu.ndarray.rng import set_default_seed
    from deeplearning4j_tpu.optimize.listeners import (
        CheckpointListener, CollectScoresIterationListener)

    if checkpoint_every is None:
        checkpoint_every = max(5, (steps + 1) // 2)
    rng = np.random.RandomState(0)
    n = steps * batch + batch // 2      # the half batch forces a partial tail
    x = rng.randn(n, 1, 28, 28).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]

    def make_it():
        return NDArrayDataSetIterator(x, y, batch_size=batch)

    def fail(msg, **extra):
        print(json.dumps({"error": msg, **extra}))
        sys.exit(1)

    prof = OpProfiler.get()
    faultinject.clear_plan()
    ckdir = tempfile.mkdtemp(prefix="dl4j_fault_smoke_")
    try:
        listeners = {}
        models = {"off": _lenet_model(), "on": _lenet_model()}
        listeners["on"] = CheckpointListener(
            ckdir, save_every_n_iterations=checkpoint_every, keep_last=2)
        models["on"].set_listeners(listeners["on"])

        # compile footprint: checkpointing must not change it
        warm = {}
        for name, model in models.items():
            prof.reset()
            model.fit(make_it(), epochs=1, batch_size=batch)
            float(model._score_dev)
            warm[name] = prof.trace_counts()
        if warm["on"] != warm["off"]:
            fail("checkpointing changed the compile footprint "
                 "(retrace delta)", off_traces=warm["off"],
                 on_traces=warm["on"])

        # paired A/B timing: async checkpoint overhead vs off. Each
        # "on" window carries its own snapshots + the writer thread's
        # concurrent serialize/commit contention; the residual in-flight
        # tail is drained BETWEEN windows (untimed) so the "off" windows
        # stay clean. Host-load drift on this box is time-correlated and
        # larger than the effect measured, so the shared
        # _ab_overhead_gate estimator applies: interleaved rounds,
        # min-over-ratios, one automatic A/B re-run before failing.
        def timed_epoch(name):
            t0 = time.perf_counter()
            models[name].fit(make_it(), epochs=1, batch_size=batch)
            float(models[name]._score_dev)      # value fence
            dt = time.perf_counter() - t0
            if name == "on":
                listeners["on"].flush()         # drain tail, untimed
            return dt

        timed_epoch("on")                       # untimed settle-in round
        timed_epoch("off")
        prof.reset()
        overhead, times, overhead_runs = _ab_overhead_gate(
            "async checkpoint", 0.10,
            lambda: _ab_rounds(timed_epoch, rounds=6), fail)
        hot = prof.trace_counts()
        if any(hot.values()):
            fail("train step retraced inside a timed window", traces=hot)
        ckpt_ledger = prof.checkpoint_stats()
        t_off = _stats.median(times["off"])
        t_on = _stats.median(times["on"])

        # one injected transient input fault: retried, recovered, counted
        prof.reset()
        faultinject.set_plan(faultinject.FaultPlan(
            [{"site": "pipeline/bind", "index": 1, "kind": "transient"}]))
        models["on"].fit(make_it(), epochs=1, batch_size=batch)
        faultinject.clear_plan()
        if prof.counter_value("pipeline/retries") != 1:
            fail("injected transient fault was not retried exactly once",
                 retries=prof.counter_value("pipeline/retries"))
        if prof.trace_counts():
            fail("fault retry retraced the train step",
                 traces=prof.trace_counts())
        fault_ledger = prof.fault_stats()

        # kill-resume parity: uninterrupted baseline vs crash+resume.
        # Retire the timing listener's writer BEFORE clearing its
        # directory out from under it.
        listeners["on"].close()
        shutil.rmtree(ckdir)
        os.makedirs(ckdir)
        par_epochs = 2
        par_steps = min(steps, 8)
        xs, ys = x[:par_steps * batch], y[:par_steps * batch]

        def par_it():
            return NDArrayDataSetIterator(xs, ys, batch_size=batch,
                                          shuffle=True, seed=3)

        set_default_seed(99)
        base_model = _lenet_model()
        base_scores = CollectScoresIterationListener()
        base_model.set_listeners(base_scores)
        base_model.fit(par_it(), epochs=par_epochs, batch_size=batch)
        baseline = [s for _, s in base_scores.scores]

        set_default_seed(99)
        victim = _lenet_model()
        vs = CollectScoresIterationListener()
        cl = CheckpointListener(ckdir, save_every_n_iterations=3,
                                keep_last=2)
        victim.set_listeners(vs, cl)
        crash_at = par_steps + 1       # mid-epoch-2
        faultinject.set_plan(faultinject.FaultPlan(
            [{"site": "train/step", "index": crash_at, "kind": "crash"}]))
        crashed = False
        try:
            victim.fit(par_it(), epochs=par_epochs, batch_size=batch)
        except faultinject.SimulatedCrash:
            crashed = True
        faultinject.clear_plan()
        cl.close()
        if not crashed:
            fail("injected crash did not fire", crash_at=crash_at)
        last = CheckpointListener.last_checkpoint(ckdir)
        if last is None:
            fail("no intact checkpoint after simulated kill")
        resumed_model = _lenet_model()
        rs = CollectScoresIterationListener()
        resumed_model.set_listeners(rs)
        resumed_model.fit(par_it(), epochs=par_epochs, batch_size=batch,
                          resume_from=last)
        resumed = [s for _, s in rs.scores]
        if resumed != baseline:
            diff = next((i for i, (a, b) in enumerate(zip(baseline, resumed))
                         if a != b), min(len(baseline), len(resumed)))
            fail("resume-parity mismatch: killed+resumed loss sequence "
                 "differs from the uninterrupted run",
                 first_diff_step=diff, baseline_len=len(baseline),
                 resumed_len=len(resumed),
                 resumed_from=os.path.basename(last))

        images = (n + (batch - n % batch) % batch)
        return {
            "metric": "fault_smoke",
            "value": images / t_on,
            "unit": "images/sec",
            "batch": batch,
            "platform": jax.devices()[0].platform,
            "traces": warm["on"],
            "checkpoint_overhead_frac": round(overhead, 4),
            "overhead_runs": overhead_runs,
            "epoch_s_off_median": round(t_off, 4),
            "epoch_s_on_median": round(t_on, 4),
            "checkpoint_ledger": {k: (round(v, 5) if isinstance(v, float)
                                      else v)
                                  for k, v in ckpt_ledger.items()},
            "fault_ledger": fault_ledger,
            "resume_parity": "exact",
            "resume_steps_compared": len(baseline),
            "data": "synthetic LeNet batches with a partial final batch; "
                    "async checkpointing on vs off interleaved, one "
                    "injected transient fault, one simulated kill+resume",
        }
    finally:
        faultinject.clear_plan()
        shutil.rmtree(ckdir, ignore_errors=True)


def bench_supervisor_smoke(steps: int, batch: int = 64,
                           checkpoint_every: int | None = None) -> dict:
    """CPU-friendly smoke of the self-healing layer (ISSUE 4): the same
    LeNet-class config as fault-smoke, trained once per round under a
    plain CheckpointListener ("off") and once under a TrainingSupervisor
    ("on" — incarnation claim, anchor checkpoint, heartbeat listener,
    monitor thread, same checkpoint cadence), interleaved A/B; then one
    injected mid-epoch crash that the supervisor must heal WITHOUT human
    intervention. Self-validating hard-fails:

    - resume-parity mismatch: the supervised run with an injected restart
      must reproduce the uninterrupted run's loss sequence EXACTLY
      (bit-identical float equality, CPU);
    - any retrace inside a timed no-fault window (supervision must not
      perturb the compile story);
    - supervision overhead > 10% in the no-fault case (min over
      per-round on/off ratios with one automatic A/B re-run, the shared
      ``_ab_overhead_gate`` estimator; the "on"
      window deliberately pays the supervisor's FULL per-fit cost —
      incarnation claim, anchor save_now, writer drain on close — and
      each timed window spans several epochs so that fixed per-fit cost
      amortizes the way any real run amortizes it);
    - supervisor counters not visible (restart/attempt ledger empty after
      the healed run).

    Emits the supervisor ledger alongside the checkpoint ledger."""
    import shutil
    import statistics as _stats
    import tempfile

    import jax

    from deeplearning4j_tpu.common import faultinject
    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.data import NDArrayDataSetIterator
    from deeplearning4j_tpu.ndarray.rng import set_default_seed
    from deeplearning4j_tpu.optimize.listeners import (
        CheckpointListener, CollectScoresIterationListener)
    from deeplearning4j_tpu.parallel import TrainingSupervisor

    if checkpoint_every is None:
        checkpoint_every = max(5, (steps + 1) // 2)
    rng = np.random.RandomState(0)
    n = steps * batch + batch // 2
    x = rng.randn(n, 1, 28, 28).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]

    def make_it():
        return NDArrayDataSetIterator(x, y, batch_size=batch)

    def fail(msg, **extra):
        print(json.dumps({"error": msg, **extra}))
        sys.exit(1)

    prof = OpProfiler.get()
    faultinject.clear_plan()
    dirs = {"off": tempfile.mkdtemp(prefix="dl4j_sup_smoke_off_"),
            "on": tempfile.mkdtemp(prefix="dl4j_sup_smoke_on_")}
    try:
        models = {"off": _lenet_model(), "on": _lenet_model()}
        off_ckpt = CheckpointListener(
            dirs["off"], save_every_n_iterations=checkpoint_every,
            keep_last=2)
        models["off"].set_listeners(off_ckpt)
        sup = TrainingSupervisor(models["on"], dirs["on"],
                                 save_every_n_iterations=checkpoint_every,
                                 keep_last=2, backoff_base_s=0.01)

        def run(name, epochs=1):
            if name == "off":
                models["off"].fit(make_it(), epochs=epochs,
                                  batch_size=batch)
            else:
                res = sup.fit(make_it, epochs=epochs, batch_size=batch,
                              resume="never")
                if res.status != "completed" or res.restarts:
                    fail("no-fault supervised epoch did not complete "
                         "cleanly", result=repr(res))
            float(models[name]._score_dev)      # value fence

        # compile footprint: supervision must not change it
        warm = {}
        for name in ("off", "on"):
            prof.reset()
            run(name)
            warm[name] = prof.trace_counts()
        if warm["on"] != warm["off"]:
            fail("supervision changed the compile footprint (retrace "
                 "delta)", off_traces=warm["off"], on_traces=warm["on"])

        # interleaved A/B timing (same estimator as fault-smoke: median
        # of per-round on/off ratios after one untimed settle round);
        # several epochs per window so the supervisor's fixed per-fit
        # cost (anchor checkpoint + close drain) amortizes realistically
        round_epochs = 4

        def timed_epoch(name):
            t0 = time.perf_counter()
            run(name, epochs=round_epochs)
            dt = time.perf_counter() - t0
            if name == "off":
                off_ckpt.flush()                # drain tail, untimed
            return dt

        timed_epoch("on")
        timed_epoch("off")
        prof.reset()
        overhead, times, overhead_runs = _ab_overhead_gate(
            "supervision", 0.10,
            lambda: _ab_rounds(timed_epoch, rounds=6), fail)
        hot = prof.trace_counts()
        if any(hot.values()):
            fail("train step retraced inside a timed window", traces=hot)
        ckpt_ledger = prof.checkpoint_stats()
        t_off = _stats.median(times["off"])
        t_on = _stats.median(times["on"])
        off_ckpt.close()

        # injected restart: crash mid-epoch-2, supervisor heals, loss
        # sequence bitwise-equal to the uninterrupted baseline
        prof.reset()
        par_epochs = 2
        par_steps = min(steps, 8)
        xs, ys = x[:par_steps * batch], y[:par_steps * batch]

        def par_it():
            return NDArrayDataSetIterator(xs, ys, batch_size=batch,
                                          shuffle=True, seed=3)

        set_default_seed(99)
        base_model = _lenet_model()
        base_scores = CollectScoresIterationListener()
        base_model.set_listeners(base_scores)
        base_model.fit(par_it(), epochs=par_epochs, batch_size=batch)
        baseline = [s for _, s in base_scores.scores]

        set_default_seed(99)
        victim = _lenet_model()
        vs = CollectScoresIterationListener()
        victim.set_listeners(vs)
        crash_at = par_steps + 1
        faultinject.set_plan(faultinject.FaultPlan(
            [{"site": "train/step", "index": crash_at, "kind": "crash"}]))
        heal_dir = tempfile.mkdtemp(prefix="dl4j_sup_smoke_heal_")
        try:
            sup2 = TrainingSupervisor(victim, heal_dir,
                                      save_every_n_iterations=3,
                                      keep_last=2, backoff_base_s=0.01)
            res = sup2.fit(par_it, epochs=par_epochs, batch_size=batch,
                           resume="never")
        finally:
            faultinject.clear_plan()
            shutil.rmtree(heal_dir, ignore_errors=True)
        if res.status != "completed" or res.restarts != 1:
            fail("supervisor did not heal the injected crash with exactly "
                 "one restart", result=repr(res),
                 history=res.history)
        resumed = [s for _, s in vs.scores]
        if resumed != baseline:
            diff = next((i for i, (a, b) in enumerate(zip(baseline, resumed))
                         if a != b), min(len(baseline), len(resumed)))
            fail("resume-parity mismatch: supervised+healed loss sequence "
                 "differs from the uninterrupted run",
                 first_diff_step=diff, baseline_len=len(baseline),
                 resumed_len=len(resumed))
        sup_ledger = prof.supervisor_stats()
        if sup_ledger.get("restarts") != 1 or \
                sup_ledger.get("attempts") != 2:
            fail("supervisor ledger does not show the healed restart",
                 ledger=sup_ledger)

        images = (n + (batch - n % batch) % batch) * round_epochs
        return {
            "metric": "supervisor_smoke",
            "value": images / t_on,
            "unit": "images/sec",
            "batch": batch,
            "platform": jax.devices()[0].platform,
            "traces": warm["on"],
            "supervision_overhead_frac": round(overhead, 4),
            "overhead_runs": overhead_runs,
            "epoch_s_off_median": round(t_off, 4),
            "epoch_s_on_median": round(t_on, 4),
            "supervisor_ledger": {k: (round(v, 5) if isinstance(v, float)
                                      else v)
                                  for k, v in sup_ledger.items()},
            "checkpoint_ledger": {k: (round(v, 5) if isinstance(v, float)
                                      else v)
                                  for k, v in ckpt_ledger.items()},
            "resume_parity": "exact",
            "resume_steps_compared": len(baseline),
            "data": "synthetic LeNet batches; supervised vs plain "
                    "checkpointed epochs interleaved, one injected "
                    "mid-epoch crash healed by restart",
        }
    finally:
        faultinject.clear_plan()
        for d in dirs.values():
            shutil.rmtree(d, ignore_errors=True)


def bench_zero1_smoke(steps: int, batch: int = 64, workers: int = 4) -> dict:
    """CPU-friendly smoke of ZeRO-1 cross-replica weight-update sharding
    (ISSUE 5; arXiv:2004.13336): the flagship LeNet config trained through
    ParallelWrapper once with the dense all-reduce accumulator and once
    with ReduceScatterAccumulator (reduce-scatter grads → sharded updater
    apply → all-gather params), paired interleaved A/B. Self-validating
    hard-fails:

    - parity break: the sharded-updater loss sequence (and final params)
      must be BITWISE-equal to the dense path's on CPU;
    - any retrace delta between the two paths, or any retrace inside a
      timed window (the sharded step must stay one-compile-per-config);
    - per-replica updater-state bytes not ≈ 1/workers of the dense
      footprint (asserted via the zero1/* memory ledger; the flat
      bucketing may pad by at most one shard per dtype bucket);
    - step-time regression > 5% vs dense (median of per-round ratios —
      the ZeRO-1 point on one host is the memory/redundancy win, it must
      not cost step time);
    - encoded-exchange density/bytes counters empty after a short
      EncodedGradientsAccumulator fit (the DCN-path ledger must populate).

    Emits the collective-bytes ledger alongside the timing."""
    import shutil  # noqa: F401  (parity with sibling smokes' imports)
    import statistics as _stats

    # a multi-replica mesh is the whole point: on single-device hosts
    # (CPU build machines) request virtual CPU devices BEFORE jax loads
    if "jax" not in sys.modules:
        _flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in _flags:
            os.environ["XLA_FLAGS"] = (
                _flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.data import NDArrayDataSetIterator
    from deeplearning4j_tpu.ndarray.rng import set_default_seed
    from deeplearning4j_tpu.optimize.listeners import (
        CollectScoresIterationListener)
    from deeplearning4j_tpu.parallel import (EncodedGradientsAccumulator,
                                             ParallelWrapper,
                                             ReduceScatterAccumulator)

    def fail(msg, **extra):
        print(json.dumps({"error": msg, **extra}))
        sys.exit(1)

    workers = min(workers, len(jax.devices()))
    if workers < 2:
        fail("zero1-smoke needs >= 2 devices (virtual CPU device request "
             "came too late — is jax initialized before bench dispatch?)",
             devices=len(jax.devices()))
    rng = np.random.RandomState(0)
    n = steps * batch
    x = rng.randn(n, 1, 28, 28).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]

    def make_it():
        return NDArrayDataSetIterator(x, y, batch_size=batch)

    def build(acc):
        set_default_seed(99)
        model = _lenet_model()
        b = ParallelWrapper.Builder(model).workers(workers)
        if acc is not None:
            b.gradients_accumulator(acc)
        return model, b.build()

    prof = OpProfiler.get()
    prof.reset()

    # --- bitwise parity + compile footprint (one warmup epoch each) ----
    seqs, models, wrappers, warm = {}, {}, {}, {}
    for name, acc in (("dense", None), ("zero1", ReduceScatterAccumulator())):
        model, pw = build(acc)
        scores = CollectScoresIterationListener()
        pw.set_listeners(scores)
        prof.reset()
        pw.fit(make_it(), epochs=1, batch_size=batch)
        float(model._score_dev)
        warm[name] = prof.trace_counts()
        seqs[name] = [s for _, s in scores.scores]
        models[name], wrappers[name] = model, pw
    if seqs["zero1"] != seqs["dense"]:
        diff = next((i for i, (a, b) in enumerate(
            zip(seqs["dense"], seqs["zero1"])) if a != b),
            min(len(seqs["dense"]), len(seqs["zero1"])))
        fail("ZeRO-1 parity break: sharded-updater loss sequence is not "
             "bitwise-identical to the dense path", first_diff_step=diff)
    pd = jax.device_get(models["dense"]._params)
    pz = jax.device_get(models["zero1"]._params)
    if not all(np.array_equal(a, b) for a, b in
               zip(jax.tree.leaves(pd), jax.tree.leaves(pz))):
        fail("ZeRO-1 parity break: final params differ from the dense "
             "path's")
    if warm["zero1"] != warm["dense"]:
        fail("retrace delta between dense and ZeRO-1 paths",
             dense_traces=warm["dense"], zero1_traces=warm["zero1"])

    # --- memory ledger: sharded updater state is ~1/workers of dense ---
    dense_upd_bytes = int(sum(
        l.size * l.dtype.itemsize
        for l in jax.tree.leaves(jax.device_get(
            models["dense"]._updater_state))))
    per_replica = OpProfiler.get().counter_value(
        "zero1/updater_state_bytes_per_replica")
    # flat bucketing pads each dtype bucket to a multiple of `workers`
    pad_slack = workers * 8 * 4
    if not (0 < per_replica <= dense_upd_bytes // workers + pad_slack):
        fail("sharded updater-state footprint is not ~1/workers of dense",
             dense_bytes=dense_upd_bytes, per_replica_bytes=per_replica,
             workers=workers)

    # --- interleaved A/B step time (median of per-round ratios) --------
    def timed_epoch(name):
        t0 = time.perf_counter()
        wrappers[name].fit(make_it(), epochs=1, batch_size=batch)
        float(models[name]._score_dev)
        return time.perf_counter() - t0

    timed_epoch("zero1")
    timed_epoch("dense")                 # settle round, untimed
    prof.reset()
    times = {"dense": [], "zero1": []}
    ratios = []
    for r in range(6):
        order = ("zero1", "dense") if r % 2 == 0 else ("dense", "zero1")
        round_t = {name: timed_epoch(name) for name in order}
        times["dense"].append(round_t["dense"])
        times["zero1"].append(round_t["zero1"])
        ratios.append(round_t["zero1"] / round_t["dense"])
    hot = prof.trace_counts()
    if any(hot.values()):
        fail("train step retraced inside a timed window", traces=hot)
    coll_ledger = prof.collective_stats()
    t_dense = _stats.median(times["dense"])
    t_zero1 = _stats.median(times["zero1"])
    regression = _stats.median(ratios) - 1.0
    if regression > 0.05:
        fail(f"ZeRO-1 step-time regression {regression:.1%} exceeds the "
             "5% budget",
             dense_s=round(t_dense, 4), zero1_s=round(t_zero1, 4),
             zero1_times=[round(t, 4) for t in times["zero1"]],
             dense_times=[round(t, 4) for t in times["dense"]])

    # --- encoded-exchange ledger populates (short DCN-path fit) --------
    prof.reset()
    model_e, pw_e = build(EncodedGradientsAccumulator())
    pw_e.fit(NDArrayDataSetIterator(x[:4 * batch], y[:4 * batch],
                                    batch_size=batch), epochs=1,
             batch_size=batch)
    float(model_e._score_dev)
    enc = prof.collective_stats()
    if not (enc.get("encoded_steps") and enc.get("encoded_elems_total")
            and "encoded_density" in enc and enc.get("encoded_bytes_est")):
        fail("encoded-exchange ledger did not populate", ledger=enc)

    return {
        "metric": "zero1_smoke",
        "value": n / t_zero1,
        "unit": "images/sec",
        "batch": batch,
        "workers": workers,
        "platform": jax.devices()[0].platform,
        "traces": warm["zero1"],
        "parity": "exact",
        "parity_steps_compared": len(seqs["dense"]),
        "step_time_ratio_zero1_vs_dense": round(1.0 + regression, 4),
        "epoch_s_dense_median": round(t_dense, 4),
        "epoch_s_zero1_median": round(t_zero1, 4),
        "updater_state_bytes_dense": dense_upd_bytes,
        "updater_state_bytes_per_replica": per_replica,
        "collective_ledger": {k: (round(v, 5) if isinstance(v, float)
                                  else v)
                              for k, v in coll_ledger.items()},
        "encoded_ledger": {k: (round(v, 5) if isinstance(v, float) else v)
                           for k, v in enc.items()},
        "data": "synthetic LeNet batches; dense vs ZeRO-1 sharded-updater "
                "epochs interleaved, bitwise parity enforced",
    }


def bench_mfu_smoke(steps: int, batch: int = 64) -> dict:
    """CPU-friendly smoke of the in-graph MFU tier (ISSUE 8): the
    flagship LeNet config with an Adam updater trained three ways —
    per-leaf fp32 baseline (A), fused flat-bucket update (B), fused +
    bf16 updater state with stochastic rounding (C) — interleaved A/B
    timing, same estimator as zero1-smoke. Self-validating hard-fails:

    - fused fp32 kernel not BITWISE-identical to the per-leaf reference
      at the kernel level (fused_apply vs updater.apply on the warmed
      model's real param/grad trees, production mode);
    - fit-level fused fp32 params drifting past the documented ulp bound
      (4e-6 — XLA's fma contraction on the flat shape, nothing more;
      measured 0.6-2.0e-6 on CPU across step counts, and bitwise-stable
      against the flat-backward epilogue);
    - bf16-state parity outside the documented envelope
      (|Δ| <= 1e-3 + 0.05*|ref| per step loss and final params);
    - updater-state footprint above 0.55x fp32 (the halving is the
      point: moments are the whole Adam state);
    - a fused fit that compiled WITHOUT the flat-backward epilogue
      (precision/grads_flat_in_step gauge must read 1 — the grads are
      born in bucket layout and the updater folds into the same
      dispatch; remat-smoke A/Bs the knob itself);
    - any retrace delta between configs, or any retrace inside a timed
      window;
    - step-time regression (ratio of min-over-interleaved-rounds — the
      additive-noise-robust estimator): fused fp32 > 12% over base on
      CPU (quiet-box truth is +1-3%; shared runners resolve no finer
      than ~±10%, and the budget still catches an accidental per-leaf
      fallback), fused+bf16 > 20% on CPU (adds the software-threefry SR
      draws); both 5% on TPU where timing is clean and the PRNG is
      hardware;
    - fused epilogue: inference parity break vs the dense ops on a
      residual BN block, or an empty precision ledger.

    Emits the precision ledger alongside the timing."""
    import statistics as _stats

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.data import NDArrayDataSetIterator
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.learning.precision import updater_state_bytes
    from deeplearning4j_tpu.ndarray.rng import set_default_seed
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import layers as L
    from deeplearning4j_tpu.ops import pallas_update
    from deeplearning4j_tpu.optimize.listeners import (
        CollectScoresIterationListener)
    from deeplearning4j_tpu.parallel import Zero1Plan

    def fail(msg, **extra):
        print(json.dumps({"error": msg, **extra}))
        sys.exit(1)

    rng = np.random.RandomState(0)
    n = steps * batch
    x = rng.randn(n, 1, 28, 28).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]

    def make_it():
        return NDArrayDataSetIterator(x, y, batch_size=batch)

    def build(fused: bool, state_dtype):
        set_default_seed(99)
        upd = Adam(learning_rate=1e-3)
        upd.state_dtype = state_dtype
        b = (NeuralNetConfiguration.builder().seed(123).updater(upd)
             .activation("relu").weight_init("xavier"))
        if fused:
            b = b.fused_update()
        conf = (b.list()
                .layer(L.ConvolutionLayer(n_out=20, kernel_size=(5, 5)))
                .layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(L.ConvolutionLayer(n_out=50, kernel_size=(5, 5)))
                .layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(L.DenseLayer(n_out=500))
                .layer(L.OutputLayer(n_out=10, loss="mcxent",
                                     activation="softmax"))
                .set_input_type(InputType.convolutional(28, 28, 1))
                .build())
        return MultiLayerNetwork(conf).init()

    prof = OpProfiler.get()
    configs = {"base": (False, None), "fused": (True, None),
               "fused16": (True, "bfloat16")}
    models, seqs, warm = {}, {}, {}
    for name, (fused, sd) in configs.items():
        m = build(fused, sd)
        scores = CollectScoresIterationListener()
        m.set_listeners(scores)
        prof.reset()
        m.fit(make_it(), epochs=1, batch_size=batch)
        float(m._score_dev)
        warm[name] = prof.trace_counts()
        seqs[name] = [s for _, s in scores.scores]
        models[name] = m

    # the warm fits' trace-time precision counters (reset below wipes
    # them before the timed windows)
    fit_ledger = prof.precision_stats()

    # --- gate 1: kernel-level bitwise (production mode, real trees) ----
    base = models["base"]
    params = jax.tree.map(jnp.asarray, jax.device_get(base._params))
    grads = jax.tree.map(
        lambda p: (jax.random.normal(jax.random.PRNGKey(7), p.shape)
                   * 0.01).astype(p.dtype), params)
    upd = Adam(learning_rate=1e-3)
    state = upd.init(params)
    ref_p, ref_s = upd.apply(grads, state, params, 5)
    plan = Zero1Plan(params, 1)
    # the bitwise invariant is mode-local to "xla" (pallas_update doc:
    # the kernel's own compile may fma-contract, ulp-bounded) — pin the
    # mode so the gate cannot flake on TPU where default is "pallas"
    nf, ns = pallas_update.fused_apply(
        upd, plan.flatten(params), plan.flatten(grads),
        plan.flatten_state(state, xp=jnp), 5, None, mode="xla")
    got_p = plan.unflatten(nf)
    got_s = {k: plan.unflatten(v, xp=jnp) for k, v in ns.items()}
    for a, b in zip(jax.tree.leaves(jax.device_get((ref_p, ref_s))),
                    jax.tree.leaves(jax.device_get((got_p, got_s)))):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            fail("fused fp32 kernel (mode=xla) is not bitwise-identical "
                 "to the per-leaf reference")

    # --- gate 2: fit-level parity envelopes ----------------------------
    for a, b in zip(jax.tree.leaves(jax.device_get(base._params)),
                    jax.tree.leaves(jax.device_get(
                        models["fused"]._params))):
        d = float(np.max(np.abs(a - b)))
        # measured envelope on this config: 0.6-2.0e-6 across step
        # counts (the drift is XLA fma-contracting Adam's flat-shape
        # update differently — it wanders, it does not compound; the
        # flat-backward epilogue is BITWISE vs the legacy fused step,
        # gated in remat-smoke). 4e-6 is 2x the measured worst case.
        if d > 4e-6:
            fail(f"fused fp32 fit-level param drift {d:.2e} exceeds the "
                 "documented 4e-6 ulp bound")
    for s_a, s_c in zip(seqs["base"], seqs["fused16"]):
        if abs(s_a - s_c) > 1e-3 + 0.05 * abs(s_a):
            fail("bf16-state loss parity outside the documented envelope",
                 base=s_a, fused16=s_c)
    for a, c in zip(jax.tree.leaves(jax.device_get(base._params)),
                    jax.tree.leaves(jax.device_get(
                        models["fused16"]._params))):
        d = float(np.max(np.abs(a - c)))
        # param trajectories accumulate zero-mean rounding noise and
        # wander apart chaotically — the per-step loss envelope above is
        # the numerics gate; this one only catches gross divergence
        if d > 0.01 + 0.1 * float(np.max(np.abs(a))):
            fail(f"bf16-state param divergence {d:.2e} is gross, not "
                 "rounding noise")

    # --- gate 3: compile footprint + state bytes -----------------------
    if not (warm["base"] == warm["fused"] == warm["fused16"]):
        fail("retrace delta between configs", traces=warm)
    bytes_a = updater_state_bytes(jax.device_get(base._updater_state))
    bytes_c = updater_state_bytes(
        jax.device_get(models["fused16"]._updater_state))
    if bytes_c["total"] > 0.55 * bytes_a["total"]:
        fail("bf16 updater-state footprint above 0.55x fp32",
             fp32_bytes=bytes_a["total"], bf16_bytes=bytes_c["total"])
    # the fused configs must have taken the flat-backward epilogue —
    # grads born in bucket layout, optimizer folded into the same
    # compiled dispatch, no dense grad tree materialized (the trace-time
    # gauge records which path the fused step compiled with; remat-smoke
    # A/Bs the knob itself)
    if fit_ledger.get("grads_flat_in_step") != 1:
        fail("fused fit did not compile the flat-backward epilogue "
             "(precision/grads_flat_in_step != 1)", ledger=fit_ledger)

    # --- gate 4: interleaved A/B step time -----------------------------
    # Two budgets: the FUSION must be free (fused fp32 vs base ≤5% —
    # measured ~+1% CPU), while the bf16-state config additionally pays
    # the stochastic-rounding draws (one threefry uint32 per state
    # element per step — ~10% on CPU where the PRNG is software; on TPU
    # the hardware PRNG makes it ~free) → ≤20% CPU budget, and its real
    # win (0.5x state bytes) is gated above.
    def timed_epoch(name):
        t0 = time.perf_counter()
        models[name].fit(make_it(), epochs=1, batch_size=batch)
        float(models[name]._score_dev)
        return time.perf_counter() - t0

    for name in ("fused16", "fused", "base"):     # settle round, untimed
        timed_epoch(name)
    prof.reset()
    times = {name: [] for name in configs}
    for r in range(10):
        order = (("fused16", "fused", "base") if r % 2 == 0
                 else ("base", "fused", "fused16"))
        for name in order:
            times[name].append(timed_epoch(name))
    hot = prof.trace_counts()
    if any(hot.values()):
        fail("train step retraced inside a timed window", traces=hot)
    t_base = _stats.median(times["base"])
    t_fused = _stats.median(times["fused16"])
    # build boxes carry bursty background load (2x per-epoch swings
    # observed); that noise is strictly ADDITIVE, so the min over rounds
    # is the unloaded estimate — gate on min ratios, report medians
    reg_fused = min(times["fused"]) / min(times["base"]) - 1.0
    reg_16 = min(times["fused16"]) / min(times["base"]) - 1.0
    # CPU budget calibration: quiet-box truth is fused ~+1-3%, but shared
    # build runners resolve no finer than ~±10% even with min-over-rounds
    # (measured: the same config's rounds spread 2x under load bursts).
    # The budgets below catch gross regressions (an accidental per-leaf
    # fallback roughly doubles update cost); the sharp gates in this
    # smoke are parity / footprint / retrace. On TPU the timing floor is
    # clean — hold both paths to 5%.
    on_cpu = jax.devices()[0].platform == "cpu"
    budget_fused = 0.12 if on_cpu else 0.05
    if reg_fused > budget_fused:
        fail(f"fused-update step-time regression {reg_fused:.1%} exceeds "
             f"the {budget_fused:.0%} budget",
             **{f"{k}_times": [round(t, 4) for t in v]
                for k, v in times.items()})
    budget_16 = 0.20 if on_cpu else 0.05
    if reg_16 > budget_16:
        fail(f"fused+bf16 step-time regression {reg_16:.1%} exceeds the "
             f"{budget_16:.0%} budget (SR draws included)",
             **{f"{k}_times": [round(t, 4) for t in v]
                for k, v in times.items()})

    # --- gate 5: fused epilogue (inference tier) -----------------------
    prof.reset()
    from deeplearning4j_tpu.ops import pallas_epilogue
    from deeplearning4j_tpu.ops.registry import get_op

    erng = np.random.default_rng(3)
    ex = jnp.asarray(erng.normal(size=(4, 256, 7, 7)), jnp.float32)
    em = jnp.asarray(erng.normal(size=256), jnp.float32)
    ev = jnp.asarray(erng.uniform(0.5, 2.0, size=256), jnp.float32)
    eg = jnp.asarray(erng.normal(size=256), jnp.float32)
    eb = jnp.asarray(erng.normal(size=256), jnp.float32)
    eres = jnp.asarray(erng.normal(size=(4, 256, 7, 7)), jnp.float32)
    fused_out = pallas_epilogue.bn_act(ex, em, ev, eg, eb, axis=1,
                                       act="relu", residual=eres)
    dense_out = jnp.maximum(get_op("batchnorm").fn(
        ex, em, ev, eg, eb, axis=1) + eres, 0)
    if fused_out is None or not np.allclose(
            np.asarray(fused_out), np.asarray(dense_out),
            rtol=1e-5, atol=1e-5):
        fail("fused epilogue parity break vs dense ops")
    pstats = prof.precision_stats()
    if not pstats.get("epilogue_hits"):
        fail("precision ledger empty after epilogue run", ledger=pstats)

    return {
        "metric": "mfu_smoke",
        "value": n / t_fused,
        "unit": "images/sec",
        "batch": batch,
        "platform": jax.devices()[0].platform,
        "traces": warm["fused16"],
        "kernel_parity": "bitwise",
        "fit_parity_fp32": "<=4e-6",
        "bf16_envelope": "|d| <= 1e-3 + 0.05|ref|",
        "parity_steps_compared": len(seqs["base"]),
        "step_time_ratio_fused_vs_base": round(1.0 + reg_fused, 4),
        "step_time_ratio_fused16_vs_base": round(1.0 + reg_16, 4),
        "epoch_s_base_median": round(t_base, 4),
        "epoch_s_fused16_median": round(t_fused, 4),
        "updater_state_bytes_fp32": bytes_a["total"],
        "updater_state_bytes_bf16": bytes_c["total"],
        "state_bytes_ratio": round(bytes_c["total"] / bytes_a["total"], 4),
        "precision_ledger": {k: (round(v, 5) if isinstance(v, float)
                                 else v)
                             for k, v in {**fit_ledger, **pstats}.items()},
        "data": "synthetic LeNet batches; per-leaf fp32 vs fused vs "
                "fused+bf16-state epochs interleaved",
    }


def bench_remat_smoke(steps: int, batch: int = 64) -> dict:
    """CPU-friendly smoke of policy-driven rematerialization + the
    flat-backward fused epilogue (ISSUE 16): a dense stack with a fused
    Adam updater trained five ways — remat policy none (A), dots_only
    (B), full (C), a selective block list (D), all on the flat-backward
    epilogue, plus the legacy dense-grads-then-flatten step (E,
    flat_backward=False) — interleaved A/B timing with the
    min-over-rounds estimator every overhead smoke shares.
    Self-validating hard-fails:

    - any remat policy NOT bitwise-identical to "none" (loss sequence
      AND final params — remat replays the same ops in the same order;
      on CPU there is no fma excuse);
    - flat-backward vs legacy params/updater-state not bitwise (the
      flat cotangent is the EXACT concatenation of the dense leaf
      cotangents via Zero1Plan.unflatten_diff — drift means the adjoint
      is wrong);
    - a flat-backward leg that compiled without the epilogue
      (precision/grads_flat_in_step must read 1) or a legacy leg that
      claims it (must read 0);
    - any retrace delta between configs, a policy flip that costs more
      than exactly ONE retrace, or any retrace inside the timed
      steady-state windows;
    - flat-backward step time > 12% over legacy on CPU (same budget as
      mfu-smoke's fused-vs-base: shared runners resolve no finer), 5%
      on TPU;
    - ON TPU ONLY: dots_only temp bytes not strictly below none (the
      HBM-watermark claim). The CPU scheduler shows the INVERSE (its
      remat graph allocates MORE temp — the same documented property
      test_l6_features and test_remat_policies gate on), so on CPU the
      per-policy temp bytes are REPORTED, never gated.

    Emits per-policy temp bytes + step times alongside the timing."""
    import statistics as _stats

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.common import tracecheck
    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.data import NDArrayDataSetIterator
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.ndarray.rng import set_default_seed
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import layers as L
    from deeplearning4j_tpu.optimize.listeners import (
        CollectScoresIterationListener)

    def fail(msg, **extra):
        print(json.dumps({"error": msg, **extra}))
        sys.exit(1)

    rng = np.random.RandomState(0)
    n = steps * batch
    D, DEPTH = 128, 6
    x = rng.randn(n, D).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]

    def make_it():
        return NDArrayDataSetIterator(x, y, batch_size=batch)

    def build(policy, flat_backward=True):
        set_default_seed(77)
        b = (NeuralNetConfiguration.builder().seed(55)
             .updater(Adam(learning_rate=1e-3)).fused_update()
             .activation("relu").weight_init("xavier"))
        if policy is not None:
            b = b.remat_policy(policy)
        lb = b.list()
        for _ in range(DEPTH):
            lb = lb.layer(L.DenseLayer(n_out=D))
        conf = (lb.layer(L.OutputLayer(n_out=10, loss="mcxent",
                                       activation="softmax"))
                .set_input_type(InputType.feed_forward(D)).build())
        conf.global_conf.flat_backward = flat_backward
        return MultiLayerNetwork(conf).init()

    prof = OpProfiler.get()
    configs = {"none": (None, True), "dots_only": ("dots_only", True),
               "full": ("full", True), "selective": ([1, 3, 5], True),
               "legacy": (None, False)}
    models, seqs, warm, ledger = {}, {}, {}, {}
    for name, (pol, fb) in configs.items():
        m = build(pol, flat_backward=fb)
        scores = CollectScoresIterationListener()
        m.set_listeners(scores)
        prof.reset()
        m.fit(make_it(), epochs=1, batch_size=batch)
        float(m._score_dev)
        warm[name] = prof.trace_counts()
        ledger[name] = prof.precision_stats()
        seqs[name] = [s for _, s in scores.scores]
        models[name] = m

    def bitwise(a, b):
        la, lb = jax.tree.leaves(jax.device_get(a)), jax.tree.leaves(
            jax.device_get(b))
        return len(la) == len(lb) and all(
            np.array_equal(np.asarray(p), np.asarray(q))
            for p, q in zip(la, lb))

    # --- gate 1: remat policies are numerically free -------------------
    for name in ("dots_only", "full", "selective"):
        if seqs[name] != seqs["none"]:
            fail(f"remat policy {name!r} loss sequence is not bitwise-"
                 "identical to none", steps_compared=len(seqs["none"]))
        if not bitwise(models[name]._params, models["none"]._params):
            fail(f"remat policy {name!r} final params drifted from none")

    # --- gate 2: flat-backward epilogue vs legacy is bitwise -----------
    if seqs["legacy"] != seqs["none"]:
        fail("flat-backward loss sequence is not bitwise-identical to "
             "the legacy dense-grads step")
    if not bitwise(models["legacy"]._params, models["none"]._params):
        fail("flat-backward final params drifted from the legacy step")
    if not bitwise(models["legacy"]._updater_state,
                   models["none"]._updater_state):
        fail("flat-backward updater state drifted from the legacy step")
    for name, want in (("none", 1), ("legacy", 0)):
        if ledger[name].get("grads_flat_in_step") != want:
            fail(f"config {name!r}: precision/grads_flat_in_step != "
                 f"{want}", ledger=ledger[name])

    # --- gate 3: retrace accounting ------------------------------------
    if len({tuple(sorted(w.items())) for w in warm.values()}) != 1:
        fail("retrace delta between configs", traces=warm)
    # the flip drill: switching policy in place costs exactly ONE
    # retrace, then the loop is steady again
    flip = models["none"]
    prof.reset()
    flip.set_remat_policy("dots_only")
    flip.fit(make_it(), epochs=1, batch_size=batch)
    float(flip._score_dev)
    flips = prof.trace_counts()
    if sum(flips.values()) != 1:
        fail("policy flip cost more than one retrace", traces=flips)
    with tracecheck.steady_state("remat-smoke post-flip refit",
                                 max_host_syncs=None):
        flip.fit(make_it(), epochs=1, batch_size=batch)
        float(flip._score_dev)
    flip.set_remat_policy(None)         # restore for the timed rounds
    flip.fit(make_it(), epochs=1, batch_size=batch)
    float(flip._score_dev)

    # --- gate 4: per-policy temp bytes (platform-aware) ----------------
    # XLA's own memory accounting of the compiled grad step. TPU gates
    # the watermark claim; the CPU scheduler's remat graph allocates
    # MORE temp (documented inverse), so CPU reports without gating.
    xb = jnp.asarray(x[:batch])
    yb = jnp.asarray(y[:batch])
    key = jax.random.PRNGKey(0)

    def temp_bytes(name):
        m = models[name]

        def loss_fn(params):
            loss, _ = m._loss(params, m._states, xb, yb, None, True, key)
            return loss

        comp = jax.jit(jax.grad(loss_fn)).lower(m._params).compile()
        return int(comp.memory_analysis().temp_size_in_bytes)

    temps = {name: temp_bytes(name)
             for name in ("none", "dots_only", "full")}
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    if on_tpu and temps["dots_only"] >= temps["none"]:
        fail("dots_only temp bytes not below none on TPU", temps=temps)

    # --- gate 5: interleaved A/B step time -----------------------------
    def timed_epoch(name):
        t0 = time.perf_counter()
        models[name].fit(make_it(), epochs=1, batch_size=batch)
        float(models[name]._score_dev)
        return time.perf_counter() - t0

    order_fwd = tuple(configs)
    for name in order_fwd:                        # settle round, untimed
        timed_epoch(name)
    prof.reset()
    times = {name: [] for name in configs}
    with tracecheck.steady_state("remat-smoke timed rounds",
                                 max_host_syncs=None):
        for r in range(10):
            for name in (order_fwd if r % 2 == 0
                         else tuple(reversed(order_fwd))):
                times[name].append(timed_epoch(name))
    hot = prof.trace_counts()
    if any(hot.values()):
        fail("train step retraced inside a timed window", traces=hot)
    # build boxes carry bursty ADDITIVE noise — min over rounds is the
    # unloaded estimate (the estimator every overhead smoke shares)
    reg_flat = min(times["none"]) / min(times["legacy"]) - 1.0
    on_cpu = jax.devices()[0].platform == "cpu"
    budget = 0.12 if on_cpu else 0.05
    if reg_flat > budget:
        fail(f"flat-backward step-time regression {reg_flat:.1%} "
             f"exceeds the {budget:.0%} budget vs the legacy step",
             **{f"{k}_times": [round(t, 4) for t in v]
                for k, v in times.items()})

    t_none = _stats.median(times["none"])
    return {
        "metric": "remat_smoke",
        "value": n / t_none,
        "unit": "images/sec",
        "batch": batch,
        "platform": jax.devices()[0].platform,
        "traces": warm["none"],
        "policy_parity": "bitwise",
        "flat_vs_legacy_parity": "bitwise",
        "parity_steps_compared": len(seqs["none"]),
        "grads_flat_in_step": ledger["none"].get("grads_flat_in_step"),
        "step_time_ratio_flat_vs_legacy": round(1.0 + reg_flat, 4),
        "temp_bytes": temps,
        "temp_bytes_gated": on_tpu,
        "epoch_s_none_median": round(t_none, 4),
        "epoch_s_dots_only_median": round(
            _stats.median(times["dots_only"]), 4),
        "epoch_s_full_median": round(_stats.median(times["full"]), 4),
        "epoch_s_legacy_median": round(_stats.median(times["legacy"]), 4),
        "data": "synthetic dense-stack batches; remat none/dots_only/"
                "full/selective + legacy dense-grad epochs interleaved",
    }


def bench_elastic_smoke(steps: int, batch: int = 64, workers: int = 4) -> dict:
    """CPU-friendly smoke of ONLINE elastic resize (ISSUE 6; ROADMAP item
    4(b)): the flagship LeNet config through ParallelWrapper with the
    ZeRO-1 accumulator, a deterministic ``device/loss`` fault mid-epoch,
    shrink-and-continue in memory, then interleaved A/B epochs at N and
    N-1 workers through the per-worker-count executable cache.
    Self-validating hard-fails:

    - parity break: the shrunk continuation's final params/updater state
      must be BITWISE-equal to a fresh (N-1)-worker run handed the same
      host-materialized state, pipeline cursor and RNG stream (the
      resharding is a pure permutation — same guarantee as checkpoint
      resharding, no disk involved);
    - retrace: the whole elastic cycle (kill -> shrink -> continue) must
      compile exactly once per worker count, and the interleaved timed
      rounds (6 x resize N <-> N-1) must trigger ZERO further traces —
      any retrace beyond one-recompile-per-worker-count fails;
    - throughput: the post-shrink epoch must sustain at least
      0.9 x (N-1)/N of the pre-shrink throughput (median of interleaved
      rounds — losing a replica may cost its share of the axis, but the
      resize itself must not tax the steady state);
    - the ``elastic/*`` ledger (resize counts, worker gauge) must
      populate — the /api/health section the drill is monitored by.

    Emits the elastic ledger alongside the timing."""
    import statistics as _stats

    # a multi-replica mesh is the whole point: on single-device hosts
    # (CPU build machines) request virtual CPU devices BEFORE jax loads
    if "jax" not in sys.modules:
        _flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in _flags:
            os.environ["XLA_FLAGS"] = (
                _flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.common import faultinject
    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.data import NDArrayDataSetIterator
    from deeplearning4j_tpu.ndarray.rng import get_random, set_default_seed
    from deeplearning4j_tpu.parallel import (ParallelWrapper,
                                             ReduceScatterAccumulator)

    def fail(msg, **extra):
        print(json.dumps({"error": msg, **extra}))
        sys.exit(1)

    workers = min(workers, len(jax.devices()))
    if workers < 2:
        fail("elastic-smoke needs >= 2 devices (virtual CPU device request "
             "came too late — is jax initialized before bench dispatch?)",
             devices=len(jax.devices()))
    rng_np = np.random.RandomState(0)
    n = steps * batch
    x = rng_np.randn(n, 1, 28, 28).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng_np.randint(0, 10, n)]

    def make_it():
        return NDArrayDataSetIterator(x, y, batch_size=batch)

    def build(n_workers):
        set_default_seed(99)
        model = _lenet_model()
        pw = (ParallelWrapper.Builder(model).workers(n_workers)
              .gradients_accumulator(ReduceScatterAccumulator()).build())
        return model, pw

    def host_state(model):
        # owning copies — the same moves resize() makes internally
        return jax.tree.map(np.array, jax.device_get(
            (model._params, model._states, model._updater_state,
             getattr(model, "_acc_state", None) or None)))

    prof = OpProfiler.get()
    prof.reset()
    faultinject.clear_plan()

    # --- elastic run: N workers, device loss mid epoch 2, shrink -------
    m1, pw = build(workers)
    kill_at = steps + max(1, steps // 2)          # mid epoch 2 of 2
    faultinject.set_plan(faultinject.FaultPlan(
        [{"site": "device/loss", "index": kill_at, "kind": "device_loss",
          "replica": 1}]))
    try:
        pw.fit(make_it(), epochs=2, batch_size=batch)
        fail("device/loss fault plan did not fire", kill_at=kill_at)
    except faultinject.DeviceLostError:
        pass
    faultinject.clear_plan()
    cursor = (int(m1._epoch - m1._fit_epoch0), int(m1._steps_in_epoch))
    snap = host_state(m1)
    it_ep = (m1._iteration, m1._epoch)
    rng_state = get_random().get_state()
    removed = pw.resize(workers - 1, lost_replicas=[1])
    if len(removed) != 1:
        fail("shrink did not remove exactly the lost device",
             removed=len(removed))
    pw.fit(make_it(), epochs=2, batch_size=batch, resume_cursor=cursor)
    float(m1._score_dev)
    traces = prof.trace_counts()
    if traces.get("trace/pw_fit_step") != 2:
        fail("elastic cycle broke one-compile-per-worker-count",
             traces=traces)

    # --- reference: fresh (N-1)-worker run from the same state ---------
    set_default_seed(99)
    m2 = _lenet_model()
    params, states, upd, acc = snap
    m2._params = jax.tree.map(jnp.array, params)
    m2._states = jax.tree.map(jnp.array, states)
    m2._updater_state = upd                 # flat: reshards on placement
    m2._acc_state = acc
    m2._iteration, m2._epoch = it_ep
    get_random().set_state(rng_state)
    pw2 = (ParallelWrapper.Builder(m2).workers(workers - 1)
           .gradients_accumulator(ReduceScatterAccumulator()).build())
    pw2.fit(make_it(), epochs=2, batch_size=batch, resume_cursor=cursor)
    float(m2._score_dev)
    for name, a, b in (("params", m1._params, m2._params),
                       ("updater state", m1._updater_state,
                        m2._updater_state)):
        la = jax.tree.leaves(jax.device_get(a))
        lb = jax.tree.leaves(jax.device_get(b))
        if len(la) != len(lb) or not all(
                np.array_equal(np.asarray(p), np.asarray(q))
                for p, q in zip(la, lb)):
            fail(f"elastic parity break: post-shrink {name} differ from a "
                 "fresh run resharded at the same step")

    # --- interleaved A/B throughput via cached executables -------------
    def timed_epoch():
        t0 = time.perf_counter()
        pw.fit(make_it(), epochs=1, batch_size=batch)
        float(m1._score_dev)
        return time.perf_counter() - t0

    pw.resize(workers)                       # grow back: cached, no compile
    timed_epoch()
    pw.resize(workers - 1)
    timed_epoch()                            # settle rounds, untimed
    prof.reset()
    times = {"pre": [], "post": []}
    ratios = []
    for r in range(6):
        pw.resize(workers)
        t_pre = timed_epoch()
        pw.resize(workers - 1)
        t_post = timed_epoch()
        times["pre"].append(t_pre)
        times["post"].append(t_post)
        ratios.append(t_pre / t_post)        # = post/pre throughput ratio
    hot = prof.trace_counts()
    if any(hot.values()):
        fail("resize retraced inside a timed window (executable cache "
             "miss)", traces=hot)
    floor = 0.9 * (workers - 1) / workers
    ratio = _stats.median(ratios)
    if ratio < floor:
        fail(f"post-shrink throughput ratio {ratio:.3f} is below the "
             f"0.9 x (N-1)/N floor {floor:.3f}",
             pre_times=[round(t, 4) for t in times["pre"]],
             post_times=[round(t, 4) for t in times["post"]])
    ledger = prof.elastic_stats()
    if not ledger.get("resizes") or "workers" not in ledger:
        fail("elastic ledger did not populate", ledger=ledger)

    t_pre = _stats.median(times["pre"])
    t_post = _stats.median(times["post"])
    return {
        "metric": "elastic_smoke",
        "value": n / t_post,
        "unit": "images/sec",
        "batch": batch,
        "workers_pre": workers,
        "workers_post": workers - 1,
        "platform": jax.devices()[0].platform,
        "parity": "exact",
        "shrink_cursor": list(cursor),
        "traces": traces,
        "throughput_ratio_post_vs_pre": round(ratio, 4),
        "throughput_floor": round(floor, 4),
        "epoch_s_pre_median": round(t_pre, 4),
        "epoch_s_post_median": round(t_post, 4),
        "elastic_ledger": {k: (round(v, 5) if isinstance(v, float) else v)
                           for k, v in ledger.items()},
        "data": "synthetic LeNet batches; mid-epoch device/loss shrink "
                "N->N-1 with bitwise parity vs a fresh (N-1)-worker run "
                "from the same state, then interleaved N/(N-1) epochs "
                "through the per-worker-count executable cache",
    }


_CLUSTER_TRAINER = r"""
import io, json, os, sys, time
import numpy as np
from deeplearning4j_tpu.parallel import cluster
from deeplearning4j_tpu.parallel.sharding import Zero1Plan
from deeplearning4j_tpu.util import checkpoint as ckpt

(cluster_dir, ckpt_dir, log_path, rank, world, total_iters, crash_rank,
 crash_iter) = (sys.argv[1], sys.argv[2], sys.argv[3], int(sys.argv[4]),
                int(sys.argv[5]), int(sys.argv[6]), int(sys.argv[7]),
                int(sys.argv[8]))
att = os.environ.get("DL4J_ATTEMPT", "0")
N = 25   # odd on purpose: padding differs across worker counts

rt = cluster.ClusterRuntime(cluster_dir, rank, world,
                            heartbeat_interval_s=0.05,
                            incarnation=int(att))
rt.form()
rt.dump_rank_blackbox()
plan = Zero1Plan({"w": np.zeros(N, np.float32)}, world)
bucket = plan.buckets[0]
key, shard, padded = bucket.key, bucket.shard, bucket.padded
lo, hi = rank * shard, (rank + 1) * shard

params = np.linspace(-1.0, 1.0, N).astype(np.float32)
m = np.zeros(padded, np.float32)
start_it = 0
last = ckpt.last_checkpoint(ckpt_dir) if os.path.isdir(ckpt_dir) else None
if last is not None:
    with np.load(last) as z:
        params = z["params"]
        start_it = int(z["iteration"])
        stored = {"m": {key: z["m"]}}
    # the group checkpoint's flat layout is replica-count independent:
    # a relaunch at ANY world size reshards the stored padding to its own
    m = np.asarray(plan.reshard_state(stored)["m"][key])
if rank == 0:
    os.makedirs(ckpt_dir, exist_ok=True)
    rt.claim_commit_incarnation(ckpt_dir)

for it in range(start_it + 1, total_iters + 1):
    gp = np.zeros(padded, np.float32)
    gp[:N] = np.float32(0.05) * params + np.float32(0.001) * np.float32(it)
    m[lo:hi] = np.float32(0.9) * m[lo:hi] + gp[lo:hi]   # OWN shard only
    np.save(os.path.join(cluster_dir, f"m-a{att}-{it}.r{rank}.npy"),
            m[lo:hi])
    rt.barrier(f"step-a{att}", gen=it, deadline_s=30.0)
    m = np.concatenate([
        np.load(os.path.join(cluster_dir, f"m-a{att}-{it}.r{r}.npy"))
        for r in range(world)])
    params = params - (np.float32(0.1) * m)[:N]
    if rank == 0:
        with open(log_path, "a") as f:
            f.write(json.dumps({"iteration": it,
                                "loss": float(np.sum(params))}) + "\n")
    if it % 3 == 0:
        buf = io.BytesIO()
        np.savez(buf, params=params, m=m, iteration=np.int64(it))
        rt.commit_group_checkpoint(ckpt_dir, f"it{it}", buf.getvalue(),
                                   it, seq=it, barrier_deadline_s=30.0)
    if att == "0" and rank == crash_rank and it == crash_iter:
        rt.dump_rank_blackbox()   # the dying rank's last words
        os._exit(1)
"""

_CLUSTER_DEAD_COORD = r"""
import json, sys, time
from deeplearning4j_tpu.parallel import cluster

cluster_dir, port = sys.argv[1], sys.argv[2]
rt = cluster.ClusterRuntime(cluster_dir, 1, 2,
                            coordinator=f"127.0.0.1:{port}",
                            init_deadline_s=4.0,
                            init_backoff_base_s=0.1,
                            init_backoff_max_s=0.5)
t0 = time.monotonic()
try:
    rt.form()
except cluster.ClusterInitError as e:
    rt.shutdown()
    print(json.dumps({"failed": True,
                      "elapsed_s": round(time.monotonic() - t0, 2),
                      "attempts": e.attempts, "coordinator": e.coordinator,
                      "reported": e.reported_ranks, "msg": str(e)}))
    sys.exit(0)
print(json.dumps({"failed": False}))
sys.exit(1)
"""


def bench_cluster_smoke(steps: int, workers: int = 3) -> dict:
    """Hardened cluster-runtime smoke (ISSUE 18): real OS processes
    through ``ClusterRuntime`` + elastic ``supervise_processes``.
    Self-validating hard-fails:

    - kill-a-rank-mid-epoch (full-count restart): the relaunched group
      must resume from the group checkpoint BIT-exactly vs a fresh
      uninterrupted N-world run, with exactly ONE finalized watchtower
      incident whose chain cause is ``cluster/rank_lost`` naming the
      killed rank and carrying the merged per-rank blackboxes;
    - shrink-to-survivors: the same drill relaunched at N-1 ranks,
      resharding the group checkpoint through ``Zero1Plan``'s
      replica-count-independent layout, bit-exact vs a fresh (N-1) run;
    - barrier timeout names the missing rank WITH its heartbeat
      staleness;
    - bring-up against a dead coordinator fails INSIDE the init
      deadline with the full diagnosis (address, attempts, ranks that
      reported) instead of jax's C++ ``abort()``;
    - zero orphan processes after every drill (process-table sweep for
      this run's unique workdir token)."""
    import shutil
    import socket
    import subprocess
    import tempfile

    import jax

    from deeplearning4j_tpu.common import faultinject, watchtower
    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.parallel import cluster
    from deeplearning4j_tpu.parallel.distributed import supervise_processes

    def fail(msg, **extra):
        print(json.dumps({"error": msg, **extra}))
        sys.exit(1)

    repo = os.path.dirname(os.path.abspath(__file__))
    env = {"PYTHONPATH": repo + (
        os.pathsep + os.environ["PYTHONPATH"]
        if os.environ.get("PYTHONPATH") else ""),
        "JAX_PLATFORMS": "cpu"}
    total_iters = max(9, min(30, steps))
    crash_iter = total_iters // 2 + 1
    prof = OpProfiler.get()
    faultinject.clear_plan()
    work = tempfile.mkdtemp(prefix="dl4j_cluster_smoke_")
    script = os.path.join(work, "trainer.py")
    with open(script, "w") as f:
        f.write(_CLUSTER_TRAINER)

    def read_log(path):
        with open(path) as f:
            rows = [json.loads(l) for l in f.read().splitlines()]
        return {r["iteration"]: r["loss"] for r in rows}

    def run_fresh(tag, world):
        """An uninterrupted baseline group run."""
        cd = os.path.join(work, f"{tag}-cd")
        log = os.path.join(work, f"{tag}.jsonl")
        procs = [subprocess.Popen(
            [sys.executable, script, cd, os.path.join(work, f"{tag}-ck"),
             log, str(r), str(world), str(total_iters), "-1", "-1"],
            env={**os.environ, **env}) for r in range(world)]
        for r, p in enumerate(procs):
            if p.wait(timeout=120) != 0:
                fail(f"baseline {tag} rank {r} failed", rc=p.returncode)
        return read_log(log)

    def run_drill(tag, world, crash_rank, shrink):
        """Kill-a-rank-mid-epoch under a fresh watchtower; returns
        (summary, losses, incident report)."""
        cd = os.path.join(work, f"{tag}-cd")
        ck = os.path.join(work, f"{tag}-ck")
        log = os.path.join(work, f"{tag}.jsonl")
        inc_dir = os.path.join(work, f"{tag}-inc")
        watchtower.uninstall()
        tower = watchtower.install(watchtower.Watchtower(
            [], incident_dir=inc_dir, interval_s=0.05,
            finalize_after_s=120.0))

        def make_commands(w, attempt):
            return [[sys.executable, script, cd, ck, log, str(r), str(w),
                     str(total_iters), str(crash_rank), str(crash_iter)]
                    for r in range(w)]

        summary = supervise_processes(
            make_commands(world, 0), env=env,
            make_env=lambda attempt: {"DL4J_ATTEMPT": str(attempt)},
            cluster_dir=cd, heartbeat_stale_s=15.0,
            make_commands=make_commands if shrink else None,
            shrink_to_survivors=shrink, min_world=world - 1,
            max_restarts=2, backoff_base_s=0.05, kill_grace_s=3.0,
            storm_min_uptime_s=0.0)
        if summary["status"] != "completed":
            fail(f"{tag}: supervised group did not complete",
                 summary=summary)
        if summary["restarts"] != 1 or \
                summary["history"][0]["failed_rank"] != crash_rank:
            fail(f"{tag}: expected exactly one restart for rank "
                 f"{crash_rank}", summary=summary)
        tower.evaluate_now()
        incs = tower.incidents()
        finalized = [i for i in incs if i.get("finalized")]
        if len(incs) != 1 or len(finalized) != 1:
            fail(f"{tag}: expected exactly one finalized incident",
                 open=len(incs), finalized=len(finalized))
        with open(finalized[0]["path"]) as f:
            report = json.load(f)
        chain = report["chain"]
        if not report["complete"] or \
                chain["cause"]["name"] != "cluster/rank_lost" or \
                chain["cause"]["attrs"].get("rank") != crash_rank:
            fail(f"{tag}: incident chain does not name the lost rank as "
                 "cause", chain=chain)
        if not report.get("attachments", {}).get("rank_blackboxes"):
            fail(f"{tag}: merged per-rank blackboxes missing from the "
                 "incident", attachments=list(report.get("attachments",
                                                         {})))
        watchtower.uninstall()
        return summary, read_log(log), report

    try:
        t0 = time.perf_counter()

        # -- drill 1: kill-a-rank, FULL-count restart, bit-exact resume
        base_n = run_fresh("base-n", workers)
        if sorted(base_n) != list(range(1, total_iters + 1)):
            fail("baseline N-world run incomplete", got=len(base_n))
        sum_full, losses_full, rep_full = run_drill(
            "full", workers, crash_rank=1, shrink=False)
        if sum_full["world"] != workers:
            fail("full-count drill changed the world size",
                 summary=sum_full)
        if losses_full != base_n:
            bad = next((i for i in sorted(base_n)
                        if losses_full.get(i) != base_n[i]), None)
            fail("full-count resume is not bit-exact vs the fresh "
                 "N-world run", first_diff_iteration=bad)

        # -- drill 2: kill-a-rank, SHRINK to survivors, bit-exact vs a
        # fresh (N-1)-world run through the resharded flat state
        base_n1 = run_fresh("base-n1", workers - 1)
        sum_shr, losses_shr, rep_shr = run_drill(
            "shrink", workers, crash_rank=workers - 1, shrink=True)
        if sum_shr["world"] != workers - 1:
            fail("shrink drill did not shrink the group",
                 summary=sum_shr)
        if losses_shr != base_n1:
            bad = next((i for i in sorted(base_n1)
                        if losses_shr.get(i) != base_n1[i]), None)
            fail("shrunk resume is not bit-exact vs the fresh (N-1) "
                 "run", first_diff_iteration=bad)

        # -- drill 3: barrier timeout names the missing rank + staleness
        bdir = os.path.join(work, "barrier-cd")
        rt = cluster.ClusterRuntime(bdir, 0, 2)
        with open(cluster.heartbeat_path(bdir, 1), "w") as f:
            json.dump({"rank": 1, "pid": 0, "incarnation": 0, "seq": 1,
                       "t_wall": time.time() - 4.0, "cadence_s": 0.25}, f)
        try:
            rt.barrier("smoke-fence", deadline_s=0.5)
            fail("barrier against a missing rank did not time out")
        except cluster.BarrierTimeout as e:
            if e.missing != [1] or not (3.0 < (e.staleness[1] or 0) < 10.0) \
                    or "stale" not in str(e):
                fail("barrier timeout diagnosis incomplete",
                     missing=e.missing, staleness=e.staleness,
                     msg=str(e))

        # -- drill 4: dead coordinator fails INSIDE the deadline with
        # the diagnosis (subprocess: jax's client would abort() us)
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()   # nobody listens here any more
        dc = os.path.join(work, "deadcoord.py")
        with open(dc, "w") as f:
            f.write(_CLUSTER_DEAD_COORD)
        p = subprocess.run(
            [sys.executable, dc, os.path.join(work, "dead-cd"),
             str(dead_port)],
            env={**os.environ, **env}, capture_output=True, text=True,
            timeout=60)
        if p.returncode != 0:
            fail("dead-coordinator drill did not fail cleanly",
                 rc=p.returncode, err=p.stderr[-1500:])
        diag = json.loads(p.stdout.strip().splitlines()[-1])
        if not diag["failed"] or diag["elapsed_s"] > 8.0 or \
                diag["attempts"] < 2 or \
                f"127.0.0.1:{dead_port}" not in diag["msg"] or \
                "ranks that reported a heartbeat" not in diag["msg"]:
            fail("dead-coordinator diagnosis incomplete", diag=diag)

        # -- drill 5: zero orphans (process-table sweep for this run's
        # unique workdir token in any live cmdline)
        token = os.path.basename(work)
        orphans = []
        for pid in os.listdir("/proc"):
            if not pid.isdigit() or int(pid) == os.getpid():
                continue
            try:
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    if token.encode() in f.read():
                        orphans.append(int(pid))
            except OSError:
                continue
        if orphans:
            fail("orphan worker processes survived the drills",
                 pids=orphans)

        wall = time.perf_counter() - t0
        ledger = {k: prof.counter_value(k) for k in
                  ("cluster/formed", "cluster/groups_formed",
                   "cluster/barriers", "cluster/barrier_timeouts",
                   "cluster/group_commits", "cluster/rank_crash",
                   "cluster/shrinks", "supervisor/proc_restarts")}
        # supervised iterations actually retrained across both drills
        return {
            "metric": "cluster_smoke",
            "value": (2 * total_iters) / wall,
            "unit": "supervised-iters/sec",
            "platform": jax.devices()[0].platform,
            "workers": workers,
            "total_iters": total_iters,
            "crash_iter": crash_iter,
            "full_count_incident": rep_full["id"],
            "shrink_incident": rep_shr["id"],
            "dead_coordinator": {"elapsed_s": diag["elapsed_s"],
                                 "attempts": diag["attempts"]},
            "orphans": 0,
            "resume_parity": "exact",
            "cluster_ledger": ledger,
            "data": "Zero1Plan flat-state trainer over real OS process "
                    "groups; kill-a-rank mid-epoch healed full-count and "
                    "shrunk-to-survivors, bit-exact vs fresh baselines",
        }
    finally:
        watchtower.uninstall()
        faultinject.clear_plan()
        shutil.rmtree(work, ignore_errors=True)


def bench_pipeline_parallel_smoke(steps: int, batch: int = 64) -> dict:
    """Self-healing pipeline-parallel smoke (ISSUE 14; ROADMAP item 2):
    a 12-layer homogeneous dense stack through ``PipelineTrainer`` as
    4-stage 1F1B x 2-way data on the CPU mesh. Self-validating
    hard-fails:

    - bubble: the schedule-accounted bubble fraction (``pipeline``
      ledger — tick occupancy of the very mask tables the compiled step
      executes) must be <= the analytic (S-1)/(M+S-1) bound + 10%. This
      polices the SCHEDULE TABLES against the closed-form bound (a
      schedule_meta regression that pads extra ticks or drops ops
      fails it); it is not a wall-clock measurement — wall-clock
      efficiency is what the throughput gate below owns;
    - retrace flatness: the whole warmup -> kill -> remap -> grow cycle
      compiles exactly once per (stage-count, schedule), and the timed
      interleaved rounds run under ``tracecheck.steady_state`` — any
      trace/compile/host-sync hard-fails;
    - recovery: a mid-epoch ``pipeline/stage`` device_loss drill
      recovers by ``remap_and_continue`` (4 -> 3 stages) with ZERO lost
      microbatches (ledger-counted against the clean expectation) and a
      finite post-remap loss;
    - throughput: the post-remap (3-stage) epoch must sustain at least
      0.9 x (S-1)/S of the 4-stage throughput (median of interleaved
      rounds through the per-stage-count executable cache).

    Emits the pipeline ledger alongside the timing."""
    import statistics as _stats

    if "jax" not in sys.modules:
        _flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in _flags:
            os.environ["XLA_FLAGS"] = (
                _flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    from deeplearning4j_tpu.common import faultinject, tracecheck
    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.data import NDArrayDataSetIterator
    from deeplearning4j_tpu.learning import Sgd
    from deeplearning4j_tpu.ndarray.rng import set_default_seed
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import layers as NL
    from deeplearning4j_tpu.parallel import PipelineTrainer

    def fail(msg, **extra):
        print(json.dumps({"error": msg, **extra}))
        sys.exit(1)

    S, M, D, n_layers, feat = 4, 8, 2, 12, 32
    if len(jax.devices()) < S * D:
        fail("pipeline-parallel-smoke needs >= 8 devices (virtual CPU "
             "device request came too late?)", devices=len(jax.devices()))
    if batch % (D * M):
        fail(f"batch {batch} must divide by data*micro = {D * M}")
    if steps < 2:
        fail("pipeline-parallel-smoke needs --steps >= 2 (the mid-epoch "
             "kill ordinal must land inside the drill fit)", steps=steps)
    rng_np = np.random.RandomState(0)
    n = steps * batch
    x = rng_np.randn(n, feat).astype(np.float32)
    y = np.tanh(x) * 0.5

    def make_it():
        return NDArrayDataSetIterator(x, y, batch_size=batch)

    def build(stages):
        set_default_seed(77)
        b = (NeuralNetConfiguration.builder().seed(77)
             .updater(Sgd(learning_rate=0.02)).list())
        for _ in range(n_layers):
            b.layer(NL.DenseLayer(n_out=feat, activation="tanh"))
        model = MultiLayerNetwork(
            b.set_input_type(InputType.feed_forward(feat)).build()).init()
        return model, PipelineTrainer(model, stages=stages, n_micro=M,
                                      schedule="1f1b", data=D)

    prof = OpProfiler.get()
    prof.reset()
    faultinject.clear_plan()
    model, tr = build(S)

    # --- warmup + bubble gate (4-stage 1F1B) ---------------------------
    busy0 = prof.counter_value("pipeline/busy_ticks")
    slots0 = prof.counter_value("pipeline/tick_slots")
    tr.fit(make_it(), epochs=1, batch_size=batch)
    float(np.asarray(model._score_dev))
    traces = prof.trace_counts()
    if traces.get("trace/pipeline_fit_step") != 1:
        fail("warmup epoch compiled more than once", traces=traces)
    busy = prof.counter_value("pipeline/busy_ticks") - busy0
    slots = prof.counter_value("pipeline/tick_slots") - slots0
    bubble = 1.0 - busy / slots
    bound = (S - 1) / (M + S - 1)
    if bubble > bound * 1.10:
        fail(f"measured bubble fraction {bubble:.4f} exceeds the "
             f"analytic (S-1)/(M+S-1) bound {bound:.4f} + 10%",
             bubble=bubble, bound=bound)

    # --- kill-a-stage drill: remap, zero lost microbatches -------------
    micro0 = prof.counter_value("pipeline/microbatches")
    kill_at = steps + max(1, steps // 2)       # mid epoch 2 of 2
    faultinject.set_plan(faultinject.FaultPlan(
        [{"site": "pipeline/stage", "kind": "device_loss",
          "index": kill_at, "stage": 1}]))
    try:
        tr.fit(make_it(), epochs=2, batch_size=batch)
        fail("pipeline/stage fault plan did not fire", kill_at=kill_at)
    except faultinject.DeviceLostError:
        pass
    faultinject.clear_plan()
    cursor = (int(model._epoch - model._fit_epoch0),
              int(model._steps_in_epoch))
    removed = tr.remap(S - 1, lost_stages=[1])
    if len(removed) != D:
        fail("remap did not retire exactly the lost stage column",
             removed=len(removed))
    tr.fit(make_it(), epochs=2, batch_size=batch, resume_cursor=cursor)
    drill_loss = float(np.asarray(model._score_dev))
    if not np.isfinite(drill_loss):
        fail("post-remap loss went non-finite", loss=drill_loss)
    micro_seen = prof.counter_value("pipeline/microbatches") - micro0
    if micro_seen != 2 * steps * M:
        fail("kill-a-stage drill lost microbatches",
             dispatched=micro_seen, expected=2 * steps * M)
    traces = prof.trace_counts()
    if traces.get("trace/pipeline_fit_step") != 2:
        fail("kill->remap cycle broke one-compile-per-(stage-count, "
             "schedule)", traces=traces)

    # --- interleaved A/B throughput via cached executables -------------
    def timed_epoch():
        t0 = time.perf_counter()
        tr.fit(make_it(), epochs=1, batch_size=batch)
        float(np.asarray(model._score_dev))
        return time.perf_counter() - t0

    tr.remap(S)                     # grow back: cached, no compile
    timed_epoch()
    tr.remap(S - 1)
    timed_epoch()                   # settle rounds, untimed
    times = {"pre": [], "post": []}
    ratios = []
    with tracecheck.steady_state("pipeline timed rounds",
                                 max_host_syncs=None):
        for _ in range(6):
            tr.remap(S)
            t_pre = timed_epoch()
            tr.remap(S - 1)
            t_post = timed_epoch()
            times["pre"].append(t_pre)
            times["post"].append(t_post)
            ratios.append(t_pre / t_post)   # = post/pre throughput ratio
    traces = prof.trace_counts()
    if traces.get("trace/pipeline_fit_step") != 2:
        fail("timed rounds retraced (executable cache miss)",
             traces=traces)
    floor = 0.9 * (S - 1) / S
    ratio = _stats.median(ratios)
    if ratio < floor:
        fail(f"post-remap throughput ratio {ratio:.3f} is below the "
             f"0.9 x (S-1)/S floor {floor:.3f}",
             pre_times=[round(t, 4) for t in times["pre"]],
             post_times=[round(t, 4) for t in times["post"]])
    ledger = prof.pipeline_stats()
    if not ledger.get("remaps") or ledger.get("stages") != S - 1:
        fail("pipeline ledger did not populate", ledger=ledger)

    t_pre = _stats.median(times["pre"])
    t_post = _stats.median(times["post"])
    return {
        "metric": "pipeline_parallel_smoke",
        "value": n / t_pre,
        "unit": "examples/sec",
        "batch": batch,
        "schedule": "1f1b",
        "stages": S,
        "data_axis": D,
        "n_micro": M,
        "layers": n_layers,
        "platform": jax.devices()[0].platform,
        "bubble_fraction": round(bubble, 4),
        "bubble_bound": round(bound, 4),
        "drill": {"kill_at": kill_at, "cursor": list(cursor),
                  "microbatches": micro_seen, "lost": 0},
        "traces": traces,
        "throughput_ratio_post_vs_pre": round(ratio, 4),
        "throughput_floor": round(floor, 4),
        "epoch_s_pre_median": round(t_pre, 4),
        "epoch_s_post_median": round(t_post, 4),
        "pipeline_ledger": {k: (round(v, 5) if isinstance(v, float) else v)
                            for k, v in ledger.items()},
        "data": "synthetic dense-stack batches; 4-stage 1F1B x 2-way "
                "data, mid-epoch pipeline/stage kill recovered by remap "
                "to 3 stages with zero lost microbatches, interleaved "
                "4/3-stage epochs through the per-stage-count executable "
                "cache",
    }


def bench_serving_smoke(steps: int, batch: int = 32,
                        workers: int = 2) -> dict:
    """SLO-gated serving load test (ISSUE 7; ROADMAP item 2): a
    ServingEngine over a small MLP, warmed AOT bucket executables, then an
    OPEN-LOOP Poisson load (arrivals scheduled by the clock, never gated
    on completions — the arrival process a real front door sees) of
    1-8-row requests. Self-validating hard-fails:

    - **zero failed requests** in both phases — every future must resolve
      with a result;
    - **steady-state p99** <= SLO_P99_MS at the target QPS, and the
      generator must actually sustain >= 90% of the target rate (an
      open-loop generator that silently falls behind measures nothing);
    - **zero traces after warmup**: the ``trace/serving_infer`` counter
      must be exactly one-per-bucket from warmup and FLAT through both
      load phases (``serving/traces_after_warmup`` == 0) — the
      compile-once-run-many contract the bucket ladder exists for;
    - **kill-a-replica drill**: a deterministic ``dead_replica`` fault at
      a mid-load dispatch retires one of the two replicas under full
      Poisson load; the in-flight batch REQUEUES (transparent
      retirement), resurrection refills the pool, and the SLO must hold —
      zero failed requests and p99 <= DEGRADED_P99_MS across the drill
      phase.

    Emits steady/degraded p50/p99/QPS plus the serving ledger (fill
    ratio, pad waste, requeues, queue-depth high-water)."""
    import threading

    import jax

    from deeplearning4j_tpu.common import faultinject
    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import layers as L
    from deeplearning4j_tpu.parallel import ServingEngine

    TARGET_QPS = 100.0
    SLO_P99_MS = 250.0          # steady-state bound (CPU build machines)
    DEGRADED_P99_MS = 600.0     # bound while one of two replicas is dead
    REQ_ROWS_MAX = 8

    def fail(msg, **extra):
        print(json.dumps({"error": msg, **extra}))
        sys.exit(1)

    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-3))
            .activation("tanh").list()
            .layer(L.DenseLayer(n_out=64))
            .layer(L.DenseLayer(n_out=64))
            .layer(L.OutputLayer(n_out=10))
            .set_input_type(InputType.feed_forward(32)).build())
    model = MultiLayerNetwork(conf).init()

    prof = OpProfiler.get()
    prof.reset()
    faultinject.clear_plan()

    t_warm0 = time.perf_counter()
    eng = (ServingEngine.Builder(model)
           .buckets([1, 2, 4, 8, 16, batch]).input_shape((32,))
           .workers(workers).max_wait_ms(2.0)
           .request_timeout_ms(15000)
           .resurrect_dead_replicas(True, backoff_ms=100)
           .build())
    warmup_s = time.perf_counter() - t_warm0
    traces_at_warmup = prof.counter_value("trace/serving_infer")
    n_buckets = len(eng.ladder.batch_sizes)
    if traces_at_warmup != n_buckets:
        fail("warmup did not compile exactly one executable per bucket",
             traces=traces_at_warmup, buckets=n_buckets)

    rng = np.random.RandomState(0)
    inputs = rng.randn(REQ_ROWS_MAX, 32).astype(np.float32)

    def poisson_phase(n_requests, qps, seed):
        """Open-loop: submit on the arrival schedule, collect completion
        latency via done-callbacks. Returns (latencies_s, failures,
        wall_s)."""
        r = np.random.RandomState(seed)
        gaps = r.exponential(1.0 / qps, n_requests)
        sizes = r.randint(1, REQ_ROWS_MAX + 1, n_requests)
        lat, failures, lock = [], [], threading.Lock()
        done = threading.Semaphore(0)

        def submit(i, t_sub):
            fut = eng.output_async(inputs[:sizes[i]])

            def on_done(f, t_sub=t_sub):
                with lock:
                    if f.exception() is not None:
                        failures.append(str(f.exception()))
                    else:
                        lat.append(time.monotonic() - t_sub)
                done.release()

            fut.add_done_callback(on_done)

        t0 = time.monotonic()
        t_next = t0
        for i in range(n_requests):
            t_next += gaps[i]
            delay = t_next - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            submit(i, t_next)      # latency from the SCHEDULED arrival
        for _ in range(n_requests):
            if not done.acquire(timeout=30):
                fail("load phase hung: requests never resolved",
                     resolved=len(lat) + len(failures), of=n_requests)
        wall = time.monotonic() - t0
        return lat, failures, wall

    # --- steady-state phase -------------------------------------------
    n_steady = max(300, steps * 10)
    lat, failures, wall = poisson_phase(n_steady, TARGET_QPS, seed=1)
    if failures:
        fail("steady-state phase had failed requests",
             n=len(failures), first=failures[0])
    qps = n_steady / wall
    p50 = float(np.percentile(np.asarray(lat) * 1e3, 50))
    p99 = float(np.percentile(np.asarray(lat) * 1e3, 99))
    if qps < 0.9 * TARGET_QPS:
        fail(f"open-loop generator fell behind: {qps:.1f} qps vs target "
             f"{TARGET_QPS}", wall_s=round(wall, 2))
    if p99 > SLO_P99_MS:
        fail(f"steady-state p99 {p99:.1f}ms violates the {SLO_P99_MS}ms "
             f"SLO", p50_ms=round(p50, 2), qps=round(qps, 1))

    # --- kill-a-replica drill -----------------------------------------
    kill_batch = prof.counter_value("serving/batches") + 10
    faultinject.set_plan(faultinject.FaultPlan(
        [{"site": "serving/dispatch", "kind": "dead_replica",
          "index": kill_batch}]))
    n_drill = max(300, steps * 10)
    dlat, dfail, dwall = poisson_phase(n_drill, TARGET_QPS, seed=2)
    faultinject.clear_plan()
    retired = prof.counter_value("inference/replica_retired")
    if retired < 1:
        fail("kill drill did not retire a replica (fault never fired)",
             kill_batch=kill_batch,
             batches=prof.counter_value("serving/batches"))
    if dfail:
        fail("kill drill had failed requests — retirement was not "
             "transparent to in-flight load", n=len(dfail),
             first=dfail[0])
    dp50 = float(np.percentile(np.asarray(dlat) * 1e3, 50))
    dp99 = float(np.percentile(np.asarray(dlat) * 1e3, 99))
    if dp99 > DEGRADED_P99_MS:
        fail(f"kill-drill p99 {dp99:.1f}ms violates the degraded-capacity "
             f"{DEGRADED_P99_MS}ms bound", p50_ms=round(dp50, 2))

    # --- retrace + ledger gates ---------------------------------------
    traces = prof.counter_value("trace/serving_infer")
    if traces != traces_at_warmup:
        fail("serving traced AFTER warmup", warmup=traces_at_warmup,
             now=traces)
    if prof.counter_value("serving/traces_after_warmup"):
        fail("serving/traces_after_warmup counter is non-zero",
             n=prof.counter_value("serving/traces_after_warmup"))
    ledger = prof.serving_stats()
    if not ledger.get("requests") or "fill_ratio" not in ledger:
        fail("serving ledger did not populate", ledger=ledger)
    if not ledger.get("requeued"):
        fail("kill drill retired a replica but nothing was requeued — "
             "the in-flight batch was dropped or failed", ledger=ledger)

    eng.shutdown()
    return {
        "metric": "serving_smoke",
        "value": qps,
        "unit": "req/sec",
        "workers": workers,
        "target_qps": TARGET_QPS,
        "platform": jax.devices()[0].platform,
        "requests_steady": n_steady,
        "requests_drill": n_drill,
        "p50_ms": round(p50, 2),
        "p99_ms": round(p99, 2),
        "slo_p99_ms": SLO_P99_MS,
        "drill_p50_ms": round(dp50, 2),
        "drill_p99_ms": round(dp99, 2),
        "drill_slo_p99_ms": DEGRADED_P99_MS,
        "drill_qps": round(n_drill / dwall, 1),
        "replicas_retired": retired,
        "replicas_resurrected":
            prof.counter_value("inference/replica_resurrected"),
        "warmup_s": round(warmup_s, 3),
        "buckets": list(eng.ladder.batch_sizes),
        "traces": traces,
        "serving_ledger": {k: (round(v, 5) if isinstance(v, float) else v)
                           for k, v in ledger.items()},
        "data": "open-loop Poisson load of 1-8-row requests over AOT "
                "bucket executables; SLO hard-fails on p99/QPS/failed "
                "requests/retraces, incl. a kill-a-replica-mid-load "
                "drill with transparent requeue",
    }


def bench_autoscale_smoke(steps: int, batch: int = 32) -> dict:
    """Overload-safe serving smoke (ISSUE 11; ROADMAP item 4): a diurnal
    + spike traffic replay at >= 5x the serving-smoke rate over an
    SLO-classed ServingEngine with the closed-loop autoscaler attached,
    inside a ``tracecheck.steady_state`` region after warmup.
    Self-validating hard-fails:

    - **gold p99 within SLO through the spike**, with **sheds strictly
      bottom-up by class**: zero gold sheds ever, batch sheds first (the
      spike must actually shed — an un-overloaded "overload test"
      measures nothing), every brownout level transition one step;
    - **scale-up reacts** within SCALE_UP_GATE_S of the spike start
      (read off the flight recorder's ``autoscale/scale`` events) and
      **scale-down fires when idle** (fleet back at min within
      SCALE_DOWN_GATE_S after the load stops) — zero process restarts;
    - **recompiles stay at one-per-(bucket x replica count)**: the
      trace counter is FLAT from warmup through every resize
      (``serving/traces_after_warmup`` == 0);
    - **canary -> promote** and **forced-violation -> rollback** drills
      each leave a complete correlation chain in the flight recorder
      (train-commit -> canary -> promote[/rollback] under one ``pub<N>``
      id), the promote serves the checkpoint weights bitwise, the
      rollback restores the prior params bitwise, and BOTH drills
      complete with zero failed gold requests.

    The spike's overload is made deterministic with an injected ``slow``
    dispatch fault (+20ms per dispatch) — this box would otherwise
    absorb 500 qps of toy-MLP traffic without ever shedding."""
    import shutil
    import tempfile
    import threading

    import jax

    from deeplearning4j_tpu.common import faultinject, flightrec, tracecheck
    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.data import NDArrayDataSetIterator
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import layers as L
    from deeplearning4j_tpu.optimize.listeners import CheckpointListener
    from deeplearning4j_tpu.parallel import (AutoscalePolicy, Autoscaler,
                                             Overloaded, ServingEngine,
                                             SLOClass)
    from deeplearning4j_tpu.parallel.serving import next_publication_ordinal
    from deeplearning4j_tpu.util.checkpoint import (committed_checkpoints,
                                                    read_checkpoint_params)

    PEAK_QPS = 500.0            # 5x serving-smoke's 100-qps target
    GOLD_SLO_P99_MS = 500.0     # the budget the brownout defends (CPU box)
    SCALE_UP_GATE_S = 4.0
    SCALE_DOWN_GATE_S = 15.0
    REQ_ROWS_MAX = 8

    def fail(msg, **extra):
        faultinject.clear_plan()
        print(json.dumps({"error": msg, **extra}))
        sys.exit(1)

    def build_model(seed):
        conf = (NeuralNetConfiguration.builder().seed(seed)
                .updater(Adam(1e-3)).activation("tanh").list()
                .layer(L.DenseLayer(n_out=64))
                .layer(L.DenseLayer(n_out=64))
                .layer(L.OutputLayer(n_out=10))
                .set_input_type(InputType.feed_forward(32)).build())
        return MultiLayerNetwork(conf).init()

    prof = OpProfiler.get()
    prof.reset()
    faultinject.clear_plan()
    # the whole bench timeline in ONE ring: the correlation-chain gates
    # grep it end to end, exactly like a real postmortem would
    flightrec.configure(capacity=65536)
    flightrec.reset()

    # ---- train-commit leg: two committed checkpoints (compiles happen
    # here, before the steady-state region) ------------------------------
    ckdir = tempfile.mkdtemp(prefix="dl4j_autoscale_smoke_")
    try:
        trainee = build_model(seed=11)
        rng = np.random.RandomState(0)
        xs = rng.randn(8 * batch, 32).astype(np.float32)
        ys = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8 * batch)]
        cl = CheckpointListener(ckdir, save_every_n_iterations=4,
                                keep_last=4)
        trainee.set_listeners(cl)
        trainee.fit(NDArrayDataSetIterator(xs, ys, batch_size=batch),
                    epochs=2)
        cl.close()
        ckpts = committed_checkpoints(ckdir)
        if len(ckpts) < 2:
            fail("training produced fewer than 2 committed checkpoints",
                 n=len(ckpts))
        ck_promote, ck_rollback = ckpts[-2], ckpts[-1]

        # ---- engine + autoscaler --------------------------------------
        model = build_model(seed=7)
        t_warm0 = time.perf_counter()
        eng = (ServingEngine.Builder(model)
               .buckets([1, 2, 4, 8, 16, batch]).input_shape((32,))
               .workers(1).max_wait_ms(2.0).queue_limit(512)
               .request_timeout_ms(15000)
               .slo_classes([SLOClass("gold", 2, GOLD_SLO_P99_MS,
                                      queue_budget=256),
                             SLOClass("silver", 1, 800.0, queue_budget=64),
                             SLOClass("batch", 0, 2000.0, queue_budget=64)])
               .brownout(interval_s=0.1, depth_trigger=24, clear_ticks=5)
               .queue_hwm_window(1.5)
               .resurrect_dead_replicas(True, backoff_ms=100)
               .build())
        warmup_s = time.perf_counter() - t_warm0
        traces_at_warmup = prof.counter_value("trace/serving_infer")
        n_buckets = len(eng.ladder.batch_sizes)
        if traces_at_warmup != n_buckets:
            fail("warmup did not compile exactly one executable per "
                 "bucket", traces=traces_at_warmup, buckets=n_buckets)
        scaler = Autoscaler(eng, AutoscalePolicy(
            min_workers=1, max_workers=4, interval_s=0.1,
            up_queue_depth=8, up_p99_frac=0.8, down_queue_depth=0,
            down_idle_s=0.8, down_fill_frac=0.25,
            cooldown_up_s=0.4, cooldown_down_s=0.8)).start()

        inputs = np.random.RandomState(1).randn(
            REQ_ROWS_MAX, 32).astype(np.float32)
        CLASS_MIX = ["batch"] * 5 + ["silver"] * 3 + ["gold"] * 2

        def phase(n_requests, qps, seed):
            """Open-loop Poisson replay of class-mixed 1-8-row requests.
            Sheds resolve synchronously (Overloaded) and are counted per
            class; admitted requests resolve via done-callbacks."""
            r = np.random.RandomState(seed)
            gaps = r.exponential(1.0 / qps, n_requests)
            sizes = r.randint(1, REQ_ROWS_MAX + 1, n_requests)
            classes = [CLASS_MIX[i] for i in r.randint(0, len(CLASS_MIX),
                                                       n_requests)]
            lat = {c: [] for c in ("gold", "silver", "batch")}
            shed = {c: 0 for c in ("gold", "silver", "batch")}
            failures = []
            lock = threading.Lock()
            done = threading.Semaphore(0)
            admitted = 0
            t0 = time.monotonic()
            t_next = t0
            for i in range(n_requests):
                t_next += gaps[i]
                delay = t_next - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                cls = classes[i]
                try:
                    fut = eng.output_async(inputs[:sizes[i]],
                                           slo_class=cls)
                except Overloaded:
                    shed[cls] += 1
                    continue
                admitted += 1

                def on_done(f, t_sub=t_next, c=cls):
                    with lock:
                        if f.exception() is not None:
                            failures.append(f"{c}: {f.exception()}")
                        else:
                            lat[c].append(time.monotonic() - t_sub)
                    done.release()

                fut.add_done_callback(on_done)
            for _ in range(admitted):
                if not done.acquire(timeout=30):
                    fail("load phase hung: requests never resolved",
                         resolved=sum(len(v) for v in lat.values())
                         + len(failures), of=admitted)
            wall = time.monotonic() - t0
            return {"lat": lat, "shed": shed, "failures": failures,
                    "wall": wall, "n": n_requests, "admitted": admitted}

        def p99_ms(lats):
            return (float(np.percentile(np.asarray(lats) * 1e3, 99))
                    if lats else 0.0)

        # ---- the replay: one steady-state region after warmup ---------
        try:
            with tracecheck.steady_state("autoscale-smoke replay",
                                         max_host_syncs=None):
                # diurnal day: low -> mid -> low (the mid leg may already
                # grow the fleet — that is the controller working)
                diurnal = [phase(250, 50.0, seed=1),
                           phase(450, 150.0, seed=2),
                           phase(150, 50.0, seed=3)]
                # diurnal night: traffic stops — the fleet must return
                # to min (the first scale-down gate), which also resets
                # the spike-reaction measurement to a 1-replica start
                t_night0 = time.monotonic()
                while time.monotonic() - t_night0 < SCALE_DOWN_GATE_S \
                        and eng.alive_replicas() > 1:
                    time.sleep(0.2)
                night_scale_down_s = time.monotonic() - t_night0
                if eng.alive_replicas() != 1:
                    fail("fleet did not scale down to min during the "
                         "idle night", alive=eng.alive_replicas(),
                         ledger=prof.autoscale_stats())
                # spike at 5x serving-smoke, overload made deterministic
                faultinject.set_plan(faultinject.FaultPlan(
                    [{"site": "serving/dispatch", "kind": "slow",
                      "seconds": 0.02, "times": 10 ** 6}]))
                t_spike = time.monotonic()
                spike = phase(int(5 * PEAK_QPS), PEAK_QPS, seed=4)
                faultinject.clear_plan()
        except tracecheck.SteadyStateViolation as e:
            fail("serving retraced/compiled inside the replay — the "
                 "compile-once contract broke under resize or shed",
                 violation=str(e).splitlines()[0])

        # ---- SLO + shed-order gates -----------------------------------
        for name, ph in [("diurnal-low", diurnal[0]),
                         ("diurnal-mid", diurnal[1]),
                         ("diurnal-low2", diurnal[2]),
                         ("spike", spike)]:
            if ph["failures"]:
                fail(f"{name} phase had failed requests",
                     n=len(ph["failures"]), first=ph["failures"][0])
            if ph["shed"]["gold"] != 0:
                fail(f"{name} phase shed gold requests — shed order is "
                     "not bottom-up", shed=ph["shed"])
        if prof.counter_value("serving/shed/gold") != 0:
            fail("gold sheds counted in the ledger",
                 n=prof.counter_value("serving/shed/gold"))
        if spike["shed"]["batch"] == 0:
            fail("the spike never shed batch-class traffic — no overload "
                 "was exercised", shed=spike["shed"],
                 qps=round(spike["n"] / spike["wall"], 1))
        shed_events = flightrec.events("serving/shed")
        for e in shed_events:
            # lowest-class-first is a SET property of every level: no
            # transition may ever shed silver while batch is admitted
            if "silver" in e["attrs"]["shed"] \
                    and "batch" not in e["attrs"]["shed"]:
                fail("a brownout level shed silver while batch was "
                     "still admitted — not lowest-class-first",
                     transition=e["attrs"])
        levels = [e["attrs"]["level"] for e in shed_events]
        prevs = [e["attrs"]["prev"] for e in shed_events]
        if not levels:
            fail("no serving/shed level transitions recorded")
        if any(abs(lv - pv) != 1 for lv, pv in zip(levels, prevs)):
            fail("brownout level jumped more than one step",
                 transitions=list(zip(prevs, levels)))
        first_shed = next(e for e in shed_events
                          if e["attrs"]["level"] > e["attrs"]["prev"])
        if first_shed["attrs"]["shed"] != ["batch"]:
            fail("first brownout step did not shed exactly the batch "
                 "class", shed=first_shed["attrs"]["shed"])
        spike_qps = spike["n"] / spike["wall"]
        if spike_qps < 0.9 * PEAK_QPS:
            fail(f"open-loop generator fell behind: {spike_qps:.0f} qps "
                 f"vs target {PEAK_QPS:.0f}", wall_s=round(spike["wall"], 2))
        gold_spike_p99 = p99_ms(spike["lat"]["gold"])
        if gold_spike_p99 > GOLD_SLO_P99_MS:
            fail(f"gold p99 {gold_spike_p99:.1f}ms violated the "
                 f"{GOLD_SLO_P99_MS:.0f}ms SLO through the spike",
                 gold_requests=len(spike["lat"]["gold"]))

        # ---- autoscale reaction gates ---------------------------------
        scale_ups = [e for e in flightrec.events("autoscale/scale")
                     if e["attrs"]["to"] > e["attrs"]["frm"]
                     and e["m"] >= t_spike]
        if not scale_ups:
            fail("the autoscaler never scaled up during the spike",
                 alive=eng.alive_replicas(),
                 ledger=prof.autoscale_stats())
        scale_up_latency = scale_ups[0]["m"] - t_spike
        if scale_up_latency > SCALE_UP_GATE_S:
            fail(f"scale-up reacted in {scale_up_latency:.1f}s — over "
                 f"the {SCALE_UP_GATE_S}s gate")
        replicas_peak = max(e["attrs"]["to"] for e in scale_ups)
        t_idle0 = time.monotonic()
        while time.monotonic() - t_idle0 < SCALE_DOWN_GATE_S:
            if eng.alive_replicas() == 1:
                break
            time.sleep(0.2)
        scale_down_s = time.monotonic() - t_idle0
        if eng.alive_replicas() != 1:
            fail(f"scale-down did not return the fleet to min within "
                 f"{SCALE_DOWN_GATE_S}s of going idle",
                 alive=eng.alive_replicas(),
                 ledger=prof.autoscale_stats())
        if prof.counter_value("autoscale/scale_downs") < 1:
            fail("no scale-down was ever counted",
                 ledger=prof.autoscale_stats())

        # ---- recompile gate -------------------------------------------
        traces = prof.counter_value("trace/serving_infer")
        if traces != traces_at_warmup:
            fail("serving traced after warmup across resizes",
                 warmup=traces_at_warmup, now=traces)
        if prof.counter_value("serving/traces_after_warmup"):
            fail("serving/traces_after_warmup is non-zero",
                 n=prof.counter_value("serving/traces_after_warmup"))

        # ---- canary -> promote drill ----------------------------------
        gold_x = inputs[:2]

        def gold_load_until(handle):
            failures = []
            while not handle.done:
                try:
                    eng.output(gold_x, slo_class="gold")
                except Exception as e:      # census, not control flow
                    failures.append(str(e))
            return failures

        h1 = eng.publish_checkpoint(ck_promote, canary_window_s=0.8,
                                    confirm_window_s=0.8,
                                    check_interval_s=0.1)
        gold_failures = gold_load_until(h1)
        if h1.result(timeout=15) != "promoted" or gold_failures:
            fail("canary->promote drill failed",
                 outcome=h1.phase, gold_failures=gold_failures[:3])
        want = jax.tree.leaves(read_checkpoint_params(
            ck_promote, model._params, model._states))
        got = jax.tree.leaves(eng._dev_params[0])
        if not all(np.array_equal(np.asarray(g), np.asarray(w))
                   for g, w in zip(got, want)):
            fail("promoted fleet params are not bitwise the checkpoint's")
        chain1 = [e["name"] for e in flightrec.events(corr=h1.corr)]
        commit_files = {e["attrs"].get("file")
                        for e in flightrec.events("checkpoint/commit")}
        if os.path.basename(ck_promote) not in commit_files:
            fail("train-commit leg missing from the recorder",
                 commits=sorted(commit_files))
        if not ("serving/canary" in chain1 and "serving/promote" in chain1
                and chain1.index("serving/canary")
                < chain1.index("serving/promote")):
            fail("promote correlation chain incomplete", chain=chain1,
                 corr=h1.corr)

        # ---- forced-violation -> rollback drill -----------------------
        prior = [np.array(a) for a in jax.tree.leaves(eng._dev_params[0])]
        ordinal = next_publication_ordinal()
        faultinject.set_plan(faultinject.FaultPlan(
            [{"site": "serving/promote", "kind": "transient",
              "index": ordinal}]))
        h2 = eng.publish_checkpoint(ck_rollback, canary_window_s=0.5,
                                    confirm_window_s=5.0,
                                    check_interval_s=0.1)
        gold_failures = gold_load_until(h2)
        faultinject.clear_plan()
        if h2.result(timeout=15) != "rolled_back" or gold_failures:
            fail("forced-violation drill did not roll back cleanly",
                 outcome=h2.phase, gold_failures=gold_failures[:3])
        after = [np.array(a) for a in jax.tree.leaves(eng._dev_params[0])]
        if not all(np.array_equal(a, b) for a, b in zip(after, prior)):
            fail("rollback did not restore the prior params bitwise")
        chain2 = [e["name"] for e in flightrec.events(corr=h2.corr)]
        if not ("serving/canary" in chain2 and "serving/promote" in chain2
                and "serving/rollback" in chain2):
            fail("rollback correlation chain incomplete", chain=chain2,
                 corr=h2.corr)
        if prof.counter_value("serving/shed/gold") != 0:
            fail("gold sheds during the canary drills",
                 n=prof.counter_value("serving/shed/gold"))

        serving_ledger = prof.serving_stats()
        autoscale_ledger = prof.autoscale_stats()
        scaler.stop()
        eng.shutdown()
        return {
            "metric": "autoscale_smoke",
            "value": spike_qps,
            "unit": "req/sec",
            "platform": jax.devices()[0].platform,
            "peak_qps_target": PEAK_QPS,
            "gold_slo_p99_ms": GOLD_SLO_P99_MS,
            "gold_spike_p99_ms": round(gold_spike_p99, 2),
            "gold_diurnal_p99_ms": round(
                p99_ms([v for ph in diurnal
                        for v in ph["lat"]["gold"]]), 2),
            "spike_shed": spike["shed"],
            "diurnal_shed": {c: sum(ph["shed"][c] for ph in diurnal)
                             for c in ("gold", "silver", "batch")},
            "brownout_transitions": list(zip(prevs, levels)),
            "scale_up_latency_s": round(scale_up_latency, 2),
            "scale_up_gate_s": SCALE_UP_GATE_S,
            "night_scale_down_s": round(night_scale_down_s, 2),
            "scale_down_s": round(scale_down_s, 2),
            "replicas_peak": replicas_peak,
            "canary_promote": {"corr": h1.corr, "outcome": "promoted",
                               "file": os.path.basename(ck_promote)},
            "canary_rollback": {"corr": h2.corr, "outcome": "rolled_back",
                                "file": os.path.basename(ck_rollback)},
            "warmup_s": round(warmup_s, 3),
            "traces": traces,
            "serving_ledger": {k: (round(v, 5) if isinstance(v, float)
                                   else v)
                               for k, v in serving_ledger.items()
                               if isinstance(v, (int, float))},
            "autoscale_ledger": autoscale_ledger,
            "data": "diurnal+spike open-loop Poisson replay of class-"
                    "mixed 1-8-row requests at 5x serving-smoke rate; "
                    "hard gates on gold SLO, bottom-up sheds, scale "
                    "up/down latency, flat recompiles, canaried "
                    "promote/rollback correlation chains",
        }
    finally:
        faultinject.clear_plan()
        flightrec.configure(capacity=4096)
        shutil.rmtree(ckdir, ignore_errors=True)


def bench_soak_smoke(steps: int, batch: int = 32) -> dict:
    """Production-day chaos soak (ISSUE 17): the watchtower SLO engine
    proven end to end. Supervised training publishes checkpoints into a
    live autoscaled serving fleet under replayed traffic while a
    scheduled chaos plan fires the FAULT_SITES catalog — train-step
    crash, device loss, NaN poison, wedged dispatch, SIGTERM preemption,
    dead serving replica, forced promote-violation, pipeline stage kill —
    with the watchtower evaluating compressed-window SLOs (5m/1h/6h
    scaled to 1s/3s/6s) the whole time. Self-validating hard-fails:

    - **clean window is silent**: a no-fault load + train + publish leg
      must page zero times and open zero incidents (false-positive gate);
    - **every fault becomes exactly ONE incident** with a COMPLETE
      cause -> detection -> mitigation -> recovery chain anchored on the
      right fault site (precision = recall = 1.0 over 8 injected faults),
      and supervisor incidents carry the blackbox tail;
    - **zero failed or shed gold requests** through every phase,
      including the dead-replica and rollback drills;
    - **a wobbly evaluator loses a sample, not the alert**: the
      ``watchtower/evaluate`` transient drill must skip exactly one tick
      with no state transition and no incident;
    - **watchtower overhead <= 5%** on a warm training loop (interleaved
      on/off A/B, min-over-ratios via ``_ab_overhead_gate``) with ZERO
      retrace delta inside the timed window;
    - the incident registry is served over HTTP: ``/api/incidents``,
      ``/api/health``'s ``last_incident`` pointer, the ``?corr=``
      filtered ``/api/trace`` export, and the ``dl4j_alert_state`` /
      ``dl4j_serving_latency_ms`` Prometheus families all answer."""
    import shutil
    import tempfile
    import threading
    import urllib.request

    import jax

    from deeplearning4j_tpu.common import (faultinject, flightrec,
                                           tracecheck, watchtower)
    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.data import NDArrayDataSetIterator
    from deeplearning4j_tpu.learning import Adam, Sgd
    from deeplearning4j_tpu.ndarray.rng import set_default_seed
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import layers as L
    from deeplearning4j_tpu.optimize.listeners import CheckpointListener
    from deeplearning4j_tpu.optimize.telemetry import NanSentinelListener
    from deeplearning4j_tpu.parallel import (AutoscalePolicy, Autoscaler,
                                             Overloaded, PipelineTrainer,
                                             ServingEngine, SLOClass,
                                             TrainingSupervisor)
    from deeplearning4j_tpu.parallel.serving import next_publication_ordinal
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.util.checkpoint import committed_checkpoints

    TICK_S = 0.1                 # evaluator cadence (compressed time)
    # 5m/1h/6h windows compressed to 1s/3s/6s over a 30s budget period:
    # one bad tick at 0.1s cadence burns fast~100x/mid~33x a 0.1% budget,
    # comfortably over the stock 14.4x page threshold, and ages out of
    # every window seconds later — raise-fast/clear-fast, same math
    WIN = dict(fast_s=1.0, mid_s=3.0, slow_s=6.0, period_s=30.0,
               clear_ticks=2)
    REQ_ROWS_MAX = 8
    CLASS_MIX = ["batch"] * 5 + ["silver"] * 3 + ["gold"] * 2

    def fail(msg, **extra):
        faultinject.clear_plan()
        print(json.dumps({"error": msg, **extra}, default=str))
        sys.exit(1)

    def build_mlp(seed):
        conf = (NeuralNetConfiguration.builder().seed(seed)
                .updater(Adam(1e-3)).activation("tanh").list()
                .layer(L.DenseLayer(n_out=64))
                .layer(L.DenseLayer(n_out=64))
                .layer(L.OutputLayer(n_out=10))
                .set_input_type(InputType.feed_forward(32)).build())
        return MultiLayerNetwork(conf).init()

    prof = OpProfiler.get()
    prof.reset()
    faultinject.clear_plan()
    # the whole soak timeline in ONE ring: incident assembly walks it
    flightrec.configure(capacity=65536)
    flightrec.reset()
    t_soak0 = time.monotonic()

    incident_dir = tempfile.mkdtemp(prefix="dl4j_soak_incidents_")
    ckdir = tempfile.mkdtemp(prefix="dl4j_soak_ckpt_")
    tmpdirs = [incident_dir, ckdir]
    eng = scaler = ui = None
    try:
        # ---- train-commit leg: checkpoints the fleet will consume ------
        trainee = build_mlp(seed=11)
        rng = np.random.RandomState(0)
        xs = rng.randn(8 * batch, 32).astype(np.float32)
        ys = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 8 * batch)]
        cl = CheckpointListener(ckdir, save_every_n_iterations=4,
                                keep_last=4)
        trainee.set_listeners(cl)
        trainee.fit(NDArrayDataSetIterator(xs, ys, batch_size=batch),
                    epochs=2)
        cl.close()
        ckpts = committed_checkpoints(ckdir)
        if len(ckpts) < 2:
            fail("training produced fewer than 2 committed checkpoints",
                 n=len(ckpts))
        ck_clean, ck_drill = ckpts[-2], ckpts[-1]

        # ---- serving fleet + autoscaler --------------------------------
        model = build_mlp(seed=7)
        eng = (ServingEngine.Builder(model)
               .buckets([1, 2, 4, 8, 16, batch]).input_shape((32,))
               .workers(2).max_wait_ms(2.0).queue_limit(512)
               .request_timeout_ms(15000)
               .slo_classes([SLOClass("gold", 2, 500.0, queue_budget=256),
                             SLOClass("silver", 1, 800.0, queue_budget=64),
                             SLOClass("batch", 0, 2000.0, queue_budget=64)])
               .brownout(interval_s=0.1, depth_trigger=24, clear_ticks=5)
               .queue_hwm_window(1.5)
               .resurrect_dead_replicas(True, backoff_ms=100)
               .build())
        scaler = Autoscaler(eng, AutoscalePolicy(
            min_workers=2, max_workers=4, interval_s=0.1,
            up_queue_depth=8, up_p99_frac=0.8, down_queue_depth=0,
            down_idle_s=0.8, down_fill_frac=0.25,
            cooldown_up_s=0.4, cooldown_down_s=0.8)).start()

        # ---- the watchtower: stock catalog + drill objectives ----------
        slos = watchtower.default_slos(engine=eng, **WIN)
        slos += [
            watchtower.SLO(
                "replica-health",
                watchtower.counter_increment_sampler(
                    "inference/replica_retired"),
                budget=0.001,
                description="serving replicas stay alive", **WIN),
            watchtower.SLO(
                "rollback-budget",
                watchtower.counter_increment_sampler("serving/rollbacks"),
                budget=0.001,
                description="published checkpoints stick", **WIN),
            watchtower.SLO(
                "remap-budget",
                watchtower.counter_increment_sampler("pipeline/remaps"),
                budget=0.001,
                description="pipeline stages stay up", **WIN),
        ]
        tower = watchtower.install(watchtower.Watchtower(
            slos, interval_s=TICK_S, incident_dir=incident_dir,
            ring_context=600, lookback_s=60.0, finalize_after_s=30.0))
        tower.start()
        ui = UIServer()
        port = ui.enable(0)

        # ---- shared helpers --------------------------------------------
        inputs = np.random.RandomState(1).randn(
            REQ_ROWS_MAX, 32).astype(np.float32)

        def phase(n_requests, qps, seed):
            r = np.random.RandomState(seed)
            gaps = r.exponential(1.0 / qps, n_requests)
            sizes = r.randint(1, REQ_ROWS_MAX + 1, n_requests)
            classes = [CLASS_MIX[i]
                       for i in r.randint(0, len(CLASS_MIX), n_requests)]
            shed = {c: 0 for c in ("gold", "silver", "batch")}
            failures = []
            lock = threading.Lock()
            done = threading.Semaphore(0)
            admitted = 0
            t0 = time.monotonic()
            t_next = t0
            for i in range(n_requests):
                t_next += gaps[i]
                delay = t_next - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                cls = classes[i]
                try:
                    fut = eng.output_async(inputs[:sizes[i]],
                                           slo_class=cls)
                except Overloaded:
                    shed[cls] += 1
                    continue
                admitted += 1

                def on_done(f, c=cls):
                    with lock:
                        if f.exception() is not None:
                            failures.append(f"{c}: {f.exception()}")
                    done.release()

                fut.add_done_callback(on_done)
            for _ in range(admitted):
                if not done.acquire(timeout=30):
                    fail("soak load phase hung: requests never resolved")
            return {"shed": shed, "failures": failures, "n": n_requests,
                    "admitted": admitted,
                    "wall": time.monotonic() - t0}

        def gate_phase(name, ph):
            if ph["failures"]:
                fail(f"{name}: requests failed", n=len(ph["failures"]),
                     first=ph["failures"][0])
            if ph["shed"]["gold"]:
                fail(f"{name}: gold requests shed", shed=ph["shed"])

        gold_x = inputs[:2]

        def gold_load_until(handle):
            failures = []
            while not handle.done:
                try:
                    eng.output(gold_x, slo_class="gold")
                except Exception as e:      # census, not control flow
                    failures.append(str(e))
            return failures

        def wait_for(cond, timeout_s, what):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                if cond():
                    return
                time.sleep(0.05)
            fail(f"soak: timed out waiting for {what}",
                 alert_states=tower.alert_states(),
                 incidents=watchtower.incidents())

        def incident_ids():
            return {i["id"] for i in watchtower.incidents()}

        chronicle = {}

        def expect_incident(drill, before_ids, *, kind, cause_site=None,
                            detection=None, mitigation=None, recovery=None,
                            timeout_s=25.0):
            """Exactly ONE new incident, finalized with a complete chain
            anchored where the drill says it must be."""
            deadline = time.monotonic() + timeout_s
            new = []
            while time.monotonic() < deadline:
                new = [i for i in watchtower.incidents()
                       if i["id"] not in before_ids]
                if len(new) > 1:
                    fail(f"{drill}: one injected fault opened "
                         f"{len(new)} incidents", incidents=new)
                if new and new[0]["finalized"]:
                    break
                time.sleep(0.05)
            if not new or not new[0]["finalized"]:
                fail(f"{drill}: no finalized incident within "
                     f"{timeout_s}s", incidents=watchtower.incidents(),
                     alert_states=tower.alert_states())
            meta = new[0]
            # the index flips finalized a beat before the finalize
            # rewrite lands on disk — read the file until it agrees
            rep = None
            file_deadline = time.monotonic() + 5.0
            while time.monotonic() < file_deadline:
                with open(meta["path"], "r", encoding="utf-8") as f:
                    rep = json.load(f)
                if rep.get("finalized"):
                    break
                time.sleep(0.05)
            ch = rep["chain"]
            names = {k: (v or {}).get("name")
                     for k, v in ch.items() if k != "complete"}
            if not rep["complete"] or not rep["resolved"]:
                fail(f"{drill}: incident chain incomplete", chain=names,
                     id=meta["id"],
                     rep={k: v for k, v in rep.items()
                          if k not in ("events", "ledgers", "census",
                                       "watermarks", "blackbox")},
                     alert_states=tower.alert_states())
            if rep["kind"] != kind:
                fail(f"{drill}: incident kind {rep['kind']!r}, "
                     f"wanted {kind!r}", id=meta["id"])
            if cause_site is not None and \
                    ch["cause"]["attrs"].get("site") != cause_site:
                fail(f"{drill}: cause anchored on the wrong fault site",
                     cause=ch["cause"])
            for role, allowed in (("detection", detection),
                                  ("mitigation", mitigation),
                                  ("recovery", recovery)):
                if allowed is not None and ch[role]["name"] not in allowed:
                    fail(f"{drill}: {role} anchored on "
                         f"{ch[role]['name']!r}", chain=names)
            seqs = (ch["cause"]["seq"], ch["mitigation"]["seq"],
                    ch["recovery"]["seq"])
            if not (seqs[0] <= seqs[1] <= seqs[2]) or \
                    ch["cause"]["seq"] > ch["detection"]["seq"]:
                fail(f"{drill}: chain events out of causal order",
                     chain=names, seqs=seqs)
            if kind == "supervisor" and not rep.get("blackbox"):
                fail(f"{drill}: supervisor incident carries no blackbox "
                     "tail", id=meta["id"])
            chronicle[drill] = {
                "id": meta["id"], "kind": rep["kind"],
                "reason": rep["reason"], "corr": rep.get("corr"),
                "chain": names,
                "mttr_s": round(rep["updated_t"] - rep["opened_t"], 2)}
            return meta["id"], rep

        # ---- supervised-drill scaffolding ------------------------------
        n_tr = 8 * batch
        tx = rng.randn(n_tr, 32).astype(np.float32)
        ty = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n_tr)]

        def make_train_it():
            return NDArrayDataSetIterator(tx, ty, batch_size=batch)

        def supervised_run(drill, plan, *, listeners=(), policies=None,
                           hang_deadline_s=None, poll_s=0.05,
                           resume="never", sup_dir=None,
                           expect_status="completed", expect_restarts=0):
            d = sup_dir or tempfile.mkdtemp(prefix=f"dl4j_soak_sup_")
            if d not in tmpdirs:
                tmpdirs.append(d)
            m = build_mlp(seed=23)
            if listeners:
                m.set_listeners(*listeners)
            sup = TrainingSupervisor(m, d, save_every_n_iterations=3,
                                     keep_last=2, backoff_base_s=0.01,
                                     hang_deadline_s=hang_deadline_s,
                                     poll_s=poll_s, policies=policies)
            if plan:
                faultinject.set_plan(faultinject.FaultPlan(plan))
            try:
                res = sup.fit(make_train_it, epochs=2, batch_size=batch,
                              resume=resume)
            finally:
                faultinject.clear_plan()
            if res.status != expect_status or \
                    (expect_restarts is not None
                     and res.restarts != expect_restarts):
                fail(f"{drill}: supervised run ended "
                     f"{res.status}/{res.restarts} restarts, wanted "
                     f"{expect_status}/{expect_restarts}",
                     history=res.history)
            return res, d

        # ================================================================
        # Phase 1 — the CLEAN window: load + train + publish, silence
        # ================================================================
        pages0 = prof.counter_value("watchtower/pages")
        clean_phases = [phase(150, 40.0, seed=1),
                        phase(250, 120.0, seed=2)]
        for i, ph in enumerate(clean_phases):
            gate_phase(f"clean-window load {i}", ph)
        supervised_run("clean-window train", None)
        h = eng.publish_checkpoint(ck_clean, canary_window_s=0.5,
                                   confirm_window_s=0.5,
                                   check_interval_s=0.1)
        gold_failures = gold_load_until(h)
        if h.result(timeout=15) != "promoted" or gold_failures:
            fail("clean-window publish did not promote",
                 outcome=h.phase, gold_failures=gold_failures[:3])
        time.sleep(4 * TICK_S)          # let the evaluator see all of it
        if prof.counter_value("watchtower/pages") != pages0:
            fail("false-positive page in the clean window",
                 pages=prof.counter_value("watchtower/pages") - pages0,
                 alert_states=tower.alert_states())
        if incident_ids():
            fail("incident opened during the clean window",
                 incidents=watchtower.incidents())

        # ================================================================
        # Phase 2 — watchtower A/B overhead on a warm training loop
        # ================================================================
        n_ab = 32 * batch
        ax = rng.randn(n_ab, 32).astype(np.float32)
        ay = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n_ab)]
        ab_model = build_mlp(seed=31)

        def ab_epoch():
            ab_model.fit(NDArrayDataSetIterator(ax, ay, batch_size=batch),
                         epochs=3, batch_size=batch)
            float(np.asarray(ab_model._score_dev))     # value fence

        ab_epoch()                                     # warm/compile

        def timed_epoch(name):
            tower.configure(enabled=(name == "on"))
            t0 = time.perf_counter()
            ab_epoch()
            return time.perf_counter() - t0

        timed_epoch("on")
        timed_epoch("off")                             # settle rounds
        traces0 = prof.trace_counts()
        try:
            with tracecheck.steady_state("soak watchtower A/B",
                                         max_host_syncs=None):
                overhead, _times, overhead_runs = _ab_overhead_gate(
                    "watchtower", 0.05,
                    lambda: _ab_rounds(timed_epoch, rounds=5), fail)
        except tracecheck.SteadyStateViolation as e:
            fail("watchtower A/B window retraced/synced",
                 violation=str(e).splitlines()[0])
        if prof.trace_counts() != traces0:
            fail("watchtower A/B window changed the compile footprint",
                 before=traces0, after=prof.trace_counts())
        tower.configure(enabled=True)

        # ================================================================
        # Phase 3 — the chaos plan, one incident per fault
        # ================================================================
        # (a) train-step crash -> restart
        before = incident_ids()
        supervised_run(
            "crash", [{"site": "train/step", "index": 10,
                       "kind": "crash"}], expect_restarts=1)
        expect_incident(
            "crash", before, kind="supervisor", cause_site="train/step",
            detection=("supervisor/attempt_failed",),
            mitigation=("supervisor/restart",),
            recovery=("supervisor/attempt_start", "checkpoint/restore"))

        # (b) device loss -> restart (non-elastic target: the documented
        # shrink_and_continue fallback)
        before = incident_ids()
        supervised_run(
            "device-loss", [{"site": "device/loss", "index": 10,
                             "kind": "device_loss", "replica": 0}],
            expect_restarts=1)
        expect_incident(
            "device-loss", before, kind="supervisor",
            cause_site="device/loss",
            detection=("supervisor/attempt_failed",),
            mitigation=("supervisor/restart",),
            recovery=("supervisor/attempt_start", "checkpoint/restore"))

        # (c) NaN poison -> sentinel raises -> policy restart
        before = incident_ids()
        supervised_run(
            "nan-poison", [{"site": "pipeline/bind", "index": 10,
                            "kind": "nan"}],
            listeners=(NanSentinelListener("raise", check_every_n=1),),
            policies={"poisoned_numerics": "restart"}, expect_restarts=1)
        expect_incident(
            "nan-poison", before, kind="supervisor",
            cause_site="pipeline/bind",
            detection=("supervisor/attempt_failed",),
            mitigation=("supervisor/restart",),
            recovery=("supervisor/attempt_start", "checkpoint/restore"))

        # (d) wedged dispatch -> watchdog abandonment -> restart
        before = incident_ids()
        supervised_run(
            "wedge", [{"site": "train/wedge", "index": 9,
                       "kind": "wedge"}],
            hang_deadline_s=0.5, poll_s=0.02, expect_restarts=1)
        expect_incident(
            "wedge", before, kind="supervisor", cause_site="train/wedge",
            detection=("supervisor/watchdog_fire",
                       "supervisor/attempt_failed"),
            mitigation=("supervisor/restart",),
            recovery=("supervisor/attempt_start", "checkpoint/restore"))

        # (e) SIGTERM preemption -> flush checkpoint -> exit -> resume
        before = incident_ids()
        _, pre_dir = supervised_run(
            "preempt", [{"site": "train/step", "index": 10,
                         "kind": "preempt"}],
            expect_status="preempted", expect_restarts=0)
        supervised_run("preempt-resume", None, resume="auto",
                       sup_dir=pre_dir)
        expect_incident(
            "preempt", before, kind="supervisor", cause_site="train/step",
            detection=("supervisor/attempt_failed",),
            mitigation=("supervisor/preempted",),
            recovery=("supervisor/attempt_start", "checkpoint/restore"))

        # (f) dead serving replica -> retire -> resurrection
        before = incident_ids()
        resurrected0 = len(flightrec.events("inference/resurrected"))
        faultinject.set_plan(faultinject.FaultPlan(
            [{"site": "serving/dispatch", "kind": "dead_replica",
              "times": 1}]))
        dead_ph = phase(120, 80.0, seed=6)
        faultinject.clear_plan()
        gate_phase("dead-replica load", dead_ph)
        wait_for(lambda: len(flightrec.events("inference/resurrected"))
                 > resurrected0, 10.0, "replica resurrection")
        expect_incident(
            "dead-replica", before, kind="alert",
            cause_site="serving/dispatch",
            detection=("watchtower/alert",),
            mitigation=("serving/retire",),
            recovery=("inference/resurrected", "watchtower/alert"))

        # (g) forced promote-violation -> rollback -> clean republish
        before = incident_ids()
        ordinal = next_publication_ordinal()
        faultinject.set_plan(faultinject.FaultPlan(
            [{"site": "serving/promote", "kind": "transient",
              "index": ordinal}]))
        h2 = eng.publish_checkpoint(ck_drill, canary_window_s=0.5,
                                    confirm_window_s=5.0,
                                    check_interval_s=0.1)
        gold_failures = gold_load_until(h2)
        faultinject.clear_plan()
        if h2.result(timeout=15) != "rolled_back" or gold_failures:
            fail("forced-violation drill did not roll back cleanly",
                 outcome=h2.phase, gold_failures=gold_failures[:3])
        h3 = eng.publish_checkpoint(ck_clean, canary_window_s=0.5,
                                    confirm_window_s=0.5,
                                    check_interval_s=0.1)
        gold_failures = gold_load_until(h3)
        if h3.result(timeout=15) != "promoted" or gold_failures:
            fail("post-rollback republish did not promote",
                 outcome=h3.phase, gold_failures=gold_failures[:3])
        expect_incident(
            "promote-violation", before, kind="alert",
            cause_site="serving/promote",
            detection=("watchtower/alert",),
            mitigation=("serving/rollback",),
            recovery=("serving/promote", "watchtower/alert"))

        # (h) the evaluator itself wobbles: one skipped tick, no alert
        wait_for(lambda: all(v == 0
                             for v in tower.alert_states().values()),
                 20.0, "alert states to settle before the evaluator drill")
        states0 = tower.alert_states()
        stats0 = tower.stats()
        before = incident_ids()
        faultinject.set_plan(faultinject.FaultPlan(
            [{"site": "watchtower/evaluate", "kind": "transient",
              "index": int(stats0["evaluations"]) + 2}]))
        wait_for(lambda: tower.stats()["skipped_evals"]
                 >= stats0["skipped_evals"] + 1, 5.0,
                 "the watchtower/evaluate transient to fire")
        faultinject.clear_plan()
        if tower.alert_states() != states0 or incident_ids() != before:
            fail("a skipped evaluation tick changed alert state or "
                 "opened an incident", states=tower.alert_states())

        # (i) pipeline stage kill -> remap -> resume (the scaler is done
        # at this point; stopping it keeps the mitigation anchor exact)
        scaler.stop()
        before = incident_ids()
        batch_pp, M, feat = 16, 4, 16
        n_pp = 6 * batch_pp
        set_default_seed(55)
        pb = (NeuralNetConfiguration.builder().seed(55)
              .updater(Sgd(learning_rate=0.02)).list())
        for _ in range(6):
            pb.layer(L.DenseLayer(n_out=feat, activation="tanh"))
        pmodel = MultiLayerNetwork(
            pb.set_input_type(InputType.feed_forward(feat)).build()).init()
        tr = PipelineTrainer(pmodel, stages=3, n_micro=M,
                             schedule="1f1b", data=1)
        prng = np.random.RandomState(9)
        px = prng.randn(n_pp, feat).astype(np.float32)
        py = prng.randn(n_pp, feat).astype(np.float32)

        def make_pp_it():
            return NDArrayDataSetIterator(px, py, batch_size=batch_pp)

        tr.fit(make_pp_it(), epochs=1, batch_size=batch_pp)   # warm
        faultinject.set_plan(faultinject.FaultPlan(
            [{"site": "pipeline/stage", "kind": "device_loss",
              "index": 9, "stage": 1}]))
        try:
            tr.fit(make_pp_it(), epochs=2, batch_size=batch_pp)
            fail("pipeline/stage fault plan did not fire")
        except faultinject.DeviceLostError:
            pass
        faultinject.clear_plan()
        cursor = (int(pmodel._epoch - pmodel._fit_epoch0),
                  int(pmodel._steps_in_epoch))
        removed = tr.remap(2, lost_stages=[1])
        if len(removed) != 1:
            fail("stage-kill remap did not retire exactly the lost "
                 "stage column", removed=len(removed))
        tr.fit(make_pp_it(), epochs=2, batch_size=batch_pp,
               resume_cursor=cursor)
        if not np.isfinite(float(np.asarray(pmodel._score_dev))):
            fail("post-remap loss went non-finite")
        expect_incident(
            "stage-kill", before, kind="alert",
            cause_site="pipeline/stage",
            detection=("watchtower/alert",),
            mitigation=("pipeline/remap",),
            recovery=("watchtower/alert",))

        # ================================================================
        # Phase 4 — registry totals + the HTTP surface
        # ================================================================
        DRILLS = ("crash", "device-loss", "nan-poison", "wedge",
                  "preempt", "dead-replica", "promote-violation",
                  "stage-kill")
        incs = watchtower.incidents()
        if len(incs) != len(DRILLS):
            fail(f"{len(DRILLS)} faults injected but {len(incs)} "
                 "incidents assembled (precision/recall broke)",
                 incidents=incs)
        if any(not i["finalized"] or not i["resolved"] for i in incs):
            fail("unresolved incidents at end of soak", incidents=incs)

        def http_json(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=10) as r:
                return json.loads(r.read().decode("utf-8"))

        http_incs = http_json("/api/incidents")
        if len(http_incs) != len(DRILLS):
            fail("/api/incidents does not list every incident",
                 n=len(http_incs))
        served = http_json(f"/api/incidents?id={http_incs[-1]['id']}")
        if not served.get("complete"):
            fail("/api/incidents?id= served an incomplete report",
                 id=http_incs[-1]["id"])
        health = http_json("/api/health")
        li = health.get("last_incident")
        if not li or not (li.get("tail") or {}).get("complete"):
            fail("/api/health last_incident pointer missing or "
                 "incomplete", last_incident=li)
        crash_corr = chronicle["crash"]["corr"]
        trace = http_json(f"/api/trace?corr={crash_corr}")
        tevs = trace.get("traceEvents", [])
        if not tevs or any(e.get("args", {}).get("corr") != crash_corr
                           for e in tevs if e.get("ph") != "M"):
            fail("/api/trace?corr= filter broke", corr=crash_corr,
                 n=len(tevs))
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/metrics", timeout=10) as r:
            metrics_text = r.read().decode("utf-8")
        for needle in ("dl4j_alert_state{",
                       'dl4j_serving_latency_ms{class="gold"'):
            if needle not in metrics_text:
                fail(f"/api/metrics is missing {needle!r}")

        soak_wall_s = time.monotonic() - t_soak0
        tower_stats = tower.stats()
        return {
            "metric": "soak_smoke",
            "value": 60.0 * len(DRILLS) / soak_wall_s,
            "unit": "faults/min",
            "platform": jax.devices()[0].platform,
            "faults_injected": len(DRILLS),
            "incidents_assembled": len(incs),
            "chains_complete": len(DRILLS),
            "mttr_s_mean": round(sum(c["mttr_s"]
                                     for c in chronicle.values())
                                 / len(chronicle), 2),
            "incidents": chronicle,
            "clean_window": {
                "requests": sum(ph["n"] for ph in clean_phases),
                "pages": 0, "incidents": 0},
            "watchtower_overhead_frac": round(overhead, 4),
            "overhead_runs": overhead_runs,
            "pages_total": prof.counter_value("watchtower/pages"),
            "alerts_total": prof.counter_value("watchtower/alerts"),
            "evaluations": int(tower_stats["evaluations"]),
            "skipped_evals": int(tower_stats["skipped_evals"]),
            "soak_wall_s": round(soak_wall_s, 1),
            "data": "clean diurnal window + 8-fault chaos plan over "
                    "supervised training publishing into an autoscaled "
                    "serving fleet; gates: silent clean window, exactly "
                    "one complete-chain incident per fault, zero "
                    "failed/shed gold, <=5% watchtower A/B overhead, "
                    "zero retrace delta, HTTP incident/trace/metrics "
                    "surface",
        }
    finally:
        faultinject.clear_plan()
        watchtower.uninstall()
        if scaler is not None:
            try:
                scaler.stop()
            except Exception:
                pass
        if eng is not None:
            try:
                eng.shutdown()
            except Exception:
                pass
        if ui is not None:
            try:
                ui.stop()
            except Exception:
                pass
        flightrec.configure(capacity=4096)
        for d in tmpdirs:
            shutil.rmtree(d, ignore_errors=True)


def bench_integrity_smoke(steps: int, batch: int = 64,
                          workers: int = 4) -> dict:
    """Silent-corruption defense smoke (ISSUE 19): the in-graph
    replica-consistency fingerprints, the divergent-replica quarantine
    and the checkpoint scrubber proven end to end. Self-validating
    hard-fails:

    - **fingerprint overhead <= 5%**: the uint32 bitcast fold over the
      ZeRO-1 flat buckets plus the cross-replica majority vote, riding
      the jitted step at the TIGHTEST cadence (``check_every=1``),
      against the same wrapper with no IntegrityListener — interleaved
      A/B, min over per-round on/off ratios (the shared
      ``_ab_overhead_gate``), with ZERO retrace delta: identical warm
      compile footprints and zero traces inside the timed
      ``tracecheck.steady_state`` window;
    - **clean window has zero false positives**: every A/B epoch checks
      at cadence 1 and must never count a divergence — bitwise-identical
      replicas are an exact invariant, not a tolerance — and the stock
      ``replica-consistency`` SLO sampler must stay silent through it;
    - **bitflip drill**: one ``integrity/fingerprint`` fault (``bitflip``
      kind) on replica 1 of 4 under a TrainingSupervisor must quarantine
      exactly that replica through the elastic shrink (no restart
      consumed, training completes on 3 workers) and assemble exactly
      ONE finalized watchtower incident whose chain reads cause
      ``fault/fired`` (site ``integrity/fingerprint``, the replica
      named) -> detection ``integrity/divergence`` -> mitigation
      ``integrity/quarantine`` -> recovery; the SLO sampler trips;
    - **scrub drill**: a ``checkpoint/scrub`` transient skips one entry
      for one pass (``integrity/scrub_retries``), then the advisory
      bitflip rots a retained zip ON DISK and the scrubber must
      quarantine that generation in the manifest WITHOUT deleting the
      evidence, every restore path skipping it.

    Emits the ``integrity`` ledger alongside the timing."""
    import shutil
    import statistics as _stats
    import tempfile

    # a multi-replica mesh is the whole point: on single-device hosts
    # (CPU build machines) request virtual CPU devices BEFORE jax loads
    if "jax" not in sys.modules:
        _flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in _flags:
            os.environ["XLA_FLAGS"] = (
                _flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    from deeplearning4j_tpu.common import (faultinject, flightrec,
                                           integrity, tracecheck,
                                           watchtower)
    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.data import NDArrayDataSetIterator
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.ndarray.rng import set_default_seed
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import layers as L
    from deeplearning4j_tpu.optimize.listeners import CheckpointListener
    from deeplearning4j_tpu.parallel import (ParallelWrapper,
                                             ReduceScatterAccumulator,
                                             TrainingSupervisor)
    from deeplearning4j_tpu.util.checkpoint import (committed_checkpoints,
                                                    last_checkpoint)

    def fail(msg, **extra):
        faultinject.clear_plan()
        print(json.dumps({"error": msg, **extra}, default=str))
        sys.exit(1)

    workers = min(workers, len(jax.devices()))
    if workers < 4:
        fail("integrity-smoke needs >= 4 devices for an attributable "
             "majority vote (virtual CPU device request came too late?)",
             devices=len(jax.devices()))

    rng_np = np.random.RandomState(0)
    n = steps * batch
    x = rng_np.randn(n, 1, 28, 28).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng_np.randint(0, 10, n)]

    def make_it():
        return NDArrayDataSetIterator(x, y, batch_size=batch)

    prof = OpProfiler.get()
    prof.reset()
    faultinject.clear_plan()
    flightrec.reset()

    # ---- phase 1: A/B overhead at the tightest cadence ----------------
    wrappers = {}
    integ_lst = integrity.IntegrityListener(check_every=1)
    for name in ("off", "on"):
        set_default_seed(99)
        model = _lenet_model()
        pw = (ParallelWrapper.Builder(model).workers(workers)
              .gradients_accumulator(ReduceScatterAccumulator()).build())
        if name == "on":
            pw.set_listeners(integ_lst)
        wrappers[name] = (model, pw)

    def run(name, epochs=1):
        model, pw = wrappers[name]
        pw.fit(make_it(), epochs=epochs, batch_size=batch)
        float(model._score_dev)          # value fence

    # compile footprint: the fold and the vote ride the SAME jitted
    # step — ON and OFF each compile once, identically counted
    warm = {}
    for name in ("off", "on"):
        prof.reset()
        run(name)
        warm[name] = prof.trace_counts()
    if warm["on"] != warm["off"]:
        fail("fingerprinting changed the compile footprint (retrace "
             "delta)", off_traces=warm["off"], on_traces=warm["on"])

    def timed_epoch(name):
        t0 = time.perf_counter()
        run(name)
        return time.perf_counter() - t0

    timed_epoch("on")
    timed_epoch("off")                   # settle rounds, untimed
    prof.reset()
    try:
        # the ON config drains one 4-byte verdict per dispatch by
        # design — host syncs counted, traces policed
        with tracecheck.steady_state("integrity-smoke timed rounds",
                                     max_host_syncs=None):
            overhead, times, overhead_runs = _ab_overhead_gate(
                "integrity fingerprints", 0.05,
                lambda: _ab_rounds(timed_epoch, rounds=6), fail)
    except tracecheck.SteadyStateViolation as e:
        fail("train step retraced inside a timed window — the "
             "fingerprint fold must not destabilize shapes",
             violation=str(e).splitlines()[0])
    hot = prof.trace_counts()
    if any(hot.values()):
        fail("train step retraced inside a timed window", traces=hot)
    t_off = _stats.median(times["off"])
    t_on = _stats.median(times["on"])

    # clean window: every timed ON epoch checked at cadence 1 — the
    # exact-invariant gate is ZERO divergences, ever
    clean_checks = int(prof.counter_value("integrity/checks"))
    if not clean_checks or not integ_lst.fingerprints:
        fail("integrity checks did not run in the ON config",
             checks=clean_checks)
    if prof.counter_value("integrity/divergences") or integ_lst.divergences:
        fail("false positive: clean window counted a divergence",
             divergences=integ_lst.divergences)
    slo = next(s for s in watchtower.default_slos()
               if s.name == "replica-consistency")
    slo.sampler()                        # arming sample
    if slo.sampler():
        fail("replica-consistency SLO sampler tripped on a clean window")

    # ---- phase 2: bitflip -> quarantine -> one finalized incident -----
    def small_mlp():
        conf = (NeuralNetConfiguration.builder().seed(5)
                .updater(Adam(learning_rate=0.05)).activation("tanh")
                .list()
                .layer(L.DenseLayer(n_out=9))
                .layer(L.OutputLayer(n_out=3, loss="mcxent",
                                     activation="softmax"))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()

    def small_iter():
        r = np.random.RandomState(7)
        xs = r.randn(96, 4).astype(np.float32)
        ys = np.eye(3, dtype=np.float32)[r.randint(0, 3, 96)]
        return NDArrayDataSetIterator(xs, ys, batch_size=24, shuffle=True,
                                      seed=3)

    inc_dir = tempfile.mkdtemp(prefix="dl4j_integrity_inc_")
    sup_dir = tempfile.mkdtemp(prefix="dl4j_integrity_sup_")
    scrub_dir = tempfile.mkdtemp(prefix="dl4j_integrity_scrub_")
    watchtower.uninstall()
    tower = watchtower.install(watchtower.Watchtower(
        [], incident_dir=inc_dir, interval_s=0.05,
        finalize_after_s=120.0))
    try:
        flightrec.reset()
        prof.reset()
        set_default_seed(99)
        m = small_mlp()
        pw = (ParallelWrapper.Builder(m).workers(4)
              .gradients_accumulator(ReduceScatterAccumulator()).build())
        pw.set_listeners(integrity.IntegrityListener(check_every=1))
        faultinject.set_plan(faultinject.FaultPlan(
            [{"site": "integrity/fingerprint", "index": 5,
              "kind": "bitflip", "replica": 1}]))
        sup = TrainingSupervisor(pw, checkpoint_dir=sup_dir,
                                 elastic_grow=False)
        res = sup.fit(small_iter, epochs=3)
        faultinject.clear_plan()
        if res.status != "completed" or res.restarts != 0:
            fail("quarantine drill did not complete without a restart",
                 result=repr(res), history=res.history)
        if [h.get("policy") for h in res.history] \
                != ["quarantine_and_continue"] or pw.workers_count != 3:
            fail("divergent replica was not quarantined through the "
                 "elastic shrink", history=res.history,
                 workers=pw.workers_count)
        if prof.counter_value("supervisor/quarantines") != 1 or \
                prof.counter_value("integrity/divergences") != 1 or \
                prof.counter_value("integrity/bitflips_injected") != 1:
            fail("quarantine ledger mismatch",
                 ledger=prof.integrity_stats())
        if not slo.sampler():
            fail("replica-consistency SLO sampler missed the divergence")

        tower.evaluate_now()
        incs = tower.incidents()
        finalized = [i for i in incs if i.get("finalized")]
        if len(incs) != 1 or len(finalized) != 1:
            fail("expected exactly one finalized incident from the "
                 "bitflip drill", open=len(incs),
                 finalized=len(finalized))
        with open(finalized[0]["path"]) as f:
            report = json.load(f)
        chain = report["chain"]
        if not report["complete"] or \
                chain["cause"]["name"] != "fault/fired" or \
                chain["cause"]["attrs"].get("site") != \
                "integrity/fingerprint" or \
                chain["cause"]["attrs"].get("replica") != 1:
            fail("incident chain does not name the flipped replica as "
                 "cause", chain=chain)
        if chain["detection"]["name"] != "integrity/divergence" or \
                chain["mitigation"]["name"] != "integrity/quarantine":
            fail("incident detection/mitigation anchors wrong",
                 chain=chain)
        incident_id = report["id"]

        # ---- phase 3: checkpoint scrub drill ---------------------------
        set_default_seed(11)
        trainee = small_mlp()
        cl = CheckpointListener(scrub_dir, save_every_n_iterations=2,
                                keep_last=6)
        trainee.set_listeners(cl)
        trainee.fit(small_iter(), epochs=2)
        cl.close()
        paths = committed_checkpoints(scrub_dir)
        if len(paths) < 2:
            fail("scrub drill produced fewer than 2 retained "
                 "checkpoints", n=len(paths))
        scrub = integrity.CheckpointScrubber(scrub_dir, interval_s=60.0)
        faultinject.set_plan(faultinject.FaultPlan(
            [{"site": "checkpoint/scrub", "index": 0,
              "kind": "transient"}]))
        s1 = scrub.scrub_now()
        if s1["skipped"] < 1 or \
                prof.counter_value("integrity/scrub_retries") != 1:
            fail("transient scrub fault did not skip-and-retry",
                 summary=s1)
        faultinject.set_plan(faultinject.FaultPlan(
            [{"site": "checkpoint/scrub", "index": len(paths),
              "kind": "bitflip", "offset": 300, "bit": 2}]))
        s2 = scrub.scrub_now()
        faultinject.clear_plan()
        if s2["quarantined"] != 1 or scrub.passes != 2:
            fail("advisory bitflip did not quarantine the rotten "
                 "generation", summary=s2, passes=scrub.passes)
        q = flightrec.events("integrity/quarantine")[-1]
        rotten = q["attrs"].get("file")
        if not rotten or not os.path.exists(
                os.path.join(scrub_dir, rotten)):
            fail("quarantined checkpoint was deleted — evidence must "
                 "be retained", file=rotten)
        lc = last_checkpoint(scrub_dir)
        if lc is not None and os.path.basename(lc) == rotten:
            fail("restore path did not skip the quarantined generation",
                 restored=lc)
        if prof.counter_value("integrity/quarantined_checkpoints") != 1:
            fail("quarantined-checkpoint counter mismatch",
                 ledger=prof.integrity_stats())

        ledger = prof.integrity_stats()
        return {
            "metric": "integrity_smoke",
            "value": n / t_on,
            "unit": "images/sec",
            "batch": batch,
            "workers": workers,
            "platform": jax.devices()[0].platform,
            "check_every": 1,
            "traces": warm["on"],
            "fingerprint_overhead_frac": round(overhead, 4),
            "overhead_runs": overhead_runs,
            "epoch_s_off_median": round(t_off, 4),
            "epoch_s_on_median": round(t_on, 4),
            "clean_checks": clean_checks,
            "false_positives": 0,
            "quarantine_incident": incident_id,
            "quarantined_replica": 1,
            "workers_after_quarantine": pw.workers_count,
            "scrub": {"passes": scrub.passes, "quarantined_file": rotten,
                      "retries": 1},
            "integrity_ledger": {k: (round(v, 5) if isinstance(v, float)
                                     else v) for k, v in ledger.items()},
            "data": "LeNet A/B epochs with the in-graph fingerprint "
                    "fold at check_every=1 vs no listener; one injected "
                    "bitflip quarantined through the elastic shrink "
                    "with a finalized incident naming the replica; one "
                    "rotten retained zip quarantined by the scrubber",
        }
    finally:
        faultinject.clear_plan()
        watchtower.uninstall()
        for d in (inc_dir, sup_dir, scrub_dir):
            shutil.rmtree(d, ignore_errors=True)


def bench_obs_smoke(steps: int, batch: int = 64) -> dict:
    """CPU-friendly smoke of the observability layer (ISSUE 10). Three
    self-validating phases, every gate a hard fail:

    1. **Correlated supervised-restart drill** with the flight recorder
       ON: a deterministic crash mid-run, the supervisor heals it, and
       the exported Chrome trace must schema-validate AND contain spans
       (B/E pairs or profiler-section X slices) from >= 3 subsystems
       carrying the drill's ``incN.aM`` correlation ids; the black-box
       JSONL beside the checkpoints must reconstruct the
       fault → classify → restart → resume chain.
    2. **Interleaved A/B overhead** (recorder off vs on) inside a
       ``tracecheck.steady_state`` region: recorder-on step-time
       overhead > 5% (min-over-ratios, one automatic A/B re-run — the
       shared ``_ab_overhead_gate``) fails, any retrace delta fails.
    3. **``/api/metrics``** must parse as Prometheus text exposition
       (TYPE-before-samples, well-formed sample lines) and carry the
       counter/ledger/flight-recorder families.
    """
    import re
    import statistics as _stats
    import tempfile
    import urllib.request

    import jax

    from deeplearning4j_tpu.common import faultinject, flightrec, tracecheck
    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.data import NDArrayDataSetIterator
    from deeplearning4j_tpu.parallel import TrainingSupervisor
    from deeplearning4j_tpu.ui.server import UIServer

    prof = OpProfiler.get()
    rng = np.random.RandomState(0)
    n = steps * batch + batch // 2      # partial tail like the other smokes
    x = rng.randn(n, 1, 28, 28).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]

    def make_it():
        return NDArrayDataSetIterator(x, y, batch_size=batch)

    def fail(msg, **extra):
        print(json.dumps({"error": msg, **extra}))
        sys.exit(1)

    # ---- phase 1: correlated supervised-restart drill ------------------
    flightrec.configure(enabled=True)
    flightrec.reset()
    tmpdir = tempfile.mkdtemp(prefix="obs_smoke_ckpt_")
    faultinject.set_plan(faultinject.FaultPlan(
        [{"site": "train/step", "index": max(2, steps // 2),
          "kind": "crash"}]))
    model = _lenet_model()
    sup = TrainingSupervisor(model, tmpdir,
                             save_every_n_iterations=max(2, steps // 3),
                             backoff_base_s=0.01)
    res = sup.fit(make_it, epochs=1, resume="never")
    faultinject.clear_plan()
    if res.status != "completed" or res.restarts != 1:
        fail("supervised-restart drill did not heal as scripted",
             status=res.status, restarts=res.restarts)
    bb_path = sup.blackbox_path()
    if not os.path.exists(bb_path):
        fail("no black box beside the checkpoints after the drill",
             expected=bb_path)
    bb_names = [json.loads(l)["name"] for l in open(bb_path)]
    chain = ("fault/fired", "supervisor/attempt_failed",
             "supervisor/restart", "supervisor/attempt_start",
             "checkpoint/commit", "checkpoint/restore",
             "supervisor/completed")
    missing = [c for c in chain if c not in bb_names]
    if missing:
        fail("black box does not reconstruct the incident chain",
             missing=missing)

    trace_path = os.path.join(tmpdir, "drill_trace.json")
    flightrec.export_chrome_trace(trace_path)
    blob = json.load(open(trace_path))
    trace_events = blob.get("traceEvents")
    if not isinstance(trace_events, list) or not trace_events:
        fail("chrome trace export is empty or malformed")
    depth: dict = {}
    # B/E balance is only a valid invariant when the ring evicted
    # nothing — a long drill can legitimately drop a span's B while its
    # E survives (Perfetto tolerates the orphan; a gate must not)
    check_balance = flightrec.stats()["dropped"] == 0
    for ev in trace_events:
        if not {"ph", "pid", "tid", "name"} <= set(ev):
            fail("chrome trace event missing required keys", event=ev)
        if ev["ph"] != "M" and not isinstance(ev.get("ts"), (int, float)):
            fail("chrome trace event missing ts", event=ev)
        if ev["ph"] == "X" and not isinstance(ev.get("dur"), (int, float)):
            fail("X event without dur", event=ev)
        if not check_balance:
            continue
        if ev["ph"] == "B":
            depth[ev["tid"]] = depth.get(ev["tid"], 0) + 1
        elif ev["ph"] == "E":
            depth[ev["tid"]] = depth.get(ev["tid"], 0) - 1
            if depth[ev["tid"]] < 0:
                fail("unbalanced E before B in chrome trace",
                     tid=ev["tid"])
    if any(v != 0 for v in depth.values()):
        fail("unbalanced B/E pairs in chrome trace", depth=depth)
    corr_re = re.compile(r"inc\d+\.a\d+")
    drill_span_cats = {ev["cat"] for ev in trace_events
                      if ev["ph"] in ("B", "X")
                      and corr_re.fullmatch(
                          str(ev.get("args", {}).get("corr", "")))}
    if len(drill_span_cats) < 3:
        fail("chrome trace spans cover < 3 subsystems of the correlated "
             "drill", subsystems=sorted(drill_span_cats))

    # ---- phase 2: interleaved A/B recorder overhead --------------------
    models = {"off": _lenet_model(), "on": _lenet_model()}
    for m in models.values():       # warmup compile outside the region
        m.fit(make_it(), epochs=1)
        float(m._score_dev)
    prof.reset()

    def timed_epoch(name):
        m = models[name]
        flightrec.configure(enabled=(name == "on"))
        t0 = time.perf_counter()
        m.fit(make_it(), epochs=1)
        float(m._score_dev)         # value fence
        return time.perf_counter() - t0

    try:
        with tracecheck.steady_state("obs-smoke timed rounds",
                                     max_host_syncs=None):
            overhead, times, overhead_runs = _ab_overhead_gate(
                "flight-recorder", 0.05,
                lambda: _ab_rounds(timed_epoch, rounds=5), fail)
    except tracecheck.SteadyStateViolation as e:
        fail("train step retraced inside a timed window — the recorder "
             "must not destabilize shapes",
             violation=str(e).splitlines()[0])
    finally:
        flightrec.configure(enabled=True)
    t_off = _stats.median(times["off"])
    t_on = _stats.median(times["on"])

    # ---- phase 3: /api/metrics conformance -----------------------------
    ui = UIServer()
    port = ui.enable(0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/metrics", timeout=10) as r:
            metrics_text = r.read().decode()
    finally:
        ui.stop()
    families: dict = {}
    typed = None
    sample_re = re.compile(
        r'([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(-?[\d.eE+-]+)$')
    for line in metrics_text.splitlines():
        if not line.strip() or line.startswith("# HELP"):
            continue
        if line.startswith("# TYPE"):
            _h, _t, fam, mtype = line.split(None, 3)
            families[fam] = {"type": mtype, "samples": 0}
            typed = fam
            continue
        m = sample_re.match(line)
        if not m or m.group(1) not in families or m.group(1) != typed:
            fail("non-conformant /api/metrics line", line=line)
        families[m.group(1)]["samples"] += 1
    for fam in ("dl4j_counter_total", "dl4j_section_seconds_total",
                "dl4j_ledger", "dl4j_flightrec_events_total"):
        if families.get(fam, {}).get("samples", 0) < 1:
            fail(f"/api/metrics missing the {fam} family",
                 families=sorted(families))

    images = n + (batch - n % batch) % batch
    return {
        "metric": "obs_smoke",
        "value": images / t_on,
        "unit": "images/sec",
        "batch": batch,
        "platform": jax.devices()[0].platform,
        "recorder_overhead_frac": round(overhead, 4),
        "overhead_runs": overhead_runs,
        "epoch_s_off_median": round(t_off, 4),
        "epoch_s_on_median": round(t_on, 4),
        "drill_restarts": res.restarts,
        "blackbox_events": len(bb_names),
        "trace_events": len(trace_events),
        "drill_span_subsystems": sorted(drill_span_cats),
        "metrics_families": len(families),
        "flightrec": flightrec.stats(),
        "data": "synthetic LeNet batches; supervised crash drill with "
                "correlated chrome-trace/blackbox gates, recorder "
                "off/on interleaved A/B, /api/metrics conformance",
    }


def bench_xprof_smoke(steps: int, batch: int = 64) -> dict:
    """CPU-friendly smoke of the XLA performance observatory (ISSUE 15).
    Five self-validating phases, every gate a hard fail:

    1. **Census coverage**: a LeNet-class fit (per-step jit + infer jit)
       and a warmed ServingEngine bucket ladder; after
       ``xprof.analyze()`` every executable the smoke compiled must
       appear in the census with non-empty cost fields (flops/bytes) or
       an explicit counted fallback — a compiled-but-invisible
       executable is the bug class the census exists for.
    2. **Interleaved A/B census overhead** (census off vs on) inside a
       ``tracecheck.steady_state`` region: >5% min-over-ratios overhead
       (one automatic A/B re-run — the shared ``_ab_overhead_gate``)
       fails, any retrace delta fails (flipping the census must never
       rebuild a step).
    3. **Roofline ledger**: the ``xla`` entry of ``ledger_stats`` must
       carry per-executable flops/MFU/bound rows, and ``/api/metrics``
       (``prometheus_text``) must expose them.
    4. **Regression gate drill**: a deliberately-regressed synthetic
       record (step time +20%) against this run's own record must TRIP
       ``benchtrack.compare_records``; the clean copy must pass.
    5. **HBM watermarks**: the per-epoch ``fit`` phase must have
       sampled, and ``dump_memory_census`` must write a parseable
       census (the crash-blackbox companion).
    """
    import statistics as _stats
    import tempfile

    import jax

    from deeplearning4j_tpu.common import tracecheck, xprof
    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.data import NDArrayDataSetIterator
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import layers as L
    from deeplearning4j_tpu.parallel import ServingEngine
    from deeplearning4j_tpu.ui.server import prometheus_text
    from tools import benchtrack

    prof = OpProfiler.get()
    rng = np.random.RandomState(0)
    n = steps * batch + batch // 2
    x = rng.randn(n, 1, 28, 28).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]

    def make_it():
        return NDArrayDataSetIterator(x, y, batch_size=batch)

    def fail(msg, **extra):
        print(json.dumps({"error": msg, **extra}, default=str))
        sys.exit(1)

    # ---- phase 1: census coverage (fit + infer + serving ladder) -------
    xprof.reset()
    xprof.configure(enabled=True)
    prof.reset()
    model = _lenet_model()
    model.fit(make_it(), epochs=1)
    float(model._score_dev)
    model.output(x[:batch])                      # mln/infer executable

    sconf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-3))
             .activation("tanh").list()
             .layer(L.DenseLayer(n_out=32))
             .layer(L.OutputLayer(n_out=10))
             .set_input_type(InputType.feed_forward(16)).build())
    smodel = MultiLayerNetwork(sconf).init()
    eng = (ServingEngine.Builder(smodel)
           .buckets([1, 4, 8]).input_shape((16,))
           .workers(1).max_wait_ms(1.0).build())
    try:
        analyzed = xprof.analyze()
        census = xprof.census()
        compiled_here = ["mln/fit_step", "mln/infer", "serving/bucket"]
        missing = [name for name in compiled_here if name not in census]
        if missing:
            fail("compiled executables missing from the census",
                 missing=missing, census=sorted(census))
        for name in compiled_here:
            entry = census[name]
            cost = entry.get("cost") or {}
            if entry.get("cost_source") == "xla" and not cost:
                fail(f"census entry {name} claims xla analysis but "
                     "carries no cost fields", entry=entry)
            if entry.get("cost_source") is None:
                fail(f"census entry {name} was never analyzed (no cost, "
                     "no counted fallback)", entry=entry)
        if census["serving/bucket"]["variants"] \
                != len(eng.ladder.batch_sizes):
            fail("serving bucket census variants != ladder size",
                 variants=census["serving/bucket"]["variants"],
                 buckets=len(eng.ladder.batch_sizes))
    finally:
        eng.shutdown()

    # ---- phase 2: interleaved A/B census on/off ------------------------
    models = {"off": _lenet_model(), "on": _lenet_model()}
    for m in models.values():
        m.fit(make_it(), epochs=1)               # warmup compile
        float(m._score_dev)
    prof.reset()

    def timed_epoch(name):
        m = models[name]
        xprof.configure(enabled=(name == "on"))
        t0 = time.perf_counter()
        m.fit(make_it(), epochs=1)
        float(m._score_dev)
        return time.perf_counter() - t0

    try:
        with tracecheck.steady_state("xprof-smoke timed rounds",
                                     max_host_syncs=None):
            overhead, times, overhead_runs = _ab_overhead_gate(
                "executable-census", 0.05,
                lambda: _ab_rounds(timed_epoch, rounds=5), fail)
    except tracecheck.SteadyStateViolation as e:
        fail("train step retraced inside a timed window — flipping the "
             "census must not destabilize shapes",
             violation=str(e).splitlines()[0])
    finally:
        xprof.configure(enabled=True)
    t_off = _stats.median(times["off"])
    t_on = _stats.median(times["on"])

    # ---- phase 3: xla roofline ledger + Prometheus exposition ----------
    ledgers = prof.ledger_stats()
    xla = ledgers.get("xla", {})
    if not any(k.endswith("/flops") for k in xla):
        fail("xla ledger carries no per-executable flops rows",
             keys=sorted(xla)[:20])
    if not any(k.endswith("/compute_bound") for k in xla):
        fail("xla ledger carries no bound-classification rows",
             keys=sorted(xla)[:20])
    metrics_text = prometheus_text()
    if 'ledger="xla"' not in metrics_text:
        fail("/api/metrics exposition is missing the xla ledger family")

    # ---- phase 4: the --compare-to regression gate drill ---------------
    epoch_steps = -(-len(x) // batch)
    step_ms = t_on / epoch_steps * 1e3
    base_rec = {"metric": "xprof_smoke", "value": len(x) / t_on,
                "unit": "images/sec", "batch": batch,
                "platform": jax.devices()[0].platform,
                "step_ms_median": round(step_ms, 3),
                "step_ms_p10": round(step_ms * 0.97, 3)}
    regressed = dict(base_rec)
    regressed["step_ms_median"] = round(step_ms * 1.2, 3)
    regressed["step_ms_p10"] = round(step_ms * 1.18, 3)
    regressed["value"] = base_rec["value"] / 1.2
    tripped = benchtrack.compare_records(
        {"xprof_smoke": base_rec}, {"xprof_smoke": regressed})
    if not tripped["violations"]:
        fail("the regression gate FAILED to flag a 20% step-time "
             "regression", result=tripped)
    clean = benchtrack.compare_records(
        {"xprof_smoke": base_rec}, {"xprof_smoke": dict(base_rec)})
    if clean["violations"]:
        fail("the regression gate flagged an identical re-run",
             result=clean)

    # ---- phase 5: HBM watermarks + memory-census dump ------------------
    wms = xprof.watermarks()
    if "fit" not in wms or wms["fit"]["samples"] < 1:
        fail("per-epoch fit watermark never sampled", watermarks=wms)
    if wms["fit"]["peak_live_bytes"] <= 0:
        fail("fit watermark peak is zero", watermarks=wms)
    dump_path = os.path.join(tempfile.mkdtemp(prefix="xprof_smoke_"),
                             "memcensus.json")
    xprof.dump_memory_census(dump_path)
    blob = json.load(open(dump_path))
    if "watermarks" not in blob or "census" not in blob:
        fail("memory-census dump is malformed", keys=sorted(blob))

    images = n + (batch - n % batch) % batch
    return {
        "metric": "xprof_smoke",
        "value": images / t_on,
        "unit": "images/sec",
        "batch": batch,
        "platform": jax.devices()[0].platform,
        "census_overhead_frac": round(overhead, 4),
        "overhead_runs": overhead_runs,
        "epoch_s_off_median": round(t_off, 4),
        "epoch_s_on_median": round(t_on, 4),
        "census_executables": len(census),
        "analyzed": sorted(analyzed),
        "xla_ledger_rows": len(xla),
        "fit_watermark": wms.get("fit"),
        "gate_drill_violations": tripped["violations"],
        "data": "synthetic LeNet batches + a warmed 3-bucket serving "
                "ladder; census coverage, A/B census overhead, xla "
                "roofline/Prometheus, regression-gate drill, HBM "
                "watermark + memcensus dump",
    }


def _fleet_mlp(seed=7, n_in=64, n_out=10, hidden=32, lr=1e-3):
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import layers as L

    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(learning_rate=lr)).activation("tanh")
            .weight_init("xavier").list()
            .layer(L.DenseLayer(n_out=hidden))
            .layer(L.OutputLayer(n_out=n_out, loss="mse",
                                 activation="identity"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def bench_fleet_smoke(steps: int, batch: int = 64,
                      members: int = 8) -> dict:
    """CPU-friendly smoke of fleet training (parallel.fleet): an M-member
    stacked MLP population trained through ONE vmapped+jitted step.
    Self-validating hard gates:

    - **bitwise member parity**: member 3 of the fleet equals the same
      model trained SOLO with the same RNG stream (``solo_twin``), every
      param leaf bit-for-bit, after the full timed run;
    - **one compile for the whole fleet**: ``trace/fleet_step`` moves by
      exactly 1 per fleet instance, and the lifecycle phase — steps, a
      mid-run cull, a spawn, a per-member NaN injection, a telemetry
      drain — runs inside ``tracecheck.steady_state`` (any retrace
      fails the run; the drain's batched device_get is the declared
      sync budget);
    - **cull drill**: the culled member's params are bit-frozen while
      the rest keep training;
    - **per-member NaN drill**: a NaN batch fed to ONE member flips only
      that member's alive bit (``fleet/nan_cull``), and every OTHER
      member's params are bitwise identical to a clean control run;
    - **throughput**: the fleet trains M=8 members at >= 3x the summed
      per-model sequential baseline (one model's timed epoch x M).
    """
    import statistics as _stats

    import jax

    from deeplearning4j_tpu.common import flightrec, tracecheck
    from deeplearning4j_tpu.common.profiler import OpProfiler
    from deeplearning4j_tpu.optimize import NanSentinelListener
    from deeplearning4j_tpu.parallel import FleetTrainer

    rng = np.random.RandomState(0)
    x = rng.randn(batch, 64).astype(np.float32)
    y = rng.randn(batch, 10).astype(np.float32)
    prof = OpProfiler.get()

    def fail(msg, **extra):
        print(json.dumps({"error": msg, **extra}))
        sys.exit(1)

    # ---- phase 1: parity + throughput (no telemetry, the hot shape) ----
    fleet = FleetTrainer(_fleet_mlp(), members, seed=7)
    solo = fleet.solo_twin(3)
    from deeplearning4j_tpu.data.dataset import DataSet

    ds = DataSet(x, y)
    t0 = prof.counter_value("trace/fleet_step")
    fleet.step(x, y)                      # warmup (the one compile)
    solo.fit(ds, epochs=1)
    jax.block_until_ready(fleet._params)

    t_start = time.perf_counter()
    for _ in range(steps):
        fleet.step(x, y)
    jax.block_until_ready(fleet._params)
    fleet_s = time.perf_counter() - t_start

    # first solo epoch lands on the SAME step count as the fleet — the
    # parity gate compares here; two more epochs refine the timing median
    solo_times = []
    t_start = time.perf_counter()
    for _ in range(steps):
        solo.fit(ds, epochs=1)
    jax.block_until_ready(solo._params)
    solo_times.append(time.perf_counter() - t_start)

    if prof.counter_value("trace/fleet_step") - t0 != 1:
        fail("fleet step traced more than once",
             traces=prof.trace_counts())
    p_f = jax.tree.leaves(jax.tree.map(lambda a: np.array(a[3]),
                                       fleet._params))
    p_s = jax.tree.leaves(jax.tree.map(np.array, solo._params))
    if not all(np.array_equal(a, b) for a, b in zip(p_f, p_s)):
        md = max(float(np.max(np.abs(a - b))) for a, b in zip(p_f, p_s))
        fail("fleet member 3 is not bitwise identical to its solo twin",
             max_abs_diff=md)

    for _ in range(2):
        t_start = time.perf_counter()
        for _ in range(steps):
            solo.fit(ds, epochs=1)
        jax.block_until_ready(solo._params)
        solo_times.append(time.perf_counter() - t_start)
    solo_s = _stats.median(solo_times)
    speedup = (members * solo_s) / fleet_s
    if speedup < 3.0:
        fail(f"fleet throughput {speedup:.2f}x the summed sequential "
             f"baseline (gate: >= 3x at M={members})",
             fleet_s=round(fleet_s, 4), solo_s=round(solo_s, 4))

    # ---- phase 2: lifecycle under steady_state (sweep + cull + NaN) ----
    def lifecycle(inject_nan: bool):
        """One deterministic lifecycle run; the drill and its clean
        control share everything but the poisoned batch."""
        fl = FleetTrainer.from_sweep(
            _fleet_mlp(), {"lr": [1e-3] * (members // 2)
                           + [3e-3] * (members - members // 2)},
            seed=7, drain_every_n=4)
        fl.set_listeners(NanSentinelListener("cull", check_every_n=4))
        # warmup: trace the step, warm the cull/spawn dispatch paths and
        # the per-member batch shape, then drain
        fl.step(x, y)
        xs = np.broadcast_to(x, (members,) + x.shape).copy()
        ys = np.broadcast_to(y, (members,) + y.shape).copy()
        fl.step(xs, ys, per_member=True)
        fl.cull(0, reason="warmup")
        fl.step(x, y)
        fl.spawn(0)
        fl.drain()
        return fl, xs, ys

    fl, xs, ys = lifecycle(False)
    ctrl, _, _ = lifecycle(False)
    flightrec.reset()
    try:
        with tracecheck.steady_state("fleet lifecycle",
                                     max_host_syncs=None):
            for s in range(6):
                fl.step(x, y)
                ctrl.step(x, y)
            # cull drill: member 5 freezes mid-run (both runs)
            fl.cull(5, reason="drill")
            ctrl.cull(5, reason="drill")
            frozen_at = jax.tree.map(lambda a: np.array(a[5]),
                                     fl._params)
            for s in range(4):
                fl.step(x, y)
                ctrl.step(x, y)
            # NaN drill: poison member 2's batch in the drill run only
            bad = xs.copy()
            bad[2] = np.nan
            fl.step(bad, ys, per_member=True)
            ctrl.step(xs, ys, per_member=True)
            for s in range(4):
                fl.step(x, y)
                ctrl.step(x, y)
            frozen_check = jax.tree.map(lambda a: np.array(a[5]),
                                        fl._params)
            fl.spawn(5)
            ctrl.spawn(5)
            fl.step(x, y)
            ctrl.step(x, y)
            fl.drain()
            ctrl.drain()
    except tracecheck.SteadyStateViolation as e:
        fail("fleet lifecycle retraced inside the steady-state region",
             violation=str(e).splitlines()[0])

    alive = fl.alive_mask()
    if alive[2] != 0:
        fail("per-member NaN drill did not cull the poisoned member",
             alive=alive.tolist())
    if not flightrec.events("fleet/nan_cull"):
        fail("no fleet/nan_cull event on the timeline")
    # cull drill: between its cull and its spawn, member 5's slice must
    # not have moved a single bit while the rest of the fleet trained on
    if not all(np.array_equal(a, b)
               for a, b in zip(jax.tree.leaves(frozen_at),
                               jax.tree.leaves(frozen_check))):
        fail("cull drill: the culled member's params moved")
    for m in range(members):
        if m == 2:
            continue
        a = jax.tree.leaves(jax.tree.map(lambda t: np.array(t[m]),
                                         fl._params))
        b = jax.tree.leaves(jax.tree.map(lambda t: np.array(t[m]),
                                         ctrl._params))
        if not all(np.array_equal(u, v) for u, v in zip(a, b)):
            fail(f"NaN drill perturbed member {m} (must be "
                 f"bit-unaffected)", member=m)

    images = steps * batch * members
    return {
        "metric": "fleet_smoke",
        "value": images / fleet_s,
        "unit": "member-images/sec",
        "batch": batch,
        "members": members,
        "platform": jax.devices()[0].platform,
        "fleet_epoch_s": round(fleet_s, 4),
        "solo_epoch_s": round(solo_s, 4),
        "speedup_vs_sequential": round(speedup, 2),
        "speedup_gate": 3.0,
        "traces": prof.trace_counts(),
        "bitwise_member_parity": True,
        "nan_cull_events": len(flightrec.events("fleet/nan_cull")),
        "cull_events": len(flightrec.events("fleet/cull")),
        "spawn_events": len(flightrec.events("fleet/spawn")),
        "alive_after_drills": alive.tolist(),
        "fleet_ledger": prof.fleet_stats(),
        "telemetry_drain": {k: (round(v, 5) if isinstance(v, float) else v)
                            for k, v in prof.telemetry_stats().items()},
        "data": "synthetic 64-feature MLP batches; M-member vmapped "
                "fleet vs solo-twin bitwise parity, cull/spawn/NaN "
                "drills inside one steady_state region",
    }


def bench_word2vec(steps: int) -> dict:
    """North-star config 4: Word2Vec skip-gram + negative sampling over a
    synthetic zipfian corpus; throughput = corpus words consumed / sec
    end-to-end (host pair-generation + fused device rounds), the number the
    reference logs at INFO during SequenceVectors.fit (SURVEY §3.6).
    ``steps`` scales the corpus: steps * 1000 sentences of 20 words.
    The word2vec default is 200 (a 4M-word corpus): throughput on this
    config is steady-state-dominated the way the reference's INFO number
    is; tiny corpora mostly measure per-process trace/executable-load."""
    import jax

    from deeplearning4j_tpu.nlp import Word2Vec

    rng = np.random.default_rng(123)
    vocab_size, n_sent, sent_len = 10_000, steps * 1000, 20
    p = 1.0 / np.arange(1, vocab_size + 1)
    p /= p.sum()
    words = np.array([f"w{i}" for i in range(vocab_size)])
    ids = rng.choice(vocab_size, size=(n_sent, sent_len), p=p)
    sents = [" ".join(row) for row in words[ids]]

    w2v = _w2v_model()
    w2v.set_sentence_iterator(sents)
    # Same methodology as the lenet/resnet/bert benches: compile excluded,
    # steady state timed. fit() #1 builds vocab + traces/compiles the block
    # and trains once (cold, recorded); fit() #2 reuses the compiled block
    # (resume semantics) — its words/sec is uploads + pair derivation +
    # device rounds + final value-fence, none of it compilation.
    w2v.fit()
    cold = w2v.words_per_sec
    w2v.fit()
    return {
        "metric": "word2vec_skipgram_train",
        "value": w2v.words_per_sec,
        "unit": "words/sec",
        "platform": jax.devices()[0].platform,
        "vocab": len(w2v.vocab),
        "corpus_words": n_sent * sent_len,
        "pairs_per_sec": round(w2v.pairs_per_sec),
        "cold_words_per_sec": round(cold),
        "layer_size": 100, "negative": 5, "window": 5,
        "data": "synthetic zipfian corpus (host RAM)",
        "final_loss": round(w2v.last_loss, 4),
    }


def _first_step_child(config: str) -> None:
    """ONE optimizer step end-to-end, meant to run in a FRESH process (the
    parent times the whole process: interpreter + imports + model build +
    trace + compile-or-cache-load + execute = time-to-first-step)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.common.environment import Environment
    from deeplearning4j_tpu.data import DataSet

    Environment.get()   # applies DL4J_TPU_COMPILE_CACHE (library path)
    rng = np.random.RandomState(0)
    if config == "lenet":
        model = _lenet_model()                 # shared flagship builder
        x = rng.randn(128, 1, 28, 28).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 128)]
        model.fit(DataSet(x, y))
        loss = float(model._score_dev)
    elif config == "resnet50":
        # batch 64 (the cold ledger's recorded shape); the throughput
        # bench default is 128 — the MODEL is the shared builder either way
        model = _resnet50_model(224)
        x = rng.randn(64, 3, 224, 224).astype(np.float32)
        y = np.eye(1000, dtype=np.float32)[rng.randint(0, 1000, 64)]
        model.fit(DataSet(jnp.asarray(x), jnp.asarray(y)))
        loss = float(model._score_dev)
    elif config == "bert":
        step, params, upd, ph, _ = _bert_training(batch=32, seq=128)
        _, _, loss_dev = step(params, upd, ph, jax.random.PRNGKey(0),
                              jnp.asarray(0))
        loss = float(loss_dev)
    elif config == "word2vec":
        w2v = _w2v_model()
        w2v.set_sentence_iterator(_zipf_sentences(400_000))
        w2v.fit()
        loss = w2v.last_loss
    else:
        raise SystemExit(f"unknown first-step config {config}")
    assert np.isfinite(loss), f"non-finite first-step loss for {config}"
    print(f"FIRST_STEP_OK {config} loss={loss:.4f}", flush=True)


def cold_audit(configs=("lenet", "resnet50", "bert", "word2vec")) -> None:
    """Time-to-first-step ledger (round-5 item 6; SURVEY §5.6, §7.3 item
    8 compile-cost honesty): for each flagship, spawn a FRESH process
    against an empty persistent compile cache (cold) and a second fresh
    process against the now-populated cache (warm). Emits one JSON line
    per config with both wall times."""
    import subprocess
    import sys
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__))
    for config in configs:
        with tempfile.TemporaryDirectory(prefix="d4t_coldaudit_") as cache:
            times = []
            for run in ("cold", "warm_cache"):
                env = dict(os.environ)
                env["DL4J_TPU_COMPILE_CACHE"] = cache
                t0 = time.perf_counter()
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--first-step", config],
                    env=env, cwd=here, capture_output=True, text=True)
                dt = time.perf_counter() - t0
                if proc.returncode != 0 or "FIRST_STEP_OK" not in proc.stdout:
                    raise RuntimeError(
                        f"first-step {config} ({run}) failed rc="
                        f"{proc.returncode}:\n{proc.stdout}\n{proc.stderr}")
                times.append(dt)
            print(json.dumps({
                "metric": f"time_to_first_step_{config}",
                "value": round(times[1], 2), "unit": "seconds",
                "vs_baseline": 1.0,
                "cold_s": round(times[0], 2),
                "warm_cache_s": round(times[1], 2),
                "speedup": round(times[0] / max(times[1], 1e-9), 2),
                "note": "fresh process each; cold = empty persistent "
                        "compile cache, warm = same cache populated by the "
                        "cold run; time includes interpreter+imports+build+"
                        "trace+compile-or-load+one optimizer step",
            }), flush=True)


def _zipf_sentences(n_words: int, vocab_size: int = 10_000,
                    sent_len: int = 20, seed: int = 123):
    rng = np.random.default_rng(seed)
    n_sent = max(1, n_words // sent_len)
    p = 1.0 / np.arange(1, vocab_size + 1)
    p /= p.sum()
    words = np.array([f"w{i}" for i in range(vocab_size)])
    ids = rng.choice(vocab_size, size=(n_sent, sent_len), p=p)
    return [" ".join(row) for row in words[ids]]


def bench_word2vec_variant(steps: int, algorithm: str = "cbow",
                           hs: bool = False) -> dict:
    """CBOW / hierarchical-softmax driver-visible lines (VERDICT r4 item
    7): same corpus/methodology as bench_word2vec, different training
    path."""
    import jax

    from deeplearning4j_tpu.nlp import Word2Vec

    sents = _zipf_sentences(steps * 1000 * 20)
    w2v = Word2Vec(min_word_frequency=5, layer_size=100, window=5,
                   negative=0 if hs else 5, use_hierarchic_softmax=hs,
                   sampling=1e-3, epochs=1, batch_size=8192, seed=42,
                   algorithm=algorithm)
    w2v.set_sentence_iterator(sents)
    w2v.fit()
    cold = w2v.words_per_sec
    w2v.fit()
    name = f"word2vec_{algorithm}{'_hs' if hs else ''}_train"
    return {
        "metric": name, "value": w2v.words_per_sec, "unit": "words/sec",
        "platform": jax.devices()[0].platform, "vocab": len(w2v.vocab),
        "corpus_words": len(sents) * 20,
        "cold_words_per_sec": round(cold),
        "layer_size": 100, "window": 5,
        "negative": w2v.negative, "hs": hs,
        "data": "synthetic zipfian corpus (host RAM)",
        "final_loss": round(w2v.last_loss, 4),
    }


def bench_paragraph_vectors(steps: int) -> dict:
    """PV-DBOW on the device-windowed machinery (VERDICT r4 weak #1 /
    round-5 item 2): 40k docs x 100 words; words/sec includes the
    interleaved word-vector pass (reference default
    trainElementsRepresentation=true)."""
    import jax

    from deeplearning4j_tpu.nlp import ParagraphVectors
    from deeplearning4j_tpu.nlp.text import LabelAwareIterator

    doc_len = 100
    n_docs = max(10, steps * 1000 * 20 // doc_len)
    docs = _zipf_sentences(n_docs * doc_len, sent_len=doc_len)
    labels = [f"DOC_{i}" for i in range(len(docs))]
    pv = (ParagraphVectors.builder().min_word_frequency(5).layer_size(100)
          .epochs(1).negative_sample(5).batch_size(8192).seed(42)
          .sampling(1e-3)
          .iterate(LabelAwareIterator(docs, labels)).build())
    pv.fit()
    cold = pv.words_per_sec
    pv.fit()
    return {
        "metric": "paragraph_vectors_dbow_train",
        "value": pv.words_per_sec, "unit": "words/sec",
        "platform": jax.devices()[0].platform,
        "vocab": len(pv.vocab), "n_docs": n_docs,
        "corpus_words": n_docs * doc_len,
        "cold_words_per_sec": round(cold),
        "train_word_vectors": True,
        "data": "synthetic zipfian docs (host RAM)",
        "final_loss": round(pv.last_loss, 4),
    }


def bench_glove(n_words: int = 1_000_000) -> dict:
    import jax

    from deeplearning4j_tpu.nlp import Glove

    sents = _zipf_sentences(n_words)
    g = (Glove.builder().min_word_frequency(5).layer_size(100)
         .window_size(5).epochs(5).batch_size(8192).seed(42)
         .iterate(sents).build())
    g.fit()
    return {
        "metric": "glove_train", "value": g.words_per_sec,
        "unit": "words/sec", "platform": jax.devices()[0].platform,
        "vocab": len(g.vocab), "corpus_words": n_words, "epochs": 5,
        "data": "synthetic zipfian corpus (host RAM); includes host "
                "co-occurrence accumulation",
    }


def bench_fasttext(n_words: int = 1_000_000) -> dict:
    import jax

    from deeplearning4j_tpu.nlp import FastText

    sents = _zipf_sentences(n_words)
    ft = (FastText.builder().min_word_frequency(5).layer_size(100)
          .negative_sample(5).epochs(1).batch_size(8192).seed(42)
          .iterate(sents).build())
    ft.fit()
    cold = ft.words_per_sec
    ft.fit()
    return {
        "metric": "fasttext_train", "value": ft.words_per_sec,
        "unit": "words/sec", "platform": jax.devices()[0].platform,
        "vocab": len(ft.vocab), "corpus_words": n_words,
        "cold_words_per_sec": round(cold),
        "data": "synthetic zipfian corpus (host RAM); round-5 "
                "device-windowed subword path (subword windows gathered "
                "on device)",
    }


def main() -> None:
    # zero1-smoke / elastic-smoke need a multi-replica mesh: request
    # virtual CPU devices BEFORE anything imports jax (the library import
    # just below does). The flag only affects the host platform —
    # harmless on TPU runs.
    if ({"zero1-smoke", "elastic-smoke", "pipeline-parallel-smoke",
         "soak-smoke", "integrity-smoke"}
            & set(sys.argv)) and "jax" not in sys.modules:
        _flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in _flags:
            os.environ["XLA_FLAGS"] = (
                _flags + " --xla_force_host_platform_device_count=8").strip()
    # Persistent executable cache: compile each bench module once per
    # MACHINE, not once per process (the reference ships pre-built libnd4j
    # kernels; this is the XLA analog). First-ever run still pays the
    # compile; every later run loads the serialized executable.
    from deeplearning4j_tpu.common.environment import enable_compilation_cache
    enable_compilation_cache(os.path.join(os.path.dirname(
        os.path.abspath(__file__)), ".jax_cache"))

    parser = argparse.ArgumentParser()
    parser.add_argument("--first-step", default=None,
                        help="internal: run ONE optimizer step of the named "
                             "config and exit (spawned by --cold-audit)")
    parser.add_argument("--cold-audit", nargs="?", const="all", default=None,
                        help="time-to-first-step ledger: fresh process per "
                             "flagship, cold vs populated compile cache; "
                             "optionally a comma-separated config subset")
    parser.add_argument("--config", default="flagships",
                        choices=["flagships", "lenet", "resnet50", "bert",
                                 "word2vec", "word2vec-cbow", "word2vec-hs",
                                 "paragraph-vectors", "glove", "fasttext",
                                 "resnet50-disk", "resnet50-predecoded",
                                 "pipeline-smoke", "telemetry-smoke",
                                 "fault-smoke", "supervisor-smoke",
                                 "zero1-smoke", "elastic-smoke",
                                 "cluster-smoke",
                                 "pipeline-parallel-smoke",
                                 "serving-smoke", "autoscale-smoke",
                                 "mfu-smoke", "obs-smoke", "fleet-smoke",
                                 "xprof-smoke", "remat-smoke",
                                 "soak-smoke", "integrity-smoke"])
    parser.add_argument("--steps", type=int, default=None)
    parser.add_argument("--batch", type=int, default=None,
                        help="per-config default: resnet50=128, bert=32")
    parser.add_argument("--image-size", type=int, default=None,
                        help="resnet50 input resolution (default 224; "
                             "smaller sizes make CPU re-baselines "
                             "tractable — the emitted record pins it)")
    parser.add_argument("--compare-to", default=None, metavar="ROUND",
                        help="regression gate: after the run, hold every "
                             "emitted record against the same metric in "
                             "this BENCH_r*.json round (tools/benchtrack "
                             "min-over-rounds gates: step time, "
                             "throughput, MFU, compile counts, state "
                             "bytes); exit non-zero on any violation")
    parser.add_argument("--with-listener", action="store_true",
                        help="attach a ScoreIterationListener during the timed "
                             "run (validates the listener bus does not tax the "
                             "hot loop)")
    args = parser.parse_args()

    if args.first_step:
        # NOTE: no enable_compilation_cache here — the child honors the
        # DL4J_TPU_COMPILE_CACHE env var through Environment.get() inside
        # the library, which is exactly the path being audited
        _first_step_child(args.first_step)
        return
    if args.cold_audit:
        if args.cold_audit == "all":
            cold_audit()
        else:
            cold_audit(tuple(args.cold_audit.split(",")))
        return

    if args.config.endswith("-smoke"):
        # dirty lint refuses to bench: the smoke configs assert hot-loop
        # invariants (no retraces, no host syncs, fault sites firing) —
        # running them over a package that fails the static versions of
        # those same invariants produces numbers nobody should trust
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from tools import graftlint

        lint = graftlint.lint(os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "deeplearning4j_tpu"))
        if not lint.clean:
            for f in lint.findings:
                print(f.render(), file=sys.stderr)
            print(json.dumps({"error": "graftlint preflight failed — fix "
                              "or suppress (with a reason) before "
                              "benching",
                              "findings": len(lint.findings)}))
            sys.exit(1)

    steps = args.steps or 30
    emitted: list = []

    def emit(result: dict) -> None:
        base = BASELINES.get(result["metric"], {}).get("value")
        vs = (result["value"] / base) if base else 1.0
        ordered = {"metric": result.pop("metric"),
                   "value": round(result.pop("value"), 2),
                   "unit": result.pop("unit"),
                   "vs_baseline": round(vs, 3)}
        ordered.update(result)
        emitted.append(ordered)
        print(json.dumps(ordered), flush=True)

    def finish() -> None:
        """The --compare-to regression gate (ISSUE 15): every emitted
        record is held against the baseline round's same-metric record;
        any violation is a hard non-zero exit. Cross-platform records
        are skipped (reported, never failed)."""
        if not args.compare_to:
            return
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from tools import benchtrack

        baseline = benchtrack.parse_round(args.compare_to)
        current = {r["metric"]: r for r in emitted}
        result = benchtrack.compare_records(baseline["records"], current)
        print(json.dumps({"compare_to": args.compare_to, **result}),
              flush=True)
        if result["violations"]:
            sys.exit(1)

    if args.config == "flagships":
        # The default run tells the WHOLE flagship story (round-3 verdict
        # item 5): BERT (the matmul-dominated model, 48.7% MFU class) and
        # Word2Vec print first, ResNet-50 LAST for drivers that parse the
        # final line (the bandwidth-bound model whose 25-30% MFU band the
        # round-3 audit pinned to BatchNorm/HBM, not code). --steps scales
        # all three; --batch applies to ResNet-50 only (BERT's 32 is its
        # measured plateau and its vs_baseline anchor is batch-32).
        emit(bench_bert(args.steps or 80, batch=32))
        emit(bench_word2vec(args.steps or 200))
        # NLP family (round-5 items 2+7): CBOW + HS driver-visible w2v
        # variants, PV-DBOW on the device-windowed path, GloVe + FastText
        emit(bench_word2vec_variant(args.steps or 200, "cbow"))
        emit(bench_word2vec_variant(args.steps or 200, "skipgram", hs=True))
        emit(bench_paragraph_vectors(args.steps or 200))
        emit(bench_glove())
        emit(bench_fasttext())
        emit(bench_resnet50(args.steps or 80, batch=args.batch or 128,
                            image_size=args.image_size or 224,
                            with_listener=args.with_listener))
        finish()
        return
    if args.config == "lenet":
        result = bench_lenet(steps, with_listener=args.with_listener)
    elif args.config == "bert":
        # batch 32 is the measured throughput plateau (BASELINE.md); 8 was
        # relay-latency-bound and understated the hardware ~3×
        result = bench_bert(steps, batch=args.batch or 32)
    elif args.config == "word2vec":
        result = bench_word2vec(args.steps or 200)
    elif args.config == "word2vec-cbow":
        result = bench_word2vec_variant(args.steps or 200, "cbow")
    elif args.config == "word2vec-hs":
        result = bench_word2vec_variant(args.steps or 200, "skipgram",
                                        hs=True)
    elif args.config == "paragraph-vectors":
        result = bench_paragraph_vectors(args.steps or 200)
    elif args.config == "glove":
        result = bench_glove(n_words=(args.steps or 50) * 20_000)
    elif args.config == "fasttext":
        result = bench_fasttext(n_words=(args.steps or 20) * 20_000)
    elif args.config == "pipeline-smoke":
        result = bench_pipeline_smoke(steps, batch=args.batch or 64)
    elif args.config == "telemetry-smoke":
        result = bench_telemetry_smoke(steps, batch=args.batch or 64)
    elif args.config == "fault-smoke":
        result = bench_fault_smoke(steps, batch=args.batch or 64)
    elif args.config == "supervisor-smoke":
        result = bench_supervisor_smoke(steps, batch=args.batch or 64)
    elif args.config == "zero1-smoke":
        result = bench_zero1_smoke(steps, batch=args.batch or 64)
    elif args.config == "mfu-smoke":
        result = bench_mfu_smoke(steps, batch=args.batch or 64)
    elif args.config == "remat-smoke":
        result = bench_remat_smoke(steps, batch=args.batch or 64)
    elif args.config == "elastic-smoke":
        result = bench_elastic_smoke(steps, batch=args.batch or 64)
    elif args.config == "cluster-smoke":
        result = bench_cluster_smoke(steps)
    elif args.config == "pipeline-parallel-smoke":
        result = bench_pipeline_parallel_smoke(steps, batch=args.batch or 64)
    elif args.config == "serving-smoke":
        result = bench_serving_smoke(steps, batch=args.batch or 32)
    elif args.config == "autoscale-smoke":
        result = bench_autoscale_smoke(steps, batch=args.batch or 32)
    elif args.config == "soak-smoke":
        result = bench_soak_smoke(steps, batch=args.batch or 32)
    elif args.config == "integrity-smoke":
        result = bench_integrity_smoke(steps, batch=args.batch or 64)
    elif args.config == "obs-smoke":
        result = bench_obs_smoke(steps, batch=args.batch or 64)
    elif args.config == "fleet-smoke":
        result = bench_fleet_smoke(steps, batch=args.batch or 64)
    elif args.config == "xprof-smoke":
        result = bench_xprof_smoke(steps, batch=args.batch or 64)
    elif args.config == "resnet50-disk":
        result = bench_resnet50_disk(steps, batch=args.batch or 64,
                                     image_size=args.image_size or 224)
    elif args.config == "resnet50-predecoded":
        result = bench_resnet50_predecoded(
            steps, batch=args.batch or 64,
            image_size=args.image_size or 224)
    else:
        result = bench_resnet50(steps, batch=args.batch or 128,
                                image_size=args.image_size or 224,
                                with_listener=args.with_listener)
    emit(result)
    finish()


if __name__ == "__main__":
    main()
