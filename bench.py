#!/usr/bin/env python
"""Benchmark entry point (driver contract): prints ONE JSON line
{"metric", "value", "unit", "vs_baseline"}.

Current flagship config: LeNet/MNIST training throughput via
MultiLayerNetwork.fit() on the default device (TPU under the driver;
BASELINE.json configs[0]). vs_baseline compares against the reference-shaped
CPU measurement recorded in BASELINE.md (the reference publishes no numbers —
SURVEY.md §6 — so the CPU run of this same config is the baseline ledger row).

Usage: python bench.py [--config lenet] [--steps N]
"""

import argparse
import json
import sys
import time

import numpy as np

# Baseline ledger (see BASELINE.md "Measured" table). The LeNet row is this
# same config measured with JAX_PLATFORMS=cpu on the build machine.
BASELINES = {
    "lenet_mnist_train": {"value": None, "unit": "images/sec"},  # filled below
}
# Measured 2026-07-29 on the build container CPU (see BASELINE.md):
BASELINES["lenet_mnist_train"]["value"] = 1470.0
# ResNet-50 training baseline: the north-star targets "match nd4j-cuda on
# V100"; the reference publishes no numbers (SURVEY.md §6), so the planning
# anchor from BASELINE.md is used: V100 fp32 ≈ 390 img/s.
BASELINES["resnet50_imagenet_train"] = {"value": 390.0, "unit": "images/sec"}


def bench_resnet50(steps: int, batch: int = 64, image_size: int = 224) -> dict:
    import jax
    import numpy as np

    from deeplearning4j_tpu.data import DataSet
    from deeplearning4j_tpu.models import ResNet50

    from deeplearning4j_tpu.nn.graph import ComputationGraph

    model = ResNet50(num_classes=1000, image_size=image_size).init()
    # bf16 compute on the MXU, fp32 master params
    model.conf.global_conf.compute_dtype = "bfloat16"

    rng = np.random.RandomState(0)
    x = rng.randn(batch, 3, image_size, image_size).astype(np.float32)
    y = np.eye(1000, dtype=np.float32)[rng.randint(0, 1000, batch)]
    ds = DataSet(x, y)

    model.fit(ds, epochs=1)  # warmup/compile
    jax.block_until_ready(model._params)  # drain warmup before starting clock
    t0 = time.perf_counter()
    for _ in range(steps):
        model.fit(ds, epochs=1)
    jax.block_until_ready(model._params)
    dt = time.perf_counter() - t0
    return {"metric": "resnet50_imagenet_train", "value": steps * batch / dt,
            "unit": "images/sec"}


def bench_lenet(steps: int) -> dict:
    import jax

    from deeplearning4j_tpu.data import MnistDataSetIterator
    from deeplearning4j_tpu.learning import Nesterovs
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import layers as L

    batch = 128
    conf = (NeuralNetConfiguration.builder()
            .seed(123)
            .updater(Nesterovs(learning_rate=0.01, momentum=0.9))
            .activation("relu")
            .weight_init("xavier")
            .list()
            .layer(L.ConvolutionLayer(n_out=20, kernel_size=(5, 5)))
            .layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(L.ConvolutionLayer(n_out=50, kernel_size=(5, 5)))
            .layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(L.DenseLayer(n_out=500))
            .layer(L.OutputLayer(n_out=10, loss="mcxent", activation="softmax"))
            .set_input_type(InputType.convolutional(28, 28, 1))
            .build())
    model = MultiLayerNetwork(conf).init()
    it = MnistDataSetIterator(batch_size=batch, train=True,
                              num_examples=batch * max(steps, 8), flatten=False)
    # trim to full batches: a trailing partial batch would retrace the train
    # step inside the timed region and skew the denominator
    n_batches = it.total_examples() // batch
    it.features = it.features[:n_batches * batch]
    it.labels = it.labels[:n_batches * batch]

    # warmup: first fit compiles the train-step module
    warm = MnistDataSetIterator(batch_size=batch, train=True, num_examples=batch * 2,
                                flatten=False)
    model.fit(warm, epochs=1)

    t0 = time.perf_counter()
    model.fit(it, epochs=1)
    # block on final params so the clock includes all device work
    jax.block_until_ready(model._params)
    dt = time.perf_counter() - t0
    imgs_per_sec = n_batches * batch / dt
    return {"metric": "lenet_mnist_train", "value": imgs_per_sec,
            "unit": "images/sec"}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", default="resnet50", choices=["lenet", "resnet50"])
    parser.add_argument("--steps", type=int, default=None)
    parser.add_argument("--batch", type=int, default=64)
    args = parser.parse_args()

    if args.config == "lenet":
        result = bench_lenet(args.steps or 64)
    else:
        result = bench_resnet50(args.steps or 20, batch=args.batch)

    base = BASELINES.get(result["metric"], {}).get("value")
    result["vs_baseline"] = (result["value"] / base) if base else 1.0
    print(json.dumps({"metric": result["metric"],
                      "value": round(result["value"], 2),
                      "unit": result["unit"],
                      "vs_baseline": round(result["vs_baseline"], 3)}))


if __name__ == "__main__":
    main()
