"""Weight initialization.

Reference: dl4j-nn ``org.deeplearning4j.nn.weights.WeightInit`` (+ IWeightInit
impls): XAVIER, XAVIER_UNIFORM, RELU (He), RELU_UNIFORM, LECUN_NORMAL,
LECUN_UNIFORM, NORMAL, UNIFORM, SIGMOID_UNIFORM, ZERO, ONES, IDENTITY,
VAR_SCALING_*. Fan computation follows the reference's ParamInitializer
conventions (dense W=[nIn,nOut]; conv W=[out,in,kH,kW]).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _fans(shape: Sequence[int]) -> Tuple[float, float]:
    shape = tuple(shape)
    if len(shape) == 2:                      # dense [nIn, nOut]
        return float(shape[0]), float(shape[1])
    if len(shape) == 4:                      # conv OIHW [out, in, kh, kw]
        rf = shape[2] * shape[3]
        return float(shape[1] * rf), float(shape[0] * rf)
    if len(shape) == 5:                      # conv3d OIDHW
        rf = shape[2] * shape[3] * shape[4]
        return float(shape[1] * rf), float(shape[0] * rf)
    if len(shape) == 1:
        return float(shape[0]), float(shape[0])
    n = int(np.prod(shape))
    return float(n), float(n)


def init_weights(key: jax.Array, shape: Sequence[int], scheme: str = "xavier",
                 dtype=jnp.float32, gain: float = 1.0) -> jnp.ndarray:
    scheme = scheme.lower()
    fan_in, fan_out = _fans(shape)
    shape = tuple(shape)
    if scheme == "zero":
        return jnp.zeros(shape, dtype)
    if scheme == "ones":
        return jnp.ones(shape, dtype)
    if scheme == "identity":
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("IDENTITY init needs a square 2d shape")
        return jnp.eye(shape[0], dtype=dtype)
    if scheme == "xavier":
        std = float(gain * np.sqrt(2.0 / (fan_in + fan_out)))
        return jax.random.normal(key, shape, dtype) * std
    if scheme == "xavier_uniform":
        lim = float(gain * np.sqrt(6.0 / (fan_in + fan_out)))
        return jax.random.uniform(key, shape, dtype, -lim, lim)
    if scheme == "xavier_fan_in":
        return jax.random.normal(key, shape, dtype) * float(gain * np.sqrt(1.0 / fan_in))
    if scheme in ("relu", "he", "he_normal"):
        return jax.random.normal(key, shape, dtype) * float(gain * np.sqrt(2.0 / fan_in))
    if scheme in ("relu_uniform", "he_uniform"):
        lim = float(gain * np.sqrt(6.0 / fan_in))
        return jax.random.uniform(key, shape, dtype, -lim, lim)
    if scheme == "lecun_normal":
        return jax.random.normal(key, shape, dtype) * float(gain * np.sqrt(1.0 / fan_in))
    if scheme == "lecun_uniform":
        lim = float(gain * np.sqrt(3.0 / fan_in))
        return jax.random.uniform(key, shape, dtype, -lim, lim)
    if scheme == "normal":
        return jax.random.normal(key, shape, dtype) * float(gain / np.sqrt(fan_in))
    if scheme == "uniform":
        lim = float(gain * np.sqrt(1.0 / fan_in))
        return jax.random.uniform(key, shape, dtype, -lim, lim)
    if scheme == "sigmoid_uniform":
        lim = float(gain * 4.0 * np.sqrt(6.0 / (fan_in + fan_out)))
        return jax.random.uniform(key, shape, dtype, -lim, lim)
    if scheme == "var_scaling_normal_fan_in":
        return jax.random.normal(key, shape, dtype) * float(gain * np.sqrt(1.0 / fan_in))
    if scheme == "var_scaling_normal_fan_out":
        return jax.random.normal(key, shape, dtype) * float(gain * np.sqrt(1.0 / fan_out))
    if scheme == "var_scaling_normal_fan_avg":
        return jax.random.normal(key, shape, dtype) * float(gain * np.sqrt(2.0 / (fan_in + fan_out)))
    raise ValueError(f"unknown weight init {scheme!r}")
