"""ComputationGraph — named-vertex DAG models (ResNet-50 et al).

Reference: dl4j-nn ``org.deeplearning4j.nn.graph.ComputationGraph`` (~4.5k LoC)
+ ``conf.ComputationGraphConfiguration.GraphBuilder`` + vertex impls
``nn.graph.vertex.impl.*`` (SURVEY.md §2.3, §3.2). The reference executes
~2000 JNI-dispatched ops per ResNet-50 iteration; here the topologically-
sorted vertex walk is traced ONCE and the whole iteration (fwd+bwd+updater)
compiles to a single XLA module (SURVEY.md §7.1.1).

Vertices: Merge, ElementWise (add/sub/mul/avg/max), Subset, Scale, Shift,
L2Normalize, Stack, Unstack, Preprocessor — reference ``conf/graph/*``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..common import xprof
from ..common.profiler import OpProfiler
from ..data import pipeline as _pipe
from ..data.dataset import DataSet, MultiDataSet
from ..ndarray.ndarray import NDArray
from ..ndarray.rng import get_random
from .conf import layers as L
from .conf.builder import (GlobalConf, MultiLayerConfiguration, _deser_obj,
                           _ser_obj, remat_wrap)
from .conf.inputs import CNNFlatInput, CNNInput, FFInput, InputType, RNNInput, cnn_to_ff, flat_to_cnn


# --- graph vertices (reference conf/graph/*) ---------------------------------


@dataclass
class GraphVertex:
    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def apply(self, *inputs):
        raise NotImplementedError


@dataclass
class MergeVertex(GraphVertex):
    """Concat along the feature/channel dim (reference MergeVertex)."""

    def output_type(self, *ts):
        t0 = ts[0]
        if isinstance(t0, CNNInput):
            return CNNInput(sum(t.channels for t in ts), t0.height, t0.width)
        if isinstance(t0, FFInput):
            return FFInput(sum(t.size for t in ts))
        if isinstance(t0, RNNInput):
            return RNNInput(sum(t.size for t in ts), t0.timesteps)
        raise ValueError(f"cannot merge {ts}")

    def apply(self, *inputs):
        axis = 1 if inputs[0].ndim == 4 else -1
        return jnp.concatenate(inputs, axis=axis)


@dataclass
class ElementWiseVertex(GraphVertex):
    """reference ElementWiseVertex.Op: Add/Subtract/Product/Average/Max."""

    op: str = "add"

    def apply(self, *inputs):
        op = self.op.lower()
        if op == "add":
            out = inputs[0]
            for v in inputs[1:]:
                out = out + v
            return out
        if op == "subtract":
            if len(inputs) != 2:
                raise ValueError(
                    f"ElementWiseVertex(subtract) needs exactly 2 inputs, got {len(inputs)}")
            return inputs[0] - inputs[1]
        if op in ("product", "mul"):
            out = inputs[0]
            for v in inputs[1:]:
                out = out * v
            return out
        if op in ("average", "avg"):
            return sum(inputs) / len(inputs)
        if op == "max":
            out = inputs[0]
            for v in inputs[1:]:
                out = jnp.maximum(out, v)
            return out
        if op == "min":
            out = inputs[0]
            for v in inputs[1:]:
                out = jnp.minimum(out, v)
            return out
        raise ValueError(f"unknown elementwise op {self.op!r}")


@dataclass
class DotProductVertex(GraphVertex):
    """Keras functional ``Dot`` merge (round-5 Keras-import tail): batched
    dot of two FF inputs over the feature axis, optionally L2-normalized
    (cosine proximity). Output is [B, 1]."""

    normalize: bool = False

    def output_type(self, *ts):
        if len(ts) != 2 or not all(isinstance(t, FFInput) for t in ts):
            raise ValueError("DotProductVertex needs two FF inputs")
        if ts[0].size != ts[1].size:
            raise ValueError(
                f"DotProductVertex inputs differ: {ts[0].size} vs "
                f"{ts[1].size}")
        return FFInput(1)

    def apply(self, a, b):
        if self.normalize:
            a = a / jnp.maximum(jnp.linalg.norm(a, axis=-1, keepdims=True),
                                1e-12)
            b = b / jnp.maximum(jnp.linalg.norm(b, axis=-1, keepdims=True),
                                1e-12)
        return jnp.sum(a * b, axis=-1, keepdims=True)


@dataclass
class SubsetVertex(GraphVertex):
    """Feature-dim slice [from, to] inclusive (reference SubsetVertex)."""

    from_idx: int = 0
    to_idx: int = 0

    def output_type(self, *ts):
        n = self.to_idx - self.from_idx + 1
        t = ts[0]
        if isinstance(t, FFInput):
            return FFInput(n)
        if isinstance(t, CNNInput):
            return CNNInput(n, t.height, t.width)
        if isinstance(t, RNNInput):
            return RNNInput(n, t.timesteps)
        raise ValueError(f"subset of {t}")

    def apply(self, *inputs):
        x = inputs[0]
        sl = slice(self.from_idx, self.to_idx + 1)
        if x.ndim == 4:
            return x[:, sl]
        return x[..., sl]


@dataclass
class ScaleVertex(GraphVertex):
    scale: float = 1.0

    def apply(self, *inputs):
        return inputs[0] * self.scale


@dataclass
class ShiftVertex(GraphVertex):
    shift: float = 0.0

    def apply(self, *inputs):
        return inputs[0] + self.shift


@dataclass
class L2NormalizeVertex(GraphVertex):
    eps: float = 1e-8

    def apply(self, *inputs):
        x = inputs[0]
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=tuple(range(1, x.ndim)),
                                keepdims=True))
        return x / jnp.maximum(norm, self.eps)


@dataclass
class StackVertex(GraphVertex):
    """Stack along batch dim (reference StackVertex)."""

    def apply(self, *inputs):
        return jnp.concatenate(inputs, axis=0)


@dataclass
class UnstackVertex(GraphVertex):
    from_idx: int = 0
    stack_size: int = 1

    def apply(self, *inputs):
        x = inputs[0]
        n = x.shape[0] // self.stack_size
        return x[self.from_idx * n:(self.from_idx + 1) * n]


@dataclass
class ReshapeVertex(GraphVertex):
    shape: Tuple[int, ...] = ()

    def apply(self, *inputs):
        return inputs[0].reshape((inputs[0].shape[0],) + tuple(self.shape))


# register vertex dataclasses with the config serde (builder._CLASSES)
from .conf.builder import _CLASSES as _SERDE_CLASSES  # noqa: E402

for _v in (GraphVertex, MergeVertex, ElementWiseVertex, SubsetVertex, ScaleVertex,
           ShiftVertex, L2NormalizeVertex, StackVertex, UnstackVertex, ReshapeVertex):
    _SERDE_CLASSES[_v.__name__] = _v


# --- graph node wiring -------------------------------------------------------


@dataclass
class _Node:
    name: str
    kind: str                       # "input" | "layer" | "vertex"
    layer: Optional[L.Layer] = None
    vertex: Optional[GraphVertex] = None
    inputs: List[str] = field(default_factory=list)
    preprocessors: Dict[int, Any] = field(default_factory=dict)  # per-input adapters


class ComputationGraphConfiguration:
    def __init__(self, global_conf: GlobalConf):
        self.global_conf = global_conf
        self.network_inputs: List[str] = []
        self.network_outputs: List[str] = []
        self.nodes: Dict[str, _Node] = {}
        self.order: List[str] = []
        self.input_types: Dict[str, InputType] = {}
        self.node_output_types: Dict[str, InputType] = {}

    @staticmethod
    def graph_builder(builder=None) -> "GraphBuilder":
        from .conf.builder import Builder

        b = builder._conf if builder is not None else GlobalConf()
        return GraphBuilder(b)

    # --- shape inference ------------------------------------------------
    def set_input_types(self, *types: InputType) -> None:
        assert len(types) == len(self.network_inputs), "one InputType per input"
        self.input_types = dict(zip(self.network_inputs, types))
        self.node_output_types = {}
        for name in self.order:
            node = self.nodes[name]
            if node.kind == "input":
                t = self.input_types[name]
                if isinstance(t, CNNFlatInput):
                    node.preprocessors[0] = flat_to_cnn(t)
                    t = node.preprocessors[0].out_type
                self.node_output_types[name] = t
                continue
            in_types = [self.node_output_types[i] for i in node.inputs]
            if node.kind == "vertex":
                self.node_output_types[name] = node.vertex.output_type(*in_types)
                continue
            # layer node: insert CNN→FF adapter when needed (reference
            # automatic preprocessor insertion)
            t = in_types[0]
            ff_like = (L.DenseLayer, L.OutputLayer, L.ElementWiseMultiplicationLayer)
            if isinstance(t, CNNInput) and isinstance(node.layer, ff_like) \
                    and not isinstance(node.layer, L.RnnOutputLayer):
                node.preprocessors[0] = cnn_to_ff(t)
                t = node.preprocessors[0].out_type
            self.node_output_types[name] = node.layer.set_input_type(t)

    # --- serde -----------------------------------------------------------
    def to_json(self) -> str:
        import json

        return json.dumps({
            "format_version": 1,
            "global": _ser_obj(self.global_conf),
            "inputs": self.network_inputs,
            "outputs": self.network_outputs,
            "order": self.order,
            "nodes": [
                {"name": n.name, "kind": n.kind,
                 "layer": _ser_obj(n.layer) if n.layer else None,
                 "vertex": _ser_obj(n.vertex) if n.vertex else None,
                 "inputs": n.inputs}
                for n in (self.nodes[nm] for nm in self.order)
            ],
            "input_types": {k: _ser_obj(v) for k, v in self.input_types.items()},
        }, indent=2)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        import json

        d = json.loads(s)
        conf = ComputationGraphConfiguration(_deser_obj(d["global"]))
        conf.network_inputs = d["inputs"]
        conf.network_outputs = d["outputs"]
        for nd in d["nodes"]:
            node = _Node(nd["name"], nd["kind"],
                         _deser_obj(nd["layer"]) if nd["layer"] else None,
                         _deser_obj(nd["vertex"]) if nd["vertex"] else None,
                         nd["inputs"])
            conf.nodes[node.name] = node
            conf.order.append(node.name)
        if d.get("input_types"):
            conf.set_input_types(*[_deser_obj(v) for v in d["input_types"].values()])
        return conf


class GraphBuilder:
    """reference ComputationGraphConfiguration.GraphBuilder."""

    def __init__(self, global_conf: GlobalConf):
        self._conf = ComputationGraphConfiguration(global_conf)

    def add_inputs(self, *names: str) -> "GraphBuilder":
        for n in names:
            self._conf.network_inputs.append(n)
            self._conf.nodes[n] = _Node(n, "input")
            self._conf.order.append(n)
        return self

    addInputs = add_inputs

    def add_layer(self, name: str, layer: L.Layer, *inputs: str) -> "GraphBuilder":
        self._check_inputs(name, inputs)
        layer.name = name
        self._apply_defaults(layer)
        self._conf.nodes[name] = _Node(name, "layer", layer=layer, inputs=list(inputs))
        self._conf.order.append(name)
        return self

    addLayer = add_layer

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str) -> "GraphBuilder":
        self._check_inputs(name, inputs)
        self._conf.nodes[name] = _Node(name, "vertex", vertex=vertex, inputs=list(inputs))
        self._conf.order.append(name)
        return self

    addVertex = add_vertex

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._conf.network_outputs = list(names)
        return self

    setOutputs = set_outputs

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        self._pending_types = types
        return self

    setInputTypes = set_input_types

    def build(self) -> ComputationGraphConfiguration:
        if not self._conf.network_outputs:
            raise ValueError("set_outputs(...) required")
        for out in self._conf.network_outputs:
            if out not in self._conf.nodes:
                raise ValueError(f"unknown output node {out!r}")
        types = getattr(self, "_pending_types", None)
        if types:
            self._conf.set_input_types(*types)
        return self._conf

    def _check_inputs(self, name: str, inputs: Sequence[str]) -> None:
        if name in self._conf.nodes:
            raise ValueError(f"duplicate node name {name!r}")
        if not inputs:
            raise ValueError(f"node {name!r} needs at least one input")
        for i in inputs:
            if i not in self._conf.nodes:
                raise ValueError(f"node {name!r}: unknown input {i!r} "
                                 f"(declare nodes in topological order)")

    def _apply_defaults(self, l: L.Layer) -> None:
        from .conf.builder import apply_layer_defaults

        apply_layer_defaults(l, self._conf.global_conf)


class ComputationGraph:
    """Runtime twin of the configuration (reference ComputationGraph)."""

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self._params: Dict[str, Dict[str, jnp.ndarray]] = {}
        self._states: Dict[str, Dict[str, jnp.ndarray]] = {}
        self._updater_state = None
        self._initialized = False
        self._iteration = 0
        self._epoch = 0
        self._listeners: List[Any] = []
        self._telemetry = None
        self._fit_step = None
        self._chunk_step = None
        self._infer_fn = None
        self._score_dev = None

    @property
    def score_value(self) -> float:
        return float(self._score_dev) if self._score_dev is not None else float("nan")

    @score_value.setter
    def score_value(self, v) -> None:
        self._score_dev = v

    # ------------------------------------------------------------------
    def init(self, seed: Optional[int] = None) -> "ComputationGraph":
        if not self.conf.node_output_types:
            raise ValueError("configuration needs set_input_types(...) before init()")
        key = jax.random.PRNGKey(seed if seed is not None else self.conf.global_conf.seed)
        dtype = jnp.dtype(self.conf.global_conf.dtype)
        for name in self.conf.order:
            node = self.conf.nodes[name]
            if node.kind == "layer":
                key, sub = jax.random.split(key)
                self._params[name] = (node.layer.init_params(sub, dtype)
                                      if node.layer.has_params else {})
                self._states[name] = node.layer.init_state()
        self._initialized = True
        return self

    def set_listeners(self, *listeners) -> None:
        self._listeners = list(listeners)
        for lst in self._listeners:
            # checkpoint-style listeners snapshot their peers' state for
            # exact resume (see MultiLayerNetwork.set_listeners)
            bind = getattr(lst, "bind_group", None)
            if callable(bind):
                bind(self._listeners)
        from ..optimize.telemetry import config_for

        cfg = config_for(self._listeners)
        if cfg != self._telemetry:
            # in-graph telemetry is a build-time property of the jitted
            # step (see MultiLayerNetwork.set_listeners)
            self._telemetry = cfg
            self._fit_step = None
            self._chunk_step = None

    def set_remat_policy(self, policy) -> None:
        """Switch the rematerialization policy in place — a build-time
        property of the jitted step (see MultiLayerNetwork
        .set_remat_policy): exactly one rebuild on the next fit."""
        if policy == self.conf.global_conf.remat_policy:
            return
        self.conf.global_conf.remat_policy = policy
        self._fit_step = None
        self._chunk_step = None

    def num_params(self) -> int:
        return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(self._params))

    def params(self) -> NDArray:
        leaves = jax.tree.leaves(self._params)
        if not leaves:
            return NDArray(jnp.zeros((0,)))
        return NDArray(jnp.concatenate([l.ravel() for l in leaves]))

    # --- forward ---------------------------------------------------------
    def _epilogue_fusion_plan(self):
        """The resnet-block-tail chains ``BN(identity) →
        ElementWiseVertex(add, 2 inputs) → ActivationLayer(relu)`` that
        inference ``_forward`` collapses into one fused BN+residual+relu
        epilogue (ops/pallas_epilogue) when ``GlobalConf.fused_epilogue``
        is on. Conservative: every interior node must have exactly one
        consumer (the next link), no preprocessors on the add/act links,
        and neither interior node may be a network output — so skipping
        their dense materialization can never change any other node.
        Returns None when the knob is off or nothing matches; the chain
        falls back to the dense ops per call if the kernel's shape gate
        refuses at trace time."""
        if not getattr(self.conf.global_conf, "fused_epilogue", False):
            return None
        consumers: Dict[str, set] = {}
        for name in self.conf.order:
            for i in self.conf.nodes[name].inputs:
                consumers.setdefault(i, set()).add(name)
        outputs = set(self.conf.network_outputs)
        bn_nodes, add_nodes, act_nodes = set(), {}, {}
        for name in self.conf.order:
            node = self.conf.nodes[name]
            if (node.kind != "layer"
                    or not isinstance(node.layer, L.ActivationLayer)
                    or (node.layer.activation or "").lower() != "relu"
                    or len(node.inputs) != 1 or node.preprocessors):
                continue
            add_name = node.inputs[0]
            add_node = self.conf.nodes.get(add_name)
            if (add_node is None or add_node.kind != "vertex"
                    or not isinstance(add_node.vertex, ElementWiseVertex)
                    or add_node.vertex.op.lower() != "add"
                    or len(add_node.inputs) != 2
                    or add_name in outputs
                    or consumers.get(add_name) != {name}):
                continue
            if add_node.inputs[0] == add_node.inputs[1]:
                # relu(bn(x) + bn(x)): deferring the BN would starve the
                # "other" operand — leave the degenerate chain dense
                continue
            bn_name = None
            for cand, oth in (add_node.inputs, reversed(add_node.inputs)):
                bn = self.conf.nodes.get(cand)
                if (bn is not None and bn.kind == "layer"
                        and isinstance(bn.layer, L.BatchNormalization)
                        # honor a per-layer fused_epilogue=False opt-out
                        # even when the global knob is on
                        and bn.layer.fused_epilogue
                        and (bn.layer.activation
                             or "identity").lower() == "identity"
                        and cand not in outputs and cand not in bn_nodes
                        and consumers.get(cand) == {add_name}):
                    bn_name, other = cand, oth
                    break
            if bn_name is None:
                continue
            bn_nodes.add(bn_name)
            add_nodes[add_name] = (bn_name, other)
            act_nodes[name] = (bn_name, add_name)
        if not act_nodes:
            return None
        return {"bn": bn_nodes, "add": add_nodes, "act": act_nodes}

    def _forward(self, params, states, inputs: Dict[str, jnp.ndarray],
                 training: bool, rng, to_preout: bool = False):
        cd = self.conf.global_conf.compute_dtype
        if cd:
            ct = jnp.dtype(cd)
            cast = lambda a: (a.astype(ct)
                              if jnp.issubdtype(a.dtype, jnp.floating) else a)
            params = jax.tree.map(cast, params)
            inputs = {k: cast(v) for k, v in inputs.items()}
        acts: Dict[str, jnp.ndarray] = {}
        new_states = dict(states)
        out_set = set(self.conf.network_outputs)
        plan = None if training else self._epilogue_fusion_plan()
        pending_bn: Dict[str, Any] = {}
        pending_add: Dict[str, Any] = {}
        for name in self.conf.order:
            node = self.conf.nodes[name]
            if node.kind == "input":
                x = inputs[name]
                if 0 in node.preprocessors:
                    x = node.preprocessors[0](x)
                acts[name] = x
                continue
            if plan is not None and node.kind == "vertex" \
                    and name in plan["add"]:
                # fused-epilogue chain: defer the residual add to the relu
                bn_name, other = plan["add"][name]
                pending_add[name] = (pending_bn.pop(bn_name), acts[other])
                continue
            if plan is not None and name in plan["act"]:
                # the fused BN+residual+relu launch (rng split mirrors the
                # dense path's one-split-per-layer-node stream exactly)
                rng, sub = jax.random.split(rng)
                _, add_name = plan["act"][name]
                (xbn, bnp, bns, bnl), other = pending_add.pop(add_name)
                from ..ops.pallas_epilogue import bn_act

                y = bn_act(xbn, bns["mean"], bns["var"], bnp.get("gamma"),
                           bnp.get("beta"), epsilon=bnl.eps,
                           axis=1 if xbn.ndim == 4 else -1, act="relu",
                           residual=other)
                if y is None:
                    # shape gate refused: replay the dense chain verbatim
                    bn_out, _ = bnl.apply(bnp, xbn, bns, training, sub)
                    y, _ = node.layer.apply(params.get(name, {}),
                                            bn_out + other,
                                            states.get(name, {}),
                                            training, sub)
                acts[name] = y
                continue
            ins = [acts[i] for i in node.inputs]
            if node.kind == "vertex":
                acts[name] = node.vertex.apply(*ins)
                continue
            x = ins[0]
            if 0 in node.preprocessors:
                x = node.preprocessors[0](x)
            rng, sub = jax.random.split(rng)
            if plan is not None and name in plan["bn"]:
                # head of a fused chain: stash the raw input for the relu
                pending_bn[name] = (x, params.get(name, {}),
                                    states.get(name, {}), node.layer)
                continue
            if to_preout and name in out_set and isinstance(node.layer, (L.OutputLayer, L.LossLayer)):
                x = node.layer._maybe_dropout(x, training, sub)
                head_params = params.get(name, {})
                if cd:
                    # run the head matmul + downstream loss in fp32 (matches
                    # the MultiLayerNetwork mixed-precision policy)
                    f32 = lambda a: (a.astype(jnp.float32)
                                     if jnp.issubdtype(a.dtype, jnp.floating) else a)
                    head_params = jax.tree.map(f32, head_params)
                    x = f32(x)
                acts[name] = node.layer.pre_output(head_params, x)
            else:
                def run(lp, xx, st, k, _l=node.layer):
                    return _l.apply(lp, xx, st, training, k)

                if training:
                    # rematerialize this node's activations in backward
                    # per the configured policy (GlobalConf.remat_policy /
                    # legacy gradient_checkpointing); selective lists
                    # match on the vertex NAME here
                    run = remat_wrap(self.conf.global_conf, run,
                                     block=name)
                y, st = run(params.get(name, {}), x,
                            states.get(name, {}), sub)
                acts[name] = y
                if st:
                    new_states[name] = st
        return acts, new_states

    def output(self, *inputs, training: bool = False) -> List[NDArray]:
        self._check_init()
        feed = self._bind_inputs(inputs)
        if self._infer_fn is None:
            def infer(params, states, ins, key, train: bool):
                acts, _ = self._forward(params, states, ins, train, key)
                return tuple(acts[o] for o in self.conf.network_outputs)

            self._infer_fn = xprof.register_jit(
                "graph/infer", jax.jit(infer, static_argnames=("train",)),
                static_argnames=("train",))
        outs = self._infer_fn(self._params, self._states, feed,
                              get_random().next_key(), train=training)
        return [NDArray(o) for o in outs]

    def _bind_inputs(self, inputs) -> Dict[str, jnp.ndarray]:
        names = self.conf.network_inputs
        if len(inputs) == 1 and isinstance(inputs[0], dict):
            return {k: jnp.asarray(v.value if isinstance(v, NDArray) else v)
                    for k, v in inputs[0].items()}
        if len(inputs) != len(names):
            raise ValueError(f"expected {len(names)} inputs {names}, got {len(inputs)}")
        return {n: jnp.asarray(v.value if isinstance(v, NDArray) else v)
                for n, v in zip(names, inputs)}

    # --- loss ------------------------------------------------------------
    def _loss(self, params, states, inputs, labels: Dict[str, jnp.ndarray],
              masks, training, rng, w=None, w_denom=None):
        acts, new_states = self._forward(params, states, inputs, training, rng,
                                         to_preout=True)
        total = 0.0
        for out_name in self.conf.network_outputs:
            node = self.conf.nodes[out_name]
            if not isinstance(node.layer, (L.OutputLayer, L.LossLayer)):
                continue
            pre = acts[out_name]
            # under reduced-precision compute, reduce the loss in fp32; leave
            # fp64 runs (gradient checks) untouched
            if self.conf.global_conf.compute_dtype and \
                    jnp.issubdtype(pre.dtype, jnp.floating):
                pre = pre.astype(jnp.float32)
            mask = masks.get(out_name) if masks else None
            if w is None:
                total = total + node.layer.loss.compute_score(
                    labels[out_name], pre, node.layer.activation, mask,
                    average=True)
            else:
                # example-weighted mean (shape-stable batching, see
                # multilayer._loss): pad rows carry w=0 and the divisor is
                # the real example count
                from .multilayer import _fold_weights

                s = node.layer.loss.compute_score(
                    labels[out_name], pre, node.layer.activation,
                    _fold_weights(mask, w), average=False)
                total = total + s / (w_denom if w_denom is not None
                                     else jnp.maximum(jnp.sum(w), 1.0))
        gc = self.conf.global_conf
        reg = 0.0
        for lname, lp in params.items():
            layer = self.conf.nodes[lname].layer
            l1 = layer.l1 if layer.l1 is not None else gc.l1
            l2 = layer.l2 if layer.l2 is not None else gc.l2
            for pname, w in lp.items():
                if pname in ("b", "beta"):
                    continue
                if l2:
                    reg = reg + 0.5 * l2 * jnp.sum(jnp.square(w))
                if l1:
                    reg = reg + l1 * jnp.sum(jnp.abs(w))
        return total + reg, new_states

    def score(self, ds: Union[DataSet, MultiDataSet], training: bool = False) -> float:
        self._check_init()
        inputs, labels, masks = self._bind_dataset(ds)
        loss, _ = self._loss(self._params, self._states, inputs, labels, masks,
                             training, get_random().next_key())
        return float(loss)

    def compute_gradient_and_score(self, ds):
        self._check_init()
        inputs, labels, masks = self._bind_dataset(ds)
        key = jax.random.PRNGKey(0)

        def loss_fn(params):
            loss, _ = self._loss(params, self._states, inputs, labels, masks, False, key)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(self._params)
        self.score_value = float(loss)
        return grads, self.score_value

    def _bind_fit_batch(self, ds, w):
        """The fit-loop bind: the training tuple plus the bookkeeping
        only fit needs (PerformanceListener derives samples/sec from the
        bound batch size; evaluate() shares _bind_dataset without it)."""
        self._last_batch_size = ds.num_examples()
        return self._bind_dataset(ds) + (w,)

    def _bind_dataset(self, ds):
        in_names = self.conf.network_inputs
        out_names = [o for o in self.conf.network_outputs
                     if isinstance(self.conf.nodes[o].layer, (L.OutputLayer, L.LossLayer))]
        if isinstance(ds, MultiDataSet):
            inputs = {n: jnp.asarray(f.value) for n, f in zip(in_names, ds.features)}
            labels = {n: jnp.asarray(l.value) for n, l in zip(out_names, ds.labels)}
            masks = {}
            if ds.labels_masks:
                masks = {n: jnp.asarray(m.value)
                         for n, m in zip(out_names, ds.labels_masks) if m is not None}
            return inputs, labels, masks
        inputs = {in_names[0]: jnp.asarray(ds.features.value)}
        labels = {out_names[0]: jnp.asarray(ds.labels.value)}
        masks = {}
        if ds.labels_mask is not None:
            masks = {out_names[0]: jnp.asarray(ds.labels_mask.value)}
        return inputs, labels, masks

    # --- training --------------------------------------------------------
    def _fused_flat_plan(self):
        from .multilayer import _fused_flat_plan

        return _fused_flat_plan(self.conf, self._params)

    def _step_core(self):
        """Single train-step computation, shared by the per-step jit and
        the multi-step lax.scan dispatch (see multilayer._step_core)."""
        gc = self.conf.global_conf
        updater = gc.updater
        tele = self._telemetry
        fused_plan = self._fused_flat_plan()
        # backward-epilogue fusion gate — see multilayer._step_core
        flat_bwd = (fused_plan is not None and tele is None
                    and not gc.grad_normalization
                    and getattr(gc, "flat_backward", True))
        from ..learning import precision as _prec
        from ..optimize import telemetry as _tel
        from .multilayer import _apply_fused_flat

        def core(params, states, upd_state, inputs, labels, masks, key,
                 iteration, w):
            def loss_fn(p):
                loss, new_states = self._loss(p, states, inputs, labels, masks,
                                              True, key, w=w)
                return loss, new_states

            if flat_bwd:
                flat_params = fused_plan.flatten(params)
                (loss, new_states), flat_grads = jax.value_and_grad(
                    lambda fp: loss_fn(fused_plan.unflatten_diff(fp)),
                    has_aux=True)(flat_params)
                new_params, new_upd = _apply_fused_flat(
                    fused_plan, updater, flat_grads, upd_state, params,
                    iteration, key, flat_params=flat_params,
                    grads_flat=True)
            else:
                (loss, new_states), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                if gc.grad_normalization:
                    from .multilayer import _normalize_gradients

                    grads = _normalize_gradients(
                        grads, gc.grad_normalization,
                        gc.grad_norm_threshold)
                if fused_plan is not None:
                    new_params, new_upd = _apply_fused_flat(
                        fused_plan, updater, grads, upd_state, params,
                        iteration, key)
                else:
                    new_params, new_upd = _prec.apply_updater(
                        updater, grads, upd_state, params, iteration, key)
            if tele is None:
                return new_params, new_states, new_upd, loss
            # per-node stats in sorted node-name order (telemetry.groups)
            # graftlint: disable=donated-grad-escape -- in-graph read: the
            # telemetry path runs with grads_flat=False, so _apply_fused_flat
            # flattened a COPY and XLA keeps the traced dense tree alive
            aux = _tel.layer_stats(params, new_params, grads, loss)
            if tele.nan_guard:
                aux, new_params, new_states, new_upd = _tel.apply_nan_guard(
                    aux, new_params, params, new_states, states, new_upd,
                    upd_state)
            return new_params, new_states, new_upd, loss, aux

        return core

    def _build_fit_step(self):
        core = self._step_core()

        def step(params, states, upd_state, inputs, labels, masks, key,
                 iteration, w=None):
            OpProfiler.get().count("trace/graph_fit_step")
            return core(params, states, upd_state, inputs, labels, masks,
                        key, iteration, w)

        return xprof.register_jit(
            "graph/fit_step", jax.jit(step, donate_argnums=(0, 1, 2)),
            donate=(0, 1, 2))

    def _build_chunk_step(self):
        """steps_per_dispatch=K device loop (see multilayer)."""
        core = self._step_core()
        tele = self._telemetry

        def chunk(params, states, upd_state, inputs, labels, masks, keys,
                  iteration0, ws):
            OpProfiler.get().count("trace/graph_fit_chunk")

            def body(carry, inp):
                params, states, upd_state, it = carry
                ins, lbl, msk, k, w = inp
                out = core(params, states, upd_state, ins, lbl, msk, k, it, w)
                if tele is None:
                    params, states, upd_state, loss = out
                    return (params, states, upd_state, it + 1), loss
                params, states, upd_state, loss, aux = out
                return (params, states, upd_state, it + 1), (loss, aux)

            (params, states, upd_state, _), ys_out = jax.lax.scan(
                body, (params, states, upd_state, iteration0),
                (inputs, labels, masks, keys, ws))
            if tele is None:
                return params, states, upd_state, ys_out
            losses, auxes = ys_out
            return params, states, upd_state, losses, auxes

        return xprof.register_jit(
            "graph/fit_chunk", jax.jit(chunk, donate_argnums=(0, 1, 2)),
            donate=(0, 1, 2))

    def fit(self, data, epochs: int = 1, batch_size: Optional[int] = None,
            *, pad_partial: Optional[bool] = None,
            drop_remainder: bool = False, prefetch: int = 2,
            steps_per_dispatch: int = 1, host_prefetch: int = 0,
            resume_from: Optional[str] = None) -> None:
        """Training loop on the shared input/dispatch pipeline
        (data/pipeline.py): shape-stable padded batching with the example
        weight threaded into every output's loss, device placement issued
        ``prefetch`` batches ahead, and an opt-in ``steps_per_dispatch``
        lax.scan device loop. See MultiLayerNetwork.fit for knob docs,
        including ``resume_from`` (exact checkpoint resume)."""
        self._check_init()
        skip = self._begin_fit(resume_from)
        if self._updater_state is None:
            self._updater_state = self.conf.global_conf.updater.init(self._params)
        from ..learning.precision import note_state_bytes

        note_state_bytes(self._updater_state)
        if self._fit_step is None:
            self._fit_step = self._build_fit_step()
        if isinstance(data, (DataSet, MultiDataSet)) and batch_size is None:
            self._fit_serial(data, epochs, skip=skip)
            return
        if steps_per_dispatch > 1 and self._chunk_step is None:
            self._chunk_step = self._build_chunk_step()
        prof = OpProfiler.get()

        def on_epoch():
            self._epoch += 1
            self._steps_in_epoch = 0
            for lst in self._listeners:
                if hasattr(lst, "epoch_done"):
                    lst.epoch_done(self, self._epoch)

        _pipe.run_epochs(
            data, epochs, batch_size,
            pad_partial=True if pad_partial is None else pad_partial,
            drop_remainder=drop_remainder, prefetch=prefetch,
            steps_per_dispatch=steps_per_dispatch,
            bind=lambda ds, w: self._bind_fit_batch(ds, w),
            place=jax.device_put,
            dispatch_one=lambda b: self._dispatch_one(b, prof),
            dispatch_chunk=lambda g: self._dispatch_chunk(g, prof),
            stackable=_chunk_stackable, on_epoch=on_epoch,
            allow_multi=True, host_prefetch=host_prefetch, skip=skip)

    def _begin_fit(self, resume_from: Optional[str]):
        from ..util.checkpoint import begin_fit_cursor

        return begin_fit_cursor(self, resume_from,
                                listeners=self._listeners)

    def _dispatch_one(self, b, prof) -> None:
        inputs, labels, masks, w = b
        key = get_random().next_key()
        with prof.time_section("pipeline/dispatch"):
            out = self._fit_step(self._params, self._states,
                                 self._updater_state, inputs, labels, masks,
                                 key, jnp.asarray(self._iteration), w)
        _pipe.note_dispatch(self, self._listeners, out,
                            self._telemetry is not None)

    def _dispatch_chunk(self, group, prof) -> None:
        stack = lambda col: jax.tree.map(  # noqa: E731
            lambda *leaves: jnp.stack(leaves), *[b[col] for b in group])
        inputs, labels, masks = stack(0), stack(1), stack(2)
        ws = jnp.stack([b[3] for b in group])
        keys = jnp.stack([get_random().next_key() for _ in group])
        with prof.time_section("pipeline/dispatch"):
            out = self._chunk_step(self._params, self._states,
                                   self._updater_state, inputs, labels, masks,
                                   keys, jnp.asarray(self._iteration), ws)
        _pipe.note_dispatch(self, self._listeners, out,
                            self._telemetry is not None, len(group))

    def _fit_serial(self, data, epochs: int = 1, skip=None) -> None:
        skip_epochs, skip_steps = skip if skip is not None else (0, 0)
        for e in range(max(1, epochs)):
            if e < skip_epochs:
                for _ in _iter_graph_data(data):
                    pass
                continue
            to_skip = skip_steps if e == skip_epochs else 0
            for ds in _iter_graph_data(data):
                if to_skip:
                    to_skip -= 1
                    continue
                inputs, labels, masks = self._bind_dataset(ds)
                key = get_random().next_key()
                out = self._fit_step(self._params, self._states,
                                     self._updater_state, inputs, labels,
                                     masks, key,
                                     jnp.asarray(self._iteration))
                _pipe.note_dispatch(self, self._listeners, out,
                                    self._telemetry is not None)
            self._epoch += 1
            self._steps_in_epoch = 0
            for lst in self._listeners:
                if hasattr(lst, "epoch_done"):
                    lst.epoch_done(self, self._epoch)

    def evaluate(self, data):
        from ..eval.evaluation import Evaluation

        ev = Evaluation()
        for ds in _iter_graph_data(data):
            if isinstance(ds, MultiDataSet):
                out = self.output(*[f for f in ds.features])[0]
                ev.eval(ds.labels[0].to_numpy(), out.to_numpy())
            else:
                out = self.output(ds.features)[0]
                ev.eval(ds.labels.to_numpy(), out.to_numpy(),
                        ds.labels_mask.to_numpy() if ds.labels_mask is not None else None)
        return ev

    # --- persistence ------------------------------------------------------
    def save(self, path: str, save_updater: bool = False) -> None:
        from ..util.model_serializer import write_model

        write_model(self, path, save_updater)

    @staticmethod
    def load(path: str, load_updater: bool = False) -> "ComputationGraph":
        from ..util.model_serializer import restore_computation_graph

        return restore_computation_graph(path, load_updater)

    def summary(self) -> str:
        lines = [f"{'node':<28}{'kind':<10}{'out type':<34}{'params':<10}"]
        total = 0
        for name in self.conf.order:
            node = self.conf.nodes[name]
            n = (sum(int(np.prod(p.shape)) for p in jax.tree.leaves(self._params.get(name, {})))
                 if self._initialized else 0)
            total += n
            ot = self.conf.node_output_types.get(name, "?")
            kind = node.kind if node.kind != "layer" else type(node.layer).__name__
            lines.append(f"{name:<28}{kind[:24]:<10}{str(ot):<34}{n:<10}")
        lines.append(f"Total params: {total}")
        return "\n".join(lines)

    def _check_init(self):
        if not self._initialized:
            raise ValueError("call init() first")


def _chunk_stackable(group) -> bool:
    """Stacking precondition for multi-step dispatch: every batch in the
    chunk binds the same dict keys with the same array shapes."""
    def sig(b):
        def d(m):
            return tuple(sorted((k, tuple(v.shape)) for k, v in m.items()))

        return d(b[0]), d(b[1]), d(b[2]), tuple(b[3].shape)

    first = sig(group[0])
    return all(sig(b) == first for b in group[1:])


def _iter_graph_data(data):
    # one data protocol for serial and pipelined paths alike
    yield from _pipe.iter_datasets(data, None, allow_multi=True)
