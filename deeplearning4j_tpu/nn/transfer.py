"""Transfer learning: fine-tune / freeze / re-head an existing network.

Reference: dl4j-nn ``org.deeplearning4j.nn.transferlearning.
{TransferLearning, FineTuneConfiguration, TransferLearningHelper}``
(SURVEY.md §2.3): take a trained ``MultiLayerNetwork``, freeze the feature
extractor, swap/replace the head, override training hyper-parameters, and
keep every compatible weight.

TPU shape: frozen layers are wrapped in ``FrozenLayer`` — ``stop_gradient``
inside the ONE compiled train step, plus a post-updater restore, so frozen
params take exactly zero update (including weight decay) with no second
execution path. ``TransferLearningHelper`` gets the same shortcut the
reference uses: featurize once through the frozen bottom, then fit only the
unfrozen top on cached activations.
"""

from __future__ import annotations

import copy
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..common import xprof
from ..data.dataset import DataSet
from .conf import layers as L
from .conf.builder import (GlobalConf, MultiLayerConfiguration,
                           apply_layer_defaults)
from .multilayer import MultiLayerNetwork


class FineTuneConfiguration:
    """Hyper-parameter overrides applied to the copied network's global
    conf (reference: FineTuneConfiguration.Builder)."""

    class Builder:
        def __init__(self) -> None:
            self._over = {}

        def updater(self, u):
            self._over["updater"] = u
            return self

        def seed(self, s: int):
            self._over["seed"] = s
            return self

        def l1(self, v: float):
            self._over["l1"] = v
            return self

        def l2(self, v: float):
            self._over["l2"] = v
            return self

        def dropout(self, v: float):
            self._over["dropout"] = v
            return self

        def activation(self, a: str):
            self._over["activation"] = a
            return self

        def build(self) -> "FineTuneConfiguration":
            return FineTuneConfiguration(self._over)

    @staticmethod
    def builder() -> "FineTuneConfiguration.Builder":
        return FineTuneConfiguration.Builder()

    def __init__(self, overrides: dict):
        self.overrides = dict(overrides)

    def apply_to(self, gc: GlobalConf) -> None:
        for k, v in self.overrides.items():
            setattr(gc, k, v)


def _unwrap(layer: L.Layer) -> L.Layer:
    return layer.layer if isinstance(layer, L.FrozenLayer) else layer


class TransferLearning:
    class Builder:
        def __init__(self, model: MultiLayerNetwork):
            model._check_init()
            self._src = model
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._freeze_until: Optional[int] = None
            self._n_out_replace = {}          # idx -> (n_out, weight_init)
            self._remove_from = None          # keep layers [0, remove_from)
            self._added: List[L.Layer] = []

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, layer_idx: int):
            """Freeze layers 0..layer_idx inclusive (reference
            setFeatureExtractor)."""
            self._freeze_until = layer_idx
            return self

        def n_out_replace(self, layer_idx: int, n_out: int,
                          weight_init: str = "xavier"):
            """Change a layer's n_out and re-init it (+ the next layer's
            n_in re-infers; reference nOutReplace)."""
            self._n_out_replace[layer_idx] = (n_out, weight_init)
            return self

        def remove_output_layer(self):
            return self.remove_layers_from_output(1)

        def remove_layers_from_output(self, n: int):
            cur = self._remove_from if self._remove_from is not None \
                else len(self._src.layers)
            self._remove_from = max(0, cur - n)
            return self

        def add_layer(self, layer: L.Layer):
            self._added.append(layer)
            return self

        addLayer = add_layer

        def build(self) -> MultiLayerNetwork:
            src = self._src
            keep_until = self._remove_from if self._remove_from is not None \
                else len(src.layers)
            new_layers: List[L.Layer] = []
            reinit_idx = set()                 # new-net indices needing fresh params
            for i, layer in enumerate(src.layers[:keep_until]):
                lcopy = copy.deepcopy(_unwrap(layer))
                if i in self._n_out_replace:
                    n_out, wi = self._n_out_replace[i]
                    if not hasattr(lcopy, "n_out"):
                        raise ValueError(
                            f"layer {i} ({type(lcopy).__name__}) has no n_out")
                    lcopy.n_out = n_out
                    lcopy.weight_init = wi
                    reinit_idx.add(i)
                    if i + 1 < keep_until:
                        reinit_idx.add(i + 1)  # its n_in changes
                if self._freeze_until is not None and i <= self._freeze_until:
                    if i in reinit_idx:
                        raise ValueError(
                            f"layer {i} is both frozen and re-initialized")
                    lcopy = L.FrozenLayer(layer=lcopy)
                new_layers.append(lcopy)

            gc = copy.deepcopy(src.conf.global_conf)
            if self._fine_tune is not None:
                self._fine_tune.apply_to(gc)
            for layer in self._added:
                apply_layer_defaults(layer, gc)
                new_layers.append(layer)
                reinit_idx.add(len(new_layers) - 1)

            conf = MultiLayerConfiguration(gc, new_layers)
            conf.backprop_type = src.conf.backprop_type
            conf.tbptt_fwd_length = src.conf.tbptt_fwd_length
            conf.tbptt_back_length = src.conf.tbptt_back_length
            # n_in re-inference must start clean: deep-copied layers carry
            # their old n_in, which set_input_type overwrites in order
            conf.set_input_type(src.conf.input_type)
            net = MultiLayerNetwork(conf).init(gc.seed)

            # carry over weights for kept, un-reinitialized layers
            for i in range(min(keep_until, len(new_layers))):
                if i in reinit_idx:
                    continue
                src_p = src._params[i]
                dst_p = net._params[i]
                if {k: v.shape for k, v in src_p.items()} != \
                        {k: v.shape for k, v in dst_p.items()}:
                    raise ValueError(
                        f"layer {i} shape mismatch carrying weights over: "
                        f"{ {k: v.shape for k, v in src_p.items()} } vs "
                        f"{ {k: v.shape for k, v in dst_p.items()} }")
                # real copies — the source model's donating fit step must
                # not invalidate the transferred net's buffers
                net._params[i] = jax.tree.map(jnp.array, src_p)
                net._states[i] = jax.tree.map(jnp.array, src._states[i])
            return net

    @staticmethod
    def builder(model: MultiLayerNetwork) -> "TransferLearning.Builder":
        return TransferLearning.Builder(model)


class TransferLearningHelper:
    """Featurize-once training for frozen-bottom networks (reference:
    TransferLearningHelper.featurize / fitFeaturized)."""

    def __init__(self, model: MultiLayerNetwork,
                 frozen_until: Optional[int] = None):
        model._check_init()
        if frozen_until is None:
            frozen = [i for i, l in enumerate(model.layers)
                      if isinstance(l, L.FrozenLayer)]
            if not frozen:
                raise ValueError("model has no FrozenLayer layers; pass "
                                 "frozen_until explicitly")
            frozen_until = max(frozen)
        self.frozen_until = frozen_until
        self.model = model
        self._featurize_fn = None

    def featurize(self, ds: DataSet) -> DataSet:
        """Run the frozen bottom once; result feeds fit_featurized."""
        import jax

        model = self.model
        if self._featurize_fn is None:
            def bottom(params, states, x, key):
                params, x = model._cast_compute(params, x)
                for i, layer in enumerate(
                        model.layers[:self.frozen_until + 1]):
                    pre = model.conf.preprocessors.get(i)
                    if pre is not None:
                        x = pre(x)
                    key, sub = jax.random.split(key)
                    x, _ = layer.apply(params[i], x, states[i], False, sub)
                return x

            self._featurize_fn = xprof.register_jit("transfer/featurize",
                                                    jax.jit(bottom))
        feats = self._featurize_fn(model._params, model._states,
                                   jnp.asarray(ds.features.value),
                                   jax.random.PRNGKey(0))
        return DataSet(np.asarray(feats), ds.labels,
                       labels_mask=ds.labels_mask)

    def fit_featurized(self, ds: DataSet, epochs: int = 1) -> None:
        """Train ONLY the unfrozen top on featurized data (reference
        fitFeaturized builds the same headless sub-network)."""
        top = self._top_net()
        top.fit(ds, epochs=epochs)
        # write trained top params back into the full model
        for j, i in enumerate(range(self.frozen_until + 1,
                                    len(self.model.layers))):
            self.model._params[i] = top._params[j]
            self.model._states[i] = top._states[j]
        self.model._fit_step = None
        self.model._chunk_step = None
        self.model._infer_fn = None

    def _top_net(self) -> MultiLayerNetwork:
        model = self.model
        if getattr(self, "_top", None) is None:
            gc = copy.deepcopy(model.conf.global_conf)
            top_layers = [copy.deepcopy(_unwrap(l))
                          for l in model.layers[self.frozen_until + 1:]]
            conf = MultiLayerConfiguration(gc, top_layers)
            conf.set_input_type(
                model.conf.layer_output_types[self.frozen_until])
            net = MultiLayerNetwork(conf).init(gc.seed)
            for j, i in enumerate(range(self.frozen_until + 1,
                                        len(model.layers))):
                net._params[j] = jax.tree.map(jnp.array, model._params[i])
                net._states[j] = jax.tree.map(jnp.array, model._states[i])
            self._top = net
        return self._top

    def unfrozen_mln(self) -> MultiLayerNetwork:
        return self._top_net()
