"""Layer configurations + their pure-function runtime.

Reference: dl4j-nn ``org.deeplearning4j.nn.conf.layers.*`` (the ~60 config
classes, SURVEY.md §2.3) merged with their runtime twins in
``org.deeplearning4j.nn.layers.**``. The reference splits config (Jackson
beans) from runtime (INDArray code); here each dataclass carries both: the
config fields plus ``init_params`` / ``apply`` pure functions that trace into
the one compiled train-step module. Param layouts follow the reference
ParamInitializers: dense W=[nIn,nOut], conv W=[out,in,kH,kW] (OIHW),
bias=[nOut].

Every ``apply`` is functional: (params, x, state, training, rng) -> (y, state)
where ``state`` carries batchnorm running stats (the only stateful layer).
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.registry import get_op
from ..activations import activation_fn
from ..losses import ILossFunction, LossMCXENT, loss_from_name
from ..weights import init_weights
from .inputs import CNNInput, FFInput, InputType, RNNInput


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


# --- trace-time dropout-rate override (fleet hyperparameter sweeps) --------
#
# A vmapped model population (parallel.fleet) sweeps the INPUT-dropout
# rate per member by threading a traced scalar through the one compiled
# step. The rate cannot live on the layer dataclass (it is a Python float
# baked at trace time), so the fleet core installs the traced value here
# for the duration of its loss trace; ``_maybe_dropout`` picks it up.
# Per-thread (concurrent traces stay independent) and trace-time only —
# a compiled step never reads it again. The gate (is dropout configured
# at all?) stays on the layer's own Python float, so only layers that
# already drop out participate in the sweep.
_DROPOUT_OVERRIDE = threading.local()


@contextlib.contextmanager
def dropout_rate_override(rate):
    """Install a traced input-dropout RATE override for every
    dropout-configured layer traced inside the block. The value must be
    float64 (weak-Python-float matching under x64) for an override equal
    to the configured rate to be bitwise identical."""
    prev = getattr(_DROPOUT_OVERRIDE, "rate", None)
    _DROPOUT_OVERRIDE.rate = rate
    try:
        yield
    finally:
        _DROPOUT_OVERRIDE.rate = prev


@dataclass
class Layer:
    """Base layer config. Fields that default to None inherit the network's
    global defaults (NeuralNetConfiguration.Builder contract)."""

    name: Optional[str] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    # Input dropout RATE (fraction dropped). None = inherit the builder's
    # global dropout; 0.0 = explicitly disabled.
    dropout: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None

    # filled by the builder
    n_in: Optional[int] = None
    # post-update weight projections (reference api.layers.constraint.*;
    # applied by the fit step after the updater)
    constraints: Optional[list] = None
    # training-time param perturbation (reference conf.weightnoise.*;
    # applied by the network before apply())
    weight_noise: Optional[Any] = None

    def set_input_type(self, input_type: InputType) -> InputType:
        """Infer nIn from the incoming type; return this layer's output type."""
        return input_type

    def init_params(self, key: jax.Array, dtype=jnp.float32) -> Dict[str, jnp.ndarray]:
        return {}

    def init_state(self) -> Dict[str, jnp.ndarray]:
        return {}

    def apply(self, params, x, state, training: bool, rng):
        raise NotImplementedError

    def _maybe_dropout(self, x, training: bool, rng):
        if training and self.dropout and self.dropout > 0.0:
            rate = getattr(_DROPOUT_OVERRIDE, "rate", None)
            if rate is None:
                rate = self.dropout
            return get_op("dropout").fn(x, rng, rate=rate)
        return x

    @property
    def has_params(self) -> bool:
        return True

    # -- per-timestep feature masking (reference: Layer.setMaskArray /
    # feedForwardMaskArray; SURVEY §5.7 masking row) --------------------
    def apply_masked(self, params, x, state, training, rng, fmask):
        """Forward with a [B, T] feature mask (1 = real step). Default:
        mask-oblivious layers ignore it; recurrent/attention layers
        override to zero padded steps / mask attention keys."""
        return self.apply(params, x, state, training, rng)

    # -- streaming/truncated-BPTT state (reference: BaseRecurrentLayer
    # stateMap / tBpttStateMap) -----------------------------------------
    def is_rnn(self) -> bool:
        return False

    def init_rnn_state(self, batch: int, dtype=jnp.float32):
        """Zero carry for apply_rnn; None for stateless layers."""
        return None

    def apply_rnn(self, params, x, rnn_state, state, training, rng):
        """Forward one time chunk from an explicit recurrent carry.
        Returns (y, new_rnn_state, new_state)."""
        y, st = self.apply(params, x, state, training, rng)
        return y, rnn_state, st


@dataclass
class DenseLayer(Layer):
    """Reference conf.layers.DenseLayer → layers.feedforward.dense."""

    n_out: int = 0
    has_bias: bool = True

    def set_input_type(self, input_type):
        if isinstance(input_type, FFInput):
            self.n_in = input_type.size
        else:
            raise ValueError(f"DenseLayer needs FF input, got {input_type}")
        return FFInput(self.n_out)

    def init_params(self, key, dtype=jnp.float32):
        kw, _ = jax.random.split(key)
        p = {"W": init_weights(kw, (self.n_in, self.n_out),
                               self.weight_init or "xavier", dtype)}
        if self.has_bias:
            p["b"] = jnp.zeros((self.n_out,), dtype)
        return p

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        out = x @ params["W"]
        if self.has_bias:
            out = out + params["b"]
        return activation_fn(self.activation or "identity")(out), state


@dataclass
class ConvolutionLayer(Layer):
    """Reference conf.layers.ConvolutionLayer (2D). W=[out,in,kH,kW]."""

    n_out: int = 0
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Union[Tuple[int, int], str] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: str = "truncate"   # truncate | same (reference ConvolutionMode)
    has_bias: bool = True

    def _padding(self):
        return "SAME" if self.convolution_mode.lower() == "same" else self.padding

    def set_input_type(self, input_type):
        if not isinstance(input_type, CNNInput):
            raise ValueError(f"ConvolutionLayer needs CNN input, got {input_type}")
        self.n_in = input_type.channels
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        dh, dw = _pair(self.dilation)
        if self.convolution_mode.lower() == "same":
            oh = -(-input_type.height // sh)
            ow = -(-input_type.width // sw)
        else:
            ph, pw = _pair(self.padding) if not isinstance(self.padding, str) else (0, 0)
            eff_kh, eff_kw = (kh - 1) * dh + 1, (kw - 1) * dw + 1
            oh = (input_type.height + 2 * ph - eff_kh) // sh + 1
            ow = (input_type.width + 2 * pw - eff_kw) // sw + 1
        return CNNInput(self.n_out, oh, ow)

    def init_params(self, key, dtype=jnp.float32):
        kh, kw = _pair(self.kernel_size)
        p = {"W": init_weights(key, (self.n_out, self.n_in, kh, kw),
                               self.weight_init or "xavier", dtype)}
        if self.has_bias:
            p["b"] = jnp.zeros((self.n_out,), dtype)
        return p

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        out = get_op("conv2d").fn(x, params["W"], params.get("b"),
                                  strides=_pair(self.stride), padding=self._padding(),
                                  dilation=_pair(self.dilation))
        return activation_fn(self.activation or "identity")(out), state


@dataclass
class Deconvolution2D(ConvolutionLayer):
    """Reference conf.layers.Deconvolution2D. W=[in,out,kH,kW]."""

    def set_input_type(self, input_type):
        if not isinstance(input_type, CNNInput):
            raise ValueError("Deconvolution2D needs CNN input")
        self.n_in = input_type.channels
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        if self.convolution_mode.lower() == "same":
            oh, ow = input_type.height * sh, input_type.width * sw
        else:
            ph, pw = _pair(self.padding) if not isinstance(self.padding, str) else (0, 0)
            oh = sh * (input_type.height - 1) + kh - 2 * ph
            ow = sw * (input_type.width - 1) + kw - 2 * pw
        return CNNInput(self.n_out, oh, ow)

    def init_params(self, key, dtype=jnp.float32):
        kh, kw = _pair(self.kernel_size)
        p = {"W": init_weights(key, (self.n_in, self.n_out, kh, kw),
                               self.weight_init or "xavier", dtype)}
        if self.has_bias:
            p["b"] = jnp.zeros((self.n_out,), dtype)
        return p

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        out = get_op("deconv2d").fn(x, params["W"], params.get("b"),
                                    strides=_pair(self.stride), padding=self._padding())
        return activation_fn(self.activation or "identity")(out), state


@dataclass
class DepthwiseConvolution2D(ConvolutionLayer):
    """Reference conf.layers.DepthwiseConvolution2D. W=[mult,C,kH,kW]."""

    depth_multiplier: int = 1

    def set_input_type(self, input_type):
        out_type = ConvolutionLayer.set_input_type(self, input_type)
        return CNNInput(self.n_in * self.depth_multiplier, out_type.height, out_type.width)

    def init_params(self, key, dtype=jnp.float32):
        kh, kw = _pair(self.kernel_size)
        p = {"W": init_weights(key, (self.depth_multiplier, self.n_in, kh, kw),
                               self.weight_init or "xavier", dtype)}
        if self.has_bias:
            p["b"] = jnp.zeros((self.n_in * self.depth_multiplier,), dtype)
        return p

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        out = get_op("depthwise_conv2d").fn(x, params["W"], params.get("b"),
                                            strides=_pair(self.stride),
                                            padding=self._padding(),
                                            dilation=_pair(self.dilation))
        return activation_fn(self.activation or "identity")(out), state


@dataclass
class SeparableConvolution2D(ConvolutionLayer):
    """Reference conf.layers.SeparableConvolution2D: depthwise + pointwise."""

    depth_multiplier: int = 1

    def init_params(self, key, dtype=jnp.float32):
        kd, kp = jax.random.split(key)
        kh, kw = _pair(self.kernel_size)
        p = {
            "dW": init_weights(kd, (self.depth_multiplier, self.n_in, kh, kw),
                               self.weight_init or "xavier", dtype),
            "pW": init_weights(kp, (self.n_out, self.n_in * self.depth_multiplier, 1, 1),
                               self.weight_init or "xavier", dtype),
        }
        if self.has_bias:
            p["b"] = jnp.zeros((self.n_out,), dtype)
        return p

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        out = get_op("sconv2d").fn(x, params["dW"], params["pW"], params.get("b"),
                                   strides=_pair(self.stride), padding=self._padding())
        return activation_fn(self.activation or "identity")(out), state


@dataclass
class SubsamplingLayer(Layer):
    """Reference conf.layers.SubsamplingLayer (max/avg/pnorm pooling)."""

    pooling_type: str = "max"
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: str = "truncate"
    pnorm: int = 2

    def set_input_type(self, input_type):
        if not isinstance(input_type, CNNInput):
            raise ValueError("SubsamplingLayer needs CNN input")
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        if self.convolution_mode.lower() == "same":
            oh = -(-input_type.height // sh)
            ow = -(-input_type.width // sw)
        else:
            ph, pw = _pair(self.padding)
            oh = (input_type.height + 2 * ph - kh) // sh + 1
            ow = (input_type.width + 2 * pw - kw) // sw + 1
        return CNNInput(input_type.channels, oh, ow)

    def apply(self, params, x, state, training, rng):
        pad = "SAME" if self.convolution_mode.lower() == "same" else _pair(self.padding)
        kind = self.pooling_type.lower()
        if kind == "max":
            out = get_op("maxpool2d").fn(x, _pair(self.kernel_size), _pair(self.stride), pad)
        elif kind in ("avg", "average"):
            out = get_op("avgpool2d").fn(x, _pair(self.kernel_size), _pair(self.stride), pad)
        elif kind == "pnorm":
            out = get_op("pnormpool2d").fn(x, _pair(self.kernel_size), _pair(self.stride),
                                           pad, pnorm=self.pnorm)
        else:
            raise ValueError(f"unknown pooling type {self.pooling_type!r}")
        return out, state

    @property
    def has_params(self):
        return False


@dataclass
class BatchNormalization(Layer):
    """Reference conf.layers.BatchNormalization: per-channel normalization with
    running-mean/var state (decay), trainable gamma/beta."""

    decay: float = 0.9
    eps: float = 1e-5
    lock_gamma_beta: bool = False
    # Fused inference epilogue (ops/pallas_epilogue): collapse inference
    # BN + relu/identity activation into one kernel. None → inherit
    # GlobalConf.fused_epilogue (cascaded by apply_layer_defaults).
    # Opt-in because the folded affine is a reassociation of the dense
    # ops (tolerance-bounded, not bitwise); shape-gated with a dense
    # fallback. Training mode is never fused (batch stats + hand VJP).
    fused_epilogue: Optional[bool] = None

    def set_input_type(self, input_type):
        if isinstance(input_type, CNNInput):
            self.n_in = input_type.channels
        elif isinstance(input_type, FFInput):
            self.n_in = input_type.size
        else:
            raise ValueError("BatchNormalization needs FF or CNN input")
        return input_type

    def init_params(self, key, dtype=jnp.float32):
        if self.lock_gamma_beta:
            return {}
        return {"gamma": jnp.ones((self.n_in,), dtype),
                "beta": jnp.zeros((self.n_in,), dtype)}

    def init_state(self):
        return {"mean": jnp.zeros((self.n_in,), jnp.float32),
                "var": jnp.ones((self.n_in,), jnp.float32)}

    def apply(self, params, x, state, training, rng):
        gamma = params.get("gamma")
        beta = params.get("beta")
        axis = 1 if x.ndim == 4 else -1
        if training:
            # fused training form: single-pass statistics + hand VJP (the
            # autodiff of the naive form costs extra full passes over the
            # activations — measured ~10% of a ResNet-50 step on v5e)
            out, mean, var = get_op("batchnorm_train").fn(
                x, gamma, beta, epsilon=self.eps, axis=axis,
                pivot=state["mean"])
            new_state = {
                "mean": self.decay * state["mean"] + (1 - self.decay) * mean,
                "var": self.decay * state["var"] + (1 - self.decay) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
            if self.fused_epilogue:
                from ...ops.pallas_epilogue import bn_act

                fused = bn_act(x, mean, var, gamma, beta, epsilon=self.eps,
                               axis=axis, act=self.activation)
                if fused is not None:
                    return fused, new_state
            out = get_op("batchnorm").fn(x, mean.astype(x.dtype),
                                         var.astype(x.dtype),
                                         gamma, beta, epsilon=self.eps, axis=axis)
        return activation_fn(self.activation or "identity")(out), new_state


@dataclass
class LocalResponseNormalization(Layer):
    n: int = 5
    k: float = 2.0
    alpha: float = 1e-4
    beta: float = 0.75

    def apply(self, params, x, state, training, rng):
        # DL4J applies alpha directly to the squared-window sum (no /n Caffe
        # rescale): out = x / (k + alpha*sum(x^2 over window))^beta
        out = get_op("lrn").fn(x, depth=self.n, bias=self.k,
                               alpha=self.alpha, beta=self.beta)
        return out, state

    @property
    def has_params(self):
        return False


@dataclass
class DropoutLayer(Layer):
    rate: float = 0.5

    def apply(self, params, x, state, training, rng):
        if training and self.rate > 0:
            return get_op("dropout").fn(x, rng, rate=self.rate), state
        return x, state

    @property
    def has_params(self):
        return False


@dataclass
class ActivationLayer(Layer):
    # Optional slope/shape parameter (reference ActivationLReLU/ELU take one);
    # forwarded to ops that accept an alpha (leakyrelu, elu).
    alpha: Optional[float] = None

    def apply(self, params, x, state, training, rng):
        act = (self.activation or "identity").lower()
        if self.alpha is not None and act in ("leakyrelu", "elu"):
            return get_op(act).fn(x, alpha=self.alpha), state
        return activation_fn(act)(x), state

    @property
    def has_params(self):
        return False


@dataclass
class PReLULayer(Layer):
    """Learned leak parameter, per-feature (reference PReLULayer)."""

    def set_input_type(self, input_type):
        if isinstance(input_type, FFInput):
            self.n_in = input_type.size
        elif isinstance(input_type, CNNInput):
            self.n_in = input_type.channels
        return input_type

    def init_params(self, key, dtype=jnp.float32):
        return {"alpha": jnp.zeros((self.n_in,), dtype)}

    def apply(self, params, x, state, training, rng):
        a = params["alpha"]
        if x.ndim == 4:
            a = a.reshape(1, -1, 1, 1)
        return get_op("prelu").fn(x, a), state


@dataclass
class Upsampling2D(Layer):
    size: Tuple[int, int] = (2, 2)

    def set_input_type(self, input_type):
        fh, fw = _pair(self.size)
        return CNNInput(input_type.channels, input_type.height * fh, input_type.width * fw)

    def apply(self, params, x, state, training, rng):
        return get_op("upsampling2d").fn(x, factor=_pair(self.size)), state

    @property
    def has_params(self):
        return False


@dataclass
class ZeroPaddingLayer(Layer):
    padding: Tuple[int, int, int, int] = (1, 1, 1, 1)  # top,bottom,left,right

    def set_input_type(self, input_type):
        t, b, l, r = self.padding
        return CNNInput(input_type.channels, input_type.height + t + b,
                        input_type.width + l + r)

    def apply(self, params, x, state, training, rng):
        t, b, l, r = self.padding
        return jnp.pad(x, ((0, 0), (0, 0), (t, b), (l, r))), state

    @property
    def has_params(self):
        return False


@dataclass
class Cropping2D(Layer):
    cropping: Tuple[int, int, int, int] = (0, 0, 0, 0)

    def set_input_type(self, input_type):
        t, b, l, r = self.cropping
        return CNNInput(input_type.channels, input_type.height - t - b,
                        input_type.width - l - r)

    def apply(self, params, x, state, training, rng):
        t, b, l, r = self.cropping
        h, w = x.shape[2], x.shape[3]
        return x[:, :, t:h - b, l:w - r], state

    @property
    def has_params(self):
        return False


@dataclass
class GlobalPoolingLayer(Layer):
    """Reference conf.layers.GlobalPoolingLayer: pools CNN spatial dims or RNN
    time dim (mask-aware) down to FF."""

    pooling_type: str = "max"

    def set_input_type(self, input_type):
        if isinstance(input_type, CNNInput):
            self._mode = "cnn"
            return FFInput(input_type.channels)
        if isinstance(input_type, RNNInput):
            self._mode = "rnn"
            return FFInput(input_type.size)
        from .inputs import CNN3DInput
        if isinstance(input_type, CNN3DInput):
            self._mode = "cnn3d"
            return FFInput(input_type.channels)
        raise ValueError("GlobalPoolingLayer needs CNN/CNN3D/RNN input")

    def apply(self, params, x, state, training, rng, mask=None):
        kind = self.pooling_type.lower()
        if x.ndim == 5:    # NCDHW
            axes = (2, 3, 4)
        elif x.ndim == 4:
            axes = (2, 3)
        else:  # [B, T, F]
            axes = (1,)
        if kind == "max":
            out = jnp.max(x, axis=axes)
        elif kind in ("avg", "average"):
            if mask is not None and x.ndim == 3:
                m = mask[..., None]
                out = jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1e-9)
            else:
                out = jnp.mean(x, axis=axes)
        elif kind == "sum":
            out = jnp.sum(x, axis=axes)
        elif kind == "pnorm":
            out = jnp.sum(jnp.abs(x) ** 2, axis=axes) ** 0.5
        else:
            raise ValueError(f"unknown pooling {self.pooling_type!r}")
        return out, state

    def apply_masked(self, params, x, state, training, rng, fmask):
        """Mask-aware time pooling (reference: masked GlobalPoolingLayer):
        padded steps are excluded from max/avg/sum."""
        if x.ndim != 3:
            return self.apply(params, x, state, training, rng)
        kind = self.pooling_type.lower()
        m = fmask[..., None].astype(x.dtype)
        if kind == "max":
            neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
            out = jnp.max(jnp.where(m > 0, x, neg), axis=1)
        elif kind in ("avg", "average"):
            out = jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1e-9)
        elif kind == "sum":
            out = jnp.sum(x * m, axis=1)
        elif kind == "pnorm":
            out = jnp.sum(jnp.abs(x * m) ** 2, axis=1) ** 0.5
        else:
            raise ValueError(f"unknown pooling {self.pooling_type!r}")
        return out, state

    @property
    def has_params(self):
        return False


# --- recurrent ---------------------------------------------------------------


@dataclass
class LSTM(Layer):
    """Reference conf.layers.LSTM (fused impl ≈ LSTMHelpers). Weight layout is
    the fused [nIn+nOut, 4*nOut] IFOG gemm (documented divergence from the
    reference's separate W/RW matrices — same math, one MXU matmul)."""

    n_out: int = 0

    def set_input_type(self, input_type):
        if not isinstance(input_type, RNNInput):
            raise ValueError("LSTM needs RNN input [B, T, F]")
        self.n_in = input_type.size
        return RNNInput(self.n_out, input_type.timesteps)

    def init_params(self, key, dtype=jnp.float32):
        w = init_weights(key, (self.n_in + self.n_out, 4 * self.n_out),
                         self.weight_init or "xavier", dtype)
        b = jnp.zeros((4 * self.n_out,), dtype)
        # forget-gate bias = 1 (reference forgetGateBiasInit default)
        b = b.at[self.n_out:2 * self.n_out].set(1.0)
        return {"W": w, "b": b}

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        ys, _ = get_op("lstm_layer").fn(x, params["W"], params["b"])
        act = self.activation
        if act and act.lower() not in ("tanh", "identity"):
            ys = activation_fn(act)(ys)
        return ys, state

    def apply_masked(self, params, x, state, training, rng, fmask):
        y, st = self.apply(params, x, state, training, rng)
        return y * fmask[:, :, None].astype(y.dtype), st

    def is_rnn(self):
        return True

    def init_rnn_state(self, batch, dtype=jnp.float32):
        z = jnp.zeros((batch, self.n_out), dtype)
        return (z, z)

    def apply_rnn(self, params, x, rnn_state, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        h0, c0 = rnn_state
        ys, (h, c) = get_op("lstm_layer").fn(x, params["W"], params["b"],
                                             h0=h0, c0=c0)
        act = self.activation
        if act and act.lower() not in ("tanh", "identity"):
            ys = activation_fn(act)(ys)
        return ys, (h, c), state


@dataclass
class GravesLSTM(LSTM):
    """Reference GravesLSTM (peepholes omitted — deprecated upstream; the
    non-peephole path is identical to LSTM)."""


@dataclass
class GRU(Layer):
    """GRU layer. ``reset_after=False`` is the reference gruCell form
    (reset applied before the recurrent matmul — libnd4j
    ``generic/recurrent/gruCell.cpp`` semantics); ``reset_after=True`` is
    the CuDNN/Keras form, provided so Keras h5 checkpoints import exactly
    (imports/keras_import.py)."""

    n_out: int = 0
    reset_after: bool = False

    def set_input_type(self, input_type):
        if not isinstance(input_type, RNNInput):
            raise ValueError("GRU needs RNN input [B, T, F]")
        self.n_in = input_type.size
        return RNNInput(self.n_out, input_type.timesteps)

    def init_params(self, key, dtype=jnp.float32):
        k1, k2, k3 = jax.random.split(key, 3)
        wi = self.weight_init or "xavier"
        p = {"W_ru": init_weights(k1, (self.n_in + self.n_out,
                                       2 * self.n_out), wi, dtype),
             "b_ru": jnp.zeros((2 * self.n_out,), dtype)}
        if self.reset_after:
            p["W_cx"] = init_weights(k2, (self.n_in, self.n_out), wi, dtype)
            p["W_ch"] = init_weights(k3, (self.n_out, self.n_out), wi,
                                     dtype)
            p["b_cx"] = jnp.zeros((self.n_out,), dtype)
            p["b_ch"] = jnp.zeros((self.n_out,), dtype)
        else:
            p["W_c"] = init_weights(k2, (self.n_in + self.n_out,
                                         self.n_out), wi, dtype)
            p["b_c"] = jnp.zeros((self.n_out,), dtype)
        return p

    def _run(self, params, x, h0=None):
        if self.reset_after:
            return get_op("gru_layer_ra").fn(
                x, params["W_ru"], params["W_cx"], params["W_ch"],
                params["b_ru"], params["b_cx"], params["b_ch"], h0=h0)
        return get_op("gru_layer").fn(x, params["W_ru"], params["W_c"],
                                      params["b_ru"], params["b_c"], h0=h0)

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        ys, _ = self._run(params, x)
        return ys, state

    def apply_masked(self, params, x, state, training, rng, fmask):
        y, st = self.apply(params, x, state, training, rng)
        return y * fmask[:, :, None].astype(y.dtype), st

    def is_rnn(self):
        return True

    def init_rnn_state(self, batch, dtype=jnp.float32):
        return jnp.zeros((batch, self.n_out), dtype)

    def apply_rnn(self, params, x, rnn_state, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        ys, h = self._run(params, x, h0=rnn_state)
        return ys, h, state


@dataclass
class SimpleRnn(Layer):
    n_out: int = 0

    def set_input_type(self, input_type):
        self.n_in = input_type.size
        return RNNInput(self.n_out, input_type.timesteps)

    def init_params(self, key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        return {
            "W": init_weights(k1, (self.n_in, self.n_out), self.weight_init or "xavier", dtype),
            "RW": init_weights(k2, (self.n_out, self.n_out), self.weight_init or "xavier", dtype),
            "b": jnp.zeros((self.n_out,), dtype),
        }

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        ys, _ = get_op("simple_rnn_layer").fn(
            x, params["W"], params["RW"], params["b"],
            activation=activation_fn(self.activation or "tanh"))
        return ys, state

    def apply_masked(self, params, x, state, training, rng, fmask):
        y, st = self.apply(params, x, state, training, rng)
        return y * fmask[:, :, None].astype(y.dtype), st

    def is_rnn(self):
        return True

    def init_rnn_state(self, batch, dtype=jnp.float32):
        return jnp.zeros((batch, self.n_out), dtype)

    def apply_rnn(self, params, x, rnn_state, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        ys, h = get_op("simple_rnn_layer").fn(
            x, params["W"], params["RW"], params["b"], h0=rnn_state,
            activation=activation_fn(self.activation or "tanh"))
        return ys, h, state


@dataclass
class Bidirectional(Layer):
    """Reference recurrent.Bidirectional wrapper: runs the wrapped recurrent
    layer forward + on the time-reversed sequence, merges by mode."""

    layer: Optional[Layer] = None
    mode: str = "concat"     # concat | add | mul | average

    def set_input_type(self, input_type):
        out = self.layer.set_input_type(input_type)
        if self.mode.lower() == "concat":
            return RNNInput(out.size * 2, out.timesteps)
        return out

    def init_params(self, key, dtype=jnp.float32):
        kf, kb = jax.random.split(key)
        return {"fwd": self.layer.init_params(kf, dtype),
                "bwd": self.layer.init_params(kb, dtype)}

    def apply(self, params, x, state, training, rng):
        fwd, _ = self.layer.apply(params["fwd"], x, {}, training, rng)
        bwd, _ = self.layer.apply(params["bwd"], jnp.flip(x, axis=1), {}, training, rng)
        bwd = jnp.flip(bwd, axis=1)
        mode = self.mode.lower()
        if mode == "concat":
            out = jnp.concatenate([fwd, bwd], axis=-1)
        elif mode == "add":
            out = fwd + bwd
        elif mode == "mul":
            out = fwd * bwd
        else:
            out = 0.5 * (fwd + bwd)
        return out, state


@dataclass
class SelfAttentionLayer(Layer):
    """Reference conf.layers.SelfAttentionLayer → libnd4j
    multi_head_dot_product_attention with Q=K=V=input.

    ``project_input=True`` learns Wq/Wk/Wv/Wo projections (required when
    n_heads > 1); otherwise raw single-head dot-product attention over the
    input and n_out must equal n_in. Input/output [B, T, F]; a feature mask
    masks attention KEYS, so padded timesteps receive no attention weight.
    """

    n_out: int = 0
    n_heads: int = 1
    head_size: Optional[int] = None
    project_input: bool = True

    def set_input_type(self, input_type):
        if not isinstance(input_type, RNNInput):
            raise ValueError("SelfAttentionLayer needs RNN input [B, T, F]")
        self.n_in = input_type.size
        if not self.project_input:
            if self.n_heads != 1:
                raise ValueError("project_input=False requires n_heads=1")
            self.n_out = self.n_in
        return RNNInput(self.n_out, input_type.timesteps)

    def _hs(self) -> int:
        return self.head_size or self.n_out // self.n_heads

    def init_params(self, key, dtype=jnp.float32):
        if not self.project_input:
            return {}
        hs = self._hs()
        ks = jax.random.split(key, 4)
        wi = self.weight_init or "xavier"
        return {
            "Wq": init_weights(ks[0], (self.n_in, self.n_heads * hs), wi, dtype),
            "Wk": init_weights(ks[1], (self.n_in, self.n_heads * hs), wi, dtype),
            "Wv": init_weights(ks[2], (self.n_in, self.n_heads * hs), wi, dtype),
            "Wo": init_weights(ks[3], (self.n_heads * hs, self.n_out), wi, dtype),
        }

    def _attend(self, params, q, kv, fmask):
        if self.project_input:
            return get_op("multi_head_dot_product_attention").fn(
                q, kv, kv, params["Wq"], params["Wk"], params["Wv"],
                params["Wo"], num_heads=self.n_heads, mask=fmask)
        m = fmask[:, None, :] if fmask is not None else None
        return get_op("dot_product_attention").fn(q, kv, kv, mask=m)

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        return self._attend(params, x, x, None), state

    def apply_masked(self, params, x, state, training, rng, fmask):
        x = self._maybe_dropout(x, training, rng)
        y = self._attend(params, x, x, fmask)
        return y * fmask[:, :, None].astype(y.dtype), state

    @property
    def has_params(self):
        return self.project_input


@dataclass
class LearnedSelfAttentionLayer(SelfAttentionLayer):
    """Reference conf.layers.LearnedSelfAttentionLayer: n_queries LEARNED
    query vectors attend over the sequence — output is a fixed-length
    [B, n_queries, n_out] regardless of input length (the attention-pooling
    trick the reference uses ahead of feed-forward heads)."""

    n_queries: int = 1

    def set_input_type(self, input_type):
        if not isinstance(input_type, RNNInput):
            raise ValueError("LearnedSelfAttentionLayer needs RNN input")
        self.n_in = input_type.size
        if not self.project_input:
            if self.n_heads != 1:
                raise ValueError("project_input=False requires n_heads=1")
            self.n_out = self.n_in
        return RNNInput(self.n_out, self.n_queries)

    def init_params(self, key, dtype=jnp.float32):
        kq, key = jax.random.split(key)
        p = super().init_params(key, dtype)
        p["Q"] = init_weights(kq, (self.n_queries, self.n_in),
                              self.weight_init or "xavier", dtype)
        return p

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        q = jnp.broadcast_to(params["Q"][None],
                             (x.shape[0],) + params["Q"].shape)
        return self._attend(params, q, x, None), state

    def apply_masked(self, params, x, state, training, rng, fmask):
        x = self._maybe_dropout(x, training, rng)
        q = jnp.broadcast_to(params["Q"][None],
                             (x.shape[0],) + params["Q"].shape)
        # keys masked; output timesteps are the learned queries (all real)
        return self._attend(params, q, x, fmask), state

    @property
    def has_params(self):
        return True


@dataclass
class RecurrentAttentionLayer(Layer):
    """Reference conf.layers.RecurrentAttentionLayer: per timestep,
    y_t = activation(Wx·x_t + Wr·a_t + b) where a_t is multi-head attention
    queried by the previous output y_{t-1} over the whole input sequence.
    The reference defines this via a SameDiff per-step loop; here the step
    is a ``lax.scan`` whose attention logits against the full sequence are
    one batched matmul per step."""

    n_out: int = 0
    n_heads: int = 1
    head_size: Optional[int] = None

    def set_input_type(self, input_type):
        if not isinstance(input_type, RNNInput):
            raise ValueError("RecurrentAttentionLayer needs RNN input")
        self.n_in = input_type.size
        return RNNInput(self.n_out, input_type.timesteps)

    def _hs(self) -> int:
        return self.head_size or self.n_out // self.n_heads

    def init_params(self, key, dtype=jnp.float32):
        hs = self._hs()
        ks = jax.random.split(key, 6)
        wi = self.weight_init or "xavier"
        return {
            "Wx": init_weights(ks[0], (self.n_in, self.n_out), wi, dtype),
            "Wr": init_weights(ks[1], (self.n_out, self.n_out), wi, dtype),
            "b": jnp.zeros((self.n_out,), dtype),
            "Wq": init_weights(ks[2], (self.n_out, self.n_heads * hs), wi, dtype),
            "Wk": init_weights(ks[3], (self.n_in, self.n_heads * hs), wi, dtype),
            "Wv": init_weights(ks[4], (self.n_in, self.n_heads * hs), wi, dtype),
            "Wo": init_weights(ks[5], (self.n_heads * hs, self.n_out), wi, dtype),
        }

    def _run(self, params, x, fmask):
        act = activation_fn(self.activation or "tanh")
        mha = get_op("multi_head_dot_product_attention").fn
        xT = jnp.swapaxes(x, 0, 1)                     # [T, B, F]
        y0 = jnp.zeros((x.shape[0], self.n_out), x.dtype)

        def step(y_prev, xt):
            a = mha(y_prev[:, None, :], x, x, params["Wq"], params["Wk"],
                    params["Wv"], params["Wo"], num_heads=self.n_heads,
                    mask=fmask)[:, 0]
            y = act(xt @ params["Wx"] + a @ params["Wr"] + params["b"])
            return y, y

        _, ys = jax.lax.scan(step, y0, xT)
        return jnp.swapaxes(ys, 0, 1)

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        return self._run(params, x, None), state

    def apply_masked(self, params, x, state, training, rng, fmask):
        x = self._maybe_dropout(x, training, rng)
        y = self._run(params, x, fmask)
        return y * fmask[:, :, None].astype(y.dtype), state


@dataclass
class LastTimeStep(Layer):
    """Reference recurrent.LastTimeStep wrapper: RNN [B,T,F] → FF [B,F]."""

    layer: Optional[Layer] = None

    def set_input_type(self, input_type):
        out = self.layer.set_input_type(input_type)
        return FFInput(out.size)

    def init_params(self, key, dtype=jnp.float32):
        return self.layer.init_params(key, dtype)

    def apply(self, params, x, state, training, rng):
        ys, state = self.layer.apply(params, x, state, training, rng)
        return ys[:, -1], state


# --- embeddings --------------------------------------------------------------


@dataclass
class EmbeddingLayer(Layer):
    """Reference conf.layers.EmbeddingLayer: int index [B] (or one-hot) → [B, nOut].

    ``table_sharding`` names a mesh axis to row-shard the table over
    (SURVEY §2.4 row 4 — the VoidParameterServer translation). When the
    layer runs inside a ``shard_map`` binding that axis (ParallelWrapper
    with ``model_axis``), lookups become masked-local-gather + psum and
    the gradient scatter touches only owned rows; outside any mesh the
    layer behaves exactly like the dense one."""

    n_out: int = 0
    table_sharding: Optional[str] = None

    def set_input_type(self, input_type):
        self.n_in = input_type.size  # vocab size
        return FFInput(self.n_out)

    def init_params(self, key, dtype=jnp.float32):
        return {"W": init_weights(key, (self.n_in, self.n_out),
                                  self.weight_init or "xavier", dtype)}

    def _lookup(self, W, idx):
        if self.table_sharding:
            from ...ops.embeddings import sharded_rows_lookup
            try:
                rows, _ = sharded_rows_lookup(W, idx, self.table_sharding)
                return rows
            except NameError:
                pass   # axis not bound: plain single-table lookup
        return jnp.take(W, idx, axis=0)

    def apply(self, params, x, state, training, rng):
        if jnp.issubdtype(x.dtype, jnp.floating) and x.ndim == 2 and x.shape[-1] == self.n_in:
            idx = jnp.argmax(x, axis=-1)  # one-hot form
        else:
            idx = x.astype(jnp.int32)
            if idx.ndim == 2 and idx.shape[-1] == 1:
                idx = idx[:, 0]
        out = self._lookup(params["W"], idx)
        return activation_fn(self.activation or "identity")(out), state


@dataclass
class EmbeddingSequenceLayer(EmbeddingLayer):
    """[B, T] int → RNN [B, T, nOut]."""

    def set_input_type(self, input_type):
        self.n_in = input_type.size
        ts = getattr(input_type, "timesteps", None)
        return RNNInput(self.n_out, ts)

    def apply(self, params, x, state, training, rng):
        idx = x.astype(jnp.int32)
        if idx.ndim == 3 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        out = self._lookup(params["W"], idx)
        return activation_fn(self.activation or "identity")(out), state


@dataclass
class ElementWiseMultiplicationLayer(Layer):
    """out = activation(w * x + b), elementwise (reference layer of same name)."""

    def set_input_type(self, input_type):
        self.n_in = input_type.size
        return input_type

    def init_params(self, key, dtype=jnp.float32):
        return {"w": jnp.ones((self.n_in,), dtype), "b": jnp.zeros((self.n_in,), dtype)}

    def apply(self, params, x, state, training, rng):
        out = x * params["w"] + params["b"]
        return activation_fn(self.activation or "identity")(out), state


@dataclass
class FrozenLayer(Layer):
    """Reference FrozenLayer wrapper: parameters excluded from updates.
    Implemented with stop_gradient — updater math never sees a gradient."""

    layer: Optional[Layer] = None

    def set_input_type(self, input_type):
        return self.layer.set_input_type(input_type)

    def init_params(self, key, dtype=jnp.float32):
        return self.layer.init_params(key, dtype)

    def init_state(self):
        return self.layer.init_state()

    def apply(self, params, x, state, training, rng):
        frozen = jax.tree.map(jax.lax.stop_gradient, params)
        return self.layer.apply(frozen, x, state, training, rng)

    def apply_masked(self, params, x, state, training, rng, fmask):
        frozen = jax.tree.map(jax.lax.stop_gradient, params)
        return self.layer.apply_masked(frozen, x, state, training, rng, fmask)

    def is_rnn(self):
        return self.layer.is_rnn()

    def init_rnn_state(self, batch, dtype=jnp.float32):
        return self.layer.init_rnn_state(batch, dtype)

    def apply_rnn(self, params, x, rnn_state, state, training, rng):
        frozen = jax.tree.map(jax.lax.stop_gradient, params)
        return self.layer.apply_rnn(frozen, x, rnn_state, state, training, rng)

    @property
    def has_params(self):
        return self.layer.has_params


# --- output layers -----------------------------------------------------------


@dataclass
class OutputLayer(DenseLayer):
    """Reference conf.layers.OutputLayer: dense + loss head."""

    loss: Union[str, ILossFunction, None] = None

    def __post_init__(self):
        if self.loss is None:
            self.loss = LossMCXENT()
        elif isinstance(self.loss, str):
            self.loss = loss_from_name(self.loss)
        if self.activation is None:
            self.activation = "softmax"

    def pre_output(self, params, x):
        out = x @ params["W"]
        if self.has_bias:
            out = out + params["b"]
        return out

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        return activation_fn(self.activation)(self.pre_output(params, x)), state

    def compute_score(self, params, x, labels, mask=None, average: bool = True):
        pre = self.pre_output(params, x)
        return self.loss.compute_score(labels, pre, self.activation, mask, average)


@dataclass
class RnnOutputLayer(OutputLayer):
    """Per-timestep output head on [B, T, F] (reference RnnOutputLayer):
    the dense W=[nIn,nOut] applies at every timestep (matmul broadcasts)."""

    def set_input_type(self, input_type):
        if not isinstance(input_type, RNNInput):
            raise ValueError(f"RnnOutputLayer needs RNN input, got {input_type}")
        self.n_in = input_type.size
        return RNNInput(self.n_out, input_type.timesteps)


@dataclass
class LossLayer(Layer):
    """No-param loss head (reference conf.layers.LossLayer)."""

    loss: Union[str, ILossFunction, None] = None

    def __post_init__(self):
        if self.loss is None:
            self.loss = LossMCXENT()
        elif isinstance(self.loss, str):
            self.loss = loss_from_name(self.loss)
        if self.activation is None:
            self.activation = "identity"

    def pre_output(self, params, x):
        return x

    def apply(self, params, x, state, training, rng):
        return activation_fn(self.activation)(x), state

    def compute_score(self, params, x, labels, mask=None, average: bool = True):
        return self.loss.compute_score(labels, x, self.activation, mask, average)

    @property
    def has_params(self):
        return False


# extended families (1D/3D convs, capsules, VAE, YOLO, constraints, ...)
from .layers_ext import *  # noqa: E402,F401,F403
