from .builder import NeuralNetConfiguration, MultiLayerConfiguration, Builder, ListBuilder
from .inputs import InputType
from . import layers
