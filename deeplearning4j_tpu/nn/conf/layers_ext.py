"""Extended layer families: 1D/3D conv stacks, locally-connected, capsules,
VAE, YOLOv2 head, center loss, spatial reshapes, dropout variants,
constraints + weight noise.

Reference: the remainder of dl4j-nn ``org.deeplearning4j.nn.conf.layers.*``
flagged missing by the round-1 verdict (SURVEY.md §2.3 conf-layer row):
``Convolution1D/3D + Subsampling/Upsampling/ZeroPadding/Cropping 1D/3D``,
``LocallyConnected1D/2D``, ``SpaceToDepthLayer/SpaceToBatchLayer``,
``RepeatVector``, ``TimeDistributed``, ``Alpha/GaussianDropout``,
``GaussianNoise``, ``variational.VariationalAutoencoder``,
``CenterLossOutputLayer``, ``CapsuleLayer/PrimaryCapsules/
CapsuleStrengthLayer``, ``objdetect.Yolo2OutputLayer``, plus the
``constraint.*`` and ``weightnoise.*`` SPIs.

Layout conventions match the main layer module: 1D sequence layers ride the
RNN layout [B, T, F] (the reference's Conv1D also consumes recurrent input),
3D layers are NCDHW via ``CNN3DInput``. Imported star-wise at the bottom of
``layers.py`` so every class is reachable as ``conf.layers.X``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.registry import get_op
from ..activations import activation_fn
from ..losses import ILossFunction, LossMCXENT, loss_from_name
from ..weights import init_weights
from .inputs import CNN3DInput, CNNInput, FFInput, InputType, RNNInput
from .layers import (ActivationLayer, DenseLayer, Layer, OutputLayer, _pair)


def _triple(v):
    return (v, v, v) if isinstance(v, int) else tuple(v)


def _triple_pairs(v):
    """3-D per-side padding/cropping spec → ((lo, hi),)*3. Accepts an int,
    a (d, h, w) triple, or Keras-style ((d1, d2), (h1, h2), (w1, w2))."""
    if isinstance(v, int):
        return ((v, v),) * 3
    v = tuple(v)
    if all(isinstance(e, int) for e in v):
        return tuple((e, e) for e in v)
    return tuple((int(a), int(b)) for a, b in v)


# =========================================================================
# 1D convolution family (on [B, T, F] sequence input, reference Conv1D
# consumes recurrent input the same way)
# =========================================================================

@dataclass
class Convolution1DLayer(Layer):
    """Reference conf.layers.Convolution1DLayer. W=[out, in, k]."""

    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    padding: int = 0
    dilation: int = 1
    convolution_mode: str = "truncate"
    has_bias: bool = True

    def set_input_type(self, input_type):
        if not isinstance(input_type, RNNInput):
            raise ValueError("Convolution1DLayer needs RNN input [B, T, F]")
        self.n_in = input_type.size
        t = input_type.timesteps
        if t is not None:
            if self.convolution_mode.lower() == "same":
                t = -(-t // self.stride)
            else:
                eff_k = (self.kernel_size - 1) * self.dilation + 1
                t = (t + 2 * self.padding - eff_k) // self.stride + 1
        return RNNInput(self.n_out, t)

    def init_params(self, key, dtype=jnp.float32):
        p = {"W": init_weights(key, (self.n_out, self.n_in, self.kernel_size),
                               self.weight_init or "xavier", dtype)}
        if self.has_bias:
            p["b"] = jnp.zeros((self.n_out,), dtype)
        return p

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        pad = ("SAME" if self.convolution_mode.lower() == "same"
               else self.padding)
        out = get_op("conv1d").fn(jnp.swapaxes(x, 1, 2), params["W"],
                                  params.get("b"), stride=self.stride,
                                  padding=pad, dilation=self.dilation)
        out = jnp.swapaxes(out, 1, 2)
        return activation_fn(self.activation or "identity")(out), state


@dataclass
class Subsampling1DLayer(Layer):
    """Reference Subsampling1DLayer: max/avg pooling along time."""

    kernel_size: int = 2
    stride: int = 2
    padding: int = 0
    pooling_type: str = "max"

    def set_input_type(self, input_type):
        if not isinstance(input_type, RNNInput):
            raise ValueError("Subsampling1DLayer needs RNN input")
        self.n_in = input_type.size
        t = input_type.timesteps
        if t is not None:
            t = (t + 2 * self.padding - self.kernel_size) // self.stride + 1
        return RNNInput(self.n_in, t)

    def apply(self, params, x, state, training, rng):
        xc = jnp.swapaxes(x, 1, 2)[..., None]       # [B, F, T, 1]
        op = "maxpool2d" if self.pooling_type.lower() == "max" else "avgpool2d"
        out = get_op(op).fn(xc, kernel=(self.kernel_size, 1),
                            strides=(self.stride, 1),
                            padding=(self.padding, 0))
        return jnp.swapaxes(out[..., 0], 1, 2), state

    @property
    def has_params(self):
        return False


@dataclass
class Upsampling1D(Layer):
    """Repeat each timestep ``size`` times (reference Upsampling1D)."""

    size: int = 2

    def set_input_type(self, input_type):
        self.n_in = input_type.size
        t = input_type.timesteps
        return RNNInput(self.n_in, t * self.size if t else None)

    def apply(self, params, x, state, training, rng):
        return jnp.repeat(x, self.size, axis=1), state

    @property
    def has_params(self):
        return False


@dataclass
class ZeroPadding1DLayer(Layer):
    padding: Tuple[int, int] = (1, 1)

    def set_input_type(self, input_type):
        self.n_in = input_type.size
        t = input_type.timesteps
        p = _pair(self.padding)
        return RNNInput(self.n_in, t + p[0] + p[1] if t else None)

    def apply(self, params, x, state, training, rng):
        p = _pair(self.padding)
        return jnp.pad(x, ((0, 0), (p[0], p[1]), (0, 0))), state

    @property
    def has_params(self):
        return False


@dataclass
class Cropping1D(Layer):
    cropping: Tuple[int, int] = (1, 1)

    def set_input_type(self, input_type):
        self.n_in = input_type.size
        t = input_type.timesteps
        c = _pair(self.cropping)
        return RNNInput(self.n_in, t - c[0] - c[1] if t else None)

    def apply(self, params, x, state, training, rng):
        c = _pair(self.cropping)
        return x[:, c[0]:x.shape[1] - c[1]], state

    @property
    def has_params(self):
        return False


# =========================================================================
# 3D convolution family (NCDHW)
# =========================================================================

@dataclass
class Convolution3DLayer(Layer):
    """Reference conf.layers.Convolution3D. W=[out, in, kD, kH, kW]."""

    n_out: int = 0
    kernel_size: Tuple[int, int, int] = (3, 3, 3)
    stride: Tuple[int, int, int] = (1, 1, 1)
    padding: Tuple[int, int, int] = (0, 0, 0)
    dilation: Tuple[int, int, int] = (1, 1, 1)
    convolution_mode: str = "truncate"
    has_bias: bool = True

    def _dims(self, d, h, w):
        k = _triple(self.kernel_size)
        s = _triple(self.stride)
        if self.convolution_mode.lower() == "same":
            return tuple(-(-v // sv) for v, sv in zip((d, h, w), s))
        p = _triple(self.padding)
        dil = _triple(self.dilation)
        out = []
        for v, kv, sv, pv, dv in zip((d, h, w), k, s, p, dil):
            eff = (kv - 1) * dv + 1
            out.append((v + 2 * pv - eff) // sv + 1)
        return tuple(out)

    def set_input_type(self, input_type):
        if not isinstance(input_type, CNN3DInput):
            raise ValueError("Convolution3DLayer needs CNN3D input (use "
                             "InputType.convolutional_3d)")
        self.n_in = input_type.channels
        d, h, w = self._dims(input_type.depth, input_type.height,
                             input_type.width)
        return CNN3DInput(self.n_out, d, h, w)

    def init_params(self, key, dtype=jnp.float32):
        k = _triple(self.kernel_size)
        p = {"W": init_weights(key, (self.n_out, self.n_in) + k,
                               self.weight_init or "xavier", dtype)}
        if self.has_bias:
            p["b"] = jnp.zeros((self.n_out,), dtype)
        return p

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        pad = ("SAME" if self.convolution_mode.lower() == "same"
               else _triple(self.padding))
        out = get_op("conv3d").fn(x, params["W"], params.get("b"),
                                  strides=_triple(self.stride), padding=pad,
                                  dilation=_triple(self.dilation))
        return activation_fn(self.activation or "identity")(out), state


@dataclass
class Subsampling3DLayer(Layer):
    kernel_size: Tuple[int, int, int] = (2, 2, 2)
    stride: Tuple[int, int, int] = (2, 2, 2)
    padding: Tuple[int, int, int] = (0, 0, 0)
    pooling_type: str = "max"

    def set_input_type(self, input_type):
        if not isinstance(input_type, CNN3DInput):
            raise ValueError("Subsampling3DLayer needs CNN3D input")
        self.n_in = input_type.channels
        k, s, p = (_triple(self.kernel_size), _triple(self.stride),
                   _triple(self.padding))
        dims = tuple((v + 2 * pv - kv) // sv + 1 for v, kv, sv, pv in
                     zip((input_type.depth, input_type.height,
                          input_type.width), k, s, p))
        return CNN3DInput(self.n_in, *dims)

    def apply(self, params, x, state, training, rng):
        op = "maxpool3d" if self.pooling_type.lower() == "max" else "avgpool3d"
        out = get_op(op).fn(x, kernel=_triple(self.kernel_size),
                            strides=_triple(self.stride),
                            padding=_triple(self.padding))
        return out, state

    @property
    def has_params(self):
        return False


@dataclass
class Upsampling3D(Layer):
    size: Tuple[int, int, int] = (2, 2, 2)

    def set_input_type(self, input_type):
        self.n_in = input_type.channels
        s = _triple(self.size)
        return CNN3DInput(self.n_in, input_type.depth * s[0],
                          input_type.height * s[1], input_type.width * s[2])

    def apply(self, params, x, state, training, rng):
        out = get_op("upsampling3d").fn(x, factor=_triple(self.size))
        return out, state

    @property
    def has_params(self):
        return False


@dataclass
class ZeroPadding3DLayer(Layer):
    # int, (d, h, w), or per-side ((d1, d2), (h1, h2), (w1, w2))
    padding: Any = (1, 1, 1)

    def set_input_type(self, input_type):
        self.n_in = input_type.channels
        p = _triple_pairs(self.padding)
        return CNN3DInput(self.n_in,
                          input_type.depth + p[0][0] + p[0][1],
                          input_type.height + p[1][0] + p[1][1],
                          input_type.width + p[2][0] + p[2][1])

    def apply(self, params, x, state, training, rng):
        p = _triple_pairs(self.padding)
        return jnp.pad(x, ((0, 0), (0, 0)) + p), state

    @property
    def has_params(self):
        return False


@dataclass
class Cropping3D(Layer):
    # int, (d, h, w), or per-side ((d1, d2), (h1, h2), (w1, w2))
    cropping: Any = (1, 1, 1)

    def set_input_type(self, input_type):
        self.n_in = input_type.channels
        c = _triple_pairs(self.cropping)
        return CNN3DInput(self.n_in,
                          input_type.depth - c[0][0] - c[0][1],
                          input_type.height - c[1][0] - c[1][1],
                          input_type.width - c[2][0] - c[2][1])

    def apply(self, params, x, state, training, rng):
        c = _triple_pairs(self.cropping)
        return x[:, :,
                 c[0][0]:x.shape[2] - c[0][1],
                 c[1][0]:x.shape[3] - c[1][1],
                 c[2][0]:x.shape[4] - c[2][1]], state

    @property
    def has_params(self):
        return False


# =========================================================================
# Locally connected (unshared conv weights)
# =========================================================================

@dataclass
class LocallyConnected2D(Layer):
    """Reference conf.layers.LocallyConnected2D: convolution arithmetic with
    a SEPARATE kernel per output position. Lowered to
    ``conv_general_dilated_patches`` (one im2col) + a per-position einsum —
    a single large batched matmul on the MXU instead of the reference's
    per-position GEMM loop."""

    n_out: int = 0
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    has_bias: bool = True

    def set_input_type(self, input_type):
        if not isinstance(input_type, CNNInput):
            raise ValueError("LocallyConnected2D needs CNN input")
        self.n_in = input_type.channels
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        self._oh = (input_type.height - kh) // sh + 1
        self._ow = (input_type.width - kw) // sw + 1
        return CNNInput(self.n_out, self._oh, self._ow)

    def init_params(self, key, dtype=jnp.float32):
        kh, kw = _pair(self.kernel_size)
        patch = self.n_in * kh * kw
        kw_, kb_ = jax.random.split(key)
        # fan-in-correct init per position
        w = init_weights(kw_, (self._oh * self._ow, patch, self.n_out),
                         self.weight_init or "xavier", dtype)
        p = {"W": w}
        if self.has_bias:
            p["b"] = jnp.zeros((self.n_out, self._oh, self._ow), dtype)
        return p

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        patches = jax.lax.conv_general_dilated_patches(
            x, _pair(self.kernel_size), _pair(self.stride),
            padding="VALID")                         # [B, C*kh*kw, oh, ow]
        b, p, oh, ow = patches.shape
        flat = patches.reshape(b, p, oh * ow)
        out = jnp.einsum("bpl,lpo->bol", flat, params["W"])
        out = out.reshape(b, self.n_out, oh, ow)
        if self.has_bias:
            out = out + params["b"][None]
        return activation_fn(self.activation or "identity")(out), state


@dataclass
class LocallyConnected1D(Layer):
    """Reference LocallyConnected1D on [B, T, F]."""

    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    has_bias: bool = True

    def set_input_type(self, input_type):
        if not isinstance(input_type, RNNInput):
            raise ValueError("LocallyConnected1D needs RNN input")
        self.n_in = input_type.size
        t = input_type.timesteps
        if t is None:
            raise ValueError("LocallyConnected1D needs a known sequence "
                             "length (unshared weights are per-position)")
        self._ot = (t - self.kernel_size) // self.stride + 1
        return RNNInput(self.n_out, self._ot)

    def init_params(self, key, dtype=jnp.float32):
        patch = self.n_in * self.kernel_size
        p = {"W": init_weights(key, (self._ot, patch, self.n_out),
                               self.weight_init or "xavier", dtype)}
        if self.has_bias:
            p["b"] = jnp.zeros((self._ot, self.n_out), dtype)
        return p

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        xc = jnp.swapaxes(x, 1, 2)[..., None]       # [B, F, T, 1]
        patches = jax.lax.conv_general_dilated_patches(
            xc, (self.kernel_size, 1), (self.stride, 1), padding="VALID")
        b, p, ot, _ = patches.shape
        flat = patches.reshape(b, p, ot)
        out = jnp.einsum("bpl,lpo->blo", flat, params["W"])
        if self.has_bias:
            out = out + params["b"][None]
        return activation_fn(self.activation or "identity")(out), state


# =========================================================================
# Spatial reshapes + sequence utility layers
# =========================================================================

@dataclass
class SpaceToDepthLayer(Layer):
    """Reference SpaceToDepthLayer (block rearrangement, zero FLOPs)."""

    block_size: int = 2

    def set_input_type(self, input_type):
        self.n_in = input_type.channels
        b = self.block_size
        return CNNInput(self.n_in * b * b, input_type.height // b,
                        input_type.width // b)

    def apply(self, params, x, state, training, rng):
        return get_op("space_to_depth").fn(x, block_size=self.block_size,
                                           data_format="NCHW"), state

    @property
    def has_params(self):
        return False


@dataclass
class SpaceToBatchLayer(Layer):
    """Reference SpaceToBatchLayer (NCHW shell over the NHWC op)."""

    block_size: int = 2

    def set_input_type(self, input_type):
        self.n_in = input_type.channels
        b = self.block_size
        return CNNInput(self.n_in, input_type.height // b,
                        input_type.width // b)

    def apply(self, params, x, state, training, rng):
        b = self.block_size
        nhwc = jnp.transpose(x, (0, 2, 3, 1))
        out = get_op("space_to_batch").fn(nhwc, (b, b), ((0, 0), (0, 0)))
        return jnp.transpose(out, (0, 3, 1, 2)), state

    @property
    def has_params(self):
        return False


@dataclass
class FlattenLayer(Layer):
    """Explicit row-major flatten of the non-batch dims (the Keras-import
    Flatten target: unlike the builder's automatic CnnToFeedForward
    preprocessor, this works with ANY following layer, e.g.
    Flatten→LayerNormalization→Dense)."""

    def set_input_type(self, input_type):
        if isinstance(input_type, FFInput):
            self.n_in = input_type.size
            return input_type
        if isinstance(input_type, CNNInput):
            n = input_type.channels * input_type.height * input_type.width
        elif isinstance(input_type, CNN3DInput):
            n = (input_type.channels * input_type.depth
                 * input_type.height * input_type.width)
        elif isinstance(input_type, RNNInput):
            if input_type.timesteps is None:
                raise ValueError("FlattenLayer needs known timesteps")
            n = input_type.size * input_type.timesteps
        else:
            raise ValueError(f"FlattenLayer: unsupported {input_type}")
        self.n_in = n
        return FFInput(n)

    def apply(self, params, x, state, training, rng):
        return x.reshape(x.shape[0], -1), state

    @property
    def has_params(self):
        return False


@dataclass
class LayerNormalization(Layer):
    """Feature-axis layer norm with learned gain/bias. FF input normalizes
    [B, F] over F; RNN input [B, T, F] over F; CNN input [B, C, H, W] over
    C (Keras's axis=-1 on NHWC == the channel dim, which is axis 1 in this
    NCHW body). Backed by the registry ``layer_norm`` op."""

    eps: float = 1e-3

    def set_input_type(self, input_type):
        if isinstance(input_type, FFInput):
            self.n_in = input_type.size
        elif isinstance(input_type, RNNInput):
            self.n_in = input_type.size
        elif isinstance(input_type, CNNInput):
            self.n_in = input_type.channels
        else:
            raise ValueError("LayerNormalization needs FF/RNN/CNN input")
        return input_type

    def init_params(self, key, dtype=jnp.float32):
        return {"gain": jnp.ones((self.n_in,), dtype),
                "bias": jnp.zeros((self.n_in,), dtype)}

    def apply(self, params, x, state, training, rng):
        axis = 1 if x.ndim == 4 else -1
        return get_op("layer_norm").fn(x, params["gain"], params["bias"],
                                       axis=axis, epsilon=self.eps), state


@dataclass
class Permute(Layer):
    """Permute non-batch dims of a sequence input (reference Keras-parity
    helper; dims are 1-based like Keras's Permute). Only the [B, T, F]
    layout is supported — image layouts differ between the Keras (NHWC)
    and this body (NCHW), so a dims tuple would be ambiguous there."""

    dims: Tuple[int, ...] = (2, 1)

    def set_input_type(self, input_type):
        if not isinstance(input_type, RNNInput) or tuple(self.dims) \
                not in ((2, 1), (1, 2)):
            raise ValueError("Permute supports RNN input with dims "
                             "(2,1)/(1,2) only")
        self.n_in = input_type.size
        if tuple(self.dims) == (1, 2):
            return input_type
        return RNNInput(input_type.timesteps, self.n_in)

    def apply(self, params, x, state, training, rng):
        if tuple(self.dims) == (2, 1):
            x = jnp.swapaxes(x, 1, 2)
        return x, state

    @property
    def has_params(self):
        return False


@dataclass
class ReshapeLayer(Layer):
    """Reshape the non-batch dims (row-major). FF→FF, FF→RNN, RNN→FF,
    RNN→RNN — image shapes are excluded for the same NHWC/NCHW ambiguity
    Permute documents."""

    shape: Tuple[int, ...] = ()

    def set_input_type(self, input_type):
        if isinstance(input_type, FFInput):
            n = input_type.size
        elif isinstance(input_type, RNNInput):
            if input_type.timesteps is None:
                raise ValueError("ReshapeLayer needs a known timestep count")
            n = input_type.size * input_type.timesteps
        else:
            raise ValueError("ReshapeLayer supports FF/RNN input only")
        import numpy as _np

        if int(_np.prod(self.shape)) != n:
            raise ValueError(f"cannot reshape {n} features into "
                             f"{self.shape}")
        self.n_in = n
        if len(self.shape) == 1:
            return FFInput(self.shape[0])
        if len(self.shape) == 2:
            return RNNInput(self.shape[1], self.shape[0])
        raise ValueError("ReshapeLayer target rank must be 1 or 2")

    def apply(self, params, x, state, training, rng):
        return x.reshape((x.shape[0],) + tuple(self.shape)), state

    @property
    def has_params(self):
        return False


@dataclass
class RepeatVector(Layer):
    """[B, F] → [B, n, F] (reference RepeatVector)."""

    n: int = 1

    def set_input_type(self, input_type):
        if not isinstance(input_type, FFInput):
            raise ValueError("RepeatVector needs FF input")
        self.n_in = input_type.size
        return RNNInput(self.n_in, self.n)

    def apply(self, params, x, state, training, rng):
        return jnp.repeat(x[:, None, :], self.n, axis=1), state

    @property
    def has_params(self):
        return False


@dataclass
class TimeDistributed(Layer):
    """Apply a feed-forward layer independently at every timestep
    (reference recurrent.TimeDistributed wrapper)."""

    layer: Optional[Layer] = None

    def set_input_type(self, input_type):
        if not isinstance(input_type, RNNInput):
            raise ValueError("TimeDistributed needs RNN input")
        inner_out = self.layer.set_input_type(FFInput(input_type.size))
        self.n_in = input_type.size
        return RNNInput(inner_out.size, input_type.timesteps)

    def init_params(self, key, dtype=jnp.float32):
        return self.layer.init_params(key, dtype)

    def init_state(self):
        return self.layer.init_state()

    def apply(self, params, x, state, training, rng):
        b, t, f = x.shape
        flat = x.reshape(b * t, f)
        out, st = self.layer.apply(params, flat, state, training, rng)
        return out.reshape(b, t, -1), st

    @property
    def has_params(self):
        return self.layer.has_params


# =========================================================================
# Dropout variants (ops already registered; train-only, identity at infer)
# =========================================================================

@dataclass
class AlphaDropoutLayer(Layer):
    """SELU-preserving dropout (reference AlphaDropout)."""

    rate: float = 0.5

    def apply(self, params, x, state, training, rng):
        if training and self.rate > 0:
            return get_op("alpha_dropout").fn(x, rng, rate=self.rate), state
        return x, state

    @property
    def has_params(self):
        return False


@dataclass
class GaussianDropoutLayer(Layer):
    """Multiplicative N(1, rate/(1-rate)) noise (reference GaussianDropout)."""

    rate: float = 0.5

    def apply(self, params, x, state, training, rng):
        if training and self.rate > 0:
            return get_op("gaussian_dropout").fn(x, rng, rate=self.rate), state
        return x, state

    @property
    def has_params(self):
        return False


@dataclass
class GaussianNoiseLayer(Layer):
    """Additive N(0, stddev) noise during training (reference GaussianNoise)."""

    stddev: float = 0.1

    def apply(self, params, x, state, training, rng):
        if training and self.stddev > 0:
            return get_op("gaussian_noise").fn(x, rng,
                                               stddev=self.stddev), state
        return x, state

    @property
    def has_params(self):
        return False


# =========================================================================
# Parameter constraints + weight noise (reference: api.layers.constraint.*,
# conf.weightnoise.*)
# =========================================================================

class ParamConstraint:
    """Projection applied to weights AFTER each update (reference
    BaseConstraint.applyConstraint)."""

    def apply(self, w):
        raise NotImplementedError


class MaxNormConstraint(ParamConstraint):
    def __init__(self, max_norm: float, axis: int = 0):
        self.max_norm = max_norm
        self.axis = axis

    def apply(self, w):
        norms = jnp.sqrt(jnp.sum(jnp.square(w), axis=self.axis,
                                 keepdims=True))
        scale = jnp.minimum(1.0, self.max_norm / jnp.maximum(norms, 1e-12))
        return w * scale


class MinMaxNormConstraint(ParamConstraint):
    def __init__(self, min_norm: float, max_norm: float, axis: int = 0):
        self.min_norm, self.max_norm, self.axis = min_norm, max_norm, axis

    def apply(self, w):
        norms = jnp.sqrt(jnp.sum(jnp.square(w), axis=self.axis,
                                 keepdims=True))
        clipped = jnp.clip(norms, self.min_norm, self.max_norm)
        return w * clipped / jnp.maximum(norms, 1e-12)


class NonNegativeConstraint(ParamConstraint):
    def apply(self, w):
        return jnp.maximum(w, 0.0)


class UnitNormConstraint(ParamConstraint):
    def __init__(self, axis: int = 0):
        self.axis = axis

    def apply(self, w):
        norms = jnp.sqrt(jnp.sum(jnp.square(w), axis=self.axis,
                                 keepdims=True))
        return w / jnp.maximum(norms, 1e-12)


class IWeightNoise:
    """Perturb a layer's params during TRAINING forward passes (reference
    conf.weightnoise.IWeightNoise; applied by the network before
    layer.apply, so every layer type supports it without code)."""

    def apply(self, params: Dict[str, Any], rng, training: bool):
        raise NotImplementedError


class DropConnect(IWeightNoise):
    """Randomly zero weights with probability p (reference DropConnect)."""

    def __init__(self, weight_retain_prob: float = 0.5,
                 apply_to_biases: bool = False):
        self.p = weight_retain_prob
        self.apply_to_biases = apply_to_biases

    def apply(self, params, rng, training):
        if not training:
            return params
        out = {}
        for k, w in params.items():
            if k == "b" and not self.apply_to_biases:
                out[k] = w
                continue
            rng, sub = jax.random.split(rng)
            keep = jax.random.bernoulli(sub, self.p, w.shape)
            out[k] = jnp.where(keep, w / self.p, 0.0)
        return out


class WeightNoise(IWeightNoise):
    """Additive/multiplicative gaussian weight noise (reference
    WeightNoise)."""

    def __init__(self, mean: float = 0.0, stddev: float = 0.1,
                 additive: bool = True):
        self.mean, self.stddev, self.additive = mean, stddev, additive

    def apply(self, params, rng, training):
        if not training:
            return params
        out = {}
        for k, w in params.items():
            if k == "b":
                out[k] = w
                continue
            rng, sub = jax.random.split(rng)
            noise = self.mean + self.stddev * \
                jax.random.normal(sub, w.shape, dtype=w.dtype)
            out[k] = w + noise if self.additive else w * noise
        return out


# =========================================================================
# Variational autoencoder (reference conf.layers.variational.*)
# =========================================================================

@dataclass
class VariationalAutoencoder(Layer):
    """Reference variational.VariationalAutoencoder: encoder MLP →
    (mean, logvar) of q(z|x) → decoder MLP → reconstruction distribution.

    Supervised forward (``apply``) returns the posterior MEAN activations —
    exactly what the reference's activate() feeds downstream layers. The
    unsupervised objective (negative ELBO, ``pretrain_loss``) drives
    ``MultiLayerNetwork.pretrain`` (reference layerwise pretraining path).
    Reconstruction distributions: "gaussian" (diagonal, reference
    GaussianReconstructionDistribution) or "bernoulli".
    """

    n_out: int = 0                                   # size of z
    encoder_layer_sizes: Tuple[int, ...] = (64,)
    decoder_layer_sizes: Tuple[int, ...] = (64,)
    reconstruction_distribution: str = "gaussian"
    num_samples: int = 1

    def set_input_type(self, input_type):
        if not isinstance(input_type, FFInput):
            raise ValueError("VariationalAutoencoder needs FF input")
        self.n_in = input_type.size
        return FFInput(self.n_out)

    def init_params(self, key, dtype=jnp.float32):
        wi = self.weight_init or "xavier"
        p: Dict[str, jnp.ndarray] = {}
        sizes = (self.n_in,) + tuple(self.encoder_layer_sizes)
        for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
            key, sub = jax.random.split(key)
            p[f"eW{i}"] = init_weights(sub, (a, b), wi, dtype)
            p[f"eb{i}"] = jnp.zeros((b,), dtype)
        key, k1, k2 = jax.random.split(key, 3)
        p["meanW"] = init_weights(k1, (sizes[-1], self.n_out), wi, dtype)
        p["meanb"] = jnp.zeros((self.n_out,), dtype)
        p["lvW"] = init_weights(k2, (sizes[-1], self.n_out), wi, dtype)
        p["lvb"] = jnp.zeros((self.n_out,), dtype)
        dsizes = (self.n_out,) + tuple(self.decoder_layer_sizes)
        for i, (a, b) in enumerate(zip(dsizes[:-1], dsizes[1:])):
            key, sub = jax.random.split(key)
            p[f"dW{i}"] = init_weights(sub, (a, b), wi, dtype)
            p[f"db{i}"] = jnp.zeros((b,), dtype)
        out_w = (2 * self.n_in
                 if self.reconstruction_distribution == "gaussian"
                 else self.n_in)
        key, sub = jax.random.split(key)
        p["rW"] = init_weights(sub, (dsizes[-1], out_w), wi, dtype)
        p["rb"] = jnp.zeros((out_w,), dtype)
        return p

    def _encode(self, params, x):
        act = activation_fn(self.activation or "tanh")
        h = x
        for i in range(len(self.encoder_layer_sizes)):
            h = act(h @ params[f"eW{i}"] + params[f"eb{i}"])
        mean = h @ params["meanW"] + params["meanb"]
        logvar = h @ params["lvW"] + params["lvb"]
        return mean, logvar

    def _decode(self, params, z):
        act = activation_fn(self.activation or "tanh")
        h = z
        for i in range(len(self.decoder_layer_sizes)):
            h = act(h @ params[f"dW{i}"] + params[f"db{i}"])
        return h @ params["rW"] + params["rb"]

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        mean, _ = self._encode(params, x)
        return mean, state

    def is_pretrain_layer(self) -> bool:
        return True

    def pretrain_loss(self, params, x, rng):
        """Negative ELBO, averaged over the batch (and num_samples z
        draws): reconstruction log-likelihood + KL(q(z|x) || N(0, I))."""
        mean, logvar = self._encode(params, x)
        kl = 0.5 * jnp.sum(jnp.exp(logvar) + mean ** 2 - 1.0 - logvar,
                           axis=1)
        recon = 0.0
        for _ in range(self.num_samples):
            rng, sub = jax.random.split(rng)
            eps = jax.random.normal(sub, mean.shape, dtype=mean.dtype)
            z = mean + jnp.exp(0.5 * logvar) * eps
            out = self._decode(params, z)
            if self.reconstruction_distribution == "gaussian":
                rmean, rlogvar = jnp.split(out, 2, axis=1)
                ll = -0.5 * jnp.sum(
                    rlogvar + (x - rmean) ** 2 / jnp.exp(rlogvar)
                    + jnp.log(2 * jnp.pi), axis=1)
            else:  # bernoulli logits
                ll = -jnp.sum(
                    jnp.maximum(out, 0) - out * x
                    + jnp.log1p(jnp.exp(-jnp.abs(out))), axis=1)
            recon = recon + ll
        recon = recon / self.num_samples
        return jnp.mean(kl - recon)

    def reconstruction_error(self, params, x, rng):
        """Deterministic (mean-z) reconstruction error for scoring."""
        mean, _ = self._encode(params, x)
        out = self._decode(params, mean)
        if self.reconstruction_distribution == "gaussian":
            rmean, _ = jnp.split(out, 2, axis=1)
        else:
            rmean = jax.nn.sigmoid(out)
        return jnp.mean(jnp.sum((x - rmean) ** 2, axis=1))


# =========================================================================
# Center loss output (reference CenterLossOutputLayer)
# =========================================================================

@dataclass
class CenterLossOutputLayer(OutputLayer):
    """Softmax-CE + lambda/2 * ||features - center_{y}||².

    DOCUMENTED DIVERGENCE: the reference updates class centers with a
    dedicated alpha moving average outside the optimizer; here the centers
    are ordinary parameters trained by the same gradient step (the gradient
    of the center term is alpha*(c_y - f) — the same direction, scheduled by
    the optimizer instead of a fixed alpha)."""

    alpha: float = 0.05          # accepted for config parity
    lambda_: float = 0.5

    def init_params(self, key, dtype=jnp.float32):
        p = super().init_params(key, dtype)
        p["centers"] = jnp.zeros((self.n_out, self.n_in), dtype)
        return p

    def compute_score(self, params, x, labels, mask=None, average=True):
        base = self.loss.compute_score(labels, self.pre_output(params, x),
                                       self.activation, mask, average)
        centers_batch = labels @ params["centers"]     # [B, n_in]
        center_term = 0.5 * self.lambda_ * jnp.sum(
            (x - centers_batch) ** 2, axis=1)
        if mask is not None:
            center_term = center_term * mask.reshape(center_term.shape)
        return base + (jnp.mean(center_term) if average
                       else jnp.sum(center_term))


# =========================================================================
# Capsule network trio (reference CapsuleLayer / PrimaryCapsules /
# CapsuleStrengthLayer)
# =========================================================================

def _squash(s, axis=-1):
    n2 = jnp.sum(jnp.square(s), axis=axis, keepdims=True)
    return (n2 / (1.0 + n2)) * s / jnp.sqrt(n2 + 1e-9)


@dataclass
class PrimaryCapsules(Layer):
    """Conv → capsule reshape → squash (reference PrimaryCapsules)."""

    capsules: int = 0               # derived if 0
    capsule_dimensions: int = 8
    channels: int = 32
    kernel_size: Tuple[int, int] = (9, 9)
    stride: Tuple[int, int] = (2, 2)

    def set_input_type(self, input_type):
        if not isinstance(input_type, CNNInput):
            raise ValueError("PrimaryCapsules needs CNN input")
        self.n_in = input_type.channels
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        oh = (input_type.height - kh) // sh + 1
        ow = (input_type.width - kw) // sw + 1
        self.capsules = self.channels * oh * ow
        return RNNInput(self.capsule_dimensions, self.capsules)

    def init_params(self, key, dtype=jnp.float32):
        kh, kw = _pair(self.kernel_size)
        n_out = self.channels * self.capsule_dimensions
        return {"W": init_weights(key, (n_out, self.n_in, kh, kw),
                                  self.weight_init or "xavier", dtype),
                "b": jnp.zeros((n_out,), dtype)}

    def apply(self, params, x, state, training, rng):
        out = get_op("conv2d").fn(x, params["W"], params["b"],
                                  strides=_pair(self.stride),
                                  padding=(0, 0))
        b = out.shape[0]
        caps = out.reshape(b, self.capsule_dimensions, -1)
        caps = jnp.swapaxes(caps, 1, 2)            # [B, caps, capsDim]
        return _squash(caps), state


@dataclass
class CapsuleLayer(Layer):
    """Dynamic-routing capsule layer (reference CapsuleLayer). The routing
    loop is a fixed small iteration count — unrolled at trace time, all
    matmuls batched on the MXU."""

    capsules: int = 10
    capsule_dimensions: int = 16
    routings: int = 3

    def set_input_type(self, input_type):
        if not isinstance(input_type, RNNInput):
            raise ValueError("CapsuleLayer needs capsule input "
                             "[B, inCaps, inDim]")
        self._in_caps = input_type.timesteps
        self.n_in = input_type.size
        if self._in_caps is None:
            raise ValueError("CapsuleLayer needs a known capsule count")
        return RNNInput(self.capsule_dimensions, self.capsules)

    def init_params(self, key, dtype=jnp.float32):
        return {"W": init_weights(
            key, (self._in_caps, self.capsules,
                  self.capsule_dimensions, self.n_in),
            self.weight_init or "xavier", dtype)}

    def apply(self, params, x, state, training, rng):
        # u_hat[b,i,j,d] = W[i,j,d,:] · x[b,i,:]
        u_hat = jnp.einsum("ijdc,bic->bijd", params["W"], x)
        b_logits = jnp.zeros(u_hat.shape[:3], u_hat.dtype)
        v = None
        for r in range(self.routings):
            c = jax.nn.softmax(b_logits, axis=2)           # over out caps
            s = jnp.einsum("bij,bijd->bjd", c, u_hat)
            v = _squash(s)
            if r < self.routings - 1:
                b_logits = b_logits + jnp.einsum("bijd,bjd->bij", u_hat, v)
        return v, state


@dataclass
class CapsuleStrengthLayer(Layer):
    """Capsule lengths [B, caps, dim] → [B, caps] (reference
    CapsuleStrengthLayer — the classification read-out)."""

    def set_input_type(self, input_type):
        if not isinstance(input_type, RNNInput):
            raise ValueError("CapsuleStrengthLayer needs capsule input")
        self.n_in = input_type.size
        return FFInput(input_type.timesteps)

    def apply(self, params, x, state, training, rng):
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=-1) + 1e-9), state

    @property
    def has_params(self):
        return False


# =========================================================================
# YOLOv2 detection head (reference objdetect.Yolo2OutputLayer)
# =========================================================================

@dataclass
class Yolo2OutputLayer(Layer):
    """YOLOv2 composite detection loss (reference Yolo2OutputLayer).

    Input: [B, A*(5+C), H, W] raw activations (A = len(anchors)).
    Labels (reference label format): [B, 4+C, H, W] — per grid cell the
    ground-truth box corners (x1, y1, x2, y2, in GRID units) followed by the
    one-hot class; cells with an all-zero class vector contain no object.

    Loss terms follow the paper/reference: lambda_coord on xy + sqrt-wh of
    the responsible anchor (best IoU), objectness toward IoU for
    responsible anchors, lambda_noobj on everything else, softmax-CE on the
    class distribution of object cells.
    """

    anchors: Tuple[Tuple[float, float], ...] = ((1.0, 1.0),)
    lambda_coord: float = 5.0
    lambda_no_obj: float = 0.5
    loss: Union[str, ILossFunction, None] = None

    def __post_init__(self):
        self.anchors = tuple(tuple(map(float, a)) for a in self.anchors)

    def set_input_type(self, input_type):
        if not isinstance(input_type, CNNInput):
            raise ValueError("Yolo2OutputLayer needs CNN input")
        self.n_in = input_type.channels
        a = len(self.anchors)
        if input_type.channels % a:
            raise ValueError(
                f"channels {input_type.channels} not divisible by "
                f"{a} anchors")
        self._n_classes = input_type.channels // a - 5
        if self._n_classes < 0:
            raise ValueError("channels must be anchors*(5+classes)")
        self._grid = (input_type.height, input_type.width)
        return input_type

    def _split(self, x):
        b, _, h, w = x.shape
        a, c = len(self.anchors), self._n_classes
        x = x.reshape(b, a, 5 + c, h, w)
        txy = jax.nn.sigmoid(x[:, :, 0:2])
        twh = x[:, :, 2:4]
        conf = jax.nn.sigmoid(x[:, :, 4])
        cls = x[:, :, 5:]
        return txy, twh, conf, cls

    def apply(self, params, x, state, training, rng):
        return x, state

    @property
    def has_params(self):
        return False

    def compute_score(self, params, x, labels, mask=None, average=True):
        txy, twh, conf, cls_logits = self._split(x)
        b, a, _, h, w = txy.shape
        anchors = jnp.asarray(self.anchors, dtype=x.dtype)   # [A, 2]
        gy, gx = jnp.meshgrid(jnp.arange(h, dtype=x.dtype),
                              jnp.arange(w, dtype=x.dtype), indexing="ij")
        # predicted boxes in grid units
        px = gx[None, None] + txy[:, :, 0]
        py = gy[None, None] + txy[:, :, 1]
        pw = anchors[None, :, 0, None, None] * jnp.exp(twh[:, :, 0])
        ph = anchors[None, :, 1, None, None] * jnp.exp(twh[:, :, 1])

        gt_x1, gt_y1 = labels[:, 0], labels[:, 1]
        gt_x2, gt_y2 = labels[:, 2], labels[:, 3]
        gt_cls = labels[:, 4:]
        obj = (jnp.sum(gt_cls, axis=1) > 0).astype(x.dtype)  # [B, H, W]
        gw = gt_x2 - gt_x1
        gh = gt_y2 - gt_y1
        gcx = 0.5 * (gt_x1 + gt_x2)
        gcy = 0.5 * (gt_y1 + gt_y2)

        # IoU of each anchor's predicted box with the cell's gt box
        ix1 = jnp.maximum(px - pw / 2, gt_x1[:, None])
        iy1 = jnp.maximum(py - ph / 2, gt_y1[:, None])
        ix2 = jnp.minimum(px + pw / 2, gt_x2[:, None])
        iy2 = jnp.minimum(py + ph / 2, gt_y2[:, None])
        inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
        union = pw * ph + (gw * gh)[:, None] - inter
        iou = inter / jnp.maximum(union, 1e-9)               # [B, A, H, W]

        best = jnp.argmax(iou, axis=1)                       # [B, H, W]
        resp = jax.nn.one_hot(best, a, dtype=x.dtype) \
            .transpose(0, 3, 1, 2) * obj[:, None]            # [B, A, H, W]

        # coordinate loss (xy within cell + sqrt wh), responsible only
        tx = gcx - gx[None]
        ty = gcy - gy[None]
        xy_l = (txy[:, :, 0] - tx[:, None]) ** 2 + \
               (txy[:, :, 1] - ty[:, None]) ** 2
        wh_l = (jnp.sqrt(jnp.maximum(pw, 1e-9))
                - jnp.sqrt(jnp.maximum(gw, 1e-9))[:, None]) ** 2 + \
               (jnp.sqrt(jnp.maximum(ph, 1e-9))
                - jnp.sqrt(jnp.maximum(gh, 1e-9))[:, None]) ** 2
        coord = self.lambda_coord * jnp.sum(resp * (xy_l + wh_l),
                                            axis=(1, 2, 3))

        # objectness: responsible → IoU target; others → 0
        obj_l = jnp.sum(resp * (conf - jax.lax.stop_gradient(iou)) ** 2,
                        axis=(1, 2, 3))
        noobj_l = self.lambda_no_obj * jnp.sum(
            (1.0 - resp) * conf ** 2, axis=(1, 2, 3))

        # classification: softmax-CE over classes at object cells,
        # responsible anchor
        logp = jax.nn.log_softmax(cls_logits, axis=2)
        ce = -jnp.sum(gt_cls[:, None] * logp, axis=2)        # [B, A, H, W]
        cls_l = jnp.sum(resp * ce, axis=(1, 2, 3))

        total = coord + obj_l + noobj_l + cls_l              # [B]
        return jnp.mean(total) if average else jnp.sum(total)


# =========================================================================
# round-5 Keras-import tail (VERDICT r4 missing #2): TimeDistributed,
# Masking, Lambda, ConvLSTM2D, SeparableConv1D, ThresholdedReLU
# =========================================================================

@dataclass
class ThresholdedReLULayer(Layer):
    """Keras ThresholdedReLU: f(x) = x for x > theta else 0 (reference
    KerasThresholdedReLU → ActivationLayer(ThresholdedReLU))."""

    theta: float = 1.0

    def set_input_type(self, input_type):
        self.n_in = getattr(input_type, "size", None)
        return input_type

    def apply(self, params, x, state, training, rng):
        return x * (x > self.theta).astype(x.dtype), state

    @property
    def has_params(self):
        return False


@dataclass
class MaskingLayer(Layer):
    """Keras Masking: timesteps whose features ALL equal ``mask_value``
    are masked. The layer zeroes them; ``derive_mask`` yields the
    [B, T] feature mask that MultiLayerNetwork threads to downstream
    mask-aware layers (recurrent state freezing, mask-aware pooling,
    masked loss) — the reference's per-timestep mask-array plumbing
    (SURVEY §5.7), derived in-graph."""

    mask_value: float = 0.0

    def set_input_type(self, input_type):
        if not isinstance(input_type, RNNInput):
            raise ValueError("MaskingLayer needs RNN input [B, T, F]")
        self.n_in = input_type.size
        return input_type

    def derive_mask(self, x):
        return jnp.any(x != self.mask_value, axis=-1).astype(jnp.float32)

    def apply(self, params, x, state, training, rng):
        m = self.derive_mask(x)
        return x * m[:, :, None].astype(x.dtype), state

    def apply_masked(self, params, x, state, training, rng, fmask):
        y, st = self.apply(params, x, state, training, rng)
        return y * fmask[:, :, None].astype(y.dtype), st

    @property
    def has_params(self):
        return False


@dataclass
class TimeDistributedLayer(Layer):
    """Keras TimeDistributed wrapper: applies a feed-forward ``inner``
    layer independently at every timestep of [B, T, F] input (reference
    conf.layers.recurrent.TimeDistributed). Import-oriented: nested-layer
    configs are not part of the frozen JSON serde surface."""

    inner: Optional[Layer] = None

    def set_input_type(self, input_type):
        if not isinstance(input_type, RNNInput):
            raise ValueError("TimeDistributedLayer needs RNN input")
        self.n_in = input_type.size
        out = self.inner.set_input_type(FFInput(input_type.size))
        return RNNInput(out.size, input_type.timesteps)

    def init_params(self, key, dtype=jnp.float32):
        return self.inner.init_params(key, dtype)

    @property
    def has_params(self):
        return self.inner.has_params

    def apply(self, params, x, state, training, rng):
        B, T = x.shape[0], x.shape[1]
        y, st = self.inner.apply(params, x.reshape(B * T, -1), state,
                                 training, rng)
        return y.reshape(B, T, -1), st

    def apply_masked(self, params, x, state, training, rng, fmask):
        y, st = self.apply(params, x, state, training, rng)
        return y * fmask[:, :, None].astype(y.dtype), st


@dataclass
class LambdaLayer(Layer):
    """A user-supplied elementwise/tensor function as a layer (reference
    KerasLambdaLayer/SameDiffLambdaLayer: lambda bodies are not portable
    across serialization, so the implementation is REGISTERED in code and
    looked up by name at import — keras_import.register_lambda)."""

    fn: Optional[Any] = None
    name: str = ""

    def set_input_type(self, input_type):
        self.n_in = getattr(input_type, "size", None)
        # derive the output type by tracing the fn over a dummy batch
        t_unknown = (isinstance(input_type, RNNInput)
                     and input_type.timesteps is None)
        dummy_t = 4   # placeholder for unknown T; must round-trip intact
        if isinstance(input_type, FFInput):
            shape = (1, input_type.size)
        elif isinstance(input_type, RNNInput):
            shape = (1, input_type.timesteps or dummy_t, input_type.size)
        elif isinstance(input_type, CNNInput):
            shape = (1, input_type.channels, input_type.height,
                     input_type.width)
        else:
            raise ValueError(
                f"Lambda {self.name!r}: unsupported input {input_type}")
        out = jax.eval_shape(self.fn,
                             jax.ShapeDtypeStruct(shape, jnp.float32))
        s = out.shape
        if len(s) == 2:
            return FFInput(s[1])
        if len(s) == 3:
            if t_unknown:
                if s[1] != dummy_t:
                    raise ValueError(
                        f"Lambda {self.name!r}: changes the time dimension "
                        "but the input timesteps are unknown — give the "
                        "input a static sequence length")
                return RNNInput(s[2], None)
            return RNNInput(s[2], s[1])
        if len(s) == 4:
            return CNNInput(s[1], s[2], s[3])
        raise ValueError(f"Lambda {self.name!r}: unsupported output rank "
                         f"{len(s)}")

    def apply(self, params, x, state, training, rng):
        return self.fn(x), state

    @property
    def has_params(self):
        return False


@dataclass
class SeparableConvolution1D(Layer):
    """Depthwise + pointwise 1-D convolution on [B, T, F] sequence input
    (Keras SeparableConv1D; rides the 2-D separable kernel with a
    singleton width, like Subsampling1DLayer rides pool2d).
    dW=[m, C, k, 1], pW=[F_out, C·m, 1, 1]."""

    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    depth_multiplier: int = 1
    convolution_mode: str = "truncate"
    has_bias: bool = True

    def set_input_type(self, input_type):
        if not isinstance(input_type, RNNInput):
            raise ValueError("SeparableConvolution1D needs RNN input")
        self.n_in = input_type.size
        t = input_type.timesteps
        if t is not None:
            if self.convolution_mode.lower() == "same":
                t = -(-t // self.stride)
            else:
                t = (t - self.kernel_size) // self.stride + 1
        return RNNInput(self.n_out, t)

    def init_params(self, key, dtype=jnp.float32):
        kd, kp = jax.random.split(key)
        p = {"dW": init_weights(
                kd, (self.depth_multiplier, self.n_in, self.kernel_size, 1),
                self.weight_init or "xavier", dtype),
             "pW": init_weights(
                kp, (self.n_out, self.n_in * self.depth_multiplier, 1, 1),
                self.weight_init or "xavier", dtype)}
        if self.has_bias:
            p["b"] = jnp.zeros((self.n_out,), dtype)
        return p

    def apply(self, params, x, state, training, rng):
        x = self._maybe_dropout(x, training, rng)
        xc = jnp.swapaxes(x, 1, 2)[..., None]        # [B, F, T, 1]
        pad = ("SAME" if self.convolution_mode.lower() == "same"
               else (0, 0))
        out = get_op("sconv2d").fn(xc, params["dW"], params["pW"],
                                   params.get("b"),
                                   strides=(self.stride, 1), padding=pad)
        out = jnp.swapaxes(out[..., 0], 1, 2)
        return activation_fn(self.activation or "identity")(out), state


@dataclass
class ConvLSTM2DLayer(Layer):
    """Convolutional LSTM over frame sequences (Keras ConvLSTM2D;
    reference KerasConvLSTM2D). Input rides the CNN3D layout
    [B, C, T, H, W] with the DEPTH axis as time; output is
    [B, F, T, H', W'] (return_sequences) or [B, F, H', W'].

    Gate math matches Keras: per step, gates = conv(x_t, Wx; configured
    padding) + conv(h, Wh; SAME) + b with channel-split order (i, f, c, o);
    c' = f*c + i*tanh(g); h' = o*tanh(c'). Weights are stored in Keras
    gate order — the importer loads them without permutation (documented;
    this layer exists for import parity, SURVEY §2.3 Keras row)."""

    n_out: int = 0
    kernel_size: Tuple[int, int] = (3, 3)
    convolution_mode: str = "truncate"
    return_sequences: bool = True
    has_bias: bool = True

    def set_input_type(self, input_type):
        if not isinstance(input_type, CNN3DInput):
            raise ValueError("ConvLSTM2DLayer needs CNN3D input "
                             "[B, C, T(depth), H, W]")
        self.n_in = input_type.channels
        kh, kw = _pair(self.kernel_size)
        if self.convolution_mode.lower() == "same":
            oh, ow = input_type.height, input_type.width
        else:
            oh = input_type.height - kh + 1
            ow = input_type.width - kw + 1
        if self.return_sequences:
            return CNN3DInput(self.n_out, input_type.depth, oh, ow)
        return CNNInput(self.n_out, oh, ow)

    def init_params(self, key, dtype=jnp.float32):
        kx, kh = jax.random.split(key)
        khh, kww = _pair(self.kernel_size)
        p = {"Wx": init_weights(kx, (4 * self.n_out, self.n_in, khh, kww),
                                self.weight_init or "xavier", dtype),
             "Wh": init_weights(kh, (4 * self.n_out, self.n_out, khh, kww),
                                self.weight_init or "xavier", dtype)}
        if self.has_bias:
            p["b"] = jnp.zeros((4 * self.n_out,), dtype)
        return p

    def apply(self, params, x, state, training, rng):
        from jax import lax

        F = self.n_out
        pad = ("SAME" if self.convolution_mode.lower() == "same"
               else "VALID")

        def conv(v, w, padding):
            return lax.conv_general_dilated(
                v, w, (1, 1), padding,
                dimension_numbers=("NCHW", "OIHW", "NCHW"))

        xs = jnp.moveaxis(x, 2, 0)                   # [T, B, C, H, W]
        kh, kw = _pair(self.kernel_size)
        B, H, W = x.shape[0], x.shape[3], x.shape[4]
        oh, ow = ((H, W) if pad == "SAME" else (H - kh + 1, W - kw + 1))
        h0 = jnp.zeros((B, F, oh, ow), x.dtype)
        c0 = jnp.zeros_like(h0)
        b = params.get("b")

        def step(carry, xt):
            h, c = carry
            g = conv(xt, params["Wx"], pad) + conv(h, params["Wh"], "SAME")
            if b is not None:
                g = g + b[None, :, None, None]
            i = jax.nn.sigmoid(g[:, 0 * F:1 * F])
            f = jax.nn.sigmoid(g[:, 1 * F:2 * F])
            gg = jnp.tanh(g[:, 2 * F:3 * F])
            o = jax.nn.sigmoid(g[:, 3 * F:4 * F])
            c2 = f * c + i * gg
            h2 = o * jnp.tanh(c2)
            return (h2, c2), h2

        (hT, _), hs = lax.scan(step, (h0, c0), xs)
        if self.return_sequences:
            return jnp.moveaxis(hs, 0, 2), state     # [B, F, T, H', W']
        return hT, state


@dataclass
class GroupNormalizationLayer(Layer):
    """Keras GroupNormalization / reference GroupNorm pattern: channels
    split into ``groups``; normalize over (group-channels, spatial) per
    example; per-channel gain/bias. CNN [B, C, H, W] or FF [B, F]."""

    groups: int = 32
    eps: float = 1e-3

    def set_input_type(self, input_type):
        if isinstance(input_type, CNNInput):
            self.n_in = input_type.channels
        elif isinstance(input_type, FFInput):
            self.n_in = input_type.size
        else:
            raise ValueError("GroupNormalizationLayer needs CNN or FF "
                             f"input, got {input_type}")
        if self.n_in % self.groups:
            raise ValueError(
                f"channels ({self.n_in}) must divide into groups "
                f"({self.groups})")
        return input_type

    def init_params(self, key, dtype=jnp.float32):
        return {"gain": jnp.ones((self.n_in,), dtype),
                "bias": jnp.zeros((self.n_in,), dtype)}

    def apply(self, params, x, state, training, rng):
        B, C = x.shape[0], x.shape[1]
        G = self.groups
        spatial = x.shape[2:]
        xg = x.reshape((B, G, C // G) + spatial)
        axes = tuple(range(2, xg.ndim))
        mu = xg.mean(axis=axes, keepdims=True)
        var = ((xg - mu) ** 2).mean(axis=axes, keepdims=True)
        xn = ((xg - mu) / jnp.sqrt(var + self.eps)).reshape(x.shape)
        shape = (1, C) + (1,) * len(spatial)
        return (xn * params["gain"].reshape(shape)
                + params["bias"].reshape(shape)), state


@dataclass
class SpatialDropoutLayer(Layer):
    """Drops whole FEATURE MAPS (reference weightnoise/SpatialDropout;
    Keras SpatialDropout1D/2D): one Bernoulli draw per (example, channel),
    broadcast over time/space, inverted scaling. Works on RNN [B, T, F]
    (drops features) and CNN [B, C, H, W] (drops channels)."""

    rate: float = 0.5

    def set_input_type(self, input_type):
        self.n_in = getattr(input_type, "size",
                            getattr(input_type, "channels", None))
        return input_type

    def apply(self, params, x, state, training, rng):
        if not training or self.rate <= 0:
            return x, state
        if x.ndim == 3:      # [B, T, F]: per (example, feature)
            shape = (x.shape[0], 1, x.shape[2])
        else:                # [B, C, *spatial]: per (example, channel)
            shape = (x.shape[0], x.shape[1]) + (1,) * (x.ndim - 2)
        keep = 1.0 - self.rate
        m = jax.random.bernoulli(rng, keep, shape)
        return x * m.astype(x.dtype) / keep, state

    def apply_masked(self, params, x, state, training, rng, fmask):
        y, st = self.apply(params, x, state, training, rng)
        if y.ndim == 3:
            y = y * fmask[:, :, None].astype(y.dtype)
        return y, st

    @property
    def has_params(self):
        return False
