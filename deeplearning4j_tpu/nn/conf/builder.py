"""Network configuration builders.

Reference: dl4j-nn ``org.deeplearning4j.nn.conf.NeuralNetConfiguration.Builder``
→ ``.list()`` → ``MultiLayerConfiguration`` (SURVEY.md §2.3): global defaults
(updater, weight init, activation, l1/l2, seed) cascade onto layers that don't
set their own; ``setInputType`` walks the layer list inferring nIn and
inserting preprocessors. Configs serialize to JSON and are the model file's
topology section (ModelSerializer contract, §5.4).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

import numpy as np

from ...learning.schedules import ISchedule
from ...learning.updaters import GradientUpdater, Sgd, _BY_NAME as _UPDATERS
from ..losses import ILossFunction
from . import layers as L
from .inputs import (CNNFlatInput, CNNInput, FFInput, InputType, Preprocessor,
                     RNNInput, cnn_to_ff, flat_to_cnn, rnn_to_ff)


@dataclass
class GlobalConf:
    seed: int = 12345
    updater: GradientUpdater = field(default_factory=lambda: Sgd(1e-1))
    weight_init: str = "xavier"
    activation: str = "identity"
    l1: float = 0.0
    l2: float = 0.0
    dropout: float = 0.0
    grad_normalization: Optional[str] = None      # clip modes
    grad_norm_threshold: float = 1.0
    dtype: str = "float32"                # parameter storage dtype
    # Mixed precision: forward/backward compute dtype (e.g. "bfloat16" for the
    # MXU) while params stay in `dtype` and the loss reduces in float32.
    compute_dtype: Optional[str] = None
    # Rematerialization: wrap each layer apply in jax.checkpoint so the
    # backward pass recomputes activations instead of storing them —
    # trades FLOPs for HBM (the TPU answer to big models / long context;
    # absent from the reference, whose workspaces only recycle, not
    # recompute). Gradients are bit-identical either way.
    gradient_checkpointing: bool = False
    # Named rematerialization policy (supersedes the blanket bool above
    # when set). One of:
    #   "none"  — store every residual (the jax default; bitwise-
    #             identical to leaving both knobs off);
    #   "full"  — recompute everything (what gradient_checkpointing=True
    #             has always meant);
    #   "dots_only" — save only matmul/conv outputs, recompute the cheap
    #             elementwise tail (jax.checkpoint_policies.checkpoint_
    #             dots): the classic FLOPs-for-HBM trade that keeps the
    #             MXU-expensive results;
    #   "checkpoint_dots_with_no_batch_dims" — save only contractions
    #             with no batch dims (weight-gradient-shaped matmuls),
    #             recompute activation-shaped ones: the most aggressive
    #             named policy short of "full";
    #   [block, ...] — selective: fully rematerialize ONLY the named
    #             blocks (layer indices for MultiLayerNetwork, vertex
    #             names for ComputationGraph); everything else stores.
    # All policies change WHICH residuals are stored, never the math:
    # loss sequences are bit-identical across policies on a fixed
    # platform (pinned by tests/test_remat_policies.py).
    remat_policy: Any = None
    # Fused weight update: flatten params/grads(/updater state) into
    # Zero1Plan per-dtype buckets INSIDE the compiled step and apply the
    # updater through ops/pallas_update — one fused kernel launch per
    # bucket (a Pallas kernel on TPU, one flat XLA elementwise kernel
    # elsewhere) instead of a handful of ops per parameter leaf. fp32
    # results are bit-identical to the per-leaf path at the kernel level
    # (pinned by tests/test_precision.py); inside a full compiled step
    # XLA may fma-contract the mul-add chains differently for the flat
    # shape — Sgd stays bitwise end-to-end, the momentum/Adam family can
    # drift ≤ a few ulp (measured ≤3e-8 after 2 epochs). Composes with
    # ``updater.state_dtype`` (bf16 moments + stochastic rounding).
    # Requires an elementwise updater (falls back, warned, otherwise).
    fused_update: bool = False
    # Backward-epilogue fusion (rides on fused_update): differentiate
    # w.r.t. the plan's FLAT buckets so the cotangents accumulate
    # directly into flat layout and the dense grad pytree never
    # materializes between the backward and the updater — the
    # 2-copy→1-copy grad-epilogue fix for the HBM roofline. Bitwise
    # identical to the dense-then-flatten path (the unflatten in the
    # forward is a pure permutation, so leaf cotangents are computed by
    # the exact same ops). On by default; set False to force the legacy
    # dense-grads-then-flatten step (the bench A/B axis). Auto-disabled
    # when telemetry or a dense-tree grad-normalization mode needs the
    # dense grads.
    flat_backward: bool = True
    # Fused inference epilogue (ops/pallas_epilogue): inference-mode
    # BatchNormalization + relu/identity collapse into one kernel, and
    # ComputationGraph additionally fuses the resnet block tail
    # BN(identity) → ElementWiseVertex(add) → relu into a single
    # BN+residual+relu launch. Opt-in (the folded per-channel affine is
    # a reassociation of the dense ops — tolerance-bounded parity, never
    # silently changed numerics); shape-gated per call with a dense
    # fallback, ledgered under precision/epilogue_*. Training-mode BN
    # (batch statistics + hand VJP) is never touched.
    fused_epilogue: bool = False


#: the named policies remat_wrap resolves (selective lists are the
#: fourth, open-ended form)
REMAT_POLICIES = ("none", "full", "dots_only",
                  "checkpoint_dots_with_no_batch_dims")


def effective_remat_policy(gc: GlobalConf):
    """The policy in force: ``remat_policy`` when set, else the legacy
    ``gradient_checkpointing`` bool mapped to "full"/"none"."""
    pol = getattr(gc, "remat_policy", None)
    if pol is not None:
        return pol
    return "full" if gc.gradient_checkpointing else "none"


def remat_wrap(gc: GlobalConf, fn, block=None):
    """Apply the configured rematerialization policy to one block's
    apply function (the three wrap sites: MLN layer apply, MLN TBPTT
    segment, graph vertex apply). ``block`` is the block's identity for
    selective lists — the layer index (MLN) or vertex name (graph).
    Returns ``fn`` untouched under "none" (zero-cost default) and the
    ``jax.checkpoint``-wrapped fn otherwise; unknown policy names raise
    at step-build time, never silently store-everything."""
    pol = effective_remat_policy(gc)
    if pol == "none":
        return fn
    import jax

    if isinstance(pol, (list, tuple, set)):
        return jax.checkpoint(fn) if block in pol else fn
    if pol == "full":
        return jax.checkpoint(fn)
    if pol == "dots_only":
        return jax.checkpoint(
            fn, policy=jax.checkpoint_policies.checkpoint_dots)
    if pol == "checkpoint_dots_with_no_batch_dims":
        return jax.checkpoint(
            fn,
            policy=jax.checkpoint_policies
            .checkpoint_dots_with_no_batch_dims)
    raise ValueError(
        f"unknown remat policy {pol!r}; expected one of "
        f"{sorted(REMAT_POLICIES)} or a selective block list")


class NeuralNetConfiguration:
    @staticmethod
    def builder() -> "Builder":
        return Builder()


class Builder:
    def __init__(self) -> None:
        self._conf = GlobalConf()

    def seed(self, s: int) -> "Builder":
        self._conf.seed = int(s)
        return self

    def updater(self, u: GradientUpdater) -> "Builder":
        self._conf.updater = u
        return self

    def weight_init(self, w: str) -> "Builder":
        self._conf.weight_init = w
        return self

    def activation(self, a: str) -> "Builder":
        self._conf.activation = a
        return self

    def l1(self, v: float) -> "Builder":
        self._conf.l1 = v
        return self

    def l2(self, v: float) -> "Builder":
        self._conf.l2 = v
        return self

    def dropout(self, v: float) -> "Builder":
        self._conf.dropout = v
        return self

    def gradient_normalization(self, mode: str, threshold: float = 1.0) -> "Builder":
        self._conf.grad_normalization = mode
        self._conf.grad_norm_threshold = threshold
        return self

    def data_type(self, dtype: str) -> "Builder":
        self._conf.dtype = dtype
        return self

    def compute_dtype(self, dtype: str) -> "Builder":
        """bf16 compute with fp32 master params (TPU mixed precision)."""
        self._conf.compute_dtype = dtype
        return self

    def gradient_checkpointing(self, v: bool = True) -> "Builder":
        """Rematerialize per-layer activations in backward
        (jax.checkpoint): ~constant activation memory in depth for extra
        forward FLOPs; gradients unchanged."""
        self._conf.gradient_checkpointing = bool(v)
        return self

    def remat_policy(self, policy) -> "Builder":
        """Named rematerialization policy ("none" | "full" | "dots_only"
        | "checkpoint_dots_with_no_batch_dims") or a selective list of
        block identifiers to fully rematerialize. Supersedes
        gradient_checkpointing(); see GlobalConf.remat_policy."""
        if isinstance(policy, str) and policy not in REMAT_POLICIES:
            raise ValueError(
                f"unknown remat policy {policy!r}; expected one of "
                f"{sorted(REMAT_POLICIES)} or a selective block list")
        self._conf.remat_policy = policy
        return self

    def fused_update(self, v: bool = True) -> "Builder":
        """Apply the updater over flat per-dtype buckets in fused kernels
        (ops/pallas_update) instead of leaf-by-leaf. fp32-bitwise; see
        GlobalConf.fused_update."""
        self._conf.fused_update = bool(v)
        return self

    def fused_epilogue(self, v: bool = True) -> "Builder":
        """Fuse inference-mode BN + relu (+ the graph residual add) into
        one epilogue kernel (ops/pallas_epilogue). Tolerance-bounded vs
        the dense ops; see GlobalConf.fused_epilogue."""
        self._conf.fused_epilogue = bool(v)
        return self

    def list(self) -> "ListBuilder":
        return ListBuilder(self._conf)


class ListBuilder:
    def __init__(self, conf: GlobalConf) -> None:
        self._conf = conf
        self._layers: List[L.Layer] = []
        self._input_type: Optional[InputType] = None
        self._backprop_type = "Standard"
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def layer(self, idx_or_layer, maybe_layer: Optional[L.Layer] = None) -> "ListBuilder":
        layer = maybe_layer if maybe_layer is not None else idx_or_layer
        self._layers.append(layer)
        return self

    def set_input_type(self, input_type: InputType) -> "ListBuilder":
        self._input_type = input_type
        return self

    setInputType = set_input_type

    # -- truncated BPTT (reference: MultiLayerConfiguration.Builder
    # backpropType/tBPTTForwardLength/tBPTTBackwardLength) ---------------
    def backprop_type(self, bp: str) -> "ListBuilder":
        if bp not in ("Standard", "TruncatedBPTT"):
            raise ValueError("backprop_type must be Standard|TruncatedBPTT")
        self._backprop_type = bp
        return self

    def tbptt_fwd_length(self, k: int) -> "ListBuilder":
        self._tbptt_fwd = int(k)
        return self

    def tbptt_back_length(self, k: int) -> "ListBuilder":
        self._tbptt_back = int(k)
        return self

    def tbptt_length(self, k: int) -> "ListBuilder":
        return self.tbptt_fwd_length(k).tbptt_back_length(k)

    def build(self) -> "MultiLayerConfiguration":
        if self._backprop_type == "TruncatedBPTT" \
                and self._tbptt_fwd != self._tbptt_back:
            # DOCUMENTED DIVERGENCE: the reference supports back < fwd
            # (gradients truncated deeper than the forward segment); here one
            # lax.scan segment is both, so unequal lengths would silently do
            # something else — refuse rather than imply support.
            raise ValueError(
                "tbptt_fwd_length must equal tbptt_back_length (use "
                "tbptt_length(k)); unequal truncation windows are not "
                "supported")
        # cascade global defaults
        for l in self._layers:
            self._apply_defaults(l)
        mlc = MultiLayerConfiguration(self._conf, self._layers)
        mlc.backprop_type = self._backprop_type
        mlc.tbptt_fwd_length = self._tbptt_fwd
        mlc.tbptt_back_length = self._tbptt_back
        if self._input_type is not None:
            mlc.set_input_type(self._input_type)
        return mlc

    def _apply_defaults(self, l: L.Layer) -> None:
        apply_layer_defaults(l, self._conf)


def apply_layer_defaults(l: L.Layer, gc: GlobalConf) -> None:
    """Cascade global defaults onto a layer (shared by the list and graph
    builders — reference NeuralNetConfiguration.Builder inheritance)."""
    if l.activation is None and not isinstance(l, (L.OutputLayer, L.LossLayer)):
        l.activation = gc.activation
    if l.weight_init is None:
        l.weight_init = gc.weight_init
    if isinstance(l, L.BatchNormalization) and l.fused_epilogue is None:
        l.fused_epilogue = gc.fused_epilogue
    if l.l1 is None:
        l.l1 = gc.l1
    if l.l2 is None:
        l.l2 = gc.l2
    if l.dropout is None:
        l.dropout = gc.dropout
    inner = getattr(l, "layer", None)
    if isinstance(inner, L.Layer):
        apply_layer_defaults(inner, gc)


class MultiLayerConfiguration:
    def __init__(self, global_conf: GlobalConf, layers: List[L.Layer]):
        self.global_conf = global_conf
        self.layers = layers
        self.preprocessors: Dict[int, Preprocessor] = {}
        self.input_type: Optional[InputType] = None
        self.layer_output_types: List[InputType] = []
        self.backprop_type = "Standard"
        self.tbptt_fwd_length = 20
        self.tbptt_back_length = 20

    # --- shape inference + preprocessor insertion -----------------------
    def set_input_type(self, input_type: InputType) -> None:
        self.input_type = input_type
        self.preprocessors = {}
        self.layer_output_types = []
        cur = input_type
        for i, layer in enumerate(self.layers):
            pre = self._preprocessor_for(cur, layer)
            if pre is not None:
                self.preprocessors[i] = pre
                cur = pre.out_type
            cur = layer.set_input_type(cur)
            self.layer_output_types.append(cur)

    @staticmethod
    def _preprocessor_for(cur: InputType, layer: L.Layer) -> Optional[Preprocessor]:
        # frozen wrappers keep their inner layer's input contract
        # (transfer learning freezes CNN feature extractors whose Dense
        # heads still need the automatic CnnToFeedForward insertion)
        if isinstance(layer, L.FrozenLayer) and layer.layer is not None:
            layer = layer.layer
        ff_like = (L.DenseLayer, L.OutputLayer, L.ElementWiseMultiplicationLayer)
        if isinstance(cur, CNNFlatInput):
            return flat_to_cnn(cur)
        if isinstance(cur, CNNInput) and isinstance(layer, ff_like) \
                and not isinstance(layer, L.RnnOutputLayer):
            return cnn_to_ff(cur)
        from .inputs import CNN3DInput, cnn3d_to_ff
        if isinstance(cur, CNN3DInput) and isinstance(layer, ff_like) \
                and not isinstance(layer, L.RnnOutputLayer):
            return cnn3d_to_ff(cur)
        if isinstance(cur, RNNInput) and isinstance(layer, L.DenseLayer) \
                and not isinstance(layer, (L.OutputLayer,)):
            return rnn_to_ff(cur)
        return None

    # --- serde -----------------------------------------------------------
    def to_json(self) -> str:
        return json.dumps({
            "format_version": 1,
            "global": _ser_obj(self.global_conf),
            "layers": [_ser_obj(l) for l in self.layers],
            "input_type": _ser_obj(self.input_type) if self.input_type else None,
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
        }, indent=2)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        d = json.loads(s)
        gc = _deser_obj(d["global"])
        layers = [_deser_obj(ld) for ld in d["layers"]]
        mlc = MultiLayerConfiguration(gc, layers)
        mlc.backprop_type = d.get("backprop_type", "Standard")
        mlc.tbptt_fwd_length = d.get("tbptt_fwd_length", 20)
        mlc.tbptt_back_length = d.get("tbptt_back_length", 20)
        if d.get("input_type"):
            mlc.set_input_type(_deser_obj(d["input_type"]))
        return mlc


# --- generic dataclass (de)serialization for configs -------------------------

_CLASSES: Dict[str, type] = {}
for _mod in (L,):
    for _name in dir(_mod):
        _obj = getattr(_mod, _name)
        if isinstance(_obj, type) and dataclasses.is_dataclass(_obj):
            _CLASSES[_name] = _obj
_CLASSES["GlobalConf"] = GlobalConf
from .inputs import FFInput as _FF, RNNInput as _RNN, CNNInput as _CNN, CNNFlatInput as _CNNF  # noqa: E402
for _c in (_FF, _RNN, _CNN, _CNNF):
    _CLASSES[_c.__name__] = _c
from ...learning import schedules as _sched_mod  # noqa: E402
for _name in dir(_sched_mod):
    _obj = getattr(_sched_mod, _name)
    if isinstance(_obj, type) and dataclasses.is_dataclass(_obj):
        _CLASSES[_name] = _obj
from ...learning import updaters as _upd_mod  # noqa: E402
for _name in dir(_upd_mod):
    _obj = getattr(_upd_mod, _name)
    if isinstance(_obj, type) and dataclasses.is_dataclass(_obj):
        _CLASSES[_name] = _obj
from .. import losses as _loss_mod  # noqa: E402
for _name in dir(_loss_mod):
    _obj = getattr(_loss_mod, _name)
    if isinstance(_obj, type) and issubclass(_obj, ILossFunction) and _obj is not ILossFunction:
        _CLASSES[_name] = _obj


def _ser_obj(obj: Any) -> Any:
    if obj is None or isinstance(obj, (int, float, str, bool)):
        return obj
    if isinstance(obj, (list, tuple)):
        return {"__tuple__": [_ser_obj(v) for v in obj]} if isinstance(obj, tuple) \
            else [_ser_obj(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return {"__ndarray__": obj.tolist(), "dtype": str(obj.dtype)}
    if isinstance(obj, ILossFunction):
        return {"__class__": type(obj).__name__,
                "fields": {k: _ser_obj(v) for k, v in obj.__dict__.items()}}
    if dataclasses.is_dataclass(obj):
        fields = {}
        lambda_cls = _CLASSES.get("LambdaLayer")
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            if (lambda_cls is not None and isinstance(obj, lambda_cls)
                    and f.name == "fn" and callable(v)):
                # LambdaLayer ONLY: function bodies are not serializable —
                # the reference pattern serializes the NAME and restores
                # through the registered-lambda lookup (register_lambda).
                # Other fn-bearing objects still fail loudly below.
                if not getattr(obj, "name", ""):
                    raise TypeError(
                        "cannot serialize an unnamed LambdaLayer — give it "
                        "a unique name=... so restore can look up the "
                        "registered implementation")
                fields[f.name] = {"__lambda__": obj.name}
            else:
                fields[f.name] = _ser_obj(v)
        return {"__class__": type(obj).__name__, "fields": fields}
    if isinstance(obj, GradientUpdater):
        return {"__class__": type(obj).__name__,
                "fields": {k: _ser_obj(v) for k, v in obj.__dict__.items()}}
    raise TypeError(f"cannot serialize config object {type(obj)}")


def _deser_obj(d: Any) -> Any:
    if d is None or isinstance(d, (int, float, str, bool)):
        return d
    if isinstance(d, list):
        return [_deser_obj(v) for v in d]
    if isinstance(d, dict):
        if "__tuple__" in d:
            return tuple(_deser_obj(v) for v in d["__tuple__"])
        if "__ndarray__" in d:
            return np.asarray(d["__ndarray__"], dtype=d["dtype"])
        if "__lambda__" in d:
            from ...imports.keras_import import resolve_lambda

            return resolve_lambda(d["__lambda__"])
        if "__class__" in d:
            cls = _CLASSES[d["__class__"]]
            fields = {k: _deser_obj(v) for k, v in d["fields"].items()}
            if dataclasses.is_dataclass(cls):
                known = {f.name for f in dataclasses.fields(cls)}
                init_args = {k: v for k, v in fields.items() if k in known}
                obj = cls(**init_args)
                for k, v in fields.items():
                    if k not in known:
                        setattr(obj, k, v)
                return obj
            obj = cls.__new__(cls)
            obj.__dict__.update(fields)
            return obj
        return {k: _deser_obj(v) for k, v in d.items()}
    return d
