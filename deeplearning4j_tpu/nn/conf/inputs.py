"""Input type system + preprocessors.

Reference: dl4j-nn ``org.deeplearning4j.nn.conf.inputs.InputType`` (FF / RNN /
CNN / CNNFlat) and ``org.deeplearning4j.nn.conf.preprocessor.*``
(CnnToFeedForwardPreProcessor etc.). ``setInputType`` on the builder walks the
layer list, infers nIn for each layer, and inserts preprocessors at
representation boundaries — same contract here.

Data formats (TPU-first divergence, documented): CNN activations are NCHW like
the reference; RNN activations are **[batch, time, size]** (time-major middle)
rather than DL4J's [batch, size, time] — batch-leading time series map better
onto lax.scan and keep the feature dim minor for the VPU. Masks are [batch,
time].
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Tuple

import jax.numpy as jnp


class InputType:
    @staticmethod
    def feed_forward(size: int) -> "FFInput":
        return FFInput(size)

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "RNNInput":
        return RNNInput(size, timesteps)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "CNNInput":
        return CNNInput(channels, height, width)

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "CNNFlatInput":
        return CNNFlatInput(channels, height, width)

    @staticmethod
    def convolutional_3d(depth: int, height: int, width: int,
                         channels: int) -> "CNN3DInput":
        return CNN3DInput(channels, depth, height, width)


@dataclass(frozen=True)
class FFInput(InputType):
    size: int


@dataclass(frozen=True)
class RNNInput(InputType):
    size: int
    timesteps: Optional[int] = None


@dataclass(frozen=True)
class CNNInput(InputType):
    channels: int
    height: int
    width: int


@dataclass(frozen=True)
class CNN3DInput(InputType):
    """5-D volumetric input [B, C, D, H, W] (reference InputType.InputTypeConvolutional3D)."""

    channels: int
    depth: int
    height: int
    width: int


@dataclass(frozen=True)
class CNNFlatInput(InputType):
    channels: int
    height: int
    width: int


@dataclass
class Preprocessor:
    """Shape adapter inserted between layers (InputPreProcessor analog)."""

    name: str
    fn: Callable
    out_type: InputType

    def __call__(self, x):
        return self.fn(x)


def cnn_to_ff(t: CNNInput) -> Preprocessor:
    size = t.channels * t.height * t.width
    return Preprocessor("CnnToFeedForward",
                        lambda x: x.reshape(x.shape[0], -1), FFInput(size))


def cnn3d_to_ff(t: "CNN3DInput") -> Preprocessor:
    """Reference Cnn3DToFeedForwardPreProcessor analog (NCDHW flatten)."""
    size = t.channels * t.depth * t.height * t.width
    return Preprocessor("Cnn3DToFeedForward",
                        lambda x: x.reshape(x.shape[0], -1), FFInput(size))


def ff_to_cnn(t: FFInput, c: int, h: int, w: int) -> Preprocessor:
    return Preprocessor("FeedForwardToCnn",
                        lambda x: x.reshape(x.shape[0], c, h, w), CNNInput(c, h, w))


def flat_to_cnn(t: CNNFlatInput) -> Preprocessor:
    c, h, w = t.channels, t.height, t.width
    return Preprocessor("CnnFlatToCnn",
                        lambda x: x.reshape(x.shape[0], c, h, w), CNNInput(c, h, w))


def rnn_to_ff(t: RNNInput) -> Preprocessor:
    """[B, T, F] -> [B*T, F] (per-timestep dense application)."""
    return Preprocessor("RnnToFeedForward",
                        lambda x: x.reshape(-1, x.shape[-1]), FFInput(t.size))


def ff_to_rnn(t: FFInput, timesteps: int) -> Preprocessor:
    return Preprocessor("FeedForwardToRnn",
                        lambda x: x.reshape(-1, timesteps, x.shape[-1]),
                        RNNInput(t.size, timesteps))
