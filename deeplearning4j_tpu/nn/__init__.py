from .multilayer import MultiLayerNetwork
from .conf.builder import NeuralNetConfiguration, MultiLayerConfiguration
from .conf.inputs import InputType
from .conf import layers
