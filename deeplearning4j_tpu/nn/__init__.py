from .multilayer import MultiLayerNetwork
from .conf.builder import NeuralNetConfiguration, MultiLayerConfiguration
from .conf.inputs import InputType
from .conf import layers
from .graph import (ComputationGraph, ComputationGraphConfiguration, GraphBuilder,
                    MergeVertex, ElementWiseVertex, SubsetVertex, ScaleVertex,
                    ShiftVertex, L2NormalizeVertex, StackVertex, UnstackVertex)
from .transfer import (TransferLearning, TransferLearningHelper,
                       FineTuneConfiguration)
