"""Loss functions (ILossFunction SPI).

Reference: nd4j-api ``org.nd4j.linalg.lossfunctions.impl.{LossMCXENT,
LossBinaryXENT, LossMSE, LossL1, LossL2, LossMAE, LossHinge, LossSquaredHinge,
LossKLD, LossPoisson, LossCosineProximity, LossFMeasure, LossMixtureDensity,
LossWasserstein, LossSparseMCXENT}`` (SURVEY.md §2.1). Each computes a
per-example score from (labels, pre-output, activation) with optional label
weights and per-example/timestep masks — the DL4J contract where the loss owns
applying the output activation.

All math is traceable jax; the gradient comes from jax.grad of the whole
network, so the reference's hand-written ``computeGradient`` methods are
unnecessary (same analytic results via autodiff).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from .activations import activation_fn

_EPS = 1e-7


class ILossFunction:
    name = "base"

    def score_array(self, labels, pre_output, activation: str, mask=None):
        """Per-example loss [batch] (reference scoreArray)."""
        raise NotImplementedError

    def compute_score(self, labels, pre_output, activation: str, mask=None,
                      average: bool = True):
        per = self.score_array(labels, pre_output, activation, mask)
        return jnp.mean(per) if average else jnp.sum(per)

    def __call__(self, labels, pre_output, activation: str = "identity", mask=None):
        return self.compute_score(labels, pre_output, activation, mask)

    # --- helpers -------------------------------------------------------
    @staticmethod
    def _activate(pre_output, activation: str):
        return activation_fn(activation)(pre_output)

    @staticmethod
    def _apply_mask(per_element, mask):
        """mask: [batch] or [batch, time] broadcastable over per-element loss."""
        if mask is None:
            return per_element
        m = mask
        while m.ndim < per_element.ndim:
            m = m[..., None]
        return per_element * m

    @staticmethod
    def _sum_per_example(per_element):
        if per_element.ndim <= 1:
            return per_element
        return jnp.sum(per_element, axis=tuple(range(1, per_element.ndim)))


class LossMCXENT(ILossFunction):
    """Multi-class cross-entropy; expects softmax activation. Numerically
    fused: when activation == softmax, works on logits via log_softmax."""

    name = "mcxent"

    def __init__(self, weights=None, softmax_clip_eps: float = 1e-10):
        self.weights = weights
        self.eps = softmax_clip_eps

    def score_array(self, labels, pre_output, activation: str = "softmax", mask=None):
        if activation.lower() == "softmax":
            logp = jax.nn.log_softmax(pre_output, axis=-1)
        else:
            p = self._activate(pre_output, activation)
            logp = jnp.log(jnp.clip(p, self.eps, 1.0))
        w = jnp.asarray(self.weights) if self.weights is not None else 1.0
        per_el = -(labels * logp * w)
        per_el = self._apply_mask(per_el, mask)
        return self._sum_per_example(per_el)


class LossSparseMCXENT(LossMCXENT):
    name = "sparse_mcxent"

    def score_array(self, labels, pre_output, activation: str = "softmax", mask=None):
        logp = jax.nn.log_softmax(pre_output, axis=-1)
        idx = labels.astype(jnp.int32)
        if idx.ndim == pre_output.ndim:  # [..., 1]
            idx = idx[..., 0]
        per = -jnp.take_along_axis(logp, idx[..., None], axis=-1)[..., 0]
        per = self._apply_mask(per, mask)
        return self._sum_per_example(per)


class LossBinaryXENT(ILossFunction):
    name = "binary_xent"

    def __init__(self, weights=None, clip_eps: float = 1e-5):
        self.weights = weights
        self.eps = clip_eps

    def score_array(self, labels, pre_output, activation: str = "sigmoid", mask=None):
        if activation.lower() == "sigmoid":
            # stable form on logits
            x = pre_output
            per_el = jnp.maximum(x, 0) - x * labels + jnp.log1p(jnp.exp(-jnp.abs(x)))
        else:
            p = jnp.clip(self._activate(pre_output, activation), self.eps, 1 - self.eps)
            per_el = -(labels * jnp.log(p) + (1 - labels) * jnp.log1p(-p))
        if self.weights is not None:
            per_el = per_el * jnp.asarray(self.weights)
        per_el = self._apply_mask(per_el, mask)
        return self._sum_per_example(per_el)


class LossMSE(ILossFunction):
    name = "mse"

    def score_array(self, labels, pre_output, activation: str = "identity", mask=None):
        out = self._activate(pre_output, activation)
        per_el = jnp.square(labels - out)
        per_el = self._apply_mask(per_el, mask)
        # reference LossMSE divides by nOut (mean over output dims)
        n_out = per_el.shape[-1] if per_el.ndim > 1 else 1
        return self._sum_per_example(per_el) / n_out


class LossL2(ILossFunction):
    name = "l2"

    def score_array(self, labels, pre_output, activation: str = "identity", mask=None):
        out = self._activate(pre_output, activation)
        per_el = self._apply_mask(jnp.square(labels - out), mask)
        return self._sum_per_example(per_el)


class LossMAE(ILossFunction):
    name = "mae"

    def score_array(self, labels, pre_output, activation: str = "identity", mask=None):
        out = self._activate(pre_output, activation)
        per_el = self._apply_mask(jnp.abs(labels - out), mask)
        n_out = per_el.shape[-1] if per_el.ndim > 1 else 1
        return self._sum_per_example(per_el) / n_out


class LossL1(ILossFunction):
    name = "l1"

    def score_array(self, labels, pre_output, activation: str = "identity", mask=None):
        out = self._activate(pre_output, activation)
        per_el = self._apply_mask(jnp.abs(labels - out), mask)
        return self._sum_per_example(per_el)


class LossHinge(ILossFunction):
    name = "hinge"

    def score_array(self, labels, pre_output, activation: str = "identity", mask=None):
        out = self._activate(pre_output, activation)
        signed = 2.0 * labels - 1.0
        per_el = self._apply_mask(jnp.maximum(0.0, 1.0 - signed * out), mask)
        return self._sum_per_example(per_el)


class LossSquaredHinge(ILossFunction):
    name = "squared_hinge"

    def score_array(self, labels, pre_output, activation: str = "identity", mask=None):
        out = self._activate(pre_output, activation)
        signed = 2.0 * labels - 1.0
        per_el = self._apply_mask(jnp.square(jnp.maximum(0.0, 1.0 - signed * out)), mask)
        return self._sum_per_example(per_el)


class LossKLD(ILossFunction):
    name = "kld"

    def score_array(self, labels, pre_output, activation: str = "softmax", mask=None):
        p = jnp.clip(self._activate(pre_output, activation), _EPS, 1.0)
        l = jnp.clip(labels, _EPS, 1.0)
        per_el = self._apply_mask(labels * (jnp.log(l) - jnp.log(p)), mask)
        return self._sum_per_example(per_el)


class LossPoisson(ILossFunction):
    name = "poisson"

    def score_array(self, labels, pre_output, activation: str = "identity", mask=None):
        out = self._activate(pre_output, activation)
        per_el = out - labels * jnp.log(jnp.maximum(out, _EPS))
        per_el = self._apply_mask(per_el, mask)
        return self._sum_per_example(per_el)


class LossCosineProximity(ILossFunction):
    name = "cosine_proximity"

    def score_array(self, labels, pre_output, activation: str = "identity", mask=None):
        out = self._activate(pre_output, activation)
        ln = labels / jnp.maximum(jnp.linalg.norm(labels, axis=-1, keepdims=True), _EPS)
        on = out / jnp.maximum(jnp.linalg.norm(out, axis=-1, keepdims=True), _EPS)
        per = -jnp.sum(ln * on, axis=-1)
        if mask is not None:
            per = per * mask
        if per.ndim > 1:
            per = jnp.sum(per, axis=tuple(range(1, per.ndim)))
        return per


class LossWasserstein(ILossFunction):
    name = "wasserstein"

    def score_array(self, labels, pre_output, activation: str = "identity", mask=None):
        out = self._activate(pre_output, activation)
        per_el = self._apply_mask(labels * out, mask)
        n_out = per_el.shape[-1] if per_el.ndim > 1 else 1
        return self._sum_per_example(per_el) / n_out


class LossFMeasure(ILossFunction):
    """Differentiable (soft) F-beta on binary outputs (reference LossFMeasure:
    batch-level, non-decomposable — score_array returns the batch value
    broadcast per example)."""

    name = "fmeasure"

    def __init__(self, beta: float = 1.0):
        self.beta = beta

    def score_array(self, labels, pre_output, activation: str = "sigmoid", mask=None):
        out = self._activate(pre_output, activation)
        if out.ndim > 1 and out.shape[-1] == 2:  # two-column one-hot form
            out = out[..., 1]
            labels = labels[..., 1]
        if mask is not None:
            out = out * mask
            labels = labels * mask
        tp = jnp.sum(labels * out)
        fp = jnp.sum((1 - labels) * out)
        fn = jnp.sum(labels * (1 - out))
        b2 = self.beta ** 2
        f = (1 + b2) * tp / jnp.maximum((1 + b2) * tp + b2 * fn + fp, _EPS)
        # batch-level loss broadcast per example: mean() recovers (1-f)
        n = labels.shape[0]
        return jnp.full((n,), 1.0 - f)


class LossMixtureDensity(ILossFunction):
    """Mixture density network NLL (reference LossMixtureDensity): pre-output
    packs [alpha(K), sigma(K), mu(K*L)] per example; labels are [L]."""

    name = "mixture_density"

    def __init__(self, mixtures: int, labels_width: int):
        self.k = mixtures
        self.l = labels_width

    def score_array(self, labels, pre_output, activation: str = "identity", mask=None):
        k, l = self.k, self.l
        alpha = jax.nn.softmax(pre_output[..., :k], axis=-1)
        sigma = jnp.exp(pre_output[..., k:2 * k])
        mu = pre_output[..., 2 * k:2 * k + k * l].reshape(pre_output.shape[:-1] + (k, l))
        diff = labels[..., None, :] - mu                     # [..., K, L]
        sq = jnp.sum(jnp.square(diff), axis=-1)              # [..., K]
        log_comp = (jnp.log(alpha + _EPS)
                    - l * jnp.log(sigma + _EPS)
                    - 0.5 * l * jnp.log(2 * jnp.pi)
                    - sq / (2.0 * jnp.square(sigma)))
        per = -jax.scipy.special.logsumexp(log_comp, axis=-1)
        if mask is not None:
            per = per * mask
        if per.ndim > 1:
            per = jnp.sum(per, axis=tuple(range(1, per.ndim)))
        return per


_BY_NAME = {
    "mcxent": LossMCXENT, "sparse_mcxent": LossSparseMCXENT,
    "negativeloglikelihood": LossMCXENT,  # reference alias
    "binary_xent": LossBinaryXENT, "xent": LossBinaryXENT,
    "mse": LossMSE, "squared_loss": LossMSE, "l2": LossL2,
    "mae": LossMAE, "l1": LossL1,
    "hinge": LossHinge, "squared_hinge": LossSquaredHinge,
    "kl_divergence": LossKLD, "kld": LossKLD,
    "poisson": LossPoisson, "cosine_proximity": LossCosineProximity,
    "wasserstein": LossWasserstein, "fmeasure": LossFMeasure,
}


def loss_from_name(name: str, **kwargs) -> ILossFunction:
    return _BY_NAME[name.lower()](**kwargs)
