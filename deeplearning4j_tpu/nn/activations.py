"""Activation resolution.

Reference: nd4j-api ``org.nd4j.linalg.activations.Activation`` enum — the
config-level names users write. Each resolves to a registered op
(ops/transforms.py holds the math + the IActivation forward set).
"""

from __future__ import annotations

from typing import Callable

from ..ops.registry import get_op

# Activation enum name (reference spelling, lowercased) → op name
_ACTIVATION_OPS = {
    "relu": "relu",
    "relu6": "relu6",
    "leakyrelu": "leakyrelu",
    "prelu": "prelu",
    "rrelu": "leakyrelu",          # randomized leak: inference form
    "thresholdedrelu": "thresholdedrelu",
    "elu": "elu",
    "selu": "selu",
    "gelu": "gelu",
    "gelu_exact": "gelu_exact",    # erf form (Keras/TF default)
    "exp": "exp",
    "mish": "mish",
    "swish": "swish",
    "sigmoid": "sigmoid",
    "hardsigmoid": "hardsigmoid",
    "tanh": "tanh",
    "hardtanh": "hardtanh",
    "rationaltanh": "rationaltanh",
    "rectifiedtanh": "rectifiedtanh",
    "softmax": "softmax",
    "softplus": "softplus",
    "softsign": "softsign",
    "cube": "cube",
    "identity": "identity",
}


def activation_fn(name: str) -> Callable:
    name = name.lower()
    if name not in _ACTIVATION_OPS:
        raise ValueError(f"unknown activation {name!r}; known: {sorted(_ACTIVATION_OPS)}")
    return get_op(_ACTIVATION_OPS[name]).fn


def is_known(name: str) -> bool:
    return name.lower() in _ACTIVATION_OPS
