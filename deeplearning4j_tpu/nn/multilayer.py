"""MultiLayerNetwork — the north-star entry point.

Reference: dl4j-nn ``org.deeplearning4j.nn.multilayer.MultiLayerNetwork``
(~4k LoC; SURVEY.md §2.3, §3.1). API surface kept: ``init/fit/output/
feed_forward/score/evaluate/params/save``; the execution model inverted for
TPU: where the reference's fit loop makes ~100+ JNI crossings per iteration
(per-op dispatch through NativeOpExecutioner), here the WHOLE training
iteration — forward, loss, backward, updater — is one jit-compiled XLA module
with donated buffers, executed once per minibatch (SURVEY.md §7.1.1).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..common import xprof
from ..common.profiler import OpProfiler
from ..data import pipeline as _pipe
from ..data.dataset import DataSet
from ..ndarray.ndarray import NDArray
from ..ndarray.rng import get_random
from .conf.builder import MultiLayerConfiguration, remat_wrap
from .conf import layers as L


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers = conf.layers
        self._params: List[Dict[str, jnp.ndarray]] = []
        self._states: List[Dict[str, jnp.ndarray]] = []
        self._updater_state = None
        self._initialized = False
        self._iteration = 0
        self._epoch = 0
        self._listeners: List[Any] = []
        self._telemetry = None
        self._fit_step = None
        self._chunk_step = None
        self._tbptt_step = None
        self._infer_fn = None
        self._score_dev = None
        self._rnn_state_map = None

    @property
    def score_value(self) -> float:
        return float(self._score_dev) if self._score_dev is not None else float("nan")

    @score_value.setter
    def score_value(self, v) -> None:
        self._score_dev = v

    # ------------------------------------------------------------------
    def init(self, seed: Optional[int] = None) -> "MultiLayerNetwork":
        if self.conf.input_type is None:
            raise ValueError("configuration needs set_input_type(...) before init()")
        key = jax.random.PRNGKey(seed if seed is not None else self.conf.global_conf.seed)
        dtype = jnp.dtype(self.conf.global_conf.dtype)
        self._params = []
        self._states = []
        for layer in self.layers:
            key, sub = jax.random.split(key)
            self._params.append(layer.init_params(sub, dtype) if layer.has_params else {})
            self._states.append(layer.init_state())
        self._initialized = True
        return self

    def set_listeners(self, *listeners) -> None:
        self._listeners = list(listeners)
        for lst in self._listeners:
            # checkpoint-style listeners snapshot their peers' state
            # (state_dict protocol) for exact resume
            bind = getattr(lst, "bind_group", None)
            if callable(bind):
                bind(self._listeners)
        from ..optimize.telemetry import config_for

        cfg = config_for(self._listeners)
        if cfg != self._telemetry:
            # telemetry is a build-time property of the jitted step: the
            # aux pytree is computed IN-GRAPH, so flipping it rebuilds the
            # step exactly once (trace/<step> stays 1 per fit config) and
            # adds zero per-iteration host syncs
            self._telemetry = cfg
            self._fit_step = None
            self._chunk_step = None
            self._tbptt_step = None

    setListeners = set_listeners

    def set_remat_policy(self, policy) -> None:
        """Switch the rematerialization policy in place. Like telemetry,
        the policy is a build-time property of the jitted step: flipping
        it rebuilds the step exactly ONCE on the next fit (one trace/
        compile), after which the loop is steady again — asserted by
        tests/test_remat_policies.py under tracecheck."""
        if policy == self.conf.global_conf.remat_policy:
            return
        self.conf.global_conf.remat_policy = policy
        self._fit_step = None
        self._chunk_step = None
        self._tbptt_step = None

    # --- parameter access (flattened, reference params() contract) ------
    def params(self) -> NDArray:
        leaves = jax.tree.leaves(self._params)
        if not leaves:
            return NDArray(jnp.zeros((0,)))
        return NDArray(jnp.concatenate([l.ravel() for l in leaves]))

    def num_params(self) -> int:
        return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(self._params))

    def set_params(self, flat: Union[NDArray, np.ndarray]) -> None:
        vec = jnp.asarray(flat.value if isinstance(flat, NDArray) else flat)
        leaves, treedef = jax.tree.flatten(self._params)
        out, off = [], 0
        for l in leaves:
            n = int(np.prod(l.shape))
            out.append(vec[off:off + n].reshape(l.shape).astype(l.dtype))
            off += n
        if off != vec.size:
            raise ValueError(f"param vector length {vec.size} != model params {off}")
        self._params = jax.tree.unflatten(treedef, out)
        self._fit_step = None  # donated buffers were replaced
        self._chunk_step = None

    def param_table(self, layer_idx: int) -> Dict[str, NDArray]:
        return {k: NDArray(v) for k, v in self._params[layer_idx].items()}

    def _cast_compute(self, params, x):
        """Mixed precision: cast activations+params to the compute dtype (bf16
        on TPU); grads flow back through the cast to fp32 master params."""
        cd = self.conf.global_conf.compute_dtype
        if not cd:
            return params, x
        ct = jnp.dtype(cd)
        cast = lambda a: a.astype(ct) if jnp.issubdtype(a.dtype, jnp.floating) else a
        return jax.tree.map(cast, params), cast(x)

    # --- forward ---------------------------------------------------------
    def _apply_layer(self, layer, lp, x, st, training, rng, fmask,
                     idx=None):
        """One layer forward, routing through apply_masked when a
        per-timestep feature mask is present (SURVEY §5.7). Under the
        configured remat policy (GlobalConf.remat_policy, or the legacy
        gradient_checkpointing bool) the layer apply is wrapped in
        jax.checkpoint: backward rematerializes (some of) this layer's
        activations instead of keeping them live across the step. The
        selective-list form matches on the layer INDEX here."""

        def run(lp, x, st, rng, fmask):
            if layer.weight_noise is not None:
                rng, sub = jax.random.split(rng)
                lp = layer.weight_noise.apply(lp, sub, training)
            if fmask is not None:
                return layer.apply_masked(lp, x, st, training, rng, fmask)
            return layer.apply(lp, x, st, training, rng)

        if training:
            run = remat_wrap(self.conf.global_conf, run, block=idx)
        return run(lp, x, st, rng, fmask)

    def _forward(self, params, states, x, training: bool, rng, fmask=None):
        """Single traced forward pass through preprocessors + layers."""
        params, x = self._cast_compute(params, x)
        new_states = []
        for i, layer in enumerate(self.layers):
            pre = self.conf.preprocessors.get(i)
            if pre is not None:
                x = pre(x)
            if isinstance(layer, L.MaskingLayer) and fmask is None:
                # Keras Masking semantics: the mask is DERIVED in-graph and
                # threaded to downstream mask-aware layers (round-5)
                fmask = layer.derive_mask(x)
            rng, sub = jax.random.split(rng)
            x, st = self._apply_layer(layer, params[i], x, states[i],
                                      training, sub, fmask, idx=i)
            new_states.append(st)
        return x, new_states

    def _forward_to_preout(self, params, states, x, training: bool, rng,
                           fmask=None, rnn_states=None):
        """Forward stopping BEFORE the output head's activation (for loss).

        ``rnn_states`` (TBPTT): explicit recurrent carries per layer; when
        given, recurrent layers start from them and the new carries are
        returned as a third element."""
        params, x = self._cast_compute(params, x)
        new_states = []
        new_rnn = [] if rnn_states is not None else None
        for i, layer in enumerate(self.layers[:-1]):
            pre = self.conf.preprocessors.get(i)
            if pre is not None:
                x = pre(x)
            if isinstance(layer, L.MaskingLayer) and fmask is None:
                fmask = layer.derive_mask(x)   # see _forward
            rng, sub = jax.random.split(rng)
            if rnn_states is not None and layer.is_rnn():
                def run_rnn(lp, xx, rs, st, k, _l=layer):
                    return _l.apply_rnn(lp, xx, rs, st, training, k)

                if training:
                    # TBPTT recurrent segments are exactly where
                    # activation memory bites — same policy applies
                    run_rnn = remat_wrap(self.conf.global_conf, run_rnn,
                                         block=i)
                x, r, st = run_rnn(params[i], x, rnn_states[i],
                                   states[i], sub)
                if fmask is not None:
                    x = x * fmask[:, :, None].astype(x.dtype)
                new_rnn.append(r)
            else:
                x, st = self._apply_layer(layer, params[i], x, states[i],
                                          training, sub, fmask, idx=i)
                if rnn_states is not None:
                    new_rnn.append(rnn_states[i])
            new_states.append(st)
        i = len(self.layers) - 1
        pre = self.conf.preprocessors.get(i)
        if pre is not None:
            x = pre(x)
        # the output head's configured input dropout applies on this path too
        rng, sub = jax.random.split(rng)
        x = self.layers[i]._maybe_dropout(x, training, sub)
        new_states.append(states[i])  # output head is stateless; keep list aligned
        if rnn_states is not None:
            new_rnn.append(None)
            return x, new_states, new_rnn
        return x, new_states

    def output(self, x, training: bool = False, fmask=None) -> NDArray:
        """Inference forward (reference output()): one compiled module.
        ``fmask`` [B, T]: per-timestep feature mask for sequence inputs."""
        self._check_init()
        xv = jnp.asarray(x.value if isinstance(x, NDArray) else x)
        if fmask is not None:
            fmask = jnp.asarray(fmask.value if isinstance(fmask, NDArray)
                                else fmask)
        if self._infer_fn is None:
            def infer(params, states, xin, key, fm=None):
                out, _ = self._forward(params, states, xin, False, key, fm)
                return out

            self._infer_fn = xprof.register_jit("mln/infer",
                                                jax.jit(infer))
        out = self._infer_fn(self._params, self._states, xv,
                             get_random().next_key(), fmask)
        return NDArray(out)

    def feed_forward(self, x, training: bool = False) -> List[NDArray]:
        """All layer activations (reference feedForward)."""
        self._check_init()
        xv = jnp.asarray(x.value if isinstance(x, NDArray) else x)
        acts = [NDArray(xv)]
        rng = get_random().next_key()
        cur = xv
        for i, layer in enumerate(self.layers):
            pre = self.conf.preprocessors.get(i)
            if pre is not None:
                cur = pre(cur)
            rng, sub = jax.random.split(rng)
            cur, _ = layer.apply(self._params[i], cur, self._states[i], training, sub)
            acts.append(NDArray(cur))
        return acts

    # --- loss ------------------------------------------------------------
    def _loss(self, params, states, x, labels, mask, training: bool, rng,
              fmask=None, rnn_states=None, w=None, w_denom=None):
        out_layer = self.layers[-1]
        if not hasattr(out_layer, "compute_score"):
            raise ValueError("last layer must be a loss head (OutputLayer/"
                             "LossLayer/Yolo2OutputLayer/...) to train")
        # Keras Masking semantics end at the LOSS too: with a leading
        # MaskingLayer and no explicit masks, the derived mask masks the
        # per-timestep loss of a recurrent head (round-5; the reference
        # propagates feature masks into label masks the same way). Derived
        # here (not just inside the forward) so compute_score sees it.
        if fmask is None and self.layers \
                and isinstance(self.layers[0], L.MaskingLayer):
            x0 = x
            pre0 = self.conf.preprocessors.get(0)
            if pre0 is not None:
                x0 = pre0(x0)
            fmask = self.layers[0].derive_mask(jnp.asarray(x0))
        if mask is None and fmask is not None \
                and isinstance(out_layer, L.RnnOutputLayer):
            mask = fmask
        if rnn_states is not None:
            pre, new_states, new_rnn = self._forward_to_preout(
                params, states, x, training, rng, fmask, rnn_states)
        else:
            pre, new_states = self._forward_to_preout(params, states, x,
                                                      training, rng, fmask)
            new_rnn = None
        # under reduced-precision compute, run the head + loss reduction in
        # fp32; leave fp64 runs (gradient checks) untouched
        if self.conf.global_conf.compute_dtype:
            head_params = jax.tree.map(
                lambda a: (a.astype(jnp.float32)
                           if jnp.issubdtype(a.dtype, jnp.floating) else a),
                params[-1])
            if jnp.issubdtype(pre.dtype, jnp.floating):
                pre = pre.astype(jnp.float32)
        else:
            head_params = params[-1]
        if w is None:
            data_loss = out_layer.compute_score(head_params, pre, labels,
                                                mask, average=True)
        else:
            # example-weighted mean (shape-stable batching): pad rows carry
            # w=0, so the weighted sum excludes them exactly and the divisor
            # is the REAL example count — numerically the same loss the
            # unpadded batch would produce (sum over reals / n_real).
            # ``w_denom`` overrides the divisor for SPMD shards, where the
            # correct denominator is global_real/num_shards so the pmean of
            # per-shard losses equals the global mean over real examples
            # (the regularization term stays unscaled either way).
            total = out_layer.compute_score(head_params, pre, labels,
                                            _fold_weights(mask, w),
                                            average=False)
            data_loss = total / (w_denom if w_denom is not None
                                 else jnp.maximum(jnp.sum(w), 1.0))
        reg = 0.0
        gc = self.conf.global_conf
        for lp, layer in zip(params, self.layers):
            if isinstance(layer, L.FrozenLayer):
                continue  # frozen params take no updates, incl. weight decay
            l1 = layer.l1 if layer.l1 is not None else gc.l1
            l2 = layer.l2 if layer.l2 is not None else gc.l2
            for name, w in lp.items():
                if name in ("b", "beta", "mean", "var"):
                    continue  # biases/norm params excluded (reference default)
                if l2:
                    reg = reg + 0.5 * l2 * jnp.sum(jnp.square(w))
                if l1:
                    reg = reg + l1 * jnp.sum(jnp.abs(w))
        if new_rnn is not None:
            return data_loss + reg, (new_states, new_rnn)
        return data_loss + reg, new_states

    def score(self, dataset: DataSet, training: bool = False) -> float:
        self._check_init()
        x = jnp.asarray(dataset.features.value)
        y = jnp.asarray(dataset.labels.value)
        mask = jnp.asarray(dataset.labels_mask.value) if dataset.labels_mask is not None else None
        fmask = (jnp.asarray(dataset.features_mask.value)
                 if dataset.features_mask is not None else None)
        loss, _ = self._loss(self._params, self._states, x, y, mask, training,
                             get_random().next_key(), fmask)
        return float(loss)

    def compute_gradient_and_score(self, dataset: DataSet):
        """(gradients, score) — the GradientCheckUtil entry point."""
        self._check_init()
        x = jnp.asarray(dataset.features.value)
        y = jnp.asarray(dataset.labels.value)
        mask = jnp.asarray(dataset.labels_mask.value) if dataset.labels_mask is not None else None
        fmask = (jnp.asarray(dataset.features_mask.value)
                 if dataset.features_mask is not None else None)
        key = jax.random.PRNGKey(0)

        def loss_fn(params):
            loss, _ = self._loss(params, self._states, x, y, mask, False, key,
                                 fmask)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(self._params)
        self.score_value = float(loss)
        return grads, self.score_value

    # --- training --------------------------------------------------------
    def _frozen_indices(self):
        return [i for i, l in enumerate(self.layers)
                if isinstance(l, L.FrozenLayer)]

    def _fused_flat_plan(self):
        return _fused_flat_plan(self.conf, self._params)

    def _step_core(self):
        """The single train-step computation, shared verbatim by the
        per-step jit and the multi-step ``lax.scan`` dispatch so the two
        paths cannot drift numerically. When telemetry is enabled the core
        additionally returns the in-graph aux pytree (per-layer grad/
        update/param norms, update:param ratio, non-finite counts — see
        optimize.telemetry) computed inside the same compiled module.

        ``hyper`` (keyword-only, default None — the solo paths never pass
        it): a dict of TRACED per-call scalar hyperparameter overrides,
        the vmapped-fleet sweep hook (parallel.fleet). Recognized keys:
        ``lr`` replaces the updater's learning rate, ``l2`` replaces
        every layer's effective l2 (an additive delta on the loss under
        the same exclusions the base regularization applies), and
        ``dropout`` replaces the rate of every layer whose input dropout
        is configured on. Scalars must be float64 (weak-Python-float
        matching under x64) so an override equal to the baked value is
        bitwise identical to the solo step."""
        gc = self.conf.global_conf
        updater = gc.updater
        frozen = self._frozen_indices()
        tele = self._telemetry
        fused_plan = self._fused_flat_plan()
        # Backward-epilogue fusion: differentiate w.r.t. the plan's FLAT
        # buckets (the forward unflattens them — a pure permutation, so
        # the cotangents accumulate directly into flat layout and the
        # dense grad pytree never materializes between the backward and
        # the updater). Gated off when telemetry wants per-layer dense
        # grads or a grad-normalization mode defined on the dense tree is
        # configured — those keep the dense-then-flatten path.
        flat_bwd = (fused_plan is not None and tele is None
                    and not gc.grad_normalization
                    and getattr(gc, "flat_backward", True))
        from ..learning import precision as _prec
        from ..optimize import telemetry as _tel

        def core(params, states, upd_state, x, y, mask, key, iteration,
                 fmask, w, hyper=None):
            hp = {k: _weak_scalar(v) for k, v in (hyper or {}).items()}
            up = (dataclasses.replace(updater, learning_rate=hp["lr"])
                  if "lr" in hp else updater)

            def loss_fn(p):
                if "dropout" in hp:
                    with L.dropout_rate_override(hp["dropout"]):
                        loss, new_states = self._loss(p, states, x, y,
                                                      mask, True, key,
                                                      fmask, w=w)
                else:
                    loss, new_states = self._loss(p, states, x, y, mask,
                                                  True, key, fmask, w=w)
                if "l2" in hp:
                    loss = loss + _l2_delta(self.conf, self.layers, p,
                                            hp["l2"])
                return loss, new_states

            if flat_bwd:
                flat_params = fused_plan.flatten(params)
                (loss, new_states), flat_grads = jax.value_and_grad(
                    lambda fp: loss_fn(fused_plan.unflatten_diff(fp)),
                    has_aux=True)(flat_params)
                new_params, new_upd = _apply_fused_flat(
                    fused_plan, up, flat_grads, upd_state, params,
                    iteration, key, flat_params=flat_params,
                    grads_flat=True)
            else:
                (loss, new_states), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                if gc.grad_normalization:
                    grads = _normalize_gradients(
                        grads, gc.grad_normalization,
                        gc.grad_norm_threshold)
                if fused_plan is not None:
                    new_params, new_upd = _apply_fused_flat(
                        fused_plan, up, grads, upd_state, params,
                        iteration, key)
                else:
                    new_params, new_upd = _prec.apply_updater(
                        up, grads, upd_state, params, iteration, key)
            for i in frozen:
                # stop_gradient already zeroes their grads; restoring the
                # original tensors also shields them from stateful-updater
                # side effects (weight decay, momentum drift)
                new_params[i] = params[i]
            new_params = self._apply_constraints(new_params)
            if tele is None:
                return new_params, new_states, new_upd, loss
            # graftlint: disable=donated-grad-escape -- in-graph read: the
            # telemetry path runs with grads_flat=False, so _apply_fused_flat
            # flattened a COPY and XLA keeps the traced dense tree alive;
            # donation frees only jit-boundary buffers, never mid-graph values
            aux = _tel.layer_stats(params, new_params, grads, loss)
            if tele.nan_guard:
                aux, new_params, new_states, new_upd = _tel.apply_nan_guard(
                    aux, new_params, params, new_states, states, new_upd,
                    upd_state)
            return new_params, new_states, new_upd, loss, aux

        return core

    def _build_fit_step(self):
        core = self._step_core()

        def step(params, states, upd_state, x, y, mask, key, iteration,
                 fmask=None, w=None):
            OpProfiler.get().count("trace/mln_fit_step")
            return core(params, states, upd_state, x, y, mask, key,
                        iteration, fmask, w)

        return xprof.register_jit(
            "mln/fit_step", jax.jit(step, donate_argnums=(0, 1, 2)),
            donate=(0, 1, 2))

    def _build_chunk_step(self):
        """Multi-step dispatch (``steps_per_dispatch=K``): one jitted
        module runs K minibatches through a ``lax.scan`` device loop over
        the stacked chunk — Python dispatch, listener sync, and H2D fencing
        amortize over K steps."""
        core = self._step_core()
        tele = self._telemetry

        def chunk(params, states, upd_state, xs, ys, masks, keys,
                  iteration0, fmasks=None, ws=None):
            OpProfiler.get().count("trace/mln_fit_chunk")

            def body(carry, inp):
                params, states, upd_state, it = carry
                x, y, m, k, fm, w = inp
                out = core(params, states, upd_state, x, y, m, k, it, fm, w)
                if tele is None:
                    params, states, upd_state, loss = out
                    return (params, states, upd_state, it + 1), loss
                params, states, upd_state, loss, aux = out
                # aux rides the scan's stacked outputs: [K, ...] per leaf
                return (params, states, upd_state, it + 1), (loss, aux)

            (params, states, upd_state, _), ys_out = jax.lax.scan(
                body, (params, states, upd_state, iteration0),
                (xs, ys, masks, keys, fmasks, ws))
            if tele is None:
                return params, states, upd_state, ys_out
            losses, auxes = ys_out
            return params, states, upd_state, losses, auxes

        return xprof.register_jit(
            "mln/fit_chunk", jax.jit(chunk, donate_argnums=(0, 1, 2)),
            donate=(0, 1, 2))

    def _apply_constraints(self, params):
        """Project weights after each update (reference BaseConstraint —
        applied to weight params, biases/norm params excluded)."""
        out = params
        for i, layer in enumerate(self.layers):
            cs = getattr(layer, "constraints", None)
            if not cs:
                continue
            lp = dict(out[i])
            for name, w in lp.items():
                if name in ("b", "beta", "gamma", "mean", "var", "centers"):
                    continue
                for c in cs:
                    w = c.apply(w)
                lp[name] = w
            out[i] = lp
        return out

    def _build_tbptt_step(self):
        """TBPTT segment step (reference: MultiLayerNetwork
        truncatedBPTTGradient / rnnActivateUsingStoredState): gradients flow
        within the segment only — the incoming recurrent carries are jit
        inputs, so backprop truncates at the segment boundary by
        construction."""
        gc = self.conf.global_conf
        updater = gc.updater
        frozen = self._frozen_indices()
        tele = self._telemetry
        from ..learning import precision as _prec
        from ..optimize import telemetry as _tel

        def step(params, states, upd_state, rnn_states, x, y, mask, key,
                 iteration, fmask=None):
            def loss_fn(p):
                loss, aux = self._loss(p, states, x, y, mask, True, key,
                                       fmask, rnn_states)
                return loss, aux

            (loss, (new_states, new_rnn)), grads =                 jax.value_and_grad(loss_fn, has_aux=True)(params)
            if gc.grad_normalization:
                grads = _normalize_gradients(grads, gc.grad_normalization,
                                             gc.grad_norm_threshold)
            new_params, new_upd = _prec.apply_updater(
                updater, grads, upd_state, params, iteration, key)
            for i in frozen:
                new_params[i] = params[i]
            new_params = self._apply_constraints(new_params)
            if tele is None:
                return new_params, new_states, new_upd, new_rnn, loss
            aux = _tel.layer_stats(params, new_params, grads, loss)
            if tele.nan_guard:
                aux, new_params, new_states, new_upd = _tel.apply_nan_guard(
                    aux, new_params, params, new_states, states, new_upd,
                    upd_state)
                # the recurrent carries of a skipped segment are poisoned
                # too — restore them alongside the params
                ok = aux["skipped"] == 0
                new_rnn = jax.tree.map(lambda n, o: jnp.where(ok, n, o),
                                       new_rnn, rnn_states)
            return new_params, new_states, new_upd, new_rnn, loss, aux

        return xprof.register_jit(
            "mln/tbptt_step", jax.jit(step, donate_argnums=(0, 1, 2)),
            donate=(0, 1, 2))

    def fit(self, data, epochs: int = 1, batch_size: Optional[int] = None,
            *, pad_partial: Optional[bool] = None,
            drop_remainder: bool = False, prefetch: int = 2,
            steps_per_dispatch: int = 1, host_prefetch: int = 0,
            resume_from: Optional[str] = None) -> None:
        """The north-star loop (SURVEY.md §3.1): per minibatch, ONE compiled
        train-step executes forward+backward+updater on device. The host
        side runs the shared input/dispatch pipeline (data/pipeline.py):

        - ``pad_partial`` (default on when a target batch size is known):
          the final partial batch is padded to the configured batch size
          with a zero example-weight mask threaded into the loss, so the
          step compiles exactly ONCE per fit config instead of retracing
          on the remainder shape; ``drop_remainder=True`` skips it instead.
        - ``prefetch``: device placement of upcoming batches is issued this
          many batches ahead of compute (double-buffered H2D overlap;
          0 = serial feed).
        - ``steps_per_dispatch=K``: run K minibatches per Python dispatch
          through a ``lax.scan`` device loop, syncing loss/listeners once
          per chunk.
        - ``host_prefetch=N`` (opt-in): run batch assembly (slicing,
          padding, array conversion) on a worker thread through an
          N-deep queue. Leave 0 through the axon TPU relay — worker-
          thread jax array creation serializes catastrophically there
          (see data/record_iterator.py); safe on direct backends.

        NOTE on padding numerics: the padded run is numerically identical
        to the unpadded masked-loss run for per-example models (pinned
        bit-for-bit in tests). Layers with CROSS-example statistics
        (BatchNormalization) see the wrapped pad rows in their batch
        mean/variance on the final partial batch — the same deliberate
        policy ParallelWrapper has always used (in-distribution wrapped
        rows beat zero rows); pass ``drop_remainder=True`` or
        ``pad_partial=False`` if exact BN parity with the unpadded loop
        matters more than trace stability.

        ``resume_from`` (preemption recovery, SURVEY §5.3): path of a
        checkpoint written by CheckpointListener. Restores params, layer
        states, updater state, iteration/epoch counters, the RNG stream
        key, and listener state, then fast-forwards the input pipeline to
        the checkpoint's cursor — the resumed call must be given the SAME
        data/epochs/batch arguments as the killed one, and its loss
        sequence continues bit-identically (CPU, per-example models)
        where the uninterrupted run would have gone.
        """
        self._check_init()
        skip = self._begin_fit(resume_from)
        if self._updater_state is None:
            self._updater_state = self.conf.global_conf.updater.init(self._params)
        from ..learning.precision import note_state_bytes

        note_state_bytes(self._updater_state)
        if self._fit_step is None:
            self._fit_step = self._build_fit_step()

        tbptt = self.conf.backprop_type == "TruncatedBPTT"
        # Single-DataSet/tuple calls with no batch size have one stable
        # shape by construction (the bench hot loops); TBPTT has its own
        # segment loop — both stay on the serial path.
        if tbptt or (isinstance(data, (DataSet, tuple))
                     and batch_size is None):
            self._fit_serial(data, epochs, batch_size, skip=skip)
            return
        if steps_per_dispatch > 1 and self._chunk_step is None:
            self._chunk_step = self._build_chunk_step()
        prof = OpProfiler.get()

        def on_epoch():
            self._epoch += 1
            self._steps_in_epoch = 0
            for lst in self._listeners:
                if hasattr(lst, "epoch_done"):
                    lst.epoch_done(self, self._epoch)

        _pipe.run_epochs(
            data, epochs, batch_size,
            pad_partial=True if pad_partial is None else pad_partial,
            drop_remainder=drop_remainder, prefetch=prefetch,
            steps_per_dispatch=steps_per_dispatch,
            bind=self._bind_batch, place=jax.device_put,
            dispatch_one=lambda b: self._dispatch_one(b, prof),
            dispatch_chunk=lambda g: self._dispatch_chunk(g, prof),
            stackable=_same_shapes, on_epoch=on_epoch,
            host_prefetch=host_prefetch, skip=skip)

    def _begin_fit(self, resume_from: Optional[str]):
        from ..util.checkpoint import begin_fit_cursor

        return begin_fit_cursor(self, resume_from,
                                listeners=self._listeners)

    def _bind_batch(self, ds: DataSet, w):
        """DataSet → the jit argument tuple (x, y, mask, fmask, w)."""
        # PerformanceListener derives samples/sec from this
        self._last_batch_size = ds.num_examples()
        return (jnp.asarray(ds.features.value),
                jnp.asarray(ds.labels.value),
                jnp.asarray(ds.labels_mask.value)
                if ds.labels_mask is not None else None,
                jnp.asarray(ds.features_mask.value)
                if ds.features_mask is not None else None,
                w)

    def _dispatch_one(self, b, prof) -> None:
        x, y, mask, fmask, w = b
        key = get_random().next_key()
        with prof.time_section("pipeline/dispatch"):
            out = self._fit_step(self._params, self._states,
                                 self._updater_state, x, y, mask, key,
                                 jnp.asarray(self._iteration), fmask, w)
        _pipe.note_dispatch(self, self._listeners, out,
                            self._telemetry is not None)

    def _dispatch_chunk(self, group, prof) -> None:
        xs, ys, masks, fmasks, ws = _stack_batches(group)
        # keys drawn in batch order — the chunked loop consumes the SAME
        # rng stream the per-step loop would
        keys = jnp.stack([get_random().next_key() for _ in group])
        with prof.time_section("pipeline/dispatch"):
            out = self._chunk_step(self._params, self._states,
                                   self._updater_state, xs, ys, masks,
                                   keys, jnp.asarray(self._iteration),
                                   fmasks, ws)
        _pipe.note_dispatch(self, self._listeners, out,
                            self._telemetry is not None, len(group))

    def _fit_serial(self, data, epochs: int = 1,
                    batch_size: Optional[int] = None, skip=None) -> None:
        tbptt = self.conf.backprop_type == "TruncatedBPTT"
        skip_epochs, skip_steps = skip if skip is not None else (0, 0)
        for e in range(max(1, epochs)):
            if e < skip_epochs:
                # resume fast-forward: consume (advances iterator state),
                # dispatch nothing; on_epoch effects are already in the
                # restored checkpoint
                for _ in _iter_data(data, batch_size):
                    pass
                continue
            to_skip = skip_steps if e == skip_epochs else 0
            for ds in _iter_data(data, batch_size):
                if to_skip:
                    to_skip -= 1
                    continue
                x = jnp.asarray(ds.features.value)
                y = jnp.asarray(ds.labels.value)
                mask = (jnp.asarray(ds.labels_mask.value)
                        if ds.labels_mask is not None else None)
                fmask = (jnp.asarray(ds.features_mask.value)
                         if ds.features_mask is not None else None)
                key = get_random().next_key()
                # device scalars throughout; float() only on access (avoids
                # per-step sync). Listeners get the device values too and
                # sync only at their own print/collect/drain boundaries.
                if tbptt and x.ndim == 3:
                    loss, aux = self._fit_tbptt(x, y, mask, fmask, key)
                    _pipe.note_steps(self, self._listeners, [loss],
                                     [aux] if aux is not None else None)
                else:
                    out = self._fit_step(self._params, self._states,
                                         self._updater_state, x, y, mask,
                                         key, jnp.asarray(self._iteration),
                                         fmask)
                    _pipe.note_dispatch(self, self._listeners, out,
                                        self._telemetry is not None)
            self._epoch += 1
            self._steps_in_epoch = 0
            for lst in self._listeners:
                if hasattr(lst, "epoch_done"):
                    lst.epoch_done(self, self._epoch)

    def pretrain(self, data, epochs: int = 1) -> None:
        """Layerwise unsupervised pretraining (reference:
        MultiLayerNetwork.pretrain(DataSetIterator) over pretrainable
        layers — here the VariationalAutoencoder's negative ELBO). Each
        pretrainable layer is optimized on the inference-mode activations
        of the layers below it, with a fresh instance of the configured
        updater."""
        self._check_init()
        updater = self.conf.global_conf.updater
        for idx, layer in enumerate(self.layers):
            if not getattr(layer, "is_pretrain_layer", lambda: False)():
                continue

            def below(params, x, key, idx=idx):
                for i, ll in enumerate(self.layers[:idx]):
                    pre = self.conf.preprocessors.get(i)
                    if pre is not None:
                        x = pre(x)
                    key, sub = jax.random.split(key)
                    x, _ = ll.apply(params[i], x, self._states[i], False, sub)
                pre = self.conf.preprocessors.get(idx)
                return pre(x) if pre is not None else x

            def step(lp, upd_state, params, x, key, it, idx=idx,
                     layer=layer):
                feats = below(params, x, key)

                def loss_fn(p):
                    return layer.pretrain_loss(p, feats, key)

                loss, grads = jax.value_and_grad(loss_fn)(lp)
                from ..learning.precision import apply_updater

                new_lp, new_upd = apply_updater(updater, grads, upd_state,
                                                lp, it, key)
                return new_lp, new_upd, loss

            step = xprof.register_jit(
                "mln/pretrain_step",
                jax.jit(step, donate_argnums=(0, 1)), donate=(0, 1))
            lp = self._params[idx]
            upd_state = updater.init(lp)
            it = 0
            for _ in range(max(1, epochs)):
                for ds in _iter_data(data, None):
                    x = jnp.asarray(ds.features.value)
                    lp, upd_state, loss = step(
                        lp, upd_state, self._params, x,
                        get_random().next_key(), jnp.asarray(it))
                    it += 1
                    self._score_dev = loss
            self._params[idx] = lp
            self._fit_step = None
            self._chunk_step = None
            self._infer_fn = None

    def _fit_tbptt(self, x, y, mask, fmask, key):
        """Split [B, T, F] into tbptt_fwd_length segments, carrying recurrent
        state across segments (gradient truncates at each boundary)."""
        if self._tbptt_step is None:
            self._tbptt_step = self._build_tbptt_step()
        k = self.conf.tbptt_fwd_length
        T = x.shape[1]
        dtype = jnp.dtype(self.conf.global_conf.compute_dtype
                          or self.conf.global_conf.dtype)
        rnn = [l.init_rnn_state(x.shape[0], dtype) if l.is_rnn() else None
               for l in self.layers]
        loss, aux, seg_aux = None, None, None
        for s0 in range(0, T, k):
            seg = slice(s0, min(s0 + k, T))
            key, sub = jax.random.split(key)
            out = self._tbptt_step(
                self._params, self._states, self._updater_state, rnn,
                x[:, seg], y[:, seg] if y.ndim == 3 else y,
                mask[:, seg] if mask is not None and mask.ndim >= 2 else mask,
                sub, jnp.asarray(self._iteration),
                fmask[:, seg] if fmask is not None else None)
            if self._telemetry is not None:
                (self._params, self._states, self._updater_state, rnn,
                 loss, seg_aux) = out
                if aux is None:
                    aux = dict(seg_aux)
                else:
                    # norms report the FINAL segment (the one the carried
                    # params came from), but the NaN evidence accumulates
                    # across segments — a poisoned middle segment must not
                    # vanish from the iteration's aux or the NanSentinel
                    # would miss it
                    prev = aux
                    aux = dict(seg_aux)
                    for k_ in ("nonfinite", "nonfinite_total", "skipped"):
                        if k_ in seg_aux:
                            aux[k_] = prev[k_] + seg_aux[k_]
            else:
                (self._params, self._states, self._updater_state, rnn,
                 loss) = out
        return loss, aux

    # --- streaming inference (reference: MultiLayerNetwork.rnnTimeStep
    # with its per-layer stateMap) ----------------------------------------
    def rnn_time_step(self, x) -> NDArray:
        """Forward [B, T, F] (or [B, F] for one step) continuing from the
        stored recurrent state; updates the stored state."""
        self._check_init()
        xv = jnp.asarray(x.value if isinstance(x, NDArray) else x)
        if xv.ndim == 2:
            xv = xv[:, None, :]
        dtype = jnp.dtype(self.conf.global_conf.dtype)
        if self._rnn_state_map is None:
            self._rnn_state_map = [
                l.init_rnn_state(xv.shape[0], dtype) if l.is_rnn() else None
                for l in self.layers]
        cur = xv
        rng = get_random().next_key()
        for i, layer in enumerate(self.layers):
            pre = self.conf.preprocessors.get(i)
            if pre is not None:
                cur = pre(cur)
            rng, sub = jax.random.split(rng)
            if layer.is_rnn():
                cur, r, _ = layer.apply_rnn(self._params[i], cur,
                                            self._rnn_state_map[i],
                                            self._states[i], False, sub)
                self._rnn_state_map[i] = r
            else:
                cur, _ = layer.apply(self._params[i], cur, self._states[i],
                                     False, sub)
        return NDArray(cur)

    rnnTimeStep = rnn_time_step

    def rnn_clear_previous_state(self) -> None:
        self._rnn_state_map = None

    rnnClearPreviousState = rnn_clear_previous_state

    # --- evaluation -------------------------------------------------------
    def evaluate(self, data, batch_size: Optional[int] = None):
        from ..eval.evaluation import Evaluation

        ev = Evaluation()
        for ds in _iter_data(data, batch_size):
            out = self.output(ds.features, fmask=ds.features_mask)
            ev.eval(ds.labels.to_numpy(), out.to_numpy(),
                    ds.labels_mask.to_numpy() if ds.labels_mask is not None else None)
        return ev

    def evaluate_regression(self, data, batch_size: Optional[int] = None):
        from ..eval.evaluation import RegressionEvaluation

        ev = RegressionEvaluation()
        for ds in _iter_data(data, batch_size):
            out = self.output(ds.features)
            ev.eval(ds.labels.to_numpy(), out.to_numpy())
        return ev

    # --- persistence ------------------------------------------------------
    def save(self, path: str, save_updater: bool = False) -> None:
        from ..util.model_serializer import write_model

        write_model(self, path, save_updater)

    @staticmethod
    def load(path: str, load_updater: bool = False) -> "MultiLayerNetwork":
        from ..util.model_serializer import restore_multi_layer_network

        return restore_multi_layer_network(path, load_updater)

    # --- misc -------------------------------------------------------------
    def summary(self) -> str:
        lines = [f"{'idx':<4}{'layer':<28}{'out type':<28}{'params':<10}"]
        total = 0
        for i, layer in enumerate(self.layers):
            n = (sum(int(np.prod(p.shape)) for p in jax.tree.leaves(self._params[i]))
                 if self._initialized else 0)
            total += n
            ot = (self.conf.layer_output_types[i]
                  if i < len(self.conf.layer_output_types) else "?")
            lines.append(f"{i:<4}{type(layer).__name__:<28}{str(ot):<28}{n:<10}")
        lines.append(f"Total params: {total}")
        return "\n".join(lines)

    def get_layer(self, idx: int) -> L.Layer:
        return self.layers[idx]

    def n_layers(self) -> int:
        return len(self.layers)

    def _check_init(self) -> None:
        if not self._initialized:
            raise ValueError("call init() first")

    def clone(self) -> "MultiLayerNetwork":
        import copy

        net = MultiLayerNetwork(copy.deepcopy(self.conf))
        net.init()
        # REAL buffer copies (jnp.array), not aliases: the source's fit
        # step donates its param buffers, which would invalidate an
        # aliasing clone the next time the source trains
        net._params = jax.tree.map(jnp.array, self._params)
        net._states = jax.tree.map(jnp.array, self._states)
        return net


def _fused_flat_plan(conf, params):
    """The ``Zero1Plan(params, 1)`` behind ``fused_update`` — the
    single-device flat path shared by MultiLayerNetwork and
    ComputationGraph (both flatten params the same way: a pytree-keyed
    pure permutation): params/grads/updater state flatten into per-dtype
    buckets inside the step and the update runs as ONE fused kernel per
    bucket (ops/pallas_update) instead of per-leaf ops. None when the
    knob is off or the updater is not elementwise (flat application of a
    coupled updater would change the math — refuse and fall back,
    ledgered + warned)."""
    if not getattr(conf.global_conf, "fused_update", False):
        return None
    updater = conf.global_conf.updater
    if not getattr(updater, "elementwise", False):
        OpProfiler.get().count("precision/fused_fallbacks")
        import logging

        logging.getLogger("deeplearning4j_tpu").warning(
            "fused_update requested but %s does not declare "
            "elementwise=True; using the per-leaf updater path",
            type(updater).__name__)
        return None
    from ..parallel.sharding import Zero1Plan

    return Zero1Plan(params, 1)


def _apply_fused_flat(plan, updater, grads, upd_state, params, iteration,
                      key, flat_params=None, grads_flat=False):
    """The single-device fused-update body (traced into the step):
    flatten params/grads/state through ``plan``'s pure-permutation bucket
    layout, run one fused kernel per bucket, unflatten back. The model
    keeps its DENSE layouts between steps — checkpointing, listeners and
    the serializers see exactly what they always saw.

    ``grads_flat=True`` (the backward-epilogue path): ``grads`` is
    ALREADY the plan's flat-bucket dict — the backward differentiated
    w.r.t. the flat params, so no dense grad tree ever existed and no
    flatten copy is paid here. ``flat_params`` lets the caller reuse the
    flat view it already built for that backward. The trace-time
    ``precision/grads_flat_in_step`` gauge records which path the
    compiled step took (1 = grads born flat, single fused grad+update
    epilogue; 0 = legacy dense-grads-then-flatten) — the
    2-dispatch→1-dispatch claim, observable on /api/metrics."""
    from ..ops.pallas_update import apply_flat_updater

    OpProfiler.get().gauge("precision/grads_flat_in_step",
                           1 if grads_flat else 0)
    flat_p = plan.flatten(params) if flat_params is None else flat_params
    flat_g = grads if grads_flat else plan.flatten(grads)
    flat_s = (plan.flatten_state(upd_state, xp=jnp)
              if isinstance(upd_state, dict) else upd_state)
    new_flat, new_flat_s = apply_flat_updater(updater, flat_p, flat_g,
                                              flat_s, iteration, key)
    new_params = plan.unflatten(new_flat)
    new_upd = (plan.unflatten_state_inplan(new_flat_s)
               if isinstance(new_flat_s, dict) else new_flat_s)
    return new_params, new_upd


def _weak_scalar(v):
    """Re-weak-type a traced f64 hyperparameter scalar so it promotes
    EXACTLY like the Python float it overrides (a strong f64 tracer
    would widen f32 updater math to f64 — a different computation, not
    just different bits). Uses jax's internal weak-type convert — the
    same mechanism jnp uses for Python scalars; if the private API moves,
    the override still works strong-typed with ulp-level (documented)
    deviation from the baked-constant run."""
    try:
        from jax._src.lax.lax import _convert_element_type

        return _convert_element_type(v, jnp.float64, weak_type=True)
    except (ImportError, TypeError):    # pragma: no cover - jax internals
        return v


def _l2_delta(conf, layers, params, l2_m):
    """A traced per-member l2 override as an ADDITIVE delta on the solo
    loss: replacing every layer's effective l2 with ``l2_m`` equals
    adding ``0.5*(l2_m - base_l2)*sum(w^2)`` per layer under the same
    exclusions ``_loss`` applies (biases/norm params out, FrozenLayers
    take no decay). With a zero base l2 this is bitwise identical to a
    solo model configured with ``l2=l2_m`` (0.5*x and x-0 are exact);
    over a nonzero base it is mathematically equal but may differ in the
    last ulp from the directly-configured run."""
    gc = conf.global_conf
    delta = 0.0
    for lp, layer in zip(params, layers):
        if isinstance(layer, L.FrozenLayer):
            continue
        base = layer.l2 if layer.l2 is not None else gc.l2
        for name, wt in lp.items():
            if name in ("b", "beta", "mean", "var"):
                continue
            delta = delta + (0.5 * (l2_m - base)) * jnp.sum(jnp.square(wt))
    return delta


def _fold_weights(mask, w):
    """Fold per-example weights ``w`` [B] into an (optional) loss mask —
    the padded-batch contract: pad rows carry w=0, so their per-element
    loss terms multiply to exactly 0.0."""
    if mask is None:
        return w
    wb = w
    while wb.ndim < mask.ndim:
        wb = wb[..., None]
    return mask * wb


def _same_shapes(group) -> bool:
    """True when every batch tuple in the chunk has identical array shapes
    (None members must agree too) — the stacking precondition."""
    def sig(b):
        return tuple(None if a is None else tuple(a.shape) for a in b)

    first = sig(group[0])
    return all(sig(b) == first for b in group[1:])


def _stack_batches(group):
    """Stack K batch tuples [(x, y, mask, fmask, w), ...] along a new
    leading axis for the scan device loop; None columns stay None."""
    def col(i):
        if group[0][i] is None:
            return None
        return jnp.stack([b[i] for b in group])

    return col(0), col(1), col(2), col(3), col(4)


def _normalize_gradients(grads, mode: str, threshold: float):
    mode = mode.lower()
    if mode == "clipelementwiseabsolutevalue":
        return jax.tree.map(lambda g: jnp.clip(g, -threshold, threshold), grads)
    if mode == "clipl2pergradient":
        def clip(g):
            n = jnp.sqrt(jnp.sum(jnp.square(g)))
            return jnp.where(n > threshold, g * (threshold / n), g)

        return jax.tree.map(clip, grads)
    if mode == "clipl2perparamtype" or mode == "renormalizel2perlayer":
        leaves = jax.tree.leaves(grads)
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
        scale = jnp.minimum(1.0, threshold / jnp.maximum(gnorm, 1e-12))
        return jax.tree.map(lambda g: g * scale, grads)
    raise ValueError(f"unknown gradient normalization {mode!r}")


def _iter_data(data, batch_size):
    # one data protocol for serial and pipelined paths alike
    yield from _pipe.iter_datasets(data, batch_size)
