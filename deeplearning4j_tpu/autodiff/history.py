"""Training history (reference org.nd4j.autodiff.listeners.records.History)."""

from __future__ import annotations

from typing import Dict, List, Optional


class History:
    def __init__(self) -> None:
        self._epoch_losses: List[float] = []
        self._epochs: List[int] = []
        self._evaluations: Dict[str, List[float]] = {}

    def add_epoch(self, epoch: int, loss: float) -> None:
        self._epochs.append(epoch)
        self._epoch_losses.append(loss)

    def add_evaluation(self, name: str, value: float) -> None:
        self._evaluations.setdefault(name, []).append(value)

    def loss_curve(self) -> List[float]:
        return list(self._epoch_losses)

    def final_loss(self) -> Optional[float]:
        return self._epoch_losses[-1] if self._epoch_losses else None

    def __repr__(self) -> str:
        return f"History(epochs={len(self._epochs)}, final_loss={self.final_loss()})"
