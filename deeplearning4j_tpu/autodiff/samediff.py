"""SameDiff analog — symbolic DAG lowered to ONE compiled XLA module.

Reference: nd4j-api ``org.nd4j.autodiff.samediff.{SameDiff, SDVariable}``,
``internal/{AbstractSession, InferenceSession, TrainingSession}``,
``functions.DifferentialFunction`` (SURVEY.md §2.1, §3.3).

TPU-first design (SURVEY.md §7.1): where the reference walks the DAG op-by-op
through ``InferenceSession.doExec`` → one JNI crossing per op, here the DAG is
traced once into a single jax function and jit-compiled — the whole forward
(or train step, including gradients and the fused updater) is ONE XLA module.
This is the architecture the reference's own seldom-used native
``GraphExecutioner`` path (``SameDiff.asFlatBuffers`` → whole-graph C++ exec)
pointed at; on TPU it is the only path.

Autodiff: the reference builds a "grad" child graph by reverse-topo-walking
per-op ``doDiff`` rules. Here gradients come from ``jax.grad`` of the traced
function — the same reverse-mode math, derived by the compiler rather than
hand-written per op, so every differentiable registered op gets gradients for
free.

Control flow: TF1-style Enter/Exit/Merge/Switch frames are NOT reproduced;
``sd.cond`` / ``sd.while_loop`` wrap ``lax.cond`` / ``lax.while_loop`` for the
structured subset (documented divergence — XLA requires structured control
flow).
"""

from __future__ import annotations

import io
import json
import zipfile
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..common import xprof
from ..common.dtypes import DataType
from ..ndarray.ndarray import NDArray
from ..ndarray.rng import get_random
from ..learning.schedules import ISchedule
from ..learning.updaters import Adam, GradientUpdater
from ..ops.registry import all_ops, get_op

# v2: control-flow nodes ("control" key) + scope-prefixed npz array keys
_FORMAT_VERSION = 2


class VariableType:
    VARIABLE = "VARIABLE"        # trainable
    PLACEHOLDER = "PLACEHOLDER"  # fed per call
    CONSTANT = "CONSTANT"
    ARRAY = "ARRAY"              # op output


@dataclass
class _Var:
    name: str
    vtype: str
    shape: Optional[Tuple[Optional[int], ...]] = None
    dtype: str = "float32"
    value: Optional[np.ndarray] = None      # materialized for VARIABLE/CONSTANT
    producer: Optional[int] = None           # node id for ARRAY vars
    out_index: int = 0


@dataclass
class _Node:
    id: int
    op_name: str
    inputs: List[str]
    kwargs: Dict[str, Any]
    outputs: List[str]
    n_outputs: int = 1
    needs_rng: bool = False
    # Mixed positional spec: [("v", var_name) | ("s", static_value)]. Static
    # entries (shape tuples, axis ints) stay Python values so they remain
    # jit-static; None means every positional is a variable (legacy).
    arg_spec: Optional[List[Tuple[str, Any]]] = None
    # Structured control flow (op_name "__cond__"/"__while__"): nested
    # SameDiff graphs per branch + their placeholder/output name lists.
    subgraphs: Optional[Dict[str, "SameDiff"]] = None
    sub_inputs: Optional[Dict[str, List[str]]] = None
    sub_outputs: Optional[Dict[str, List[str]]] = None
    max_iters: Optional[int] = None


class SDVariable:
    """Symbolic handle into a SameDiff graph (reference SDVariable)."""

    def __init__(self, sd: "SameDiff", name: str):
        self.sd = sd
        self.name = name

    # --- metadata ------------------------------------------------------
    @property
    def shape(self):
        return self.sd._vars[self.name].shape

    def var_type(self) -> str:
        return self.sd._vars[self.name].vtype

    # --- evaluation ----------------------------------------------------
    def eval(self, placeholders: Optional[Dict[str, Any]] = None) -> NDArray:
        return self.sd.output(placeholders or {}, [self.name])[self.name]

    def arr(self) -> Optional[NDArray]:
        v = self.sd._vars[self.name]
        return NDArray(jnp.asarray(v.value)) if v.value is not None else None

    # --- graph-building operators --------------------------------------
    def _bin(self, op: str, other, reverse: bool = False):
        other_v = self.sd._lift(other)
        a, b = (other_v, self) if reverse else (self, other_v)
        return self.sd._add_op(op, [a, b])

    def __add__(self, o):
        return self._bin("add", o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._bin("subtract", o)

    def __rsub__(self, o):
        return self._bin("subtract", o, reverse=True)

    def __mul__(self, o):
        return self._bin("multiply", o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._bin("divide", o)

    def __rtruediv__(self, o):
        return self._bin("divide", o, reverse=True)

    def __pow__(self, o):
        return self._bin("pow", o)

    def __neg__(self):
        return self.sd._add_op("neg", [self])

    def __matmul__(self, o):
        return self._bin("matmul", o)

    # common math sugar (sd.math covers everything; these are convenience)
    def add(self, o):
        return self.__add__(o)

    def sub(self, o):
        return self.__sub__(o)

    def mul(self, o):
        return self.__mul__(o)

    def div(self, o):
        return self.__truediv__(o)

    def rsub(self, o):
        return self.__rsub__(o)

    def rdiv(self, o):
        return self.__rtruediv__(o)

    def mmul(self, o):
        return self.__matmul__(o)

    def dot(self, o):
        return self.sd._add_op("dot", [self, self.sd._lift(o)])

    def sum(self, *dims, keep_dims: bool = False):
        return self.sd._add_op("reduce_sum", [self],
                               dims=dims if dims else None, keep_dims=keep_dims)

    def mean(self, *dims, keep_dims: bool = False):
        return self.sd._add_op("reduce_mean", [self],
                               dims=dims if dims else None, keep_dims=keep_dims)

    def max(self, *dims, keep_dims: bool = False):
        return self.sd._add_op("reduce_max", [self],
                               dims=dims if dims else None, keep_dims=keep_dims)

    def min(self, *dims, keep_dims: bool = False):
        return self.sd._add_op("reduce_min", [self],
                               dims=dims if dims else None, keep_dims=keep_dims)

    def std(self, *dims, bias_corrected: bool = True):
        return self.sd._add_op("reduce_stdev", [self],
                               dims=dims if dims else None, bias_corrected=bias_corrected)

    def norm2(self, *dims):
        return self.sd._add_op("reduce_norm2", [self], dims=dims if dims else None)

    def argmax(self, dim: int = -1):
        return self.sd._add_op("argmax", [self], dims=dim)

    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return self.sd._add_op("reshape", [self], shape=shape)

    def permute(self, *dims):
        return self.sd._add_op("permute", [self], dims=dims)

    def transpose(self):
        return self.sd._add_op("transpose", [self])

    def rename(self, new_name: str) -> "SDVariable":
        self.sd._rename(self.name, new_name)
        self.name = new_name
        return self

    def __repr__(self):
        v = self.sd._vars[self.name]
        return f"SDVariable(name={self.name!r}, type={v.vtype}, shape={v.shape})"


class _OpNamespace:
    """sd.math / sd.nn / sd.cnn / ... facade (reference codegen namespaces
    SDMath, SDNN, SDCNN, SDRNN, SDLoss, SDRandom, SDImage, SDLinalg,
    SDBitwise). Any registered op is reachable; the namespace is resolution
    sugar, not a gate."""

    def __init__(self, sd: "SameDiff"):
        self._sd = sd

    def __getattr__(self, op_name: str):
        if op_name.startswith("_"):
            raise AttributeError(op_name)
        desc = get_op(op_name)  # raises KeyError for unknown ops

        def call(*args, name: Optional[str] = None, **kwargs):
            # Lift only tensor-likes into the graph; ints/floats/tuples stay
            # static positionals (axis/shape args must not become tracers).
            mixed = [self._sd._lift(a)
                     if isinstance(a, (SDVariable, NDArray, np.ndarray, jnp.ndarray))
                     else a
                     for a in args]
            return self._sd._add_op(op_name, mixed, name=name, **kwargs)

        return call


class SameDiff:
    """Graph container (reference SameDiff.java ~6k LoC; SURVEY.md §2.1)."""

    def __init__(self) -> None:
        self._vars: Dict[str, _Var] = {}
        self._nodes: List[_Node] = []
        self._name_counter: Dict[str, int] = {}
        self._fn_cache: Dict[Tuple, Callable] = {}
        self._training_config = None
        self._updater_state = None
        self._iteration = 0
        self._epoch = 0
        self._loss_var: Optional[str] = None
        self.math = _OpNamespace(self)
        # All namespaces resolve the same registry; aliases for API parity.
        self.nn = self.cnn = self.rnn = self.loss_ops = self.image = self.math
        self.linalg = self.random_ops = self.bitwise = self.math
        self.ops = self.math

    # ------------------------------------------------------------------
    @staticmethod
    def create() -> "SameDiff":
        return SameDiff()

    def _unique(self, base: str) -> str:
        if base not in self._vars:
            return base
        i = self._name_counter.get(base, 0) + 1
        while f"{base}_{i}" in self._vars:
            i += 1
        self._name_counter[base] = i
        return f"{base}_{i}"

    def _rename(self, old: str, new: str) -> None:
        if new in self._vars:
            raise ValueError(f"variable {new!r} already exists")
        v = self._vars.pop(old)
        v.name = new
        self._vars[new] = v
        for n in self._nodes:
            n.inputs = [new if i == old else i for i in n.inputs]
            n.outputs = [new if o == old else o for o in n.outputs]
            if n.arg_spec is not None:
                n.arg_spec = [("v", new) if (k == "v" and v == old) else (k, v)
                              for k, v in n.arg_spec]
        if self._loss_var == old:
            self._loss_var = new
        self._fn_cache.clear()

    # --- variable creation ---------------------------------------------
    def var(self, name: str, shape: Optional[Sequence[int]] = None,
            init: Union[str, NDArray, np.ndarray, None] = "xavier",
            dtype: str = "float32") -> SDVariable:
        """Trainable variable (reference sd.var)."""
        name = self._unique(name)
        if isinstance(init, (NDArray, np.ndarray, jnp.ndarray)):
            value = np.asarray(init.value if isinstance(init, NDArray) else init)
            shape = value.shape
        else:
            if shape is None:
                raise ValueError("var() needs a shape or an initial value")
            value = _initialize(tuple(shape), init or "zeros", dtype)
        self._vars[name] = _Var(name, VariableType.VARIABLE, tuple(shape),
                                str(np.asarray(value).dtype), np.asarray(value))
        self._fn_cache.clear()
        return SDVariable(self, name)

    def placeholder(self, name: str, shape: Optional[Sequence[Optional[int]]] = None,
                    dtype: str = "float32") -> SDVariable:
        name = self._unique(name)
        self._vars[name] = _Var(name, VariableType.PLACEHOLDER,
                                tuple(shape) if shape else None, dtype)
        return SDVariable(self, name)

    # reference API spelling
    placeHolder = placeholder

    def constant(self, name_or_value, value=None) -> SDVariable:
        if value is None:
            name, value = "const", name_or_value
        else:
            name = name_or_value
        name = self._unique(name)
        raw = value.value if isinstance(value, NDArray) else value
        # Bare Python scalars must not inherit the x64 default (under
        # jax_enable_x64 np.asarray(2.0) is float64, silently promoting the
        # whole graph); pin them to the framework defaults. Exact-type checks
        # only: np.float64/np.float32 scalars keep their explicit dtype.
        if type(raw) is float:
            arr = np.asarray(raw, dtype=np.float32)
        elif type(raw) is int:
            arr = np.asarray(raw,
                             dtype=np.int32 if -2**31 <= raw < 2**31 else np.int64)
        else:
            arr = np.asarray(raw)
        self._vars[name] = _Var(name, VariableType.CONSTANT, arr.shape,
                                str(arr.dtype), arr)
        return SDVariable(self, name)

    def get_variable(self, name: str) -> SDVariable:
        if name not in self._vars:
            raise KeyError(f"no variable {name!r}")
        return SDVariable(self, name)

    def convert_to_variables(self, names: Optional[Sequence[str]] = None,
                             min_size: int = 2) -> List[str]:
        """Promote CONSTANT vars to trainable VARIABLEs (reference
        ``SameDiff.convertToVariables``). Frozen TF graphs import every weight
        as a constant; fine-tuning (the BERT north-star flow, SURVEY.md §3.4)
        promotes them back. Default: all float constants with >= min_size
        elements (scalars/axis vectors stay constant)."""
        promoted = []
        targets = set(names) if names is not None else None
        for n, v in self._vars.items():
            if v.vtype != VariableType.CONSTANT:
                continue
            if targets is not None:
                if n not in targets:
                    continue
            else:
                val = np.asarray(v.value)
                if val.size < min_size or not np.issubdtype(val.dtype, np.floating):
                    continue
            v.vtype = VariableType.VARIABLE
            promoted.append(n)
        self._fn_cache.clear()
        return promoted

    convertToVariables = convert_to_variables

    def variables(self) -> List[str]:
        return [n for n, v in self._vars.items() if v.vtype == VariableType.VARIABLE]

    def placeholders(self) -> List[str]:
        return [n for n, v in self._vars.items() if v.vtype == VariableType.PLACEHOLDER]

    # --- graph building -------------------------------------------------
    def _lift(self, value) -> SDVariable:
        if isinstance(value, SDVariable):
            if value.sd is not self:
                raise ValueError("SDVariable belongs to a different SameDiff instance")
            return value
        return self.constant(value)

    def _add_op(self, op_name: str, inputs: List[Any],
                name: Optional[str] = None, n_outputs: Optional[int] = None,
                **kwargs) -> Union[SDVariable, Tuple[SDVariable, ...]]:
        desc = get_op(op_name)
        nid = len(self._nodes)
        needs_rng = desc.family == "random" or op_name in (
            "dropout", "alpha_dropout", "gaussian_dropout", "gaussian_noise")
        n_out = n_outputs or _N_OUTPUTS.get(op_name, 1)
        out_names = [self._unique(name or op_name if i == 0 else f"{name or op_name}:{i}")
                     for i in range(n_out)]
        arg_spec: List[Tuple[str, Any]] = []
        var_inputs: List[str] = []
        for a in inputs:
            if isinstance(a, SDVariable):
                arg_spec.append(("v", a.name))
                var_inputs.append(a.name)
            else:
                arg_spec.append(("s", a))
        node = _Node(nid, op_name, var_inputs, dict(kwargs),
                     out_names, n_out, needs_rng, arg_spec)
        self._nodes.append(node)
        for i, out in enumerate(out_names):
            self._vars[out] = _Var(out, VariableType.ARRAY, producer=nid, out_index=i)
        self._fn_cache.clear()
        outs = tuple(SDVariable(self, o) for o in out_names)
        return outs if n_out > 1 else outs[0]

    # --- structured control flow (reference: SameDiff.ifCond/whileLoop;
    # the TF1 Enter/Exit/Merge frame machinery of AbstractSession is NOT
    # reproduced — XLA requires structured control flow, so these lower to
    # lax.cond / lax.while_loop / lax.scan) -------------------------------
    def _build_branch(self, fn: Callable, n_args: int, tag: str):
        """Trace a branch body into a NESTED SameDiff whose placeholders are
        the branch arguments. Branch bodies see ONLY their operands (pass
        outer variables explicitly) — a closure over outer graph variables
        raises inside the body when it touches an unknown name."""
        sub = SameDiff()
        phs = [sub.placeholder(f"{tag}_arg{i}") for i in range(n_args)]
        out = fn(sub, *phs)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        for o in outs:
            if o.sd is not sub:
                raise ValueError(
                    f"{tag} body must return variables built in its own "
                    "scope (got one from the outer graph)")
        return sub, [p.name for p in phs], [o.name for o in outs]

    def _add_control(self, kind: str, inputs: List[SDVariable],
                     subgraphs, sub_inputs, sub_outputs, n_out: int,
                     name: Optional[str], max_iters: Optional[int] = None):
        nid = len(self._nodes)
        base = name or kind.strip("_")
        out_names = [self._unique(base if i == 0 else f"{base}:{i}")
                     for i in range(n_out)]
        node = _Node(nid, kind, [v.name for v in inputs], {}, out_names,
                     n_out, subgraphs=subgraphs, sub_inputs=sub_inputs,
                     sub_outputs=sub_outputs, max_iters=max_iters)
        self._nodes.append(node)
        for i, out in enumerate(out_names):
            self._vars[out] = _Var(out, VariableType.ARRAY, producer=nid,
                                   out_index=i)
        self._fn_cache.clear()
        outs = tuple(SDVariable(self, o) for o in out_names)
        return outs if n_out > 1 else outs[0]

    def cond(self, pred: SDVariable, true_fn: Callable, false_fn: Callable,
             *operands: SDVariable, name: Optional[str] = None):
        """``lax.cond`` over two traced branch bodies.

        ``true_fn(sub_sd, *args)`` / ``false_fn(sub_sd, *args)`` build their
        result from the given operands; both must return the same number of
        outputs. Differentiable — a graph containing ``cond`` trains.
        """
        pred = self._lift(pred)
        ops = [self._lift(o) for o in operands]
        sub_t, in_t, out_t = self._build_branch(true_fn, len(ops), "true")
        sub_f, in_f, out_f = self._build_branch(false_fn, len(ops), "false")
        if len(out_t) != len(out_f):
            raise ValueError(
                f"branches return different arity: {len(out_t)} vs "
                f"{len(out_f)}")
        return self._add_control(
            "__cond__", [pred] + ops,
            {"true": sub_t, "false": sub_f},
            {"true": in_t, "false": in_f},
            {"true": out_t, "false": out_f}, len(out_t), name)

    ifCond = cond

    def while_loop(self, cond_fn: Callable, body_fn: Callable,
                   *loop_vars: SDVariable, max_iters: Optional[int] = None,
                   name: Optional[str] = None):
        """``lax.while_loop`` over traced cond/body graphs.

        ``cond_fn(sub_sd, *vars) -> scalar bool``; ``body_fn(sub_sd, *vars)``
        returns the updated loop vars (same arity). Unbounded loops are
        forward-only (XLA's while has no reverse-mode rule); pass
        ``max_iters`` to lower to a masked ``lax.scan`` of fixed length,
        which IS differentiable and therefore trainable.
        """
        ops = [self._lift(v) for v in loop_vars]
        sub_c, in_c, out_c = self._build_branch(cond_fn, len(ops), "cond")
        if len(out_c) != 1:
            raise ValueError("cond_fn must return exactly one scalar")
        sub_b, in_b, out_b = self._build_branch(body_fn, len(ops), "body")
        if len(out_b) != len(ops):
            raise ValueError(
                f"body_fn must return {len(ops)} loop vars, got {len(out_b)}")
        return self._add_control(
            "__while__", ops,
            {"cond": sub_c, "body": sub_b},
            {"cond": in_c, "body": in_b},
            {"cond": out_c, "body": out_b}, len(ops), name,
            max_iters=max_iters)

    whileLoop = while_loop

    # --- lowering: DAG → one jax function -------------------------------
    def _topo_for(self, outputs: Sequence[str]) -> List[_Node]:
        needed: List[_Node] = []
        seen = set()

        def visit(var_name: str):
            v = self._vars.get(var_name)
            if v is None:
                raise KeyError(f"unknown variable {var_name!r}")
            if v.producer is None or v.producer in seen:
                return
            seen.add(v.producer)
            node = self._nodes[v.producer]
            for i in node.inputs:
                visit(i)
            needed.append(node)

        for o in outputs:
            visit(o)
        return needed

    def _make_fn(self, outputs: Tuple[str, ...], training: bool) -> Callable:
        """Build fn(params, placeholders, rng_key) -> tuple of outputs.
        The entire DAG becomes one traced function = one XLA module."""
        nodes = self._topo_for(outputs)
        consts = {n: jnp.asarray(v.value) for n, v in self._vars.items()
                  if v.vtype == VariableType.CONSTANT}

        def fn(params: Dict[str, jnp.ndarray], placeholders: Dict[str, jnp.ndarray],
               rng_key):
            env: Dict[str, Any] = {}
            env.update(consts)
            env.update(params)
            env.update(placeholders)
            key = rng_key
            for node in nodes:
                if node.op_name in ("__cond__", "__while__"):
                    key, sub = jax.random.split(key)
                    res = _lower_control(node, env, training, sub)
                    if node.n_outputs > 1:
                        for out_name, r in zip(node.outputs, res):
                            env[out_name] = r
                    else:
                        env[node.outputs[0]] = res[0]
                    continue
                desc = get_op(node.op_name)
                if node.arg_spec is not None:
                    args = [env[v] if kind == "v" else v
                            for kind, v in node.arg_spec]
                else:
                    args = [env[i] for i in node.inputs]
                kwargs = dict(node.kwargs)
                if node.needs_rng:
                    key, sub = jax.random.split(key)
                    if desc.family == "random":
                        args = [sub] + args
                    else:
                        args = [args[0], sub] + args[1:]
                if not training and node.op_name in _TRAIN_ONLY_IDENTITY:
                    res = args[0]
                else:
                    res = desc.fn(*args, **kwargs)
                if node.n_outputs > 1:
                    for out_name, r in zip(node.outputs, res):
                        env[out_name] = r
                else:
                    env[node.outputs[0]] = res
            return tuple(env[o] for o in outputs)

        return fn

    def _params(self) -> Dict[str, jnp.ndarray]:
        return {n: jnp.asarray(v.value) for n, v in self._vars.items()
                if v.vtype == VariableType.VARIABLE}

    def _jitted(self, outputs: Tuple[str, ...], training: bool) -> Callable:
        cache_key = (outputs, training)
        if cache_key not in self._fn_cache:
            fn = self._make_fn(outputs, training)
            self._fn_cache[cache_key] = xprof.register_jit(
                "samediff/exec", jax.jit(fn))
        return self._fn_cache[cache_key]

    # --- execution -------------------------------------------------------
    def output(self, placeholders: Dict[str, Any], outputs: Sequence[str],
               training: bool = False) -> Dict[str, NDArray]:
        """Reference sd.output(map, names): run the compiled module."""
        outputs = tuple(outputs)
        ph = {k: jnp.asarray(v.value if isinstance(v, NDArray) else v)
              for k, v in placeholders.items()}
        fn = self._jitted(outputs, training)
        key = get_random().next_key()
        res = fn(self._params(), ph, key)
        return {name: NDArray(r) for name, r in zip(outputs, res)}

    def batch_output(self, placeholders=None, outputs=None):
        return self.output(placeholders or {}, outputs or [])

    # --- autodiff --------------------------------------------------------
    def calculate_gradients(self, placeholders: Dict[str, Any], loss: str,
                            wrt: Optional[Sequence[str]] = None) -> Dict[str, NDArray]:
        """Gradient of `loss` w.r.t. trainable vars (reference
        sd.calculateGradients). One jitted jax.grad module, cached per
        (loss, wrt) — no hand-built grad graph, no per-op dispatch."""
        wrt = tuple(wrt) if wrt is not None else tuple(self.variables())
        ph = {k: jnp.asarray(v.value if isinstance(v, NDArray) else v)
              for k, v in placeholders.items()}
        cache_key = ("grad", loss, wrt)
        if cache_key not in self._fn_cache:
            fn = self._make_fn((loss,), training=True)

            def grad_fn(sub, rest, ph_, key):
                def loss_fn(p):
                    full = dict(rest)
                    full.update(p)
                    return jnp.sum(fn(full, ph_, key)[0])

                return jax.grad(loss_fn)(sub)

            self._fn_cache[cache_key] = xprof.register_jit(
                "samediff/grad", jax.jit(grad_fn))
        params = self._params()
        sub = {n: params.pop(n) for n in wrt}
        grads = self._fn_cache[cache_key](sub, params, ph, jax.random.PRNGKey(0))
        return {n: NDArray(g) for n, g in grads.items()}

    def grad(self, var_name: str, loss: Optional[str] = None) -> NDArray:
        loss = loss or self._require_loss()
        return self.calculate_gradients({}, loss, [var_name])[var_name]

    def _require_loss(self) -> str:
        if self._loss_var is None:
            raise ValueError("no loss variable set; call set_loss_variables or pass loss=")
        return self._loss_var

    def set_loss_variables(self, *names: str) -> None:
        self._loss_var = names[0]

    setLossVariables = set_loss_variables

    # --- training --------------------------------------------------------
    def set_training_config(self, config: "TrainingConfig") -> None:
        self._training_config = config
        self._updater_state = None
        # invalidate cached train steps: a replaced config/updater must
        # never hit a step traced with the old hyperparameters
        self._tc_version = getattr(self, "_tc_version", 0) + 1
        for k in [k for k in self._fn_cache if k[0] == "train_step"]:
            del self._fn_cache[k]

    setTrainingConfig = set_training_config

    def _train_step_fn(self, loss_name: str, ph_names: Tuple[str, ...]):
        """One fused XLA module: forward + backward + updater (the reference's
        TrainingSession materialized per-op; here it is one executable).

        Cached in ``_fn_cache`` (invalidated with it on graph mutation):
        without this, every ``fit`` call wrapped a FRESH ``jax.jit`` and
        re-traced — ~1 s of host work per call, pathological for per-batch
        fit callers like the RL learners."""
        tc0 = self._training_config
        # key on a set_training_config version counter + the updater's
        # hyperparameters — NOT object ids (CPython reuses freed addresses,
        # silently resurrecting a step traced with old settings)
        upd0 = tc0.updater
        cache_key = ("train_step", loss_name, ph_names,
                     getattr(self, "_tc_version", 0),
                     type(upd0).__name__,
                     getattr(upd0, "learning_rate", None),
                     getattr(upd0, "momentum", None),
                     tc0.l1, tc0.l2, tc0.grad_clip_value)
        cached = self._fn_cache.get(cache_key)
        if cached is not None:
            return cached
        fn = self._make_fn((loss_name,), training=True)
        tc = self._training_config
        updater = tc.updater
        l1, l2 = tc.l1, tc.l2

        def step(params, upd_state, ph, key, iteration):
            def loss_fn(p):
                loss = fn(p, ph, key)[0]
                reg = 0.0
                if l2:
                    # DL4J L2: score += 0.5*l2*||w||^2 (grad = l2*w) — matches
                    # MultiLayerNetwork._loss
                    reg = reg + 0.5 * l2 * sum(jnp.sum(jnp.square(w)) for w in p.values())
                if l1:
                    reg = reg + l1 * sum(jnp.sum(jnp.abs(w)) for w in p.values())
                return jnp.sum(loss) + reg

            loss, grads = jax.value_and_grad(loss_fn)(params)
            if tc.grad_clip_value:
                grads = jax.tree.map(
                    lambda g: jnp.clip(g, -tc.grad_clip_value, tc.grad_clip_value), grads)
            new_params, new_state = updater.apply(grads, upd_state, params, iteration)
            return new_params, new_state, loss

        jitted = xprof.register_jit(
            "samediff/fit_step", jax.jit(step, donate_argnums=(0, 1)),
            donate=(0, 1))
        self._fn_cache[cache_key] = jitted
        return jitted

    def fit(self, data=None, epochs: int = 1, batch_size: Optional[int] = None,
            feature_placeholder: Optional[str] = None,
            label_placeholder: Optional[str] = None,
            listeners: Optional[List] = None) -> "History":
        """Train against a DataSetIterator / DataSet / (features, labels) tuple.

        Placeholder binding follows the reference TrainingConfig data-layout
        contract: with exactly two placeholders, first=features, second=labels
        unless explicitly named.
        """
        from ..data.dataset import DataSet
        from .history import History

        if self._training_config is None:
            raise ValueError("call set_training_config first")
        loss_name = self._training_config.loss_name or self._require_loss()

        phs = self.placeholders()
        dict_batches = isinstance(data, dict) or (
            isinstance(data, list) and data and isinstance(data[0], dict))
        if feature_placeholder is None and label_placeholder is None:
            if dict_batches:
                pass  # batches carry their own {placeholder: array} binding
            elif len(phs) == 2:
                feature_placeholder, label_placeholder = phs[0], phs[1]
            elif len(phs) == 1:
                feature_placeholder = phs[0]
            else:
                raise ValueError("ambiguous placeholders; name them explicitly "
                                 "or feed dict batches {placeholder: array}")
        elif feature_placeholder is None:
            remaining = [p for p in phs if p != label_placeholder]
            if len(remaining) != 1:
                raise ValueError("ambiguous feature placeholder; name it explicitly")
            feature_placeholder = remaining[0]
        # an explicitly passed binding is never overridden; a missing label
        # placeholder stays None (unsupervised losses)

        params = self._params()
        if self._updater_state is None:
            self._updater_state = self._training_config.updater.init(params)
        state = self._updater_state
        step = self._train_step_fn(loss_name, tuple(phs))
        history = History()
        listeners = listeners or []
        # The jitted step donates its params/state inputs. If a step fails
        # after dispatch (OOM, NaN panic, Ctrl-C), whatever self._vars /
        # self._updater_state reference may already be deleted; the finally
        # block below restores the entry values so the model object stays
        # usable for recovery save/inspection (training progress since the
        # last successful fit/checkpoint is lost — same semantic as the
        # reference crashing mid-fit).
        entry_vals = {n: self._vars[n].value for n in params}
        try:
            return self._fit_loop(step, data, batch_size, epochs,
                                  feature_placeholder, label_placeholder,
                                  params, state, history, listeners)
        except BaseException:
            def _dead(a):
                return hasattr(a, "is_deleted") and a.is_deleted()

            for n, v0 in entry_vals.items():
                if _dead(self._vars[n].value):
                    self._vars[n].value = v0
            if self._updater_state is not None and any(
                    _dead(l) for l in jax.tree.leaves(self._updater_state)):
                self._updater_state = None  # momenta restart on next fit
            raise

    def _fit_loop(self, step, data, batch_size, epochs, feature_placeholder,
                  label_placeholder, params, state, history, listeners):
        for epoch in range(epochs):
            loss_sum, n_batches = None, 0
            for ds in _iter_batches(data, batch_size):
                if isinstance(ds, dict):
                    # multi-input binding (e.g. imported BERT: ids/types/mask
                    # + labels): batches are {placeholder_name: array}
                    ph = {k: jnp.asarray(v.value if isinstance(v, NDArray) else v)
                          for k, v in ds.items()}
                else:
                    ph = {feature_placeholder: jnp.asarray(ds.features.value)}
                    if label_placeholder is not None and ds.labels is not None:
                        ph[label_placeholder] = jnp.asarray(ds.labels.value)
                key = get_random().next_key()
                params, state, loss = step(params, state, ph, key,
                                           jnp.asarray(self._iteration))
                self._iteration += 1
                # device scalar all the way down: listeners receive it un-synced
                # and decide when to read (the multilayer/ui.stats contract);
                # fit itself syncs ONCE per epoch below via a running on-device
                # sum (O(1) memory, no variadic stack). The reference's
                # TrainingSession also floats per step — that cost is invisible
                # over JNI but serializes every step through the TPU relay here.
                loss_sum = loss if loss_sum is None else loss_sum + loss
                n_batches += 1
                if listeners:
                    # a listener may checkpoint THIS model mid-fit (e.g.
                    # CheckpointListener): expose the live post-step buffers.
                    # Reference assignment only — no host sync; the returned
                    # arrays are fresh (the donated ones were the inputs), so
                    # a save here serializes valid, current state.
                    for n, v in params.items():
                        self._vars[n].value = v
                    self._updater_state = state
                for lst in listeners:
                    lst.iteration_done(self, self._iteration, loss)
            self._epoch += 1
            if loss_sum is None:
                raise ValueError(
                    "training data yielded no batches this epoch (exhausted "
                    "iterator or empty dataset)")
            history.add_epoch(self._epoch, float(loss_sum) / n_batches)
            for lst in listeners:
                if hasattr(lst, "epoch_done"):
                    lst.epoch_done(self, self._epoch)
        # write trained values back into the graph (stateful shell)
        for n, val in params.items():
            self._vars[n].value = np.asarray(val)
        self._updater_state = state
        return history

    # --- serialization ---------------------------------------------------
    def save(self, path: str, save_updater: bool = False,
             save_updater_state: bool = False) -> None:
        """Zip container: graph.json + vars.npz (+ updater.npz).

        The reference serializes FlatBuffers (FlatGraph) readable by its C++
        executor; the schema is not reproducible here (SURVEY.md §0), so the
        container is a versioned zip with the same content inventory:
        variables, op graph, training config, optional updater state.

        ``save_updater`` is the listener-SPI spelling (matches
        MultiLayerNetwork/ComputationGraph.save, used by CheckpointListener);
        ``save_updater_state`` is the original SameDiff spelling — either works.
        """
        arrays: Dict[str, np.ndarray] = {}
        graph = self._graph_dict(arrays, "")
        graph.update({
            "loss_var": self._loss_var,
            "iteration": self._iteration,
            "epoch": self._epoch,
            "training_config": self._training_config.to_json() if self._training_config else None,
        })
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("graph.json", json.dumps(graph))
            buf = io.BytesIO()
            np.savez(buf, **arrays)
            zf.writestr("vars.npz", buf.getvalue())
            if (save_updater or save_updater_state) and self._updater_state is not None:
                from ..util.model_serializer import _savez_leaves
                zf.writestr("updater.npz", _savez_leaves(self._updater_state))

    def _graph_dict(self, arrays: Dict[str, np.ndarray],
                    prefix: str) -> Dict[str, Any]:
        """JSON-able graph structure; arrays collected into ``arrays`` under
        ``prefix`` (nested control-flow subgraphs recurse with a deeper
        prefix so one flat npz holds every scope's tensors)."""
        for n, v in self._vars.items():
            if v.value is not None:
                arrays[prefix + n] = v.value
        nodes = []
        for n in self._nodes:
            d = {"id": n.id, "op": n.op_name, "inputs": n.inputs,
                 "kwargs": _jsonify(n.kwargs), "outputs": n.outputs,
                 "n_outputs": n.n_outputs,
                 "arg_spec": [[k, _jsonify({"v": v})["v"]] for k, v in n.arg_spec]
                 if n.arg_spec is not None else None}
            if n.subgraphs is not None:
                d["control"] = {
                    "max_iters": n.max_iters,
                    "sub_inputs": n.sub_inputs,
                    "sub_outputs": n.sub_outputs,
                    "branches": {
                        tag: sub._graph_dict(arrays,
                                             f"{prefix}n{n.id}.{tag}/")
                        for tag, sub in n.subgraphs.items()},
                }
            nodes.append(d)
        return {
            "format_version": _FORMAT_VERSION,
            "variables": [
                {"name": v.name, "type": v.vtype, "shape": v.shape,
                 "dtype": v.dtype, "producer": v.producer, "out_index": v.out_index}
                for v in self._vars.values()
            ],
            "nodes": nodes,
        }

    @staticmethod
    def _from_graph_dict(graph: Dict[str, Any], arrays,
                         prefix: str) -> "SameDiff":
        sd = SameDiff()
        for v in graph["variables"]:
            pname = prefix + v["name"]
            sd._vars[v["name"]] = _Var(
                v["name"], v["type"],
                tuple(v["shape"]) if v["shape"] else None, v["dtype"],
                arrays[pname] if pname in arrays else None,
                v["producer"], v["out_index"])
        for n in graph["nodes"]:
            spec = n.get("arg_spec")
            ctl = n.get("control")
            # JSON turns kwarg tuples into lists; ops normalize internally.
            needs_rng = False
            if not n["op"].startswith("__"):
                # recompute exactly as _add_op does — the flag is derived
                # state, so serializing it would just invite skew
                desc = get_op(n["op"])
                needs_rng = desc.family == "random" or n["op"] in (
                    "dropout", "alpha_dropout", "gaussian_dropout",
                    "gaussian_noise")
            node = _Node(
                n["id"], n["op"], n["inputs"], n["kwargs"],
                n["outputs"], n["n_outputs"], needs_rng=needs_rng,
                arg_spec=[(k, tuple(v) if isinstance(v, list) and k == "s" else v)
                          for k, v in spec] if spec is not None else None)
            if ctl is not None:
                node.max_iters = ctl.get("max_iters")
                node.sub_inputs = ctl["sub_inputs"]
                node.sub_outputs = ctl["sub_outputs"]
                node.subgraphs = {
                    tag: SameDiff._from_graph_dict(
                        sub, arrays, f"{prefix}n{n['id']}.{tag}/")
                    for tag, sub in ctl["branches"].items()}
            sd._nodes.append(node)
        return sd

    @staticmethod
    def load(path: str) -> "SameDiff":
        with zipfile.ZipFile(path) as zf:
            graph = json.loads(zf.read("graph.json"))
            arrays = np.load(io.BytesIO(zf.read("vars.npz")))
            if graph["format_version"] > _FORMAT_VERSION:
                raise ValueError("file written by a newer format version")
            sd = SameDiff._from_graph_dict(graph, arrays, "")
            sd._loss_var = graph.get("loss_var")
            sd._iteration = graph.get("iteration", 0)
            sd._epoch = graph.get("epoch", 0)
            tc = graph.get("training_config")
            if tc:
                sd._training_config = TrainingConfig.from_json(tc)
            if "updater.npz" in zf.namelist() and sd._training_config is not None:
                # rebuild the state treedef from updater.init over the loaded
                # params (the model_serializer._restore pattern — works for any
                # pytree an updater returns, no schema file needed)
                from ..util.model_serializer import _load_into_tree
                template = sd._training_config.updater.init(sd._params())
                sd._updater_state = _load_into_tree(
                    zf.read("updater.npz"), template, "updater state")
        return sd

    # --- structured control flow (documented divergence from TF1 frames) --
    def summary(self) -> str:
        lines = [f"SameDiff: {len(self._vars)} vars, {len(self._nodes)} ops"]
        for v in self._vars.values():
            if v.vtype != VariableType.ARRAY:
                lines.append(f"  {v.vtype:<12} {v.name:<24} {v.shape}")
        for n in self._nodes:
            lines.append(f"  op#{n.id:<4} {n.op_name:<24} {n.inputs} -> {n.outputs}")
        return "\n".join(lines)


@dataclass
class TrainingConfig:
    """Reference org.nd4j.autodiff.samediff.TrainingConfig."""

    updater: GradientUpdater = field(default_factory=Adam)
    l1: float = 0.0
    l2: float = 0.0
    loss_name: Optional[str] = None
    grad_clip_value: Optional[float] = None

    def to_json(self) -> Dict[str, Any]:
        import dataclasses

        cfg = {}
        for k, v in self.updater.__dict__.items():
            if isinstance(v, ISchedule):
                cfg[k] = {"__schedule__": type(v).__name__,
                          "config": dataclasses.asdict(v)}
            elif isinstance(v, (int, float, str, bool)):
                cfg[k] = v
        return {
            "updater": type(self.updater).__name__,
            "updater_config": cfg,
            "l1": self.l1, "l2": self.l2, "loss_name": self.loss_name,
            "grad_clip_value": self.grad_clip_value,
        }

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "TrainingConfig":
        from ..learning import schedules as _sched
        from ..learning.updaters import _BY_NAME

        cfg = {}
        for k, v in d.get("updater_config", {}).items():
            if isinstance(v, dict) and "__schedule__" in v:
                cfg[k] = getattr(_sched, v["__schedule__"])(**v["config"])
            else:
                cfg[k] = v
        upd_cls = _BY_NAME[d["updater"].lower()]
        return TrainingConfig(
            updater=upd_cls(**cfg),
            l1=d.get("l1", 0.0), l2=d.get("l2", 0.0),
            loss_name=d.get("loss_name"),
            grad_clip_value=d.get("grad_clip_value"),
        )


# ops whose multi-output arity the builder must know
_N_OUTPUTS = {
    "moments": 2, "lstm_layer": 2, "gru_layer": 2, "simple_rnn_layer": 2,
    "sru_layer": 2, "lstm_cell": 2, "qr": 2, "svd": 3, "lu": 2,
    "log_matrix_determinant": 2, "self_adjoint_eig": 2, "top_k": 2, "unique": 2,
    "normalize_moments": 2, "sufficient_statistics": 4,
}

# train-only stochastic ops that become identity at inference
_TRAIN_ONLY_IDENTITY = {"dropout", "alpha_dropout", "gaussian_dropout", "gaussian_noise"}


def _lower_control(node: "_Node", env: Dict[str, Any], training: bool, key):
    """Lower a __cond__/__while__ node to lax control flow. Branch bodies
    are nested SameDiff graphs executed via their own _make_fn — the whole
    construct still traces into the ONE enclosing XLA module."""
    from jax import lax

    def branch_fn(tag: str):
        sub = node.subgraphs[tag]
        outs = tuple(node.sub_outputs[tag])
        fn = sub._make_fn(outs, training)
        names = node.sub_inputs[tag]

        def run(args, k):
            return fn(sub._params(), dict(zip(names, args)), k)

        return run

    if node.op_name == "__cond__":
        pred = env[node.inputs[0]]
        args = tuple(env[n] for n in node.inputs[1:])
        tb, fb = branch_fn("true"), branch_fn("false")
        return lax.cond(jnp.asarray(pred).astype(bool).reshape(()),
                        lambda a: tb(a, key), lambda a: fb(a, key), args)

    # __while__ — the rng key rides the loop carry and splits per iteration
    # so random ops in the body draw FRESH values each step
    cond_run = branch_fn("cond")
    body_run = branch_fn("body")
    args = tuple(env[n] for n in node.inputs)

    def cond_scalar(vs, k):
        return jnp.asarray(cond_run(vs, k)[0]).astype(bool).reshape(())

    # rng scheme (IDENTICAL for both lowerings so bounded and unbounded
    # runs are statistically equivalent): per iteration, the carried key
    # derives DISTINCT cond and body streams, then advances
    def iter_keys(k):
        kc = jax.random.fold_in(k, 1)
        kb = jax.random.fold_in(k, 2)
        return kc, kb, jax.random.fold_in(k, 0)

    if node.max_iters is None:
        # exact while semantics; forward-only (no reverse-mode rule in XLA)
        def wcond(carry):
            vs, k = carry
            kc, _, _ = iter_keys(k)
            return cond_scalar(vs, kc)

        def wbody(carry):
            vs, k = carry
            _, kb, k_next = iter_keys(k)
            return body_run(vs, kb), k_next

        final, _ = lax.while_loop(wcond, wbody, (args, key))
        return final

    # bounded, DIFFERENTIABLE form: fixed-length scan, iterations after the
    # condition first fails hold their values (masked update)
    def scan_step(carry, _):
        vs, k = carry
        kc, kb, k_next = iter_keys(k)
        go = cond_scalar(vs, kc)
        new_vs = body_run(vs, kb)
        held = tuple(jnp.where(go, nv, v) for v, nv in zip(vs, new_vs))
        return (held, k_next), None

    (final, _), _ = lax.scan(scan_step, (args, key), None,
                             length=node.max_iters)
    return final


def _initialize(shape: Tuple[int, ...], init: str, dtype: str) -> np.ndarray:
    rng = get_random()
    init = init.lower()
    if init == "zeros":
        return np.zeros(shape, dtype=dtype)
    if init == "ones":
        return np.ones(shape, dtype=dtype)
    fan_in = shape[0] if shape else 1
    fan_out = shape[-1] if len(shape) > 1 else 1
    if init == "xavier":
        std = float(np.sqrt(2.0 / (fan_in + fan_out)))
        return np.asarray(rng.gaussian(shape, std=std).to_numpy(), dtype=dtype)
    if init in ("relu", "he"):
        std = float(np.sqrt(2.0 / fan_in))
        return np.asarray(rng.gaussian(shape, std=std).to_numpy(), dtype=dtype)
    if init == "normal":
        return np.asarray(rng.gaussian(shape).to_numpy(), dtype=dtype)
    if init == "uniform":
        lim = float(np.sqrt(1.0 / fan_in))
        return np.asarray(rng.uniform(shape, -lim, lim).to_numpy(), dtype=dtype)
    raise ValueError(f"unknown initializer {init!r}")


def _jsonify(kwargs: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in kwargs.items():
        if isinstance(v, (np.ndarray, jnp.ndarray)):
            out[k] = np.asarray(v).tolist()
        elif isinstance(v, tuple):
            out[k] = list(v)
        else:
            out[k] = v
    return out


def _iter_batches(data, batch_size):
    """Accept DataSetIterator-like, DataSet, or (features, labels) tuple."""
    from ..data.dataset import DataSet

    if isinstance(data, dict):
        yield data  # one multi-input batch: {placeholder_name: array}
        return
    if isinstance(data, list) and data and isinstance(data[0], dict):
        yield from data
        return
    if hasattr(data, "reset") and hasattr(data, "__iter__"):
        data.reset()
        yield from data
        return
    if isinstance(data, DataSet):
        if batch_size is None:
            yield data
        else:
            yield from data.batch_by(batch_size)
        return
    if isinstance(data, tuple) and len(data) == 2:
        ds = DataSet(data[0], data[1])
        yield from _iter_batches(ds, batch_size)
        return
    raise TypeError(f"cannot iterate training data of type {type(data)}")
