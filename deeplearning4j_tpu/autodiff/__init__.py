from .samediff import SameDiff, SDVariable, TrainingConfig, VariableType
from .history import History
