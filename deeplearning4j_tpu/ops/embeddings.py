"""Fused embedding-training rounds: skip-gram / CBOW, NS + HS.

TPU-native rebuild of the reference's fused word2vec kernels (reference:
libnd4j ``ops/declarable/helpers/cpu/sg_cb.cpp`` — ``skipgram``/``cbow``
declarable ops doing fused dot/sigmoid/axpy over syn0/syn1 rows, dispatched
per center/context pair over JNI).

The TPU formulation inverts the granularity: instead of one kernel launch per
training pair, a whole BATCH of pairs becomes one jitted XLA module —
gather rows → batched dot → sigmoid → scaled error → accumulate back into
the tables. All rounds return ``(syn0', syn1', loss)``; callers jit with
``donate_argnums=(0, 1)`` so the tables update in place on device.

Table accumulation has two lowerings, selected by the static ``dense`` flag:

- ``dense=False`` (the production path): XLA scatter-add
  (``Array.at[idx].add``) — deterministic, sums duplicate indices exactly
  like the reference's serialized per-pair axpy, and touches ONLY the
  sampled rows (the reference sg_cb's O(batch·D) shape). Round-3
  re-measurement with value-fenced rep-differencing
  (``tools/w2v_update_bench.py`` on v5e): 326M rows/s at V=10k, 74M rows/s
  at V=100k — the earlier "per-row serialized ~100–200k rows/s" claim was
  a broken-fence artifact of the round-1 methodology.
- ``dense=True``: the update becomes ``onehot(idx)ᵀ @ grads`` — a bf16 MXU
  matmul accumulated into the f32 table. O(batch·V) one-hot HBM traffic
  makes it 8–16× SLOWER than scatter at every vocab measured (9.9k–100k);
  kept for MXU experiments and as a numerical cross-check in tests, never
  auto-selected.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .registry import op

# jax.enable_x64 only exists on newer jax; 0.4.x spells it
# jax.experimental.enable_x64 — same semantics (see pallas_attention)
_enable_x64 = getattr(jax, "enable_x64", None)
if _enable_x64 is None:
    from jax.experimental import enable_x64 as _enable_x64

# Vocab threshold below which SequenceVectors picks the dense one-hot MXU
# update. Round-3 measurement (module docstring) shows scatter wins at every
# size, so the threshold is 0 = never dense; the knob survives so the
# shootout in tools/w2v_update_bench.py can keep regression-checking it.
DENSE_UPDATE_MAX_ROWS = 0


def _table_add(table, idx, grads, dense: bool):
    """table[idx] += grads with the scatter or MXU-matmul lowering.

    idx [N] int32, grads [N, D]. Duplicate indices sum in both paths.
    """
    if dense:
        onehot = jax.nn.one_hot(idx, table.shape[0], dtype=jnp.bfloat16)
        return table + jnp.einsum(
            "nv,nd->vd", onehot, grads.astype(jnp.bfloat16),
            preferred_element_type=table.dtype)
    # grads may be f32 even when the table is bf16 (the NS/HS math promotes
    # through the f32 labels/lr); cast so the scatter writes table-width
    return table.at[idx].add(grads.astype(table.dtype))


def _bag_kernel(idx_ref, row_ref, mask_ref, count_ref, o_ref, *,
                n_w: int, mean: bool):
    # Grid is (B, W) with W innermost: the out block (one pooled row) is
    # revisited across the W iterations and accumulates in VMEM; the
    # table row for (b, w) is DMA'd in by the scalar-prefetch index map —
    # the [B, W, D] gathered tensor never materializes in HBM. ``idx_ref``
    # is consumed by the index maps only.
    del idx_ref
    w = pl.program_id(1)

    @pl.when(w == jnp.int32(0))
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref[...])

    o_ref[...] += row_ref[...] * mask_ref[0, 0]

    if mean:
        @pl.when(w == jnp.int32(n_w - 1))
        def _final():
            o_ref[...] = o_ref[...] / count_ref[0, 0]


def _bag_pallas(table, indices, mask, counts, mean: bool, interpret: bool):
    from jax.experimental.pallas import tpu as pltpu

    B, W = indices.shape
    D = table.shape[1]
    kernel = functools.partial(_bag_kernel, n_w=W, mean=mean)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, W),
        in_specs=[
            pl.BlockSpec((1, D), lambda b, w, idx: (idx[b, w], 0)),
            pl.BlockSpec((1, 1), lambda b, w, idx: (b, w)),
            pl.BlockSpec((1, 1), lambda b, w, idx: (b, 0)),
        ],
        out_specs=pl.BlockSpec((1, D), lambda b, w, idx: (b, 0)),
    )
    with _enable_x64(False):
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((B, D), table.dtype),
            interpret=interpret,
        )(indices.astype(jnp.int32), table, mask, counts)


@op("embedding_bag", "nlp")
def embedding_bag(table, indices, mask=None, mode: str = "mean",
                  impl: str = None):
    """Pooled embedding lookup: ``table [V, D]``, ``indices [B, W]``,
    optional ``mask [B, W]`` (0 = pad) → ``[B, D]`` masked mean/sum of
    the gathered rows — the CBOW window pooling and the
    ``EmbeddingSequenceLayer``-style bag in one op.

    ``impl="xla"`` (default off-TPU) is the reference lowering and is
    BITWISE the expression the nlp rounds always computed
    (``(table[ix] * mask).sum(1) / counts``); ``impl="pallas"`` (default
    on TPU; ``"interpret"`` for the CPU test mesh) streams one table row
    per grid step through a scalar-prefetch index map, so the [B, W, D]
    gather never hits HBM — the bandwidth fix on the lookup side. The
    pallas path is forward-only (the nlp rounds apply their updates by
    hand); differentiate through the xla path."""
    if mode not in ("mean", "sum"):
        raise ValueError(f"embedding_bag mode {mode!r}")
    if impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if mask is None:
        mask = jnp.ones(indices.shape, table.dtype)
    mask = mask.astype(table.dtype)
    counts = jnp.maximum(mask.sum(axis=1, keepdims=True), 1.0)
    if impl == "xla":
        cvecs = table[indices]                            # [B, W, D]
        h = (cvecs * mask[..., None]).sum(axis=1)
        return h / counts if mode == "mean" else h
    return _bag_pallas(table, indices, mask, counts.astype(table.dtype),
                       mode == "mean", interpret=impl == "interpret")


def _neg_round(h, u, labels, lr, pair_mask):
    """Shared NS math: h [B,D] vs u [B,K,D], labels [B,K] in {0,1}.

    Returns (grad_h [B,D], grad_u [B,K,D], loss scalar). Gradients are
    ASCENT direction pre-scaled by lr (reference sg_cb applies
    ``g = (label - sigmoid) * alpha`` then axpy)."""
    # The reference evaluates sigmoid through a lookup table clamped to
    # ±MAX_EXP=6 (libnd4j sg_cb expTable); the clamp doubles as its
    # stability mechanism — keep it so batched updates stay bounded.
    logits = jnp.clip(jnp.einsum("bd,bkd->bk", h, u), -6.0, 6.0)
    sig = jax.nn.sigmoid(logits)
    g = (labels - sig) * lr * pair_mask[:, None]          # [B, K]
    grad_h = jnp.einsum("bk,bkd->bd", g, u)
    grad_u = g[..., None] * h[:, None, :]
    # Masked mean binary-XE purely for monitoring (the reference never
    # computes a loss in sg_cb; we surface one for listeners/benches).
    eps = 1e-7
    xe = -(labels * jnp.log(sig + eps) + (1 - labels) * jnp.log(1 - sig + eps))
    denom = jnp.maximum(pair_mask.sum() * labels.shape[1], 1.0)
    loss = (xe * pair_mask[:, None]).sum() / denom
    return grad_h, grad_u, loss


@op("skipgram", "nlp")
def skipgram(syn0, syn1neg, centers, targets, labels, lr, pair_mask,
             dense: bool = False):
    """One negative-sampling skip-gram round over a batch of pairs.

    syn0 [V,D] input vectors; syn1neg [V,D] output vectors;
    centers [B] int32; targets [B,K] int32 (col 0 = true context, rest
    negatives); labels [B,K] float (1 positive / 0 negative);
    lr scalar; pair_mask [B] float zeroing padded pairs.
    """
    h = syn0[centers]                                     # [B, D]
    u = syn1neg[targets]                                  # [B, K, D]
    grad_h, grad_u, loss = _neg_round(h, u, labels, lr, pair_mask)
    d = syn0.shape[1]
    syn0 = _table_add(syn0, centers, grad_h, dense)
    syn1neg = _table_add(syn1neg, targets.reshape(-1),
                         grad_u.reshape(-1, d), dense)
    return syn0, syn1neg, loss


@op("skipgram_hs", "nlp")
def skipgram_hs(syn0, syn1, centers, points, codes, path_mask, lr, pair_mask,
                dense: bool = False):
    """One hierarchical-softmax skip-gram round.

    points/codes/path_mask [B,L]: the context word's padded Huffman path;
    HS label per inner node is ``1 - code`` (word2vec convention the
    reference's hSoftmax path implements).
    """
    h = syn0[centers]
    u = syn1[points]                                      # [B, L, D]
    labels = (1.0 - codes.astype(h.dtype)) * path_mask
    grad_h, grad_u, loss = _neg_round(h, u * path_mask[..., None],
                                      labels, lr, pair_mask)
    grad_u = grad_u * path_mask[..., None]
    d = syn0.shape[1]
    syn0 = _table_add(syn0, centers, grad_h, dense)
    syn1 = _table_add(syn1, points.reshape(-1), grad_u.reshape(-1, d), dense)
    return syn0, syn1, loss


# ---------------------------------------------------------------------------
# Row-sharded variants (the VoidParameterServer workload, SURVEY §2.4 row 4):
# tables live split over a mesh axis inside shard_map; lookups psum the
# masked local gathers (the collective IS the parameter-server round-trip)
# and updates touch only owned rows. Plain functions, not registry ops —
# they only have meaning under a bound mesh axis.


def sharded_local_offsets(table_l, ids, axis: str):
    """Global ids → (clipped local offsets, ownership mask) for a row
    shard [V/N, D] living at this device's position on ``axis``."""
    from jax import lax

    me = lax.axis_index(axis)
    v_local = table_l.shape[0]
    local = ids - me * v_local
    hit = (local >= 0) & (local < v_local)
    return jnp.clip(local, 0, v_local - 1), hit


def sharded_rows_lookup(table_l, ids, axis: str):
    """[B*] global ids → (psum-assembled rows [B*, D], (local, hit)) from a
    row-sharded table shard [V/N, D]."""
    from jax import lax

    local, hit = sharded_local_offsets(table_l, ids, axis)
    rows = table_l[local]
    rows = rows * hit[..., None].astype(rows.dtype)
    return lax.psum(rows, axis), (local, hit)


def sharded_rows_add(table_l, aux, grads):
    """Scatter-add grads into the owned rows only (duplicates sum)."""
    local, hit = aux
    g = grads * hit[..., None].astype(grads.dtype)
    return table_l.at[local].add(g.astype(table_l.dtype))


def sharded_skipgram(syn0_l, syn1_l, centers, targets, labels, lr,
                     pair_mask, axis: str):
    """:func:`skipgram` with syn0/syn1 row-sharded over ``axis`` (call
    inside shard_map). Identical math: the psum-assembled h/u rows make the
    NS round replicated; each shard then applies only its own row updates,
    so the post-round GLOBAL table state equals the single-device round."""
    h, aux_c = sharded_rows_lookup(syn0_l, centers, axis)
    B, K1 = targets.shape
    u_flat, aux_t = sharded_rows_lookup(syn1_l, targets.reshape(-1), axis)
    u = u_flat.reshape(B, K1, -1)
    grad_h, grad_u, loss = _neg_round(h, u, labels, lr, pair_mask)
    d = syn0_l.shape[1]
    syn0_l = sharded_rows_add(syn0_l, aux_c, grad_h)
    syn1_l = sharded_rows_add(syn1_l, aux_t, grad_u.reshape(-1, d))
    return syn0_l, syn1_l, loss


@op("cbow", "nlp")
def cbow(syn0, syn1neg, contexts, ctx_mask, targets, labels, lr, pair_mask,
         dense: bool = False):
    """One negative-sampling CBOW round.

    contexts [B,W] int32 window word ids, ctx_mask [B,W] float (0 = pad);
    h = masked MEAN of context vectors.
    """
    # masked-mean window pooling via the embedding_bag op (the xla impl
    # is bitwise this round's historical inline expression; on TPU the
    # pallas impl streams the gather row-by-row)
    counts = jnp.maximum(ctx_mask.sum(axis=1, keepdims=True), 1.0)
    h = embedding_bag(syn0, contexts, ctx_mask, mode="mean")
    u = syn1neg[targets]
    grad_h, grad_u, loss = _neg_round(h, u, labels, lr, pair_mask)
    d = syn0.shape[1]
    # DOCUMENTED DIVERGENCE from word2vec.c/the reference's CBOW: they apply
    # the full hidden error to EVERY context row, i.e. the true gradient of
    # the mean-forward loss times the window size. Batched accumulation
    # makes that over-scaling unstable (many windows sum into one row per
    # step), so we apply the exact gradient grad_h / |window| instead.
    gctx = (grad_h / counts)[:, None, :] * ctx_mask[..., None]  # [B, W, D]
    syn0 = _table_add(syn0, contexts.reshape(-1), gctx.reshape(-1, d), dense)
    syn1neg = _table_add(syn1neg, targets.reshape(-1),
                         grad_u.reshape(-1, d), dense)
    return syn0, syn1neg, loss


@op("cbow_hs", "nlp")
def cbow_hs(syn0, syn1, contexts, ctx_mask, points, codes, path_mask, lr,
            pair_mask, dense: bool = False):
    """One hierarchical-softmax CBOW round (center word's Huffman path)."""
    counts = jnp.maximum(ctx_mask.sum(axis=1, keepdims=True), 1.0)
    h = embedding_bag(syn0, contexts, ctx_mask, mode="mean")  # masked mean
    u = syn1[points]
    labels = (1.0 - codes.astype(h.dtype)) * path_mask
    grad_h, grad_u, loss = _neg_round(h, u * path_mask[..., None],
                                      labels, lr, pair_mask)
    grad_u = grad_u * path_mask[..., None]
    d = syn0.shape[1]
    # Exact gradient of the mean-forward loss (see cbow's divergence note).
    gctx = (grad_h / counts)[:, None, :] * ctx_mask[..., None]
    syn0 = _table_add(syn0, contexts.reshape(-1), gctx.reshape(-1, d), dense)
    syn1 = _table_add(syn1, points.reshape(-1), grad_u.reshape(-1, d), dense)
    return syn0, syn1, loss
