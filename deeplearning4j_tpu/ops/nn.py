"""Neural-net ops: convolutions, pooling, normalization, attention.

Reference: libnd4j ``include/ops/declarable/generic/nn/**`` (conv2d/conv3d/
deconv2d/depthwiseConv2d, pooling, batchnorm, lrn,
multi_head_dot_product_attention) and their CPU/CUDA helper impls
(im2col+GEMM). On TPU every conv lowers straight onto the MXU via
``lax.conv_general_dilated`` — no im2col, no vendor-lib seam needed; XLA is
the single "platform helper" (SURVEY.md §2.2).

Weight layouts follow the reference's param initializers (dl4j-nn
``org/deeplearning4j/nn/params/ConvolutionParamInitializer``):
conv W = [out, in, kH, kW] (OIHW); dense W = [nIn, nOut]. Data format default
NCHW like DL4J, with NHWC supported (NHWC is marginally friendlier to TPU
vector layout; zoo models use it internally where shapes allow).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .registry import op


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _conv_padding(padding, kernel, strides, dilation=(1, 1)):
    """DL4J uses explicit pad amounts + a 'same mode' flag; map both."""
    if isinstance(padding, str):
        return padding.upper()  # "SAME" / "VALID"
    ph, pw = _pair(padding)
    return ((ph, ph), (pw, pw))


@op("conv2d", "nn")
def conv2d(x, w, b=None, strides=(1, 1), padding=(0, 0), dilation=(1, 1),
           data_format: str = "NCHW", groups: int = 1):
    """2D convolution. x: NCHW or NHWC; w: OIHW (reference layout).

    ``groups`` maps to XLA's ``feature_group_count`` (ONNX Conv ``group``
    semantics: w is [O, I/groups, kH, kW], output channels blocked by
    group)."""
    sh, sw = _pair(strides)
    dh, dw = _pair(dilation)
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "OIHW", "NHWC"),
    )
    # no preferred_element_type: the TPU MXU already accumulates bf16 inputs
    # in fp32, and mixing it with AD breaks the transpose-conv dtype rule
    out = lax.conv_general_dilated(
        x, w, window_strides=(sh, sw), padding=_conv_padding(padding, w.shape[2:], (sh, sw)),
        rhs_dilation=(dh, dw), dimension_numbers=dn,
        feature_group_count=int(groups),
    )
    if b is not None:
        bshape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        out = out + b.reshape(bshape).astype(out.dtype)
    return out.astype(x.dtype)


@op("conv1d", "nn")
def conv1d(x, w, b=None, stride: int = 1, padding=0, dilation: int = 1,
           data_format: str = "NCW"):
    """x: [N, C, W]; w: [O, I, K]."""
    x4 = jnp.expand_dims(x, -1 if data_format == "NCW" else -2)
    w4 = jnp.expand_dims(w, -1)
    if data_format == "NCW":
        out = conv2d(x4, w4, b, strides=(stride, 1),
                     padding=padding if isinstance(padding, str) else (padding, 0),
                     dilation=(dilation, 1), data_format="NCHW")
        return jnp.squeeze(out, -1)
    out = conv2d(x4, w4, b, strides=(stride, 1),
                 padding=padding if isinstance(padding, str) else (padding, 0),
                 dilation=(dilation, 1), data_format="NHWC")
    return jnp.squeeze(out, -2)


@op("conv3d", "nn")
def conv3d(x, w, b=None, strides=(1, 1, 1), padding=(0, 0, 0), dilation=(1, 1, 1),
           data_format: str = "NCDHW"):
    """x: NCDHW; w: [O, I, kD, kH, kW]."""
    s = tuple(int(v) for v in strides)
    d = tuple(int(v) for v in dilation)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        pad = tuple((int(p), int(p)) for p in padding)
    dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NCDHW", "OIDHW", "NCDHW"))
    out = lax.conv_general_dilated(x, w, window_strides=s, padding=pad,
                                   rhs_dilation=d, dimension_numbers=dn)
    if b is not None:
        out = out + b.reshape(1, -1, 1, 1, 1).astype(out.dtype)
    return out.astype(x.dtype)


@op("deconv2d", "nn")
def deconv2d(x, w, b=None, strides=(1, 1), padding=(0, 0), data_format: str = "NCHW"):
    """Transposed conv (reference Deconvolution2D). w: [I, O, kH, kW] —
    the reference DeconvolutionParamInitializer layout [inDepth, outDepth, k, k].
    Implemented as lhs-dilated conv with the spatially-flipped, IO-swapped
    kernel, which XLA maps straight onto the MXU."""
    sh, sw = _pair(strides)
    kh, kw = w.shape[2], w.shape[3]
    if isinstance(padding, str) and padding.upper() == "SAME":
        # SAME transposed conv (output = input×stride, TF/Keras contract):
        # the gradient-of-forward-SAME-conv padding, pb_t = k-1-pb_f with
        # pb_f = max(k-s,0)//2 — lax can't take a string here because the
        # lhs is dilated
        pad = []
        for k, s in ((kh, sh), (kw, sw)):
            tot_f = max(k - s, 0)
            pb_f = tot_f // 2
            pe_f = tot_f - pb_f
            pad.append((k - 1 - pb_f, k - 1 - pe_f + max(s - k, 0)))
        pad = tuple(pad)
    else:
        ph, pw = _pair(padding)
        pad = ((kh - 1 - ph, kh - 1 - ph), (kw - 1 - pw, kw - 1 - pw))
    wt = jnp.flip(w, axis=(2, 3)).transpose(1, 0, 2, 3)  # -> [O, I, kh, kw]
    dn = lax.conv_dimension_numbers(
        x.shape, wt.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "OIHW", "NHWC"),
    )
    out = lax.conv_general_dilated(x, wt, window_strides=(1, 1),
                                   padding=pad, lhs_dilation=(sh, sw),
                                   dimension_numbers=dn)
    if b is not None:
        bshape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        out = out + b.reshape(bshape).astype(out.dtype)
    return out.astype(x.dtype)


@op("depthwise_conv2d", "nn")
def depthwise_conv2d(x, w, b=None, strides=(1, 1), padding=(0, 0), dilation=(1, 1),
                     data_format: str = "NCHW"):
    """w: [depthMult, C, kH, kW] (reference layout) — grouped conv on MXU."""
    mult, c = w.shape[0], w.shape[1]
    sh, sw = _pair(strides)
    dh, dw = _pair(dilation)
    # jax wants [O, I/groups, kH, kW] with groups=C: O = C*mult, I/groups = 1
    wg = w.transpose(1, 0, 2, 3).reshape(c * mult, 1, w.shape[2], w.shape[3])
    dn = lax.conv_dimension_numbers(
        x.shape, wg.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "OIHW", "NHWC"),
    )
    out = lax.conv_general_dilated(
        x, wg, window_strides=(sh, sw), padding=_conv_padding(padding, wg.shape[2:], (sh, sw)),
        rhs_dilation=(dh, dw), dimension_numbers=dn, feature_group_count=c,
    )
    if b is not None:
        bshape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        out = out + b.reshape(bshape).astype(out.dtype)
    return out.astype(x.dtype)


@op("sconv2d", "nn")
def sconv2d(x, depth_w, point_w=None, b=None, strides=(1, 1), padding=(0, 0),
            data_format: str = "NCHW"):
    """Separable conv: depthwise then 1x1 pointwise (reference sconv2d)."""
    out = depthwise_conv2d(x, depth_w, None, strides, padding, data_format=data_format)
    if point_w is not None:
        out = conv2d(out, point_w, None, (1, 1), (0, 0), data_format=data_format)
    if b is not None:
        bshape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        out = out + b.reshape(bshape).astype(out.dtype)
    return out


def _pool(x, kind: str, kernel, strides, padding, data_format: str = "NCHW"):
    kh, kw = _pair(kernel)
    sh, sw = _pair(strides)
    if data_format == "NCHW":
        dims, strides_full = (1, 1, kh, kw), (1, 1, sh, sw)
    else:
        dims, strides_full = (1, kh, kw, 1), (1, sh, sw, 1)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        ph, pw = _pair(padding)
        pad = ((0, 0), (0, 0), (ph, ph), (pw, pw)) if data_format == "NCHW" else \
              ((0, 0), (ph, ph), (pw, pw), (0, 0))
    if kind == "max":
        init, fn = -jnp.inf, lax.max
        out = lax.reduce_window(x, init, fn, dims, strides_full, pad)
        return out
    # avg: sum then divide by actual window size (DL4J divides by kernel area,
    # excluding padding only in 'exclude padding' mode; default includes)
    out = lax.reduce_window(x, 0.0, lax.add, dims, strides_full, pad)
    return out / (kh * kw)


@op("maxpool2d", "nn")
def maxpool2d(x, kernel=(2, 2), strides=(2, 2), padding=(0, 0), data_format: str = "NCHW"):
    return _pool(x, "max", kernel, strides, padding, data_format)


@op("avgpool2d", "nn")
def avgpool2d(x, kernel=(2, 2), strides=(2, 2), padding=(0, 0), data_format: str = "NCHW"):
    return _pool(x, "avg", kernel, strides, padding, data_format)


@op("pnormpool2d", "nn")
def pnormpool2d(x, kernel=(2, 2), strides=(2, 2), padding=(0, 0), pnorm: int = 2,
                data_format: str = "NCHW"):
    kh, kw = _pair(kernel)
    sh, sw = _pair(strides)
    dims = (1, 1, kh, kw) if data_format == "NCHW" else (1, kh, kw, 1)
    strd = (1, 1, sh, sw) if data_format == "NCHW" else (1, sh, sw, 1)
    ph, pw = _pair(padding) if not isinstance(padding, str) else (0, 0)
    pad = padding.upper() if isinstance(padding, str) else (
        ((0, 0), (0, 0), (ph, ph), (pw, pw)) if data_format == "NCHW"
        else ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    s = lax.reduce_window(jnp.abs(x) ** pnorm, 0.0, lax.add, dims, strd, pad)
    return s ** (1.0 / pnorm)


@op("maxpool3d", "nn")
def maxpool3d(x, kernel=(2, 2, 2), strides=(2, 2, 2), padding=(0, 0, 0)):
    k = tuple(int(v) for v in kernel)
    s = tuple(int(v) for v in strides)
    p = tuple((int(v), int(v)) for v in padding)
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, 1) + k, (1, 1) + s,
                             ((0, 0), (0, 0)) + p)


@op("avgpool3d", "nn")
def avgpool3d(x, kernel=(2, 2, 2), strides=(2, 2, 2), padding=(0, 0, 0)):
    k = tuple(int(v) for v in kernel)
    s = tuple(int(v) for v in strides)
    p = tuple((int(v), int(v)) for v in padding)
    out = lax.reduce_window(x, 0.0, lax.add, (1, 1) + k, (1, 1) + s,
                            ((0, 0), (0, 0)) + p)
    return out / (k[0] * k[1] * k[2])


@op("global_avgpool", "nn")
def global_avgpool(x, data_format: str = "NCHW"):
    axes = (2, 3) if data_format == "NCHW" else (1, 2)
    return jnp.mean(x, axis=axes)


@op("upsampling2d", "nn")
def upsampling2d(x, factor=(2, 2), data_format: str = "NCHW"):
    fh, fw = _pair(factor)
    if data_format == "NCHW":
        return jnp.repeat(jnp.repeat(x, fh, axis=2), fw, axis=3)
    return jnp.repeat(jnp.repeat(x, fh, axis=1), fw, axis=2)


@op("upsampling3d", "nn")
def upsampling3d(x, factor=(2, 2, 2)):
    f = tuple(int(v) for v in factor)
    x = jnp.repeat(x, f[0], axis=2)
    x = jnp.repeat(x, f[1], axis=3)
    return jnp.repeat(x, f[2], axis=4)


@op("im2col", "nn")
def im2col(x, kernel=(2, 2), strides=(1, 1), padding=(0, 0), dilation=(1, 1)):
    """Kept for reference parity/tests; convs do NOT go through im2col on TPU."""
    kh, kw = _pair(kernel)
    sh, sw = _pair(strides)
    ph, pw = _pair(padding)
    dh, dw = _pair(dilation)
    n, c, h, w = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            patches.append(xp[:, :, i * dh:i * dh + oh * sh:sh, j * dw:j * dw + ow * sw:sw])
    out = jnp.stack(patches, axis=2).reshape(n, c, kh, kw, oh, ow)
    return out


@op("batchnorm", "nn")
def batchnorm(x, mean, var, gamma=None, beta=None, epsilon: float = 1e-5, axis: int = 1):
    """Inference-form batchnorm over `axis` (channel dim; NCHW → 1)."""
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    inv = lax.rsqrt(var.reshape(shape) + epsilon)
    out = (x - mean.reshape(shape)) * inv
    if gamma is not None:
        out = out * gamma.reshape(shape)
    if beta is not None:
        out = out + beta.reshape(shape)
    return out.astype(x.dtype)


def _bn_axes_shape(ndim, channel_shape, axis):
    axes = tuple(i for i in range(ndim) if i != (axis % ndim))
    shape = [1] * ndim
    shape[axis] = channel_shape
    return axes, tuple(shape)


def _bn_fwd_impl(x, gamma, beta, pivot, axis, epsilon):
    axes, shape = _bn_axes_shape(x.ndim, x.shape[axis], axis)
    n = 1.0
    for a in axes:
        n *= x.shape[a]
    x32 = x.astype(jnp.float32)
    # SIBLING reductions over one shared input: XLA merges them into a single
    # multi-output fusion (one read of x, often fused into the producing
    # conv's epilogue). jnp.var's (x-mean)^2 form costs a second dependent
    # pass; profiled on v5e it is ~10% of the whole ResNet-50 step.
    # The sums are taken about a per-channel PIVOT so the E[d^2]-E[d]^2 form
    # does not cancel catastrophically when |mean| >> std. The pivot must be
    # INDEPENDENT of x (the BN layer passes its running mean): a pivot
    # gathered from x itself re-introduces a dependency that breaks the
    # conv-epilogue fusion (measured: +8.5 ms on the ResNet-50 v5e step).
    d = x32 - pivot.reshape(shape)
    s = jnp.sum(d, axis=axes)
    ss = jnp.sum(jnp.square(d), axis=axes)
    mean_c = s / n
    var = jnp.maximum(ss / n - jnp.square(mean_c), 0.0)
    mean = mean_c + pivot
    inv = lax.rsqrt(var + epsilon)
    out = ((x - mean.reshape(shape).astype(x.dtype))
           * (inv * gamma.astype(jnp.float32)).reshape(shape).astype(x.dtype)
           + beta.reshape(shape).astype(x.dtype))
    return (out, mean, var), (x, gamma, mean, inv)


def _bn_bwd_impl(axis, epsilon, res, cts):
    dx, dgamma, dbeta = _bn_bwd_math(axis, res, cts)
    return dx, dgamma, dbeta, jnp.zeros_like(res[2])  # pivot gets no gradient


def _bn_bwd_math(axis, res, cts):
    dy = cts[0]  # cotangents for (mean, var) are dropped: running stats are
    #              detached buffers, as in the reference (BatchNormalization
    #              running mean/var never backprop into the graph)
    x, gamma, mean, inv = res
    axes, shape = _bn_axes_shape(x.ndim, x.shape[axis], axis)
    n = 1.0
    for a in axes:
        n *= x.shape[a]
    xhat = (x - mean.reshape(shape).astype(x.dtype)) \
        * inv.reshape(shape).astype(x.dtype)
    dy = dy.astype(x.dtype)
    # sibling reduces again: one pass over (dy, dy*xhat)
    sdy = jnp.sum(dy.astype(jnp.float32), axis=axes)
    sdyx = jnp.sum((dy * xhat).astype(jnp.float32), axis=axes)
    gi = (gamma.astype(jnp.float32) * inv).reshape(shape).astype(x.dtype)
    dx = gi * (dy
               - (sdy / n).reshape(shape).astype(x.dtype)
               - xhat * (sdyx / n).reshape(shape).astype(x.dtype))
    return dx, sdyx.astype(gamma.dtype), sdy.astype(gamma.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _batchnorm_train_core(x, gamma, beta, pivot, axis, epsilon):
    return _bn_fwd_impl(x, gamma, beta, pivot, axis, epsilon)[0]


_batchnorm_train_core.defvjp(_bn_fwd_impl, _bn_bwd_impl)


@op("batchnorm_train", "nn")
def batchnorm_train(x, gamma=None, beta=None, epsilon: float = 1e-5,
                    axis: int = 1, pivot=None):
    """Training-form batchnorm: returns (out, batch_mean, batch_var).

    Reference: libnd4j generic/nn/batchnorm.cpp training path +
    dl4j-nn layers/normalization/BatchNormalization. Hand-written VJP keeps
    the statistics and gradient reductions to ONE fused pass each (profiled:
    the naive autodiff form spends ~46% of a ResNet-50 v5e step in separate
    reduction passes). batch_mean/var are float32 and detached (running-stat
    buffers do not receive gradients, matching the reference).

    ``pivot`` (optional, [C] float32, x-independent — the BN layer passes its
    running mean) recenters the single-pass variance so it stays accurate for
    |mean| >> std inputs; it receives no gradient.
    """
    if gamma is None:
        gamma = jnp.ones((x.shape[axis],), jnp.float32)
    if beta is None:
        beta = jnp.zeros((x.shape[axis],), jnp.float32)
    if pivot is None:
        pivot = jnp.zeros((x.shape[axis],), jnp.float32)
    return _batchnorm_train_core(x, gamma, beta,
                                 pivot.astype(jnp.float32), axis,
                                 float(epsilon))


@op("layer_norm", "nn")
def layer_norm(x, gain=None, bias=None, axis=-1, epsilon: float = 1e-5):
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + epsilon)
    if gain is not None:
        out = out * gain
    if bias is not None:
        out = out + bias
    return out.astype(x.dtype)


@op("lrn", "nn")
def lrn(x, depth: int = 5, bias: float = 1.0, alpha: float = 1.0, beta: float = 0.5):
    """Local response normalization across channels (NCHW)."""
    half = depth // 2
    sq = jnp.square(x)
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    windows = sum(padded[:, i:i + x.shape[1]] for i in range(depth))
    return x / jnp.power(bias + alpha * windows, beta)


@op("dropout", "nn")
def dropout(x, key, rate: float, inverted: bool = True):
    """Inverted dropout (train-time scaling), jax key passed explicitly."""
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    if inverted:
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


@op("alpha_dropout", "nn")
def alpha_dropout(x, key, rate: float):
    """SELU-preserving dropout (reference AlphaDropout)."""
    alpha_p = -1.7580993408473766
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
    b = -a * alpha_p * (1 - keep)
    return (a * jnp.where(mask, x, alpha_p) + b).astype(x.dtype)


@op("gaussian_dropout", "nn")
def gaussian_dropout(x, key, rate: float):
    std = jnp.sqrt(rate / (1.0 - rate))
    return (x * (1.0 + std * jax.random.normal(key, x.shape, dtype=x.dtype))).astype(x.dtype)


@op("gaussian_noise", "nn")
def gaussian_noise(x, key, stddev: float):
    return (x + stddev * jax.random.normal(key, x.shape, dtype=x.dtype)).astype(x.dtype)


@op("linear", "nn")
def linear(x, w, b=None):
    """xW+b — dense W = [nIn, nOut] (reference layout). MXU matmul."""
    out = x @ w
    if b is not None:
        out = out + b
    return out


@op("bias_add", "nn")
def bias_add(x, b, data_format: str = "NCHW"):
    if x.ndim == 4:
        shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        return x + b.reshape(shape)
    return x + b


@op("embedding_lookup", "nn")
def embedding_lookup(table, ids):
    return jnp.take(table, ids, axis=0)


@op("dot_product_attention", "nn")
def dot_product_attention(q, k, v, mask=None, scaled: bool = True):
    """Single-head attention: q,k,v = [..., T, d]."""
    d = q.shape[-1]
    scores = jnp.einsum("...qd,...kd->...qk", q, k)
    if scaled:
        scores = scores / jnp.sqrt(jnp.asarray(d, dtype=scores.dtype))
    if mask is not None:
        scores = jnp.where(mask.astype(bool), scores, jnp.asarray(-1e9, dtype=scores.dtype))
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", weights, v)


@op("multi_head_dot_product_attention", "nn")
def multi_head_dot_product_attention(q, k, v, wq, wk, wv, wo, mask=None,
                                     num_heads: int = 1, scaled: bool = True):
    """Reference multi_head_dot_product_attention
    (libnd4j generic/nn/multi_head_dot_product_attention.cpp):
    q,k,v = [B, T, dModel]; per-head projections then fused attention."""
    b, tq, _ = q.shape
    tk = k.shape[1]

    def split_heads(x, w):
        proj = x @ w  # [B, T, H*dh]
        return proj.reshape(b, x.shape[1], num_heads, -1).transpose(0, 2, 1, 3)

    qh, kh, vh = split_heads(q, wq), split_heads(k, wk), split_heads(v, wv)
    out = None
    if tq == tk:
        # self-attention routes through the Pallas flash kernel on TPU
        # (3-8x at long T, no T×T buffer — BASELINE.md); a padding mask
        # rides as an additive logits bias streamed block-by-block, so
        # the masked path BERT runs is the SAME fused kernel. The dense
        # path remains the reference semantics everywhere else.
        from ..common.environment import Environment
        from .pallas_attention import flash_attention, supports_flash

        if (Environment.get().allow_pallas()
                and jax.default_backend() == "tpu"
                and supports_flash(tq, qh.shape[-1])):
            scale = (qh.shape[-1] ** -0.5) if scaled else 1.0
            bias = None
            if mask is not None:
                bias = jnp.where(mask.reshape(b, 1, 1, tk).astype(bool),
                                 jnp.float32(0.0), jnp.float32(-1e9))
            out = flash_attention(qh, kh, vh, sm_scale=scale, bias=bias,
                                  interpret=False)
    if out is None:
        m = None
        if mask is not None:
            m = mask.reshape(b, 1, 1, tk)
        out = dot_product_attention(qh, kh, vh, m, scaled)  # [B, H, Tq, dh]
    out = out.transpose(0, 2, 1, 3).reshape(b, tq, -1)
    return out @ wo


@op("xw_plus_b", "nn")
def xw_plus_b(x, w, b):
    return x @ w + b


@op("relu_layer", "nn")
def relu_layer(x, w, b):
    return jnp.maximum(x @ w + b, 0)


@op("log_sigmoid", "nn")
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


@op("softmax_bp", "nn")
def softmax_bp(x, grad, axis: int = -1):
    """VJP of softmax — exposed as an op for reference parity tests."""
    s = jax.nn.softmax(x, axis=axis)
    return s * (grad - jnp.sum(grad * s, axis=axis, keepdims=True))
