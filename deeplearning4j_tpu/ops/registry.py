"""Op registry + coverage ledger.

TPU-native analog of libnd4j's ``OpRegistrator`` (reference:
libnd4j/include/ops/declarable/OpRegistrator.h) fused with the op-validation
coverage ledger from ``org.nd4j.autodiff.opvalidation.OpValidation`` (SURVEY.md
§4.2): every op is registered by name; the test harness marks ops validated as
they are exercised, and a ledger test fails when a registered op was never
validated and isn't on the explicit skip list.

Ops are pure functions over raw jax arrays (+ static kwargs) so they are
jit-traceable; they never see the NDArray shell. The registry's name→fn table
is also the serialization contract — the SameDiff-analog graph stores op names
and rebuilds callables from here on load (the role the reference's
FlatBuffers op-num mapping plays in ``FlatBuffersMapper``).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Set


@dataclass
class OpDescriptor:
    name: str
    fn: Callable
    family: str
    # Differentiable through jax autodiff (False for int/bool/shape-query ops).
    differentiable: bool = True
    doc: str = ""


_REGISTRY: Dict[str, OpDescriptor] = {}
_VALIDATED: Set[str] = set()


def op(name: str, family: str = "misc", differentiable: bool = True):
    """Decorator: register a pure-jax op under `name`."""

    def wrap(fn: Callable) -> Callable:
        if name in _REGISTRY:
            raise ValueError(f"duplicate op registration: {name}")
        _REGISTRY[name] = OpDescriptor(
            name=name, fn=fn, family=family, differentiable=differentiable,
            doc=next(iter((fn.__doc__ or "").strip().splitlines()), ""),
        )
        return fn

    return wrap


def get_op(name: str) -> OpDescriptor:
    _ensure_loaded()
    if name not in _REGISTRY:
        raise KeyError(f"unknown op: {name!r} (registered: {len(_REGISTRY)})")
    return _REGISTRY[name]


def has_op(name: str) -> bool:
    _ensure_loaded()
    return name in _REGISTRY


def all_ops() -> Dict[str, OpDescriptor]:
    _ensure_loaded()
    return dict(_REGISTRY)


def exec_op(name: str, *args, **kwargs):
    """Execute a registered op by name, recording it as validated when called
    from the test harness (Nd4j.exec analog for raw arrays). Numpy args are
    promoted to jax arrays so ops can index them with tracers."""
    import numpy as _np
    import jax.numpy as _jnp

    desc = get_op(name)
    _VALIDATED.add(name)
    args = tuple(_jnp.asarray(a) if isinstance(a, _np.ndarray) else a for a in args)
    return desc.fn(*args, **kwargs)


def mark_validated(name: str) -> None:
    _VALIDATED.add(name)


def validated_ops() -> Set[str]:
    return set(_VALIDATED)


def coverage_report() -> Dict[str, Any]:
    _ensure_loaded()
    missing = sorted(set(_REGISTRY) - _VALIDATED)
    return {
        "registered": len(_REGISTRY),
        "validated": len(_VALIDATED & set(_REGISTRY)),
        "missing": missing,
    }


_loaded = False


def _ensure_loaded() -> None:
    """Import all op-family modules exactly once (registration side effects)."""
    global _loaded
    if _loaded:
        return
    _loaded = True
    from . import (  # noqa: F401
        broadcastable,
        transforms,
        reduce,
        shape,
        nn,
        recurrent,
        linalg,
        random,
        loss,
        image,
        pallas_attention,
        bitwise,
        embeddings,
    )
