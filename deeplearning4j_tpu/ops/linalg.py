"""Linear-algebra ops.

Reference: libnd4j ``include/ops/declarable/generic/linalg/`` (svd, qr,
cholesky, lstsq, triangular_solve, matrix_inverse, ...) + ``blas/`` matmul
family and ``helpers/MmulHelper``. Dense factorizations route through
jnp.linalg (XLA custom calls); matmuls ride the MXU.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import op


@op("matmul", "linalg")
def matmul(x, y, transpose_x: bool = False, transpose_y: bool = False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    return jnp.matmul(x, y)


@op("batched_gemm", "linalg")
def batched_gemm(x, y, transpose_x: bool = False, transpose_y: bool = False,
                 alpha: float = 1.0):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    return alpha * jnp.matmul(x, y)


@op("tensormmul", "linalg")
def tensormmul(x, y, axes_x, axes_y):
    return jnp.tensordot(x, y, axes=(tuple(axes_x), tuple(axes_y)))


@op("outer", "linalg")
def outer(x, y):
    return jnp.outer(x, y)


@op("svd", "linalg")
def svd(x, full_matrices: bool = False, compute_uv: bool = True):
    if compute_uv:
        u, s, vt = jnp.linalg.svd(x, full_matrices=full_matrices)
        return s, u, jnp.swapaxes(vt, -1, -2)  # reference returns (s, u, v)
    return jnp.linalg.svd(x, full_matrices=full_matrices, compute_uv=False)


@op("qr", "linalg")
def qr(x, full_matrices: bool = False):
    return jnp.linalg.qr(x, mode="complete" if full_matrices else "reduced")


@op("cholesky", "linalg")
def cholesky(x):
    return jnp.linalg.cholesky(x)


@op("lu", "linalg")
def lu(x):
    import jax.scipy.linalg as jsl

    lu_, piv = jsl.lu_factor(x)
    return lu_, piv


@op("triangular_solve", "linalg")
def triangular_solve(a, b, lower: bool = True, adjoint: bool = False):
    import jax.scipy.linalg as jsl

    return jsl.solve_triangular(a, b, lower=lower, trans=1 if adjoint else 0)


@op("solve", "linalg")
def solve(a, b, adjoint: bool = False):
    if adjoint:
        a = jnp.swapaxes(a, -1, -2)
    return jnp.linalg.solve(a, b)


@op("lstsq", "linalg")
def lstsq(a, b, l2_regularizer: float = 0.0):
    if l2_regularizer > 0:
        ata = jnp.swapaxes(a, -1, -2) @ a + l2_regularizer * jnp.eye(a.shape[-1], dtype=a.dtype)
        return jnp.linalg.solve(ata, jnp.swapaxes(a, -1, -2) @ b)
    return jnp.linalg.lstsq(a, b)[0]


@op("matrix_inverse", "linalg")
def matrix_inverse(x):
    return jnp.linalg.inv(x)


@op("pinv", "linalg")
def pinv(x):
    return jnp.linalg.pinv(x)


@op("matrix_determinant", "linalg")
def matrix_determinant(x):
    return jnp.linalg.det(x)


@op("log_matrix_determinant", "linalg")
def log_matrix_determinant(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return sign, logdet


@op("trace", "linalg")
def trace(x):
    return jnp.trace(x, axis1=-2, axis2=-1)


@op("cross", "linalg")
def cross(x, y):
    return jnp.cross(x, y)


@op("self_adjoint_eig", "linalg")
def self_adjoint_eig(x):
    """Symmetric/Hermitian eigendecomposition only (eigh). General eig is not
    TPU-lowerable; the reference op set has no general eig either."""
    return jnp.linalg.eigh(x)


@op("norm", "linalg")
def norm(x, ord=None, axis=None):
    return jnp.linalg.norm(x, ord=ord, axis=axis)


@op("matrix_band_part", "linalg")
def matrix_band_part(x, num_lower: int, num_upper: int):
    m, n = x.shape[-2], x.shape[-1]
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    keep_lower = (i - j) <= num_lower if num_lower >= 0 else jnp.ones((m, n), bool)
    keep_upper = (j - i) <= num_upper if num_upper >= 0 else jnp.ones((m, n), bool)
    return jnp.where(keep_lower & keep_upper, x, jnp.zeros((), dtype=x.dtype))


@op("sufficient_statistics", "linalg")
def sufficient_statistics(x, dims, shift=None):
    ax = tuple(dims)
    count = jnp.asarray(1.0)
    for d in ax:
        count = count * x.shape[d]
    if shift is not None:
        m = jnp.sum(x - shift, axis=ax)
        v = jnp.sum(jnp.square(x - shift), axis=ax)
    else:
        m = jnp.sum(x, axis=ax)
        v = jnp.sum(jnp.square(x), axis=ax)
    return count, m, v, shift
