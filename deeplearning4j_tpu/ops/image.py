"""Image ops.

Reference: libnd4j ``include/ops/declarable/generic/images/`` +
``helpers/{cpu,cuda}/image_resize`` (resize_bilinear/nearest, adjust_hue/
saturation/contrast, rgb↔hsv/yuv, non_max_suppression, crop_and_resize,
extract_image_patches). All NHWC like the reference image ops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import op


@op("resize_nearest", "image")
def resize_nearest(x, height: int, width: int, align_corners: bool = False,
                   half_pixel_centers: bool = False):
    """x: [N, H, W, C]. ``half_pixel_centers`` is TF2's default sampling
    (floor((i + 0.5) * scale)); the legacy default is floor(i * scale)."""
    n, h, w, c = x.shape

    def idx(out_size, in_size):
        if align_corners and out_size > 1:
            return jnp.round(
                jnp.linspace(0, in_size - 1, out_size)).astype(jnp.int32)
        scale = in_size / out_size
        pts = ((jnp.arange(out_size) + 0.5) * scale if half_pixel_centers
               else jnp.arange(out_size) * scale)
        return jnp.clip(jnp.floor(pts).astype(jnp.int32), 0, in_size - 1)

    return x[:, idx(height, h)][:, :, idx(width, w)]


@op("resize_bilinear", "image")
def resize_bilinear(x, height: int, width: int, align_corners: bool = False,
                    half_pixel_centers: bool = False):
    n, h, w, c = x.shape
    dtype = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32
    xf = x.astype(dtype)

    def src_coords(out_size, in_size):
        if align_corners and out_size > 1:
            return jnp.linspace(0.0, in_size - 1.0, out_size)
        scale = in_size / out_size
        if half_pixel_centers:
            return jnp.maximum((jnp.arange(out_size) + 0.5) * scale - 0.5, 0.0)
        return jnp.arange(out_size) * scale

    ys = src_coords(height, h)
    xs = src_coords(width, w)
    y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    wy = (ys - y0).astype(dtype)[None, :, None, None]
    wx = (xs - x0).astype(dtype)[None, None, :, None]
    top = xf[:, y0][:, :, x0] * (1 - wx) + xf[:, y0][:, :, x1] * wx
    bot = xf[:, y1][:, :, x0] * (1 - wx) + xf[:, y1][:, :, x1] * wx
    return (top * (1 - wy) + bot * wy).astype(x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else dtype)


@op("resize_bicubic", "image")
def resize_bicubic(x, height: int, width: int):
    """Keys cubic (a=-0.5) resize with half-pixel centers, the TF2
    ``resize(method="bicubic")`` contract; x: [N, H, W, C]."""
    n, _, _, c = x.shape
    dtype = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32
    return jax.image.resize(x.astype(dtype), (n, height, width, c),
                            method="cubic", antialias=False)


@op("resize_lanczos3", "image")
def resize_lanczos3(x, height: int, width: int, antialias: bool = True):
    """Lanczos-windowed sinc (a=3) resize — the reference images/ dir's
    ``resize_images`` LANCZOS3 method; x: [N, H, W, C]."""
    n, _, _, c = x.shape
    dtype = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32
    return jax.image.resize(x.astype(dtype), (n, height, width, c),
                            method="lanczos3", antialias=antialias)


@op("resize_lanczos5", "image")
def resize_lanczos5(x, height: int, width: int, antialias: bool = True):
    n, _, _, c = x.shape
    dtype = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32
    return jax.image.resize(x.astype(dtype), (n, height, width, c),
                            method="lanczos5", antialias=antialias)


@op("resize_mitchellcubic", "image")
def resize_mitchellcubic(x, height: int, width: int):
    """Mitchell–Netravali cubic (B=C=1/3) — composed from the separable
    kernel the same way jax.image builds its cubic (Keys) resizer, since
    jax.image exposes only the a=-0.5 cubic."""
    import numpy as np

    def mitchell(t):
        t = np.abs(t)
        B = C = 1.0 / 3.0
        return np.where(
            t < 1,
            ((12 - 9 * B - 6 * C) * t ** 3 + (-18 + 12 * B + 6 * C) * t ** 2
             + (6 - 2 * B)) / 6.0,
            np.where(
                t < 2,
                ((-B - 6 * C) * t ** 3 + (6 * B + 30 * C) * t ** 2
                 + (-12 * B - 48 * C) * t + (8 * B + 24 * C)) / 6.0,
                0.0))

    def axis_weights(out_size, in_size):
        scale = in_size / out_size
        centers = (np.arange(out_size) + 0.5) * scale - 0.5
        idx = np.arange(in_size)
        w = mitchell(centers[:, None] - idx[None, :]) \
            if scale <= 1 else mitchell(
                (centers[:, None] - idx[None, :]) / scale) / scale
        # edge handling: renormalize rows (kernel mass clipped at borders)
        return (w / np.maximum(w.sum(1, keepdims=True), 1e-12)).astype(
            np.float32)

    n, h, w_in, c = x.shape
    dtype = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32
    xf = x.astype(dtype)
    wh = jnp.asarray(axis_weights(height, h))
    ww = jnp.asarray(axis_weights(width, w_in))
    out = jnp.einsum("oh,nhwc->nowc", wh, xf)
    return jnp.einsum("pw,nowc->nopc", ww, out)


def _area_weights(out_size: int, in_size: int):
    """[out, in] interval-overlap weight matrix: output cell i averages the
    source interval [i·s, (i+1)·s) (TF area-resize semantics)."""
    import numpy as np

    scale = in_size / out_size
    wm = np.zeros((out_size, in_size), np.float32)
    for i in range(out_size):
        lo, hi = i * scale, (i + 1) * scale
        j0, j1 = int(np.floor(lo)), int(np.ceil(hi))
        for j in range(j0, min(j1, in_size)):
            overlap = min(hi, j + 1) - max(lo, j)
            if overlap > 0:
                wm[i, j] = overlap / scale
    return jnp.asarray(wm)


@op("resize_area", "image")
def resize_area(x, height: int, width: int):
    """Box-integration (area) resize — each output pixel is the exact mean
    of its source box (TF ``resize_area``); x: [N, H, W, C]. The overlap
    weights are small dense [out, in] matrices so the whole resize is two
    MXU-friendly contractions."""
    n, h, w, c = x.shape
    dtype = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32
    wy = _area_weights(height, h)
    wx = _area_weights(width, w)
    return jnp.einsum("oh,nhwc,pw->nopc", wy, x.astype(dtype),
                      wx).astype(dtype)


@op("adjust_gamma", "image")
def adjust_gamma(x, gamma: float = 1.0, gain: float = 1.0):
    """out = gain * x**gamma (reference adjust_gamma / tf.image); integer
    inputs promote to float32 like the sibling image ops."""
    dtype = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32
    return (gain * jnp.power(x.astype(dtype), gamma)).astype(dtype)


@op("rgb_to_hsv", "image")
def rgb_to_hsv(x):
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    maxc = jnp.maximum(jnp.maximum(r, g), b)
    minc = jnp.minimum(jnp.minimum(r, g), b)
    v = maxc
    delta = maxc - minc
    s = jnp.where(maxc > 0, delta / jnp.maximum(maxc, 1e-12), 0.0)
    safe = jnp.maximum(delta, 1e-12)
    rc = (maxc - r) / safe
    gc = (maxc - g) / safe
    bc = (maxc - b) / safe
    h = jnp.where(r == maxc, bc - gc, jnp.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc))
    h = jnp.where(delta > 0, (h / 6.0) % 1.0, 0.0)
    return jnp.stack([h, s, v], axis=-1)


@op("hsv_to_rgb", "image")
def hsv_to_rgb(x):
    h, s, v = x[..., 0], x[..., 1], x[..., 2]
    i = jnp.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(jnp.int32) % 6
    r = jnp.choose(i, [v, q, p, p, t, v], mode="clip")
    g = jnp.choose(i, [t, v, v, q, p, p], mode="clip")
    b = jnp.choose(i, [p, p, t, v, v, q], mode="clip")
    return jnp.stack([r, g, b], axis=-1)


@op("adjust_hue", "image")
def adjust_hue(x, delta: float):
    hsv = rgb_to_hsv(x)
    h = (hsv[..., 0] + delta) % 1.0
    return hsv_to_rgb(jnp.stack([h, hsv[..., 1], hsv[..., 2]], axis=-1))


@op("adjust_saturation", "image")
def adjust_saturation(x, factor: float):
    hsv = rgb_to_hsv(x)
    s = jnp.clip(hsv[..., 1] * factor, 0.0, 1.0)
    return hsv_to_rgb(jnp.stack([hsv[..., 0], s, hsv[..., 2]], axis=-1))


@op("adjust_contrast", "image")
def adjust_contrast(x, factor: float):
    mean = jnp.mean(x, axis=(-3, -2), keepdims=True)
    return (x - mean) * factor + mean


@op("rgb_to_grayscale", "image")
def rgb_to_grayscale(x):
    w = jnp.asarray([0.2989, 0.5870, 0.1140], dtype=x.dtype)
    return jnp.sum(x * w, axis=-1, keepdims=True)


@op("rgb_to_yuv", "image")
def rgb_to_yuv(x):
    m = jnp.asarray([[0.299, -0.14714119, 0.61497538],
                     [0.587, -0.28886916, -0.51496512],
                     [0.114, 0.43601035, -0.10001026]], dtype=x.dtype)
    return x @ m


@op("yuv_to_rgb", "image")
def yuv_to_rgb(x):
    m = jnp.asarray([[1.0, 1.0, 1.0],
                     [0.0, -0.394642334, 2.03206185],
                     [1.13988303, -0.58062185, 0.0]], dtype=x.dtype)
    return x @ m


@op("image_flip", "image")
def image_flip(x, horizontal: bool = True):
    return jnp.flip(x, axis=2 if horizontal else 1)


@op("crop_and_resize", "image")
def crop_and_resize(image, boxes, box_indices, crop_size):
    """image: [N,H,W,C]; boxes: [M,4] normalized y1,x1,y2,x2."""
    ch, cw = crop_size
    image = jnp.asarray(image)

    def one(box, bi):
        y1, x1, y2, x2 = box
        img = image[bi]
        h, w = img.shape[0], img.shape[1]
        ys = y1 * (h - 1) + jnp.arange(ch) / jnp.maximum(ch - 1, 1) * (y2 - y1) * (h - 1)
        xs = x1 * (w - 1) + jnp.arange(cw) / jnp.maximum(cw - 1, 1) * (x2 - x1) * (w - 1)
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1i] * wx
        bot = img[y1i][:, x0] * (1 - wx) + img[y1i][:, x1i] * wx
        return top * (1 - wy) + bot * wy

    return jax.vmap(one)(boxes, box_indices)


@op("non_max_suppression", "image", differentiable=False)
def non_max_suppression(boxes, scores, max_output_size: int,
                        iou_threshold: float = 0.5, score_threshold: float = -jnp.inf):
    """Greedy NMS with static output size (padded with -1), XLA-friendly
    lax.fori_loop form. boxes: [N,4] (y1,x1,y2,x2); returns int32 [max_output_size]."""
    boxes = jnp.asarray(boxes)
    scores = jnp.asarray(scores)
    n = boxes.shape[0]
    y1, x1, y2, x2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    areas = jnp.maximum(y2 - y1, 0) * jnp.maximum(x2 - x1, 0)

    def iou(i, j):
        yy1 = jnp.maximum(y1[i], y1[j])
        xx1 = jnp.maximum(x1[i], x1[j])
        yy2 = jnp.minimum(y2[i], y2[j])
        xx2 = jnp.minimum(x2[i], x2[j])
        inter = jnp.maximum(yy2 - yy1, 0) * jnp.maximum(xx2 - xx1, 0)
        return inter / jnp.maximum(areas[i] + areas[j] - inter, 1e-12)

    def body(k, state):
        sel, alive, scr = state
        best = jnp.argmax(jnp.where(alive, scr, -jnp.inf))
        ok = jnp.where(alive, scr, -jnp.inf)[best] > score_threshold
        sel = sel.at[k].set(jnp.where(ok, best.astype(jnp.int32), -1))
        ious = jax.vmap(lambda j: iou(best, j))(jnp.arange(n))
        alive = alive & (ious <= iou_threshold) & ok
        return sel, alive, scr

    sel0 = jnp.full((max_output_size,), -1, dtype=jnp.int32)
    alive0 = jnp.ones((n,), dtype=bool)
    sel, _, _ = jax.lax.fori_loop(0, max_output_size, body, (sel0, alive0, scores))
    return sel


@op("extract_image_patches", "image")
def extract_image_patches(x, ksizes, strides, rates=(1, 1), padding: str = "VALID"):
    """x: [N,H,W,C] → [N,oh,ow,kh*kw*C] (TF semantics)."""
    kh, kw = ksizes
    sh, sw = strides
    rh, rw = rates
    n, h, w, c = x.shape
    if padding.upper() == "SAME":
        eff_kh, eff_kw = (kh - 1) * rh + 1, (kw - 1) * rw + 1
        oh = -(-h // sh)
        ow = -(-w // sw)
        ph = max((oh - 1) * sh + eff_kh - h, 0)
        pw = max((ow - 1) * sw + eff_kw - w, 0)
        x = jnp.pad(x, ((0, 0), (ph // 2, ph - ph // 2), (pw // 2, pw - pw // 2), (0, 0)))
        h, w = x.shape[1], x.shape[2]
    eff_kh, eff_kw = (kh - 1) * rh + 1, (kw - 1) * rw + 1
    oh = (h - eff_kh) // sh + 1
    ow = (w - eff_kw) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            patches.append(x[:, i * rh:i * rh + oh * sh:sh, j * rw:j * rw + ow * sw:sw, :])
    return jnp.concatenate(patches, axis=-1)
