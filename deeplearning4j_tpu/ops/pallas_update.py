"""Fused Pallas weight-update kernels over ZeRO-1 flat buckets.

The per-leaf updater path emits a handful of XLA elementwise ops PER
PARAMETER LEAF — a ResNet-50's ~160 leaves become hundreds of small
kernels whose launch overhead and HBM re-reads the graph compiler does
not always fuse away (the TVM argument, arXiv:1802.04799: graph-level
compilers leave cross-op fusion on the table that hand kernels recover).
This module applies SGD / Nesterovs / Adam / AdamW to a ``Zero1Plan``
flat per-dtype bucket in ONE Pallas kernel launch: params, grads and
moments stream HBM→VMEM once, the whole update (including the
bf16-state + stochastic-rounding path of ``learning/precision.py``)
happens in registers, and the new params/moments stream back out.

Three execution modes, one shared math function (``_update_math`` — the
SAME jnp expressions as ``learning/updaters.py``, so fp32 results are
bit-identical to the per-leaf reference):

- ``"pallas"`` (TPU default): the real Mosaic-compiled kernel;
- ``"interpret"``: the same kernel through the Pallas interpreter (CPU
  test mesh — exactly the ``ops/pallas_attention.py`` fallback recipe);
- ``"xla"`` (non-TPU default): the shared math applied directly to the
  flat bucket — still ONE fused XLA elementwise kernel per bucket
  instead of hundreds of per-leaf ops, and bitwise-identical to the
  per-leaf reference (same expressions through the same compiler).

Cross-mode parity (xla vs interpret/pallas) is ulp-bounded, not bitwise:
the kernel body gets its own compile, and whether XLA fma-contracts a
``p - lr*g`` style mul-add there is environment-dependent (observed to
flip with the device-count flags alone) — tests pin the drift ≤2 ulp.
The production invariant is mode-local and strict: the ``xla`` mode (the
non-TPU hot path) is BITWISE-identical to the per-leaf fp32 reference,
and with ``state_dtype`` set every mode consumes the same SR bits.

Stochastic rounding draws ride the step's existing RNG stream: one
uint32 per element per bucket, generated OUTSIDE the kernel with
``jax.random.bits`` (identical bits in every mode — that is what makes
the modes mutually bitwise-comparable); Adam spends the low halfword on
``m`` and the high halfword on ``v``.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..common.profiler import OpProfiler
from ..learning.updaters import Adam, AdamW, Nesterovs, Sgd, _lr_at

# jax 0.4.x spells the x64 context manager under experimental (see
# ops/pallas_attention.py — the kernel must trace in the 32-bit world)
_enable_x64 = getattr(jax, "enable_x64", None)
if _enable_x64 is None:
    from jax.experimental import enable_x64 as _enable_x64

BLOCK_ROWS = 256          # f32 rows of 128 lanes per grid program (~128KB
LANES = 128               # per buffer in VMEM; 8 buffers stay well inside)

# exact-type match: AdaMax/Nadam/AMSGrad subclass Adam with DIFFERENT
# apply() math — isinstance would silently run the wrong update
_KINDS = {Sgd: "sgd", Nesterovs: "nesterovs", Adam: "adam", AdamW: "adamw"}
_SLOTS = {"sgd": (), "nesterovs": ("v",), "adam": ("m", "v"),
          "adamw": ("m", "v")}
# analytic flops per element for the census's counted sub-executable
# entry (rough op counts of _update_math, SR excluded — order-of-
# magnitude roofline inputs, not a cycle model)
_FLOPS_PER_ELEM = {"sgd": 2, "nesterovs": 5, "adam": 12, "adamw": 14}


def supports_fused(updater) -> bool:
    """True when ``updater`` has a fused flat-bucket kernel (exact type:
    Sgd / Nesterovs / Adam / AdamW)."""
    return type(updater) in _KINDS


def _scalars(updater, kind: str, iteration) -> Tuple[Any, ...]:
    """Hyperparameter scalars as f32, computed with the SAME expressions
    as the per-leaf updaters (the f32 cast matches the implicit cast XLA
    inserts when a weak scalar meets the f32 tensors)."""
    f32 = lambda v: jnp.asarray(v, jnp.float32)  # noqa: E731
    lr = _lr_at(updater.learning_rate, iteration)
    if kind == "sgd":
        return (f32(lr),)
    if kind == "nesterovs":
        # (1+mu) precomputed in python (f64) then cast — the per-leaf
        # path's weak scalars round to f32 the same way; deriving it from
        # an f32 mu INSIDE the kernel can land one ulp off
        return f32(lr), f32(updater.momentum), f32(1.0 + updater.momentum)
    t = iteration + 1
    bc1 = 1 - updater.beta1 ** t
    bc2 = 1 - updater.beta2 ** t
    sc = [f32(lr), f32(updater.beta1), f32(updater.beta2),
          f32(updater.epsilon), f32(bc1), f32(bc2),
          f32(1 - updater.beta1), f32(1 - updater.beta2)]
    if kind == "adamw":
        sc.append(f32(updater.weight_decay))
    return tuple(sc)


def _update_math(kind: str, sc, p, g, slots: Dict[str, Any],
                 bits, sr_dtype):
    """The one update-math definition every mode traces. ``slots`` holds
    the stored moments (possibly low-precision); math runs in f32; when
    ``sr_dtype`` is set the new moments are stochastically rounded back
    down with ``bits`` (low halfword first slot, high halfword second)."""
    from ..learning.precision import stochastic_round

    up = lambda a: a.astype(jnp.float32)  # noqa: E731

    def down(a, which: int):
        if sr_dtype is None:
            return a
        half = bits if which == 0 else (bits >> jnp.uint32(16))
        return stochastic_round(a, half, sr_dtype)

    if kind == "sgd":
        return p - sc[0] * g, {}
    if kind == "nesterovs":
        lr, mu, opmu = sc
        v = up(slots["v"])
        v_new = mu * v - lr * g
        p_new = p + (-mu * v + opmu * v_new)
        return p_new, {"v": down(v_new, 0)}
    lr, b1, b2, eps, bc1, bc2, omb1, omb2 = sc[:8]
    m, v = up(slots["m"]), up(slots["v"])
    m_new = b1 * m + omb1 * g
    v_new = b2 * v + omb2 * jnp.square(g)
    if kind == "adamw":
        step = lr * ((m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
                     + sc[8] * p)
    else:
        step = lr * (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    return p - step, {"m": down(m_new, 0), "v": down(v_new, 1)}


# --------------------------------------------------------------------------
# the kernel
# --------------------------------------------------------------------------

def _kernel(kind, slot_names, has_bits, sr_dtype, n_sc, sc_ref, *refs):
    n_in = 2 + len(slot_names) + (1 if has_bits else 0)
    ins, outs = refs[:n_in], refs[n_in:]
    p, g = ins[0][...], ins[1][...]
    slots = {name: ins[2 + i][...]
             for i, name in enumerate(slot_names)}
    bits = ins[2 + len(slot_names)][...] if has_bits else None
    sc = tuple(sc_ref[0, i] for i in range(n_sc))
    new_p, new_slots = _update_math(kind, sc, p, g, slots, bits, sr_dtype)
    outs[0][...] = new_p
    for i, name in enumerate(slot_names):
        outs[1 + i][...] = new_slots[name]


def _pad2d(a, tile: int):
    L = a.shape[0]
    pad = -(-L // tile) * tile - L
    if pad:
        a = jnp.concatenate([a, jnp.zeros((pad,), a.dtype)])
    return a.reshape(-1, LANES)


def _launch_kernel(kind, sc, p, g, slots, bits, sr_dtype, interpret):
    """One pallas_call over the whole (padded) flat bucket. Zero padding
    is self-consistent for every supported kind: g=0 and zero moments
    leave the padded tail of p exactly unchanged, and the caller slices
    it off anyway."""
    L = p.shape[0]
    tile = BLOCK_ROWS * LANES
    slot_names = _SLOTS[kind]
    sc_arr = jnp.zeros((1, LANES), jnp.float32).at[0, :len(sc)].set(
        jnp.stack(sc))
    tensors = [p, g] + [slots[n] for n in slot_names]
    if bits is not None:
        tensors.append(bits)
    tensors = [_pad2d(t, tile) for t in tensors]
    rows = tensors[0].shape[0]
    grid = (rows // BLOCK_ROWS,)
    blk = lambda: pl.BlockSpec((BLOCK_ROWS, LANES),  # noqa: E731
                               lambda i: (i, 0))
    state_dt = sr_dtype if sr_dtype is not None else (
        tensors[2].dtype if slot_names else None)
    out_shape = [jax.ShapeDtypeStruct(tensors[0].shape, p.dtype)]
    out_shape += [jax.ShapeDtypeStruct(tensors[0].shape, state_dt)
                  for _ in slot_names]
    kernel = functools.partial(_kernel, kind, slot_names, bits is not None,
                               sr_dtype, len(sc))
    with _enable_x64(False):
        outs = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[pl.BlockSpec((1, LANES), lambda i: (0, 0))]
            + [blk() for _ in tensors],
            out_specs=tuple(blk() for _ in out_shape),
            out_shape=tuple(out_shape),
            interpret=interpret,
        )(sc_arr, *tensors)
    new_p = outs[0].reshape(-1)[:L]
    new_slots = {n: outs[1 + i].reshape(-1)[:L]
                 for i, n in enumerate(slot_names)}
    return new_p, new_slots


# --------------------------------------------------------------------------
# public entry
# --------------------------------------------------------------------------

def default_mode() -> str:
    """``pallas`` on real TPUs, ``xla`` elsewhere (the interpret-mode
    kernel is for parity tests — running it on the CPU hot path would be
    a de-optimization, exactly like ops/pallas_attention's gate)."""
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def fused_apply(updater, flat_params: Dict[str, Any],
                flat_grads: Dict[str, Any], state: Dict[str, Any],
                iteration, key, mode: Optional[str] = None
                ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Apply ``updater`` to ``Zero1Plan`` flat buckets in one fused kernel
    per float32 bucket (non-f32 buckets take the same shared math as a
    plain XLA expression — f32 arithmetic with round-to-storage
    write-back, dtype-stable but tolerance-level vs a per-leaf updater
    whose weak scalars would have kept the math in the narrow dtype).

    ``flat_params``/``flat_grads``: ``{"flat::<dtype>": [L]}``;
    ``state``: ``{slot: {"flat::<dtype>": [L]}}`` in the same layout
    (shard- or full-length — the updaters are elementwise, so any slice
    works). Returns ``(new_flat_params, new_state)`` in the same layout.

    fp32 state: bitwise-identical to ``updater.apply`` on the same
    buckets (and hence to the per-leaf dense path — the flat layout is a
    pure permutation). ``state_dtype`` set: moments upcast in-register,
    f32 math, stochastic rounding on ``key``'s fold_in-derived stream —
    one uint32 draw per element per bucket, identical across modes.
    """
    from ..learning.precision import (SR_STREAM_TAG, random_bits_for,
                                      state_dtype_of)

    kind = _KINDS.get(type(updater))
    if kind is None:
        raise NotImplementedError(
            f"no fused kernel for {type(updater).__name__}; gate on "
            "supports_fused() and fall back to apply_updater")
    if mode is None:
        mode = default_mode()
    if mode not in ("pallas", "interpret", "xla"):
        raise ValueError(f"unknown fused-update mode {mode!r}")
    sd = state_dtype_of(updater)
    sr_dtype = jnp.dtype(sd) if sd else None
    if sr_dtype is not None and key is None:
        raise ValueError("state_dtype set but no RNG key threaded to "
                         "fused_apply")
    slot_names = _SLOTS[kind]
    sc = _scalars(updater, kind, iteration)
    prof = OpProfiler.get()
    new_flat: Dict[str, Any] = {}
    new_state: Dict[str, Dict[str, Any]] = {n: {} for n in slot_names}
    for bi, (bkey, p) in enumerate(sorted(flat_params.items())):
        g = flat_grads[bkey].astype(p.dtype) \
            if flat_grads[bkey].dtype != p.dtype else flat_grads[bkey]
        slots = {n: state[n][bkey] for n in slot_names}
        bits = None
        # slot_names gate: a stateless updater (Sgd) with state_dtype set
        # has nothing to round — don't pay threefry for unused bits
        if sr_dtype is not None and slot_names:
            sub = jax.random.fold_in(jax.random.fold_in(key, SR_STREAM_TAG),
                                     bi)
            bits = random_bits_for(sub, p.shape)
        if mode != "xla" and p.dtype == jnp.float32:
            prof.count("precision/fused_buckets_pallas")
            np_, ns = _launch_kernel(kind, sc, p, g, slots, bits, sr_dtype,
                                     interpret=(mode == "interpret"))
        else:
            prof.count("precision/fused_buckets_xla")
            np_, ns = _update_math(kind, sc, p, g, slots, bits, sr_dtype)
            # dtype stability: the f32 scalar arrays widen a non-f32
            # bucket's math to f32 — write back in the stored dtypes so
            # the param pytree never flips dtype (which would retrace
            # the step). For f32 buckets these casts are no-ops.
            np_ = np_.astype(p.dtype)
            if sr_dtype is None:
                ns = {k: v.astype(slots[k].dtype) for k, v in ns.items()}
        prof.count("precision/fused_hits")
        new_flat[bkey] = np_
        for n in slot_names:
            new_state[n][bkey] = ns[n]
    # executable census, counted sub-executable: the fused kernels
    # dispatch INSIDE the parent step, so their cost rides the parent's
    # measured time — record analytic flops/bytes here at trace time
    # (once per parent compile, like the precision/* counters above)
    elems = sum(p.size for p in flat_params.values())
    nbytes = sum(3 * p.size * p.dtype.itemsize      # read p,g + write p
                 for p in flat_params.values())
    for n in slot_names:
        nbytes += sum(2 * v.size * v.dtype.itemsize  # read + write slots
                      for v in state[n].values())
    from ..common import xprof

    xprof.note_subexec("pallas/update_bucket",
                       flops=float(_FLOPS_PER_ELEM.get(kind, 4) * elems),
                       bytes_accessed=float(nbytes),
                       kind=kind, mode=mode,
                       buckets=len(flat_params))
    return new_flat, ({} if not slot_names else new_state)


def apply_flat_updater(updater, flat_params, flat_grads, state, iteration,
                       key, mode: Optional[str] = None):
    """The flat-bucket dispatch the ZeRO-1 step and the single-device
    fused path share: the fused kernel when the updater has one, else the
    generic elementwise updater on the buckets (through
    ``learning.precision.apply_updater`` so ``state_dtype`` still works).
    Fallbacks are ledgered (``precision/fused_fallbacks``)."""
    if supports_fused(updater):
        return fused_apply(updater, flat_params, flat_grads, state,
                           iteration, key, mode=mode)
    from ..learning.precision import apply_updater

    OpProfiler.get().count("precision/fused_fallbacks")
    return apply_updater(updater, flat_grads, state, flat_params, iteration,
                         key)
