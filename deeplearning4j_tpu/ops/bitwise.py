"""Bitwise ops.

Reference: libnd4j ``include/ops/declarable/generic/bitwise/`` (and/or/xor,
shifts, cyclic shifts, bits_hamming_distance).
"""

from __future__ import annotations

import jax.numpy as jnp

from .registry import op


@op("bitwise_and", "bitwise", differentiable=False)
def bitwise_and(x, y):
    return jnp.bitwise_and(x, y)


@op("bitwise_or", "bitwise", differentiable=False)
def bitwise_or(x, y):
    return jnp.bitwise_or(x, y)


@op("bitwise_xor", "bitwise", differentiable=False)
def bitwise_xor(x, y):
    return jnp.bitwise_xor(x, y)


@op("bitwise_not", "bitwise", differentiable=False)
def bitwise_not(x):
    return jnp.bitwise_not(x)


@op("shift_left", "bitwise", differentiable=False)
def shift_left(x, n):
    return jnp.left_shift(x, n)


@op("shift_right", "bitwise", differentiable=False)
def shift_right(x, n):
    return jnp.right_shift(x, n)


def _rotate(x, n, left: bool):
    """Rotate on the unsigned view: signed right-shift is arithmetic (sign-
    extending) in XLA, and a shift by the full bit width is undefined."""
    bits = x.dtype.itemsize * 8
    udt = jnp.dtype(f"uint{bits}")
    u = x.astype(udt) if not jnp.issubdtype(x.dtype, jnp.unsignedinteger) else x
    n = jnp.asarray(n).astype(udt) % bits
    back = (bits - n) % bits
    if left:
        out = jnp.left_shift(u, n) | jnp.where(n == 0, 0, jnp.right_shift(u, back))
    else:
        out = jnp.right_shift(u, n) | jnp.where(n == 0, 0, jnp.left_shift(u, back))
    return out.astype(x.dtype)


@op("cyclic_shift_left", "bitwise", differentiable=False)
def cyclic_shift_left(x, n):
    return _rotate(x, n, left=True)


@op("cyclic_shift_right", "bitwise", differentiable=False)
def cyclic_shift_right(x, n):
    return _rotate(x, n, left=False)


@op("bits_hamming_distance", "bitwise", differentiable=False)
def bits_hamming_distance(x, y):
    diff = jnp.bitwise_xor(x, y)
    return jnp.sum(jnp.unpackbits(diff.view(jnp.uint8)).astype(jnp.int64)) \
        if hasattr(jnp, "unpackbits") else _popcount_sum(diff)


def _popcount_sum(v):
    v = v.astype(jnp.uint64)
    count = jnp.zeros_like(v)
    for shift in range(64):
        count = count + ((v >> shift) & 1)
    return jnp.sum(count.astype(jnp.int64))
