"""Op layer: registry + families (see registry.py for the design contract)."""

from .registry import all_ops, coverage_report, exec_op, get_op, has_op, mark_validated, op
