"""Broadcastable pairwise ops.

Reference: libnd4j ``include/ops/declarable/generic/broadcastable/*.cpp`` and
the legacy pairwise/broadcast loop kernels (``include/loops/``). On TPU these
all lower to fused XLA elementwise HLO — no hand kernels (SURVEY.md §2.2).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import op


@op("add", "broadcastable")
def add(x, y):
    return jnp.add(x, y)


@op("subtract", "broadcastable")
def subtract(x, y):
    return jnp.subtract(x, y)


@op("multiply", "broadcastable")
def multiply(x, y):
    return jnp.multiply(x, y)


@op("divide", "broadcastable")
def divide(x, y):
    return jnp.divide(x, y)


@op("reversesubtract", "broadcastable")
def reversesubtract(x, y):
    return jnp.subtract(y, x)


@op("reversedivide", "broadcastable")
def reversedivide(x, y):
    return jnp.divide(y, x)


@op("pow", "broadcastable")
def pow_(x, y):
    return jnp.power(x, y)


@op("mod", "broadcastable")
def mod(x, y):
    """Truncated remainder (Java/C % semantics): mod(-7, 3) == -1.
    Distinct from floormod, which floors: floormod(-7, 3) == 2."""
    return jnp.fmod(x, y)


@op("floormod", "broadcastable")
def floormod(x, y):
    return jnp.mod(x, y)


@op("floordiv", "broadcastable")
def floordiv(x, y):
    return jnp.floor_divide(x, y)


@op("truncatediv", "broadcastable")
def truncatediv(x, y):
    return jnp.trunc(jnp.divide(x, y)).astype(jnp.result_type(x, y))


@op("maximum", "broadcastable")
def maximum(x, y):
    return jnp.maximum(x, y)


@op("minimum", "broadcastable")
def minimum(x, y):
    return jnp.minimum(x, y)


@op("squaredsubtract", "broadcastable")
def squaredsubtract(x, y):
    return jnp.square(jnp.subtract(x, y))


@op("atan2", "broadcastable")
def atan2(x, y):
    return jnp.arctan2(x, y)


@op("boolean_and", "broadcastable", differentiable=False)
def boolean_and(x, y):
    return jnp.logical_and(x, y)


@op("boolean_or", "broadcastable", differentiable=False)
def boolean_or(x, y):
    return jnp.logical_or(x, y)


@op("boolean_xor", "broadcastable", differentiable=False)
def boolean_xor(x, y):
    return jnp.logical_xor(x, y)


@op("boolean_not", "broadcastable", differentiable=False)
def boolean_not(x):
    return jnp.logical_not(x)


@op("equals", "broadcastable", differentiable=False)
def equals(x, y):
    return jnp.equal(x, y)


@op("not_equals", "broadcastable", differentiable=False)
def not_equals(x, y):
    return jnp.not_equal(x, y)


@op("less", "broadcastable", differentiable=False)
def less(x, y):
    return jnp.less(x, y)


@op("less_equal", "broadcastable", differentiable=False)
def less_equal(x, y):
    return jnp.less_equal(x, y)


@op("greater", "broadcastable", differentiable=False)
def greater(x, y):
    return jnp.greater(x, y)


@op("greater_equal", "broadcastable", differentiable=False)
def greater_equal(x, y):
    return jnp.greater_equal(x, y)
