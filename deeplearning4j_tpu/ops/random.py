"""Random ops — explicit-key distribution draws.

Reference: libnd4j random kernels (``include/loops/random.cpp``, ``include/ops/
declarable/generic/random/``: uniform/normal/gamma/poisson/multinomial/
dropout). Every op takes a jax PRNG key explicitly so draws are traceable and
reproducible under jit (the stateful shell lives in ndarray/rng.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import op


@op("random_uniform", "random", differentiable=False)
def random_uniform(key, shape, low: float = 0.0, high: float = 1.0, dtype=jnp.float32):
    return jax.random.uniform(key, tuple(shape), dtype=dtype, minval=low, maxval=high)


@op("random_normal", "random", differentiable=False)
def random_normal(key, shape, mean: float = 0.0, stddev: float = 1.0, dtype=jnp.float32):
    return jax.random.normal(key, tuple(shape), dtype=dtype) * stddev + mean


@op("random_truncated_normal", "random", differentiable=False)
def random_truncated_normal(key, shape, mean: float = 0.0, stddev: float = 1.0,
                            dtype=jnp.float32):
    return jax.random.truncated_normal(key, -2.0, 2.0, tuple(shape), dtype=dtype) * stddev + mean


@op("random_lognormal", "random", differentiable=False)
def random_lognormal(key, shape, mean: float = 0.0, stddev: float = 1.0, dtype=jnp.float32):
    return jnp.exp(jax.random.normal(key, tuple(shape), dtype=dtype) * stddev + mean)


@op("random_bernoulli", "random", differentiable=False)
def random_bernoulli(key, shape, p: float = 0.5, dtype=jnp.float32):
    return jax.random.bernoulli(key, p, tuple(shape)).astype(dtype)


@op("random_binomial", "random", differentiable=False)
def random_binomial(key, shape, trials: int, p: float, dtype=jnp.float32):
    draws = jax.random.bernoulli(key, p, (trials,) + tuple(shape))
    return jnp.sum(draws.astype(dtype), axis=0)


@op("random_exponential", "random", differentiable=False)
def random_exponential(key, shape, lam: float = 1.0, dtype=jnp.float32):
    return jax.random.exponential(key, tuple(shape), dtype=dtype) / lam


@op("random_gamma", "random", differentiable=False)
def random_gamma(key, shape, alpha: float, beta: float = 1.0, dtype=jnp.float32):
    return jax.random.gamma(key, alpha, tuple(shape), dtype=dtype) / beta


@op("random_poisson", "random", differentiable=False)
def random_poisson(key, shape, lam: float, dtype=jnp.int32):
    return jax.random.poisson(key, lam, tuple(shape), dtype=dtype)


@op("random_multinomial", "random", differentiable=False)
def random_multinomial(key, logits, num_samples: int):
    return jax.random.categorical(key, logits, shape=(logits.shape[0], num_samples))


@op("random_shuffle", "random", differentiable=False)
def random_shuffle(key, x, axis: int = 0):
    return jax.random.permutation(key, x, axis=axis)


@op("random_crop", "random", differentiable=False)
def random_crop(key, x, crop_shape):
    starts = [
        jax.random.randint(k, (), 0, dim - c + 1)
        for k, dim, c in zip(jax.random.split(key, x.ndim), x.shape, crop_shape)
    ]
    import jax.lax as lax

    return lax.dynamic_slice(x, starts, tuple(crop_shape))


@op("dropout_bp", "random", differentiable=False)
def dropout_bp(key, grad, rate: float):
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, grad.shape)
    return jnp.where(mask, grad / keep, 0.0).astype(grad.dtype)
