"""Reduction + index-accumulation ops.

Reference: libnd4j legacy reduce/indexreduce/summarystats kernels
(``include/loops/cpu/reduce/``, ``indexreduce.cpp``, ``summarystatsreduce.cpp``).
XLA lowers all of these to tiled reduction HLO on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp

from .registry import op


def _axis(dims):
    if dims is None or dims == ():
        return None
    if isinstance(dims, int):
        return dims
    return tuple(dims)


@op("reduce_sum", "reduce")
def reduce_sum(x, dims=None, keep_dims: bool = False):
    return jnp.sum(x, axis=_axis(dims), keepdims=keep_dims)


@op("reduce_mean", "reduce")
def reduce_mean(x, dims=None, keep_dims: bool = False):
    return jnp.mean(x, axis=_axis(dims), keepdims=keep_dims)


@op("reduce_max", "reduce")
def reduce_max(x, dims=None, keep_dims: bool = False):
    return jnp.max(x, axis=_axis(dims), keepdims=keep_dims)


@op("reduce_min", "reduce")
def reduce_min(x, dims=None, keep_dims: bool = False):
    return jnp.min(x, axis=_axis(dims), keepdims=keep_dims)


@op("reduce_prod", "reduce")
def reduce_prod(x, dims=None, keep_dims: bool = False):
    return jnp.prod(x, axis=_axis(dims), keepdims=keep_dims)


@op("reduce_variance", "reduce")
def reduce_variance(x, dims=None, keep_dims: bool = False, bias_corrected: bool = True):
    return jnp.var(x, axis=_axis(dims), keepdims=keep_dims, ddof=1 if bias_corrected else 0)


@op("reduce_stdev", "reduce")
def reduce_stdev(x, dims=None, keep_dims: bool = False, bias_corrected: bool = True):
    return jnp.std(x, axis=_axis(dims), keepdims=keep_dims, ddof=1 if bias_corrected else 0)


@op("reduce_norm1", "reduce")
def reduce_norm1(x, dims=None, keep_dims: bool = False):
    return jnp.sum(jnp.abs(x), axis=_axis(dims), keepdims=keep_dims)


@op("reduce_norm2", "reduce")
def reduce_norm2(x, dims=None, keep_dims: bool = False):
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=_axis(dims), keepdims=keep_dims))


@op("reduce_norm_max", "reduce")
def reduce_norm_max(x, dims=None, keep_dims: bool = False):
    return jnp.max(jnp.abs(x), axis=_axis(dims), keepdims=keep_dims)


@op("reduce_sqnorm", "reduce")
def reduce_sqnorm(x, dims=None, keep_dims: bool = False):
    return jnp.sum(jnp.square(x), axis=_axis(dims), keepdims=keep_dims)


@op("reduce_logsumexp", "reduce")
def reduce_logsumexp(x, dims=None, keep_dims: bool = False):
    import jax

    return jax.scipy.special.logsumexp(x, axis=_axis(dims), keepdims=keep_dims)


@op("reduce_amean", "reduce")
def reduce_amean(x, dims=None, keep_dims: bool = False):
    return jnp.mean(jnp.abs(x), axis=_axis(dims), keepdims=keep_dims)


@op("reduce_amax", "reduce")
def reduce_amax(x, dims=None, keep_dims: bool = False):
    return jnp.max(jnp.abs(x), axis=_axis(dims), keepdims=keep_dims)


@op("reduce_amin", "reduce")
def reduce_amin(x, dims=None, keep_dims: bool = False):
    return jnp.min(jnp.abs(x), axis=_axis(dims), keepdims=keep_dims)


@op("count_nonzero", "reduce", differentiable=False)
def count_nonzero(x, dims=None, keep_dims: bool = False):
    return jnp.sum((x != 0).astype(jnp.int64), axis=_axis(dims), keepdims=keep_dims)


@op("count_zero", "reduce", differentiable=False)
def count_zero(x, dims=None, keep_dims: bool = False):
    return jnp.sum((x == 0).astype(jnp.int64), axis=_axis(dims), keepdims=keep_dims)


@op("all", "reduce", differentiable=False)
def all_(x, dims=None, keep_dims: bool = False):
    return jnp.all(x, axis=_axis(dims), keepdims=keep_dims)


@op("any", "reduce", differentiable=False)
def any_(x, dims=None, keep_dims: bool = False):
    return jnp.any(x, axis=_axis(dims), keepdims=keep_dims)


@op("argmax", "indexreduce", differentiable=False)
def argmax(x, dims=None, keep_dims: bool = False):
    ax = _axis(dims)
    if isinstance(ax, tuple):
        ax = ax[0]
    return jnp.argmax(x, axis=ax, keepdims=keep_dims)


@op("argmin", "indexreduce", differentiable=False)
def argmin(x, dims=None, keep_dims: bool = False):
    ax = _axis(dims)
    if isinstance(ax, tuple):
        ax = ax[0]
    return jnp.argmin(x, axis=ax, keepdims=keep_dims)


@op("argamax", "indexreduce", differentiable=False)
def argamax(x, dims=None, keep_dims: bool = False):
    """Index of max absolute value (reference IAMax)."""
    ax = _axis(dims)
    if isinstance(ax, tuple):
        ax = ax[0]
    return jnp.argmax(jnp.abs(x), axis=ax, keepdims=keep_dims)


@op("argamin", "indexreduce", differentiable=False)
def argamin(x, dims=None, keep_dims: bool = False):
    ax = _axis(dims)
    if isinstance(ax, tuple):
        ax = ax[0]
    return jnp.argmin(jnp.abs(x), axis=ax, keepdims=keep_dims)


@op("cumsum", "reduce")
def cumsum(x, axis: int = 0, exclusive: bool = False, reverse: bool = False):
    v = jnp.flip(x, axis) if reverse else x
    out = jnp.cumsum(v, axis=axis)
    if exclusive:
        out = out - v
    return jnp.flip(out, axis) if reverse else out


@op("cumprod", "reduce")
def cumprod(x, axis: int = 0, exclusive: bool = False, reverse: bool = False):
    v = jnp.flip(x, axis) if reverse else x
    out = jnp.cumprod(v, axis=axis)
    if exclusive:
        out = out / jnp.where(v == 0, 1, v)  # best-effort exclusive form
    return jnp.flip(out, axis) if reverse else out


@op("dot", "reduce")
def dot(x, y, dims=None):
    if dims is None:
        return jnp.sum(x * y)
    return jnp.sum(x * y, axis=_axis(dims))


@op("cosine_similarity", "reduce")
def cosine_similarity(x, y, dims=None):
    ax = _axis(dims)
    num = jnp.sum(x * y, axis=ax)
    den = jnp.sqrt(jnp.sum(jnp.square(x), axis=ax)) * jnp.sqrt(jnp.sum(jnp.square(y), axis=ax))
    return num / den


@op("cosine_distance", "reduce")
def cosine_distance(x, y, dims=None):
    return 1.0 - cosine_similarity(x, y, dims)


@op("euclidean_distance", "reduce")
def euclidean_distance(x, y, dims=None):
    return jnp.sqrt(jnp.sum(jnp.square(x - y), axis=_axis(dims)))


@op("manhattan_distance", "reduce")
def manhattan_distance(x, y, dims=None):
    return jnp.sum(jnp.abs(x - y), axis=_axis(dims))


@op("hamming_distance", "reduce", differentiable=False)
def hamming_distance(x, y, dims=None):
    return jnp.sum((x != y).astype(jnp.int64), axis=_axis(dims))


@op("jaccard_distance", "reduce")
def jaccard_distance(x, y, dims=None):
    ax = _axis(dims)
    num = jnp.sum(jnp.minimum(x, y), axis=ax)
    den = jnp.sum(jnp.maximum(x, y), axis=ax)
    return 1.0 - num / den


@op("moments", "reduce")
def moments(x, dims=None, keep_dims: bool = False):
    ax = _axis(dims)
    return jnp.mean(x, axis=ax, keepdims=keep_dims), jnp.var(x, axis=ax, keepdims=keep_dims)


@op("normalize_moments", "reduce")
def normalize_moments(counts, mean_ss, var_ss, shift: float = 0.0):
    mean = mean_ss / counts + shift
    variance = var_ss / counts - jnp.square(mean_ss / counts)
    return mean, variance


@op("zero_fraction", "reduce", differentiable=False)
def zero_fraction(x):
    return jnp.mean((x == 0).astype(jnp.float32))


@op("percentile", "reduce", differentiable=False)
def percentile(x, q: float, axis=None, interpolation: str = "linear"):
    """Reference percentile op; interpolation per numpy (linear|lower|
    higher|nearest|midpoint)."""
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.percentile(x, q, axis=ax, method=interpolation)
