"""Flash attention: a hand-written Pallas TPU kernel for the hot op.

Reference: the reference's attention is a dense libnd4j kernel
(``generic/nn/multi_head_dot_product_attention.cpp``) materializing the
full [T, T] score matrix. On TPU the memory-bound way to run long-sequence
attention is the blockwise online-softmax construction (Flash Attention /
Rabe-Staats), tiled for VMEM with Pallas/Mosaic — this module implements
it natively (forward kernel + memory-efficient blockwise backward), the
"pallas kernels for the hot ops" role in this framework's layer map.

Shapes: q, k, v ``[B, H, T, D]``. The kernel grid is (B·H, T/block_q);
each program holds one q block in VMEM and streams k/v blocks with an
online max/denominator, so nothing of size T×T ever materializes. The
backward pass is the standard FA recipe (recompute p per block from the
saved row max/denominator) expressed as an XLA ``lax.scan`` over k blocks
— also free of T×T buffers.

``interpret=True`` runs the kernel in Pallas interpret mode (used by the
CPU test mesh); on the TPU the same kernel lowers through Mosaic
(verified through the axon relay). Sequence lengths must divide the block
sizes — callers fall back to the dense op otherwise
(``ops/nn.dot_product_attention``).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

# jax.enable_x64 (the public context manager) only exists on newer jax;
# 0.4.x spells it jax.experimental.enable_x64 — same semantics
_enable_x64 = getattr(jax, "enable_x64", None)
if _enable_x64 is None:
    from jax.experimental import enable_x64 as _enable_x64

from .registry import op

# Tuned on v5e at T=4096 (BASELINE.md): 512/1024 runs 3.4x faster than
# dense XLA attention; 128/128 was 1.7x SLOWER. Blocks auto-shrink to T.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024


def _fa_kernel(*refs, scale: float, causal: bool, block_q: int,
               block_k: int, n_k: int, has_bias: bool = False):
    # NOTE (Mosaic, this jax version — pinned empirically on the real
    # chip): the kernel must trace in the 32-bit world. This framework
    # enables jax_enable_x64 globally (NDArray fp64 parity), under which
    # weak python ints become i64 — Mosaic then fails muli verification,
    # and its i64→i32 convert fallback recurses. _fa_forward therefore
    # traces the pallas_call under enable_x64(False); in-kernel integer
    # scalars are strong jnp.int32, floats weak python scalars, and no
    # dtype casts appear inside the kernel (inputs are pre-cast f32).
    #
    # Grid is (B·H, n_q, n_k) with the k axis innermost: k/v stream
    # through VMEM one block at a time (T never resides whole), while the
    # online-softmax state (m, l, acc) lives in VMEM scratch that
    # persists across the k iterations of one q block.
    if has_bias:
        q_ref, k_ref, v_ref, b_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
        b_ref = None
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == jnp.int32(0))
    def _init():
        m_scr[...] = jnp.full_like(m_scr[...], -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr[...])
        acc_scr[...] = jnp.zeros_like(acc_scr[...])

    def _compute():
        q = q_ref[0] * scale                              # [bq, d]
        k = k_ref[0]                                      # [bk, d]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))
        if has_bias:
            # additive logits bias (BERT attention mask / relative-pos
            # bias), streamed block-by-block like k/v — the [T, T] bias
            # never resides whole in VMEM
            s = s + b_ref[0]
        if causal:
            qpos = (qi * jnp.int32(block_q)
                    + lax.broadcasted_iota(jnp.int32,
                                           (block_q, block_k), 0))
            kpos = (kj * jnp.int32(block_k)
                    + lax.broadcasted_iota(jnp.int32,
                                           (block_q, block_k), 1))
            s = jnp.where(qpos >= kpos, s, jnp.float32(-jnp.inf))
        m_prev = jnp.max(m_scr[...], axis=1, keepdims=True)   # [bq, 1]
        l_prev = jnp.max(l_scr[...], axis=1, keepdims=True)
        m_blk = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        f0 = jnp.float32(0.0)
        safe = jnp.where(jnp.isfinite(m_new), m_new, f0)
        p = jnp.exp(s - safe)
        p = jnp.where(jnp.isfinite(s), p, f0)
        alpha = jnp.where(jnp.isfinite(m_prev),
                          jnp.exp(m_prev - safe), f0)        # [bq, 1]
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())))
        ones = jnp.ones((1, m_scr.shape[1]), jnp.float32)
        m_scr[...] = m_new * ones
        l_scr[...] = l_new * ones

    if causal:
        # whole k block above the diagonal → nothing to do
        pl.when(kj * jnp.int32(block_k)
                <= qi * jnp.int32(block_q)
                + jnp.int32(block_q - 1))(_compute)
    else:
        _compute()

    @pl.when(kj == jnp.int32(n_k - 1))
    def _finalize():
        l = jnp.max(l_scr[...], axis=1, keepdims=True)
        o_ref[0] = acc_scr[...] / jnp.maximum(l, jnp.float32(1e-30))


def _fa_forward(q, k, v, scale, causal, block_q, block_k, interpret,
                bias=None):
    from jax.experimental.pallas import tpu as pltpu

    bh, T, d = q.shape
    n_q = T // block_q
    n_k = T // block_k
    kernel = functools.partial(_fa_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, n_k=n_k,
                               has_bias=bias is not None)
    scratch = [
        pltpu.VMEM((block_q, 128), jnp.float32),   # running row max
        pltpu.VMEM((block_q, 128), jnp.float32),   # running denominator
        pltpu.VMEM((block_q, d), jnp.float32),     # unnormalized out
    ]
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
    ]
    args = (q, k, v)
    if bias is not None:
        in_specs.append(pl.BlockSpec((1, block_q, block_k),
                                     lambda b, i, j: (b, i, j)))
        args = (q, k, v, bias)
    with _enable_x64(False):
        o = pl.pallas_call(
            kernel,
            grid=(bh, n_q, n_k),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, block_q, d),
                                   lambda b, i, j: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct((bh, T, d), q.dtype),
            scratch_shapes=scratch,
            interpret=interpret,
        )(*args)
    return o


def _row_stats(q, k, scale, causal, block_k, bias=None):
    """Blockwise recomputation of the softmax row max/denominator
    (the stats the kernel keeps in registers), as an XLA scan."""
    bh, T, d = q.shape
    n_k = T // block_k
    qf = q.astype(jnp.float32)
    qpos = jnp.arange(T)

    def blk(carry, i):
        m, l = carry
        ks = lax.dynamic_slice_in_dim(k, i * block_k, block_k, 1) \
            .astype(jnp.float32)
        s = jnp.einsum("bqd,bkd->bqk", qf, ks) * scale
        if bias is not None:
            s = s + lax.dynamic_slice_in_dim(bias, i * block_k, block_k,
                                             2).astype(jnp.float32)
        if causal:
            kpos = i * block_k + jnp.arange(block_k)
            s = jnp.where(qpos[:, None] >= kpos[None, :], s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - safe[..., None]), 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - safe), 0.0)
        return (m_new, l * alpha + p.sum(-1)), None

    m0 = jnp.full((bh, T), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bh, T), jnp.float32)
    (m, l), _ = lax.scan(blk, (m0, l0), jnp.arange(n_k))
    return jnp.where(jnp.isfinite(m), m, 0.0), l


def _fa_backward(q, k, v, o, do, scale, causal, block_k, bias=None,
                 need_dbias=False):
    """Blockwise FA backward (XLA scan over k blocks, no T×T buffers).

    p_ij = exp(s_ij - m_i) / l_i;  D_i = Σ_d dO_id O_id;
    dV_j = Σ_i p_ij dO_i;  dS = p ∘ (dO·Vᵀ − D);  dQ += dS·K·scale;
    dK_j = Σ_i dS_ij q_i · scale;  dBias = dS (the bias adds to the
    post-scale logits, so its cotangent is dS verbatim — stacked back to
    [bh, T, T] only when ``need_dbias``; with the usual broadcast bias
    the sum back to the small shape happens OUTSIDE the custom_vjp
    through the broadcast's own VJP).
    """
    bh, T, d = q.shape
    m, l = _row_stats(q, k, scale, causal, block_k, bias=bias)
    n_k = T // block_k
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    D = jnp.sum(dof * o.astype(jnp.float32), axis=-1)       # [bh, T]
    qpos = jnp.arange(T)

    def blk(carry, i):
        dq_acc = carry
        ks = lax.dynamic_slice_in_dim(k, i * block_k, block_k, 1) \
            .astype(jnp.float32)                             # [bh, bk, d]
        vs = lax.dynamic_slice_in_dim(v, i * block_k, block_k, 1) \
            .astype(jnp.float32)
        s = jnp.einsum("bqd,bkd->bqk", qf, ks) * scale
        if bias is not None:
            s = s + lax.dynamic_slice_in_dim(bias, i * block_k, block_k,
                                             2).astype(jnp.float32)
        if causal:
            kpos = i * block_k + jnp.arange(block_k)
            s = jnp.where(qpos[:, None] >= kpos[None, :], s, -jnp.inf)
        p = jnp.exp(s - m[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0) \
            / jnp.maximum(l, 1e-30)[..., None]               # [bh, T, bk]
        dv = jnp.einsum("bqk,bqd->bkd", p, dof)
        dp = jnp.einsum("bqd,bkd->bqk", dof, vs)
        ds = p * (dp - D[..., None])
        dq_acc = dq_acc + jnp.einsum("bqk,bkd->bqd", ds, ks) * scale
        dk = jnp.einsum("bqk,bqd->bkd", ds, qf) * scale
        outs = (dk, dv, ds) if need_dbias else (dk, dv)
        return dq_acc, outs

    dq0 = jnp.zeros_like(qf)
    dq, outs = lax.scan(blk, dq0, jnp.arange(n_k))
    if need_dbias:
        dks, dvs, dss = outs
    else:
        dks, dvs = outs
    dk = jnp.moveaxis(dks, 0, 1).reshape(bh, T, d)
    dv = jnp.moveaxis(dvs, 0, 1).reshape(bh, T, d)
    grads = (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))
    if need_dbias:
        # [n_k, bh, T, bk] -> [bh, T, n_k, bk] -> [bh, T, T]
        dbias = jnp.moveaxis(dss, 0, 2).reshape(bh, T, T)
        grads = grads + (dbias.astype(bias.dtype),)
    return grads


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash3(q, k, v, scale, causal, block_q, block_k, interpret):
    return _fa_forward(q, k, v, scale, causal, block_q, block_k, interpret)


def _flash3_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    o = _fa_forward(q, k, v, scale, causal, block_q, block_k, interpret)
    return o, (q, k, v, o)


def _flash3_bwd(scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, o = res
    return _fa_backward(q, k, v, o, do, scale, causal, block_k)


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash3b(q, k, v, bias, scale, causal, block_q, block_k, interpret):
    return _fa_forward(q, k, v, scale, causal, block_q, block_k,
                       interpret, bias=bias)


def _flash3b_fwd(q, k, v, bias, scale, causal, block_q, block_k,
                 interpret):
    o = _fa_forward(q, k, v, scale, causal, block_q, block_k, interpret,
                    bias=bias)
    return o, (q, k, v, bias, o)


def _flash3b_bwd(scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, bias, o = res
    return _fa_backward(q, k, v, o, do, scale, causal, block_k,
                        bias=bias, need_dbias=True)


_flash3b.defvjp(_flash3b_fwd, _flash3b_bwd)


def pick_blocks(T: int, block_q: Optional[int] = None,
                block_k: Optional[int] = None):
    bq = block_q or min(DEFAULT_BLOCK_Q, T)
    bk = block_k or min(DEFAULT_BLOCK_K, T)
    return bq, bk


def supports_flash(T: int, d: int, block_q: Optional[int] = None,
                   block_k: Optional[int] = None) -> bool:
    bq, bk = pick_blocks(T, block_q, block_k)
    # Mosaic tiling: q-block sublane dim % 8, k-block (and the [bq, bk]
    # score tile's lane dim) % 128
    return (T % bq == 0 and T % bk == 0 and T >= bq
            and bq % 8 == 0 and bk % 128 == 0)


@op("flash_attention", "nn")
def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    bias=None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Blockwise fused attention. q, k, v: [B, H, T, D] (or [B, T, D] for
    a single head); returns the same shape. T must divide the block sizes
    (``supports_flash``); use ``dot_product_attention`` otherwise.

    ``bias``: additive logits bias, broadcastable to [B, H, T, T] — the
    full attention+bias+softmax path BERT runs (padding mask as
    ``where(mask, 0, -1e9)``, or a learned relative-position bias: it is
    differentiated, with the cotangent summed back through the broadcast).
    The bias streams through VMEM one [block_q, block_k] tile at a time,
    same as k/v — no [T, T] residency."""
    squeeze = q.ndim == 3
    if squeeze:
        q, k, v = q[:, None], k[:, None], v[:, None]
    b, h, T, d = q.shape
    block_q, block_k = pick_blocks(T, block_q, block_k)
    if not supports_flash(T, d, block_q, block_k):
        raise ValueError(
            f"flash_attention needs T % block == 0 (T={T}, blocks "
            f"{block_q}/{block_k}); fall back to dot_product_attention")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(d)
    in_dtype = q.dtype
    qf = q.reshape(b * h, T, d).astype(jnp.float32)
    kf = k.reshape(b * h, T, d).astype(jnp.float32)
    vf = v.reshape(b * h, T, d).astype(jnp.float32)
    if bias is not None:
        if squeeze and bias.ndim == 3:
            bias = bias[:, None]
        # broadcast OUTSIDE the custom_vjp: dbias sums back to the
        # caller's small shape through the broadcast's own VJP
        bf = jnp.broadcast_to(bias.astype(jnp.float32),
                              (b, h, T, T)).reshape(b * h, T, T)
        o = _flash3b(qf, kf, vf, bf, float(scale), bool(causal),
                     int(block_q), int(block_k), bool(interpret))
    else:
        o = _flash3(qf, kf, vf, float(scale), bool(causal), int(block_q),
                    int(block_k), bool(interpret))
    o = o.reshape(b, h, T, d).astype(in_dtype)
    return o[:, 0] if squeeze else o
