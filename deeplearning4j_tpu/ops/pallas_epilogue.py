"""Fused BN+activation(+residual-add) inference epilogue (Pallas).

The next-hottest fusion XLA misses on the resnet blocks (ROADMAP item 3,
the TVM argument again): inference-mode BatchNormalization collapses to a
per-channel affine ``y = x*scale + shift``, and the resnet block tail is
exactly ``relu(bn(x) + residual)`` — three HBM round-trips (normalize,
add, activate) that one kernel does in a single x/residual read and one
write. Training-mode BN is NOT fused here: it computes batch statistics
(a reduction) behind a hand-written VJP (ops/nn.batchnorm_train) and
stays on that path untouched.

Layout: the kernel streams the tensor as channels-last 2-D ``[rows, C]``
(NCHW transposes around the call — XLA fuses the transposes into the
neighbouring ops), per-channel scale/shift ride along as a ``(1, C)``
row indexed by the lane-program. Shape gate (:func:`fusable`): float
inputs, relu/identity activation, channel count a multiple of 128 (the
TPU lane width — resnet block channels 256/512/1024/2048 pass, the
7x7-stem's 64 falls back to the dense ops). Refusals return ``None`` and
are ledgered (``precision/epilogue_fallbacks``); callers keep their
dense path.

Modes mirror ``ops/pallas_update``: ``pallas`` (real Mosaic kernel, TPU
default), ``interpret`` (CPU test mesh), ``xla`` (non-TPU default: the
same affine+act expression broadcast in the original layout — one fused
XLA elementwise kernel, no transposes). All modes share one math
expression; scale/shift are computed ONCE in f32 outside the kernel, so
mode-to-mode agreement is elementwise-exact up to XLA's fma contraction
of ``x*scale + shift`` when it compiles the kernel body (≤2 ulp, pinned
by tests/test_precision.py). Against the UNFUSED dense
ops the epilogue is a reassociation — ``(x-mean)*inv*gamma+beta`` vs
``x*(gamma*inv) + (beta-mean*gamma*inv)`` — so parity is
tolerance-bounded (documented, tested), not bitwise; that is why the
fusion is opt-in (``GlobalConf.fused_epilogue``), never silent.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..common.profiler import OpProfiler
from .pallas_update import LANES, _enable_x64, default_mode

BLOCK_ROWS = 256


def _act_fn(act: str):
    if act == "relu":
        return lambda y: jnp.maximum(y, jnp.zeros((), y.dtype))
    return lambda y: y


def fusable(x, axis: int, act: Optional[str]) -> bool:
    """Shape gate: can :func:`bn_act` fuse this epilogue?"""
    act = (act or "identity").lower()
    if act not in ("relu", "identity"):
        return False
    if not (hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)):
        return False
    nd = getattr(x, "ndim", 0)
    if nd == 4 and axis % 4 == 1:
        c = x.shape[1]
    elif nd == 2 and axis % 2 == 1:
        c = x.shape[1]
    else:
        return False
    return c % LANES == 0


def _kernel(act, has_res, x_ref, scale_ref, shift_ref, *rest):
    res_ref, out_ref = (rest[0], rest[1]) if has_res else (None, rest[0])
    y = x_ref[...] * scale_ref[...] + shift_ref[...]
    if has_res:
        y = y + res_ref[...]
    out_ref[...] = _act_fn(act)(y)


def _launch(x2d, scale, shift, res2d, act, interpret):
    rows, C = x2d.shape
    pad = -(-rows // BLOCK_ROWS) * BLOCK_ROWS - rows
    if pad:
        z = jnp.zeros((pad, C), x2d.dtype)
        x2d = jnp.concatenate([x2d, z])
        if res2d is not None:
            res2d = jnp.concatenate([res2d, z])
    grid = (x2d.shape[0] // BLOCK_ROWS, C // LANES)
    blk = pl.BlockSpec((BLOCK_ROWS, LANES), lambda i, j: (i, j))
    vec = pl.BlockSpec((1, LANES), lambda i, j: (0, j))
    ins = [x2d, scale.reshape(1, C), shift.reshape(1, C)]
    in_specs = [blk, vec, vec]
    if res2d is not None:
        ins.append(res2d)
        in_specs.append(blk)
    kernel = functools.partial(_kernel, act, res2d is not None)
    with _enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=in_specs,
            out_specs=blk,
            out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
            interpret=interpret,
        )(*ins)
    return out[:rows] if pad else out


def bn_act(x, mean, var, gamma=None, beta=None, *, epsilon: float = 1e-5,
           axis: int = 1, act: Optional[str] = None, residual=None,
           mode: Optional[str] = None):
    """Fused inference epilogue ``act(bn(x) [+ residual])`` — or ``None``
    when the shape gate refuses (caller falls back to its dense path;
    the refusal is ledgered).

    ``mean``/``var``/``gamma``/``beta``: per-channel ``(C,)`` f32 (the BN
    layer's running stats and affine params; gamma/beta may be None).
    ``residual`` must match ``x``'s shape. scale/shift are folded in f32
    then cast to ``x.dtype`` — identical across all three modes.
    """
    act = (act or "identity").lower()
    prof = OpProfiler.get()
    if residual is not None and residual.shape != x.shape:
        prof.count("precision/epilogue_fallbacks")
        return None
    if not fusable(x, axis, act):
        prof.count("precision/epilogue_fallbacks")
        return None
    if mode is None:
        mode = default_mode()
    if mode not in ("pallas", "interpret", "xla"):
        raise ValueError(f"unknown epilogue mode {mode!r}")
    f32 = jnp.float32
    inv = lax.rsqrt(var.astype(f32) + jnp.asarray(epsilon, f32))
    scale = inv if gamma is None else gamma.astype(f32) * inv
    shift = -mean.astype(f32) * scale
    if beta is not None:
        shift = beta.astype(f32) + shift
    scale, shift = scale.astype(x.dtype), shift.astype(x.dtype)
    if residual is not None:
        residual = residual.astype(x.dtype)
    prof.count("precision/epilogue_hits")
    if residual is not None:
        prof.count("precision/epilogue_residual_hits")
    if mode == "xla":
        shape = [1] * x.ndim
        shape[1] = x.shape[1]
        y = x * scale.reshape(shape) + shift.reshape(shape)
        if residual is not None:
            y = y + residual
        return _act_fn(act)(y)
    if x.ndim == 4:
        to2d = lambda a: a.transpose(0, 2, 3, 1).reshape(-1, x.shape[1])
        n, c, h, w = x.shape
        back = lambda a: a.reshape(n, h, w, c).transpose(0, 3, 1, 2)
    else:
        to2d = back = lambda a: a
    out = _launch(to2d(x), scale, shift,
                  None if residual is None else to2d(residual),
                  act, interpret=(mode == "interpret"))
    return back(out)
