"""Elementwise transform ops (same/float/strict families) + activations.

Reference: libnd4j legacy transform kernels (``include/loops/cpu/transform/``)
and the ``IActivation`` SPI impl set (nd4j-api
``org/nd4j/linalg/activations/impl/`` — ReLU..GELU..Mish, SURVEY.md §2.1).
All lower to XLA elementwise HLO and fuse into neighbors.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import op

# --- float transforms -------------------------------------------------------


@op("abs", "transform")
def abs_(x):
    return jnp.abs(x)


@op("neg", "transform")
def neg(x):
    return jnp.negative(x)


@op("sign", "transform")
def sign(x):
    return jnp.sign(x)


@op("ceil", "transform")
def ceil(x):
    return jnp.ceil(x)


@op("floor", "transform")
def floor(x):
    return jnp.floor(x)


@op("round", "transform")
def round_(x):
    return jnp.round(x)


@op("rint", "transform")
def rint(x):
    return jnp.rint(x)


@op("square", "transform")
def square(x):
    return jnp.square(x)


@op("cube", "transform")
def cube(x):
    return x * x * x


@op("reciprocal", "transform")
def reciprocal(x):
    return jnp.reciprocal(x)


@op("sqrt", "transform")
def sqrt(x):
    return jnp.sqrt(x)


@op("rsqrt", "transform")
def rsqrt(x):
    return lax.rsqrt(x)


@op("cbrt", "transform")
def cbrt(x):
    return jnp.cbrt(x)


@op("exp", "transform")
def exp(x):
    return jnp.exp(x)


@op("expm1", "transform")
def expm1(x):
    return jnp.expm1(x)


@op("log", "transform")
def log(x):
    return jnp.log(x)


@op("log1p", "transform")
def log1p(x):
    return jnp.log1p(x)


@op("log2", "transform")
def log2(x):
    return jnp.log2(x)


@op("log10", "transform")
def log10(x):
    return jnp.log10(x)


@op("sin", "transform")
def sin(x):
    return jnp.sin(x)


@op("cos", "transform")
def cos(x):
    return jnp.cos(x)


@op("tan", "transform")
def tan(x):
    return jnp.tan(x)


@op("asin", "transform")
def asin(x):
    return jnp.arcsin(x)


@op("acos", "transform")
def acos(x):
    return jnp.arccos(x)


@op("atan", "transform")
def atan(x):
    return jnp.arctan(x)


@op("sinh", "transform")
def sinh(x):
    return jnp.sinh(x)


@op("cosh", "transform")
def cosh(x):
    return jnp.cosh(x)


@op("tanh", "transform")
def tanh(x):
    return jnp.tanh(x)


@op("asinh", "transform")
def asinh(x):
    return jnp.arcsinh(x)


@op("acosh", "transform")
def acosh(x):
    return jnp.arccosh(x)


@op("atanh", "transform")
def atanh(x):
    return jnp.arctanh(x)


@op("erf", "transform")
def erf(x):
    return jax.scipy.special.erf(x)


@op("erfc", "transform")
def erfc(x):
    return jax.scipy.special.erfc(x)


@op("clip_by_value", "transform")
def clip_by_value(x, clip_min: float, clip_max: float):
    return jnp.clip(x, clip_min, clip_max)


@op("clip_by_norm", "transform")
def clip_by_norm(x, clip_norm: float):
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return jnp.where(norm > clip_norm, x * (clip_norm / norm), x)


@op("clip_by_global_norm", "transform")
def clip_by_global_norm(*xs, clip_norm: float):
    gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(x)) for x in xs))
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(gnorm, 1e-12))
    out = tuple(x * scale for x in xs)
    return out if len(out) > 1 else out[0]


@op("isnan", "transform", differentiable=False)
def isnan(x):
    return jnp.isnan(x)


@op("isinf", "transform", differentiable=False)
def isinf(x):
    return jnp.isinf(x)


@op("isfinite", "transform", differentiable=False)
def isfinite(x):
    return jnp.isfinite(x)


@op("step", "transform", differentiable=False)
def step(x):
    return (x > 0).astype(x.dtype)


# --- activations (IActivation SPI analog) -----------------------------------


@op("relu", "activation")
def relu(x):
    return jnp.maximum(x, 0)


@op("relu6", "activation")
def relu6(x):
    return jnp.clip(x, 0, 6)


@op("leakyrelu", "activation")
def leakyrelu(x, alpha: float = 0.01):
    return jnp.where(x >= 0, x, alpha * x)


@op("prelu", "activation")
def prelu(x, alpha):
    """Learned per-channel leak (alpha broadcasts against x)."""
    return jnp.where(x >= 0, x, alpha * x)


@op("thresholdedrelu", "activation")
def thresholdedrelu(x, theta: float = 1.0):
    return jnp.where(x > theta, x, 0.0).astype(x.dtype)


@op("elu", "activation")
def elu(x, alpha: float = 1.0):
    return jnp.where(x > 0, x, alpha * jnp.expm1(x))


@op("selu", "activation")
def selu(x):
    alpha, scale = 1.6732632423543772, 1.0507009873554805
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@op("gelu", "activation")
def gelu(x):
    """tanh-approximation GELU (matches the reference's GELU impl)."""
    return 0.5 * x * (1.0 + jnp.tanh(0.7978845608028654 * (x + 0.044715 * x * x * x)))


@op("gelu_exact", "activation")
def gelu_exact(x):
    return jax.nn.gelu(x, approximate=False)


@op("mish", "activation")
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@op("swish", "activation")
def swish(x):
    return x * jax.nn.sigmoid(x)


@op("sigmoid", "activation")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@op("hardsigmoid", "activation")
def hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


@op("hardtanh", "activation")
def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


@op("rationaltanh", "activation")
def rationaltanh(x):
    """1.7159 * tanh_approx(2x/3) — reference RationalTanh."""
    a = 0.6666667 * x
    approx = jnp.sign(a) * (1.0 - 1.0 / (1.0 + jnp.abs(a) + a * a + 1.41645 * (a ** 4)))
    return 1.7159 * approx


@op("rectifiedtanh", "activation")
def rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x)).astype(x.dtype)


@op("softplus", "activation")
def softplus(x):
    return jax.nn.softplus(x)


@op("softsign", "activation")
def softsign(x):
    return jax.nn.soft_sign(x)


@op("identity", "activation")
def identity(x):
    return x


@op("softmax", "activation")
def softmax(x, axis: int = -1):
    return jax.nn.softmax(x, axis=axis)


@op("log_softmax", "activation")
def log_softmax(x, axis: int = -1):
    return jax.nn.log_softmax(x, axis=axis)


@op("cast", "datatype")
def cast(x, dtype="float32"):
    """Dtype cast (reference DataTypes family / TF Cast import target)."""
    return jnp.asarray(x).astype(jnp.dtype(dtype))


@op("stop_gradient", "transform")
def stop_gradient(x):
    return lax.stop_gradient(x)


@op("einsum", "linalg")
def einsum(*xs, equation: str):
    """General tensor contraction (TF Einsum import target) — XLA lowers
    contractions straight onto the MXU."""
    return jnp.einsum(equation, *xs)


@op("tf_strided_slice", "shape")
def tf_strided_slice(x, spec=None):
    """TF StridedSlice semantics. ``spec`` is a JSON-safe encoding (so
    SameDiff graphs serialize) of a numpy-style index, computed at import
    time from the TF begin/end/stride masks (imports/tf_graph_mapper.py):
    each entry is ["slice", b, e, s] | ["idx", i] | ["newaxis"] |
    ["ellipsis"]."""
    idx = []
    for ent in spec:
        kind = ent[0]
        if kind == "slice":
            idx.append(slice(ent[1], ent[2], ent[3]))
        elif kind == "idx":
            idx.append(int(ent[1]))
        elif kind == "newaxis":
            idx.append(None)
        elif kind == "ellipsis":
            idx.append(Ellipsis)
        else:
            raise ValueError(f"bad strided-slice spec entry {ent!r}")
    return x[tuple(idx)]


# --- round-4 tail: special functions + utility transforms the reference
# ships as generic ops (libnd4j generic/parity_ops + transforms; SURVEY
# §2.2) that were still absent from the registry ------------------------


@op("lgamma", "transform")
def lgamma(x):
    return jax.scipy.special.gammaln(x)


@op("digamma", "transform")
def digamma(x):
    return jax.scipy.special.digamma(x)


@op("polygamma", "transform")
def polygamma(n, x):
    return jax.scipy.special.polygamma(jnp.asarray(n, jnp.int32), x)


@op("zeta", "transform")
def zeta(x, q):
    """Hurwitz zeta (reference zeta op)."""
    return jax.scipy.special.zeta(x, q)


@op("igamma", "transform")
def igamma(a, x):
    """Regularized lower incomplete gamma P(a, x)."""
    return jax.scipy.special.gammainc(a, x)


@op("igammac", "transform")
def igammac(a, x):
    """Regularized upper incomplete gamma Q(a, x) = 1 - P(a, x)."""
    return jax.scipy.special.gammaincc(a, x)


@op("betainc", "transform", differentiable=False)
def betainc(a, b, x):
    """Regularized incomplete beta. Marked non-differentiable: jax defines
    no gradient w.r.t. a/b (only x), so the conservative contract holds."""
    return jax.scipy.special.betainc(a, b, x)


@op("erfinv", "transform")
def erfinv(x):
    return jax.scipy.special.erfinv(x)


@op("roll", "transform")
def roll(x, shift, axis=None):
    """Circular shift (reference roll op)."""
    if axis is None:
        return jnp.roll(x, shift)
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else int(axis)
    shift = tuple(shift) if isinstance(shift, (list, tuple)) else int(shift)
    return jnp.roll(x, shift, axis)


@op("standardize", "transform")
def standardize(x, dims=(-1,)):
    """Zero-mean unit-variance along ``dims`` (reference standardize op)."""
    dims = tuple(dims) if isinstance(dims, (list, tuple)) else (int(dims),)
    mean = jnp.mean(x, axis=dims, keepdims=True)
    std = jnp.std(x, axis=dims, keepdims=True)
    return (x - mean) / jnp.maximum(std, 1e-12)
