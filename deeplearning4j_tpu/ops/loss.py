"""Loss ops (raw-array level; the ILossFunction SPI shell lives in nn/).

Reference: libnd4j ``include/ops/declarable/generic/loss/`` (log_loss,
mean_sqerr_loss, hinge_loss, huber_loss, softmax_cross_entropy, ctc_loss...).
Reductions follow the TF-style reduction modes the reference exposes:
none / sum / mean_by_weight / mean_by_nonzero_weight.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import op


def _reduce(per_ex, weights, reduction: str):
    if weights is None:
        weights = jnp.ones_like(per_ex)
    weighted = per_ex * weights
    r = reduction.lower()
    if r == "none":
        return weighted
    if r == "sum":
        return jnp.sum(weighted)
    if r == "mean_by_weight":
        return jnp.sum(weighted) / jnp.maximum(jnp.sum(weights), 1e-12)
    if r == "mean_by_nonzero_weight" or r == "mean":
        nz = jnp.sum((weights != 0).astype(per_ex.dtype))
        return jnp.sum(weighted) / jnp.maximum(nz, 1.0)
    raise ValueError(f"unknown reduction {reduction!r}")


@op("log_loss", "loss")
def log_loss(predictions, labels, weights=None, epsilon: float = 1e-7,
             reduction: str = "mean_by_nonzero_weight"):
    """Binary cross-entropy on probabilities."""
    p = jnp.clip(predictions, epsilon, 1.0 - epsilon)
    per = -(labels * jnp.log(p) + (1.0 - labels) * jnp.log1p(-p))
    return _reduce(per, weights, reduction)


@op("sigmoid_cross_entropy", "loss")
def sigmoid_cross_entropy(logits, labels, weights=None, label_smoothing: float = 0.0,
                          reduction: str = "mean_by_nonzero_weight"):
    if label_smoothing > 0:
        labels = labels * (1.0 - label_smoothing) + 0.5 * label_smoothing
    per = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return _reduce(per, weights, reduction)


@op("softmax_cross_entropy", "loss")
def softmax_cross_entropy(logits, labels, weights=None, label_smoothing: float = 0.0,
                          reduction: str = "mean_by_nonzero_weight"):
    """labels: one-hot/soft distribution over last axis."""
    if label_smoothing > 0:
        n = logits.shape[-1]
        labels = labels * (1.0 - label_smoothing) + label_smoothing / n
    logp = jax.nn.log_softmax(logits, axis=-1)
    per = -jnp.sum(labels * logp, axis=-1)
    return _reduce(per, weights, reduction)


@op("sparse_softmax_cross_entropy", "loss")
def sparse_softmax_cross_entropy(logits, labels, weights=None,
                                 reduction: str = "mean_by_nonzero_weight"):
    """labels: int class indices."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    per = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return _reduce(per, weights, reduction)


@op("mean_sqerr_loss", "loss")
def mean_sqerr_loss(predictions, labels, weights=None,
                    reduction: str = "mean_by_nonzero_weight"):
    per = jnp.mean(jnp.square(predictions - labels), axis=tuple(range(1, predictions.ndim))) \
        if predictions.ndim > 1 else jnp.square(predictions - labels)
    return _reduce(per, weights, reduction)


@op("mean_pairwssqerr_loss", "loss")
def mean_pairwssqerr_loss(predictions, labels, weights=None,
                          reduction: str = "mean_by_nonzero_weight"):
    diff = predictions - labels
    b = diff.shape[0]
    flat = diff.reshape(b, -1)
    n = flat.shape[1]
    sum_sq = jnp.sum(jnp.square(flat), axis=1)
    sq_sum = jnp.square(jnp.sum(flat, axis=1))
    per = (n * sum_sq - sq_sum) / jnp.maximum(n * (n - 1) / 2.0, 1.0) / 2.0
    return _reduce(per, weights, reduction)


@op("absolute_difference_loss", "loss")
def absolute_difference_loss(predictions, labels, weights=None,
                             reduction: str = "mean_by_nonzero_weight"):
    per = jnp.mean(jnp.abs(predictions - labels), axis=tuple(range(1, predictions.ndim))) \
        if predictions.ndim > 1 else jnp.abs(predictions - labels)
    return _reduce(per, weights, reduction)


@op("hinge_loss", "loss")
def hinge_loss(logits, labels, weights=None, reduction: str = "mean_by_nonzero_weight"):
    """labels in {0,1} (reference converts to ±1)."""
    signed = 2.0 * labels - 1.0
    per = jnp.mean(jnp.maximum(0.0, 1.0 - signed * logits),
                   axis=tuple(range(1, logits.ndim))) if logits.ndim > 1 \
        else jnp.maximum(0.0, 1.0 - signed * logits)
    return _reduce(per, weights, reduction)


@op("huber_loss", "loss")
def huber_loss(predictions, labels, weights=None, delta: float = 1.0,
               reduction: str = "mean_by_nonzero_weight"):
    err = jnp.abs(predictions - labels)
    quad = jnp.minimum(err, delta)
    per_el = 0.5 * jnp.square(quad) + delta * (err - quad)
    per = jnp.mean(per_el, axis=tuple(range(1, predictions.ndim))) \
        if predictions.ndim > 1 else per_el
    return _reduce(per, weights, reduction)


@op("cosine_distance_loss", "loss")
def cosine_distance_loss(predictions, labels, weights=None, dim: int = -1,
                         reduction: str = "mean_by_nonzero_weight"):
    per = 1.0 - jnp.sum(predictions * labels, axis=dim)
    return _reduce(per, weights, reduction)


@op("kld_loss", "loss")
def kld_loss(predictions, labels, weights=None, epsilon: float = 1e-7,
             reduction: str = "mean_by_nonzero_weight"):
    p = jnp.clip(predictions, epsilon, 1.0)
    l = jnp.clip(labels, epsilon, 1.0)
    per = jnp.sum(labels * (jnp.log(l) - jnp.log(p)), axis=-1)
    return _reduce(per, weights, reduction)


@op("poisson_loss", "loss")
def poisson_loss(predictions, labels, weights=None,
                 reduction: str = "mean_by_nonzero_weight", log_input: bool = False):
    if log_input:
        per_el = jnp.exp(predictions) - labels * predictions
    else:
        per_el = predictions - labels * jnp.log(jnp.maximum(predictions, 1e-7))
    per = jnp.mean(per_el, axis=tuple(range(1, predictions.ndim))) \
        if predictions.ndim > 1 else per_el
    return _reduce(per, weights, reduction)


@op("ctc_loss", "loss")
def ctc_loss(log_probs, targets, input_lengths, target_lengths, blank: int = 0):
    """CTC via the stable log-alpha recursion over a lax.scan (reference
    helpers/cpu/ctcLoss.cpp). log_probs: [B, T, C]; targets: [B, S]."""
    b, t_max, c = log_probs.shape
    s_max = targets.shape[1]
    # extended label sequence with interleaved blanks: length 2S+1
    ext = jnp.full((b, 2 * s_max + 1), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(targets.astype(jnp.int32))
    ext_len = 2 * target_lengths.astype(jnp.int32) + 1
    neg_inf = jnp.asarray(-1e30, dtype=log_probs.dtype)

    # transition allowed from s-2 when ext[s] != blank and ext[s] != ext[s-2]
    ext_prev2 = jnp.concatenate([jnp.full((b, 2), blank, jnp.int32), ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (ext != ext_prev2)

    alpha0 = jnp.full((b, 2 * s_max + 1), neg_inf)
    alpha0 = alpha0.at[:, 0].set(log_probs[:, 0, blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(target_lengths > 0,
                  jnp.take_along_axis(log_probs[:, 0], ext[:, 1:2], axis=1)[:, 0], neg_inf))

    def step(alpha, xs_t):
        lp_t, t = xs_t  # lp_t: [B, C]
        prev1 = jnp.concatenate([jnp.full((b, 1), neg_inf), alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate([jnp.full((b, 2), neg_inf), alpha[:, :-2]], axis=1)
        prev2 = jnp.where(can_skip, prev2, neg_inf)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
        emit = jnp.take_along_axis(lp_t, ext, axis=1)
        new_alpha = merged + emit
        # freeze past input_lengths
        active = (t < input_lengths)[:, None]
        return jnp.where(active, new_alpha, alpha), None

    lp_rest = jnp.swapaxes(jnp.asarray(log_probs), 0, 1)[1:]  # [T-1, B, C]
    alpha, _ = jax.lax.scan(step, alpha0, (lp_rest, jnp.arange(1, t_max)))
    last = jnp.take_along_axis(alpha, (ext_len - 1)[:, None], axis=1)[:, 0]
    last2 = jnp.take_along_axis(alpha, jnp.maximum(ext_len - 2, 0)[:, None], axis=1)[:, 0]
    return -jnp.logaddexp(last, last2)
