"""Recurrent ops: fused LSTM/GRU/SimpleRNN layers and single cells.

Reference: libnd4j ``include/ops/declarable/generic/recurrent/{lstmLayer,
lstmCell,gruCell,sru}.cpp`` + helper ``lstmLayer.cpp``; DL4J's Java fused
impl ``org.deeplearning4j.nn.layers.recurrent.LSTMHelpers``.

TPU design: the time loop is a ``lax.scan`` — compiled once, no per-step
dispatch; the four gate matmuls are fused into ONE [nIn+nOut, 4*nOut] GEMM per
step (the same trick LSTMHelpers uses), which keeps the MXU busy. Gate order
follows the reference: [input(i), forget(f), output(o), cell(g)] — DL4J uses
IFOG ordering in its recurrent weight layout.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .registry import op


@op("lstm_cell", "recurrent")
def lstm_cell(x, h_prev, c_prev, w, b):
    """One LSTM step. x: [B, nIn]; w: [nIn+nOut, 4*nOut] (IFOG); b: [4*nOut]."""
    n_out = h_prev.shape[-1]
    z = jnp.concatenate([x, h_prev], axis=-1) @ w + b
    i, f, o, g = jnp.split(z, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    return h, c


@op("lstm_layer", "recurrent")
def lstm_layer(x, w, b, h0=None, c0=None, time_major: bool = False,
               return_sequences: bool = True):
    """Full-sequence LSTM via lax.scan.

    x: [B, T, nIn] (or [T, B, nIn] when time_major); w: [nIn+nOut, 4*nOut].
    Returns (outputs [B, T, nOut], (hT, cT)).
    """
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)  # -> [T, B, nIn]
    t, bsz, _ = x.shape
    n_out = w.shape[1] // 4
    h = h0 if h0 is not None else jnp.zeros((bsz, n_out), dtype=x.dtype)
    c = c0 if c0 is not None else jnp.zeros((bsz, n_out), dtype=x.dtype)

    def step(carry, xt):
        h, c = carry
        h, c = lstm_cell(xt, h, c, w, b)
        return (h, c), h

    (h_t, c_t), ys = lax.scan(step, (h, c), x)
    if not time_major:
        ys = jnp.swapaxes(ys, 0, 1)
    if not return_sequences:
        ys = ys[:, -1] if not time_major else ys[-1]
    return ys, (h_t, c_t)


@op("gru_cell", "recurrent")
def gru_cell(x, h_prev, w_ru, w_c, b_ru, b_c):
    """One GRU step (reference gruCell): w_ru: [nIn+nOut, 2*nOut] (reset,update),
    w_c: [nIn+nOut, nOut]."""
    xa = jnp.concatenate([x, h_prev], axis=-1)
    ru = jax.nn.sigmoid(xa @ w_ru + b_ru)
    r, u = jnp.split(ru, 2, axis=-1)
    xc = jnp.concatenate([x, r * h_prev], axis=-1)
    c = jnp.tanh(xc @ w_c + b_c)
    return u * h_prev + (1.0 - u) * c


@op("gru_layer", "recurrent")
def gru_layer(x, w_ru, w_c, b_ru, b_c, h0=None, time_major: bool = False):
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)
    t, bsz, _ = x.shape
    n_out = w_c.shape[1]
    h = h0 if h0 is not None else jnp.zeros((bsz, n_out), dtype=x.dtype)

    def step(h, xt):
        h = gru_cell(xt, h, w_ru, w_c, b_ru, b_c)
        return h, h

    h_t, ys = lax.scan(step, h, x)
    if not time_major:
        ys = jnp.swapaxes(ys, 0, 1)
    return ys, h_t


@op("gru_layer_ra", "recurrent")
def gru_layer_ra(x, w_ru, w_cx, w_ch, b_ru, b_cx, b_ch, h0=None,
                 time_major: bool = False):
    """GRU with the CuDNN/Keras ``reset_after=True`` candidate form:
    ``r,u = σ([x,h]·w_ru + b_ru)``;
    ``c = tanh(x·w_cx + b_cx + r*(h·w_ch + b_ch))``;
    ``h' = u*h + (1-u)*c``.
    Distinct from :func:`gru_layer` (the v1 form resets BEFORE the
    recurrent matmul); both exist because Keras h5 checkpoints default to
    reset_after=True while the reference's gruCell is the v1 form."""
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)
    t, bsz, _ = x.shape
    n_out = w_cx.shape[1]
    h = h0 if h0 is not None else jnp.zeros((bsz, n_out), dtype=x.dtype)

    def step(h, xt):
        ru = jax.nn.sigmoid(jnp.concatenate([xt, h], axis=-1) @ w_ru + b_ru)
        r, u = jnp.split(ru, 2, axis=-1)
        c = jnp.tanh(xt @ w_cx + b_cx + r * (h @ w_ch + b_ch))
        h = u * h + (1.0 - u) * c
        return h, h

    h_t, ys = lax.scan(step, h, x)
    if not time_major:
        ys = jnp.swapaxes(ys, 0, 1)
    return ys, h_t


@op("simple_rnn_layer", "recurrent")
def simple_rnn_layer(x, w, rw, b, h0=None, time_major: bool = False,
                     activation=jnp.tanh):
    """SimpleRnn: h_t = act(x_t W + h_{t-1} R + b); act defaults to tanh
    (the reference's SimpleRnn applies its CONFIGURED activation inside the
    recurrence, so the layer passes it through)."""
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)
    t, bsz, _ = x.shape
    n_out = w.shape[1]
    h = h0 if h0 is not None else jnp.zeros((bsz, n_out), dtype=x.dtype)

    def step(h, xt):
        h = activation(xt @ w + h @ rw + b)
        return h, h

    h_t, ys = lax.scan(step, h, x)
    if not time_major:
        ys = jnp.swapaxes(ys, 0, 1)
    return ys, h_t


@op("sru_layer", "recurrent")
def sru_layer(x, w, b, c0=None, time_major: bool = False):
    """Simple Recurrent Unit (reference sru op). w: [nIn, 3*nIn]; the heavy
    matmul is time-parallel, only the light recurrence scans."""
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)
    t, bsz, n = x.shape
    z = x @ w  # [T, B, 3n] — one big MXU matmul for the whole sequence
    xt_, f_, r_ = jnp.split(z, 3, axis=-1)
    bf, br = jnp.split(b, 2)
    f = jax.nn.sigmoid(f_ + bf)
    r = jax.nn.sigmoid(r_ + br)
    c = c0 if c0 is not None else jnp.zeros((bsz, n), dtype=x.dtype)

    def step(c, t_in):
        xt, ft, rt, raw = t_in
        c = ft * c + (1.0 - ft) * xt
        h = rt * jnp.tanh(c) + (1.0 - rt) * raw
        return c, h

    c_t, ys = lax.scan(step, c, (xt_, f, r, x))
    if not time_major:
        ys = jnp.swapaxes(ys, 0, 1)
    return ys, c_t


@op("bidirectional_lstm", "recurrent")
def bidirectional_lstm(x, w_fwd, b_fwd, w_bwd, b_bwd, mode: str = "concat"):
    """Reference Bidirectional wrapper modes: ADD/MUL/AVERAGE/CONCAT."""
    fwd, _ = lstm_layer(x, w_fwd, b_fwd)
    bwd, _ = lstm_layer(jnp.flip(x, axis=1), w_bwd, b_bwd)
    bwd = jnp.flip(bwd, axis=1)
    mode = mode.lower()
    if mode == "concat":
        return jnp.concatenate([fwd, bwd], axis=-1)
    if mode == "add":
        return fwd + bwd
    if mode == "mul":
        return fwd * bwd
    if mode == "average":
        return 0.5 * (fwd + bwd)
    raise ValueError(f"unknown bidirectional mode {mode!r}")
