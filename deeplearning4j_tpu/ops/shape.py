"""Shape / gather-scatter / segment ops.

Reference: libnd4j ``include/ops/declarable/generic/shape/``, ``transforms/``
(concat/split/tile/gather/scatter/pad/...), and ``parity_ops/`` segment ops.
Gather/scatter lower to XLA gather/scatter HLO; segment ops use jax's
``segment_sum`` family which XLA tiles well on TPU.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from .registry import op


@op("reshape", "shape")
def reshape(x, shape):
    return jnp.reshape(x, tuple(shape))


@op("permute", "shape")
def permute(x, dims):
    return jnp.transpose(x, tuple(dims))


@op("transpose", "shape")
def transpose(x):
    return jnp.transpose(x)


@op("expand_dims", "shape")
def expand_dims(x, axis: int):
    return jnp.expand_dims(x, axis)


@op("squeeze", "shape")
def squeeze(x, axis=None):
    return jnp.squeeze(x, axis=axis)


@op("concat", "shape")
def concat(*xs, axis: int = 0):
    return jnp.concatenate(xs, axis=axis)


@op("split", "shape")
def split(x, num_split: int, axis: int = 0):
    return tuple(jnp.split(x, num_split, axis=axis))


@op("split_v", "shape")
def split_v(x, sizes: Sequence[int], axis: int = 0):
    # split points stay Python ints: jnp math here would become tracers under
    # jit and jnp.split needs static indices
    idx, acc = [], 0
    for s in list(sizes)[:-1]:
        acc += int(s)
        idx.append(acc)
    return tuple(jnp.split(x, idx, axis=axis))


@op("stack", "shape")
def stack(*xs, axis: int = 0):
    return jnp.stack(xs, axis=axis)


@op("unstack", "shape")
def unstack(x, axis: int = 0):
    n = x.shape[axis]
    return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis))


@op("tile", "shape")
def tile(x, reps):
    return jnp.tile(x, tuple(reps))


@op("repeat", "shape")
def repeat(x, repeats: int, axis: int):
    return jnp.repeat(x, repeats, axis=axis)


@op("reverse", "shape")
def reverse(x, dims):
    return jnp.flip(x, axis=tuple(dims) if not isinstance(dims, int) else dims)


@op("pad", "shape")
def pad(x, paddings, mode: str = "constant", constant_value: float = 0.0):
    mode = mode.lower()
    pads = tuple(tuple(p) for p in paddings)
    if mode == "constant":
        return jnp.pad(x, pads, mode="constant", constant_values=constant_value)
    if mode == "reflect":
        return jnp.pad(x, pads, mode="reflect")
    if mode == "symmetric":
        return jnp.pad(x, pads, mode="symmetric")
    if mode == "edge":   # replicate boundary value (ONNX Pad mode="edge")
        return jnp.pad(x, pads, mode="edge")
    raise ValueError(f"unknown pad mode {mode!r}")


@op("gather", "shape")
def gather(x, indices, axis: int = 0):
    return jnp.take(x, indices, axis=axis)


@op("gather_nd", "shape")
def gather_nd(x, indices):
    """TF-style gather_nd: trailing index dim addresses leading x dims."""
    indices = jnp.asarray(indices)
    idx = tuple(jnp.moveaxis(indices, -1, 0))
    return x[idx]


@op("scatter_update", "scatter")
def scatter_update(ref, indices, updates):
    return jnp.asarray(ref).at[indices].set(updates)


@op("scatter_add", "scatter")
def scatter_add(ref, indices, updates):
    return jnp.asarray(ref).at[indices].add(updates)


@op("scatter_sub", "scatter")
def scatter_sub(ref, indices, updates):
    return jnp.asarray(ref).at[indices].add(-jnp.asarray(updates))


@op("scatter_mul", "scatter")
def scatter_mul(ref, indices, updates):
    return jnp.asarray(ref).at[indices].multiply(updates)


@op("scatter_div", "scatter")
def scatter_div(ref, indices, updates):
    return jnp.asarray(ref).at[indices].divide(updates)


@op("scatter_max", "scatter")
def scatter_max(ref, indices, updates):
    return jnp.asarray(ref).at[indices].max(updates)


@op("scatter_min", "scatter")
def scatter_min(ref, indices, updates):
    return jnp.asarray(ref).at[indices].min(updates)


@op("slice", "shape")
def slice_(x, begin, sizes):
    return lax.dynamic_slice(x, tuple(begin), tuple(sizes))


@op("strided_slice", "shape")
def strided_slice(x, begin, end, strides=None):
    idx = tuple(
        slice(b, e, s)
        for b, e, s in zip(begin, end, strides or [1] * len(begin))
    )
    return x[idx]


@op("size", "shape", differentiable=False)
def size(x):
    return jnp.asarray(x.size, dtype=jnp.int64)


@op("shape_of", "shape", differentiable=False)
def shape_of(x):
    return jnp.asarray(x.shape, dtype=jnp.int64)


@op("rank", "shape", differentiable=False)
def rank(x):
    return jnp.asarray(x.ndim, dtype=jnp.int32)


@op("fill", "shape", differentiable=False)
def fill(shape, value, dtype=jnp.float32):
    return jnp.full(tuple(shape), value, dtype=dtype)


@op("zeros_as", "shape", differentiable=False)
def zeros_as(x):
    return jnp.zeros_like(x)


@op("ones_as", "shape", differentiable=False)
def ones_as(x):
    return jnp.ones_like(x)


@op("linspace", "shape", differentiable=False)
def linspace(start, stop, num: int):
    return jnp.linspace(start, stop, num)


@op("range", "shape", differentiable=False)
def range_(start, limit, delta=1):
    return jnp.arange(start, limit, delta)


@op("eye", "shape", differentiable=False)
def eye(rows: int, cols: int = None):
    return jnp.eye(rows, cols)


@op("diag", "shape")
def diag(x):
    """Input vector → diagonal matrix (reference diag op)."""
    return jnp.diag(x.ravel()).reshape(x.shape + x.shape)if x.ndim > 1 else jnp.diag(x)


@op("diag_part", "shape")
def diag_part(x):
    return jnp.diagonal(x)


@op("matrix_diag", "shape")
def matrix_diag(x):
    """Batched: last dim becomes a diagonal matrix."""
    return x[..., :, None] * jnp.eye(x.shape[-1], dtype=x.dtype)


@op("matrix_diag_part", "shape")
def matrix_diag_part(x):
    return jnp.diagonal(x, axis1=-2, axis2=-1)


@op("matrix_set_diag", "shape")
def matrix_set_diag(x, diagonal):
    eye = jnp.eye(x.shape[-2], x.shape[-1], dtype=bool)
    return jnp.where(eye, _diag_embed(diagonal, x.shape), x)


def _diag_embed(diagonal, shape):
    out = jnp.zeros(shape, dtype=diagonal.dtype)
    idx = jnp.arange(min(shape[-2], shape[-1]))
    return out.at[..., idx, idx].set(diagonal)


@op("broadcast_to", "shape")
def broadcast_to(x, shape):
    return jnp.broadcast_to(x, tuple(shape))


@op("meshgrid", "shape")
def meshgrid(*xs, indexing: str = "xy"):
    return tuple(jnp.meshgrid(*xs, indexing=indexing))


@op("where", "shape")
def where(cond, x=None, y=None):
    if x is None:
        return jnp.argwhere(cond)
    return jnp.where(cond, x, y)


@op("select", "shape")
def select(cond, x, y):
    return jnp.where(cond, x, y)


@op("boolean_mask", "shape", differentiable=False)
def boolean_mask(x, mask):
    return x[jnp.asarray(mask)]


@op("one_hot", "shape", differentiable=False)
def one_hot(indices, depth: int, on_value: float = 1.0, off_value: float = 0.0,
            axis: int = -1, dtype=jnp.float32):
    oh = jax.nn.one_hot(indices, depth, axis=axis, dtype=dtype)
    return oh * (on_value - off_value) + off_value


@op("flatten_2d", "shape")
def flatten_2d(x, axis: int = 1):
    """Collapse dims [axis:] (reference Flatten2D)."""
    lead = int(jnp.prod(jnp.asarray(x.shape[:axis]))) if axis > 0 else 1
    return jnp.reshape(x, (lead, -1))


@op("top_k", "shape", differentiable=False)
def top_k(x, k: int, sorted: bool = True):
    return lax.top_k(x, k)


@op("in_top_k", "shape", differentiable=False)
def in_top_k(predictions, targets, k: int):
    _, idx = lax.top_k(predictions, k)
    return jnp.any(idx == targets[:, None], axis=-1)


@op("unique", "shape", differentiable=False)
def unique(x):
    vals, idx = jnp.unique(x, return_inverse=True, size=x.size, fill_value=0)
    return vals, idx


@op("sequence_mask", "shape", differentiable=False)
def sequence_mask(lengths, maxlen: int, dtype=jnp.bool_):
    return (jnp.arange(maxlen)[None, :] < jnp.asarray(lengths)[..., None]).astype(dtype)


@op("confusion_matrix", "shape", differentiable=False)
def confusion_matrix(labels, predictions, num_classes: int, weights=None):
    idx = labels.astype(jnp.int32) * num_classes + predictions.astype(jnp.int32)
    w = weights if weights is not None else jnp.ones_like(idx, dtype=jnp.float64)
    flat = jnp.zeros((num_classes * num_classes,), dtype=w.dtype).at[idx].add(w)
    return flat.reshape(num_classes, num_classes)


@op("dynamic_partition", "shape", differentiable=False)
def dynamic_partition(x, partitions, num_partitions: int):
    """Static-shaped variant: returns (num_partitions, N) padded with zeros +
    a mask — XLA needs static shapes (SURVEY.md §7.3.3 dynamic-shape policy)."""
    outs = []
    for p in range(num_partitions):
        mask = partitions == p
        outs.append(jnp.where(mask, x, jnp.zeros_like(x)))
    return tuple(outs)


@op("dynamic_stitch", "shape", differentiable=False)
def dynamic_stitch(indices, data):
    n = sum(i.size for i in indices)
    out = jnp.zeros((n,) + data[0].shape[1:], dtype=data[0].dtype)
    for idx, d in zip(indices, data):
        out = out.at[idx.ravel()].set(d.reshape((-1,) + d.shape[len(idx.shape):]))
    return out


# --- segment ops (reference parity_ops/segment_*.cpp) ------------------------


@op("segment_sum", "segment")
def segment_sum(data, segment_ids, num_segments: int):
    return jax.ops.segment_sum(data, segment_ids, num_segments)


@op("segment_mean", "segment")
def segment_mean(data, segment_ids, num_segments: int):
    sums = jax.ops.segment_sum(data, segment_ids, num_segments)
    counts = jax.ops.segment_sum(jnp.ones_like(data), segment_ids, num_segments)
    return sums / jnp.maximum(counts, 1)


@op("segment_max", "segment")
def segment_max(data, segment_ids, num_segments: int):
    return jax.ops.segment_max(data, segment_ids, num_segments)


@op("segment_min", "segment")
def segment_min(data, segment_ids, num_segments: int):
    return jax.ops.segment_min(data, segment_ids, num_segments)


@op("segment_prod", "segment")
def segment_prod(data, segment_ids, num_segments: int):
    return jax.ops.segment_prod(data, segment_ids, num_segments)


@op("unsorted_segment_sum", "segment")
def unsorted_segment_sum(data, segment_ids, num_segments: int):
    return jax.ops.segment_sum(data, segment_ids, num_segments, indices_are_sorted=False)


@op("unsorted_segment_mean", "segment")
def unsorted_segment_mean(data, segment_ids, num_segments: int):
    sums = jax.ops.segment_sum(data, segment_ids, num_segments, indices_are_sorted=False)
    counts = jax.ops.segment_sum(jnp.ones_like(data), segment_ids, num_segments,
                                 indices_are_sorted=False)
    return sums / jnp.maximum(counts, 1)


@op("unsorted_segment_max", "segment")
def unsorted_segment_max(data, segment_ids, num_segments: int):
    return jax.ops.segment_max(data, segment_ids, num_segments, indices_are_sorted=False)


@op("unsorted_segment_min", "segment")
def unsorted_segment_min(data, segment_ids, num_segments: int):
    return jax.ops.segment_min(data, segment_ids, num_segments, indices_are_sorted=False)


@op("unsorted_segment_prod", "segment")
def unsorted_segment_prod(data, segment_ids, num_segments: int):
    return jax.ops.segment_prod(data, segment_ids, num_segments, indices_are_sorted=False)


@op("unsorted_segment_sqrt_n", "segment")
def unsorted_segment_sqrt_n(data, segment_ids, num_segments: int):
    sums = jax.ops.segment_sum(data, segment_ids, num_segments, indices_are_sorted=False)
    counts = jax.ops.segment_sum(jnp.ones_like(data), segment_ids, num_segments,
                                 indices_are_sorted=False)
    return sums / jnp.sqrt(jnp.maximum(counts, 1))


# --- space/depth rearrangement (reference generic/transforms) ----------------


@op("space_to_depth", "shape")
def space_to_depth(x, block_size: int, data_format: str = "NHWC"):
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 2, 3, 1))
    n, h, w, c = x.shape
    b = block_size
    x = x.reshape(n, h // b, b, w // b, b, c).transpose(0, 1, 3, 2, 4, 5)
    x = x.reshape(n, h // b, w // b, b * b * c)
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 3, 1, 2))
    return x


@op("depth_to_space", "shape")
def depth_to_space(x, block_size: int, data_format: str = "NHWC"):
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 2, 3, 1))
    n, h, w, c = x.shape
    b = block_size
    x = x.reshape(n, h, w, b, b, c // (b * b)).transpose(0, 1, 3, 2, 4, 5)
    x = x.reshape(n, h * b, w * b, c // (b * b))
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 3, 1, 2))
    return x


@op("batch_to_space", "shape")
def batch_to_space(x, block_shape, crops):
    n = x.shape[0]
    bs = int(jnp.prod(jnp.asarray(block_shape)))
    b0, b1 = block_shape
    _, h, w, c = x.shape
    out = x.reshape(b0, b1, n // bs, h, w, c).transpose(2, 3, 0, 4, 1, 5)
    out = out.reshape(n // bs, h * b0, w * b1, c)
    (ct, cb), (cl, cr) = crops
    return out[:, ct:out.shape[1] - cb, cl:out.shape[2] - cr, :]


@op("space_to_batch", "shape")
def space_to_batch(x, block_shape, paddings):
    (pt, pb), (pl, pr) = paddings
    x = jnp.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0)))
    n, h, w, c = x.shape
    b0, b1 = block_shape
    out = x.reshape(n, h // b0, b0, w // b1, b1, c).transpose(2, 4, 0, 1, 3, 5)
    return out.reshape(n * b0 * b1, h // b0, w // b1, c)


@op("mirror_pad", "shape")
def mirror_pad(x, paddings, mode: str = "reflect"):
    """TF MirrorPad semantics (reference mirror_pad op): mode "reflect"
    excludes the edge value from the mirror, "symmetric" includes it."""
    m = mode.lower()
    if m not in ("reflect", "symmetric"):
        raise ValueError(f"mirror_pad mode must be reflect|symmetric, got {mode!r}")
    pads = tuple((int(lo), int(hi)) for lo, hi in paddings)
    return jnp.pad(x, pads, mode=m)


@op("searchsorted", "shape", differentiable=False)
def searchsorted(sorted_seq, values, side: str = "left"):
    return jnp.searchsorted(sorted_seq, values, side=side)


@op("bincount", "shape", differentiable=False)
def bincount(x, weights=None, length=None, maxlength=None):
    """Reference/TF bincount with a STATIC output length (XLA shapes
    cannot grow with max(x) the way numpy's ``minlength`` does — that
    param is deliberately absent so its grows-to-fit semantics can't be
    assumed). Values ≥ length are dropped, matching TF's
    ``maxlength`` contract."""
    n = length or maxlength
    if not n:
        raise ValueError("bincount needs a static output length "
                         "(length=/maxlength=)")
    return jnp.bincount(jnp.asarray(x, jnp.int32).reshape(-1),
                        weights=None if weights is None
                        else jnp.asarray(weights).reshape(-1),
                        length=int(n))


@op("histogram_fixed_width", "shape", differentiable=False)
def histogram_fixed_width(x, value_range, nbins: int = 100):
    """Reference histogram_fixed_width: counts per equal-width bin over
    ``value_range``, outliers clamped to the edge bins."""
    lo, hi = value_range[0], value_range[1]
    xf = jnp.asarray(x, jnp.float32).reshape(-1)
    idx = jnp.clip(((xf - lo) / jnp.maximum(hi - lo, 1e-30)
                    * nbins).astype(jnp.int32), 0, nbins - 1)
    return jnp.zeros((nbins,), jnp.int32).at[idx].add(1)


@op("nth_element", "shape", differentiable=False)
def nth_element(x, n: int, reverse: bool = False):
    """n-th smallest (or largest with reverse=True) along the last axis
    (reference nth_element)."""
    s = jnp.sort(x, axis=-1)
    if reverse:
        s = jnp.flip(s, axis=-1)
    return s[..., n]
