"""Model zoo.

Reference: dl4j-zoo ``org.deeplearning4j.zoo.model.{LeNet, AlexNet, VGG16,
VGG19, ResNet50, SqueezeNet, Darknet19, TinyYOLO, UNet, SimpleCNN,
TextGenerationLSTM, ...}`` (SURVEY.md §2.3). Architectures follow the
reference's published configurations; ``init_pretrained`` has no weight server
in this environment (zero egress) and raises with instructions instead of
silently downloading.

All CNN zoo models use NCHW like the reference; ResNet-50 is the
ComputationGraph flagship (north-star config 2).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from ..learning.updaters import Adam, Nesterovs
from ..nn.conf import layers as L
from ..nn.conf.builder import NeuralNetConfiguration
from ..nn.conf.inputs import InputType
from ..nn.graph import (ComputationGraph, ComputationGraphConfiguration,
                        ElementWiseVertex, MergeVertex)
from ..nn.multilayer import MultiLayerNetwork


class ZooModel:
    """Base (reference org.deeplearning4j.zoo.ZooModel)."""

    def init(self):
        raise NotImplementedError

    def init_pretrained(self, kind: str = "imagenet"):
        raise RuntimeError(
            f"{type(self).__name__}: pretrained weights unavailable — this "
            "environment has no network egress. Train from scratch via init() "
            "or load a local checkpoint with MultiLayerNetwork/"
            "ComputationGraph.load().")

    initPretrained = init_pretrained


class LeNet(ZooModel):
    """reference zoo.model.LeNet (MNIST)."""

    def __init__(self, num_classes: int = 10, seed: int = 123):
        self.num_classes = num_classes
        self.seed = seed

    def init(self) -> MultiLayerNetwork:
        conf = (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(Nesterovs(learning_rate=0.01, momentum=0.9))
                .activation("relu").weight_init("xavier")
                .list()
                .layer(L.ConvolutionLayer(n_out=20, kernel_size=(5, 5)))
                .layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(L.ConvolutionLayer(n_out=50, kernel_size=(5, 5)))
                .layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(L.DenseLayer(n_out=500))
                .layer(L.OutputLayer(n_out=self.num_classes, loss="mcxent",
                                     activation="softmax"))
                .set_input_type(InputType.convolutional(28, 28, 1))
                .build())
        return MultiLayerNetwork(conf).init()


class SimpleCNN(ZooModel):
    """reference zoo.model.SimpleCNN."""

    def __init__(self, num_classes: int = 10, input_shape=(3, 48, 48), seed: int = 123):
        self.num_classes = num_classes
        self.input_shape = input_shape
        self.seed = seed

    def init(self) -> MultiLayerNetwork:
        c, h, w = self.input_shape
        conf = (NeuralNetConfiguration.builder()
                .seed(self.seed).updater(Adam(5e-4)).activation("relu")
                .list()
                .layer(L.ConvolutionLayer(n_out=16, kernel_size=(3, 3), padding=(1, 1)))
                .layer(L.BatchNormalization())
                .layer(L.ConvolutionLayer(n_out=16, kernel_size=(3, 3), padding=(1, 1)))
                .layer(L.BatchNormalization())
                .layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(L.ConvolutionLayer(n_out=32, kernel_size=(3, 3), padding=(1, 1)))
                .layer(L.BatchNormalization())
                .layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(L.DenseLayer(n_out=256))
                .layer(L.DropoutLayer(rate=0.5))
                .layer(L.OutputLayer(n_out=self.num_classes))
                .set_input_type(InputType.convolutional(h, w, c))
                .build())
        return MultiLayerNetwork(conf).init()


class AlexNet(ZooModel):
    """reference zoo.model.AlexNet (single-tower variant)."""

    def __init__(self, num_classes: int = 1000, seed: int = 123):
        self.num_classes = num_classes
        self.seed = seed

    def init(self) -> MultiLayerNetwork:
        conf = (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(Nesterovs(learning_rate=1e-2, momentum=0.9))
                .activation("relu").weight_init("relu")
                .list()
                .layer(L.ConvolutionLayer(n_out=96, kernel_size=(11, 11), stride=(4, 4)))
                .layer(L.LocalResponseNormalization())
                .layer(L.SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(L.ConvolutionLayer(n_out=256, kernel_size=(5, 5), padding=(2, 2)))
                .layer(L.LocalResponseNormalization())
                .layer(L.SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(L.ConvolutionLayer(n_out=384, kernel_size=(3, 3), padding=(1, 1)))
                .layer(L.ConvolutionLayer(n_out=384, kernel_size=(3, 3), padding=(1, 1)))
                .layer(L.ConvolutionLayer(n_out=256, kernel_size=(3, 3), padding=(1, 1)))
                .layer(L.SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(L.DenseLayer(n_out=4096, dropout=0.5))
                .layer(L.DenseLayer(n_out=4096, dropout=0.5))
                .layer(L.OutputLayer(n_out=self.num_classes))
                .set_input_type(InputType.convolutional(227, 227, 3))
                .build())
        return MultiLayerNetwork(conf).init()


class VGG16(ZooModel):
    """reference zoo.model.VGG16."""

    def __init__(self, num_classes: int = 1000, seed: int = 123):
        self.num_classes = num_classes
        self.seed = seed

    def _blocks(self) -> Sequence[Tuple[int, int]]:
        return [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]

    def init(self) -> MultiLayerNetwork:
        lb = (NeuralNetConfiguration.builder()
              .seed(self.seed)
              .updater(Nesterovs(learning_rate=1e-2, momentum=0.9))
              .activation("relu").weight_init("relu")
              .list())
        for n_convs, ch in self._blocks():
            for _ in range(n_convs):
                lb = lb.layer(L.ConvolutionLayer(n_out=ch, kernel_size=(3, 3),
                                                 padding=(1, 1)))
            lb = lb.layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        conf = (lb.layer(L.DenseLayer(n_out=4096, dropout=0.5))
                .layer(L.DenseLayer(n_out=4096, dropout=0.5))
                .layer(L.OutputLayer(n_out=self.num_classes))
                .set_input_type(InputType.convolutional(224, 224, 3))
                .build())
        return MultiLayerNetwork(conf).init()


class VGG19(VGG16):
    """reference zoo.model.VGG19."""

    def _blocks(self):
        return [(2, 64), (2, 128), (4, 256), (4, 512), (4, 512)]


class ResNet50(ZooModel):
    """reference zoo.model.ResNet50 — the north-star ComputationGraph config:
    conv/identity bottleneck blocks with ElementWiseVertex(Add) residuals."""

    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 image_size: int = 224):
        self.num_classes = num_classes
        self.seed = seed
        self.image_size = image_size

    def init(self) -> ComputationGraph:
        gb = (ComputationGraphConfiguration
              .graph_builder(NeuralNetConfiguration.builder()
                             .seed(self.seed)
                             .updater(Nesterovs(learning_rate=0.1, momentum=0.9))
                             .activation("relu").weight_init("relu").l2(1e-4))
              .add_inputs("input"))
        # stem
        gb.add_layer("stem_conv", L.ConvolutionLayer(
            n_out=64, kernel_size=(7, 7), stride=(2, 2), padding=(3, 3),
            has_bias=False, activation="identity"), "input")
        gb.add_layer("stem_bn", L.BatchNormalization(activation="relu"), "stem_conv")
        gb.add_layer("stem_pool", L.SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), padding=(1, 1)), "stem_bn")

        prev = "stem_pool"
        stages = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2),
                  (3, 512, 2048, 2)]
        for s, (blocks, mid, out_ch, first_stride) in enumerate(stages):
            for b in range(blocks):
                stride = first_stride if b == 0 else 1
                name = f"s{s}b{b}"
                # main path: 1x1 -> 3x3 -> 1x1 (bottleneck)
                gb.add_layer(f"{name}_c1", L.ConvolutionLayer(
                    n_out=mid, kernel_size=(1, 1), stride=(stride, stride),
                    has_bias=False, activation="identity"), prev)
                gb.add_layer(f"{name}_bn1", L.BatchNormalization(activation="relu"),
                             f"{name}_c1")
                gb.add_layer(f"{name}_c2", L.ConvolutionLayer(
                    n_out=mid, kernel_size=(3, 3), padding=(1, 1),
                    has_bias=False, activation="identity"), f"{name}_bn1")
                gb.add_layer(f"{name}_bn2", L.BatchNormalization(activation="relu"),
                             f"{name}_c2")
                gb.add_layer(f"{name}_c3", L.ConvolutionLayer(
                    n_out=out_ch, kernel_size=(1, 1), has_bias=False,
                    activation="identity"), f"{name}_bn2")
                gb.add_layer(f"{name}_bn3", L.BatchNormalization(activation="identity"),
                             f"{name}_c3")
                # shortcut
                if b == 0:
                    gb.add_layer(f"{name}_sc", L.ConvolutionLayer(
                        n_out=out_ch, kernel_size=(1, 1), stride=(stride, stride),
                        has_bias=False, activation="identity"), prev)
                    gb.add_layer(f"{name}_scbn", L.BatchNormalization(
                        activation="identity"), f"{name}_sc")
                    shortcut = f"{name}_scbn"
                else:
                    shortcut = prev
                gb.add_vertex(f"{name}_add", ElementWiseVertex(op="add"),
                              f"{name}_bn3", shortcut)
                gb.add_layer(f"{name}_relu", L.ActivationLayer(activation="relu"),
                             f"{name}_add")
                prev = f"{name}_relu"

        gb.add_layer("avgpool", L.GlobalPoolingLayer(pooling_type="avg"), prev)
        gb.add_layer("output", L.OutputLayer(n_out=self.num_classes, loss="mcxent",
                                             activation="softmax"), "avgpool")
        conf = (gb.set_outputs("output")
                .set_input_types(InputType.convolutional(
                    self.image_size, self.image_size, 3))
                .build())
        return ComputationGraph(conf).init()


class SqueezeNet(ZooModel):
    """reference zoo.model.SqueezeNet (fire modules via MergeVertex)."""

    def __init__(self, num_classes: int = 1000, seed: int = 123):
        self.num_classes = num_classes
        self.seed = seed

    def init(self) -> ComputationGraph:
        gb = (ComputationGraphConfiguration
              .graph_builder(NeuralNetConfiguration.builder()
                             .seed(self.seed).updater(Adam(1e-3))
                             .activation("relu").weight_init("relu"))
              .add_inputs("input"))
        gb.add_layer("conv1", L.ConvolutionLayer(n_out=64, kernel_size=(3, 3),
                                                 stride=(2, 2)), "input")
        gb.add_layer("pool1", L.SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)),
                     "conv1")
        prev = "pool1"

        def fire(name, squeeze, expand, inp):
            gb.add_layer(f"{name}_sq", L.ConvolutionLayer(
                n_out=squeeze, kernel_size=(1, 1)), inp)
            gb.add_layer(f"{name}_e1", L.ConvolutionLayer(
                n_out=expand, kernel_size=(1, 1)), f"{name}_sq")
            gb.add_layer(f"{name}_e3", L.ConvolutionLayer(
                n_out=expand, kernel_size=(3, 3), padding=(1, 1)), f"{name}_sq")
            gb.add_vertex(f"{name}_cat", MergeVertex(), f"{name}_e1", f"{name}_e3")
            return f"{name}_cat"

        prev = fire("fire2", 16, 64, prev)
        prev = fire("fire3", 16, 64, prev)
        gb.add_layer("pool3", L.SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)), prev)
        prev = fire("fire4", 32, 128, "pool3")
        prev = fire("fire5", 32, 128, prev)
        gb.add_layer("pool5", L.SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)), prev)
        prev = fire("fire6", 48, 192, "pool5")
        prev = fire("fire7", 48, 192, prev)
        prev = fire("fire8", 64, 256, prev)
        prev = fire("fire9", 64, 256, prev)
        gb.add_layer("drop", L.DropoutLayer(rate=0.5), prev)
        gb.add_layer("conv10", L.ConvolutionLayer(n_out=self.num_classes,
                                                  kernel_size=(1, 1)), "drop")
        gb.add_layer("gap", L.GlobalPoolingLayer(pooling_type="avg"), "conv10")
        gb.add_layer("output", L.LossLayer(loss="mcxent", activation="softmax"), "gap")
        conf = (gb.set_outputs("output")
                .set_input_types(InputType.convolutional(224, 224, 3)).build())
        return ComputationGraph(conf).init()


class Darknet19(ZooModel):
    """reference zoo.model.Darknet19."""

    def __init__(self, num_classes: int = 1000, seed: int = 123, image_size: int = 224):
        self.num_classes = num_classes
        self.seed = seed
        self.image_size = image_size

    def init(self) -> MultiLayerNetwork:
        def conv_bn(lb, ch, k):
            pad = (k // 2, k // 2) if k > 1 else (0, 0)
            return (lb.layer(L.ConvolutionLayer(n_out=ch, kernel_size=(k, k),
                                                padding=pad, has_bias=False,
                                                activation="identity"))
                    .layer(L.BatchNormalization(activation="leakyrelu")))

        lb = (NeuralNetConfiguration.builder()
              .seed(self.seed).updater(Nesterovs(1e-3, 0.9))
              .weight_init("relu").list())
        lb = conv_bn(lb, 32, 3)
        lb = lb.layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        lb = conv_bn(lb, 64, 3)
        lb = lb.layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        for chs in ([128, 64, 128], [256, 128, 256]):
            for i, ch in enumerate(chs):
                lb = conv_bn(lb, ch, 3 if i % 2 == 0 else 1)
            lb = lb.layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        for chs in ([512, 256, 512, 256, 512], [1024, 512, 1024, 512, 1024]):
            for i, ch in enumerate(chs):
                lb = conv_bn(lb, ch, 3 if i % 2 == 0 else 1)
            if chs[0] == 512:
                lb = lb.layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        lb = lb.layer(L.ConvolutionLayer(n_out=self.num_classes, kernel_size=(1, 1)))
        lb = lb.layer(L.GlobalPoolingLayer(pooling_type="avg"))
        conf = (lb.layer(L.LossLayer(loss="mcxent", activation="softmax"))
                .set_input_type(InputType.convolutional(self.image_size,
                                                        self.image_size, 3))
                .build())
        return MultiLayerNetwork(conf).init()


class UNet(ZooModel):
    """reference zoo.model.UNet (segmentation; encoder-decoder with skip
    merges)."""

    def __init__(self, n_channels: int = 1, n_classes: int = 1, seed: int = 123,
                 image_size: int = 128, base: int = 32):
        self.n_channels = n_channels
        self.n_classes = n_classes
        self.seed = seed
        self.image_size = image_size
        self.base = base

    def init(self) -> ComputationGraph:
        gb = (ComputationGraphConfiguration
              .graph_builder(NeuralNetConfiguration.builder()
                             .seed(self.seed).updater(Adam(1e-4))
                             .activation("relu").weight_init("relu"))
              .add_inputs("input"))

        def double_conv(name, ch, inp):
            gb.add_layer(f"{name}_c1", L.ConvolutionLayer(
                n_out=ch, kernel_size=(3, 3), padding=(1, 1)), inp)
            gb.add_layer(f"{name}_c2", L.ConvolutionLayer(
                n_out=ch, kernel_size=(3, 3), padding=(1, 1)), f"{name}_c1")
            return f"{name}_c2"

        b = self.base
        d1 = double_conv("down1", b, "input")
        gb.add_layer("pool1", L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)), d1)
        d2 = double_conv("down2", b * 2, "pool1")
        gb.add_layer("pool2", L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)), d2)
        d3 = double_conv("down3", b * 4, "pool2")
        gb.add_layer("pool3", L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)), d3)
        mid = double_conv("mid", b * 8, "pool3")

        gb.add_layer("up3", L.Deconvolution2D(n_out=b * 4, kernel_size=(2, 2),
                                              stride=(2, 2)), mid)
        gb.add_vertex("cat3", MergeVertex(), "up3", d3)
        u3 = double_conv("upc3", b * 4, "cat3")
        gb.add_layer("up2", L.Deconvolution2D(n_out=b * 2, kernel_size=(2, 2),
                                              stride=(2, 2)), u3)
        gb.add_vertex("cat2", MergeVertex(), "up2", d2)
        u2 = double_conv("upc2", b * 2, "cat2")
        gb.add_layer("up1", L.Deconvolution2D(n_out=b, kernel_size=(2, 2),
                                              stride=(2, 2)), u2)
        gb.add_vertex("cat1", MergeVertex(), "up1", d1)
        u1 = double_conv("upc1", b, "cat1")
        gb.add_layer("head", L.ConvolutionLayer(n_out=self.n_classes,
                                                kernel_size=(1, 1),
                                                activation="identity"), u1)
        gb.add_layer("output", L.LossLayer(loss="binary_xent", activation="sigmoid"),
                     "head")
        conf = (gb.set_outputs("output")
                .set_input_types(InputType.convolutional(
                    self.image_size, self.image_size, self.n_channels))
                .build())
        return ComputationGraph(conf).init()


class TextGenerationLSTM(ZooModel):
    """reference zoo.model.TextGenerationLSTM (char-level LM)."""

    def __init__(self, vocab_size: int, hidden: int = 256, seed: int = 123):
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.seed = seed

    def init(self) -> MultiLayerNetwork:
        conf = (NeuralNetConfiguration.builder()
                .seed(self.seed).updater(Adam(2e-3))
                .list()
                .layer(L.LSTM(n_out=self.hidden))
                .layer(L.LSTM(n_out=self.hidden))
                .layer(L.RnnOutputLayer(n_out=self.vocab_size, loss="mcxent",
                                        activation="softmax"))
                .set_input_type(InputType.recurrent(self.vocab_size))
                .build())
        return MultiLayerNetwork(conf).init()
