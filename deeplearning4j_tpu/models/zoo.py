"""Model zoo.

Reference: dl4j-zoo ``org.deeplearning4j.zoo.model.{LeNet, AlexNet, VGG16,
VGG19, ResNet50, SqueezeNet, Darknet19, TinyYOLO, UNet, SimpleCNN,
TextGenerationLSTM, ...}`` (SURVEY.md §2.3). Architectures follow the
reference's published configurations; ``init_pretrained`` loads
``PretrainedType``-keyed ModelSerializer containers from a LOCAL weight
cache (``DL4J_TPU_PRETRAINED_DIR``) — this environment has no egress, so a
missing entry raises with the exact path to populate instead of
downloading (see ``ZooModel``).

All CNN zoo models use NCHW like the reference; ResNet-50 is the
ComputationGraph flagship (north-star config 2).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

from ..learning.updaters import Adam, Nesterovs
from ..nn.conf import layers as L
from ..nn.conf.builder import NeuralNetConfiguration
from ..nn.conf.inputs import InputType
from ..nn.graph import (ComputationGraph, ComputationGraphConfiguration,
                        ElementWiseVertex, MergeVertex)
from ..nn.multilayer import MultiLayerNetwork


class PretrainedType:
    """Reference org.deeplearning4j.zoo.PretrainedType."""

    IMAGENET = "imagenet"
    MNIST = "mnist"
    CIFAR10 = "cifar10"
    VGGFACE = "vggface"


class ZooModel:
    """Base (reference org.deeplearning4j.zoo.ZooModel).

    ``init_pretrained`` follows the reference's API shape (a
    ``PretrainedType``-keyed weight cache + ModelSerializer container) with
    ONE documented divergence: the reference downloads missing weights
    from Konduit's CDN; this environment has no network egress (SURVEY
    §0), so the cache is local-only — a missing entry raises with the
    exact path where a checkpoint must be placed. The cache directory is
    ``$DL4J_TPU_PRETRAINED_DIR`` (default ``~/.deeplearning4j_tpu/
    pretrained``); entries are ``<ModelClass>_<type>.zip`` ModelSerializer
    containers (write one with ``util.model_serializer.write_model``)."""

    def init(self):
        raise NotImplementedError

    @staticmethod
    def pretrained_cache_dir() -> str:
        return os.environ.get(
            "DL4J_TPU_PRETRAINED_DIR",
            os.path.join(os.path.expanduser("~"),
                         ".deeplearning4j_tpu", "pretrained"))

    def pretrained_path(self, kind: str = PretrainedType.IMAGENET) -> str:
        return os.path.join(self.pretrained_cache_dir(),
                            f"{type(self).__name__}_{kind}.zip")

    def pretrained_available(self,
                             kind: str = PretrainedType.IMAGENET) -> bool:
        return os.path.exists(self.pretrained_path(kind))

    def init_pretrained(self, kind: str = PretrainedType.IMAGENET):
        from ..util.model_serializer import restore_model

        path = self.pretrained_path(kind)
        if not os.path.exists(path):
            raise RuntimeError(
                f"{type(self).__name__}: no pretrained {kind!r} weights in "
                f"the local cache ({path}). This environment has no "
                "network egress, so automatic download is unavailable — "
                "place a ModelSerializer container at that path (or set "
                "DL4J_TPU_PRETRAINED_DIR), or train from scratch via "
                "init().")
        return restore_model(path)

    initPretrained = init_pretrained


class LeNet(ZooModel):
    """reference zoo.model.LeNet (MNIST)."""

    def __init__(self, num_classes: int = 10, seed: int = 123):
        self.num_classes = num_classes
        self.seed = seed

    def init(self) -> MultiLayerNetwork:
        conf = (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(Nesterovs(learning_rate=0.01, momentum=0.9))
                .activation("relu").weight_init("xavier")
                .list()
                .layer(L.ConvolutionLayer(n_out=20, kernel_size=(5, 5)))
                .layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(L.ConvolutionLayer(n_out=50, kernel_size=(5, 5)))
                .layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(L.DenseLayer(n_out=500))
                .layer(L.OutputLayer(n_out=self.num_classes, loss="mcxent",
                                     activation="softmax"))
                .set_input_type(InputType.convolutional(28, 28, 1))
                .build())
        return MultiLayerNetwork(conf).init()


class SimpleCNN(ZooModel):
    """reference zoo.model.SimpleCNN."""

    def __init__(self, num_classes: int = 10, input_shape=(3, 48, 48), seed: int = 123):
        self.num_classes = num_classes
        self.input_shape = input_shape
        self.seed = seed

    def init(self) -> MultiLayerNetwork:
        c, h, w = self.input_shape
        conf = (NeuralNetConfiguration.builder()
                .seed(self.seed).updater(Adam(5e-4)).activation("relu")
                .list()
                .layer(L.ConvolutionLayer(n_out=16, kernel_size=(3, 3), padding=(1, 1)))
                .layer(L.BatchNormalization())
                .layer(L.ConvolutionLayer(n_out=16, kernel_size=(3, 3), padding=(1, 1)))
                .layer(L.BatchNormalization())
                .layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(L.ConvolutionLayer(n_out=32, kernel_size=(3, 3), padding=(1, 1)))
                .layer(L.BatchNormalization())
                .layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(L.DenseLayer(n_out=256))
                .layer(L.DropoutLayer(rate=0.5))
                .layer(L.OutputLayer(n_out=self.num_classes))
                .set_input_type(InputType.convolutional(h, w, c))
                .build())
        return MultiLayerNetwork(conf).init()


class AlexNet(ZooModel):
    """reference zoo.model.AlexNet (single-tower variant)."""

    def __init__(self, num_classes: int = 1000, seed: int = 123):
        self.num_classes = num_classes
        self.seed = seed

    def init(self) -> MultiLayerNetwork:
        conf = (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(Nesterovs(learning_rate=1e-2, momentum=0.9))
                .activation("relu").weight_init("relu")
                .list()
                .layer(L.ConvolutionLayer(n_out=96, kernel_size=(11, 11), stride=(4, 4)))
                .layer(L.LocalResponseNormalization())
                .layer(L.SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(L.ConvolutionLayer(n_out=256, kernel_size=(5, 5), padding=(2, 2)))
                .layer(L.LocalResponseNormalization())
                .layer(L.SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(L.ConvolutionLayer(n_out=384, kernel_size=(3, 3), padding=(1, 1)))
                .layer(L.ConvolutionLayer(n_out=384, kernel_size=(3, 3), padding=(1, 1)))
                .layer(L.ConvolutionLayer(n_out=256, kernel_size=(3, 3), padding=(1, 1)))
                .layer(L.SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(L.DenseLayer(n_out=4096, dropout=0.5))
                .layer(L.DenseLayer(n_out=4096, dropout=0.5))
                .layer(L.OutputLayer(n_out=self.num_classes))
                .set_input_type(InputType.convolutional(227, 227, 3))
                .build())
        return MultiLayerNetwork(conf).init()


class VGG16(ZooModel):
    """reference zoo.model.VGG16."""

    def __init__(self, num_classes: int = 1000, seed: int = 123):
        self.num_classes = num_classes
        self.seed = seed

    def _blocks(self) -> Sequence[Tuple[int, int]]:
        return [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]

    def init(self) -> MultiLayerNetwork:
        lb = (NeuralNetConfiguration.builder()
              .seed(self.seed)
              .updater(Nesterovs(learning_rate=1e-2, momentum=0.9))
              .activation("relu").weight_init("relu")
              .list())
        for n_convs, ch in self._blocks():
            for _ in range(n_convs):
                lb = lb.layer(L.ConvolutionLayer(n_out=ch, kernel_size=(3, 3),
                                                 padding=(1, 1)))
            lb = lb.layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        conf = (lb.layer(L.DenseLayer(n_out=4096, dropout=0.5))
                .layer(L.DenseLayer(n_out=4096, dropout=0.5))
                .layer(L.OutputLayer(n_out=self.num_classes))
                .set_input_type(InputType.convolutional(224, 224, 3))
                .build())
        return MultiLayerNetwork(conf).init()


class VGG19(VGG16):
    """reference zoo.model.VGG19."""

    def _blocks(self):
        return [(2, 64), (2, 128), (4, 256), (4, 512), (4, 512)]


class ResNet50(ZooModel):
    """reference zoo.model.ResNet50 — the north-star ComputationGraph config:
    conv/identity bottleneck blocks with ElementWiseVertex(Add) residuals."""

    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 image_size: int = 224):
        self.num_classes = num_classes
        self.seed = seed
        self.image_size = image_size

    def init(self) -> ComputationGraph:
        gb = (ComputationGraphConfiguration
              .graph_builder(NeuralNetConfiguration.builder()
                             .seed(self.seed)
                             .updater(Nesterovs(learning_rate=0.1, momentum=0.9))
                             .activation("relu").weight_init("relu").l2(1e-4))
              .add_inputs("input"))
        # stem
        gb.add_layer("stem_conv", L.ConvolutionLayer(
            n_out=64, kernel_size=(7, 7), stride=(2, 2), padding=(3, 3),
            has_bias=False, activation="identity"), "input")
        gb.add_layer("stem_bn", L.BatchNormalization(activation="relu"), "stem_conv")
        gb.add_layer("stem_pool", L.SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), padding=(1, 1)), "stem_bn")

        prev = "stem_pool"
        stages = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2),
                  (3, 512, 2048, 2)]
        for s, (blocks, mid, out_ch, first_stride) in enumerate(stages):
            for b in range(blocks):
                stride = first_stride if b == 0 else 1
                name = f"s{s}b{b}"
                # main path: 1x1 -> 3x3 -> 1x1 (bottleneck)
                gb.add_layer(f"{name}_c1", L.ConvolutionLayer(
                    n_out=mid, kernel_size=(1, 1), stride=(stride, stride),
                    has_bias=False, activation="identity"), prev)
                gb.add_layer(f"{name}_bn1", L.BatchNormalization(activation="relu"),
                             f"{name}_c1")
                gb.add_layer(f"{name}_c2", L.ConvolutionLayer(
                    n_out=mid, kernel_size=(3, 3), padding=(1, 1),
                    has_bias=False, activation="identity"), f"{name}_bn1")
                gb.add_layer(f"{name}_bn2", L.BatchNormalization(activation="relu"),
                             f"{name}_c2")
                gb.add_layer(f"{name}_c3", L.ConvolutionLayer(
                    n_out=out_ch, kernel_size=(1, 1), has_bias=False,
                    activation="identity"), f"{name}_bn2")
                gb.add_layer(f"{name}_bn3", L.BatchNormalization(activation="identity"),
                             f"{name}_c3")
                # shortcut
                if b == 0:
                    gb.add_layer(f"{name}_sc", L.ConvolutionLayer(
                        n_out=out_ch, kernel_size=(1, 1), stride=(stride, stride),
                        has_bias=False, activation="identity"), prev)
                    gb.add_layer(f"{name}_scbn", L.BatchNormalization(
                        activation="identity"), f"{name}_sc")
                    shortcut = f"{name}_scbn"
                else:
                    shortcut = prev
                gb.add_vertex(f"{name}_add", ElementWiseVertex(op="add"),
                              f"{name}_bn3", shortcut)
                gb.add_layer(f"{name}_relu", L.ActivationLayer(activation="relu"),
                             f"{name}_add")
                prev = f"{name}_relu"

        gb.add_layer("avgpool", L.GlobalPoolingLayer(pooling_type="avg"), prev)
        gb.add_layer("output", L.OutputLayer(n_out=self.num_classes, loss="mcxent",
                                             activation="softmax"), "avgpool")
        conf = (gb.set_outputs("output")
                .set_input_types(InputType.convolutional(
                    self.image_size, self.image_size, 3))
                .build())
        return ComputationGraph(conf).init()


class SqueezeNet(ZooModel):
    """reference zoo.model.SqueezeNet (fire modules via MergeVertex)."""

    def __init__(self, num_classes: int = 1000, seed: int = 123):
        self.num_classes = num_classes
        self.seed = seed

    def init(self) -> ComputationGraph:
        gb = (ComputationGraphConfiguration
              .graph_builder(NeuralNetConfiguration.builder()
                             .seed(self.seed).updater(Adam(1e-3))
                             .activation("relu").weight_init("relu"))
              .add_inputs("input"))
        gb.add_layer("conv1", L.ConvolutionLayer(n_out=64, kernel_size=(3, 3),
                                                 stride=(2, 2)), "input")
        gb.add_layer("pool1", L.SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)),
                     "conv1")
        prev = "pool1"

        def fire(name, squeeze, expand, inp):
            gb.add_layer(f"{name}_sq", L.ConvolutionLayer(
                n_out=squeeze, kernel_size=(1, 1)), inp)
            gb.add_layer(f"{name}_e1", L.ConvolutionLayer(
                n_out=expand, kernel_size=(1, 1)), f"{name}_sq")
            gb.add_layer(f"{name}_e3", L.ConvolutionLayer(
                n_out=expand, kernel_size=(3, 3), padding=(1, 1)), f"{name}_sq")
            gb.add_vertex(f"{name}_cat", MergeVertex(), f"{name}_e1", f"{name}_e3")
            return f"{name}_cat"

        prev = fire("fire2", 16, 64, prev)
        prev = fire("fire3", 16, 64, prev)
        gb.add_layer("pool3", L.SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)), prev)
        prev = fire("fire4", 32, 128, "pool3")
        prev = fire("fire5", 32, 128, prev)
        gb.add_layer("pool5", L.SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)), prev)
        prev = fire("fire6", 48, 192, "pool5")
        prev = fire("fire7", 48, 192, prev)
        prev = fire("fire8", 64, 256, prev)
        prev = fire("fire9", 64, 256, prev)
        gb.add_layer("drop", L.DropoutLayer(rate=0.5), prev)
        gb.add_layer("conv10", L.ConvolutionLayer(n_out=self.num_classes,
                                                  kernel_size=(1, 1)), "drop")
        gb.add_layer("gap", L.GlobalPoolingLayer(pooling_type="avg"), "conv10")
        gb.add_layer("output", L.LossLayer(loss="mcxent", activation="softmax"), "gap")
        conf = (gb.set_outputs("output")
                .set_input_types(InputType.convolutional(224, 224, 3)).build())
        return ComputationGraph(conf).init()


class Darknet19(ZooModel):
    """reference zoo.model.Darknet19."""

    def __init__(self, num_classes: int = 1000, seed: int = 123, image_size: int = 224):
        self.num_classes = num_classes
        self.seed = seed
        self.image_size = image_size

    def init(self) -> MultiLayerNetwork:
        def conv_bn(lb, ch, k):
            pad = (k // 2, k // 2) if k > 1 else (0, 0)
            return (lb.layer(L.ConvolutionLayer(n_out=ch, kernel_size=(k, k),
                                                padding=pad, has_bias=False,
                                                activation="identity"))
                    .layer(L.BatchNormalization(activation="leakyrelu")))

        lb = (NeuralNetConfiguration.builder()
              .seed(self.seed).updater(Nesterovs(1e-3, 0.9))
              .weight_init("relu").list())
        lb = conv_bn(lb, 32, 3)
        lb = lb.layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        lb = conv_bn(lb, 64, 3)
        lb = lb.layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        for chs in ([128, 64, 128], [256, 128, 256]):
            for i, ch in enumerate(chs):
                lb = conv_bn(lb, ch, 3 if i % 2 == 0 else 1)
            lb = lb.layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        for chs in ([512, 256, 512, 256, 512], [1024, 512, 1024, 512, 1024]):
            for i, ch in enumerate(chs):
                lb = conv_bn(lb, ch, 3 if i % 2 == 0 else 1)
            if chs[0] == 512:
                lb = lb.layer(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        lb = lb.layer(L.ConvolutionLayer(n_out=self.num_classes, kernel_size=(1, 1)))
        lb = lb.layer(L.GlobalPoolingLayer(pooling_type="avg"))
        conf = (lb.layer(L.LossLayer(loss="mcxent", activation="softmax"))
                .set_input_type(InputType.convolutional(self.image_size,
                                                        self.image_size, 3))
                .build())
        return MultiLayerNetwork(conf).init()


class UNet(ZooModel):
    """reference zoo.model.UNet (segmentation; encoder-decoder with skip
    merges)."""

    def __init__(self, n_channels: int = 1, n_classes: int = 1, seed: int = 123,
                 image_size: int = 128, base: int = 32):
        self.n_channels = n_channels
        self.n_classes = n_classes
        self.seed = seed
        self.image_size = image_size
        self.base = base

    def init(self) -> ComputationGraph:
        gb = (ComputationGraphConfiguration
              .graph_builder(NeuralNetConfiguration.builder()
                             .seed(self.seed).updater(Adam(1e-4))
                             .activation("relu").weight_init("relu"))
              .add_inputs("input"))

        def double_conv(name, ch, inp):
            gb.add_layer(f"{name}_c1", L.ConvolutionLayer(
                n_out=ch, kernel_size=(3, 3), padding=(1, 1)), inp)
            gb.add_layer(f"{name}_c2", L.ConvolutionLayer(
                n_out=ch, kernel_size=(3, 3), padding=(1, 1)), f"{name}_c1")
            return f"{name}_c2"

        b = self.base
        d1 = double_conv("down1", b, "input")
        gb.add_layer("pool1", L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)), d1)
        d2 = double_conv("down2", b * 2, "pool1")
        gb.add_layer("pool2", L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)), d2)
        d3 = double_conv("down3", b * 4, "pool2")
        gb.add_layer("pool3", L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)), d3)
        mid = double_conv("mid", b * 8, "pool3")

        gb.add_layer("up3", L.Deconvolution2D(n_out=b * 4, kernel_size=(2, 2),
                                              stride=(2, 2)), mid)
        gb.add_vertex("cat3", MergeVertex(), "up3", d3)
        u3 = double_conv("upc3", b * 4, "cat3")
        gb.add_layer("up2", L.Deconvolution2D(n_out=b * 2, kernel_size=(2, 2),
                                              stride=(2, 2)), u3)
        gb.add_vertex("cat2", MergeVertex(), "up2", d2)
        u2 = double_conv("upc2", b * 2, "cat2")
        gb.add_layer("up1", L.Deconvolution2D(n_out=b, kernel_size=(2, 2),
                                              stride=(2, 2)), u2)
        gb.add_vertex("cat1", MergeVertex(), "up1", d1)
        u1 = double_conv("upc1", b, "cat1")
        gb.add_layer("head", L.ConvolutionLayer(n_out=self.n_classes,
                                                kernel_size=(1, 1),
                                                activation="identity"), u1)
        gb.add_layer("output", L.LossLayer(loss="binary_xent", activation="sigmoid"),
                     "head")
        conf = (gb.set_outputs("output")
                .set_input_types(InputType.convolutional(
                    self.image_size, self.image_size, self.n_channels))
                .build())
        return ComputationGraph(conf).init()


class TextGenerationLSTM(ZooModel):
    """reference zoo.model.TextGenerationLSTM (char-level LM)."""

    def __init__(self, vocab_size: int, hidden: int = 256, seed: int = 123):
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.seed = seed

    def init(self) -> MultiLayerNetwork:
        conf = (NeuralNetConfiguration.builder()
                .seed(self.seed).updater(Adam(2e-3))
                .list()
                .layer(L.LSTM(n_out=self.hidden))
                .layer(L.LSTM(n_out=self.hidden))
                .layer(L.RnnOutputLayer(n_out=self.vocab_size, loss="mcxent",
                                        activation="softmax"))
                .set_input_type(InputType.recurrent(self.vocab_size))
                .build())
        return MultiLayerNetwork(conf).init()


class TinyYOLO(ZooModel):
    """reference zoo.model.TinyYOLO: darknet-tiny conv/bn/leaky backbone +
    YOLOv2 detection head (reference anchors, VOC-style defaults)."""

    ANCHORS = ((1.08, 1.19), (3.42, 4.41), (6.63, 11.38), (9.42, 5.11),
               (16.62, 10.52))

    def __init__(self, num_classes: int = 20, seed: int = 123,
                 image_size: int = 416):
        self.num_classes = num_classes
        self.seed = seed
        self.image_size = image_size

    def init(self) -> MultiLayerNetwork:
        def conv_bn(lb, ch):
            return (lb.layer(L.ConvolutionLayer(
                        n_out=ch, kernel_size=(3, 3), padding=(1, 1),
                        has_bias=False, activation="identity"))
                    .layer(L.BatchNormalization(activation="leakyrelu")))

        lb = (NeuralNetConfiguration.builder()
              .seed(self.seed).updater(Adam(1e-3)).weight_init("relu")
              .list())
        for i, ch in enumerate((16, 32, 64, 128, 256, 512)):
            lb = conv_bn(lb, ch)
            stride = (2, 2) if i < 5 else (1, 1)
            lb = lb.layer(L.SubsamplingLayer(kernel_size=(2, 2),
                                             stride=stride,
                                             padding=(0, 0) if i < 5
                                             else (1, 1)))
        lb = conv_bn(lb, 1024)
        lb = conv_bn(lb, 1024)
        lb = lb.layer(L.ConvolutionLayer(
            n_out=len(self.ANCHORS) * (5 + self.num_classes),
            kernel_size=(1, 1), activation="identity"))
        conf = (lb.layer(L.Yolo2OutputLayer(anchors=self.ANCHORS))
                .set_input_type(InputType.convolutional(
                    self.image_size, self.image_size, 3))
                .build())
        return MultiLayerNetwork(conf).init()


class YOLO2(ZooModel):
    """reference zoo.model.YOLO2: Darknet-19 backbone + the passthrough
    (reorg) route — SpaceToDepth on the high-res feature map concatenated
    with the deep path (MergeVertex) — + YOLOv2 head."""

    ANCHORS = ((0.57273, 0.677385), (1.87446, 2.06253), (3.33843, 5.47434),
               (7.88282, 3.52778), (9.77052, 9.16828))

    def __init__(self, num_classes: int = 80, seed: int = 123,
                 image_size: int = 416):
        self.num_classes = num_classes
        self.seed = seed
        self.image_size = image_size

    def init(self) -> ComputationGraph:
        gb = (ComputationGraphConfiguration
              .graph_builder(NeuralNetConfiguration.builder()
                             .seed(self.seed).updater(Adam(1e-3))
                             .weight_init("relu"))
              .add_inputs("input"))
        idx = [0]

        def conv_bn(name_in, ch, k):
            i = idx[0]
            idx[0] += 1
            pad = (k // 2, k // 2) if k > 1 else (0, 0)
            gb.add_layer(f"conv{i}", L.ConvolutionLayer(
                n_out=ch, kernel_size=(k, k), padding=pad, has_bias=False,
                activation="identity"), name_in)
            gb.add_layer(f"bn{i}", L.BatchNormalization(
                activation="leakyrelu"), f"conv{i}")
            return f"bn{i}"

        def pool(name_in):
            i = idx[0]
            idx[0] += 1
            gb.add_layer(f"pool{i}", L.SubsamplingLayer(
                kernel_size=(2, 2), stride=(2, 2)), name_in)
            return f"pool{i}"

        prev = conv_bn("input", 32, 3)
        prev = pool(prev)
        prev = conv_bn(prev, 64, 3)
        prev = pool(prev)
        for chs in ([128, 64, 128], [256, 128, 256]):
            for j, ch in enumerate(chs):
                prev = conv_bn(prev, ch, 3 if j % 2 == 0 else 1)
            prev = pool(prev)
        for j, ch in enumerate([512, 256, 512, 256, 512]):
            prev = conv_bn(prev, ch, 3 if j % 2 == 0 else 1)
        route = prev                       # 26x26x512 passthrough source
        prev = pool(prev)
        for j, ch in enumerate([1024, 512, 1024, 512, 1024]):
            prev = conv_bn(prev, ch, 3 if j % 2 == 0 else 1)
        prev = conv_bn(prev, 1024, 3)
        prev = conv_bn(prev, 1024, 3)
        # passthrough: reorg the 26x26 map to 13x13 and concat
        gb.add_layer("reorg", L.SpaceToDepthLayer(block_size=2), route)
        gb.add_vertex("route_cat", MergeVertex(), "reorg", prev)
        prev = conv_bn("route_cat", 1024, 3)
        gb.add_layer("head", L.ConvolutionLayer(
            n_out=len(self.ANCHORS) * (5 + self.num_classes),
            kernel_size=(1, 1), activation="identity"), prev)
        gb.add_layer("yolo", L.Yolo2OutputLayer(anchors=self.ANCHORS),
                     "head")
        conf = (gb.set_outputs("yolo")
                .set_input_types(InputType.convolutional(
                    self.image_size, self.image_size, 3))
                .build())
        return ComputationGraph(conf).init()


class Xception(ZooModel):
    """reference zoo.model.Xception: entry/middle/exit flows of separable
    convolutions with conv-projection residuals (ElementWiseVertex add)."""

    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 image_size: int = 299):
        self.num_classes = num_classes
        self.seed = seed
        self.image_size = image_size

    def init(self) -> ComputationGraph:
        gb = (ComputationGraphConfiguration
              .graph_builder(NeuralNetConfiguration.builder()
                             .seed(self.seed).updater(Adam(1e-3))
                             .activation("relu").weight_init("relu"))
              .add_inputs("input"))
        n = [0]

        def sep_bn(name_in, ch, act="relu"):
            i = n[0]
            n[0] += 1
            gb.add_layer(f"sep{i}", L.SeparableConvolution2D(
                n_out=ch, kernel_size=(3, 3), convolution_mode="same",
                has_bias=False, activation="identity"), name_in)
            gb.add_layer(f"sbn{i}", L.BatchNormalization(activation=act),
                         f"sep{i}")
            return f"sbn{i}"

        def conv_bn(name_in, ch, k, stride, act="relu"):
            i = n[0]
            n[0] += 1
            gb.add_layer(f"cv{i}", L.ConvolutionLayer(
                n_out=ch, kernel_size=(k, k), stride=(stride, stride),
                convolution_mode="same", has_bias=False,
                activation="identity"), name_in)
            gb.add_layer(f"cbn{i}", L.BatchNormalization(activation=act),
                         f"cv{i}")
            return f"cbn{i}"

        def maxpool(name_in):
            i = n[0]
            n[0] += 1
            gb.add_layer(f"mp{i}", L.SubsamplingLayer(
                kernel_size=(3, 3), stride=(2, 2), padding=(1, 1)), name_in)
            return f"mp{i}"

        # entry flow
        prev = conv_bn("input", 32, 3, 2)
        prev = conv_bn(prev, 64, 3, 1)
        for ch in (128, 256, 728):
            res = conv_bn(prev, ch, 1, 2, act="identity")
            x = sep_bn(prev, ch)
            x = sep_bn(x, ch, act="identity")
            x = maxpool(x)
            i = n[0]
            n[0] += 1
            gb.add_vertex(f"add{i}", ElementWiseVertex("add"), x, res)
            prev = f"add{i}"
        # middle flow: 8 blocks of 3 separable convs + identity residual
        for _ in range(8):
            x = prev
            for _ in range(3):
                x = sep_bn(x, 728)
            i = n[0]
            n[0] += 1
            gb.add_vertex(f"add{i}", ElementWiseVertex("add"), x, prev)
            prev = f"add{i}"
        # exit flow
        res = conv_bn(prev, 1024, 1, 2, act="identity")
        x = sep_bn(prev, 728)
        x = sep_bn(x, 1024, act="identity")
        x = maxpool(x)
        i = n[0]
        n[0] += 1
        gb.add_vertex(f"add{i}", ElementWiseVertex("add"), x, res)
        prev = sep_bn(f"add{i}", 1536)
        prev = sep_bn(prev, 2048)
        gb.add_layer("gap", L.GlobalPoolingLayer(pooling_type="avg"), prev)
        gb.add_layer("out", L.OutputLayer(n_out=self.num_classes,
                                          loss="mcxent",
                                          activation="softmax"), "gap")
        conf = (gb.set_outputs("out")
                .set_input_types(InputType.convolutional(
                    self.image_size, self.image_size, 3))
                .build())
        return ComputationGraph(conf).init()


class InceptionResNetV1(ZooModel):
    """reference zoo.model.InceptionResNetV1 (FaceNetNN4-era): stem +
    5x inception-resnet-A + reduction-A + 10x block-B + reduction-B +
    5x block-C, residual branches merged by concat then 1x1-projected and
    added back (ElementWiseVertex)."""

    def __init__(self, num_classes: int = 128, seed: int = 123,
                 image_size: int = 160):
        self.num_classes = num_classes
        self.seed = seed
        self.image_size = image_size

    def init(self) -> ComputationGraph:
        gb = (ComputationGraphConfiguration
              .graph_builder(NeuralNetConfiguration.builder()
                             .seed(self.seed).updater(Adam(1e-3))
                             .activation("relu").weight_init("relu"))
              .add_inputs("input"))
        n = [0]

        def conv(name_in, ch, k, stride=1, same=True, act="relu"):
            i = n[0]
            n[0] += 1
            gb.add_layer(f"c{i}", L.ConvolutionLayer(
                n_out=ch, kernel_size=(k, k), stride=(stride, stride),
                convolution_mode="same" if same else "truncate",
                has_bias=False, activation="identity"), name_in)
            gb.add_layer(f"b{i}", L.BatchNormalization(activation=act),
                         f"c{i}")
            return f"b{i}"

        def resnet_block(prev, branches, proj_ch):
            """concat(branches) → 1x1 proj → add residual → relu."""
            i = n[0]
            n[0] += 1
            gb.add_vertex(f"cat{i}", MergeVertex(), *branches)
            gb.add_layer(f"proj{i}", L.ConvolutionLayer(
                n_out=proj_ch, kernel_size=(1, 1),
                activation="identity"), f"cat{i}")
            gb.add_vertex(f"radd{i}", ElementWiseVertex("add"),
                          f"proj{i}", prev)
            gb.add_layer(f"ract{i}", L.ActivationLayer(activation="relu"),
                         f"radd{i}")
            return f"ract{i}"

        # stem (simplified faithful widths)
        prev = conv("input", 32, 3, stride=2)
        prev = conv(prev, 32, 3)
        prev = conv(prev, 64, 3)
        gb.add_layer("stem_pool", L.SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), padding=(1, 1)), prev)
        prev = conv("stem_pool", 80, 1)
        prev = conv(prev, 192, 3)
        prev = conv(prev, 256, 3, stride=2)

        # 5x inception-resnet-A (channels 256)
        for _ in range(5):
            b1 = conv(prev, 32, 1)
            b2 = conv(conv(prev, 32, 1), 32, 3)
            b3 = conv(conv(conv(prev, 32, 1), 32, 3), 32, 3)
            prev = resnet_block(prev, (b1, b2, b3), 256)
        # reduction-A → 896 channels
        ra1 = conv(prev, 384, 3, stride=2)
        ra2 = conv(conv(conv(prev, 192, 1), 192, 3), 256, 3, stride=2)
        gb.add_layer("redA_pool", L.SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), padding=(1, 1)), prev)
        gb.add_vertex("redA", MergeVertex(), ra1, ra2, "redA_pool")
        prev = "redA"
        # 10x inception-resnet-B (channels 896)
        for _ in range(10):
            b1 = conv(prev, 128, 1)
            b2 = conv(conv(prev, 128, 1), 128, 7)
            prev = resnet_block(prev, (b1, b2), 896)
        # reduction-B → 1792 channels
        rb1 = conv(conv(prev, 256, 1), 384, 3, stride=2)
        rb2 = conv(conv(prev, 256, 1), 256, 3, stride=2)
        rb3 = conv(conv(conv(prev, 256, 1), 256, 3), 256, 3, stride=2)
        gb.add_layer("redB_pool", L.SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), padding=(1, 1)), prev)
        gb.add_vertex("redB", MergeVertex(), rb1, rb2, rb3, "redB_pool")
        prev = "redB"
        # 5x inception-resnet-C (channels 1792)
        for _ in range(5):
            b1 = conv(prev, 192, 1)
            b2 = conv(conv(prev, 192, 1), 192, 3)
            prev = resnet_block(prev, (b1, b2), 1792)

        gb.add_layer("gap", L.GlobalPoolingLayer(pooling_type="avg"), prev)
        gb.add_layer("bottleneck", L.DenseLayer(
            n_out=self.num_classes, activation="identity"), "gap")
        gb.add_layer("out", L.LossLayer(loss="mcxent",
                                        activation="softmax"), "bottleneck")
        conf = (gb.set_outputs("out")
                .set_input_types(InputType.convolutional(
                    self.image_size, self.image_size, 3))
                .build())
        return ComputationGraph(conf).init()


class FaceNetNN4Small2(ZooModel):
    """reference zoo.model.FaceNetNN4Small2: the OpenFace nn4.small2
    inception variant — stem convs + inception modules (1x1/3x3/5x5 +
    pooling-projection branches merged channel-wise), a 128-d embedding
    bottleneck, L2 normalization, and a center-loss softmax head
    (reference: FaceNetHelper.appendGraph + CenterLossOutputLayer)."""

    def __init__(self, num_classes: int = 100, embedding_size: int = 128,
                 seed: int = 123, image_size: int = 96):
        self.num_classes = num_classes
        self.embedding_size = embedding_size
        self.seed = seed
        self.image_size = image_size

    def init(self) -> ComputationGraph:
        from ..nn.conf.layers_ext import CenterLossOutputLayer
        from ..nn.graph import L2NormalizeVertex

        gb = (ComputationGraphConfiguration
              .graph_builder(NeuralNetConfiguration.builder()
                             .seed(self.seed).updater(Adam(1e-3))
                             .activation("relu").weight_init("relu"))
              .add_inputs("input"))
        n = [0]

        def conv_bn(inp, ch, k, stride=1, pad=None):
            i = n[0]
            n[0] += 1
            pad = pad if pad is not None else k // 2
            gb.add_layer(f"c{i}", L.ConvolutionLayer(
                n_out=ch, kernel_size=(k, k), stride=(stride, stride),
                padding=(pad, pad), has_bias=False,
                activation="identity"), inp)
            gb.add_layer(f"b{i}", L.BatchNormalization(activation="relu"),
                         f"c{i}")
            return f"b{i}"

        def inception(name, inp, b1x1, b3r, b3, b5r, b5, pool_proj):
            """Four branches: 1x1 | 1x1→3x3 | 1x1→5x5 | pool→1x1;
            a zero channel count drops that branch (nn4.small2 trims
            branches in the later modules)."""
            outs = []
            if b1x1:
                outs.append(conv_bn(inp, b1x1, 1))
            if b3:
                r = conv_bn(inp, b3r, 1)
                outs.append(conv_bn(r, b3, 3))
            if b5:
                r = conv_bn(inp, b5r, 1)
                outs.append(conv_bn(r, b5, 5))
            if pool_proj:
                gb.add_layer(f"{name}_pool", L.SubsamplingLayer(
                    kernel_size=(3, 3), stride=(1, 1), padding=(1, 1)), inp)
                outs.append(conv_bn(f"{name}_pool", pool_proj, 1))
            gb.add_vertex(f"{name}_cat", MergeVertex(), *outs)
            return f"{name}_cat"

        # stem (96 -> 24 -> 12)
        prev = conv_bn("input", 64, 7, 2, 3)
        gb.add_layer("stem_pool", L.SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), padding=(1, 1)), prev)
        prev = conv_bn("stem_pool", 64, 1)
        prev = conv_bn(prev, 192, 3)
        gb.add_layer("stem_pool2", L.SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), padding=(1, 1)), prev)
        prev = "stem_pool2"
        # inception stack (nn4.small2 module shapes)
        prev = inception("i3a", prev, 64, 96, 128, 16, 32, 32)
        prev = inception("i3b", prev, 64, 96, 128, 32, 64, 64)
        gb.add_layer("pool3", L.SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), padding=(1, 1)), prev)
        prev = inception("i4a", "pool3", 256, 96, 192, 32, 64, 128)
        prev = inception("i4e", prev, 0, 160, 256, 64, 128, 0)
        gb.add_layer("pool4", L.SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), padding=(1, 1)), prev)
        prev = inception("i5a", "pool4", 256, 96, 384, 0, 0, 96)
        prev = inception("i5b", prev, 256, 96, 384, 0, 0, 96)
        gb.add_layer("gap", L.GlobalPoolingLayer(pooling_type="avg"), prev)
        gb.add_layer("bottleneck", L.DenseLayer(
            n_out=self.embedding_size, activation="identity"), "gap")
        gb.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
        gb.add_layer("lossLayer", CenterLossOutputLayer(
            n_out=self.num_classes, loss="mcxent", activation="softmax",
            alpha=0.1, lambda_=3e-4), "embeddings")
        conf = (gb.set_outputs("lossLayer")
                .set_input_types(InputType.convolutional(
                    self.image_size, self.image_size, 3))
                .build())
        return ComputationGraph(conf).init()


class NASNet(ZooModel):
    """reference zoo.model.NASNet (NASNet-A mobile): stem conv + stacks of
    NASNet-A normal cells with reduction cells between stacks. Cells follow
    the published NASNet-A block structure — five branch pairs of
    {separable 3x3/5x5, avg/max pool, identity} combined by adds and
    concatenated — with 1x1 "adjust" projections aligning the previous
    cell's channels (the reference's adjustBlock)."""

    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 image_size: int = 96, penultimate_filters: int = 192,
                 cells_per_stack: int = 2):
        self.num_classes = num_classes
        self.seed = seed
        self.image_size = image_size
        self.filters = penultimate_filters // 24 * 4   # base cell width
        self.cells_per_stack = cells_per_stack

    def init(self) -> ComputationGraph:
        gb = (ComputationGraphConfiguration
              .graph_builder(NeuralNetConfiguration.builder()
                             .seed(self.seed).updater(Adam(1e-3))
                             .activation("relu").weight_init("relu"))
              .add_inputs("input"))
        n = [0]

        def uid(tag):
            n[0] += 1
            return f"{tag}{n[0]}"

        def adjust(inp, ch, stride=1):
            """1x1 projection + BN to ch channels (reference adjustBlock)."""
            c = uid("adj")
            gb.add_layer(c, L.ConvolutionLayer(
                n_out=ch, kernel_size=(1, 1), stride=(stride, stride),
                has_bias=False, activation="identity"), inp)
            b = uid("adjbn")
            gb.add_layer(b, L.BatchNormalization(activation="identity"), c)
            return b

        def sep(inp, ch, k, stride=1):
            s = uid("sep")
            gb.add_layer(s, L.SeparableConvolution2D(
                n_out=ch, kernel_size=(k, k), stride=(stride, stride),
                convolution_mode="same", has_bias=False,
                activation="identity"), inp)
            b = uid("sepbn")
            gb.add_layer(b, L.BatchNormalization(activation="relu"), s)
            return b

        def avgp(inp, stride=1):
            p = uid("avg")
            gb.add_layer(p, L.SubsamplingLayer(
                kernel_size=(3, 3), stride=(stride, stride), padding=(1, 1),
                pooling_type="avg"), inp)
            return p

        def maxp(inp, stride=1):
            p = uid("max")
            gb.add_layer(p, L.SubsamplingLayer(
                kernel_size=(3, 3), stride=(stride, stride),
                padding=(1, 1)), inp)
            return p

        def add(a, b):
            v = uid("addv")
            gb.add_vertex(v, ElementWiseVertex("add"), a, b)
            return v

        def normal_cell(prev, cur, ch, prev_stride=1):
            """NASNet-A normal cell over (h_{i-1}, h_i); ``prev_stride=2``
            is the adjustBlock's spatial alignment right after a
            reduction cell."""
            p = adjust(prev, ch, prev_stride)
            h = adjust(cur, ch)
            b1 = add(sep(h, ch, 5), sep(p, ch, 3))
            b2 = add(sep(p, ch, 5), sep(p, ch, 3))
            b3 = add(avgp(h), p)
            b4 = add(avgp(p), avgp(p))
            b5 = add(sep(h, ch, 3), h)
            cat = uid("ncat")
            gb.add_vertex(cat, MergeVertex(), b1, b2, b3, b4, b5)
            return cat

        def reduction_cell(prev, cur, ch):
            """NASNet-A reduction cell (stride-2 branches)."""
            p = adjust(prev, ch)
            h = adjust(cur, ch)
            b1 = add(sep(h, ch, 5, 2), sep(p, ch, 7, 2))
            b2 = add(maxp(h, 2), sep(p, ch, 7, 2))
            b3 = add(avgp(h, 2), sep(p, ch, 5, 2))
            b4 = add(maxp(h, 2), sep(b1, ch, 3))
            b5 = add(avgp(b1), b2)
            cat = uid("rcat")
            gb.add_vertex(cat, MergeVertex(), b2, b3, b4, b5)
            return cat

        ch = self.filters
        stem = uid("stem")
        gb.add_layer(stem, L.ConvolutionLayer(
            n_out=ch, kernel_size=(3, 3), stride=(2, 2), padding=(1, 1),
            has_bias=False, activation="identity"), "input")
        stem_bn = uid("stembn")
        gb.add_layer(stem_bn, L.BatchNormalization(activation="identity"),
                     stem)
        prev_cell, cur = stem_bn, stem_bn
        after_reduction = False
        for stack in range(3):
            for _ in range(self.cells_per_stack):
                nxt = normal_cell(prev_cell, cur, ch,
                                  prev_stride=2 if after_reduction else 1)
                after_reduction = False
                prev_cell, cur = cur, nxt
            if stack < 2:
                nxt = reduction_cell(prev_cell, cur, ch * 2)
                prev_cell, cur = cur, nxt
                ch *= 2
                after_reduction = True
        act = uid("relu")
        gb.add_layer(act, L.ActivationLayer(activation="relu"), cur)
        gb.add_layer("gap", L.GlobalPoolingLayer(pooling_type="avg"), act)
        gb.add_layer("out", L.OutputLayer(n_out=self.num_classes,
                                          loss="mcxent",
                                          activation="softmax"), "gap")
        conf = (gb.set_outputs("out")
                .set_input_types(InputType.convolutional(
                    self.image_size, self.image_size, 3))
                .build())
        return ComputationGraph(conf).init()
