from .zoo import (AlexNet, Darknet19, LeNet, ResNet50, SimpleCNN, SqueezeNet,
                  TextGenerationLSTM, UNet, VGG16, VGG19, ZooModel)
