from .zoo import (AlexNet, Darknet19, FaceNetNN4Small2, InceptionResNetV1,
                  LeNet, NASNet, ResNet50, SimpleCNN, SqueezeNet,
                  TextGenerationLSTM, TinyYOLO, UNet, VGG16, VGG19, Xception,
                  YOLO2, ZooModel, PretrainedType)
