from .zoo import (AlexNet, Darknet19, InceptionResNetV1, LeNet, ResNet50,
                  SimpleCNN, SqueezeNet, TextGenerationLSTM, TinyYOLO, UNet,
                  VGG16, VGG19, Xception, YOLO2, ZooModel)
