"""deeplearning4j_tpu — a TPU-native deep learning framework.

A from-scratch rebuild of the capability surface of deeplearning4j
(reference: qdh0520/deeplearning4j, a fork of eclipse/deeplearning4j) designed
TPU-first: whole-graph XLA compilation instead of per-op JNI dispatch, SPMD
sharding over a jax device mesh instead of trainer-thread topologies, and a
functional jax core under a familiar stateful API shell.

Layer map (≈ SURVEY.md §1):
  ndarray/    INDArray + Nd4j factory analog           (ref: nd4j-api linalg)
  ops/        op registry + coverage ledger            (ref: libnd4j declarable ops)
  autodiff/   SameDiff analog — symbolic DAG → one jitted XLA module
  nn/         layer configs, MultiLayerNetwork, ComputationGraph (ref: dl4j-nn)
  data/       datasets, iterators, readers, normalizers (ref: datavec, dl4j-data)
  parallel/   SPMD mesh wrapper, ParallelWrapper analog (ref: dl4j-scaleout)
  models/     model zoo                                 (ref: dl4j-zoo)
  imports/    TF frozen-GraphDef → SameDiff, Keras h5   (ref: dl4j-modelimport,
              → MultiLayerNetwork                        samediff-import)
  eval/       Evaluation / ROC / RegressionEvaluation   (ref: nd4j evaluation)
  optimize/   training listeners, early stopping        (ref: dl4j optimize,
                                                         dl4j earlystopping)
  nlp/        Word2Vec / ParagraphVectors / vocab / serde (ref: dl4j-nlp)
  rl/         DQN / replay / policies / MDP envs        (ref: rl4j)
  ui/         StatsListener -> TensorBoard events       (ref: dl4j-ui)
  native/     C++ host-ETL hot loops via ctypes         (ref: libnd4j CPU helpers)
"""

import jax as _jax

# The dtype zoo advertises DOUBLE/INT64/UINT64 as first-class (reference
# DataType set); without x64 jax silently downcasts them to 32-bit. Enable it
# process-wide at import. Defaults stay 32-bit — wide types are used only when
# requested (on TPU, f64 is slow/emulated; the reference's fp64 paths are
# gradient checks, which run on CPU).
_jax.config.update("jax_enable_x64", True)

from .common.dtypes import DataType
from .common.environment import Environment
from .ndarray.ndarray import NDArray
from .ndarray import factory
from .ndarray.rng import get_random, set_default_seed

__version__ = "0.1.0"

__all__ = [
    "DataType",
    "Environment",
    "NDArray",
    "factory",
    "get_random",
    "set_default_seed",
]
