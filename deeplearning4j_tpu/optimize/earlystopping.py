"""Early stopping: termination conditions, savers, trainer.

Reference: deeplearning4j-core ``org.deeplearning4j.earlystopping.*`` —
``EarlyStoppingConfiguration`` (epoch + iteration termination conditions,
score calculator, model saver, evaluate-every-N), ``EarlyStoppingTrainer``,
``EarlyStoppingResult`` (SURVEY.md §2.3; round-1 VERDICT missing #4).

Host-side control loop — it decides WHEN to stop/save; every training step
remains the network's own compiled module.
"""

from __future__ import annotations

import io
import time
from pathlib import Path
from typing import Any, List, Optional, Sequence


# --- termination conditions (reference: termination/*.java) --------------

class EpochTerminationCondition:
    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch, score):
        return epoch >= self.max_epochs

    def __str__(self):
        return f"MaxEpochs({self.max_epochs})"


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after ``patience`` epochs without (min_improvement) progress."""

    def __init__(self, patience: int, min_improvement: float = 0.0):
        self.patience = patience
        self.min_improvement = min_improvement
        self._best: Optional[float] = None
        self._best_epoch = -1

    def terminate(self, epoch, score):
        if self._best is None or score < self._best - self.min_improvement:
            self._best = score
            self._best_epoch = epoch
            return False
        return (epoch - self._best_epoch) >= self.patience

    def __str__(self):
        return f"ScoreImprovement(patience={self.patience})"


class IterationTerminationCondition:
    def terminate(self, score: float) -> bool:
        raise NotImplementedError


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Abort immediately when the score explodes past a bound."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, score):
        return score > self.max_score or score != score  # NaN aborts too

    def __str__(self):
        return f"MaxScore({self.max_score})"


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start: Optional[float] = None   # clock starts at first check

    def terminate(self, score):
        if self._start is None:
            self._start = time.monotonic()
        return (time.monotonic() - self._start) >= self.max_seconds

    def __str__(self):
        return f"MaxTime({self.max_seconds}s)"


# --- score calculators (reference: scorecalc/*.java) ---------------------

class ScoreCalculator:
    def calculate_score(self, model) -> float:
        raise NotImplementedError


class DataSetLossCalculator(ScoreCalculator):
    """Average loss over a held-out iterator (reference:
    DataSetLossCalculator, average=true)."""

    def __init__(self, iterator):
        self.iterator = iterator

    def calculate_score(self, model):
        total, n = 0.0, 0
        self.iterator.reset()
        for ds in self.iterator:
            total += model.score(ds) * ds.num_examples()
            n += ds.num_examples()
        return total / max(n, 1)


# --- model savers (reference: saver/*.java) ------------------------------

class InMemoryModelSaver:
    def __init__(self) -> None:
        self._best = None
        self._latest = None

    def save_best_model(self, model, score: float) -> None:
        self._best = model.clone()

    def save_latest_model(self, model, score: float) -> None:
        self._latest = model.clone()

    def get_best_model(self):
        return self._best

    def get_latest_model(self):
        return self._latest


class LocalFileModelSaver:
    """Best/latest model zips under a directory (reference:
    LocalFileModelSaver bestModel.bin/latestModel.bin)."""

    def __init__(self, directory):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)

    def _best_path(self):
        return str(self.dir / "bestModel.zip")

    def save_best_model(self, model, score: float) -> None:
        model.save(self._best_path(), save_updater=True)

    def save_latest_model(self, model, score: float) -> None:
        model.save(str(self.dir / "latestModel.zip"), save_updater=True)

    def get_best_model(self):
        from ..nn.multilayer import MultiLayerNetwork

        return MultiLayerNetwork.load(self._best_path(), load_updater=True)

    def get_latest_model(self):
        from ..nn.multilayer import MultiLayerNetwork

        return MultiLayerNetwork.load(str(self.dir / "latestModel.zip"),
                                      load_updater=True)


# --- configuration + trainer ---------------------------------------------

class EarlyStoppingConfiguration:
    class Builder:
        def __init__(self) -> None:
            self._epoch_conds: List[EpochTerminationCondition] = []
            self._iter_conds: List[IterationTerminationCondition] = []
            self._calc: Optional[ScoreCalculator] = None
            self._saver = InMemoryModelSaver()
            self._every_n = 1
            self._save_last = False

        def epoch_termination_conditions(self, *conds):
            self._epoch_conds = list(conds)
            return self

        def iteration_termination_conditions(self, *conds):
            self._iter_conds = list(conds)
            return self

        def score_calculator(self, calc: ScoreCalculator):
            self._calc = calc
            return self

        def model_saver(self, saver):
            self._saver = saver
            return self

        def evaluate_every_n_epochs(self, n: int):
            self._every_n = n
            return self

        def save_last_model(self, flag: bool = True):
            self._save_last = flag
            return self

        def build(self) -> "EarlyStoppingConfiguration":
            if not self._epoch_conds and not self._iter_conds:
                raise ValueError("need at least one termination condition")
            return EarlyStoppingConfiguration(self)

    @staticmethod
    def builder() -> "EarlyStoppingConfiguration.Builder":
        return EarlyStoppingConfiguration.Builder()

    def __init__(self, b: "EarlyStoppingConfiguration.Builder"):
        self.epoch_conditions = b._epoch_conds
        self.iteration_conditions = b._iter_conds
        self.score_calculator = b._calc
        self.saver = b._saver
        self.evaluate_every_n = b._every_n
        self.save_last = b._save_last


class EarlyStoppingResult:
    class TerminationReason:
        EpochTerminationCondition = "EpochTerminationCondition"
        IterationTerminationCondition = "IterationTerminationCondition"
        Error = "Error"

    def __init__(self, reason: str, details: str, total_epochs: int,
                 best_epoch: int, best_score: float, saver):
        self.termination_reason = reason
        self.termination_details = details
        self.total_epochs = total_epochs
        self.best_model_epoch = best_epoch
        self.best_model_score = best_score
        self._saver = saver

    def get_best_model(self):
        return self._saver.get_best_model()

    def __repr__(self):
        return (f"EarlyStoppingResult(reason={self.termination_reason}, "
                f"details={self.termination_details!r}, "
                f"epochs={self.total_epochs}, "
                f"best_epoch={self.best_model_epoch}, "
                f"best_score={self.best_model_score:.6f})")


class EarlyStoppingTrainer:
    """Reference: EarlyStoppingTrainer over a MultiLayerNetwork (the
    ComputationGraph twin works identically — any model with
    fit/score/clone)."""

    def __init__(self, config: EarlyStoppingConfiguration, model,
                 train_iterator):
        self.config = config
        self.model = model
        self.train_iterator = train_iterator

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        best_score: Optional[float] = None
        best_epoch = -1
        epoch = 0
        while True:
            # one training epoch, iteration conditions checked per batch
            self.train_iterator.reset()
            for ds in self.train_iterator:
                self.model.fit(ds, epochs=1)
                if not cfg.iteration_conditions:
                    continue   # no per-batch device sync unless needed
                score = float(self.model.score_value)
                for cond in cfg.iteration_conditions:
                    if cond.terminate(score):
                        if best_score is None:
                            cfg.saver.save_best_model(self.model, score)
                            best_score, best_epoch = score, epoch
                        return EarlyStoppingResult(
                            EarlyStoppingResult.TerminationReason
                            .IterationTerminationCondition,
                            str(cond), epoch + 1, best_epoch, best_score,
                            cfg.saver)
            epoch += 1
            if cfg.save_last:
                cfg.saver.save_latest_model(self.model,
                                            float(self.model.score_value))
            if epoch % cfg.evaluate_every_n == 0:
                score = (cfg.score_calculator.calculate_score(self.model)
                         if cfg.score_calculator is not None
                         else float(self.model.score_value))
                if best_score is None or score < best_score:
                    best_score, best_epoch = score, epoch - 1
                    cfg.saver.save_best_model(self.model, score)
                for cond in cfg.epoch_conditions:
                    if cond.terminate(epoch, score):
                        return EarlyStoppingResult(
                            EarlyStoppingResult.TerminationReason
                            .EpochTerminationCondition,
                            str(cond), epoch, best_epoch, best_score,
                            cfg.saver)
