from .listeners import (TrainingListener, ScoreIterationListener, PerformanceListener,
                        EvaluativeListener, CheckpointListener, TimeIterationListener,
                        CollectScoresIterationListener, PipelineMetricsListener)
from .telemetry import (TelemetryConfig, TelemetrySink, NanSentinelListener)
from .earlystopping import (EarlyStoppingConfiguration, EarlyStoppingResult,
                            EarlyStoppingTrainer, MaxEpochsTerminationCondition,
                            ScoreImprovementEpochTerminationCondition,
                            MaxScoreIterationTerminationCondition,
                            MaxTimeIterationTerminationCondition,
                            DataSetLossCalculator, InMemoryModelSaver,
                            LocalFileModelSaver)
