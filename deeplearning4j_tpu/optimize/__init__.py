from .listeners import (TrainingListener, ScoreIterationListener, PerformanceListener,
                        EvaluativeListener, CheckpointListener, TimeIterationListener,
                        CollectScoresIterationListener)
