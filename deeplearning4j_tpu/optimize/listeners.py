"""Training listeners.

Reference: dl4j-nn ``org.deeplearning4j.optimize.listeners.{
ScoreIterationListener, PerformanceListener, EvaluativeListener,
CheckpointListener, TimeIterationListener, CollectScoresIterationListener}``
(SURVEY.md §2.3). The listener SPI is THE metrics bus (§5.5).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, List, Optional

logger = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    """SPI note: ``score`` arrives as a DEVICE scalar (jax array), not a
    Python float — converting it (``float(score)``) forces a device sync, so
    listeners must only do that at their own print/collect boundaries. This
    keeps the hot loop fully async (reference: the listener bus must not tax
    the hot loop, SURVEY.md §5.5)."""

    def iteration_done(self, model, iteration: int, score) -> None:
        pass

    def epoch_done(self, model, epoch: int) -> None:
        pass


class ScoreIterationListener(TrainingListener):
    def __init__(self, print_iterations: int = 10):
        self.print_iterations = max(1, print_iterations)

    def iteration_done(self, model, iteration, score):
        # float(score) syncs the device — only pay for messages actually emitted
        if (iteration % self.print_iterations == 0
                and logger.isEnabledFor(logging.INFO)):
            logger.info("Score at iteration %d is %s", iteration, float(score))


class CollectScoresIterationListener(TrainingListener):
    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration, score):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(score)))

    # checkpoint/resume protocol (util.checkpoint): a resumed run's score
    # history continues the killed run's instead of restarting empty
    def state_dict(self) -> dict:
        return {"scores": [[i, s] for i, s in self.scores]}

    def load_state_dict(self, state: dict) -> None:
        self.scores = [(int(i), float(s)) for i, s in state.get("scores", [])]


class PerformanceListener(TrainingListener):
    """Samples/sec + iteration latency (reference PerformanceListener).

    Beyond the reference's log line, each sample is PUBLISHED: through an
    attached :class:`StatsStorage` (``storage=``) as the scalars
    ``iterations_per_sec`` / ``iteration_ms`` / ``samples_per_sec`` — so
    throughput charts on the dashboard beside loss — and as a
    ``perf/rate`` flight-recorder event on the shared timeline.
    Samples/sec uses the batch size the fit loop bound last
    (``model._last_batch_size``); absent that, only the iteration-based
    figures are reported."""

    def __init__(self, frequency: int = 10, report_batch: bool = True,
                 storage=None, session_id: str = "performance"):
        self.frequency = max(1, frequency)
        self.report_batch = report_batch
        self.storage = storage
        self.session_id = session_id
        self._last_time = None
        self._last_iter = None
        self.last_iterations_per_sec = 0.0
        self.last_iteration_ms = 0.0
        self.last_samples_per_sec = 0.0

    def iteration_done(self, model, iteration, score):
        now = time.time()
        if self._last_time is not None and iteration % self.frequency == 0:
            dt = now - self._last_time
            iters = iteration - self._last_iter
            if dt > 0 and iters > 0:
                ips = iters / dt
                self.last_iterations_per_sec = ips
                self.last_iteration_ms = dt / iters * 1e3
                batch = getattr(model, "_last_batch_size", None)
                if batch:
                    self.last_samples_per_sec = ips * batch
                if logger.isEnabledFor(logging.INFO):
                    logger.info("iteration %d: %.1f iter/s, score=%s",
                                iteration, ips, float(score))
                if self.storage is not None:
                    self.storage.put_scalar(self.session_id,
                                            "iterations_per_sec",
                                            iteration, ips)
                    self.storage.put_scalar(self.session_id,
                                            "iteration_ms", iteration,
                                            self.last_iteration_ms)
                    if batch:
                        self.storage.put_scalar(self.session_id,
                                                "samples_per_sec",
                                                iteration,
                                                self.last_samples_per_sec)
                from ..common import flightrec

                flightrec.event(
                    "perf/rate", iteration=iteration,
                    iterations_per_sec=round(ips, 3),
                    iteration_ms=round(self.last_iteration_ms, 3),
                    **({"samples_per_sec":
                        round(self.last_samples_per_sec, 1)}
                       if batch else {}))
            self._last_time = now
            self._last_iter = iteration
        elif self._last_time is None:
            self._last_time = now
            self._last_iter = iteration


class TimeIterationListener(TrainingListener):
    """ETA logging over an expected iteration count."""

    def __init__(self, expected_iterations: int, frequency: int = 50):
        self.expected = expected_iterations
        self.frequency = max(1, frequency)
        self.start = time.time()

    def iteration_done(self, model, iteration, score):
        if iteration % self.frequency == 0 and iteration > 0:
            elapsed = time.time() - self.start
            remaining = elapsed / iteration * (self.expected - iteration)
            logger.info("iteration %d/%d, ETA %.0fs", iteration, self.expected,
                        max(0.0, remaining))


class EvaluativeListener(TrainingListener):
    """Periodic holdout evaluation (reference EvaluativeListener)."""

    def __init__(self, data, frequency: int = 100, metric: str = "accuracy"):
        self.data = data
        self.frequency = max(1, frequency)
        self.metric = metric
        self.history: List[tuple] = []

    def iteration_done(self, model, iteration, score):
        if iteration % self.frequency == 0:
            try:
                ev = model.evaluate(self.data)
            except Exception:
                # a bad holdout batch (shape drift, corrupt record, OOM on
                # the eval path) must not kill a long training run — log
                # and resume; the next boundary retries
                logger.warning("EvaluativeListener: evaluation failed at "
                               "iteration %d; skipping this boundary",
                               iteration, exc_info=True)
                return
            # a misconfigured metric NAME is a config error, not a bad
            # batch — resolve it unguarded so the typo fails fast
            metric_fn = getattr(ev, self.metric)
            try:
                value = metric_fn()
            except Exception:
                logger.warning("EvaluativeListener: %s computation failed "
                               "at iteration %d; skipping this boundary",
                               self.metric, iteration, exc_info=True)
                return
            self.history.append((iteration, value))
            logger.info("eval at iteration %d: %s=%.4f", iteration, self.metric, value)


class PipelineMetricsListener(TrainingListener):
    """Surfaces the input/dispatch pipeline's observability through the
    listener bus (the metrics bus, SURVEY §5.5): per-epoch snapshots of the
    OpProfiler ``trace/*`` compile/retrace counters, the pipeline padding
    counters, and the transfer-vs-compute overlap ledger
    (``pipeline/next_batch`` host-wait vs ``pipeline/dispatch`` time).

    The headline assertion it enables: ``trace_count("mln_fit_step") == 1``
    after an epoch whose final batch was partial — shape-stable batching
    compiled the step exactly once per fit config."""

    def __init__(self, frequency_epochs: int = 1):
        self.frequency = max(1, frequency_epochs)
        self.snapshots: List[dict] = []

    def _profiler(self):
        from ..common.profiler import OpProfiler

        return OpProfiler.get()

    def epoch_done(self, model, epoch: int) -> None:
        if epoch % self.frequency:
            return
        prof = self._profiler()
        self.snapshots.append({
            "epoch": epoch,
            "traces": prof.trace_counts(),
            "counters": {k: v for k, v in prof.get_counters().items()
                         if k.startswith("pipeline/")},
            "overlap": prof.overlap_stats(),
            "telemetry": prof.telemetry_stats(),
        })

    def trace_count(self, step_name: str) -> int:
        """Current (re)trace count for a step, e.g. ``mln_fit_step``,
        ``graph_fit_step``, ``pw_fit_step`` or their ``*_chunk`` twins."""
        return self._profiler().counter_value(f"trace/{step_name}")

    def overlap_stats(self) -> dict:
        return self._profiler().overlap_stats()


class CheckpointListener(TrainingListener):
    """Rolling checkpoints every N iterations/epochs (reference
    CheckpointListener with keepLast retention + checkpoint.json index),
    rebuilt on the util.checkpoint atomic/async machinery:

    - ``_save`` snapshots device state in ONE batched readback on the
      training thread, then (``async_write=True``, the default) hands the
      host snapshot to a background writer — serialization, fsync, and the
      atomic tmp→rename commit never block the hot loop. Durability points
      are explicit: :meth:`flush`, :meth:`close`, or reading ``saved``; a
      kill can only lose the (bounded) writes still in flight, and resume
      falls back to the last committed checkpoint.
    - The ``checkpoint.json`` manifest carries a sha256 per committed
      file; :meth:`last_checkpoint` verifies and falls back to the newest
      intact checkpoint, so a torn or bit-flipped write is skipped, never
      resumed from.
    - Construction rebuilds the retention state from the directory (a
      relaunched process keeps rotating the SAME checkpoint set instead of
      forgetting it) and clears stale ``*.tmp`` wreckage.
    - Under ``steps_per_dispatch`` chunking, a save due mid-chunk is
      deferred to the dispatch boundary (the holder's params correspond to
      the chunk's last step only) — the tag records the iteration actually
      snapshotted.

    Models that don't expose the ``_params``/``conf`` internals (SameDiff)
    keep the legacy path: ``model.save`` (itself atomic now), committed
    into the same verified manifest, synchronously.
    """

    def __init__(self, directory: str, save_every_n_iterations: Optional[int] = None,
                 save_every_n_epochs: Optional[int] = None, keep_last: int = 3,
                 async_write: bool = True,
                 max_total_bytes: Optional[int] = None,
                 incarnation: Optional[int] = None):
        from ..util import checkpoint as _ckpt

        self.dir = directory
        self.every_iter = save_every_n_iterations
        self.every_epoch = save_every_n_epochs
        self.keep_last = keep_last
        self.async_write = async_write
        # disk-budget retention on top of keep_last: oldest committed
        # checkpoints GC until the total fits (the newest always
        # survives) — long supervised runs can't fill the disk
        self.max_total_bytes = max_total_bytes
        # supervised-restart fence: commits from an older incarnation are
        # refused at the manifest (util.checkpoint.StaleIncarnationError)
        self.incarnation = incarnation
        os.makedirs(directory, exist_ok=True)
        _ckpt.clean_stale_tmp(directory)
        # survive a process restart: retention + last_checkpoint continue
        # from what is actually on disk, not an empty in-memory list
        self._saved: List[str] = _ckpt.committed_checkpoints(directory)
        self._writer = None
        self._group: Optional[List[Any]] = None
        self._pending_tag: Optional[str] = None
        self._seq = len(self._saved)
        # guards the writer handle and the committed-paths mirror: the
        # background writer's on_commit callback mutates _saved from the
        # writer thread while the training thread reads/saves
        self._lock = threading.Lock()

    @property
    def saved(self) -> List[str]:
        """Committed checkpoint paths (oldest first). Reading it is a
        durability point: pending async writes are flushed first, so the
        list never under-reports what a crash right now would keep."""
        self.flush()
        return self._saved

    # --- wiring ---------------------------------------------------------
    def bind_group(self, listeners: List[Any]) -> None:
        """set_listeners hands the full listener list over so snapshots
        can capture peer listeners' ``state_dict`` for exact resume."""
        # graftlint: disable=lock-discipline -- wiring step: set_listeners
        # runs on the training thread before any fit/writer activity
        self._group = list(listeners)

    def _note_commit(self, path: str) -> None:
        # mirror the retention the commit just applied, WITHOUT re-reading
        # the manifest from disk on every commit (the writer thread calls
        # this once per checkpoint; sync commits call it from the
        # training thread — hence the lock)
        with self._lock:
            saved = [p for p in self._saved if p != path] + [path]
            if self.keep_last and len(saved) > self.keep_last:
                saved = saved[-self.keep_last:]
            if self.max_total_bytes:
                # the byte-budget GC already unlinked its victims — one
                # stat per survivor keeps the mirror honest without a
                # manifest read
                saved = [p for p in saved if os.path.exists(p)]
            self._saved = saved

    def _get_writer(self):
        from ..util import checkpoint as _ckpt

        with self._lock:
            if self._writer is None:
                self._writer = _ckpt.CheckpointWriter(
                    self.dir, self.keep_last, on_commit=self._note_commit,
                    max_total_bytes=self.max_total_bytes,
                    incarnation=self.incarnation)
            return self._writer

    # --- saving ---------------------------------------------------------
    def _save(self, model, tag: str, sync: bool = False) -> Optional[str]:
        from ..util import checkpoint as _ckpt

        if hasattr(model, "_params") and hasattr(model, "conf"):
            snapshot = _ckpt.snapshot_training_state(model,
                                                     listeners=self._group)
            if self.async_write and not sync:
                self._get_writer().submit(snapshot, tag)
                return None
            return self._commit_snapshot(snapshot, tag)
        # legacy self-serializing models (SameDiff): synchronous, but
        # still atomic + manifested + retained
        path = os.path.join(self.dir, f"checkpoint_{tag}.zip")
        model.save(path, save_updater=True)
        _ckpt.register_committed(self.dir, path,
                                 int(getattr(model, "_iteration", 0)),
                                 self.keep_last,
                                 max_total_bytes=self.max_total_bytes,
                                 incarnation=self.incarnation)
        self._note_commit(path)
        return path

    def _commit_snapshot(self, snapshot: dict, tag: str) -> str:
        from ..util import checkpoint as _ckpt

        data = _ckpt.serialize_snapshot(snapshot)
        path = _ckpt.commit_checkpoint(self.dir, tag, data,
                                       snapshot["iteration"],
                                       self.keep_last, seq=self._seq,
                                       max_total_bytes=self.max_total_bytes,
                                       incarnation=self.incarnation,
                                       state_dtype=snapshot.get("state_dtype"))
        # graftlint: disable=lock-discipline -- training-thread-only: sync
        # commits never overlap the async writer (save_now flushes first)
        self._seq += 1
        self._note_commit(path)
        return path

    def save_now(self, model, tag: str,
                 rng_state: Optional[dict] = None) -> str:
        """Flush-quality checkpoint: snapshot NOW on the calling thread,
        commit synchronously (atomic + manifested + retained), and drain
        any in-flight async writes first so this commit is the NEWEST.
        The preemption-signal path (TrainingSupervisor) and the
        supervisor's attempt-0 anchor come through here. ``rng_state``:
        see ``util.checkpoint.snapshot_training_state``."""
        from ..util import checkpoint as _ckpt

        self.flush()
        if hasattr(model, "_params") and hasattr(model, "conf"):
            snapshot = _ckpt.snapshot_training_state(
                model, listeners=self._group, rng_state=rng_state)
            return self._commit_snapshot(snapshot, tag)
        path = self._save(model, tag, sync=True)
        assert path is not None
        return path

    def iteration_done(self, model, iteration, score):
        if self.every_iter and iteration % self.every_iter == 0:
            # graftlint: disable=lock-discipline -- listener-bus state:
            # iteration_done only ever runs on the training thread
            self._pending_tag = f"iter_{iteration}"
        if self._pending_tag is not None and \
                getattr(model, "_at_dispatch_boundary", True):
            # under chunked dispatch the holder's params are only
            # consistent with the LAST step of the chunk — tag that one
            tag = (f"iter_{iteration}" if self._pending_tag.startswith("iter_")
                   else self._pending_tag)
            # graftlint: disable=lock-discipline -- same training-thread
            # ownership as the arm above
            self._pending_tag = None
            self._save(model, tag)

    def epoch_done(self, model, epoch):
        if self.every_epoch and epoch % self.every_epoch == 0:
            self._save(model, f"epoch_{epoch}")

    # --- durability -----------------------------------------------------
    def flush(self, timeout: Optional[float] = 60.0) -> None:
        """Block until every submitted checkpoint is committed (async
        path). The durability points are explicit — ``flush()``,
        ``close()``, or reading ``saved`` — NOT every epoch boundary, so
        the training loop never stalls on the writer; a kill can only
        lose the writes currently in flight, and resume falls back to the
        last committed checkpoint."""
        if self._writer is not None:
            self._writer.flush(timeout)

    def close(self) -> None:
        with self._lock:
            writer, self._writer = self._writer, None
        if writer is not None:
            writer.close()
            # graftlint: disable=lock-discipline -- written after
            # writer.close() joined the background thread; no concurrent
            # reader exists past that point
            self._closed_errors = list(writer.errors)

    def errors(self) -> List[BaseException]:
        """Write failures recorded by the async writer (a failed write
        never touches the manifest — it is observable here and in logs).
        Survives :meth:`close`."""
        if self._writer is not None:
            return list(self._writer.errors)
        return list(getattr(self, "_closed_errors", []))

    @staticmethod
    def last_checkpoint(directory: str) -> Optional[str]:
        """Newest checkpoint PROVEN intact (manifest checksum, with a
        directory-scan fallback) — see util.checkpoint.last_checkpoint."""
        from ..util import checkpoint as _ckpt

        return _ckpt.last_checkpoint(directory)
