"""Training listeners.

Reference: dl4j-nn ``org.deeplearning4j.optimize.listeners.{
ScoreIterationListener, PerformanceListener, EvaluativeListener,
CheckpointListener, TimeIterationListener, CollectScoresIterationListener}``
(SURVEY.md §2.3). The listener SPI is THE metrics bus (§5.5).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, List, Optional

logger = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    """SPI note: ``score`` arrives as a DEVICE scalar (jax array), not a
    Python float — converting it (``float(score)``) forces a device sync, so
    listeners must only do that at their own print/collect boundaries. This
    keeps the hot loop fully async (reference: the listener bus must not tax
    the hot loop, SURVEY.md §5.5)."""

    def iteration_done(self, model, iteration: int, score) -> None:
        pass

    def epoch_done(self, model, epoch: int) -> None:
        pass


class ScoreIterationListener(TrainingListener):
    def __init__(self, print_iterations: int = 10):
        self.print_iterations = max(1, print_iterations)

    def iteration_done(self, model, iteration, score):
        # float(score) syncs the device — only pay for messages actually emitted
        if (iteration % self.print_iterations == 0
                and logger.isEnabledFor(logging.INFO)):
            logger.info("Score at iteration %d is %s", iteration, float(score))


class CollectScoresIterationListener(TrainingListener):
    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration, score):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(score)))


class PerformanceListener(TrainingListener):
    """Samples/sec + iteration latency (reference PerformanceListener)."""

    def __init__(self, frequency: int = 10, report_batch: bool = True):
        self.frequency = max(1, frequency)
        self.report_batch = report_batch
        self._last_time = None
        self._last_iter = None
        self.last_iterations_per_sec = 0.0

    def iteration_done(self, model, iteration, score):
        now = time.time()
        if self._last_time is not None and iteration % self.frequency == 0:
            dt = now - self._last_time
            iters = iteration - self._last_iter
            if dt > 0:
                self.last_iterations_per_sec = iters / dt
                if logger.isEnabledFor(logging.INFO):
                    logger.info("iteration %d: %.1f iter/s, score=%s", iteration,
                                self.last_iterations_per_sec, float(score))
            self._last_time = now
            self._last_iter = iteration
        elif self._last_time is None:
            self._last_time = now
            self._last_iter = iteration


class TimeIterationListener(TrainingListener):
    """ETA logging over an expected iteration count."""

    def __init__(self, expected_iterations: int, frequency: int = 50):
        self.expected = expected_iterations
        self.frequency = max(1, frequency)
        self.start = time.time()

    def iteration_done(self, model, iteration, score):
        if iteration % self.frequency == 0 and iteration > 0:
            elapsed = time.time() - self.start
            remaining = elapsed / iteration * (self.expected - iteration)
            logger.info("iteration %d/%d, ETA %.0fs", iteration, self.expected,
                        max(0.0, remaining))


class EvaluativeListener(TrainingListener):
    """Periodic holdout evaluation (reference EvaluativeListener)."""

    def __init__(self, data, frequency: int = 100, metric: str = "accuracy"):
        self.data = data
        self.frequency = max(1, frequency)
        self.metric = metric
        self.history: List[tuple] = []

    def iteration_done(self, model, iteration, score):
        if iteration % self.frequency == 0:
            try:
                ev = model.evaluate(self.data)
            except Exception:
                # a bad holdout batch (shape drift, corrupt record, OOM on
                # the eval path) must not kill a long training run — log
                # and resume; the next boundary retries
                logger.warning("EvaluativeListener: evaluation failed at "
                               "iteration %d; skipping this boundary",
                               iteration, exc_info=True)
                return
            # a misconfigured metric NAME is a config error, not a bad
            # batch — resolve it unguarded so the typo fails fast
            metric_fn = getattr(ev, self.metric)
            try:
                value = metric_fn()
            except Exception:
                logger.warning("EvaluativeListener: %s computation failed "
                               "at iteration %d; skipping this boundary",
                               self.metric, iteration, exc_info=True)
                return
            self.history.append((iteration, value))
            logger.info("eval at iteration %d: %s=%.4f", iteration, self.metric, value)


class PipelineMetricsListener(TrainingListener):
    """Surfaces the input/dispatch pipeline's observability through the
    listener bus (the metrics bus, SURVEY §5.5): per-epoch snapshots of the
    OpProfiler ``trace/*`` compile/retrace counters, the pipeline padding
    counters, and the transfer-vs-compute overlap ledger
    (``pipeline/next_batch`` host-wait vs ``pipeline/dispatch`` time).

    The headline assertion it enables: ``trace_count("mln_fit_step") == 1``
    after an epoch whose final batch was partial — shape-stable batching
    compiled the step exactly once per fit config."""

    def __init__(self, frequency_epochs: int = 1):
        self.frequency = max(1, frequency_epochs)
        self.snapshots: List[dict] = []

    def _profiler(self):
        from ..common.profiler import OpProfiler

        return OpProfiler.get()

    def epoch_done(self, model, epoch: int) -> None:
        if epoch % self.frequency:
            return
        prof = self._profiler()
        self.snapshots.append({
            "epoch": epoch,
            "traces": prof.trace_counts(),
            "counters": {k: v for k, v in prof.get_counters().items()
                         if k.startswith("pipeline/")},
            "overlap": prof.overlap_stats(),
            "telemetry": prof.telemetry_stats(),
        })

    def trace_count(self, step_name: str) -> int:
        """Current (re)trace count for a step, e.g. ``mln_fit_step``,
        ``graph_fit_step``, ``pw_fit_step`` or their ``*_chunk`` twins."""
        return self._profiler().counter_value(f"trace/{step_name}")

    def overlap_stats(self) -> dict:
        return self._profiler().overlap_stats()


class CheckpointListener(TrainingListener):
    """Rolling checkpoints every N iterations/epochs (reference
    CheckpointListener with keepLast retention + checkpoint.json index)."""

    def __init__(self, directory: str, save_every_n_iterations: Optional[int] = None,
                 save_every_n_epochs: Optional[int] = None, keep_last: int = 3):
        self.dir = directory
        self.every_iter = save_every_n_iterations
        self.every_epoch = save_every_n_epochs
        self.keep_last = keep_last
        self.saved: List[str] = []
        os.makedirs(directory, exist_ok=True)

    def _save(self, model, tag: str) -> None:
        path = os.path.join(self.dir, f"checkpoint_{tag}.zip")
        model.save(path, save_updater=True)
        self.saved.append(path)
        while len(self.saved) > self.keep_last:
            old = self.saved.pop(0)
            if os.path.exists(old):
                os.remove(old)
        index = os.path.join(self.dir, "checkpoint.json")
        import json

        with open(index, "w") as f:
            json.dump({"checkpoints": self.saved}, f)

    def iteration_done(self, model, iteration, score):
        if self.every_iter and iteration % self.every_iter == 0:
            self._save(model, f"iter_{iteration}")

    def epoch_done(self, model, epoch):
        if self.every_epoch and epoch % self.every_epoch == 0:
            self._save(model, f"epoch_{epoch}")

    @staticmethod
    def last_checkpoint(directory: str) -> Optional[str]:
        import json

        index = os.path.join(directory, "checkpoint.json")
        if not os.path.exists(index):
            return None
        with open(index) as f:
            saved = json.load(f)["checkpoints"]
        return saved[-1] if saved else None
