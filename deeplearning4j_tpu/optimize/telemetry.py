"""In-graph training telemetry: the device half of the metrics bus.

Reference: deeplearning4j-ui ``StatsListener`` streams per-layer param/
gradient/update statistics and update:param ratios into ``StatsStorage``
(SURVEY §2.3/§5.5), and nd4j's ``OpProfiler`` NAN_PANIC halts on the first
non-finite op output. Both are host-side observers there — every statistic
costs a device→host sync and NAN_PANIC costs per-op checks.

The TPU shape inverts this: the statistics are computed INSIDE the jitted
train step (``layer_stats`` below), so XLA fuses them with the backward
pass — per-layer gradient norm, update norm, param norm, update:param
ratio, and a non-finite element count come out as a small auxiliary pytree
of device scalars/vectors alongside the loss. Enabling telemetry therefore
adds ZERO host syncs and ZERO extra compiles to the hot loop: the step is
(re)built once with the aux outputs and ``trace/<step>`` stays 1 per fit
config; under ``ParallelWrapper`` the counts are psum'd with the same
collectives as the weight update, and under ``steps_per_dispatch`` chunks
the aux is stacked through the ``lax.scan`` device loop.

Host side, two listeners drain the aux asynchronously:

- :class:`TelemetrySink` buffers the device pytrees and every
  ``drain_every_n`` iterations does ONE batched ``jax.device_get`` into a
  ``StatsStorage`` backend (in-memory / JSONL / TensorBoard) — the same
  three-line attach as ``StatsListener``.
- :class:`NanSentinelListener` is the graded NAN_PANIC analog: it inspects
  the non-finite counts within one drain window and, per policy, warns,
  skips the poisoned update (applied in-graph via :func:`apply_nan_guard`:
  the pre-step param/updater/state copies already live in the graph, so
  the update is dropped with a ``where`` — params stay finite and equal to
  the pre-NaN step), or raises with the offending layer named.

Attaching either listener through ``set_listeners`` enables telemetry
automatically (``wants_telemetry``); the networks rebuild their step once.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..common.profiler import OpProfiler
from .listeners import TrainingListener

logger = logging.getLogger("deeplearning4j_tpu")


@dataclass(frozen=True)
class TelemetryConfig:
    """Build-time switch captured by the train-step builders. ``nan_guard``
    additionally compiles the skip-update policy into the step (see
    :func:`apply_nan_guard`). ``member_cull`` is consumed only by the
    vmapped fleet step (parallel.fleet): a member whose update the nan
    guard skipped additionally has its alive-mask bit flipped in-graph —
    permanent isolation instead of a transient skip. Solo step builders
    ignore it (a ``"cull"`` sentinel on a solo model degrades to
    ``"skip"``).

    ``stats`` gates the per-layer norm/ratio aux (:func:`layer_stats`) —
    integrity-only listeners leave it off, so the aux carries just the
    loss plus the consistency verdict and the flat-backward path stays
    eligible (the A/B overhead of a fingerprint-only config measures the
    fingerprint, nothing else). ``nan_guard`` forces it back on: the
    skip policy reads ``nonfinite_total`` from the stats.
    ``integrity_every > 0`` compiles the replica-consistency fingerprint
    check (common.integrity) into the parallel step at that iteration
    cadence — a ``lax.cond``-gated bitcast fold, verdict in the aux."""

    nan_guard: bool = False
    member_cull: bool = False
    stats: bool = True
    integrity_every: int = 0


def config_for(listeners) -> Optional[TelemetryConfig]:
    """The telemetry config a listener set implies (None = aux disabled).
    Listeners opt in with a ``wants_telemetry`` attribute; a skip-policy
    ``NanSentinelListener`` additionally sets ``wants_nan_guard``, the
    fleet ``"cull"`` policy sets ``wants_member_cull`` on top, and an
    ``IntegrityListener`` sets ``wants_integrity`` (its check cadence)
    while opting out of per-layer stats via ``wants_telemetry_stats =
    False`` — stats stay on if ANY listener wants them (absence of the
    attribute means a classic stats consumer)."""
    if not any(getattr(l, "wants_telemetry", False) for l in listeners):
        return None
    nan_guard = any(getattr(l, "wants_nan_guard", False) for l in listeners)
    stats = nan_guard or any(
        getattr(l, "wants_telemetry_stats",
                getattr(l, "wants_telemetry", False))
        for l in listeners)
    integrity_every = 0
    for l in listeners:
        integrity_every = max(integrity_every,
                              int(getattr(l, "wants_integrity", 0) or 0))
    return TelemetryConfig(
        nan_guard=nan_guard,
        member_cull=any(getattr(l, "wants_member_cull", False)
                        for l in listeners),
        stats=stats,
        integrity_every=integrity_every)


# --- in-graph statistics (called inside the jitted step) --------------------

def groups(params) -> List[Any]:
    """Per-layer param subtrees in the canonical telemetry order: list
    index for MultiLayerNetwork-style param lists, sorted node name for
    ComputationGraph-style dicts — must match :func:`layer_names`."""
    if isinstance(params, dict):
        return [params[k] for k in sorted(params)]
    return list(params)


def layer_names(model) -> List[str]:
    """Host-side labels for the aux vectors' layer axis."""
    conf = getattr(model, "conf", None)
    layers = getattr(conf, "layers", None)
    if layers is not None:
        return [f"{i}_{type(l).__name__}" for i, l in enumerate(layers)]
    params = getattr(model, "_params", None)
    if isinstance(params, dict):
        return sorted(params)
    return []


def _sumsq(tree) -> jnp.ndarray:
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)


def _nonfinite(tree) -> jnp.ndarray:
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.int32)
    return sum(jnp.sum(~jnp.isfinite(l)).astype(jnp.int32) for l in leaves)


def _stack(xs, dtype) -> jnp.ndarray:
    if not xs:
        return jnp.zeros((0,), dtype)
    return jnp.stack([x.astype(dtype) for x in xs])


def nonfinite_counts(grads) -> jnp.ndarray:
    """Per-layer non-finite element counts ([L] int32) of a gradient tree.
    Split out so ``ParallelWrapper`` can take it on the RAW per-shard
    grads and psum it across the data axis before reduction."""
    return _stack([_nonfinite(g) for g in groups(grads)], jnp.int32)


def layer_stats(params, new_params, grads, loss,
                nonfinite: Optional[jnp.ndarray] = None
                ) -> Dict[str, jnp.ndarray]:
    """The auxiliary telemetry pytree, computed in-graph.

    All entries are device values: ``loss`` (scalar), ``grad_norm`` /
    ``update_norm`` / ``param_norm`` / ``update_ratio`` ([L] float32, one
    slot per layer in :func:`groups` order), ``nonfinite`` ([L] int32
    non-finite gradient elements per layer) and ``nonfinite_total``
    (scalar, including a non-finite loss). Layers without params read 0.
    """
    po, pn, gr = groups(params), groups(new_params), groups(grads)
    grad_norm = jnp.sqrt(_stack([_sumsq(g) for g in gr], jnp.float32))
    update_norm = jnp.sqrt(_stack(
        [_sumsq(jax.tree.map(lambda n, o: n - o, n_, o_))
         for n_, o_ in zip(pn, po)], jnp.float32))
    param_norm = jnp.sqrt(_stack([_sumsq(p) for p in pn], jnp.float32))
    nf = nonfinite if nonfinite is not None else nonfinite_counts(grads)
    total = (jnp.sum(nf).astype(jnp.int32)
             + (~jnp.isfinite(loss)).astype(jnp.int32))
    return {
        "loss": loss,
        "grad_norm": grad_norm,
        "update_norm": update_norm,
        "param_norm": param_norm,
        "update_ratio": update_norm / jnp.maximum(param_norm, 1e-12),
        "nonfinite": nf,
        "nonfinite_total": total,
    }


def sharded_layer_stats(loss, parts, n_layers: int, axis_name: str,
                        nonfinite: Optional[jnp.ndarray] = None
                        ) -> Dict[str, jnp.ndarray]:
    """:func:`layer_stats` for the ZeRO-1 sharded-updater path: each
    replica holds only its flat 1/N slice of the (mean) gradient, the
    pre-step params and the updated params, so the per-layer norms are
    assembled from shard-local ``segment_sum`` partial sums-of-squares
    psum'd over the data axis — no full gradient or update tensor is ever
    materialized just for telemetry, and the result is replicated (every
    shard reports identical values, like the dense path's).

    ``parts``: per flat bucket, ``(segment_ids, grad_shard, new_param_
    shard, old_param_shard)`` where ``segment_ids`` maps each local flat
    position to its telemetry layer slot (``n_layers`` = the pad-tail
    drop bin). ``nonfinite`` comes from the RAW per-shard grads exactly as
    in the dense path (the reduced shard would smear NaNs)."""
    zeros = jnp.zeros((n_layers + 1,), jnp.float32)
    g2, u2, p2 = zeros, zeros, zeros
    for seg, g, pn, po in parts:
        g32 = g.astype(jnp.float32)
        d32 = (pn - po).astype(jnp.float32)
        p32 = pn.astype(jnp.float32)
        g2 = g2 + jax.ops.segment_sum(g32 * g32, seg, n_layers + 1,
                                      indices_are_sorted=True)
        u2 = u2 + jax.ops.segment_sum(d32 * d32, seg, n_layers + 1,
                                      indices_are_sorted=True)
        p2 = p2 + jax.ops.segment_sum(p32 * p32, seg, n_layers + 1,
                                      indices_are_sorted=True)
    g2, u2, p2 = (jax.lax.psum(v[:n_layers], axis_name)
                  for v in (g2, u2, p2))
    grad_norm, update_norm, param_norm = (jnp.sqrt(v) for v in (g2, u2, p2))
    nf = (nonfinite if nonfinite is not None
          else jnp.zeros((n_layers,), jnp.int32))
    total = (jnp.sum(nf).astype(jnp.int32)
             + (~jnp.isfinite(loss)).astype(jnp.int32))
    return {
        "loss": loss,
        "grad_norm": grad_norm,
        "update_norm": update_norm,
        "param_norm": param_norm,
        "update_ratio": update_norm / jnp.maximum(param_norm, 1e-12),
        "nonfinite": nf,
        "nonfinite_total": total,
    }


def apply_nan_guard(aux, new_params, params, new_states, states,
                    new_upd, upd_state):
    """The skip-update NAN_PANIC policy, compiled into the step: when the
    step produced any non-finite gradient (or loss), drop the param/
    updater-state/layer-state updates and carry the pre-step copies —
    which are already live in the graph — forward instead. The poisoned
    update never lands and no host round-trip is involved; the listener
    only reports. Returns (aux + ``skipped`` flag, params, states, upd)."""
    ok = aux["nonfinite_total"] == 0

    def keep(n, o):
        return jnp.where(ok, n, o)

    aux = dict(aux)
    aux["skipped"] = (~ok).astype(jnp.int32)
    return (aux,
            jax.tree.map(keep, new_params, params),
            jax.tree.map(keep, new_states, states),
            jax.tree.map(keep, new_upd, upd_state))


# --- listener-bus drains (host side, async) ---------------------------------

class TelemetrySink(TrainingListener):
    """Drains the in-graph aux into a ``StatsStorage`` backend.

    Buffers the DEVICE pytrees per iteration (cheap: references only) and
    every ``drain_every_n`` iterations performs ONE batched
    ``jax.device_get`` of the whole window — the only host sync telemetry
    pays, timed into the profiler's ``telemetry/drain`` section.
    ``keep_every_n`` subsamples iterations for long runs. Scalars emitted
    per drained iteration: ``loss``, ``nonfinite_total`` (and
    ``skipped_updates`` under the nan guard, ``exchange_density`` under an
    encoded gradient exchange), plus
    ``{grad_norm,update_norm,param_norm,update_ratio}/<layer>`` and —
    only when non-zero — ``nonfinite/<layer>``."""

    wants_telemetry = True

    def __init__(self, storage, drain_every_n: int = 10,
                 session_id: str = "", keep_every_n: int = 1):
        self.storage = storage
        self.every = max(1, drain_every_n)
        self.keep = max(1, keep_every_n)
        self.session = session_id
        self._buf: List[tuple] = []
        self._names: Optional[List[str]] = None
        self.drains = 0

    def telemetry_done(self, model, iteration: int, aux) -> None:
        if iteration % self.keep:
            return
        if self._names is None:
            self._names = layer_names(model)
        self._buf.append((iteration, aux))
        if len(self._buf) >= self.every:
            self.drain()

    def drain(self) -> None:
        """Flush the buffered window (one batched readback)."""
        if not self._buf:
            return
        prof = OpProfiler.get()
        with prof.time_section("telemetry/drain"):
            host = jax.device_get([a for _, a in self._buf])
        names = self._names or []

        def name(j: int) -> str:
            return names[j] if j < len(names) else str(j)

        put = self.storage.put_scalar
        for (it, _), aux in zip(self._buf, host):
            put(self.session, "loss", it, float(aux["loss"]))
            put(self.session, "nonfinite_total", it,
                int(aux["nonfinite_total"]))
            if "skipped" in aux:
                put(self.session, "skipped_updates", it, int(aux["skipped"]))
            if "exchange_density" in aux:
                # encoded gradient exchange: fraction of elements ≥ the
                # threshold this step (see parallel/accumulator.py)
                put(self.session, "exchange_density", it,
                    float(aux["exchange_density"]))
            for series in ("grad_norm", "update_norm", "param_norm",
                           "update_ratio"):
                vec = aux[series]
                for j in range(len(vec)):
                    put(self.session, f"{series}/{name(j)}", it,
                        float(vec[j]))
            nf = aux["nonfinite"]
            for j in range(len(nf)):
                if int(nf[j]):
                    put(self.session, f"nonfinite/{name(j)}", it,
                        int(nf[j]))
        prof.count("telemetry/drained_steps", len(self._buf))
        self.drains += 1
        self._buf.clear()

    def epoch_done(self, model, epoch: int) -> None:
        self.drain()


class NanSentinelListener(TrainingListener):
    """Graded NAN_PANIC (reference: nd4j OpProfiler NAN_PANIC / the
    all-or-nothing ``jax_debug_nans`` toggle). Policies:

    - ``"warn"``  — log a warning naming the offending layer(s);
    - ``"skip"``  — the poisoned update is dropped IN-GRAPH (the step is
      built with :func:`apply_nan_guard`, so params stay finite and equal
      to the pre-NaN step); the listener reports what was skipped;
    - ``"cull"``  — ``"skip"`` plus PERMANENT per-member isolation under a
      vmapped fleet (parallel.fleet): the poisoned member's alive-mask
      bit flips in-graph (event ``fleet/nan_cull``) and it takes no
      further updates while the other M-1 members' updates land intact.
      On a solo model this behaves exactly like ``"skip"``;
    - ``"raise"`` — raise ``FloatingPointError`` naming the layer.

    Detection is asynchronous: device non-finite counts buffer and one
    batched readback runs every ``check_every_n`` iterations (and at epoch
    end) — a poisoned step is caught within one drain window without ever
    syncing the hot loop per-iteration. (Under a fleet the trainer owns
    the drain; this listener then only carries the policy.)"""

    wants_telemetry = True
    POLICIES = ("warn", "skip", "cull", "raise")

    def __init__(self, policy: str = "warn", check_every_n: int = 10):
        if policy not in self.POLICIES:
            raise ValueError(f"policy must be one of {self.POLICIES}, "
                             f"got {policy!r}")
        self.policy = policy
        self.wants_nan_guard = policy in ("skip", "cull")
        self.wants_member_cull = policy == "cull"
        self.every = max(1, check_every_n)
        self._buf: List[tuple] = []
        self._names: Optional[List[str]] = None
        self.events: List[dict] = []

    def telemetry_done(self, model, iteration: int, aux) -> None:
        if self._names is None:
            self._names = layer_names(model)
        self._buf.append((iteration, aux["nonfinite"],
                          aux["nonfinite_total"]))
        if len(self._buf) >= self.every:
            self.check()

    def check(self) -> None:
        """Inspect the buffered window (one batched readback)."""
        if not self._buf:
            return
        with OpProfiler.get().time_section("telemetry/drain"):
            host = jax.device_get([(nf, tot) for _, nf, tot in self._buf])
        buf, self._buf = self._buf, []
        names = self._names or []
        for (it, _, _), (nf, tot) in zip(buf, host):
            if int(tot) == 0:
                continue
            layers = [(names[j] if j < len(names) else str(j), int(c))
                      for j, c in enumerate(nf) if int(c)]
            where = ", ".join(f"{n} ({c} non-finite grad elements)"
                              for n, c in layers) or "loss"
            self.events.append({"iteration": it, "layers": layers,
                                "total": int(tot)})
            # counter per poisoned iteration (not per element): the
            # watchtower's NaN-free-steps SLO samples increments of this
            OpProfiler.get().count("telemetry/nan_events")
            if self.policy == "raise":
                raise FloatingPointError(
                    f"non-finite gradients at iteration {it}: {where}")
            if self.policy in ("skip", "cull"):
                logger.warning("NanSentinel: skipped poisoned update at "
                               "iteration %d (%s)", it, where)
            else:
                logger.warning("NanSentinel: non-finite gradients at "
                               "iteration %d (%s)", it, where)

    def epoch_done(self, model, epoch: int) -> None:
        self.check()
