"""Pre-decoded on-disk dataset container (VERDICT r3 item 4).

Reference: ``datavec-arrow`` columnar interchange + ``nd4j-serde`` binary
DataSet serializers (SURVEY §2.3 DataVec-execution row, §2.1 nd4j-serde) —
the reference's answer to "don't re-decode JPEGs every epoch". This is the
TPU rebuild's chunked binary record format:

``.d4tbin`` layout (little-endian)::

    b"D4TB" | u32 version | u64 header_len | header JSON (padded to 4 KiB)
    chunk 0 | chunk 1 | ...

The header records the column schema (name/shape/dtype), chunk size, and
total record count. Every chunk stores ``chunk_records`` records (the last
one fewer) column-major: all of column 0's records contiguously, then
column 1, ... Fixed shapes + raw dtypes mean the reader is a ``np.memmap``
slice-and-reshape — no parsing, no decode; training reads run at page-cache
speed, which is what makes a disk-fed ResNet TPU-bound instead of
PIL-decode-bound (BASELINE.md round-3 disk row: 34 img/s on this 1-core
host vs ~2.5k device-resident).

Components:
- :class:`BinaryRecordWriter` — streaming writer.
- :class:`BinaryRecordReader` — RecordReader SPI (record-at-a-time) plus
  the fast ``iter_chunks`` path.
- :class:`BinaryRecordDataSetIterator` — DataSetIterator over the
  container (chunk reads, optional uint8→float scaling + one-hot labels).
- :func:`write_records` — converter from any RecordReader whose records
  are ``[features: ndarray, label: int]`` (e.g. ImageRecordReader), the
  "decode once" tool.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .dataset import DataSet
from .records import Record, RecordReader  # Record = List[Any]

_MAGIC = b"D4TB"
_VERSION = 1
_HEADER_PAD = 4096


class BinaryRecordWriter:
    """Append fixed-shape records column-wise into a chunked container."""

    def __init__(self, path: str,
                 columns: Sequence[Tuple[str, Tuple[int, ...], str]],
                 chunk_records: int = 512):
        self.path = str(path)
        self.columns = [(str(n), tuple(int(d) for d in shp), np.dtype(dt))
                        for n, shp, dt in columns]
        self.chunk_records = int(chunk_records)
        self._buf: List[List[np.ndarray]] = [[] for _ in self.columns]
        self._n = 0
        self._f = open(self.path, "wb")
        self._write_header()

    def _write_header(self) -> None:
        header = {
            "columns": [{"name": n, "shape": list(shp), "dtype": dt.name}
                        for n, shp, dt in self.columns],
            "chunk_records": self.chunk_records,
            "n_records": self._n,
        }
        blob = json.dumps(header).encode()
        if len(blob) > _HEADER_PAD:
            raise ValueError("schema too large for the 4 KiB header")
        self._f.seek(0)
        self._f.write(_MAGIC)
        self._f.write(np.uint32(_VERSION).tobytes())
        self._f.write(np.uint64(len(blob)).tobytes())
        self._f.write(blob.ljust(_HEADER_PAD, b"\0"))

    def append(self, *values) -> None:
        if len(values) != len(self.columns):
            raise ValueError(f"expected {len(self.columns)} columns, "
                             f"got {len(values)}")
        for (name, shp, dt), v, buf in zip(self.columns, values, self._buf):
            arr = np.asarray(v, dtype=dt)
            if arr.shape != shp:
                raise ValueError(
                    f"column {name!r}: shape {arr.shape} != schema {shp}")
            buf.append(arr)
        self._n += 1
        if len(self._buf[0]) >= self.chunk_records:
            self._flush_chunk()

    def append_batch(self, *batches) -> None:
        n = np.asarray(batches[0]).shape[0]
        for i in range(n):
            self.append(*(np.asarray(b)[i] for b in batches))

    def _flush_chunk(self) -> None:
        if not self._buf[0]:
            return
        for (name, shp, dt), buf in zip(self.columns, self._buf):
            self._f.write(np.ascontiguousarray(
                np.stack(buf).astype(dt)).tobytes())
        self._buf = [[] for _ in self.columns]

    def close(self) -> None:
        if self._f.closed:
            return
        self._flush_chunk()
        self._write_header()     # final n_records
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _Container:
    """Shared memmap view + chunk geometry."""

    def __init__(self, path: str):
        self.path = str(path)
        with open(self.path, "rb") as f:
            if f.read(4) != _MAGIC:
                raise ValueError(f"{path}: not a .d4tbin container")
            version = int(np.frombuffer(f.read(4), np.uint32)[0])
            if version != _VERSION:
                raise ValueError(f"{path}: unsupported version {version}")
            hlen = int(np.frombuffer(f.read(8), np.uint64)[0])
            header = json.loads(f.read(hlen).decode())
        self.columns = [(c["name"], tuple(c["shape"]), np.dtype(c["dtype"]))
                        for c in header["columns"]]
        self.chunk_records = int(header["chunk_records"])
        self.n_records = int(header["n_records"])
        self._data_start = 4 + 4 + 8 + _HEADER_PAD
        self._mm = np.memmap(self.path, np.uint8, mode="r")
        self._rec_bytes = [int(np.prod(shp, dtype=np.int64)) * dt.itemsize
                           for _, shp, dt in self.columns]
        # a crash mid-write (header written, last chunk not flushed) must
        # fail HERE with a clear message, not later inside read_chunk with
        # an opaque reshape error
        need = self._data_start + self.n_records * sum(self._rec_bytes)
        if self._mm.size < need:
            raise ValueError(
                f"{path}: truncated container — header promises "
                f"{self.n_records} records ({need} bytes) but the file is "
                f"{self._mm.size} bytes; the writer likely crashed "
                "mid-write (re-create the container or re-run the "
                "converter)")

    def n_chunks(self) -> int:
        return -(-self.n_records // self.chunk_records) \
            if self.n_records else 0

    def chunk_len(self, c: int) -> int:
        if c < self.n_chunks() - 1:
            return self.chunk_records
        return self.n_records - c * self.chunk_records

    def read_chunk(self, c: int) -> Dict[str, np.ndarray]:
        """Zero-copy column views of chunk ``c`` (arrays [n, *shape])."""
        n = self.chunk_len(c)
        # chunks before the last are all full-sized
        off = self._data_start + c * self.chunk_records \
            * sum(self._rec_bytes)
        out = {}
        for (name, shp, dt), rb in zip(self.columns, self._rec_bytes):
            nbytes = n * rb
            view = self._mm[off:off + nbytes].view(dt).reshape((n,) + shp)
            out[name] = view
            off += nbytes
        return out


class BinaryRecordReader(RecordReader):
    """RecordReader SPI over a container (record-at-a-time; use
    :class:`BinaryRecordDataSetIterator` for the fast batched path)."""

    def __init__(self, path: Optional[str] = None):
        if path is not None:
            self._open(path)

    def _open(self, path: str) -> None:
        self._c = _Container(path)
        self._i = 0
        self._chunk_idx = -1
        self._chunk: Optional[Dict[str, np.ndarray]] = None

    def initialize(self, split) -> None:
        locs = split.locations() if hasattr(split, "locations") else [split]
        if len(locs) != 1:
            raise ValueError("BinaryRecordReader reads one container")
        self._open(str(locs[0]))

    def reset(self) -> None:
        self._i = 0
        self._chunk_idx = -1
        self._chunk = None

    def has_next(self) -> bool:
        return self._i < self._c.n_records

    def next(self) -> Record:
        if not self.has_next():
            raise StopIteration
        c, s = divmod(self._i, self._c.chunk_records)
        if c != self._chunk_idx:
            self._chunk = self._c.read_chunk(c)
            self._chunk_idx = c
        self._i += 1
        vals: Record = []
        for name, shp, dt in self._c.columns:
            v = self._chunk[name][s]
            # .item() preserves the column dtype (int()-coercion would
            # truncate float scalar columns, e.g. regression targets)
            vals.append(v.item() if v.shape == () else np.asarray(v))
        return vals

    @property
    def n_records(self) -> int:
        return self._c.n_records

    @property
    def schema_columns(self):
        return list(self._c.columns)


class BinaryRecordDataSetIterator:
    """DataSetIterator over a container: chunked memmap reads assembled
    into DataSet batches. ``feature_scale`` (e.g. 1/255 for uint8 images)
    converts to float32 on the fly; ``num_classes`` one-hots the label."""

    def __init__(self, path: str, batch_size: int,
                 feature_col: str = "features", label_col: str = "label",
                 num_classes: Optional[int] = None,
                 feature_scale: Optional[float] = None,
                 raw_numpy: bool = False):
        self._c = _Container(path)
        self.batch_size = int(batch_size)
        self.feature_col = feature_col
        self.label_col = label_col
        self.num_classes = num_classes
        self.feature_scale = feature_scale
        # raw_numpy=True yields (x, y) numpy tuples instead of DataSet:
        # DataSet/NDArray construction eagerly device-puts, which must NOT
        # happen on a prefetch worker thread (AsyncDataSetIterator stages
        # raw tuples consumer-side; see its round-4 relay notes)
        self.raw_numpy = bool(raw_numpy)
        names = [n for n, _, _ in self._c.columns]
        for col in (feature_col, label_col):
            if col not in names:
                raise ValueError(f"column {col!r} not in container "
                                 f"({names})")
        self.reset()

    def reset(self) -> None:
        self._cursor = 0

    def has_next(self) -> bool:
        return self._cursor < self._c.n_records

    def __iter__(self):
        self.reset()
        return self

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        start, end = self._cursor, min(self._cursor + self.batch_size,
                                       self._c.n_records)
        self._cursor = end
        feats, labels = [], []
        i = start
        while i < end:
            c, s = divmod(i, self._c.chunk_records)
            take = min(end - i, self._c.chunk_len(c) - s)
            chunk = self._c.read_chunk(c)
            feats.append(chunk[self.feature_col][s:s + take])
            labels.append(chunk[self.label_col][s:s + take])
            i += take
        x = np.concatenate(feats) if len(feats) > 1 else feats[0]
        y = np.concatenate(labels) if len(labels) > 1 else labels[0]
        if self.feature_scale is not None:
            x = x.astype(np.float32) * np.float32(self.feature_scale)
        else:
            x = np.ascontiguousarray(x)
        if self.num_classes is not None:
            y = np.eye(self.num_classes,
                       dtype=np.float32)[np.asarray(y, np.int64).reshape(-1)]
        if self.raw_numpy:
            return x, np.asarray(y)
        return DataSet(x, y)

    # DataSetIterator parity helpers
    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return self._c.n_records


def write_records(reader: RecordReader, path: str,
                  feature_shape: Tuple[int, ...],
                  features_dtype: str = "uint8",
                  feature_scale: float = 255.0,
                  chunk_records: int = 512) -> int:
    """Decode-once converter: drain ``reader`` (records shaped
    ``[features ndarray, label int]`` — ImageRecordReader's output) into a
    container at ``path``. Float features in [0,1] quantize to uint8 by
    default (4× smaller on disk; read back with feature_scale=1/255).
    Returns the record count."""
    fdt = np.dtype(features_dtype)
    with BinaryRecordWriter(
            path,
            [("features", tuple(feature_shape), fdt.name),
             ("label", (), "int32")],
            chunk_records=chunk_records) as w:
        reader.reset()
        while reader.has_next():
            rec = reader.next()
            feats, label = rec[0], rec[1]
            arr = np.asarray(feats)
            if fdt == np.uint8 and np.issubdtype(arr.dtype, np.floating):
                arr = np.clip(np.round(arr * feature_scale), 0,
                              255).astype(np.uint8)
            w.append(arr, int(label))
        return w._n
