from .dataset import DataSet, MultiDataSet
