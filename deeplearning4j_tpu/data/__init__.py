from .dataset import DataSet, MultiDataSet
from .iterators import (DataSetIterator, NDArrayDataSetIterator, ExistingDataSetIterator,
                        MultipleEpochsIterator, MnistDataSetIterator, IrisDataSetIterator)
from .normalizers import (NormalizerStandardize, NormalizerMinMaxScaler,
                          ImagePreProcessingScaler, normalizer_from_json)
from .records import (RecordReader, SequenceRecordReader, CSVRecordReader,
                      CSVSequenceRecordReader, LineRecordReader,
                      CollectionRecordReader, InputSplit, FileSplit,
                      CollectionInputSplit)
from .schema import Schema, TransformProcess, ColumnType
from .image import (ImageRecordReader, ImageTransform, ResizeImageTransform,
                    FlipImageTransform, CropImageTransform,
                    RotateImageTransform, PipelineImageTransform)
from .record_iterator import (RecordReaderDataSetIterator,
                              SequenceRecordReaderDataSetIterator,
                              AsyncDataSetIterator)
