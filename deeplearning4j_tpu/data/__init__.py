from .dataset import DataSet, MultiDataSet
from .iterators import (DataSetIterator, NDArrayDataSetIterator, ExistingDataSetIterator,
                        MultipleEpochsIterator, MnistDataSetIterator, IrisDataSetIterator,
                        Cifar10DataSetIterator, EmnistDataSetIterator,
                        LFWDataSetIterator, TinyImageNetDataSetIterator,
                        UciSequenceDataSetIterator)
from .normalizers import (NormalizerStandardize, NormalizerMinMaxScaler,
                          ImagePreProcessingScaler, normalizer_from_json)
from .records import (RecordReader, SequenceRecordReader, CSVRecordReader,
                      CSVSequenceRecordReader, LineRecordReader,
                      CollectionRecordReader, InputSplit, FileSplit,
                      CollectionInputSplit)
from .schema import Schema, TransformProcess, ColumnType
from .image import (ImageRecordReader, ImageTransform, ResizeImageTransform,
                    FlipImageTransform, CropImageTransform,
                    RotateImageTransform, PipelineImageTransform)
from .record_iterator import (RecordReaderDataSetIterator,
                              SequenceRecordReaderDataSetIterator,
                              AsyncDataSetIterator)
from .reducers import Reducer, Join
from .sequence import (convert_to_sequence, window_sequence,
                       window_sequences, reduce_sequence)
from .analysis import AnalyzeLocal, DataAnalysis, ColumnAnalysis
from .binary_records import (BinaryRecordWriter, BinaryRecordReader,
                             BinaryRecordDataSetIterator, write_records)
from .pipeline import (stable_batches, pad_dataset, pad_rows, device_feed,
                       chunked, resolve_batch_size)
