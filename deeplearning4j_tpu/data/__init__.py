from .dataset import DataSet, MultiDataSet
from .iterators import (DataSetIterator, NDArrayDataSetIterator, ExistingDataSetIterator,
                        MultipleEpochsIterator, MnistDataSetIterator, IrisDataSetIterator)
from .normalizers import (NormalizerStandardize, NormalizerMinMaxScaler,
                          ImagePreProcessingScaler, normalizer_from_json)
