"""Data normalizers.

Reference: nd4j-api ``org.nd4j.linalg.dataset.api.preprocessor.{
NormalizerStandardize, NormalizerMinMaxScaler, ImagePreProcessingScaler}``
(SURVEY.md §2.1): fit statistics once, transform per batch, serializable.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .dataset import DataSet
from ..ndarray.ndarray import NDArray


class Normalizer:
    def fit(self, data) -> None:
        raise NotImplementedError

    def transform(self, ds: DataSet) -> None:
        raise NotImplementedError

    def pre_process(self, ds: DataSet) -> None:
        self.transform(ds)

    def to_json(self) -> dict:
        raise NotImplementedError


class NormalizerStandardize(Normalizer):
    def __init__(self):
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None

    def fit(self, data) -> None:
        feats = _collect_features(data)
        axes = tuple(i for i in range(feats.ndim) if i != 1) if feats.ndim > 2 else (0,)
        self.mean = feats.mean(axis=axes)
        self.std = feats.std(axis=axes) + 1e-8

    def transform(self, ds: DataSet) -> None:
        x = ds.features.to_numpy()
        shape = [1] * x.ndim
        shape[1 if x.ndim > 2 else -1] = -1
        ds.features = NDArray((x - self.mean.reshape(shape)) / self.std.reshape(shape))

    def revert_features(self, arr: NDArray) -> NDArray:
        x = arr.to_numpy()
        shape = [1] * x.ndim
        shape[1 if x.ndim > 2 else -1] = -1
        return NDArray(x * self.std.reshape(shape) + self.mean.reshape(shape))

    def to_json(self) -> dict:
        return {"type": "standardize", "mean": self.mean.tolist(),
                "std": self.std.tolist()}


class NormalizerMinMaxScaler(Normalizer):
    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range = min_range
        self.max_range = max_range
        self.data_min: Optional[np.ndarray] = None
        self.data_max: Optional[np.ndarray] = None

    def fit(self, data) -> None:
        feats = _collect_features(data)
        if feats.ndim == 2:
            # per-column stats (reference NormalizerMinMaxScaler contract)
            self.data_min = feats.min(axis=0)
            self.data_max = feats.max(axis=0)
        else:
            # images/sequences: global range (per-pixel ranges are meaningless)
            self.data_min = np.asarray(feats.min())
            self.data_max = np.asarray(feats.max())

    def transform(self, ds: DataSet) -> None:
        x = ds.features.to_numpy()
        span = np.maximum(self.data_max - self.data_min, 1e-8)
        scale = (self.max_range - self.min_range) / span
        ds.features = NDArray((x - self.data_min) * scale + self.min_range)

    def to_json(self) -> dict:
        return {"type": "minmax", "data_min": np.asarray(self.data_min).tolist(),
                "data_max": np.asarray(self.data_max).tolist(),
                "min_range": self.min_range, "max_range": self.max_range}


class ImagePreProcessingScaler(Normalizer):
    """Scale raw pixel [0, maxValue] → [min, max] (default [0,1])."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0,
                 max_pixel: float = 255.0):
        self.min_range = min_range
        self.max_range = max_range
        self.max_pixel = max_pixel

    def fit(self, data) -> None:
        pass  # stateless

    def transform(self, ds: DataSet) -> None:
        x = ds.features.to_numpy().astype(np.float32)
        ds.features = NDArray(x / self.max_pixel * (self.max_range - self.min_range)
                              + self.min_range)

    def to_json(self) -> dict:
        return {"type": "image", "min_range": self.min_range,
                "max_range": self.max_range, "max_pixel": self.max_pixel}


def normalizer_from_json(d: dict) -> Normalizer:
    t = d["type"]
    if t == "standardize":
        n = NormalizerStandardize()
        n.mean = np.asarray(d["mean"])
        n.std = np.asarray(d["std"])
        return n
    if t == "minmax":
        n = NormalizerMinMaxScaler(d["min_range"], d["max_range"])
        n.data_min = np.asarray(d["data_min"])
        n.data_max = np.asarray(d["data_max"])
        return n
    if t == "image":
        return ImagePreProcessingScaler(d["min_range"], d["max_range"], d["max_pixel"])
    raise ValueError(f"unknown normalizer type {t!r}")


def _collect_features(data) -> np.ndarray:
    if isinstance(data, DataSet):
        return data.features.to_numpy()
    # iterator
    parts = []
    data.reset()
    for ds in data:
        parts.append(ds.features.to_numpy())
    data.reset()
    return np.concatenate(parts)
