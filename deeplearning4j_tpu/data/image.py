"""ImageRecordReader + image transform pipeline (datavec-data-image analog).

Reference: ``org.datavec.image.recordreader.ImageRecordReader`` (label =
parent directory name via ``ParentPathLabelGenerator``, decode → resize →
NCHW float) and ``org.datavec.image.transform.ImageTransform`` chain
(Crop/Flip/Rotate/ResizeImageTransform...; SURVEY.md §2.3 DataVec image
row). The reference decodes through JavaCPP/OpenCV; here PIL + numpy do the
host-side decode, and the arrays head straight into the device input
pipeline (``AsyncDataSetIterator`` overlaps this decode with TPU compute).

Output layout is NCHW float32 in [0,1] (divide-by-255 happens here, like
the reference's ``ImagePreProcessingScaler`` default), labels are integer
class indices resolved from sorted directory names.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .records import InputSplit, RecordReader


class ImageTransform:
    """SPI: np.ndarray [H,W,C] uint8 -> np.ndarray [H,W,C] uint8
    (reference: ImageTransform)."""

    def __call__(self, img: np.ndarray, rng: np.random.Generator) \
            -> np.ndarray:
        raise NotImplementedError


class ResizeImageTransform(ImageTransform):
    def __init__(self, width: int, height: int):
        self.width, self.height = width, height

    def __call__(self, img, rng):
        from PIL import Image

        return np.asarray(Image.fromarray(img).resize(
            (self.width, self.height), Image.BILINEAR))


class FlipImageTransform(ImageTransform):
    """Horizontal mirror with probability p (reference: FlipImageTransform
    random mode)."""

    def __init__(self, p: float = 0.5):
        self.p = p

    def __call__(self, img, rng):
        if rng.random() < self.p:
            return img[:, ::-1]
        return img


class CropImageTransform(ImageTransform):
    """Random crop of a fixed output size (reference: CropImageTransform)."""

    def __init__(self, height: int, width: int):
        self.height, self.width = height, width

    def __call__(self, img, rng):
        h, w = img.shape[:2]
        if h < self.height or w < self.width:
            raise ValueError(f"crop {self.height}x{self.width} exceeds "
                             f"image {h}x{w}")
        top = int(rng.integers(0, h - self.height + 1))
        left = int(rng.integers(0, w - self.width + 1))
        return img[top:top + self.height, left:left + self.width]


class RotateImageTransform(ImageTransform):
    """Random rotation in ±max_degrees (reference: RotateImageTransform)."""

    def __init__(self, max_degrees: float):
        self.max_degrees = max_degrees

    def __call__(self, img, rng):
        from PIL import Image

        deg = float(rng.uniform(-self.max_degrees, self.max_degrees))
        return np.asarray(Image.fromarray(img).rotate(deg,
                                                      Image.BILINEAR))


class PipelineImageTransform(ImageTransform):
    """Chain of transforms (reference: PipelineImageTransform)."""

    def __init__(self, transforms: Sequence[ImageTransform]):
        self.transforms = list(transforms)

    def __call__(self, img, rng):
        for t in self.transforms:
            img = t(img, rng)
        return img


class ImageRecordReader(RecordReader):
    """Decode images under a FileSplit into [C,H,W] float32 in [0,1] +
    integer label from the parent directory name.

    Each record is ``[image_chw: np.ndarray, label_index: int]`` — the
    shape ``RecordReaderDataSetIterator`` assembles into NCHW batches.
    """

    def __init__(self, height: int, width: int, channels: int = 3,
                 transform: Optional[ImageTransform] = None,
                 seed: int = 0, workers: int = 1):
        self.height, self.width, self.channels = height, width, channels
        self.transform = transform
        self._rng = np.random.default_rng(seed)
        self.labels: List[str] = []
        # Decode thread pool size. PIL releases the GIL during decode, so
        # N workers ≈ N× decode throughput — the role the reference's
        # multi-threaded NativeImageLoader/Async pipeline plays. Results
        # are yielded IN ORDER with a bounded submission window (2×workers
        # outstanding) so memory stays flat on large splits.
        self.workers = max(1, workers)
        import threading

        # transforms draw from the shared rng; decode (the expensive part)
        # stays parallel, the cheap transform step serializes on this lock
        self._transform_lock = threading.Lock()

    def initialize(self, split: InputSplit) -> None:
        self._split = split
        files = split.locations()
        self.labels = sorted({p.parent.name for p in files})
        self._label_idx = {l: i for i, l in enumerate(self.labels)}
        self.reset()

    def num_labels(self) -> int:
        return len(self.labels)

    def _load(self, path: Path) -> np.ndarray:
        from PIL import Image

        with Image.open(path) as im:
            im = im.convert("L" if self.channels == 1 else "RGB")
            arr = np.asarray(im)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.transform is not None:
            with self._transform_lock:
                arr = self.transform(arr, self._rng)
            if arr.ndim == 2:
                arr = arr[:, :, None]
        if arr.shape[0] != self.height or arr.shape[1] != self.width:
            from PIL import Image as _I

            squeezed = arr[:, :, 0] if arr.shape[2] == 1 else arr
            resized = np.asarray(_I.fromarray(squeezed).resize(
                (self.width, self.height), _I.BILINEAR))
            arr = resized[:, :, None] if resized.ndim == 2 else resized
        # HWC uint8 → CHW float32 [0,1]
        return (arr.astype(np.float32) / 255.0).transpose(2, 0, 1)

    def _make_iter(self):
        paths = self._split.locations()
        if self.workers == 1:
            for path in paths:
                yield [self._load(path), self._label_idx[path.parent.name]]
            return
        from concurrent.futures import ThreadPoolExecutor

        window = 2 * self.workers
        with ThreadPoolExecutor(self.workers) as pool:
            pending = []
            idx = 0
            while idx < len(paths) or pending:
                while idx < len(paths) and len(pending) < window:
                    p = paths[idx]
                    pending.append((pool.submit(self._load, p), p))
                    idx += 1
                fut, p = pending.pop(0)
                yield [fut.result(), self._label_idx[p.parent.name]]
