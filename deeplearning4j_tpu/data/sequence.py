"""Sequence construction + windowing over record collections.

Reference: datavec-api ``transform.sequence`` —
``ConvertToSequence(groupBy, comparator)``, ``TimeWindowFunction`` /
``OverlappingTimeWindowFunction``-style windowing, and
``ReduceSequenceTransform`` (SURVEY §2.3 DataVec core row).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional, Sequence

from .records import Record, SequenceRecord
from .reducers import Reducer
from .schema import Schema


def convert_to_sequence(schema: Schema, records: Sequence[Record],
                        group_by: str, sort_by: Optional[str] = None,
                        ascending: bool = True) -> List[SequenceRecord]:
    """Group flat records into sequences by a key column, each sequence
    sorted by ``sort_by`` (reference: ConvertToSequence + the numerical
    comparator)."""
    gi = schema.index_of(group_by)
    si = schema.index_of(sort_by) if sort_by is not None else None
    groups: "OrderedDict" = OrderedDict()
    for rec in records:
        groups.setdefault(rec[gi], []).append(list(rec))
    out = []
    for _, rows in groups.items():
        if si is not None:
            rows.sort(key=lambda r: r[si], reverse=not ascending)
        out.append(rows)
    return out


def window_sequence(sequence: SequenceRecord, window_size: int,
                    stride: Optional[int] = None,
                    drop_partial: bool = True) -> List[SequenceRecord]:
    """Fixed-size windows over one sequence; ``stride < window_size``
    gives overlapping windows (reference: Overlapping vs plain
    TimeWindowFunction, expressed in steps instead of wall time)."""
    if window_size <= 0:
        raise ValueError("window_size must be positive")
    stride = stride or window_size
    out = []
    for start in range(0, len(sequence), stride):
        win = sequence[start:start + window_size]
        if not win:
            break
        if drop_partial and len(win) < window_size:
            break
        out.append(win)
        if start + window_size >= len(sequence) and stride >= window_size:
            break
    return out


def window_sequences(sequences: Sequence[SequenceRecord], window_size: int,
                     stride: Optional[int] = None,
                     drop_partial: bool = True) -> List[SequenceRecord]:
    out = []
    for seq in sequences:
        out.extend(window_sequence(seq, window_size, stride, drop_partial))
    return out


def reduce_sequence(schema: Schema, sequence: SequenceRecord,
                    reducer: Reducer) -> Record:
    """Collapse one sequence to a single record with the reducer's ops
    (reference: ReduceSequenceTransform)."""
    reduced = reducer.reduce(schema, sequence)
    if len(reduced) != 1:
        raise ValueError(
            "reducer key columns must be constant within a sequence "
            f"(got {len(reduced)} groups)")
    return reduced[0]
