"""Shared input/dispatch pipeline for the training loops.

One implementation feeds ``MultiLayerNetwork.fit``, ``ComputationGraph.fit``
and ``ParallelWrapper.fit`` (SURVEY §3.1's "one compiled train-step per
minibatch", with the host side around it made shape-stable and overlapped):

- **shape-stable batching** (:func:`stable_batches`): every batch a fit
  config sees has the SAME leading dimension — the final partial batch is
  padded to the target size by wrapping real rows, with a per-example
  weight vector (1 = real, 0 = pad) threaded into the loss so padded rows
  contribute exactly nothing. One shape ⇒ the jitted train step compiles
  exactly once per config instead of recompiling on the remainder batch
  (whole-loop compilation with stable shapes is what keeps a TPU pipeline
  saturated — cf. arXiv:1810.09868). ``drop_remainder=True`` skips the
  partial batch instead.
- **async device feed** (:func:`device_feed`, built on
  ``common.background.staged_iter``): batch placement (``jax.device_put``
  or a sharded put) is issued ``depth`` batches ahead of the consumer, so
  the H2D transfer of batch *n+1* overlaps the device compute of batch
  *n*; host-side assembly can additionally run on a prefetch thread.
- **multi-step dispatch** (:func:`chunked`): group K stable batches per
  Python dispatch; the networks stack them and run a ``lax.scan`` device
  loop, amortizing Python/dispatch overhead over K steps (the same lever
  as update-sharding's dispatch amortization, arXiv:2004.13336).
- **observability**: :func:`timed_iter` feeds the ``pipeline/next_batch``
  vs ``pipeline/dispatch`` sections of ``common.profiler.OpProfiler``,
  and the step builders bump ``trace/*`` counters at trace time — tests
  and the bench assert "one compile per config" on those.

Padding wraps REAL rows (``row[i % n]``) rather than zero-filling:
zero rows would pollute cross-example statistics (BatchNorm batch stats),
while wrapped rows keep them in-distribution; the wrapped rows' loss and
gradient contributions are removed exactly by the example-weight mask.
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common import faultinject, flightrec, xprof
from ..common.background import staged_iter
from ..common.profiler import OpProfiler
from ..ndarray.ndarray import NDArray
from .dataset import DataSet, MultiDataSet


def resolve_batch_size(data: Any, batch_size: Optional[int]) -> Optional[int]:
    """The pipeline's target (padded) batch size. A source that makes its
    own batches (an iterator reporting ``batch()``) keeps its native size
    — an explicit ``batch_size`` cannot re-batch an iterator (the pre-
    pipeline fit ignored it there too) and padding every batch UP to a
    larger figure would silently multiply per-step FLOPs. The explicit
    argument applies to sources the pipeline slices itself (DataSet /
    tuple). None = no stable target; batches pass through unpadded."""
    b = getattr(data, "batch", None)
    if callable(b):
        try:
            n = b()
            if n and n > 0:
                return int(n)
        except NotImplementedError:
            pass
    return int(batch_size) if batch_size else None


def iter_datasets(data: Any, batch_size: Optional[int] = None,
                  allow_multi: bool = False) -> Iterator[Any]:
    """The one batch-source protocol shared by every fit loop: DataSet
    iterators (reset + __iter__), a single DataSet (optionally re-batched
    by ``batch_size``), a (features, labels) tuple, and — for the graph —
    MultiDataSet."""
    if isinstance(data, (DataSet, MultiDataSet)):
        if isinstance(data, MultiDataSet):
            if not allow_multi:
                raise TypeError("MultiDataSet requires ComputationGraph.fit")
            if batch_size is not None:
                # refusing beats silently training one giant batch
                raise TypeError(
                    "a MultiDataSet cannot be re-batched by batch_size; "
                    "slice it upstream (e.g. an iterator of MultiDataSets) "
                    "or pass batch_size=None")
            yield data
        elif batch_size is None:
            yield data
        else:
            yield from data.batch_by(batch_size)
        return
    if hasattr(data, "reset") and hasattr(data, "__iter__"):
        data.reset()
        yield from data
        return
    if isinstance(data, tuple) and len(data) == 2:
        yield from iter_datasets(DataSet(data[0], data[1]), batch_size)
        return
    raise TypeError(f"cannot iterate data of type {type(data)}")


def _wrap_rows(value: jnp.ndarray, idx: np.ndarray) -> jnp.ndarray:
    return jnp.asarray(value)[idx]


def _pad_nd(nd: Optional[NDArray], idx: np.ndarray) -> Optional[NDArray]:
    if nd is None:
        return None
    return NDArray(_wrap_rows(nd.value, idx))


def pad_rows(arr: np.ndarray, target: int,
             axis: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Pad ``arr`` to ``target`` entries along ``axis`` by WRAPPING real
    rows (``row[i % n]``) — the same rule :func:`pad_dataset` applies to
    training batches, host-side (numpy) for the serving tier's bucket
    padding. Returns ``(padded, w)`` with ``w`` the [target] float32
    example-weight vector (1 = real, 0 = pad).

    The inertness argument is the same as training's: wrapped rows are
    REAL rows, so any per-example computation produces for pad slots an
    exact copy of a real slot's output, and the consumer discards them by
    the mask / by slicing ``[:n]`` — nothing about the real rows' results
    depends on the pad rows (proven bitwise in tests/test_serving.py for
    the inference forward)."""
    arr = np.asarray(arr)
    n = arr.shape[axis]
    if n > target:
        raise ValueError(f"{n} rows exceed the pad target {target}")
    w = (np.arange(target) < n).astype(np.float32)
    if n == target:
        return arr, w
    idx = np.arange(target) % n
    return np.take(arr, idx, axis=axis), w


def pad_dataset(ds: Any, target: int) -> Tuple[Any, jnp.ndarray]:
    """Pad ``ds`` (DataSet or MultiDataSet) to ``target`` examples by
    wrapping real rows; returns ``(padded_ds, w)`` with the example-weight
    vector ``w`` ([target] float32, 1 = real row, 0 = pad row).

    The padded arrays live where NDArray places them (the jax default
    device — NDArray converts eagerly, so a host-side gather is not an
    option here). ParallelWrapper's numpy bind therefore pays one host
    round-trip per PADDED batch before the sharded placement; keep the
    batch size a multiple of the worker count so only the final remainder
    batch pays it."""
    n = ds.num_examples()
    if n > target:
        raise ValueError(f"batch of {n} examples exceeds the pipeline "
                         f"target batch size {target}")
    idx = np.arange(target) % n
    w = jnp.asarray((np.arange(target) < n).astype(np.float32))
    if isinstance(ds, MultiDataSet):
        out = MultiDataSet.__new__(MultiDataSet)
        out.features = [_pad_nd(f, idx) for f in ds.features]
        out.labels = [_pad_nd(l, idx) for l in ds.labels]
        out.features_masks = ([_pad_nd(m, idx) for m in ds.features_masks]
                              if ds.features_masks else None)
        out.labels_masks = ([_pad_nd(m, idx) for m in ds.labels_masks]
                            if ds.labels_masks else None)
        return out, w
    out = DataSet.__new__(DataSet)
    out.features = _pad_nd(ds.features, idx)
    out.labels = _pad_nd(ds.labels, idx)
    out.features_mask = _pad_nd(ds.features_mask, idx)
    out.labels_mask = _pad_nd(ds.labels_mask, idx)
    return out, w


def stable_batches(data: Any, batch_size: Optional[int] = None,
                   pad_partial: bool = True, drop_remainder: bool = False,
                   round_to_multiple_of: int = 1,
                   allow_multi: bool = False
                   ) -> Iterator[Tuple[Any, jnp.ndarray, int]]:
    """Yield ``(dataset, w, n_real)`` triples with a stable leading
    dimension. The target size is ``resolve_batch_size(...)`` (falling
    back to the first batch's size), rounded up to
    ``round_to_multiple_of`` (ParallelWrapper's worker-count divisibility).
    Batches already at the target get ``w`` = ones; smaller batches are
    dropped (``drop_remainder``) or padded with zero-weight wrapped rows;
    larger batches pass through unpadded (their own ones-``w``) — a
    mixed-size source degrades to today's per-shape retraces instead of
    failing."""
    target = resolve_batch_size(data, batch_size)
    prof = OpProfiler.get()
    ones_cache: dict = {}

    def ones_w(n: int) -> jnp.ndarray:
        if n not in ones_cache:
            ones_cache[n] = jnp.ones((n,), jnp.float32)
        return ones_cache[n]

    m = max(1, int(round_to_multiple_of))
    for ds in iter_datasets(data, batch_size, allow_multi=allow_multi):
        n = ds.num_examples()
        if target is None:
            target = n
        tgt = -(-target // m) * m
        if n == tgt:
            yield ds, ones_w(n), n
        elif drop_remainder and n < target:
            # a batch is a droppable REMAINDER only vs the un-rounded
            # target: full batches merely short of the worker multiple
            # must still train (padded below), else a batch_size that is
            # not a multiple of the worker count would drop EVERY batch
            prof.count("pipeline/dropped_batches")
            continue
        elif n > tgt or not pad_partial:
            # oversize or padding disabled: pass through; round up to the
            # worker multiple only (the wrapper cannot run otherwise)
            tgt_n = -(-n // m) * m
            if tgt_n == n:
                yield ds, ones_w(n), n
            else:
                prof.count("pipeline/padded_batches")
                padded, w = pad_dataset(ds, tgt_n)
                yield padded, w, n
        else:
            prof.count("pipeline/padded_batches")
            padded, w = pad_dataset(ds, tgt)
            yield padded, w, n


def device_feed(batches: Iterable, place=None, depth: int = 2,
                host_prefetch: int = 0) -> Iterator:
    """Stage ``place(batch)`` (device placement) ``depth`` batches ahead of
    the consumer — see ``common.background.staged_iter`` for the threading
    contract. ``depth=0`` disables lookahead (fully serial feed)."""
    if place is None:
        place = lambda b: b  # noqa: E731
    return staged_iter(batches, stage=place, depth=depth,
                       host_prefetch=host_prefetch)


def timed_iter(it: Iterable, section: str = "pipeline/next_batch"):
    """Yield from ``it`` with each blocking ``next()`` timed into the
    profiler — the host-wait half of the transfer-vs-compute overlap
    ledger (the other half is the ``pipeline/dispatch`` section the fit
    loops record around step dispatch)."""
    prof = OpProfiler.get()
    src = iter(it)
    while True:
        try:
            with prof.time_section(section):
                item = next(src)
        except StopIteration:
            return
        yield item


def _poison_nan(batch):
    """Apply an injected ``nan`` fault: every floating array of the
    batch's FIRST element (features — array, dict, or list alike) is
    multiplied by NaN, which drives the step's loss and gradients
    non-finite exactly the way a corrupt record would. Composes with the
    telemetry layer's NanSentinelListener policies."""
    def nanify(a):
        if hasattr(a, "dtype") and np.issubdtype(np.dtype(a.dtype),
                                                 np.floating):
            return a * float("nan")
        return a

    return (jax.tree.map(nanify, batch[0]),) + tuple(batch[1:])


def run_epochs(data: Any, epochs: int, batch_size: Optional[int],
               pad_partial: bool, drop_remainder: bool, prefetch: int,
               steps_per_dispatch: int, bind, place, dispatch_one,
               dispatch_chunk, stackable, on_epoch,
               round_to_multiple_of: int = 1,
               allow_multi: bool = False,
               host_prefetch: int = 0,
               skip: Optional[Tuple[int, int]] = None,
               pre_dispatch=None) -> None:
    """The one training-loop skeleton shared by MultiLayerNetwork.fit,
    ComputationGraph.fit, and ParallelWrapper.fit: per epoch, stable
    batches are bound (``bind(ds, w)`` → jit argument tuple), staged
    ``prefetch`` ahead through ``place``, and dispatched either per step
    or in ``steps_per_dispatch``-sized chunks — a chunk tail (or a
    shape-unstable group, per ``stackable``) falls back to the per-step
    path instead of compiling a second device loop for its length.

    **Fault tolerance** (common.faultinject): ``bind`` and ``place`` are
    wrapped in :func:`faultinject.retry_call` — transient failures
    (injected or user-marked via a ``transient`` attribute) retry with
    bounded exponential backoff, profiler-counted under
    ``pipeline/retries``. Fault-plan sites fire here deterministically:
    ``pipeline/bind`` (indexed by the fit call's batch ordinal; advisory
    ``nan`` specs poison the bound batch), ``pipeline/place``,
    ``train/step`` (indexed by dispatch ordinal; a ``crash`` spec raises
    :class:`faultinject.SimulatedCrash` before the step dispatches — the
    in-process stand-in for preemption), and ``device/loss`` (same
    indexing; a ``device_loss`` spec raises
    :class:`faultinject.DeviceLostError` naming the lost replica — the
    deterministic elastic shrink-and-continue drill).

    **Resume** (``skip=(epochs_done, steps_in_epoch)``): fast-forward a
    checkpoint cursor by REPLAYING the host side — completed epochs are
    consumed from the source (advancing any per-epoch shuffle RNG exactly
    as the killed run did) without binding or dispatching, and the resume
    epoch's first ``steps_in_epoch`` stable batches are drawn and
    discarded. Dispatch then continues with the restored params/updater/
    RNG key, making the continuation bit-identical to the uninterrupted
    run. The post-checkpoint remainder of the resume epoch replays fully,
    including its ``on_epoch`` boundary.

    ``pre_dispatch(ordinal)``: optional per-dispatch hook run after the
    generic fault points and before the dispatch — path-specific fault
    sites (the pipeline trainer's ``pipeline/stage`` stage-loss/straggler
    drills) fire here sharing the fit call's dispatch ordinal, so a drill
    plan indexes one counter regardless of which fit path runs it."""
    k = max(1, int(steps_per_dispatch))
    skip_epochs, skip_steps = skip if skip is not None else (0, 0)
    n_bound = 0       # batch ordinal within this fit call (fault indexing)
    n_dispatched = 0  # dispatch ordinal within this fit call

    def guarded_bind(ds, w):
        nonlocal n_bound
        ordinal = n_bound
        n_bound += 1

        def attempt():
            advisory = faultinject.fault_point("pipeline/bind", ordinal)
            b = bind(ds, w)
            for spec in advisory:
                if spec["kind"] == "nan":
                    b = _poison_nan(b)
            return b

        return faultinject.retry_call(attempt, "pipeline/bind")

    n_placed = [0]

    def guarded_place(b):
        ordinal = n_placed[0]
        n_placed[0] += 1

        def attempt():
            faultinject.fault_point("pipeline/place", ordinal)
            return place(b)

        return faultinject.retry_call(attempt, "pipeline/place")

    for e in range(max(1, epochs)):
        if e < skip_epochs:
            # completed pre-kill: consume (advances iterator/shuffle
            # state), dispatch nothing, and do NOT re-fire on_epoch —
            # its effects are part of the restored checkpoint state
            for _ in iter_datasets(data, batch_size,
                                   allow_multi=allow_multi):
                pass
            continue
        with flightrec.span("pipeline/epoch", epoch=e):
            gen = stable_batches(data, batch_size, pad_partial=pad_partial,
                                 drop_remainder=drop_remainder,
                                 round_to_multiple_of=round_to_multiple_of,
                                 allow_multi=allow_multi)
            if e == skip_epochs and skip_steps:
                skipped = 0
                for _ in gen:
                    skipped += 1
                    if skipped >= skip_steps:
                        break
                if skipped < skip_steps:
                    import logging

                    logging.getLogger("deeplearning4j_tpu").warning(
                        "resume cursor wants %d steps into the epoch but "
                        "the source produced %d batches — did the data "
                        "change since the checkpoint?", skip_steps, skipped)
            bound = (guarded_bind(ds, w) for ds, w, _n in gen)
            feed = timed_iter(device_feed(
                bound, place=guarded_place, depth=max(0, int(prefetch)),
                host_prefetch=max(0, int(host_prefetch))))
            if k == 1:
                for b in feed:
                    faultinject.fault_point("train/step", n_dispatched)
                    # a wedge here is a hung dispatch: the thread blocks
                    # until the supervisor's watchdog abandons it
                    # (release_wedges); a device_loss here is a replica
                    # dying BETWEEN dispatches — the holder's state stays
                    # boundary-consistent, which is what lets the
                    # supervisor shrink the data axis online instead of
                    # checkpoint-restarting
                    faultinject.fault_point("train/wedge", n_dispatched)
                    faultinject.fault_point("device/loss", n_dispatched)
                    if pre_dispatch is not None:
                        pre_dispatch(n_dispatched)
                    flightrec.event("pipeline/dispatch",
                                    ordinal=n_dispatched)
                    n_dispatched += 1
                    dispatch_one(b)
            else:
                for group in chunked(feed, k):
                    for j in range(len(group)):
                        faultinject.fault_point("train/step",
                                                n_dispatched + j)
                        faultinject.fault_point("train/wedge",
                                                n_dispatched + j)
                        faultinject.fault_point("device/loss",
                                                n_dispatched + j)
                        if pre_dispatch is not None:
                            pre_dispatch(n_dispatched + j)
                    flightrec.event("pipeline/dispatch",
                                    ordinal=n_dispatched,
                                    steps=len(group))
                    n_dispatched += len(group)
                    if len(group) == k and stackable(group):
                        dispatch_chunk(group)
                    else:
                        for b in group:
                            dispatch_one(b)
            on_epoch()
            # HBM watermark: one live-buffer census per epoch (the same
            # walk /api/health serves) feeds the per-phase peak gauges —
            # epoch cadence, never per dispatch
            xprof.memory_watermark("fit")


def note_steps(holder: Any, listeners: Iterable, losses,
               auxes: Optional[List] = None) -> None:
    """Shared post-dispatch bookkeeping for every fit loop: advance the
    holder's iteration counter, publish the DEVICE loss scalar (listeners
    sync at their own print/collect boundaries, never here), and notify
    listeners once per step — identical whether the losses came from one
    per-step dispatch or a K-step scan chunk. ``auxes`` (aligned with
    ``losses``) carries the in-graph telemetry pytrees of DEVICE values
    when the step was built with telemetry; listeners exposing
    ``telemetry_done`` receive them un-synced (TelemetrySink /
    NanSentinelListener batch their own readbacks)."""
    last = len(losses) - 1
    for i, loss in enumerate(losses):
        holder._iteration += 1
        # resume-cursor bookkeeping: steps completed within the current
        # epoch (reset by the fit loops' on_epoch), and whether the
        # holder's published params correspond to THIS step — inside a
        # scan chunk they only do at the final step, so checkpoint-style
        # listeners defer their snapshot to the dispatch boundary
        holder._steps_in_epoch = getattr(holder, "_steps_in_epoch", 0) + 1
        holder._at_dispatch_boundary = (i == last)
        holder._score_dev = loss
        aux = auxes[i] if auxes is not None else None
        for lst in listeners:
            lst.iteration_done(holder, holder._iteration, loss)
            if aux is not None:
                cb = getattr(lst, "telemetry_done", None)
                if cb is not None:
                    cb(holder, holder._iteration, aux)


def unstack_aux(auxes, k: int) -> List:
    """Split a scan-stacked telemetry aux pytree ([K, ...] leaves) into K
    per-step pytrees of device values (lazy slices — no host sync)."""
    return [jax.tree.map(lambda a, _i=i: a[_i], auxes) for i in range(k)]


def note_dispatch(holder: Any, listeners: Iterable, out, telemetry: bool,
                  k: Optional[int] = None) -> None:
    """Decode ONE train-step (``k=None``) or scan-chunk (``k`` steps)
    output — a 4-tuple, or a 5-tuple carrying the telemetry aux when the
    step was built with it — publish the carried state onto ``holder``,
    then run :func:`note_steps`. The single place the step builders'
    return contract is unpacked; all three networks' dispatchers share it.

    Ordering matters: the holder's ``_params``/``_states``/
    ``_updater_state`` MUST be replaced before listeners run — the step
    donated the old buffers, so a listener reading ``model._params``
    during ``iteration_done`` (StatsListener, EvaluativeListener) would
    otherwise touch deleted arrays."""
    params, states, upd = out[0], out[1], out[2]
    holder._params, holder._states, holder._updater_state = \
        params, states, upd
    if k is None:
        loss = out[3]
        note_steps(holder, listeners, [loss],
                   [out[4]] if telemetry else None)
        return
    losses = out[3]
    note_steps(holder, listeners, [losses[i] for i in range(k)],
               unstack_aux(out[4], k) if telemetry else None)


def chunked(it: Iterable, k: int) -> Iterator[List]:
    """Group ``k`` items per yield for multi-step dispatch; the final
    group may be shorter (the fit loops run it through the per-step path
    rather than compiling a second device loop for the tail)."""
    if k < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")
    group: List = []
    for item in it:
        group.append(item)
        if len(group) == k:
            yield group
            group = []
    if group:
        yield group
