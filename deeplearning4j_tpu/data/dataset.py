"""DataSet / MultiDataSet — features+labels(+masks) containers.

Reference: nd4j-api ``org.nd4j.linalg.dataset.{DataSet, MultiDataSet}``
(SURVEY.md §2.1 datasets row): holds feature/label arrays with optional
per-timestep masks, supports shuffle/split/batching/serialization.
"""

from __future__ import annotations

import io
from typing import Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..ndarray.ndarray import NDArray
from ..ndarray.rng import get_random


def _nd(x) -> Optional[NDArray]:
    if x is None or isinstance(x, NDArray):
        return x
    # hand the value straight to NDArray (its constructor does jnp.asarray):
    # np.asarray here would force a device->host readback for jax-array input
    return NDArray(x)


class DataSet:
    def __init__(self, features=None, labels=None,
                 features_mask=None, labels_mask=None):
        self.features = _nd(features)
        self.labels = _nd(labels)
        self.features_mask = _nd(features_mask)
        self.labels_mask = _nd(labels_mask)

    # --- basic info ----------------------------------------------------
    def num_examples(self) -> int:
        return self.features.shape[0] if self.features is not None else 0

    def get_features(self) -> NDArray:
        return self.features

    def get_labels(self) -> NDArray:
        return self.labels

    # --- manipulation --------------------------------------------------
    def shuffle(self, seed: Optional[int] = None) -> None:
        n = self.num_examples()
        rng = np.random.RandomState(seed) if seed is not None else np.random
        perm = rng.permutation(n)
        self.features = NDArray(self.features.to_numpy()[perm])
        if self.labels is not None:
            self.labels = NDArray(self.labels.to_numpy()[perm])
        if self.features_mask is not None:
            self.features_mask = NDArray(self.features_mask.to_numpy()[perm])
        if self.labels_mask is not None:
            self.labels_mask = NDArray(self.labels_mask.to_numpy()[perm])

    def split_test_and_train(self, n_train: int) -> Tuple["DataSet", "DataSet"]:
        def cut(arr, lo, hi):
            return NDArray(arr.to_numpy()[lo:hi]) if arr is not None else None

        n = self.num_examples()
        train = DataSet(cut(self.features, 0, n_train), cut(self.labels, 0, n_train),
                        cut(self.features_mask, 0, n_train), cut(self.labels_mask, 0, n_train))
        test = DataSet(cut(self.features, n_train, n), cut(self.labels, n_train, n),
                       cut(self.features_mask, n_train, n), cut(self.labels_mask, n_train, n))
        return train, test

    def batch_by(self, batch_size: int,
                 drop_remainder: bool = False) -> Iterator["DataSet"]:
        n = self.num_examples()
        if drop_remainder:
            n = (n // batch_size) * batch_size
        for i in range(0, n, batch_size):
            yield DataSet(
                NDArray(self.features.to_numpy()[i:i + batch_size]),
                NDArray(self.labels.to_numpy()[i:i + batch_size]) if self.labels is not None else None,
                NDArray(self.features_mask.to_numpy()[i:i + batch_size]) if self.features_mask is not None else None,
                NDArray(self.labels_mask.to_numpy()[i:i + batch_size]) if self.labels_mask is not None else None,
            )

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        def cat(attr):
            if getattr(datasets[0], attr) is None:
                return None
            return np.concatenate([getattr(d, attr).to_numpy() for d in datasets])

        return DataSet(cat("features"), cat("labels"),
                       cat("features_mask"), cat("labels_mask"))

    # --- serialization -------------------------------------------------
    def save(self, path: str) -> None:
        if not path.endswith(".npz"):
            path = path + ".npz"  # np.savez appends it; keep save/load symmetric
        arrays = {"features": self.features.to_numpy()}
        if self.labels is not None:
            arrays["labels"] = self.labels.to_numpy()
        if self.features_mask is not None:
            arrays["features_mask"] = self.features_mask.to_numpy()
        if self.labels_mask is not None:
            arrays["labels_mask"] = self.labels_mask.to_numpy()
        np.savez(path, **arrays)

    @staticmethod
    def load(path: str) -> "DataSet":
        if not path.endswith(".npz"):
            path = path + ".npz"
        z = np.load(path)
        return DataSet(z["features"], z.get("labels"),
                       z.get("features_mask"), z.get("labels_mask"))

    def __repr__(self) -> str:
        f = self.features.shape if self.features is not None else None
        l = self.labels.shape if self.labels is not None else None
        return f"DataSet(features={f}, labels={l})"


class MultiDataSet:
    """N features + M labels (reference MultiDataSet for ComputationGraph)."""

    def __init__(self, features: Sequence, labels: Sequence,
                 features_masks: Optional[Sequence] = None,
                 labels_masks: Optional[Sequence] = None):
        self.features: List[NDArray] = [_nd(f) for f in features]
        self.labels: List[NDArray] = [_nd(l) for l in labels]
        self.features_masks = [_nd(m) for m in features_masks] if features_masks else None
        self.labels_masks = [_nd(m) for m in labels_masks] if labels_masks else None

    def num_examples(self) -> int:
        return self.features[0].shape[0]

    def __repr__(self) -> str:
        return (f"MultiDataSet(features={[f.shape for f in self.features]}, "
                f"labels={[l.shape for l in self.labels]})")
